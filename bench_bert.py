"""Benchmark: BERT-base seq-512 training throughput + MFU.

Prints a JSON line after EVERY completed stage (flushed), monotonically
enriched — the bench.py artifact contract from PERF.md round 4: a driver
reading the LAST line of stdout always gets the richest complete record,
and an external timeout can never erase a finished stage's numbers.

    stage 1  build + compile + warmup -> line 1 (config, compile time)
    stage 2  timed loop               -> line 2 (adds value/vs_baseline/mfu
             — the contract keys)
    stage 3  fused-kernel adoption    -> line 3 (adds pallas dispatch
             counts when telemetry is on)

Baseline = 290 samples/s/chip — the 50%-MFU ceiling from BASELINE.md
(6 * 110M params * 512 tokens ~= 338 GFLOPs/sample on a ~197 bf16-TFLOP/s
v5e chip). Runs the fused TrainStep (fwd + masked-LM CE + bwd + AdamW-style
update in one XLA executable) in bfloat16; attention runs the Pallas flash
kernels in both directions, and MXNET_PALLAS_FUSED (default ON here)
routes LayerNorm/residual/dropout and the bias+GELU epilogues through the
fused layer kernels (pallas_kernels/fused_layers.py) on TPU.

Same synthetic-data methodology as bench.py (see PERF.md): the batch is
staged on device before the timed loop. BENCH_BERT_REMAT=("" | full |
dots) threads the TrainStep remat policy for batch-size headroom runs.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINE_SAMPLES_S = 290.0   # 50%-MFU ceiling, BASELINE.md row 2
FLOPS_PER_SAMPLE = 6 * 110e6 * 512   # ~338 GF: 6ND with N=110M, D=512 tok
MFU_TARGET = 0.55            # ISSUE 7 acceptance bar


def _emit(record: dict) -> None:
    print(json.dumps(record), flush=True)


def main():
    # fused layer kernels ON by default for the published configuration;
    # BENCH_BERT_FUSED_LAYERS=0 A/Bs the eager path
    os.environ.setdefault("MXNET_PALLAS_FUSED", "1")
    if os.environ.get("BENCH_BERT_FUSED_LAYERS") == "0":
        os.environ["MXNET_PALLAS_FUSED"] = "0"
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import parallel as par
    from mxnet_tpu.callback import device_peak_flops
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo.nlp import bert

    platform = jax.devices()[0].platform
    batch = int(os.environ.get("BENCH_BERT_BATCH",
                               32 if platform != "cpu" else 2))
    seq = 512 if platform != "cpu" else 128
    steps = 20 if platform != "cpu" else 2

    fused = os.environ.get("BENCH_BERT_FUSED", "1") != "0"
    remat = os.environ.get("BENCH_BERT_REMAT") or None
    rs = np.random.RandomState(0)
    tokens = mx.nd.array(rs.randint(0, 30000, (batch, seq)).astype(np.int32))
    mesh = par.make_mesh({"dp": 1}, devices=jax.devices()[:1])

    record = {
        "metric": "bert_base_seq512_train_samples_per_sec_per_chip",
        "unit": "samples/sec",
        "bert_batch": batch,
        "bert_seq": seq,
        "bert_fused_ce": fused,
        "bert_fused_layers": os.environ["MXNET_PALLAS_FUSED"] == "1",
        "bert_remat": remat,
        "bert_mfu_target": MFU_TARGET,
    }

    if fused:
        # fused projection+CE head: the (B, L, vocab) logits never
        # materialize (ops/fused_loss.py; same params/math as the
        # decoder path, labels ride as a second data input)
        net = bert.BERTForPretrainFused(
            dropout=0.1,
            chunk=int(os.environ.get("BENCH_BERT_CHUNK", 5120)))
        net.initialize()
        net.cast("bfloat16")
        labels = mx.nd.array(
            rs.randint(0, 30000, (batch, seq)).astype(np.int32))
        step = par.TrainStep(
            net, lambda outs, *a: outs, "adam", mesh=mesh, loss_only=True,
            remat=remat,
            optimizer_params={"learning_rate": 1e-4,
                              "multi_precision": True})
        batch_args = ((tokens, labels), ())
    else:
        net = bert.bert_12_768_12(use_decoder=True, use_pooler=False,
                                  use_classifier=False)
        net.initialize()
        net.cast("bfloat16")
        labels = mx.nd.array(
            rs.randint(0, 30000, (batch, seq)).astype(np.float32))

        class MLMLoss(gloss.SoftmaxCrossEntropyLoss):
            def hybrid_forward(self, F, pred, label):
                # pred: (B, L, vocab) MLM logits; CE over every position
                return super().hybrid_forward(
                    F, pred.reshape(-1, pred.shape[-1]), label.reshape(-1))

        class LossAdapter:
            def __init__(self):
                self._l = MLMLoss()

            def __call__(self, outs, label):
                mlm = outs[1] if isinstance(outs, (list, tuple)) else outs
                return self._l(mlm, label)

        step = par.TrainStep(net, LossAdapter(), "adam", mesh=mesh,
                             remat=remat,
                             optimizer_params={"learning_rate": 1e-4,
                                               "multi_precision": True})
        batch_args = (tokens, labels)

    t_compile = time.perf_counter()
    loss, _ = step(*batch_args)
    loss.asnumpy()
    step.stage_batch(*batch_args)
    loss, _ = step(*batch_args)
    loss.asnumpy()
    record["bert_compile_warmup_s"] = round(
        time.perf_counter() - t_compile, 2)
    _emit(record)  # stage 1 complete — config + compile survive a timeout

    t0 = time.perf_counter()
    for _ in range(steps):
        loss, _ = step(*batch_args)
    loss.asnumpy()
    dt = time.perf_counter() - t0

    samples_s = batch * steps / dt
    peak = device_peak_flops() or float("nan")
    mfu = samples_s * FLOPS_PER_SAMPLE / peak if peak == peak else None
    record.update({
        "value": round(samples_s, 2),
        "vs_baseline": round(samples_s / BASELINE_SAMPLES_S, 4),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "bert_mfu_vs_target": round(mfu / MFU_TARGET, 4)
        if mfu is not None else None,
    })
    _emit(record)  # stage 2 complete — the contract keys are on stdout

    from mxnet_tpu import telemetry

    if telemetry.enabled():
        fam = telemetry.snapshot()["metrics"].get(
            "mxnet_pallas_dispatch_total")
        record["bert_pallas_dispatch"] = {
            s["labels"]["kernel"]: s["value"]
            for s in (fam["samples"] if fam else ())}
        _emit(record)  # stage 3 — kernel-adoption counters


if __name__ == "__main__":
    sys.exit(main())
