"""Benchmark: BERT-base seq-512 training throughput + MFU.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", "mfu"}.
Baseline = 290 samples/s/chip — the 50%-MFU ceiling from BASELINE.md
(6 * 110M params * 512 tokens ~= 338 GFLOPs/sample on a ~197 bf16-TFLOP/s
v5e chip). Runs the fused TrainStep (fwd + masked-LM CE + bwd + AdamW-style
update in one XLA executable) in bfloat16; attention runs the Pallas flash
kernels in both directions (pallas_kernels/flash_attention.py).

Same synthetic-data methodology as bench.py (see PERF.md): the batch is
staged on device before the timed loop.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINE_SAMPLES_S = 290.0   # 50%-MFU ceiling, BASELINE.md row 2
FLOPS_PER_SAMPLE = 6 * 110e6 * 512   # ~338 GF: 6ND with N=110M, D=512 tok


def main():
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import parallel as par
    from mxnet_tpu.callback import device_peak_flops
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo.nlp import bert

    platform = jax.devices()[0].platform
    batch = int(os.environ.get("BENCH_BERT_BATCH",
                               32 if platform != "cpu" else 2))
    seq = 512 if platform != "cpu" else 128
    steps = 20 if platform != "cpu" else 2

    fused = os.environ.get("BENCH_BERT_FUSED", "1") != "0"
    rs = np.random.RandomState(0)
    tokens = mx.nd.array(rs.randint(0, 30000, (batch, seq)).astype(np.int32))
    mesh = par.make_mesh({"dp": 1}, devices=jax.devices()[:1])

    if fused:
        # fused projection+CE head: the (B, L, vocab) logits never
        # materialize (ops/fused_loss.py; same params/math as the
        # decoder path, labels ride as a second data input)
        net = bert.BERTForPretrainFused(
            dropout=0.1,
            chunk=int(os.environ.get("BENCH_BERT_CHUNK", 5120)))
        net.initialize()
        net.cast("bfloat16")
        labels = mx.nd.array(
            rs.randint(0, 30000, (batch, seq)).astype(np.int32))
        step = par.TrainStep(
            net, lambda outs, *a: outs, "adam", mesh=mesh, loss_only=True,
            optimizer_params={"learning_rate": 1e-4,
                              "multi_precision": True})
        batch_args = ((tokens, labels), ())
    else:
        net = bert.bert_12_768_12(use_decoder=True, use_pooler=False,
                                  use_classifier=False)
        net.initialize()
        net.cast("bfloat16")
        labels = mx.nd.array(
            rs.randint(0, 30000, (batch, seq)).astype(np.float32))

        class MLMLoss(gloss.SoftmaxCrossEntropyLoss):
            def hybrid_forward(self, F, pred, label):
                # pred: (B, L, vocab) MLM logits; CE over every position
                return super().hybrid_forward(
                    F, pred.reshape(-1, pred.shape[-1]), label.reshape(-1))

        class LossAdapter:
            def __init__(self):
                self._l = MLMLoss()

            def __call__(self, outs, label):
                mlm = outs[1] if isinstance(outs, (list, tuple)) else outs
                return self._l(mlm, label)

        step = par.TrainStep(net, LossAdapter(), "adam", mesh=mesh,
                             optimizer_params={"learning_rate": 1e-4,
                                               "multi_precision": True})
        batch_args = (tokens, labels)

    loss, _ = step(*batch_args)
    loss.asnumpy()
    step.stage_batch(*batch_args)
    loss, _ = step(*batch_args)
    loss.asnumpy()

    t0 = time.perf_counter()
    for _ in range(steps):
        loss, _ = step(*batch_args)
    loss.asnumpy()
    dt = time.perf_counter() - t0

    samples_s = batch * steps / dt
    peak = device_peak_flops() or float("nan")
    mfu = samples_s * FLOPS_PER_SAMPLE / peak if peak == peak else None
    print(json.dumps({
        "metric": "bert_base_seq512_train_samples_per_sec_per_chip",
        "value": round(samples_s, 2),
        "unit": "samples/sec",
        "vs_baseline": round(samples_s / BASELINE_SAMPLES_S, 4),
        "mfu": round(mfu, 4) if mfu is not None else None,
    }))


if __name__ == "__main__":
    sys.exit(main())
