#!/usr/bin/env python
"""Create an image RecordIO dataset (reference: ``tools/im2rec.py``).

Two modes, like the reference:
  list mode:   python tools/im2rec.py --list prefix image_root
  record mode: python tools/im2rec.py prefix image_root [--resize N]

The .lst format is "index\\tlabel\\trelative_path" (one per line); record
mode packs each listed image into prefix.rec/prefix.idx via
``mx.recordio.pack_img`` (PIL codecs).
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_EXTS = {".jpg", ".jpeg", ".png", ".bmp"}


def make_list(prefix, root):
    entries = []
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    label_map = {c: i for i, c in enumerate(classes)}
    if classes:
        for c in classes:
            cdir = os.path.join(root, c)
            for fn in sorted(os.listdir(cdir)):
                if os.path.splitext(fn)[1].lower() in _EXTS:
                    entries.append((label_map[c], os.path.join(c, fn)))
    else:
        for fn in sorted(os.listdir(root)):
            if os.path.splitext(fn)[1].lower() in _EXTS:
                entries.append((0, fn))
    with open(prefix + ".lst", "w") as f:
        for i, (label, rel) in enumerate(entries):
            f.write(f"{i}\t{label}\t{rel}\n")
    print(f"wrote {len(entries)} entries to {prefix}.lst")


def make_record(prefix, root, resize=0, quality=95):
    from mxnet_tpu import image as img_mod
    from mxnet_tpu import recordio as rio

    lst = prefix + ".lst"
    if not os.path.exists(lst):
        make_list(prefix, root)
    rec = rio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    n = 0
    with open(lst) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            idx, label, rel = line.split("\t")
            arr = img_mod.imread(os.path.join(root, rel)).asnumpy()
            if resize:
                arr = img_mod.resize_short(arr, resize).asnumpy()
            header = rio.IRHeader(0, float(label), int(idx), 0)
            rec.write_idx(int(idx), rio.pack_img(header, arr,
                                                 quality=quality))
            n += 1
    rec.close()
    print(f"packed {n} images into {prefix}.rec / {prefix}.idx")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("prefix")
    ap.add_argument("root")
    ap.add_argument("--list", action="store_true", dest="list_mode",
                    help="only generate the .lst file")
    ap.add_argument("--resize", type=int, default=0,
                    help="resize short side before packing")
    ap.add_argument("--quality", type=int, default=95)
    args = ap.parse_args(argv)
    if args.list_mode:
        make_list(args.prefix, args.root)
    else:
        make_record(args.prefix, args.root, args.resize, args.quality)
    return 0


if __name__ == "__main__":
    sys.exit(main())
