"""XLA cost-analysis cross-check of the 6ND MFU accounting.

VERDICT r4 #2: the BERT MFU closure's hardware-utilization translation
was self-derived arithmetic with no independent check. This tool asks
the COMPILER: lower the exact benchmark TrainStep executable and read
``compiled.cost_analysis()['flops']`` — XLA's own static FLOP count —
then compare against the 6ND model-FLOP estimate the benchmarks divide
by. The ratio (XLA/6ND) quantifies how much real arithmetic the step
runs per model-FLOP (attention QK/PV terms, the vocab head, recompute),
i.e. the gap between model-FLOP utilization (MFU) and hardware FLOP
utilization.

  python tools/cost_check.py bert
  python tools/cost_check.py llama
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def cost_of_step(step, batch):
    """XLA's static cost analysis of the step's compiled executable.

    The accounting itself lives in ``mxnet_tpu.telemetry.xla_cost_analysis``
    so ``TrainingTelemetry`` reports the same per-step FLOP number this
    tool prints.
    """
    from mxnet_tpu.telemetry import xla_cost_analysis

    return xla_cost_analysis(step, batch)


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "bert"
    import trace_ops

    import numpy as np
    from mxnet_tpu.parallel.step import _as_tuple

    if which == "bert":
        step, batch = trace_ops.build_bert_step()
        tokens = _as_tuple(batch[0])[0]
        bsz, seq = tokens.shape[0], tokens.shape[1]
        nd_flops = 6 * 110e6 * seq * bsz
    elif which == "llama":
        step, batch = trace_ops.build_llama_step()
        tokens = _as_tuple(batch[0])[0]
        bsz, seq = tokens.shape[0], tokens.shape[1]
        n_params = sum(int(np.prod(p.shape))
                       for p in step.net.collect_params().values())
        nd_flops = 6 * n_params * bsz * seq
    elif which == "resnet":
        step, batch = trace_ops.build_resnet_step()
        x = _as_tuple(batch[0])[0]
        bsz = x.shape[0]
        # ResNet-50 fwd ~4.1 GF/image at 224^2; 6ND-style fwd+bwd = 3x
        nd_flops = 3 * 4.1e9 * bsz
    else:
        raise SystemExit(f"unknown target {which}")

    ca = cost_of_step(step, batch)
    xla_flops = float(ca.get("flops", float("nan")))
    rec = {
        "target": which,
        "xla_flops_per_step": xla_flops,
        "model_6nd_flops_per_step": nd_flops,
        "xla_over_6nd": round(xla_flops / nd_flops, 4),
        "bytes_accessed": float(ca.get("bytes accessed",
                                       ca.get("bytes_accessed", 0.0))),
    }
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
