#!/usr/bin/env python
"""Comms-path benchmark — no accelerator required.

Measures the gradient-exchange path in isolation on the virtual CPU
mesh, so a comms regression (or the bucketing win) is visible without a
TPU (or a 30-minute bench.py run):

1. **collective dispatches per step** — the ResNet-50-scale parameter
   set (161 tensors, ~25.5M params) exchanged through kvstore
   ``tpu_sync``: per-key push/pull (one compiled psum per parameter,
   the reference KVStore shape) vs the fused bucketed ``pushpull``
   (one psum per ~``MXNET_KV_BUCKET_MB`` bucket). The headline metric
   is the dispatch reduction — O(params) -> O(params·bytes / cap).
2. **exchange wall time** — median over reps of the full exchange
   (pack + reduce + scatter, synced), per-key vs bucketed vs
   bucketed + 2-bit compression.
3. **training-loss bit-identity** — a small data-parallel Trainer run
   twice (per-key vs bucketed store): losses and final weights must be
   BIT-identical, the acceptance gate for switching the trainer to the
   fused path.

Emits bench.py's JSON contract — one flushed line per completed stage,
monotonically enriched, ``{"metric", "value", "unit", "vs_baseline"}``
first — so the same last-line-of-stdout drivers parse it.
``vs_baseline`` is the measured dispatch reduction against the 10x
acceptance bar (ISSUE 5): >= 1.0 passes. Knobs: COMMS_BENCH_COPIES
(gradient copies per key, default 2), COMMS_BENCH_REPS (timed reps,
default 3), COMMS_BENCH_SCALE (``resnet50`` | ``tiny``),
MXNET_KV_BUCKET_MB (bucket cap, default 25).

Forces JAX_PLATFORMS=cpu + an 8-device virtual host mesh when run as a
script (measuring exchange mechanics, not a tunnel), like the tier-1
test environment. Importing the module has no side effects (bench.py
borrows :func:`resnet50_param_shapes`).
"""
from __future__ import annotations

import json
import os
import sys
import time

if __name__ == "__main__":
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

import numpy as np

DISPATCH_REDUCTION_BAR = 10.0   # ISSUE 5 acceptance: >= 10x fewer


def resnet50_param_shapes():
    """The 161 trainable-parameter shapes of ResNet-50 v1 (conv weights,
    BN gamma/beta, fc) — ~25.5M params, the ISSUE's 'ResNet-50-scale
    param set'. Generated, not read from the model zoo: this tool must
    not pay a model build + shape inference to know the layout."""
    shapes = [(64, 3, 7, 7), (64,), (64,)]
    in_c = 64
    for n_blocks, width in zip((3, 4, 6, 3), (64, 128, 256, 512)):
        for b in range(n_blocks):
            shapes += [(width, in_c, 1, 1), (width,), (width,),
                       (width, width, 3, 3), (width,), (width,),
                       (width * 4, width, 1, 1), (width * 4,),
                       (width * 4,)]
            if b == 0:
                shapes += [(width * 4, in_c, 1, 1), (width * 4,),
                           (width * 4,)]
            in_c = width * 4
    shapes += [(1000, 2048), (1000,)]
    return shapes


def tiny_param_shapes():
    """Small stand-in set for smoke tests (same code path, <1 MB)."""
    return [(64, 32), (64,), (32, 16, 3, 3), (32,), (128, 64), (128,),
            (8, 8), (2000,)]


def _emit(record: dict) -> None:
    print(json.dumps(record), flush=True)


def _make_store(copies, bucket_bytes, compression=None):
    import mxnet_tpu as mx
    from mxnet_tpu import kvstore as kv

    store = kv.create("tpu_sync")
    store._bucket_bytes = bucket_bytes
    if compression is not None:
        store.set_gradient_compression(compression)
    return store


def _make_grads(shapes, copies):
    import mxnet_tpu as mx

    rs = np.random.RandomState(0)
    vals, outs = [], []
    for sh in shapes:
        g = rs.randn(*sh).astype(np.float32)
        vals.append([mx.nd.array(g).as_in_context(mx.Context("cpu", c))
                     for c in range(copies)])
        outs.append([mx.nd.zeros(sh, ctx=mx.Context("cpu", c))
                     for c in range(copies)])
    return vals, outs


def _collective_counts():
    from mxnet_tpu import telemetry

    fam = telemetry.snapshot()["metrics"].get(
        "mxnet_kvstore_collective_dispatch_total")
    out = {"per_key": 0.0, "bucketed": 0.0}
    for s in (fam["samples"] if fam else ()):
        out[s["labels"]["path"]] = s["value"]
    return out


def _exchange(store, keys, vals, outs, priorities):
    import mxnet_tpu as mx

    store.pushpull(keys, vals, out=outs, priority=priorities)
    mx.nd.waitall()


def _run_variant(shapes, copies, bucket_bytes, reps, compression=None):
    """Returns (collectives_per_step, median_ms) for one exchange
    configuration."""
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry

    store = _make_store(copies, bucket_bytes, compression)
    vals, outs = _make_grads(shapes, copies)
    keys = list(range(len(shapes)))
    priorities = [-k for k in keys]
    for k, sh in zip(keys, shapes):
        store.init(k, mx.nd.zeros(sh))
    was = telemetry.enabled()
    telemetry.enable()
    try:
        _exchange(store, keys, vals, outs, priorities)   # warm compiles
        c0 = _collective_counts()
        t_all = []
        for _ in range(reps):
            t0 = time.perf_counter()
            _exchange(store, keys, vals, outs, priorities)
            t_all.append(time.perf_counter() - t0)
        c1 = _collective_counts()
    finally:
        if not was:
            telemetry.disable()
    per_step = sum(c1.values()) - sum(c0.values())
    t_all.sort()
    return per_step / reps, t_all[len(t_all) // 2] * 1e3


def _loss_bit_identity(steps=4):
    """Small 2-context data-parallel Trainer, per-key vs bucketed store:
    per-step losses and the final weight must be bit-identical."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.loss import L2Loss

    def run(bucket_mb):
        prev = os.environ.get("MXNET_KV_BUCKET_MB")
        os.environ["MXNET_KV_BUCKET_MB"] = str(bucket_mb)
        try:
            mx.random.seed(0)
            net = nn.Dense(16, in_units=32)
            net.initialize()
            rs = np.random.RandomState(7)
            net.weight.set_data(mx.nd.array(
                rs.randn(16, 32).astype(np.float32)))
            net.bias.set_data(mx.nd.zeros(16))
            ctxs = [mx.Context("cpu", 0), mx.Context("cpu", 1)]
            net.collect_params().reset_ctx(ctxs)
            tr = gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.05}, kvstore="tpu_sync")
            loss_fn = L2Loss()
            rs2 = np.random.RandomState(11)
            x = rs2.randn(8, 32).astype(np.float32)
            y = rs2.randn(8, 16).astype(np.float32)
            losses = []
            for _ in range(steps):
                with autograd.record():
                    ls = [loss_fn(net(mx.nd.array(x[i * 4:(i + 1) * 4],
                                                  ctx=c)),
                                  mx.nd.array(y[i * 4:(i + 1) * 4],
                                              ctx=c))
                          for i, c in enumerate(ctxs)]
                autograd.backward(ls)
                tr.step(8)
                losses.append(float(sum(l.asnumpy().sum() for l in ls)))
            return losses, net.weight.data(ctxs[0]).asnumpy()
        finally:
            if prev is None:
                os.environ.pop("MXNET_KV_BUCKET_MB", None)
            else:
                os.environ["MXNET_KV_BUCKET_MB"] = prev

    losses_pk, w_pk = run(0)
    losses_bk, w_bk = run(25)
    return (losses_pk == losses_bk and np.array_equal(w_pk, w_bk),
            losses_bk[-1])


def main():
    from mxnet_tpu.telemetry import pop_telemetry_out_flag

    sys.argv[1:], telemetry_out = pop_telemetry_out_flag(sys.argv[1:])
    if telemetry_out:
        from mxnet_tpu import telemetry

        telemetry.enable()

    scale = os.environ.get("COMMS_BENCH_SCALE", "resnet50")
    shapes = tiny_param_shapes() if scale == "tiny" \
        else resnet50_param_shapes()
    copies = int(os.environ.get("COMMS_BENCH_COPIES", "2"))
    reps = int(os.environ.get("COMMS_BENCH_REPS", "3"))
    from mxnet_tpu.kvstore import bucket_cap_bytes

    cap = bucket_cap_bytes()
    total_bytes = sum(4 * int(np.prod(s)) for s in shapes)

    # stage 1+2 share the variant runs (the dispatch counters come from
    # the same timed exchanges)
    perkey_n, perkey_ms = _run_variant(shapes, copies, 0, reps)
    bucket_n, bucket_ms = _run_variant(shapes, copies, cap, reps)
    reduction = perkey_n / max(bucket_n, 1.0)
    record = {
        "metric": "comms_collective_dispatch_reduction",
        "value": round(reduction, 1),
        "unit": "x",
        "vs_baseline": round(reduction / DISPATCH_REDUCTION_BAR, 4),
        "comms_params": len(shapes),
        "comms_param_mb": round(total_bytes / (1 << 20), 1),
        "comms_copies": copies,
        "comms_bucket_mb": round(cap / (1 << 20), 3),
        "comms_perkey_collectives_per_step": round(perkey_n, 1),
        "comms_bucketed_collectives_per_step": round(bucket_n, 1),
    }
    _emit(record)

    _, bucket2bit_ms = _run_variant(
        shapes, copies, cap, reps,
        compression={"type": "2bit", "threshold": 0.5})
    record.update({
        "comms_perkey_ms_per_step": round(perkey_ms, 2),
        "comms_bucketed_ms_per_step": round(bucket_ms, 2),
        "comms_bucketed_2bit_ms_per_step": round(bucket2bit_ms, 2),
        "comms_bucketed_speedup_vs_perkey": round(
            perkey_ms / max(bucket_ms, 1e-9), 2),
    })
    _emit(record)

    identical, last_loss = _loss_bit_identity()
    record.update({
        "comms_bucketed_loss_bit_identical": bool(identical),
        "comms_trainer_last_loss": round(last_loss, 6),
    })
    _emit(record)

    if telemetry_out:
        from mxnet_tpu import telemetry

        telemetry.write_snapshot(telemetry_out)
    return 0 if identical else 1


if __name__ == "__main__":
    sys.exit(main())
