#!/usr/bin/env python
"""Comms-path benchmark — no accelerator required.

Measures the gradient-exchange path in isolation on the virtual CPU
mesh, so a comms regression (or the bucketing win) is visible without a
TPU (or a 30-minute bench.py run):

1. **collective dispatches per step** — the ResNet-50-scale parameter
   set (161 tensors, ~25.5M params) exchanged through kvstore
   ``tpu_sync``: per-key push/pull (one compiled psum per parameter,
   the reference KVStore shape) vs the fused bucketed ``pushpull``
   (one psum per ~``MXNET_KV_BUCKET_MB`` bucket). The headline metric
   is the dispatch reduction — O(params) -> O(params·bytes / cap).
2. **exchange wall time** — median over reps of the full exchange
   (pack + reduce + scatter, synced), per-key vs bucketed vs
   bucketed + 2-bit compression.
3. **training-loss bit-identity** — a small data-parallel Trainer run
   twice (per-key vs bucketed store): losses and final weights must be
   BIT-identical, the acceptance gate for switching the trainer to the
   fused path.
4. **allreduce-under-backward overlap** — the same trainer with
   ``overlap_comms=True`` (grad-ready hooks dispatch each bucket's
   pushpull INSIDE ``autograd.backward``): reports the % of bucket
   collectives issued before backward() returned (the overlap win —
   their device work runs under the remaining reverse sweep via JAX
   async dispatch) and gates the overlapped run's losses/weights
   bit-identical to the per-key exchange.
5. **ZeRO-sharded optimizer state** — the same trainer under
   ``partition="zero1"`` / ``"zero2"`` (reduce-scatter + shard-local
   sweep + allgather instead of allreduce + replicated sweep): gates
   losses/weights bit-identical to the replicated fused path and
   reports the per-rank optimizer-state bytes against the replicated
   total (the ~1/world memory win) plus the fused ``zero`` collective
   dispatch count.

Emits bench.py's JSON contract — one flushed line per completed stage,
monotonically enriched, ``{"metric", "value", "unit", "vs_baseline"}``
first — so the same last-line-of-stdout drivers parse it.
``vs_baseline`` is the measured dispatch reduction against the 10x
acceptance bar (ISSUE 5): >= 1.0 passes. Knobs: COMMS_BENCH_COPIES
(gradient copies per key, default 2), COMMS_BENCH_REPS (timed reps,
default 3), COMMS_BENCH_SCALE (``resnet50`` | ``tiny``),
MXNET_KV_BUCKET_MB (bucket cap, default 25).

Forces JAX_PLATFORMS=cpu + an 8-device virtual host mesh when run as a
script (measuring exchange mechanics, not a tunnel), like the tier-1
test environment. Importing the module has no side effects (bench.py
borrows :func:`resnet50_param_shapes`).
"""
from __future__ import annotations

import json
import os
import sys
import time

if __name__ == "__main__":
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

import numpy as np

DISPATCH_REDUCTION_BAR = 10.0   # ISSUE 5 acceptance: >= 10x fewer


def resnet50_param_shapes():
    """The 161 trainable-parameter shapes of ResNet-50 v1 (conv weights,
    BN gamma/beta, fc) — ~25.5M params, the ISSUE's 'ResNet-50-scale
    param set'. Generated, not read from the model zoo: this tool must
    not pay a model build + shape inference to know the layout."""
    shapes = [(64, 3, 7, 7), (64,), (64,)]
    in_c = 64
    for n_blocks, width in zip((3, 4, 6, 3), (64, 128, 256, 512)):
        for b in range(n_blocks):
            shapes += [(width, in_c, 1, 1), (width,), (width,),
                       (width, width, 3, 3), (width,), (width,),
                       (width * 4, width, 1, 1), (width * 4,),
                       (width * 4,)]
            if b == 0:
                shapes += [(width * 4, in_c, 1, 1), (width * 4,),
                           (width * 4,)]
            in_c = width * 4
    shapes += [(1000, 2048), (1000,)]
    return shapes


def tiny_param_shapes():
    """Small stand-in set for smoke tests (same code path, <1 MB)."""
    return [(64, 32), (64,), (32, 16, 3, 3), (32,), (128, 64), (128,),
            (8, 8), (2000,)]


def _emit(record: dict) -> None:
    print(json.dumps(record), flush=True)


def _make_store(copies, bucket_bytes, compression=None):
    import mxnet_tpu as mx
    from mxnet_tpu import kvstore as kv

    store = kv.create("tpu_sync")
    store._bucket_bytes = bucket_bytes
    if compression is not None:
        store.set_gradient_compression(compression)
    return store


def _make_grads(shapes, copies):
    import mxnet_tpu as mx

    rs = np.random.RandomState(0)
    vals, outs = [], []
    for sh in shapes:
        g = rs.randn(*sh).astype(np.float32)
        vals.append([mx.nd.array(g).as_in_context(mx.Context("cpu", c))
                     for c in range(copies)])
        outs.append([mx.nd.zeros(sh, ctx=mx.Context("cpu", c))
                     for c in range(copies)])
    return vals, outs


def _collective_counts():
    from mxnet_tpu import telemetry

    fam = telemetry.snapshot()["metrics"].get(
        "mxnet_kvstore_collective_dispatch_total")
    out = {"per_key": 0.0, "bucketed": 0.0, "hierarchical": 0.0,
           "zero": 0.0}
    for s in (fam["samples"] if fam else ()):
        out[s["labels"]["path"]] = s["value"]
    return out


def _gauge_value(name, **labels):
    from mxnet_tpu import telemetry

    fam = telemetry.snapshot()["metrics"].get(name)
    for s in (fam["samples"] if fam else ()):
        if all(s["labels"].get(k) == v for k, v in labels.items()):
            return s["value"]
    return 0.0


def _exchange(store, keys, vals, outs, priorities):
    import mxnet_tpu as mx

    store.pushpull(keys, vals, out=outs, priority=priorities)
    mx.nd.waitall()


def _run_variant(shapes, copies, bucket_bytes, reps, compression=None):
    """Returns (collectives_per_step, median_ms) for one exchange
    configuration."""
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry

    store = _make_store(copies, bucket_bytes, compression)
    vals, outs = _make_grads(shapes, copies)
    keys = list(range(len(shapes)))
    priorities = [-k for k in keys]
    for k, sh in zip(keys, shapes):
        store.init(k, mx.nd.zeros(sh))
    was = telemetry.enabled()
    telemetry.enable()
    try:
        _exchange(store, keys, vals, outs, priorities)   # warm compiles
        c0 = _collective_counts()
        t_all = []
        for _ in range(reps):
            t0 = time.perf_counter()
            _exchange(store, keys, vals, outs, priorities)
            t_all.append(time.perf_counter() - t0)
        c1 = _collective_counts()
    finally:
        if not was:
            telemetry.disable()
    per_step = sum(c1.values()) - sum(c0.values())
    t_all.sort()
    return per_step / reps, t_all[len(t_all) // 2] * 1e3


def _trainer_run(bucket_mb, steps=4, overlap=False, n_dense=1,
                 partition=None, opt_args=None, opt_name="sgd"):
    """Small 2-context data-parallel Trainer run; returns (per-step
    losses, final weights sorted by param name, per-step overlap stats).
    ``bucket_mb`` configures the store's fused-pushpull cap for the run
    (0 = per-key); ``n_dense`` > 1 stacks layers so a tiny cap yields
    several buckets (the overlap stage needs a multi-bucket plan);
    ``partition`` engages the ZeRO-sharded optimizer sweep."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.loss import L2Loss

    prev = os.environ.get("MXNET_KV_BUCKET_MB")
    os.environ["MXNET_KV_BUCKET_MB"] = str(bucket_mb)
    try:
        mx.random.seed(0)
        if n_dense == 1:
            net = nn.Dense(16, in_units=32)
        else:
            net = nn.HybridSequential()
            with net.name_scope():
                for _ in range(n_dense - 1):
                    net.add(nn.Dense(64, in_units=32 if len(net) == 0
                                     else 64))
                net.add(nn.Dense(16))
        net.initialize()
        net(mx.nd.zeros((1, 32)))
        rs = np.random.RandomState(7)
        # definition order, NOT sorted-by-name: the auto-prefix counters
        # advance across runs in one process, and "dense10_" would sort
        # before "dense9_" — the seeded init must land identically
        for p in net.collect_params().values():
            p.set_data(mx.nd.array(
                rs.randn(*p.shape).astype(np.float32) * 0.1))
        ctxs = [mx.Context("cpu", 0), mx.Context("cpu", 1)]
        net.collect_params().reset_ctx(ctxs)
        tr = gluon.Trainer(net.collect_params(), opt_name,
                           dict(opt_args) if opt_args is not None
                           else {"learning_rate": 0.05},
                           kvstore="tpu_sync", overlap_comms=overlap,
                           partition=partition)
        loss_fn = L2Loss()
        rs2 = np.random.RandomState(11)
        x = rs2.randn(8, 32).astype(np.float32)
        y = rs2.randn(8, 16).astype(np.float32)
        losses, stats = [], []
        for _ in range(steps):
            with autograd.record():
                ls = [loss_fn(net(mx.nd.array(x[i * 4:(i + 1) * 4],
                                              ctx=c)),
                              mx.nd.array(y[i * 4:(i + 1) * 4],
                                          ctx=c))
                      for i, c in enumerate(ctxs)]
            autograd.backward(ls)
            tr.step(8)
            if tr.last_overlap_stats is not None:
                stats.append(dict(tr.last_overlap_stats))
            losses.append(float(sum(l.asnumpy().sum() for l in ls)))
        weights = [p.data(ctxs[0]).asnumpy()
                   for p in net.collect_params().values()]
        return losses, weights, stats
    finally:
        if prev is None:
            os.environ.pop("MXNET_KV_BUCKET_MB", None)
        else:
            os.environ["MXNET_KV_BUCKET_MB"] = prev


def _loss_bit_identity(steps=4):
    """Per-key vs bucketed store: per-step losses and the final weight
    must be bit-identical."""
    losses_pk, w_pk, _ = _trainer_run(0, steps)
    losses_bk, w_bk, _ = _trainer_run(25, steps)
    identical = losses_pk == losses_bk and all(
        np.array_equal(a, b) for a, b in zip(w_pk, w_bk))
    return identical, losses_bk[-1]


def _overlap_metrics(steps=5):
    """Backward-overlapped comms: % of bucket collectives dispatched
    inside backward() (steady state — step 1 arms the hooks during
    kvstore init, so it is excluded) plus bit-identity of the overlapped
    run against the per-key exchange."""
    losses_pk, w_pk, _ = _trainer_run(0, steps, n_dense=3)
    # ~0.01 MB cap over the 3-layer param set -> a multi-bucket plan
    losses_ov, w_ov, stats = _trainer_run(0.01, steps, overlap=True,
                                          n_dense=3)
    identical = losses_pk == losses_ov and all(
        np.array_equal(a, b) for a, b in zip(w_pk, w_ov))
    steady = stats[1:] if len(stats) > 1 else stats
    total = sum(s["groups"] for s in steady)
    in_bwd = sum(s["dispatched_in_backward"] for s in steady)
    pct = 100.0 * in_bwd / total if total else 0.0
    groups = steady[-1]["groups"] if steady else 0
    return pct, groups, identical


def _zero_metrics(steps=4):
    """ZeRO-sharded sweep vs the replicated fused path: bit-identity
    over zero1 AND zero2 under adam — deliberately t-DEPENDENT, so the
    gate also covers the per-device update-count streams that keep the
    replicated path's bias-correction clock at one tick per step per
    replica — per-rank vs replicated optimizer-state bytes off the
    gauge pair, and the fused ``zero`` collective dispatch count."""
    from mxnet_tpu import telemetry

    opt = {"learning_rate": 0.01, "wd": 0.01}
    opt_name = "adam"
    losses_rep, w_rep, _ = _trainer_run(25, steps, n_dense=3,
                                        opt_args=opt, opt_name=opt_name)
    was = telemetry.enabled()
    telemetry.enable()
    try:
        c0 = _collective_counts()["zero"]
        losses_z1, w_z1, _ = _trainer_run(25, steps, n_dense=3,
                                          opt_args=opt, opt_name=opt_name,
                                          partition="zero1")
        zero_dispatches = _collective_counts()["zero"] - c0
        per_rank = _gauge_value("mxnet_optimizer_state_bytes",
                                mode="zero1")
        replicated = _gauge_value("mxnet_optimizer_state_bytes",
                                  mode="replicated")
    finally:
        if not was:
            telemetry.disable()
    losses_z2, w_z2, _ = _trainer_run(25, steps, n_dense=3,
                                      opt_args=opt, opt_name=opt_name,
                                      partition="zero2")
    identical = (losses_rep == losses_z1 == losses_z2
                 and all(np.array_equal(a, b)
                         for a, b in zip(w_rep, w_z1))
                 and all(np.array_equal(a, b)
                         for a, b in zip(w_rep, w_z2)))
    return {
        "zero_loss_bit_identical": bool(identical),
        "zero_state_bytes_per_rank": int(per_rank),
        "zero_state_bytes_replicated": int(replicated),
        "zero_state_ratio": round(per_rank / max(replicated, 1.0), 4),
        "zero_collectives_per_step": round(zero_dispatches / steps, 1),
    }


def main():
    from mxnet_tpu.telemetry import pop_telemetry_out_flag

    sys.argv[1:], telemetry_out = pop_telemetry_out_flag(sys.argv[1:])
    if telemetry_out:
        from mxnet_tpu import telemetry

        telemetry.enable()

    scale = os.environ.get("COMMS_BENCH_SCALE", "resnet50")
    shapes = tiny_param_shapes() if scale == "tiny" \
        else resnet50_param_shapes()
    copies = int(os.environ.get("COMMS_BENCH_COPIES", "2"))
    reps = int(os.environ.get("COMMS_BENCH_REPS", "3"))
    from mxnet_tpu.kvstore import bucket_cap_bytes

    cap = bucket_cap_bytes()
    total_bytes = sum(4 * int(np.prod(s)) for s in shapes)

    # stage 1+2 share the variant runs (the dispatch counters come from
    # the same timed exchanges)
    perkey_n, perkey_ms = _run_variant(shapes, copies, 0, reps)
    bucket_n, bucket_ms = _run_variant(shapes, copies, cap, reps)
    reduction = perkey_n / max(bucket_n, 1.0)
    record = {
        "metric": "comms_collective_dispatch_reduction",
        "value": round(reduction, 1),
        "unit": "x",
        "vs_baseline": round(reduction / DISPATCH_REDUCTION_BAR, 4),
        "comms_params": len(shapes),
        "comms_param_mb": round(total_bytes / (1 << 20), 1),
        "comms_copies": copies,
        "comms_bucket_mb": round(cap / (1 << 20), 3),
        "comms_perkey_collectives_per_step": round(perkey_n, 1),
        "comms_bucketed_collectives_per_step": round(bucket_n, 1),
    }
    _emit(record)

    _, bucket2bit_ms = _run_variant(
        shapes, copies, cap, reps,
        compression={"type": "2bit", "threshold": 0.5})
    record.update({
        "comms_perkey_ms_per_step": round(perkey_ms, 2),
        "comms_bucketed_ms_per_step": round(bucket_ms, 2),
        "comms_bucketed_2bit_ms_per_step": round(bucket2bit_ms, 2),
        "comms_bucketed_speedup_vs_perkey": round(
            perkey_ms / max(bucket_ms, 1e-9), 2),
    })
    _emit(record)

    identical, last_loss = _loss_bit_identity()
    record.update({
        "comms_bucketed_loss_bit_identical": bool(identical),
        "comms_trainer_last_loss": round(last_loss, 6),
    })
    _emit(record)

    overlap_pct, overlap_groups, overlap_identical = _overlap_metrics()
    record.update({
        "comms_overlap_dispatch_pct": round(overlap_pct, 1),
        "comms_overlap_groups_per_step": overlap_groups,
        "comms_overlap_loss_bit_identical": bool(overlap_identical),
    })
    _emit(record)

    zero = _zero_metrics()
    record.update(zero)
    _emit(record)

    if telemetry_out:
        from mxnet_tpu import telemetry

        telemetry.write_snapshot(telemetry_out)
    return 0 if (identical and overlap_identical
                 and overlap_pct > 0.0
                 and zero["zero_loss_bit_identical"]) else 1


if __name__ == "__main__":
    sys.exit(main())
