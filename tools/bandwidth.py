#!/usr/bin/env python
"""Allreduce bandwidth measurement (reference: ``tools/bandwidth/`` —
``measure.py`` benchmarks kvstore push+pull GB/s across devices; tracked
metric "KVStore allreduce GB/s" in BASELINE.json).

Measures the COMPILED collective path the tpu_sync kvstore and the fused
TrainStep use: a psum over the mesh's ``dp`` axis, timed end-to-end with
a device sync. Reports algorithmic bandwidth (payload bytes / time) and
bus bandwidth (2*(n-1)/n scaling — the ring-allreduce wire bytes).

    python tools/bandwidth.py [--size-mb 64] [--devices N] [--iters 20]

On the virtual CPU mesh (XLA_FLAGS=--xla_force_host_platform_device_count=8
JAX_PLATFORMS=cpu) this exercises the code path; real numbers need real
ICI-connected chips.
"""
from __future__ import annotations

import argparse
import json
import time


def measure(size_mb=64.0, n_devices=None, iters=20, dtype="float32"):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    n = min(int(n_devices or len(devs)), len(devs))
    devs = devs[:n]
    if n < 2:
        raise SystemExit("allreduce needs >= 2 devices "
                         "(set --xla_force_host_platform_device_count)")
    mesh = Mesh(np.array(devs), ("dp",))
    itemsize = jnp.dtype(dtype).itemsize
    elems = int(size_mb * 1e6 / itemsize)
    elems = max(elems - elems % n, n)

    # per-device distinct payloads, laid out sharded over dp so the psum
    # is a real cross-device reduction, not a local fold
    x = jnp.arange(n * elems, dtype=dtype).reshape(n, elems)
    x = jax.device_put(x, NamedSharding(mesh, P("dp")))

    from jax import shard_map

    @jax.jit
    def allreduce(v):
        return shard_map(lambda s: jax.lax.psum(s, "dp"), mesh=mesh,
                         in_specs=P("dp"), out_specs=P())(v)

    out = allreduce(x)
    out.block_until_ready()                      # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = allreduce(x)
    out.block_until_ready()
    dt = time.perf_counter() - t0

    payload = elems * itemsize                   # bytes reduced per device
    algo_gbps = payload * iters / dt / 1e9
    bus_gbps = algo_gbps * 2 * (n - 1) / n
    return {
        "metric": "kvstore_allreduce_bandwidth",
        "value": round(algo_gbps, 3),
        "unit": "GB/s (algorithmic)",
        "bus_gb_s": round(bus_gbps, 3),
        "devices": n,
        "payload_mb": round(payload / 1e6, 2),
        "platform": devs[0].platform,
    }


def measure_dist(size_mb=64.0, iters=20, dtype="float32"):
    """Cross-PROCESS allreduce: run under tools/launch.py so the psum
    rides the DCN transport between jax processes (loopback TCP when the
    workers share a host — exercises the full multi-controller path).

        python tools/launch.py -n 4 python tools/bandwidth.py --dist

    Each process contributes its local devices to one global dp mesh;
    rank 0 prints the JSON record.
    """
    import numpy as np

    import mxnet_tpu  # noqa: F401 - env/bootstrap side effects
    from mxnet_tpu.kvstore.kvstore import KVStoreTPUSync

    store = KVStoreTPUSync("dist_sync")  # bootstraps jax.distributed
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()                 # GLOBAL device list
    n = len(devs)
    mesh = Mesh(np.array(devs), ("dp",))
    itemsize = jnp.dtype(dtype).itemsize
    elems = int(size_mb * 1e6 / itemsize)
    elems = max(elems - elems % n, n)
    local = jax.local_device_count()
    # per-process shards of the global array
    host_shard = np.arange(elems // n * local, dtype=dtype).reshape(
        local, 1, elems // n)
    arrs = [jax.device_put(host_shard[i], d)
            for i, d in enumerate(jax.local_devices())]
    x = jax.make_array_from_single_device_arrays(
        (n, elems // n), NamedSharding(mesh, P("dp")), arrs)

    @jax.jit
    def allreduce(v):
        return shard_map(lambda s: jax.lax.psum(s, "dp"), mesh=mesh,
                         in_specs=P("dp"), out_specs=P())(v)

    out = allreduce(x)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = allreduce(x)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0

    payload = elems // n * itemsize * local   # bytes/process reduced
    algo_gbps = payload * iters / dt / 1e9
    if jax.process_index() == 0:
        print(json.dumps({
            "metric": "kvstore_allreduce_bandwidth_cross_process",
            "value": round(algo_gbps, 3),
            "unit": "GB/s (algorithmic, per process)",
            "processes": jax.process_count(),
            "devices": n,
            "payload_mb": round(payload / 1e6, 2),
            "platform": devs[0].platform,
        }))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--size-mb", type=float, default=64.0)
    ap.add_argument("--devices", type=int, default=None)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--dist", action="store_true",
                    help="cross-process mode (run under tools/launch.py)")
    args = ap.parse_args()
    if args.dist:
        measure_dist(args.size_mb, args.iters)
        return
    print(json.dumps(measure(args.size_mb, args.devices, args.iters)))


if __name__ == "__main__":
    main()
