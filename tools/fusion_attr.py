"""Attribute opaque trace fusion names to HLO contents (conv/dot/reduce).

The per-op names in a TPU perfetto trace are XLA fusion instruction names
(``fusion.48``) that mean nothing on their own. This tool AOT-compiles the
same step the trace profiled, maps each fusion instruction to the ops its
called computation contains, and joins that against the trace's per-op
device times — the methodology behind PERF.md's round-4 conv-attribution
table (which found the "conv-bwd" cost was mostly fused BatchNorm-backward
arithmetic).

Usage:
  python tools/fusion_attr.py resnet /tmp/mxtrace_dir   # build+compile+join
"""
from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def parse_hlo(txt):
    """fusion-instruction name -> {kinds, conv signatures, big shapes}."""
    calls = {}
    for m in re.finditer(
            r'%([\w\.\-]+) = [^\n]*? fusion\([^\n]*?calls=%([\w\.\-]+)', txt):
        calls[m.group(1)] = m.group(2)

    comp_info = collections.defaultdict(
        lambda: {"convs": [], "dots": 0, "reduces": 0, "kinds": set()})
    cur = None
    for line in txt.splitlines():
        s = line.strip()
        m = re.match(r'%([\w\.\-]+) \([^)]*\) -> ', s)
        if m and s.endswith("{"):
            cur = m.group(1)
        if cur is None:
            continue
        if " convolution(" in s:
            out = re.match(r'%[\w\.\-]+ = (\S+?)\{', s)
            win = re.search(r'window=\{([^}]*)\}', s)
            dl = re.search(r'dim_labels=(\S+?)(,|$)', s)
            comp_info[cur]["convs"].append({
                "out": out.group(1) if out else "?",
                "window": win.group(1) if win else "",
                "dl": dl.group(1) if dl else "",
            })
            comp_info[cur]["kinds"].add("conv")
        elif re.search(r'= \S+ dot\(', s):
            comp_info[cur]["dots"] += 1
            comp_info[cur]["kinds"].add("dot")
        elif re.search(r'= \S+ reduce\(', s):
            comp_info[cur]["reduces"] += 1
            comp_info[cur]["kinds"].add("reduce")
    return calls, comp_info


def classify_conv(c):
    dl, w = c["dl"], c["window"]
    lhs = dl.split("->")[0].split("_")[0]
    if re.search(r'f01b|01bf', lhs) or "->fb01" in dl or "->bf01" in dl:
        return "dW"
    if "_io01" in dl or "rhs_reversal" in w or "lhs_dilate" in w:
        return "dX"
    return "fwd"


def trace_times(tdir):
    tr = sorted(glob.glob(os.path.join(tdir, "**", "*.trace.json.gz"),
                          recursive=True))[-1]
    with gzip.open(tr, "rt") as f:
        data = json.load(f)
    per_op = collections.Counter()
    for e in data.get("traceEvents", []):
        if e.get("ph") == "X":
            n = e.get("name", "")
            # host-side python/runtime frames leak into the event stream;
            # XLA device ops never contain source locations or $-frames
            if (n.startswith(("jit_", "Thread", "pjit", "$", "np.", "Pjit"))
                    or ".py:" in n or " " in n):
                continue
            per_op[n] += e.get("dur", 0) / 1e3
    return per_op


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "resnet"
    tdir = sys.argv[2]
    nsteps = int(os.environ.get("TRACE_NSTEPS", "3"))
    import trace_ops

    step, batch = {"bert": trace_ops.build_bert_step,
                   "resnet": trace_ops.build_resnet_step,
                   "llama": trace_ops.build_llama_step}[which]()
    if which in ("bert", "llama"):
        compiled = step.aot_compile(*batch)
    else:
        data, label = batch
        compiled = step.aot_compile((data,), (label,))
    txt = compiled.as_text()
    calls, comp_info = parse_hlo(txt)
    per_op = trace_times(tdir)

    by_class = collections.Counter()
    by_sig = collections.Counter()
    rows = []
    for name, t in per_op.items():
        comp = calls.get(name)
        info = comp_info.get(comp) if comp else None
        if info and info["convs"]:
            k = classify_conv(info["convs"][0])
            out = info["convs"][0]["out"].split("{")[0]
            w = info["convs"][0]["window"][:28]
            key = f"conv:{k}"
        elif info and "dot" in info["kinds"]:
            k, out, w = "dot", "", ""
            key = "dot"
        elif info and info["reduces"]:
            k, out, w = f'reduce x{info["reduces"]}', "", ""
            key = "reduce"
        elif info is not None:
            k, out, w = "elementwise", "", ""
            key = "elementwise"
        else:
            k, out, w = "?", "", ""
            key = "unfused/" + re.sub(r'[\d\.]+$', "", name)
        by_class[key] += t / nsteps
        by_sig[(k, out, w)] += t / nsteps
        rows.append((t / nsteps, name, k, out, w))

    rows.sort(reverse=True)
    print(f"-- by class (ms/step over {nsteps} steps) --")
    for k, v in by_class.most_common(15):
        print(f"  {k:28s} {v:8.2f}")
    print("\n-- by (kind, conv out, window) --")
    for (k, out, w), v in by_sig.most_common(30):
        print(f"{v:7.2f}  {k:10s} {out:26s} {w}")
    print("\n-- top fusions --")
    for t, name, k, out, w in rows[:25]:
        print(f"{t:7.3f}  {name:28s} {k:8s} {out} {w}")


if __name__ == "__main__":
    main()
