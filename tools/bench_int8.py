"""Microbench: s8 x s8 -> s32 MXU matmul vs bf16 (VERDICT round-2 #7).

Chained-matmul harness (300 dependent iterations inside one executable,
data-dependent fetch — the PERF.md relay protocol). Prints one JSON line
with both rates and the ratio; the quantized ops take the s8 path on TPU
when this ratio is why you quantized.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    M = N = K = 4096
    iters = 300
    rs = np.random.RandomState(0)
    a8 = jnp.asarray(rs.randint(-127, 128, (M, K)), jnp.int8)
    b8 = jnp.asarray(rs.randint(-127, 128, (K, N)), jnp.int8)
    abf = jnp.asarray(rs.randn(M, K), jnp.bfloat16)
    bbf = jnp.asarray(rs.randn(K, N), jnp.bfloat16)

    def bench(fn, x):
        f = jax.jit(lambda x: jax.lax.fori_loop(
            0, iters, lambda i, x: fn(x), x))
        r = f(x)
        _ = np.asarray(jax.device_get(r)).ravel()[0]
        best = float("inf")
        for _i in range(2):
            t0 = time.perf_counter()
            r = f(r)
            _ = np.asarray(jax.device_get(r)).ravel()[0]
            best = min(best, time.perf_counter() - t0)
        return best / iters * 1e3

    def mm_s8(x):
        acc = jax.lax.dot_general(x, b8, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.int32)
        return jnp.clip(acc >> 7, -127, 127).astype(jnp.int8)

    def mm_bf(x):
        return jax.lax.dot_general(
            x, bbf, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(jnp.bfloat16)

    tflop = 2 * M * N * K / 1e12
    ms_s8 = bench(mm_s8, a8)
    ms_bf = bench(mm_bf, abf)
    print(json.dumps({
        "metric": "int8_vs_bf16_matmul_speedup",
        "value": round(ms_bf / ms_s8, 3),
        "unit": "x",
        "s8_tflops": round(tflop / (ms_s8 / 1e3), 1),
        "bf16_tflops": round(tflop / (ms_bf / 1e3), 1),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
