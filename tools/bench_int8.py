"""Microbench: s8 x s8 -> s32 MXU matmul vs bf16 (VERDICT round-2 #7).

Chained-matmul harness (300 dependent iterations inside one executable,
data-dependent fetch — the PERF.md relay protocol). Prints one JSON line
with both rates and the ratio; the quantized ops take the s8 path on TPU
when this ratio is why you quantized.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def _bench_chain(fn, x, iters):
    """Chained data-dependent timing loop (the PERF.md relay protocol):
    jit a fori_loop of fn, fetch a scalar that depends on everything,
    best of 2 timed runs."""
    import jax

    f = jax.jit(lambda x: jax.lax.fori_loop(
        0, iters, lambda i, x: fn(x), x))
    r = f(x)
    _ = np.asarray(jax.device_get(r)).ravel()[0]
    best = float("inf")
    for _i in range(2):
        t0 = time.perf_counter()
        r = f(r)
        _ = np.asarray(jax.device_get(r)).ravel()[0]
        best = min(best, time.perf_counter() - t0)
    return best / iters * 1e3


def main():
    import jax
    import jax.numpy as jnp

    M = N = K = 4096
    iters = 300
    rs = np.random.RandomState(0)
    a8 = jnp.asarray(rs.randint(-127, 128, (M, K)), jnp.int8)
    b8 = jnp.asarray(rs.randint(-127, 128, (K, N)), jnp.int8)
    abf = jnp.asarray(rs.randn(M, K), jnp.bfloat16)
    bbf = jnp.asarray(rs.randn(K, N), jnp.bfloat16)

    def bench(fn, x):
        return _bench_chain(fn, x, iters)

    def mm_s8(x):
        acc = jax.lax.dot_general(x, b8, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.int32)
        return jnp.clip(acc >> 7, -127, 127).astype(jnp.int8)

    def mm_bf(x):
        return jax.lax.dot_general(
            x, bbf, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(jnp.bfloat16)

    tflop = 2 * M * N * K / 1e12
    ms_s8 = bench(mm_s8, a8)
    ms_bf = bench(mm_bf, abf)
    print(json.dumps({
        "metric": "int8_vs_bf16_matmul_speedup",
        "value": round(ms_bf / ms_s8, 3),
        "unit": "x",
        "s8_tflops": round(tflop / (ms_s8 / 1e3), 1),
        "bf16_tflops": round(tflop / (ms_bf / 1e3), 1),
    }))
    return 0


def main_layers():
    """Per-layer int8-vs-bf16 on representative ResNet-50 shapes
    (VERDICT r4 #5): the REAL quantized_conv/quantized_dense ops (s8xs8
    -> s32 on the MXU, calibrated ranges, fused rescale) against the
    bf16 Convolution/FullyConnected they replace. Chained data-dependent
    loop (the PERF.md relay protocol); NHWC layouts."""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.base import execution_platform
    from mxnet_tpu.ops.registry import get_op

    qconv = get_op("_contrib_quantized_conv").fn
    conv = get_op("Convolution").fn
    qdense = get_op("_contrib_quantized_dense").fn
    dense = get_op("FullyConnected").fn
    rs = np.random.RandomState(0)
    iters = 60

    def bench(fn, x):
        return _bench_chain(fn, x, iters)

    LAYERS = [
        ("stage1_3x3", (64, 56, 56, 64), 64, (3, 3), (1, 1)),
        ("stage2_1x1", (64, 28, 28, 512), 128, (1, 1), (0, 0)),
        ("stage3_3x3", (64, 14, 14, 256), 256, (3, 3), (1, 1)),
        ("stage4_1x1", (64, 7, 7, 2048), 512, (1, 1), (0, 0)),
    ]
    rows = []
    with execution_platform(jax.devices()[0].platform):
        for name, xshape, cout, kernel, pad in LAYERS:
            cin = xshape[-1]
            x = jnp.asarray(rs.randn(*xshape), jnp.bfloat16)
            w = jnp.asarray(rs.randn(cout, cin, *kernel) * 0.05,
                            jnp.bfloat16)
            wq = jnp.clip(jnp.round(w.astype(jnp.float32) / 0.002),
                          -127, 127).astype(jnp.int8)
            ws = jnp.full((cout,), 1.0 / 0.002, jnp.float32)

            def run_bf(xv, w=w, kernel=kernel, pad=pad, cout=cout):
                y = conv(xv, w, None, kernel=kernel, num_filter=cout,
                         pad=pad, no_bias=True, layout="NHWC")
                return xv * (1 + 1e-12 * jnp.mean(y).astype(jnp.float32)).astype(xv.dtype)

            def run_s8(xv, wq=wq, ws=ws, kernel=kernel, pad=pad,
                       cout=cout):
                y = qconv(xv, wq, ws, None, kernel=kernel,
                          num_filter=cout, pad=pad, no_bias=True,
                          layout="NHWC", min_calib_range=-4.0,
                          max_calib_range=4.0)
                return xv * (1 + 1e-12 * jnp.mean(y).astype(jnp.float32)).astype(xv.dtype)

            ms_bf = bench(run_bf, x)
            ms_s8 = bench(run_s8, x)
            rows.append({"layer": name, "bf16_ms": round(ms_bf, 3),
                         "int8_ms": round(ms_s8, 3),
                         "speedup": round(ms_bf / ms_s8, 2)})
        # the classifier head
        xh = jnp.asarray(rs.randn(256, 2048), jnp.bfloat16)
        wh = jnp.asarray(rs.randn(1000, 2048) * 0.05, jnp.bfloat16)
        whq = jnp.clip(jnp.round(wh.astype(jnp.float32) / 0.002),
                       -127, 127).astype(jnp.int8)
        whs = jnp.full((1000,), 1.0 / 0.002, jnp.float32)

        def head_bf(xv):
            y = dense(xv, wh, None, num_hidden=1000, no_bias=True)
            return xv * (1 + 1e-12 * jnp.mean(y).astype(jnp.float32)).astype(xv.dtype)

        def head_s8(xv):
            y = qdense(xv, whq, whs, None, num_hidden=1000, no_bias=True,
                       min_calib_range=-4.0, max_calib_range=4.0)
            return xv * (1 + 1e-12 * jnp.mean(y).astype(jnp.float32)).astype(xv.dtype)

        rows.append({"layer": "head_dense",
                     "bf16_ms": round(bench(head_bf, xh), 3),
                     "int8_ms": round(bench(head_s8, xh), 3)})
        rows[-1]["speedup"] = round(
            rows[-1]["bf16_ms"] / rows[-1]["int8_ms"], 2)
    print(json.dumps({"metric": "int8_vs_bf16_per_layer",
                      "layers": rows}))
    return 0


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "layers":
        sys.exit(main_layers())
    sys.exit(main())
