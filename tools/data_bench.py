#!/usr/bin/env python
"""Input-pipeline-only benchmark — no accelerator required.

Measures the three legs of the async data path in isolation, so a
pipeline regression is visible without a TPU (or a 30-minute bench.py
run):

1. **decode throughput** — ImageIter JPEG decode + augment, serial vs
   process workers (img/s both ways + speedup);
2. **shm hop latency** — one batch through the dataloader's
   shared-memory transport (`_to_shm` -> `_from_shm_numpy`), ms/batch
   and GB/s;
3. **device-feed overlap** — a synthetic host producer + fake compute
   consumer, serial loop vs `io.DeviceFeedIter`; overlap%% = how much of
   the host time the prefetch hid.

Emits bench.py's JSON contract — one flushed line per completed stage,
monotonically enriched, `{"metric", "value", "unit", "vs_baseline"}`
first — so the same last-line-of-stdout drivers parse it.
`vs_baseline` is against the r05 host-pipeline rate (266.38 img/s, the
number this pipeline exists to beat). Knobs: MXNET_DATA_WORKERS (worker
count, default all cores), DATA_BENCH_IMAGES, DATA_BENCH_BATCH.

Forces JAX_PLATFORMS=cpu (measuring host pipeline mechanics, not a
tunnel), like the tier-1 test environment.
"""
from __future__ import annotations

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

BASELINE_HOST_IMG_S = 266.38  # BENCH_r05 real_data_host_pipeline rate


def _emit(record: dict) -> None:
    print(json.dumps(record), flush=True)


def _make_rec(img_size: int, n_images: int) -> str:
    import tempfile

    from mxnet_tpu import recordio

    path = os.path.join(tempfile.gettempdir(),
                        f"data_bench_{img_size}_{n_images}.rec")
    if not os.path.exists(path):
        rs = np.random.RandomState(0)
        writer = recordio.MXRecordIO(path, "w")
        for i in range(n_images):
            img = rs.randint(0, 256, (img_size, img_size, 3), np.uint8)
            writer.write(recordio.pack_img(
                recordio.IRHeader(0, float(i % 1000), i, 0), img,
                quality=90))
        writer.close()
    return path


def _decode_stage(rec_path, img_size, batch, n_workers):
    """Stage 1: serial vs process-worker decode throughput."""
    from mxnet_tpu import image as mximg

    def rate(mode, workers):
        it = mximg.ImageIter(
            batch_size=batch, data_shape=(3, img_size, img_size),
            path_imgrec=rec_path, seed=0, dtype="uint8",
            worker_mode=mode, preprocess_threads=workers,
            aug_list=[mximg.CenterCropAug((img_size, img_size)),
                      mximg.HorizontalFlipAug(0.5)])
        try:
            it.next()  # warm (pool spin-up, first-touch buffers)
            n = 0
            t0 = time.perf_counter()
            try:
                while True:
                    b = it.next()
                    n += batch - b.pad
            except StopIteration:
                pass
            return n / (time.perf_counter() - t0)
        finally:
            it.close()

    serial = rate("serial", 1)
    procs = rate("process", n_workers)
    return serial, procs


def _shm_stage(batch, img_size, reps=10):
    """Stage 2: one uint8 batch through the shm transport, round trip.

    Reports the MIN over reps — the transport's latency floor; the mean
    on a busy 2-core container measures allocator/scheduler noise, not
    the hop."""
    from mxnet_tpu.gluon.data.dataloader import _from_shm_numpy, _to_shm

    arr = np.random.RandomState(0).randint(
        0, 256, (batch, 3, img_size, img_size), np.uint8)
    # warm /dev/shm allocation path
    _from_shm_numpy(_to_shm(arr))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = _from_shm_numpy(_to_shm(arr))
        best = min(best, time.perf_counter() - t0)
    assert np.array_equal(out, arr)
    return best * 1e3, arr.nbytes / best / 1e9


def _overlap_stage(n_batches=20, host_ms=20.0, compute_ms=20.0):
    """Stage 3: how much host time DeviceFeedIter hides.

    A producer that takes ``host_ms`` per batch feeding a consumer that
    takes ``compute_ms``: the serial loop costs the sum per batch, the
    pipelined loop max(host, compute) — overlap%% is the fraction of the
    hideable time actually hidden."""
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import io as mxio

    payload = mx.nd.array(np.zeros((8, 16), np.float32))
    label = mx.nd.array(np.zeros((8,), np.float32))

    class _SleepIter(mxio.DataIter):
        def __init__(self):
            super().__init__(8)
            self.i = 0

        def reset(self):
            self.i = 0

        def next(self):
            if self.i >= n_batches:
                raise StopIteration
            self.i += 1
            time.sleep(host_ms / 1e3)
            return mxio.DataBatch(data=[payload], label=[label])

    dev = jax.devices()[0]

    def consume(_b):
        time.sleep(compute_ms / 1e3)

    it = _SleepIter()
    t0 = time.perf_counter()
    try:
        while True:
            b = it.next()
            jax.device_put(b.data[0].data, dev)
            consume(b)
    except StopIteration:
        pass
    serial_s = time.perf_counter() - t0

    feed = mxio.DeviceFeedIter(_SleepIter(), shardings=[dev, dev], depth=2)
    try:
        t0 = time.perf_counter()
        for b in feed:
            consume(b)
        piped_s = time.perf_counter() - t0
    finally:
        feed.close()

    hideable = n_batches * min(host_ms, compute_ms) / 1e3
    overlap = max(0.0, min(1.0, (serial_s - piped_s) / hideable))
    return serial_s, piped_s, overlap * 100.0


def main():
    from mxnet_tpu.telemetry import pop_telemetry_out_flag

    sys.argv[1:], telemetry_out = pop_telemetry_out_flag(sys.argv[1:])
    if telemetry_out:
        from mxnet_tpu import telemetry

        telemetry.enable()

    img_size = 224
    n_images = int(os.environ.get("DATA_BENCH_IMAGES", "512"))
    batch = int(os.environ.get("DATA_BENCH_BATCH", "64"))
    n_workers = int(os.environ.get("MXNET_DATA_WORKERS",
                                   str(os.cpu_count() or 2)))

    rec_path = _make_rec(img_size, n_images)
    serial, procs = _decode_stage(rec_path, img_size, batch, n_workers)
    record = {
        "metric": "data_decode_images_per_sec",
        "value": round(procs, 2),
        "unit": "images/sec",
        "vs_baseline": round(procs / BASELINE_HOST_IMG_S, 4),
        "decode_serial_images_per_sec": round(serial, 2),
        "decode_workers": n_workers,
        "decode_worker_speedup": round(procs / serial, 2),
    }
    _emit(record)

    shm_ms, shm_gbps = _shm_stage(batch, img_size)
    record.update({"shm_hop_ms_per_batch": round(shm_ms, 3),
                   "shm_hop_gbytes_per_sec": round(shm_gbps, 2)})
    _emit(record)

    serial_s, piped_s, overlap = _overlap_stage()
    record.update({"feed_serial_s": round(serial_s, 3),
                   "feed_pipelined_s": round(piped_s, 3),
                   "feed_overlap_pct": round(overlap, 1)})
    _emit(record)

    if telemetry_out:
        from mxnet_tpu import telemetry

        telemetry.write_snapshot(telemetry_out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
