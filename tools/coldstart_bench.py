#!/usr/bin/env python
"""Cold-start benchmark — the compilation service's acceptance meter.

Measures the two cold-start paths ROADMAP item 5 names, each in a FRESH
subprocess (cold start is a process property; in-process timers lie):

1. **process-start -> first-train-step** — import, build a deep-MLP
   TrainStep, train once at each of six batch signatures (the gated
   headline: time to trained-at-all-signatures);
2. **replica-start -> first-response** — import, build a serving
   ``Server`` over the bucket grid, serve one request (reported, not
   gated: its total is init/machinery-dominated).

Three regimes per path:

* ``cold``          — empty XLA disk cache, no manifest: every
  executable traces AND compiles;
* ``warm_disk``     — persistent XLA cache populated by the cold run:
  compiles become disk loads, traces still pay;
* ``warm_manifest`` — disk cache + signature-manifest replay
  (``compiler.warm_start``) before first traffic: same total path, but
  all compile work happens BEFORE the first batch/request, so
  first-dispatch latency collapses to a steady-state step and the
  steady state records ZERO jit-cache misses.

Gates reported (the ISSUE 10 acceptance criteria):
* ``coldstart_speedup``      >= 2.0 (warm_manifest vs cold, first-step
  path, total process time);
* ``coldstart_bit_identical`` — the post-warm loss equals the cold loss
  bit-for-bit (warmed executables must be the same program);
* ``coldstart_zero_misses_after_warm`` — the warmed child's first +
  steady steps record no ``train_step``/``cached_op`` cache miss.

Emits bench.py's JSON contract — one flushed line per completed stage,
monotonically enriched, ``{"metric", "value", "unit", "vs_baseline"}``
first; ``vs_baseline`` is speedup/2.0 (the acceptance bar).

Forces ``JAX_PLATFORMS=cpu`` like the tier-1 test environment (compile
caching mechanics are platform-independent; the axon tunnel is
single-client and the parent bench may hold it). ``COLDSTART_PLATFORM``
overrides for on-device runs.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS",
                      os.environ.get("COLDSTART_PLATFORM", "cpu"))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SPEEDUP_TARGET = 2.0
# Deep MLP trained at SIX batch signatures (bucketed-training shape):
# per-executable, XLA:CPU compile is ~4x the trace + disk-load cost, so
# the executable count is what separates cold from warm — the same
# regime a transformer TrainStep is in on TPU, scaled to bench seconds.
# The workload is deliberately donation-free (MXNET_TPU_DONATE=0 below)
# and dense-only: this container's XLA:CPU persistent-cache
# deserializer corrupts the heap on entries carrying input-output
# aliasing metadata (reproduced with plain jax.jit, no service
# involved — same jax-version bug family as the 26 pre-existing tier-1
# failures).
N_LAYERS = int(os.environ.get("COLDSTART_LAYERS", "24"))
HIDDEN = int(os.environ.get("COLDSTART_HIDDEN", "1024"))
IMG = (64,)
TRAIN_BATCHES = (4, 8, 12, 16, 24, 32)
SERVE_BUCKETS = (1, 2, 4, 8, 16, 32)


def _emit(record: dict) -> None:
    print(json.dumps(record), flush=True)


# ---------------------------------------------------------------------------
# child workloads (run in a fresh interpreter; timed from process start)
# ---------------------------------------------------------------------------

def _build_net():
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn

    mx.random.seed(0)
    net = nn.HybridSequential(prefix="cold_")
    with net.name_scope():
        for _ in range(N_LAYERS):
            net.add(nn.Dense(HIDDEN, activation="relu"))
        net.add(nn.Dense(10))
    net.initialize()
    return net


def _child_train(t0: float, warm: bool) -> dict:
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import compiler, telemetry
    from mxnet_tpu import parallel as par
    from mxnet_tpu.gluon import loss as gloss

    net = _build_net()
    step = par.TrainStep(net, gloss.SoftmaxCrossEntropyLoss(), "adam")
    rs = np.random.RandomState(0)
    batches = [
        (mx.nd.array(rs.rand(b, *IMG).astype("float32")),
         mx.nd.array((np.arange(b) % 10).astype("float32")))
        for b in TRAIN_BATCHES]

    warm_report = None
    if warm:
        warm_report = compiler.warm_start(train_steps=[step])
    t_warm = time.perf_counter() - t0

    telemetry.enable()
    x, y = batches[0]
    t1 = time.perf_counter()
    loss, _ = step(x, y)
    loss.asnumpy()
    t_first = time.perf_counter()
    for x, y in batches[1:]:
        loss, _ = step(x, y)
        loss.asnumpy()
    t_all_sigs = time.perf_counter()
    # steady state: repeat signature 0 — must be a pure cache hit
    x, y = batches[0]
    loss, _ = step(x, y)
    loss_host = loss.asnumpy()
    t_steady = time.perf_counter()

    snap = telemetry.snapshot()["metrics"].get(
        "mxnet_jit_cache_total", {"samples": []})
    misses = {
        s["labels"]["cache"]: s["value"] for s in snap["samples"]
        if s["labels"]["result"] == "miss"}
    telemetry.disable()
    return {
        "import_s": round(_IMPORT_DONE - t0, 3),
        "warm_s": round(t_warm - (_IMPORT_DONE - t0), 3) if warm else 0.0,
        "to_first_step_s": round(t_first - t0, 3),
        "first_step_s": round(t_first - t1, 3),
        "all_sigs_s": round(t_all_sigs - t0, 3),
        "steady_step_s": round(t_steady - t_all_sigs, 4),
        "loss_hex": np.asarray(loss_host, np.float32).tobytes().hex(),
        "graph_misses": {k: v for k, v in misses.items()
                         if k in ("train_step", "cached_op")},
        "warm_report": warm_report,
        "coldstart_events": compiler.events(),
    }


def _child_serve(t0: float, warm: bool) -> dict:
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import compiler, serving

    net = _build_net()
    net.hybridize()
    srv = serving.Server(net, batch_buckets=SERVE_BUCKETS,
                         shape_buckets=[IMG], slo_ms=200,
                         name="coldstart")
    # Server._warm_block replays the active manifest automatically when
    # recording is on (MXNET_COMPILE_MANIFEST); nothing extra to do for
    # the warm regime
    srv.start()
    t_started = time.perf_counter()
    fut = srv.submit(np.zeros(IMG, np.float32))
    out = fut.result(timeout=600)
    t_first = time.perf_counter()
    srv.stop(timeout=30)
    return {
        "import_s": round(_IMPORT_DONE - t0, 3),
        "to_first_response_s": round(t_first - t0, 3),
        "start_s": round(t_started - t0, 3),
        "first_response_s": round(t_first - t_started, 4),
        "response_hex": np.asarray(out, np.float32).tobytes().hex(),
        "coldstart_events": compiler.events(),
    }


def _child_main(mode: str, warm: bool, t0: float) -> None:
    global _IMPORT_DONE

    import mxnet_tpu  # noqa: F401  (the timed import)

    _IMPORT_DONE = time.perf_counter()
    rec = (_child_train if mode == "train" else _child_serve)(t0, warm)
    _emit(rec)


# ---------------------------------------------------------------------------
# parent: three regimes x two paths, each in a fresh interpreter
# ---------------------------------------------------------------------------

def _run_child(mode: str, cache_dir: str, manifest: str,
               warm: bool) -> dict:
    # per-path cache namespace (train fleet vs serving fleet — also what
    # a real deployment shards by), and a small min-compile floor so the
    # dozens of trivial utility jits don't persist: this container's
    # XLA:CPU entry deserializer gets less reliable with every loaded
    # entry, and the sub-100ms entries carry no warm value anyway
    env = dict(os.environ,
               MXNET_XLA_CACHE="1",
               MXNET_XLA_CACHE_DIR=os.path.join(cache_dir, mode),
               MXNET_XLA_CACHE_MIN_COMPILE_S="0.2",
               # donation-carrying executables trip this container's
               # XLA:CPU cache deserializer (heap corruption on load);
               # donation is an HBM concern with no CPU value — off for
               # the measurement children (see TrainStep._build)
               MXNET_TPU_DONATE="0",
               MXNET_TELEMETRY="0")
    # the manifest is recorder (cold run journals its compiles) and warm
    # source (warm_manifest regime replays it); the warm_disk regime runs
    # with recording OFF so it measures the disk tier alone — a live
    # recorder would auto-replay inside Server._warm_block
    if manifest:
        env["MXNET_COMPILE_MANIFEST"] = manifest + "." + mode
    else:
        env["MXNET_COMPILE_MANIFEST"] = "0"
    env.pop("MXNET_TELEMETRY_OUT", None)
    argv = [sys.executable, os.path.abspath(__file__), "--child", mode]
    if warm:
        argv.append("--warm")
    out = subprocess.run(argv, capture_output=True, text=True, env=env,
                         timeout=float(os.environ.get(
                             "COLDSTART_CHILD_TIMEOUT_S", "900")))
    if out.returncode != 0:
        raise RuntimeError(
            f"coldstart child {mode} rc={out.returncode}: "
            f"{out.stderr.strip().splitlines()[-5:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def main() -> int:
    if "--child" in sys.argv:
        t0 = _T0
        mode = sys.argv[sys.argv.index("--child") + 1]
        _child_main(mode, "--warm" in sys.argv, t0)
        return 0

    base = tempfile.mkdtemp(prefix="coldstart_xla_")
    manifest = os.path.join(base, "signatures.jsonl")
    record: dict = {}
    stages = {}
    # best-of-N per child, applied to EVERY regime symmetrically: this
    # container shares cores with co-tenants and a single noisy child
    # run can swing a regime 2x (warm children measured stable at
    # ±5% back-to-back); the minimum is the capability, the rest is
    # scheduler noise
    repeats = max(1, int(os.environ.get("COLDSTART_REPEATS", "2")))

    def best_of(mode, man, warm, pick, fresh_dirs=False):
        runs = []
        for i in range(repeats):
            # cold repeats must each see an EMPTY cache — scratch dirs
            # for all but the last, which populates the shared layout
            # the warm regimes then read
            d = tempfile.mkdtemp(prefix="coldstart_scratch_") \
                if fresh_dirs and i < repeats - 1 else base
            runs.append(_run_child(mode, d, man, warm))
        return min(runs, key=lambda r: r[pick])

    for regime, warm in (("cold", False), ("warm_disk", False),
                         ("warm_manifest", True)):
        man = "" if regime == "warm_disk" else manifest
        stages[regime] = {
            "train": best_of("train", man, warm, "all_sigs_s",
                             fresh_dirs=regime == "cold"),
            "serve": best_of("serve", man, warm, "to_first_response_s",
                             fresh_dirs=regime == "cold"),
        }
        tr, sv = stages[regime]["train"], stages[regime]["serve"]
        record.update({
            f"coldstart_{regime}_first_step_s": tr["to_first_step_s"],
            f"coldstart_{regime}_all_sigs_s": tr["all_sigs_s"],
            f"coldstart_{regime}_first_step_latency_s": tr["first_step_s"],
            f"coldstart_{regime}_first_response_s":
                sv["to_first_response_s"],
        })
        if regime == "cold":
            # contract keys land after stage 1 so a later-stage failure
            # still leaves a parseable record on stdout
            record.update({"metric": "coldstart_first_step_speedup",
                           "value": None, "unit": "x",
                           "vs_baseline": None})
        _emit(record)

    cold_t = stages["cold"]["train"]
    warm_t = stages["warm_manifest"]["train"]
    # headline (the gated acceptance metric): process start -> trained
    # at every batch signature — the production cold start; a trainer is
    # not "started" while bucket shapes still compile. The serve path is
    # measured and reported (coldstart_serve_speedup,
    # coldstart_*_first_response_s) but not folded into the gate: its
    # total is dominated by model init + server machinery, not compiles.
    speedup = cold_t["all_sigs_s"] / max(warm_t["all_sigs_s"], 1e-9)
    serve_speedup = (stages["cold"]["serve"]["to_first_response_s"]
                     / max(stages["warm_manifest"]["serve"]
                           ["to_first_response_s"], 1e-9))
    bit_identical = (cold_t["loss_hex"] == warm_t["loss_hex"]
                     and stages["cold"]["serve"]["response_hex"]
                     == stages["warm_manifest"]["serve"]["response_hex"])
    zero_misses = warm_t["warm_report"] is not None and \
        sum(warm_t["graph_misses"].values()) == 0
    record.update({
        "metric": "coldstart_first_step_speedup",
        "value": round(speedup, 2),
        "unit": "x",
        "vs_baseline": round(speedup / SPEEDUP_TARGET, 4),
        "coldstart_speedup": round(speedup, 2),
        "coldstart_serve_speedup": round(serve_speedup, 2),
        "coldstart_speedup_target": SPEEDUP_TARGET,
        "coldstart_bit_identical": bit_identical,
        "coldstart_zero_misses_after_warm": zero_misses,
        "coldstart_warm_first_step_latency_s": warm_t["first_step_s"],
        "coldstart_warm_report": warm_t["warm_report"],
        "coldstart_manifest_entries": sum(
            len(open(p).readlines())
            for p in (manifest + ".train", manifest + ".serve")
            if os.path.exists(p)),
    })
    _emit(record)
    ok = (speedup >= SPEEDUP_TARGET and bit_identical and zero_misses)
    return 0 if ok else 1


_T0 = time.perf_counter()
_IMPORT_DONE = _T0

if __name__ == "__main__":
    sys.exit(main())
