"""Per-op device-time breakdown of a compiled step from a jax.profiler trace.

Usage:
  python tools/trace_ops.py bert   # trace bench_bert's TrainStep
  python tools/trace_ops.py resnet # trace bench.py's TrainStep
  python tools/trace_ops.py bert 40 --telemetry-out /tmp/telemetry.json
                                   # also dump an mx.telemetry snapshot
                                   # (op mix, jit-cache hit/miss)

Captures a few steps under jax.profiler.trace, parses the perfetto
trace.json.gz, and prints device ops aggregated by fusion-name prefix,
sorted by total time. The methodology behind PERF.md's trace tables.
"""
from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import re
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_bert_step():
    # trace the published bench configuration: fused layer kernels ON
    # (bench_bert.py sets the same default)
    os.environ.setdefault("MXNET_PALLAS_FUSED", "1")
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import parallel as par
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo.nlp import bert

    # defaults track bench_bert.py so the trace profiles the published
    # configuration
    batch, seq = int(os.environ.get("BENCH_BERT_BATCH", 32)), 512
    rs = np.random.RandomState(0)
    tokens = mx.nd.array(rs.randint(0, 30000, (batch, seq)).astype(np.int32))
    labels = mx.nd.array(rs.randint(0, 30000, (batch, seq)).astype(np.float32))

    class MLMLoss(gloss.SoftmaxCrossEntropyLoss):
        def hybrid_forward(self, F, pred, label):
            return super().hybrid_forward(
                F, pred.reshape(-1, pred.shape[-1]), label.reshape(-1))

    class LossAdapter:
        def __init__(self):
            self._l = MLMLoss()

        def __call__(self, outs, label):
            mlm = outs[1] if isinstance(outs, (list, tuple)) else outs
            return self._l(mlm, label)

    mesh = par.make_mesh({"dp": 1}, devices=jax.devices()[:1])
    if os.environ.get("BENCH_BERT_FUSED", "1") != "0":
        net = bert.BERTForPretrainFused(
            dropout=0.1,
            chunk=int(os.environ.get("BENCH_BERT_CHUNK", 5120)))
        net.initialize()
        net.cast("bfloat16")
        labels_i = mx.nd.array(labels.asnumpy().astype(np.int32))
        step = par.TrainStep(net, lambda outs, *a: outs, "adam", mesh=mesh,
                             loss_only=True,
                             optimizer_params={"learning_rate": 1e-4,
                                               "multi_precision": True})
        return step, ((tokens, labels_i), ())
    net = bert.bert_12_768_12(use_decoder=True, use_pooler=False,
                              use_classifier=False)
    net.initialize()
    net.cast("bfloat16")
    step = par.TrainStep(net, LossAdapter(), "adam", mesh=mesh,
                         optimizer_params={"learning_rate": 1e-4,
                                           "multi_precision": True})
    return step, (tokens, labels)


def build_llama_step():
    """The 0.7B proxy exactly as bench_llama.py runs it (no-remat,
    fused CE, AdamW, bf16) — VERDICT r4: trace the Llama path the way
    BERT was traced."""
    os.environ.setdefault("MXNET_PALLAS_FUSED", "1")
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import parallel as par
    from mxnet_tpu.gluon.model_zoo.nlp.llama import LlamaModel

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from pretrain_llama import CONFIGS

    batch, seq = int(os.environ.get("BENCH_LLAMA_BATCH", 8)), 2048
    cfg = CONFIGS["proxy1b"]
    raw = os.environ.get("LLAMA_REMAT", "").lower()
    remat = (True if raw in ("1", "true", "yes") else
             False if raw in ("", "0", "false", "no") else raw)
    net = LlamaModel(**cfg, remat=remat, fused_ce=True)
    net.initialize()
    net.cast("bfloat16")
    rs = np.random.RandomState(0)
    toks = mx.nd.array(rs.randint(0, cfg["vocab_size"],
                                  (batch, seq)).astype(np.int32))
    labs = mx.nd.array(rs.randint(0, cfg["vocab_size"],
                                  (batch, seq)).astype(np.int32))
    mesh = par.make_mesh({"dp": 1}, devices=jax.devices()[:1])
    step = par.TrainStep(net, lambda outs, *a: outs, "adamw", mesh=mesh,
                         loss_only=True,
                         optimizer_params={"learning_rate": 3e-4,
                                           "wd": 0.1, "beta1": 0.9,
                                           "beta2": 0.95,
                                           "multi_precision": True})
    return step, ((toks, labs), ())


def build_resnet_step():
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import parallel as par
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1

    batch = 256
    net = resnet50_v1(classes=1000,
                      layout=os.environ.get("RESNET_LAYOUT", "NHWC"))
    net.initialize()
    net.cast("bfloat16")
    rs = np.random.RandomState(0)
    images = mx.nd.array(rs.uniform(-1, 1, (batch, 3, 224, 224)).astype(
        np.float32)).astype("bfloat16")
    labels = mx.nd.array(rs.randint(0, 1000, (batch,)).astype(np.float32))
    mesh = par.make_mesh({"dp": 1}, devices=jax.devices()[:1])
    step = par.TrainStep(net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
                         mesh=mesh,
                         optimizer_params={"learning_rate": 0.1,
                                           "momentum": 0.9,
                                           "multi_precision": True})
    return step, (images, labels)


GROUPS = [
    # first so ops dispatched via an engine.bulk fused segment (the jitted
    # module is named "fused_segment", see ops/registry.py::_build_fused)
    # are attributed to bulking rather than the generic fusion bucket
    ("bulk_fused", r"fused_segment"),
    # fused layer kernels (pallas_kernels/fused_layers.py) before the
    # flash groups: their kernel names also contain _fwd/_bwd
    ("pallas_layer", r"_norm_fwd_kernel|_norm_bwd_kernel|_bias_gelu"),
    ("flash_fwd", r"flash|_fwd_kernel"),
    ("flash_bwd", r"dkdv|_bwd_"),
    ("fusion", r"^fusion"),
    ("copy", r"^copy|^bitcast"),
    ("dot", r"^dot|convolution"),
    ("custom-call", r"custom-call"),
    ("transpose", r"transpose"),
    ("rng", r"rng"),
]

# device ops executed by ANY Pallas kernel of ours — tagged "[pallas] "
# in the per-op table (like "[bulk] " for fused segments) so kernel
# adoption is visible straight in the trace, next to the
# mxnet_pallas_dispatch_total{kernel} telemetry counter
PALLAS_PAT = re.compile(
    r"_norm_fwd_kernel|_norm_bwd_kernel|_bias_gelu|_fwd_kernel"
    r"|_bwd_dkdv|_bwd_dq|_bwd_fused|flash")


def classify(name, ctx=""):
    # only the bulk group consults the HLO metadata ctx: the module name
    # lives there, whereas matching every group's pattern against ctx
    # would misbin ops whose OPERAND names mention e.g. "transpose"
    if ctx and re.search(r"fused_segment", ctx):
        return "bulk_fused"
    for g, pat in GROUPS:
        if re.search(pat, name):
            return g
    return "other"


def _event_ctx(e):
    """Trace-event metadata that carries the owning jit module / HLO
    provenance (XLA puts the module name in args, not the event name)."""
    args = e.get("args") or {}
    return " ".join(str(args[k]) for k in ("long_name", "tf_op", "source",
                                           "group_by", "hlo_module")
                    if k in args)


def main():
    from mxnet_tpu.telemetry import pop_telemetry_out_flag

    argv, telemetry_out = pop_telemetry_out_flag(sys.argv[1:])
    which = argv[0] if argv else "bert"
    topn = int(argv[1]) if len(argv) > 1 else 40
    import jax

    if telemetry_out:
        from mxnet_tpu import telemetry

        telemetry.enable()

    step, batch = {"bert": build_bert_step, "resnet": build_resnet_step,
                   "llama": build_llama_step}[which]()
    loss, _ = step(*batch)
    loss.asnumpy()
    step.stage_batch(*batch)
    loss, _ = step(*batch)
    loss.asnumpy()

    tdir = os.environ.get("TRACE_DIR") or tempfile.mkdtemp(prefix="mxtrace_")
    nsteps = 3
    with jax.profiler.trace(tdir):
        for _ in range(nsteps):
            loss, _ = step(*batch)
        loss.asnumpy()

    traces = glob.glob(os.path.join(tdir, "**", "*.trace.json.gz"),
                       recursive=True)
    if not traces:
        print("no trace.json.gz found under", tdir)
        return 1
    with gzip.open(sorted(traces)[-1], "rt") as f:
        data = json.load(f)

    # device-side complete events: pick the pid whose thread names look like
    # TPU/device lanes ("/device:" or "XLA Op" tracks carry the op names)
    events = [e for e in data.get("traceEvents", []) if e.get("ph") == "X"]
    pid_names = {}
    for e in data.get("traceEvents", []):
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_names[e["pid"]] = e["args"].get("name", "")
    dev_pids = {p for p, n in pid_names.items()
                if "TPU" in n or "/device" in n.lower() or "gpu" in n.lower()}
    dev_events = [e for e in events if e["pid"] in dev_pids]
    if not dev_events:
        # fall back: everything that is not a python/host thread
        dev_events = events

    per_op = collections.Counter()
    per_group = collections.Counter()
    total = 0.0
    for e in dev_events:
        name = e.get("name", "?")
        dur = e.get("dur", 0) / 1e3  # us -> ms
        # skip obvious host-side module-level events
        if name.startswith(("jit_", "Thread", "pjit")):
            continue
        ctx = _event_ctx(e)
        if "fused_segment" in name or "fused_segment" in ctx:
            # executed via an engine.bulk fused segment — mark it so the
            # per-op table shows which device time came from bulked
            # imperative chains vs ordinary per-op dispatch
            name = "[bulk] " + name
        elif PALLAS_PAT.search(name) or PALLAS_PAT.search(ctx):
            # executed by one of our Pallas kernels (flash attention or
            # the fused layer kernels) — adoption visible per-op
            name = "[pallas] " + name
        per_op[name] += dur
        per_group[classify(name, ctx)] += dur
        total += dur

    print(f"== {which}: {nsteps} steps, device op time total "
          f"{total:.1f} ms ({total / nsteps:.1f} ms/step) ==")
    print("-- by group (ms/step) --")
    for g, t in per_group.most_common():
        print(f"  {g:12s} {t / nsteps:8.2f}")
    print(f"-- top {topn} ops (ms/step) --")
    for name, t in per_op.most_common(topn):
        print(f"  {t / nsteps:8.3f}  {name[:110]}")
    print("trace dir:", tdir)
    if telemetry_out:
        from mxnet_tpu import telemetry

        telemetry.write_snapshot(telemetry_out)
        print("telemetry snapshot:", telemetry_out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
