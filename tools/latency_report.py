"""Per-stage latency decomposition from request-trace JSONL dumps.

Usage:
  python tools/latency_report.py /tmp/traces.*.jsonl
  python tools/latency_report.py --json /tmp/traces.12345.jsonl

Reads flight-recorder dumps (``MXNET_TRACING_OUT`` / ``/traces`` /
``mx.tracing.dump``) — one JSON object per line, completed traces and
structured events interleaved — and answers the question the serving
histograms cannot: **which stage** makes a p99 slow. Every request
trace is split into its named spans (``ingress.decode``,
``router.queue``, ``router.attempt``, ``batch.wait``, ``dispatch``,
``wire.return``, ``ingress.reply``) and each stage's p50/p99 is
reported alongside its share of end-to-end time.

The three-bucket rollup at the end maps stages onto the same
framing / socket / scheduling decomposition ``tools/serving_bench.py``
stage 8 derives from first principles (codec microbench + socket RTT):

* framing     — ``ingress.decode`` + ``ingress.reply`` (codec seams);
* socket      — ``wire.return`` (the measured socket leg home; the
  outbound leg hides inside router.attempt's wire wait);
* scheduling  — ``router.queue`` + ``batch.wait`` (time spent waiting
  for a thread or a batch slot, not moving bytes).

So ``serving_bench``'s analytical split and this tool's measured split
cross-check each other: derived from traces alone, no benchmark run
needed.

Stage spans may overlap (``router.attempt`` contains the replica-side
spans), so shares are reported against the root request span, not
summed to 100%.

Multi-tenant dumps additionally get a **per-tenant rollup** — spans
are tagged ``model`` + ``slo_class`` at every seam, so the report
groups traces by tenant and prints one table per model (request
p50/p99, TTFT and per-token percentiles for generate traces, shed
counts by reason) plus a preemption rollup pairing beneficiary with
victim ("who preempted whom", with the victim's clean-prefix length).
That answers the multi-tenant question the aggregate table cannot:
WHOSE p99 is slow, and at whose expense. Traces with no ``model`` tag
are the default tenant — absent field = default, same as the wire.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List

# stage -> serving_bench overhead bucket
_BUCKETS = {
    "ingress.decode": "framing",
    "ingress.reply": "framing",
    "wire.return": "socket",
    "router.queue": "scheduling",
    "batch.wait": "scheduling",
}

# presentation order; anything else observed is appended alphabetically
_STAGE_ORDER = ["ingress.decode", "router.queue", "router.attempt",
                "gen.queue", "prefill", "decode.step",
                "batch.wait", "dispatch", "wire.return", "ingress.reply",
                "request"]


def _pctl(xs: List[float], q: float) -> float:
    if not xs:
        return 0.0
    xs = sorted(xs)
    i = min(int(q * len(xs)), len(xs) - 1)
    return xs[i]


def load_traces(paths) -> tuple:
    """Parse dump files -> (traces, events). Unparseable lines are
    counted, not fatal — dumps happen at crash time."""
    traces, events, bad = [], [], 0
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    bad += 1
                    continue
                if "trace_id" in obj and "spans" in obj:
                    traces.append(obj)
                elif "event" in obj:
                    events.append(obj)
    if bad:
        print(f"warning: {bad} unparseable line(s) skipped",
              file=sys.stderr)
    return traces, events


def stage_latencies(traces) -> Dict[str, List[float]]:
    """stage name -> list of per-request durations (ms). A stage that
    appears more than once in a trace (failover retries both
    router.queue and router.attempt) contributes its SUM — the request
    paid all of it."""
    out: Dict[str, List[float]] = {}
    for t in traces:
        per: Dict[str, float] = {}
        for s in t.get("spans", []):
            name = s.get("name")
            dur = s.get("dur")
            if not isinstance(name, str) or \
                    not isinstance(dur, (int, float)):
                continue
            per[name] = per.get(name, 0.0) + dur / 1e3
        for name, ms in per.items():
            out.setdefault(name, []).append(ms)
    return out


def decode_rollup(traces) -> Dict:
    """TTFT vs per-token latency for generate traces (those carrying
    ``prefill`` / ``decode.step`` spans). TTFT is trace start to the
    end of ``prefill`` — the first token is emitted there — so it
    includes queueing and admission, which is what a caller feels.
    Per-token latency is the gap between consecutive ``decode.step``
    span ends inside one trace: the steady-state streaming interval,
    which stays flat only while every step re-hits the one warm
    ``(batch, 1)`` executable."""
    ttfts: List[float] = []
    gaps: List[float] = []
    ntoks: List[int] = []
    for t in traces:
        spans = [s for s in t.get("spans", [])
                 if isinstance(s.get("ts"), (int, float))]
        pre = [s for s in spans if s.get("name") == "prefill"]
        steps = [s for s in spans if s.get("name") == "decode.step"]
        if not pre and not steps:
            continue
        t0 = min(s["ts"] for s in spans)
        if pre:
            first = min(p["ts"] + (p.get("dur") or 0) for p in pre)
            ttfts.append((first - t0) / 1e3)
        ends = sorted(s["ts"] + (s.get("dur") or 0) for s in steps)
        gaps.extend((b - a) / 1e3 for a, b in zip(ends, ends[1:]))
        ntoks.append(len(steps) + (1 if pre else 0))
    if not ntoks:
        return {}
    return {
        "generate_traces": len(ntoks),
        "tokens_p50": _pctl([float(n) for n in ntoks], 0.50),
        "ttft_p50_ms": round(_pctl(ttfts, 0.50), 3),
        "ttft_p99_ms": round(_pctl(ttfts, 0.99), 3),
        "per_token_p50_ms": round(_pctl(gaps, 0.50), 3),
        "per_token_p99_ms": round(_pctl(gaps, 0.99), 3),
    }


def _trace_tenant(t) -> tuple:
    """(model, slo_class) for one trace. Tenant tags ride several
    spans (server root, ``batch.wait``, ``router.generate``); the
    first occurrence wins. No tag anywhere = the default tenant,
    mirroring the wire contract (absent field = default)."""
    model = slo = None
    for s in t.get("spans", []):
        tags = s.get("tags")
        if not isinstance(tags, dict):
            continue
        if model is None and isinstance(tags.get("model"), str):
            model = tags["model"]
        if slo is None and isinstance(tags.get("slo_class"), str):
            slo = tags["slo_class"]
        if model is not None and slo is not None:
            break
    return model or "default", slo or "standard"


def tenant_rollup(traces, events) -> List[Dict]:
    """One row per tenant: request p50/p99 off the root span, decode
    percentiles for generate traces, shed counts by reason from the
    recorder's ``shed`` events."""
    groups: Dict[str, Dict] = {}
    for t in traces:
        model, slo = _trace_tenant(t)
        g = groups.setdefault(model, {"slo_class": slo, "traces": []})
        g["traces"].append(t)
    sheds: Dict[str, Dict[str, int]] = {}
    for e in events:
        if e.get("event") != "shed":
            continue
        m = str(e.get("model", "default"))
        reason = str(e.get("reason", "?"))
        sheds.setdefault(m, {})[reason] = \
            sheds.get(m, {}).get(reason, 0) + 1
    rows = []
    for model in sorted(set(groups) | set(sheds)):
        g = groups.get(model, {"slo_class": "standard", "traces": []})
        ts = g["traces"]
        stages = stage_latencies(ts)
        roots = stages.get("request", []) + stages.get("generate", [])
        statuses: Dict[str, int] = {}
        for t in ts:
            st = t.get("status", "open")
            statuses[st] = statuses.get(st, 0) + 1
        row = {
            "model": model, "slo_class": g["slo_class"],
            "traces": len(ts), "statuses": statuses,
            "request_p50_ms": round(_pctl(roots, 0.50), 3),
            "request_p99_ms": round(_pctl(roots, 0.99), 3),
            "sheds": sheds.get(model, {}),
        }
        dec = decode_rollup(ts)
        if dec:
            row["decode"] = dec
        rows.append(row)
    return rows


def preemption_rollup(events) -> Dict:
    """Pair beneficiary with victim across the recorder's
    ``preempted`` events: who preempted whom, how often, and how long
    the victims' sealed clean prefixes were when the pages were
    taken."""
    pre = [e for e in events if e.get("event") == "preempted"]
    if not pre:
        return {}
    pairs: Dict[str, Dict] = {}
    for e in pre:
        key = (f"{e.get('beneficiary_model', '?')} preempted "
               f"{e.get('victim_model', '?')}")
        p = pairs.setdefault(key, {"count": 0, "victim_tokens": []})
        p["count"] += 1
        vt = e.get("victim_tokens")
        if isinstance(vt, (int, float)):
            p["victim_tokens"].append(float(vt))
    out = {"events": len(pre), "pairs": {}}
    for key, p in sorted(pairs.items()):
        out["pairs"][key] = {
            "count": p["count"],
            "victim_clean_prefix_p50_tokens":
                round(_pctl(p["victim_tokens"], 0.50), 1),
        }
    return out


def report(traces, events) -> Dict:
    stages = stage_latencies(traces)
    roots = stages.get("request", [])
    root_p50 = _pctl(roots, 0.50)

    order = [s for s in _STAGE_ORDER if s in stages]
    order += sorted(s for s in stages if s not in _STAGE_ORDER)

    table = []
    for name in order:
        xs = stages[name]
        p50 = _pctl(xs, 0.50)
        table.append({
            "stage": name, "n": len(xs),
            "p50_ms": round(p50, 3),
            "p99_ms": round(_pctl(xs, 0.99), 3),
            "max_ms": round(max(xs), 3),
            "share_of_request_p50": (round(p50 / root_p50, 3)
                                     if root_p50 else None),
        })

    rollup = {"framing": 0.0, "socket": 0.0, "scheduling": 0.0}
    for name, bucket in _BUCKETS.items():
        rollup[bucket] += _pctl(stages.get(name, []), 0.50)

    statuses: Dict[str, int] = {}
    for t in traces:
        st = t.get("status", "open")
        statuses[st] = statuses.get(st, 0) + 1
    ev_kinds: Dict[str, int] = {}
    for e in events:
        k = e.get("event", "?")
        ev_kinds[k] = ev_kinds.get(k, 0) + 1

    rep = {
        "traces": len(traces),
        "statuses": statuses,
        "events": ev_kinds,
        "stages": table,
        # serving_bench stage-8 cross-check (measured, per-request p50)
        "serving_ingress_overhead_framing_ms": round(rollup["framing"], 3),
        "serving_ingress_overhead_socket_ms": round(rollup["socket"], 3),
        "serving_ingress_overhead_scheduling_ms":
            round(rollup["scheduling"], 3),
    }
    dec = decode_rollup(traces)
    if dec:
        rep["decode"] = dec
    tenants = tenant_rollup(traces, events)
    # the per-tenant table earns its ink only when there IS more than
    # one tenant (or sheds/preemptions name one): a single-tenant dump
    # reads the same as the aggregate table above
    if (len(tenants) > 1 or any(t["sheds"] for t in tenants)
            or any(t["model"] != "default" for t in tenants)):
        rep["tenants"] = tenants
    pre = preemption_rollup(events)
    if pre:
        rep["preemptions"] = pre
    return rep


def _print_table(rep: Dict) -> None:
    print(f"{rep['traces']} trace(s); statuses: {rep['statuses']}")
    if rep["events"]:
        print(f"recorder events: {rep['events']}")
    print()
    hdr = f"{'stage':<16}{'n':>6}{'p50 ms':>10}{'p99 ms':>10}" \
          f"{'max ms':>10}{'share':>8}"
    print(hdr)
    print("-" * len(hdr))
    for row in rep["stages"]:
        share = ("" if row["share_of_request_p50"] is None
                 else f"{row['share_of_request_p50']:.0%}")
        print(f"{row['stage']:<16}{row['n']:>6}{row['p50_ms']:>10.3f}"
              f"{row['p99_ms']:>10.3f}{row['max_ms']:>10.3f}{share:>8}")
    print()
    print("overhead rollup (p50, serving_bench stage-8 buckets):")
    for k in ("framing", "socket", "scheduling"):
        print(f"  {k:<11} "
              f"{rep[f'serving_ingress_overhead_{k}_ms']:.3f} ms")
    dec = rep.get("decode")
    if dec:
        print()
        print(f"decode rollup ({dec['generate_traces']} generate "
              f"trace(s), {dec['tokens_p50']:.0f} tokens p50):")
        print(f"  TTFT        p50 {dec['ttft_p50_ms']:.3f} ms   "
              f"p99 {dec['ttft_p99_ms']:.3f} ms")
        print(f"  per-token   p50 {dec['per_token_p50_ms']:.3f} ms   "
              f"p99 {dec['per_token_p99_ms']:.3f} ms")
    tenants = rep.get("tenants")
    if tenants:
        print()
        print("per-tenant rollup (whose p99):")
        hdr = (f"  {'model':<12}{'slo class':<10}{'n':>6}"
               f"{'p50 ms':>10}{'p99 ms':>10}  sheds")
        print(hdr)
        print("  " + "-" * (len(hdr) - 2))
        for row in tenants:
            shed = ", ".join(f"{k}={v}"
                             for k, v in sorted(row["sheds"].items()))
            print(f"  {row['model']:<12}{row['slo_class']:<10}"
                  f"{row['traces']:>6}{row['request_p50_ms']:>10.3f}"
                  f"{row['request_p99_ms']:>10.3f}  {shed or '-'}")
            dec = row.get("decode")
            if dec:
                print(f"  {'':<12}TTFT p50 {dec['ttft_p50_ms']:.3f} / "
                      f"p99 {dec['ttft_p99_ms']:.3f} ms; per-token "
                      f"p50 {dec['per_token_p50_ms']:.3f} / "
                      f"p99 {dec['per_token_p99_ms']:.3f} ms")
    pre = rep.get("preemptions")
    if pre:
        print()
        print(f"preemptions ({pre['events']} event(s), "
              "who preempted whom):")
        for key, p in pre["pairs"].items():
            print(f"  {key}: {p['count']}x, victim clean prefix p50 "
                  f"{p['victim_clean_prefix_p50_tokens']:g} tokens")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools/latency_report.py",
        description="per-stage p50/p99 decomposition from trace JSONL")
    ap.add_argument("paths", nargs="+", help="trace dump file(s)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON instead of a table")
    args = ap.parse_args(argv)

    traces, events = load_traces(args.paths)
    if not traces:
        print("no completed traces found", file=sys.stderr)
        return 1
    rep = report(traces, events)
    if args.json:
        print(json.dumps(rep, indent=2, sort_keys=True))
    else:
        _print_table(rep)
    return 0


if __name__ == "__main__":
    sys.exit(main())
