"""Chaos check: training under seeded fault injection must match a
fault-free run bit-for-bit.

The fault-tolerance acceptance gate (ISSUE 3): when every injected fault
is *retryable* (comms faults absorbed by the kvstore retry, latency
injection at op dispatch), a training run under a seeded random
injection spec must (a) complete and (b) land on exactly the final loss
and weights of the clean run. Additionally a crash-safe checkpoint
cycle runs mid-loop: the first save attempt is killed by an injected
``checkpoint.write`` fault (previous checkpoint must stay valid), the
save is repeated, the run "crashes", and a fresh model resumes from the
bundle — the resumed tail must match the uninterrupted run bit-for-bit
(params + optimizer counters + RNG stream).

  python tools/chaos_check.py                 # default spec/steps
  python tools/chaos_check.py --steps 40 --seed 11 \
      --spec 'kvstore.push=every:7;kvstore.allreduce=p:0.1' \
      --json /tmp/chaos.json

Exit code 0 = all gates pass. Runs on the CPU oracle mesh
(JAX_PLATFORMS=cpu; the fake cluster flag is set below if absent).
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU oracle env (mirrors the test conftest): must be set before jax init
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8"
                               ).strip()

DEFAULT_SPEC = ("kvstore.push=every:5;kvstore.pull=p:0.05;"
                "kvstore.allreduce=p:0.1;engine.dispatch=latency:0.0001")


def make_data(seed):
    """Synthetic classification data from a PRIVATE numpy RNG — must not
    touch mx.random: the resume gate restores the checkpointed stream
    and a reseed here would silently clobber it (making the RNG half of
    the bit-exactness gate vacuous)."""
    import numpy as np

    rs = np.random.RandomState(seed)
    x = rs.randn(128, 64).astype(np.float32)
    y = rs.randint(0, 10, size=(128,)).astype(np.int32)
    return x, y


def build(seed):
    """Fresh model + trainer + data, deterministically from ``seed``."""
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, in_units=64, activation="relu"))
    net.add(nn.Dense(10, in_units=32))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01}, kvstore="tpu_sync")
    x, y = make_data(seed)
    return net, trainer, x, y


def run(seed, steps, batch_size=32, net=None, trainer=None,
        start_step=0, ckpt_mgr=None, ckpt_at=None, kill_first_save=False):
    """Train ``steps`` minibatch steps; returns (losses, net, trainer).

    ``ckpt_at``: step index at which to save a checkpoint through
    ``ckpt_mgr`` (with ``kill_first_save`` the first attempt runs under
    an injected ``checkpoint.write`` fault and must fail cleanly).
    """
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, fault
    from mxnet_tpu.gluon import loss as gloss

    if net is None:
        net, trainer, x, y = build(seed)
    else:
        x, y = make_data(seed)   # data only; model + RNG state passed in
    loss_fn = gloss.SoftmaxCrossEntropyLoss()
    n = x.shape[0]
    losses = []
    for step in range(start_step, steps):
        lo = (step * batch_size) % n
        xb = mx.nd.array(x[lo:lo + batch_size])
        yb = mx.nd.array(y[lo:lo + batch_size])
        with autograd.record():
            loss = loss_fn(net(xb), yb).mean()
        loss.backward()
        trainer.step(batch_size)
        losses.append(float(loss.asnumpy()))
        if ckpt_mgr is not None and step == ckpt_at:
            if kill_first_save:
                prev = ckpt_mgr.latest_step()
                try:
                    with fault.inject("checkpoint.write=once"):
                        ckpt_mgr.save(step, params=net, trainer=trainer)
                    raise AssertionError(
                        "injected checkpoint.write fault did not fire")
                except fault.FaultInjected:
                    pass
                assert ckpt_mgr.latest_step() == prev, \
                    "killed save corrupted checkpoint discovery"
            ckpt_mgr.save(step, params=net, trainer=trainer)
    return losses, net, trainer


def weights_of(net):
    return {name: p.data().asnumpy()
            for name, p in net._collect_params_with_prefix().items()}


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--steps", type=int, default=24)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--spec", default=DEFAULT_SPEC,
                    help="fault spec for the chaos run (all-retryable)")
    ap.add_argument("--json", default=None,
                    help="write the result summary to this path")
    args = ap.parse_args()

    import numpy as np

    from mxnet_tpu import checkpoint, fault, telemetry

    telemetry.enable()
    summary = {"steps": args.steps, "seed": args.seed, "spec": args.spec,
               "gates": {}}
    ok = True

    # -- gate 1: clean baseline ----------------------------------------
    clean_losses, clean_net, _ = run(args.seed, args.steps)
    print(f"[chaos] clean run: {args.steps} steps, "
          f"final loss {clean_losses[-1]:.6f}")

    # -- gate 2: chaos run matches bit-for-bit -------------------------
    with fault.inject(args.spec, seed=args.seed) as stats:
        chaos_losses, chaos_net, _ = run(args.seed, args.steps)
        injected = {site: dict(v) for site, v in stats().items()}
    total_injected = sum(v["injected"] for v in injected.values())
    losses_equal = chaos_losses == clean_losses
    clean_w, chaos_w = weights_of(clean_net), weights_of(chaos_net)
    weights_equal = all(np.array_equal(a, chaos_w[k])
                        for k, a in clean_w.items())
    summary["gates"]["chaos_matches_clean"] = {
        "pass": bool(losses_equal and weights_equal),
        "faults_injected": injected,
        "final_loss_clean": clean_losses[-1],
        "final_loss_chaos": chaos_losses[-1]}
    per_site = ", ".join(
        "{}:{}".format(s, v["injected"]) for s, v in injected.items())
    print(f"[chaos] chaos run: {total_injected} faults injected "
          f"({per_site})")
    print(f"[chaos] losses identical: {losses_equal}; "
          f"weights bit-exact: {weights_equal}")
    if total_injected == 0:
        print("[chaos] WARNING: spec injected nothing — gate is vacuous")
        ok = False
    ok = ok and losses_equal and weights_equal

    # -- gate 3: kill-during-write + bit-exact resume ------------------
    ckpt_dir = tempfile.mkdtemp(prefix="chaos_ckpt_")
    try:
        mgr = checkpoint.CheckpointManager(ckpt_dir, keep_last=2)
        half = args.steps // 2
        full_losses, full_net, _ = run(
            args.seed, args.steps, ckpt_mgr=mgr, ckpt_at=half,
            kill_first_save=True)
        # "crash": rebuild from nothing, restore, replay the tail
        net2, tr2, _, _ = build(args.seed + 1)   # wrong init on purpose
        meta = mgr.restore(block=net2, trainer=tr2)
        resumed_losses, resumed_net, _ = run(
            args.seed, args.steps, net=net2, trainer=tr2,
            start_step=meta["step"] + 1)
        tail_equal = resumed_losses == full_losses[half + 1:]
        full_w, resumed_w = weights_of(full_net), weights_of(resumed_net)
        resumed_weights_equal = all(np.array_equal(a, resumed_w[k])
                                    for k, a in full_w.items())
        summary["gates"]["crash_resume_bit_exact"] = {
            "pass": bool(tail_equal and resumed_weights_equal),
            "resumed_from_step": meta["step"]}
        print(f"[chaos] resume from step {meta['step']}: tail losses "
              f"identical: {tail_equal}; weights bit-exact: "
              f"{resumed_weights_equal}")
        ok = ok and tail_equal and resumed_weights_equal
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)

    retry_counters = {}
    for s in telemetry.snapshot()["metrics"].get(
            "mxnet_retry_total", {}).get("samples", []):
        retry_counters["/".join(s["labels"].values())] = s["value"]
    summary["retry_counters"] = retry_counters
    summary["ok"] = ok
    if args.json:
        with open(args.json, "w") as f:
            json.dump(summary, f, indent=2)
    print(f"[chaos] retries: {retry_counters or 'none'}")
    print(f"[chaos] {'PASS' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
