"""Chaos check: training under seeded fault injection must match a
fault-free run bit-for-bit.

The fault-tolerance acceptance gate (ISSUE 3): when every injected fault
is *retryable* (comms faults absorbed by the kvstore retry, latency
injection at op dispatch), a training run under a seeded random
injection spec must (a) complete and (b) land on exactly the final loss
and weights of the clean run. Additionally a crash-safe checkpoint
cycle runs mid-loop: the first save attempt is killed by an injected
``checkpoint.write`` fault (previous checkpoint must stay valid), the
save is repeated, the run "crashes", and a fresh model resumes from the
bundle — the resumed tail must match the uninterrupted run bit-for-bit
(params + optimizer counters + RNG stream).

The ELASTIC gate (ISSUE 8) runs the same contract through real process
supervision: 2 workers under ``tools/launch.py --max-restarts 1``, one
SIGKILLed mid-step (after backward, before the optimizer step), the
supervisor restarts it, ``ElasticRunner`` resumes from the newest
bundle — and every rank's loss trajectory (the survivor's THROUGH its
membership-epoch transitions, the victim's resumed tail) must be
bit-identical to an uninterrupted 2-worker run.

The SERVING gate (ISSUE 9) turns the same discipline on the inference
router: a 2-replica ``serving.Router`` under continuous traffic has one
replica killed mid-traffic via ``serving.replica.0`` faults — 100% of
submitted futures must resolve (result or typed error, zero lost/hung),
responses served by the healthy replica must be bit-identical to a
single-replica run at matched buckets, survivor p99 must stay bounded,
and after the fault clears the breaker must re-admit the replica
through a half-open probe.

The PREEMPTION gate (control plane) runs a *scripted preemption
schedule*: rank 1 receives SIGTERM (the cloud's spot-reclaim notice)
twice mid-run, each time checkpointing at the step boundary and
exiting ``PREEMPTED_EXIT_CODE`` for ``launch.py`` to respawn OUTSIDE
the ``--max-restarts`` failure budget (``save_every=0``, so the
graceful-leave bundle is the only resume point). The stitched
trajectory and the survivor's must be bit-identical to an
uninterrupted run, and every incarnation must sustain the baseline
step rate — leave/join as the common case.

The ROLLING-UPGRADE gate (control plane) walks a new model through a
3-replica fleet under continuous traffic (``serving.rolling_upgrade``):
zero lost futures, every response bit-identical to its submit window's
single-replica version oracle, and a poisoned build — the
``serving.upgrade`` fault fires AFTER the first replica already
swapped — must roll the whole fleet back automatically with at least
N-1 replicas healthy throughout.

The WORKER gate (out-of-process serving) is the SERVING gate's contract
against a REAL process death: a 2-worker fleet of ``RemoteReplica``
subprocesses serves paced traffic through the socket ``Ingress``, one
worker is SIGKILLed (-9) mid-traffic — 100% of client requests must
resolve (result or typed error frame), survivor responses must be
bit-identical to an in-process oracle at matched buckets, the dead
worker's breaker must trip and its RESPAWNED process be re-admitted
via half-open probe, and the router/ingress process itself must never
die. A second phase closes the scrape-fed loop: a ``FleetController``
whose only signal channel is ``/metrics`` scrapes must grow the
multi-process fleet under synthetic pressure and shrink it back after
the hold window.

The GENERATE gate (continuous-batching decode) kills a replica worker
process mid-completion: 8 streaming generates are in flight across a
2-worker fleet when one worker takes SIGKILL. A generate does not fail
over mid-stream (replay would duplicate streamed tokens), so the
contract is typed resolution: every casualty handle resolves with the
typed replica error, its streamed tokens are a clean prefix of the
full-recompute oracle completion, its stream is sealed (no token
after the error), completions on the survivor stay bit-identical to
the oracle, and the survivor keeps serving fresh generates after the
kill.

The ZERO gate (ISSUE 19) re-runs the elastic SIGKILL contract with
ZeRO-sharded optimizer state (``partition="zero1"``): each rank's
bundle carries only its OWN optimizer-state shard, so the survivor's
world-shrink transition and the victim's rejoin must each GATHER every
old-world shard bundle and re-shard it into the new (rank, world) plan
— trajectories bit-identical to an uninterrupted sharded run, with the
checkpoint ``zero.json`` manifests proving bundles were written under
BOTH world sizes (the re-shard actually crossed plans).

  python tools/chaos_check.py                 # default spec/steps
  python tools/chaos_check.py --steps 40 --seed 11 \
      --spec 'kvstore.push=every:7;kvstore.allreduce=p:0.1' \
      --json /tmp/chaos.json
  python tools/chaos_check.py --skip-elastic  # in-process gates only
  python tools/chaos_check.py --skip-serving  # training gates only
  python tools/chaos_check.py --skip-zero     # skip the ZeRO re-shard gate

Exit code 0 = all gates pass. Runs on the CPU oracle mesh
(JAX_PLATFORMS=cpu; the fake cluster flag is set below if absent).
"""
from __future__ import annotations

import argparse
import json
import os
import re
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU oracle env (mirrors the test conftest): must be set before jax init
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8"
                               ).strip()

DEFAULT_SPEC = ("kvstore.push=every:5;kvstore.pull=p:0.05;"
                "kvstore.allreduce=p:0.1;engine.dispatch=latency:0.0001")


def make_data(seed):
    """Synthetic classification data from a PRIVATE numpy RNG — must not
    touch mx.random: the resume gate restores the checkpointed stream
    and a reseed here would silently clobber it (making the RNG half of
    the bit-exactness gate vacuous)."""
    import numpy as np

    rs = np.random.RandomState(seed)
    x = rs.randn(128, 64).astype(np.float32)
    y = rs.randint(0, 10, size=(128,)).astype(np.int32)
    return x, y


def build(seed):
    """Fresh model + trainer + data, deterministically from ``seed``."""
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, in_units=64, activation="relu"))
    net.add(nn.Dense(10, in_units=32))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01}, kvstore="tpu_sync")
    x, y = make_data(seed)
    return net, trainer, x, y


def run(seed, steps, batch_size=32, net=None, trainer=None,
        start_step=0, ckpt_mgr=None, ckpt_at=None, kill_first_save=False):
    """Train ``steps`` minibatch steps; returns (losses, net, trainer).

    ``ckpt_at``: step index at which to save a checkpoint through
    ``ckpt_mgr`` (with ``kill_first_save`` the first attempt runs under
    an injected ``checkpoint.write`` fault and must fail cleanly).
    """
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, fault
    from mxnet_tpu.gluon import loss as gloss

    if net is None:
        net, trainer, x, y = build(seed)
    else:
        x, y = make_data(seed)   # data only; model + RNG state passed in
    loss_fn = gloss.SoftmaxCrossEntropyLoss()
    n = x.shape[0]
    losses = []
    for step in range(start_step, steps):
        lo = (step * batch_size) % n
        xb = mx.nd.array(x[lo:lo + batch_size])
        yb = mx.nd.array(y[lo:lo + batch_size])
        with autograd.record():
            loss = loss_fn(net(xb), yb).mean()
        loss.backward()
        trainer.step(batch_size)
        losses.append(float(loss.asnumpy()))
        if ckpt_mgr is not None and step == ckpt_at:
            if kill_first_save:
                prev = ckpt_mgr.latest_step()
                try:
                    with fault.inject("checkpoint.write=once"):
                        ckpt_mgr.save(step, params=net, trainer=trainer)
                    raise AssertionError(
                        "injected checkpoint.write fault did not fire")
                except fault.FaultInjected:
                    pass
                assert ckpt_mgr.latest_step() == prev, \
                    "killed save corrupted checkpoint discovery"
            ckpt_mgr.save(step, params=net, trainer=trainer)
    return losses, net, trainer


def weights_of(net):
    return {name: p.data().asnumpy()
            for name, p in net._collect_params_with_prefix().items()}


# ---------------------------------------------------------------------------
# elastic gate: SIGKILL a worker mid-step under the supervised launcher,
# verify bit-exact rejoin from the newest CheckpointManager bundle.
# ---------------------------------------------------------------------------

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_ELASTIC_WORKER = r'''
import json, os, signal, sys, time
sys.path.insert(0, os.environ["MXNET_REPO_ROOT"])
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"

import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn, loss as gloss
from mxnet_tpu.parallel import elastic

rank = int(os.environ["DMLC_WORKER_ID"])
coord = os.environ["MXNET_ELASTIC_COORD_DIR"]
steps = int(os.environ["ELASTIC_STEPS"])
kill_at = int(os.environ.get("ELASTIC_KILL_AT", "-1"))
kill_rank = int(os.environ.get("ELASTIC_KILL_RANK", "-1"))
incarnation = os.environ.get("MXNET_ELASTIC_RESTART", "0")
step_sleep = float(os.environ.get("ELASTIC_STEP_SLEEP", "0.12"))

mx.random.seed(1234 + rank)
net = nn.HybridSequential()
net.add(nn.Dense(32, in_units=64, activation="relu"))
net.add(nn.Dense(10, in_units=32))
net.initialize(mx.init.Xavier())
trainer = gluon.Trainer(net.collect_params(), "adam",
                        {"learning_rate": 0.01}, kvstore="device")
loss_fn = gloss.SoftmaxCrossEntropyLoss()
rs = np.random.RandomState(100 + rank)    # private: never touch mx.random
x = rs.randn(128, 64).astype(np.float32)
y = rs.randint(0, 10, size=(128,)).astype(np.int32)

runner = elastic.ElasticRunner(
    coord, params=net, trainer=trainer, save_every=1,
    heartbeat_interval=0.25, heartbeat_timeout=1.5, join_timeout=5.0,
    on_epoch=lambda m, rec: print(
        "ELASTIC_EPOCH %d %d left=%s joined=%s"
        % (rank, rec["epoch"], rec["left"], rec["joined"]), flush=True))


def step_fn(step, m):
    lo = (step * 32) % 128
    xb = mx.nd.array(x[lo:lo + 32])
    yb = mx.nd.array(y[lo:lo + 32])
    with autograd.record():
        loss = loss_fn(net(xb), yb).mean()
    loss.backward()
    if rank == kill_rank and step == kill_at and incarnation == "0":
        os.kill(os.getpid(), signal.SIGKILL)   # die MID-step
    trainer.step(32)
    time.sleep(step_sleep)
    return float(loss.asnumpy())


runner.start()
if runner.resumed_from is not None:
    print("ELASTIC_RESUME %d %d" % (rank, runner.start_step), flush=True)
losses = runner.run(step_fn, steps)
out = os.path.join(coord, "losses-r%d-i%s.json" % (rank, incarnation))
with open(out, "w") as f:
    json.dump({"start": runner.start_step, "losses": losses}, f)
print("ELASTIC_OK %d" % rank, flush=True)
'''


def _launch_job(workdir, worker_src, env_extra, launch_args):
    """One supervised 2-worker run of ``worker_src`` under launch.py;
    returns (rc, stdout+stderr, report, coord)."""
    import subprocess

    coord = os.path.join(workdir, "coord")
    report = os.path.join(workdir, "report.json")
    worker = os.path.join(workdir, "worker.py")
    with open(worker, "w") as f:
        f.write(worker_src)
    env = dict(os.environ, MXNET_REPO_ROOT=_REPO_ROOT, **env_extra)
    for k in ("DMLC_PS_ROOT_URI", "DMLC_PS_ROOT_PORT",
              "DMLC_NUM_WORKER", "DMLC_WORKER_ID", "DMLC_ROLE",
              "MXNET_FAULT_SPEC"):
        env.pop(k, None)
    try:
        out = subprocess.run(
            [sys.executable,
             os.path.join(_REPO_ROOT, "tools", "launch.py"),
             "-n", "2", "--poll-interval", "0.05",
             "--restart-backoff", "0.5", "--term-window", "5",
             "--coord-dir", coord, "--report", report,
             *launch_args,
             "--", sys.executable, worker],
            env=env, capture_output=True, text=True, timeout=300)
        rc, text = out.returncode, out.stdout + out.stderr
    except subprocess.TimeoutExpired as e:
        rc = -1
        text = ((e.stdout or "") + (e.stderr or "")
                if isinstance(e.stdout, str) or isinstance(e.stderr, str)
                else "") + "\n[chaos] launcher run timed out"
    # a launcher that died early leaves no report — the gate must FAIL
    # with the captured output, not crash with FileNotFoundError
    try:
        with open(report) as f:
            rep = json.load(f)
    except (OSError, ValueError):
        rep = {"rc": rc, "workers": []}
    return rc, text, rep, coord


def _launch_elastic(workdir, steps, kill_at=-1, kill_rank=-1,
                    max_restarts=0):
    """One supervised 2-worker run; returns (rc, stdout, report, coord)."""
    return _launch_job(
        workdir, _ELASTIC_WORKER,
        {"ELASTIC_STEPS": str(steps),
         "ELASTIC_KILL_AT": str(kill_at),
         "ELASTIC_KILL_RANK": str(kill_rank)},
        ["--max-restarts", str(max_restarts)])


def _read_losses(coord, rank, incarnation):
    with open(os.path.join(
            coord, f"losses-r{rank}-i{incarnation}.json")) as f:
        return json.load(f)


def elastic_gate(summary, steps=30, kill_at=6):
    """SIGKILL rank 1 mid-step under ``launch.py --max-restarts 1``; the
    restarted rank must resume from the newest bundle and every rank's
    final loss must be bit-identical to an uninterrupted 2-worker run."""
    workdir = tempfile.mkdtemp(prefix="chaos_elastic_")
    try:
        a_dir = os.path.join(workdir, "a")
        b_dir = os.path.join(workdir, "b")
        os.makedirs(a_dir)
        os.makedirs(b_dir)
        rc_a, out_a, rep_a, coord_a = _launch_elastic(a_dir, steps)
        print(f"[chaos] elastic baseline: rc {rc_a}, restarts "
              f"{[w['restarts'] for w in rep_a['workers']]}")
        rc_b, out_b, rep_b, coord_b = _launch_elastic(
            b_dir, steps, kill_at=kill_at, kill_rank=1, max_restarts=1)
        by_rank = {w["rank"]: w for w in rep_b["workers"]}
        w1 = by_rank.get(1, {"restarts": 0, "exits": []})
        print(f"[chaos] elastic kill run: rc {rc_b}, rank 1 restarts "
              f"{w1['restarts']}, rank 1 exits "
              f"{[e['signal'] or e['exit_code'] for e in w1['exits']]}")

        checks = {}
        checks["both_runs_clean"] = rc_a == 0 and rc_b == 0
        checks["victim_sigkilled_once"] = (
            w1["restarts"] == 1 and bool(w1["exits"])
            and w1["exits"][0].get("signal") == "SIGKILL")
        resumed = f"ELASTIC_RESUME 1 {kill_at}" in out_b
        checks["resumed_from_newest_bundle"] = resumed
        checks["survivor_saw_epoch_transition"] = \
            "ELASTIC_EPOCH 0 " in out_b

        final_a = final_b = None
        try:
            a0 = _read_losses(coord_a, 0, "0")
            b0 = _read_losses(coord_b, 0, "0")
            checks["survivor_bit_identical"] = \
                a0["losses"] == b0["losses"]
            a1 = _read_losses(coord_a, 1, "0")
            b1 = _read_losses(coord_b, 1, "1")     # resumed incarnation
            checks["victim_tail_bit_identical"] = (
                b1["start"] == kill_at
                and b1["losses"] == a1["losses"][b1["start"]:])
            checks["final_loss_bit_identical"] = \
                b1["losses"][-1] == a1["losses"][-1]
            final_a, final_b = a1["losses"][-1], b1["losses"][-1]
        except (OSError, ValueError, IndexError, KeyError) as e:
            # a worker that never wrote its losses file = gate FAIL
            # with diagnostics, not a chaos_check crash
            checks["loss_files_complete"] = False
            print(f"[chaos]   elastic loss files incomplete: {e}")

        ok = all(checks.values())
        summary["gates"]["elastic_rejoin_bit_exact"] = {
            "pass": ok, "checks": checks,
            "final_loss_uninterrupted": final_a,
            "final_loss_rejoined": final_b}
        for name, v in checks.items():
            print(f"[chaos]   elastic {name}: {v}")
        if not ok:
            tail = "\n".join(out_b.splitlines()[-30:])
            print(f"[chaos] elastic kill-run tail:\n{tail}")
        return ok
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


# ---------------------------------------------------------------------------
# ZeRO gate: the elastic SIGKILL/rejoin contract with SHARDED optimizer
# state — every world transition must re-gather the old world's shard
# bundles and re-shard them into the new plan, bit-exact.
# ---------------------------------------------------------------------------

# same worker, but the trainer partitions its optimizer state (virtual
# ZeRO identity adopted from the elastic membership): each bundle holds
# only this rank's state shard, so restore exercises the gather+re-shard
# path instead of a whole-state read. Ranks share ONE seed and ONE data
# stream: gathering shard bundles across ranks assumes dist_sync
# replication (identical params/state on every rank), which the plain
# elastic worker's per-rank seeds deliberately break
_ZERO_WORKER = (
    _ELASTIC_WORKER
    .replace('kvstore="device")',
             'kvstore="device",\n                        partition="zero1")')
    .replace("mx.random.seed(1234 + rank)", "mx.random.seed(1234)")
    .replace("rs = np.random.RandomState(100 + rank)",
             "rs = np.random.RandomState(100)")
    # keep every bundle: the post-run manifest audit needs the
    # mid-outage world-1 bundles (the survivor's solo plan) to still be
    # on disk after the regrown world-2 saves would have GC'd them
    .replace("save_every=1,", "save_every=1, keep_last=1000,"))


def _launch_zero(workdir, steps, kill_at=-1, kill_rank=-1,
                 max_restarts=0):
    return _launch_job(
        workdir, _ZERO_WORKER,
        {"ELASTIC_STEPS": str(steps),
         "ELASTIC_KILL_AT": str(kill_at),
         "ELASTIC_KILL_RANK": str(kill_rank),
         # slow the schedule down: the victim's resume point is coupled
         # to the survivor's progress (it rejoins at the survivor's
         # newest complete shard group), so the survivor must still be
         # mid-run when the respawned victim finishes importing
         "ELASTIC_STEP_SLEEP": "0.5"},
        # hold the respawn past the 1.5s heartbeat staleness window: a
        # warm re-import can beat it, and a victim back on the board
        # before the survivor's next membership check means no shrink
        # transition ever runs — the exact path this gate exists to test
        ["--max-restarts", str(max_restarts),
         "--restart-backoff", "4.0"])


def _bundle_partition_worlds(coord):
    """World sizes named by the ``zero.json`` manifests across every
    checkpoint bundle under ``coord`` — the evidence that bundles were
    carved under more than one partition plan."""
    worlds = set()
    root = os.path.join(coord, "ckpts")
    try:
        entries = os.listdir(root)
    except OSError:
        return worlds
    for d in entries:
        try:
            with open(os.path.join(root, d, "zero.json")) as f:
                worlds.add(int(json.load(f)["world"]))
        except (OSError, ValueError, KeyError, TypeError):
            pass
    return worlds


def zero_gate(summary, steps=48, kill_at=6):
    """SIGKILL rank 1 mid-step with ZeRO-partitioned trainers. The
    survivor's shrink-to-world-1 transition re-carves its boundary
    bundle under the solo plan; the victim's rejoin gathers the newest
    COMPLETE shard group (the survivor's — its own bundles' peer shards
    were GC'd during the outage), re-shards it into the grown world,
    and skips ahead to the survivor's schedule. Both trajectories must
    be bit-identical to an uninterrupted sharded 2-worker run, and the
    bundle manifests must show plans at BOTH world sizes."""
    workdir = tempfile.mkdtemp(prefix="chaos_zero_")
    try:
        a_dir = os.path.join(workdir, "a")
        b_dir = os.path.join(workdir, "b")
        os.makedirs(a_dir)
        os.makedirs(b_dir)
        rc_a, out_a, rep_a, coord_a = _launch_zero(a_dir, steps)
        print(f"[chaos] zero baseline: rc {rc_a}, restarts "
              f"{[w['restarts'] for w in rep_a['workers']]}")
        rc_b, out_b, rep_b, coord_b = _launch_zero(
            b_dir, steps, kill_at=kill_at, kill_rank=1, max_restarts=1)
        by_rank = {w["rank"]: w for w in rep_b["workers"]}
        w1 = by_rank.get(1, {"restarts": 0, "exits": []})
        print(f"[chaos] zero kill run: rc {rc_b}, rank 1 restarts "
              f"{w1['restarts']}, rank 1 exits "
              f"{[e['signal'] or e['exit_code'] for e in w1['exits']]}")

        checks = {}
        checks["both_runs_clean"] = rc_a == 0 and rc_b == 0
        checks["victim_sigkilled_once"] = (
            w1["restarts"] == 1 and bool(w1["exits"])
            and w1["exits"][0].get("signal") == "SIGKILL")
        # the victim's resume step floats with the survivor's progress
        # (newest complete shard group) — require evidence it restored
        # at or past its own pre-kill bundle, never before it
        m = re.search(r"ELASTIC_RESUME 1 (\d+)", out_b)
        checks["resumed_from_complete_shard_group"] = \
            m is not None and int(m.group(1)) >= kill_at
        checks["survivor_saw_epoch_transition"] = \
            "ELASTIC_EPOCH 0 " in out_b
        worlds = _bundle_partition_worlds(coord_b)
        checks["bundles_sharded_at_both_worlds"] = {1, 2} <= worlds

        final_a = final_b = None
        try:
            a0 = _read_losses(coord_a, 0, "0")
            b0 = _read_losses(coord_b, 0, "0")
            checks["survivor_bit_identical"] = \
                a0["losses"] == b0["losses"]
            a1 = _read_losses(coord_a, 1, "0")
            b1 = _read_losses(coord_b, 1, "1")     # resumed incarnation
            checks["victim_tail_bit_identical"] = (
                b1["start"] >= kill_at
                and len(b1["losses"]) > 0
                and b1["losses"] == a1["losses"][b1["start"]:])
            final_a, final_b = a1["losses"][-1], b1["losses"][-1]
        except (OSError, ValueError, IndexError, KeyError) as e:
            checks["loss_files_complete"] = False
            print(f"[chaos]   zero loss files incomplete: {e}")

        ok = all(checks.values())
        summary["gates"]["zero_rejoin_resharded_bit_exact"] = {
            "pass": ok, "checks": checks,
            "bundle_worlds": sorted(worlds),
            "final_loss_uninterrupted": final_a,
            "final_loss_rejoined": final_b}
        for name, v in checks.items():
            print(f"[chaos]   zero {name}: {v}")
        if not ok:
            tail = "\n".join(out_b.splitlines()[-30:])
            print(f"[chaos] zero kill-run tail:\n{tail}")
        return ok
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


# ---------------------------------------------------------------------------
# preemption gate: a scripted preemption schedule (SIGTERM = the cloud's
# spot reclaim notice) must cost zero bits and sustain throughput —
# leave/join as the COMMON case, not a failure.
# ---------------------------------------------------------------------------

_PREEMPT_WORKER = r'''
import json, os, signal, sys, time
sys.path.insert(0, os.environ["MXNET_REPO_ROOT"])
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"

import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn, loss as gloss
from mxnet_tpu.parallel import elastic

rank = int(os.environ["DMLC_WORKER_ID"])
coord = os.environ["MXNET_ELASTIC_COORD_DIR"]
steps = int(os.environ["ELASTIC_STEPS"])
schedule = [int(s) for s in os.environ.get("PREEMPT_AT", "").split(",")
            if s]
preempt_rank = int(os.environ.get("PREEMPT_RANK", "-1"))
incarnation = int(os.environ.get("MXNET_ELASTIC_RESTART", "0"))
step_sleep = float(os.environ.get("ELASTIC_STEP_SLEEP", "0.12"))

mx.random.seed(1234 + rank)
net = nn.HybridSequential()
net.add(nn.Dense(32, in_units=64, activation="relu"))
net.add(nn.Dense(10, in_units=32))
net.initialize(mx.init.Xavier())
trainer = gluon.Trainer(net.collect_params(), "adam",
                        {"learning_rate": 0.01}, kvstore="device")
loss_fn = gloss.SoftmaxCrossEntropyLoss()
rs = np.random.RandomState(100 + rank)    # private: never touch mx.random
x = rs.randn(128, 64).astype(np.float32)
y = rs.randint(0, 10, size=(128,)).astype(np.int32)

# save_every=0: the graceful-leave checkpoint is the ONLY bundle this
# rank writes — resume correctness rides entirely on the preemption
# protocol, which is the point of the gate
runner = elastic.ElasticRunner(
    coord, params=net, trainer=trainer, save_every=0,
    heartbeat_interval=0.25, heartbeat_timeout=1.5, join_timeout=5.0,
    on_epoch=lambda m, rec: print(
        "ELASTIC_EPOCH %d %d left=%s joined=%s"
        % (rank, rec["epoch"], rec["left"], rec["joined"]), flush=True))
runner.install_preemption_handler()
losses = []


def step_fn(step, m):
    lo = (step * 32) % 128
    xb = mx.nd.array(x[lo:lo + 32])
    yb = mx.nd.array(y[lo:lo + 32])
    with autograd.record():
        loss = loss_fn(net(xb), yb).mean()
    loss.backward()
    if rank == preempt_rank and incarnation < len(schedule) \
            and step == schedule[incarnation]:
        # the scripted reclaim notice arrives MID-step; the handler only
        # flags the runner — this step still completes, the leave is at
        # the boundary
        os.kill(os.getpid(), signal.SIGTERM)
    trainer.step(32)
    losses.append(float(loss.asnumpy()))
    time.sleep(step_sleep)
    return losses[-1]


runner.start()
if runner.resumed_from is not None:
    print("ELASTIC_RESUME %d %d" % (rank, runner.start_step), flush=True)
t0 = time.perf_counter()
rc = 0
try:
    runner.run(step_fn, steps)
except elastic.Preempted as e:
    print("ELASTIC_PREEMPTED %d %d" % (rank, e.step), flush=True)
    rc = e.exit_code
seconds = time.perf_counter() - t0
out = os.path.join(coord, "losses-r%d-i%d.json" % (rank, incarnation))
with open(out, "w") as f:
    json.dump({"start": runner.start_step, "losses": losses,
               "seconds": seconds}, f)
print("ELASTIC_OK %d" % rank, flush=True)
sys.exit(rc)
'''


def _launch_preempt(workdir, steps, schedule=(), preempt_rank=-1):
    return _launch_job(
        workdir, _PREEMPT_WORKER,
        {"ELASTIC_STEPS": str(steps),
         "PREEMPT_AT": ",".join(str(s) for s in schedule),
         "PREEMPT_RANK": str(preempt_rank)},
        # fail-fast on real failures; preemptions ride their own budget
        ["--max-restarts", "0", "--max-preempt-restarts", "4"])


def preemption_gate(summary, steps=30, schedule=(6, 14)):
    """Rank 1 is preempted TWICE on a schedule (SIGTERM mid-step →
    graceful checkpoint-then-leave → supervisor respawns it outside the
    restart budget). Gates: the stitched trajectory is bit-identical to
    an uninterrupted run, the survivor's too, preemptions never touch
    the failure budget, every leave checkpoints (save_every=0: there is
    no other bundle), and per-incarnation step throughput is sustained."""
    workdir = tempfile.mkdtemp(prefix="chaos_preempt_")
    try:
        a_dir = os.path.join(workdir, "a")
        b_dir = os.path.join(workdir, "b")
        os.makedirs(a_dir)
        os.makedirs(b_dir)
        rc_a, out_a, rep_a, coord_a = _launch_preempt(a_dir, steps)
        print(f"[chaos] preempt baseline: rc {rc_a}")
        rc_b, out_b, rep_b, coord_b = _launch_preempt(
            b_dir, steps, schedule=schedule, preempt_rank=1)
        by_rank = {w["rank"]: w for w in rep_b["workers"]}
        w1 = by_rank.get(1, {"restarts": 0, "preemptions": 0,
                             "exits": []})
        print(f"[chaos] preempt run: rc {rc_b}, rank 1 preemptions "
              f"{w1['preemptions']}, restarts {w1['restarts']}, exits "
              f"{[e['exit_code'] for e in w1['exits']]}")

        checks = {}
        checks["both_runs_clean"] = rc_a == 0 and rc_b == 0
        checks["preemptions_outside_restart_budget"] = (
            w1["preemptions"] == len(schedule)
            and w1["restarts"] == 0
            and [e["exit_code"] for e in w1["exits"]]
            == [75] * len(schedule) + [0])
        checks["every_leave_checkpointed"] = all(
            f"ELASTIC_PREEMPTED 1 {s}" in out_b for s in schedule)
        checks["resumed_at_each_boundary"] = all(
            f"ELASTIC_RESUME 1 {s + 1}" in out_b for s in schedule)
        # the survivor's epoch protocol observed the fast leave AND the
        # rejoin (the survivor may legitimately finish its own steps
        # before LATER preemption cycles complete — respawn pays the
        # interpreter/jax import — so gate on the first cycle, not all)
        checks["survivor_saw_leave_and_join"] = (
            "left=[1]" in out_b and "joined=[1]" in out_b)

        rate_floor = None
        try:
            a0 = _read_losses(coord_a, 0, "0")
            b0 = _read_losses(coord_b, 0, "0")
            checks["survivor_bit_identical"] = \
                a0["losses"] == b0["losses"]
            a1 = _read_losses(coord_a, 1, "0")
            parts = [_read_losses(coord_b, 1, str(i))
                     for i in range(len(schedule) + 1)]
            stitched = [v for p in parts for v in p["losses"]]
            checks["victim_trajectory_bit_identical"] = \
                stitched == a1["losses"]
            checks["incarnations_start_at_commit"] = all(
                parts[i + 1]["start"] == schedule[i] + 1
                for i in range(len(schedule)))
            # sustained throughput: every incarnation's steady step rate
            # within a generous factor of the uninterrupted run's (the
            # preemption machinery must not tax the steps themselves)
            base_rate = len(a1["losses"]) / max(a1["seconds"], 1e-9)
            rates = [len(p["losses"]) / max(p["seconds"], 1e-9)
                     for p in parts if p["losses"]]
            rate_floor = min(rates) / base_rate if rates else 0.0
            checks["throughput_sustained"] = rate_floor >= 0.3
        except (OSError, ValueError, IndexError, KeyError) as e:
            checks["loss_files_complete"] = False
            print(f"[chaos]   preempt loss files incomplete: {e}")

        ok = all(checks.values())
        summary["gates"]["preemption_schedule_bit_exact"] = {
            "pass": ok, "checks": checks, "schedule": list(schedule),
            "rank1_preemptions": w1.get("preemptions"),
            "rate_vs_baseline": rate_floor}
        for name, v in checks.items():
            print(f"[chaos]   preempt {name}: {v}")
        if rate_floor is not None:
            print(f"[chaos]   preempt min incarnation rate: "
                  f"{rate_floor:.2f}x baseline")
        if not ok:
            tail = "\n".join(out_b.splitlines()[-30:])
            print(f"[chaos] preempt run tail:\n{tail}")
        return ok
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


# ---------------------------------------------------------------------------
# serving gate: kill one Router replica mid-traffic via serving.replica
# faults; zero lost futures, survivor bit-identity, breaker re-admission.
# ---------------------------------------------------------------------------

SERVING_SLO_MS = 100.0


def _serving_net(seed=0):
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn

    net = nn.Dense(16, in_units=32)
    net.initialize()
    rs = np.random.RandomState(seed)
    net.weight.set_data(mx.nd.array(
        rs.randn(16, 32).astype(np.float32)))
    net.bias.set_data(mx.nd.array(rs.randn(16).astype(np.float32)))
    net.hybridize()
    return net


def _decode_net(seed=7):
    """Token model for the generate gate — seeded so worker-process
    weights are bit-identical to the in-process oracle's."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.nlp import LlamaModel

    mx.random.seed(seed)
    net = LlamaModel(vocab_size=64, num_layers=2, units=32,
                     hidden_size=64, num_heads=4, num_kv_heads=2,
                     rope_theta=10000.0, eps=1e-6)
    net.initialize()
    net(mx.nd.zeros((1, 2), dtype="int32"))    # materialize shapes
    net.hybridize()
    return net


def _failover_trace(traces, victim):
    """The flight-recorder record that EXPLAINS a failover: one trace
    whose span chain reads dispatch-on-victim -> typed failure ->
    re-dispatch-on-survivor -> ok, all under one trace_id. Returns the
    record (or None)."""
    for t in traces:
        attempts = [s for s in t.get("spans", [])
                    if s.get("name") == "router.attempt"]
        victim_failed = any(
            s.get("tags", {}).get("replica") == victim
            and s.get("tags", {}).get("outcome") not in (None, "ok")
            for s in attempts)
        survivor_ok = any(
            s.get("tags", {}).get("replica") != victim
            and s.get("tags", {}).get("outcome") == "ok"
            for s in attempts)
        # the attempt chain must share the trace id (batch spans are
        # dict-copied from the owning sibling trace and keep its id)
        one_id = all(s.get("trace_id") == t.get("trace_id")
                     for s in attempts)
        if victim_failed and survivor_ok and one_id \
                and t.get("status") == "ok":
            return t
    return None


def serving_gate(summary):
    """Kill replica 0 of a 2-replica Router mid-traffic (``serving.
    replica.0=every:1``), then clear the fault and wait for half-open
    re-admission. Gates: every submitted future resolves (zero lost),
    responses are bit-identical to a single-replica run at matched
    buckets, survivor p99 stays bounded, replica 0 trips and is
    re-admitted."""
    import time as _time

    import numpy as np

    from mxnet_tpu import fault as flt
    from mxnet_tpu import serving, tracing
    from mxnet_tpu.base import MXNetError

    os.environ["MXNET_COMM_RETRY_DELAY"] = "0.01"
    os.environ["MXNET_SERVING_BREAKER_FAILURES"] = "2"
    os.environ["MXNET_SERVING_BREAKER_COOLDOWN"] = "0.4"

    # flight recorder on: the gate must not just survive the kill, it
    # must be able to EXPLAIN it from the dumped trace afterwards
    tracing.reset()
    tracing.enable()

    grid = dict(batch_buckets=(2, 4, 8), shape_buckets=[(32,)],
                slo_ms=SERVING_SLO_MS)
    samples = [np.random.RandomState(1000 + i).randn(32).astype(np.float32)
               for i in range(32)]

    # single-replica reference: the bit-identity oracle (same grid)
    ref_srv = serving.Server(_serving_net(), name="oracle", **grid)
    ref_srv.start()
    refs = [ref_srv.submit(x).result(timeout=60) for x in samples]
    ref_srv.stop()

    replicas = [serving.Server(_serving_net(), name=f"rep{i}", **grid)
                for i in range(2)]
    router = serving.Router(replicas, slo_ms=SERVING_SLO_MS,
                            dispatch_timeout_s=2.0)
    router.start()
    checks = {}
    lat_clean, lat_fault = [], []
    records = []        # (sample_idx, future, phase, t_submit)

    def submit_phase(n, phase, lats, pace_s=0.004):
        for i in range(n):
            idx = i % len(samples)
            t0 = _time.perf_counter()
            try:
                fut = router.submit(samples[idx])
            except MXNetError:
                records.append((idx, None, phase, t0))  # typed sync shed
                continue
            fut.add_done_callback(
                lambda f, t0=t0: lats.append(_time.perf_counter() - t0)
                if not f.exception() else None)
            records.append((idx, fut, phase, t0))
            _time.sleep(pace_s)

    try:
        submit_phase(60, "clean", lat_clean)
        flt.install("serving.replica.0=every:1")
        submit_phase(80, "fault", lat_fault)        # the kill window
        injected = flt.stats()["serving.replica.0"]["injected"]
        flt.clear()
        # recovery: keep trickling traffic until the breaker closes and
        # replica 0 serves again (half-open probe re-admission)
        readmitted = False
        rep0_ok_at_clear = router.stats()["replicas"][0]["ok"]
        deadline = _time.time() + 20
        while _time.time() < deadline:
            submit_phase(8, "recover", [])
            st = {r["name"]: r for r in router.stats()["replicas"]}
            if st["rep0"]["state"] == "closed" and \
                    st["rep0"]["ok"] > rep0_ok_at_clear:
                readmitted = True
                break
            _time.sleep(0.1)

        n_ok = n_typed = n_lost = n_bits_bad = 0
        for idx, fut, phase, _t0 in records:
            if fut is None:
                n_typed += 1            # synchronous typed shed
                continue
            try:
                out = fut.result(timeout=30)
            except MXNetError:
                n_typed += 1
                continue
            except Exception:           # noqa: BLE001 - untyped = fail
                n_lost += 1
                continue
            n_ok += 1
            if not np.array_equal(out, refs[idx]):
                n_bits_bad += 1
        undone = sum(1 for _i, f, _p, _t in records
                     if f is not None and not f.done())
        stats = router.stats()
        by_name = {r["name"]: r for r in stats["replicas"]}

        def p99(xs):
            xs = sorted(xs)
            return xs[min(len(xs) - 1, int(0.99 * len(xs)))] if xs else 0.0

        p99_clean, p99_fault = p99(lat_clean), p99(lat_fault)
        bound = 3.0 * SERVING_SLO_MS / 1e3
        checks["fault_actually_injected"] = injected > 0
        checks["zero_lost_futures"] = n_lost == 0 and undone == 0
        checks["all_resolutions_typed"] = n_typed + n_ok == len(records)
        checks["survivor_bit_identical"] = n_bits_bad == 0 and n_ok > 0
        checks["replica_tripped"] = by_name["rep0"]["trips"] >= 1
        checks["replica_readmitted_by_probe"] = readmitted
        checks["survivor_p99_bounded"] = p99_fault <= bound
        from mxnet_tpu import tracing as _tr
        explained = _failover_trace(_tr.recorder().traces(), "rep0")
        checks["flight_recorder_explains_failover"] = \
            explained is not None
        ok = all(checks.values())
        summary["gates"]["serving_failover_zero_lost"] = {
            "pass": ok, "checks": checks,
            "requests": len(records), "ok": n_ok,
            "typed_errors": n_typed, "lost": n_lost + undone,
            "failovers": stats["failovers"],
            "rep0_trips": by_name["rep0"]["trips"],
            "p99_clean_ms": round(p99_clean * 1e3, 2),
            "p99_fault_ms": round(p99_fault * 1e3, 2),
            "p99_bound_ms": bound * 1e3,
            "explaining_trace": (explained or {}).get("trace_id")}
        print(f"[chaos] serving: {len(records)} requests, {n_ok} ok, "
              f"{n_typed} typed errors, {n_lost + undone} lost; "
              f"{stats['failovers']} failovers; p99 clean/fault "
              f"{p99_clean * 1e3:.1f}/{p99_fault * 1e3:.1f} ms")
        for name, v in checks.items():
            print(f"[chaos]   serving {name}: {v}")
        return ok
    finally:
        flt.clear()
        router.stop(drain=False, timeout=30)
        tracing.disable()


# ---------------------------------------------------------------------------
# rolling-upgrade gate: walk a new model through a 3-replica fleet under
# continuous traffic — zero lost futures, every response bit-identical to
# SOME version's single-replica oracle, and a poisoned build triggers
# automatic rollback with the fleet never dropping below N-1 healthy.
# ---------------------------------------------------------------------------

def upgrade_gate(summary):
    """Rolling upgrade of a 3-replica Router under paced traffic.

    Phase 1: upgrade v1 -> v2 (``rolling_upgrade``; one replica drains
    its bake while N-1 serve). Phase 2: a poisoned v3 rollout — the
    ``serving.upgrade`` fault site fires on the SECOND replica, after
    the first already swapped — must roll the fleet back to v2
    automatically (:class:`UpgradeRolledBack`). Gates: zero lost
    futures end-to-end, every response bit-identical to its submit
    window's version oracle (v1 before / v2 after, the transient window
    may serve either side of the swap), version agreement after each
    phase, fleet >= N-1 healthy throughout, and the fleet still serving
    v2 after the rollback."""
    import threading as _threading
    import time as _time

    import numpy as np

    from mxnet_tpu import fault as flt
    from mxnet_tpu import serving
    from mxnet_tpu.base import MXNetError

    grid = dict(batch_buckets=(2, 4, 8), shape_buckets=[(32,)],
                slo_ms=SERVING_SLO_MS)
    samples = [np.random.RandomState(2000 + i).randn(32).astype(np.float32)
               for i in range(24)]

    # per-version single-replica oracles (matched grid = matched buckets)
    oracle = {}
    for ver, seed in (("v1", 0), ("v2", 1), ("v3", 2)):
        srv = serving.Server(_serving_net(seed), name=f"oracle_{ver}",
                             **grid)
        srv.start()
        oracle[ver] = [srv.submit(x).result(timeout=60) for x in samples]
        srv.stop()

    replicas = [serving.Server(_serving_net(0), name=f"urep{i}", **grid)
                for i in range(3)]
    router = serving.Router(replicas, slo_ms=SERVING_SLO_MS,
                            dispatch_timeout_s=2.0)
    router.start()

    records = []            # (sample_idx, future, t_submit)
    rec_lock = _threading.Lock()
    stop_traffic = _threading.Event()
    min_healthy = [len(replicas)]

    def traffic():
        i = 0
        while not stop_traffic.is_set():
            idx = i % len(samples)
            i += 1
            t0 = _time.perf_counter()
            try:
                fut = router.submit(samples[idx])
            except MXNetError:
                fut = None          # typed synchronous shed
            with rec_lock:
                records.append((idx, fut, t0))
            healthy = sum(1 for r in router.stats()["replicas"]
                          if r["state"] == "closed"
                          and not r["draining"])
            min_healthy[0] = min(min_healthy[0], healthy)
            _time.sleep(0.004)

    checks = {}
    t = _threading.Thread(target=traffic, daemon=True)
    t.start()
    try:
        _time.sleep(0.4)                                # v1 window
        t_up0 = _time.perf_counter()
        out = serving.rolling_upgrade(
            router, lambda s: _serving_net(1), bake_s=0.25)
        t_up1 = _time.perf_counter()
        versions = [r["server"].model_version
                    for r in router.replicas()]
        checks["upgrade_version_agreement"] = (
            versions == [out["version"]] * 3
            and len(out["upgraded"]) == 3)
        _time.sleep(0.4)                                # v2 window

        # poisoned v3: first replica swaps, the second's fault fires —
        # the whole rollout must roll back
        t_bad0 = _time.perf_counter()
        flt.install("serving.upgrade=nth:2")
        rolled_back = False
        try:
            serving.rolling_upgrade(
                router, lambda s: _serving_net(2), bake_s=0.25)
        except serving.UpgradeRolledBack:
            rolled_back = True
        finally:
            flt.clear()
        t_bad1 = _time.perf_counter()
        checks["poisoned_build_rolled_back"] = rolled_back
        checks["rollback_version_agreement"] = (
            [r["server"].model_version for r in router.replicas()]
            == [out["version"]] * 3)
        _time.sleep(0.4)                                # v2-again window
    finally:
        stop_traffic.set()
        t.join(timeout=10)

    try:
        n_ok = n_typed = n_lost = n_bits_bad = 0
        for idx, fut, t0 in records:
            if fut is None:
                n_typed += 1
                continue
            try:
                got = fut.result(timeout=30)
            except MXNetError:
                n_typed += 1
                continue
            except Exception:       # noqa: BLE001 - untyped = lost
                n_lost += 1
                continue
            n_ok += 1
            # window classification is by SUBMIT time; a request queued
            # just before a rollout can be dispatched just after its
            # first swap, so each rollout's "either version" window
            # extends BACKWARD by the maximum legitimate queue dwell
            # (the request deadline = the SLO — older than that it
            # would have expired, not served)
            margin = SERVING_SLO_MS / 1e3 + 0.05
            if t0 < t_up0 - margin:
                allowed = ("v1",)
            elif t0 < t_up1:
                allowed = ("v1", "v2")     # mid-rollout: either side
            elif t0 < t_bad0 - margin:
                allowed = ("v2",)
            elif t0 < t_bad1:
                allowed = ("v2", "v3")     # poisoned window pre-rollback
            else:
                allowed = ("v2",)          # rollback restored v2
            if not any(np.array_equal(got, oracle[v][idx])
                       for v in allowed):
                n_bits_bad += 1
        undone = sum(1 for _i, f, _t in records
                     if f is not None and not f.done())
        checks["zero_lost_futures"] = n_lost == 0 and undone == 0
        checks["responses_match_version_oracles"] = \
            n_bits_bad == 0 and n_ok > 0
        checks["fleet_never_below_n_minus_1"] = \
            min_healthy[0] >= len(replicas) - 1
        ok = all(checks.values())
        summary["gates"]["rolling_upgrade_zero_lost"] = {
            "pass": ok, "checks": checks, "requests": len(records),
            "ok": n_ok, "typed_errors": n_typed,
            "lost": n_lost + undone, "bits_bad": n_bits_bad,
            "min_healthy": min_healthy[0],
            "upgrade_seconds": round(t_up1 - t_up0, 2)}
        print(f"[chaos] upgrade: {len(records)} requests, {n_ok} ok, "
              f"{n_typed} typed, {n_lost + undone} lost, "
              f"{n_bits_bad} bit-mismatched; min healthy "
              f"{min_healthy[0]}/3; rollout {t_up1 - t_up0:.2f}s")
        for name, v in checks.items():
            print(f"[chaos]   upgrade {name}: {v}")
        return ok
    finally:
        flt.clear()
        router.stop(drain=False, timeout=30)


# ---------------------------------------------------------------------------
# worker gate: SIGKILL an out-of-process replica WORKER under paced
# traffic flowing through the socket ingress — the crash-isolation
# contract. Every client request resolves (result or typed error),
# survivors stay bit-identical to the oracle, the dead worker's breaker
# trips and its respawn is re-admitted via half-open probe, and the
# router/ingress process itself never dies. A second phase closes the
# scrape-fed loop: a FleetController whose ONLY signal channel is
# /metrics scrapes grows the multi-process fleet under synthetic
# pressure and shrinks it back after the hold window.
# ---------------------------------------------------------------------------

def worker_gate(summary):
    """Crash-isolated worker fleet under ingress traffic + scrape-fed
    scaling. Two gates written to ``summary``:
    ``worker_crash_isolation_zero_lost`` and
    ``scrape_fed_scale_multiprocess``."""
    import signal as _signal
    import time as _time

    import numpy as np

    from mxnet_tpu import serving, tracing
    from mxnet_tpu.base import MXNetError

    os.environ["MXNET_COMM_RETRY_DELAY"] = "0.01"
    os.environ["MXNET_SERVING_BREAKER_FAILURES"] = "2"
    os.environ["MXNET_SERVING_BREAKER_COOLDOWN"] = "0.4"

    # flight recorder on, in THIS process and (via env) the worker
    # processes: the SIGKILL below must leave an explaining trace
    tracing.reset()
    tracing.enable()
    os.environ["MXNET_TRACING"] = "1"

    tools_dir = os.path.dirname(os.path.abspath(__file__))
    grid = dict(batch_buckets=(2, 4), shape_buckets=[(32,)],
                slo_ms=SERVING_SLO_MS)
    samples = [np.random.RandomState(2000 + i).randn(32).astype(np.float32)
               for i in range(32)]

    # single-replica in-process oracle: the bit-identity reference the
    # worker PROCESSES must match at the same buckets
    ref_srv = serving.Server(_serving_net(), name="oracle", **grid)
    ref_srv.start()
    refs = [ref_srv.submit(x).result(timeout=120) for x in samples]
    ref_srv.stop()

    def make_worker(i):
        return serving.RemoteReplica(
            "chaos_check:_serving_net", name=f"w{i}",
            python_paths=[tools_dir], respawn_backoff_s=0.3,
            spawn_timeout_s=300, **grid)

    workers = [make_worker(i) for i in range(2)]
    router = serving.Router(workers, slo_ms=SERVING_SLO_MS,
                            dispatch_timeout_s=2.0)
    t_spawn0 = _time.time()
    router.start()          # spawns both worker processes, warm + hello
    print(f"[chaos] worker: 2 worker processes up in "
          f"{_time.time() - t_spawn0:.1f}s (pids "
          f"{[w.proc.pid for w in workers]})")
    ing = serving.Ingress(router, window=64)
    ing.start()
    cli = serving.IngressClient("127.0.0.1", ing.port)
    checks = {}
    records = []            # (sample_idx, future)

    def submit_phase(n, pace_s=0.004):
        for i in range(n):
            idx = i % len(samples)
            records.append((idx, cli.submit(samples[idx])))
            _time.sleep(pace_s)

    try:
        # -- gate 8: kill one worker PROCESS mid-traffic ---------------
        submit_phase(40)                    # clean window
        victim_pid = workers[0].proc.pid
        submit_phase(10)
        os.kill(victim_pid, _signal.SIGKILL)
        submit_phase(70)                    # the kill + failover window
        # recovery: trickle traffic until the respawned worker is
        # re-admitted (breaker CLOSED again and serving)
        readmitted = False
        w0_ok_at_kill = {r["name"]: r for r in
                         router.stats()["replicas"]}["w0"]["ok"]
        deadline = _time.time() + 90
        while _time.time() < deadline:
            submit_phase(8)
            st = {r["name"]: r for r in router.stats()["replicas"]}
            if st["w0"]["state"] == "closed" and \
                    st["w0"]["ok"] > w0_ok_at_kill and \
                    workers[0].n_restarts >= 1:
                readmitted = True
                break
            _time.sleep(0.1)

        n_ok = n_typed = n_lost = n_bits_bad = 0
        for idx, fut in records:
            try:
                out = fut.result(timeout=60)
            except MXNetError:
                n_typed += 1                # typed = resolved
                continue
            except Exception:   # noqa: BLE001 - untyped = lost
                n_lost += 1
                continue
            n_ok += 1
            if not np.array_equal(out, refs[idx]):
                n_bits_bad += 1
        undone = sum(1 for _i, f in records if not f.done())
        by_name = {r["name"]: r for r in router.stats()["replicas"]}
        edge_alive = router.is_running and ing.is_running
        final_ok = False
        if edge_alive:
            try:
                final_ok = np.array_equal(
                    cli.submit(samples[0]).result(timeout=60), refs[0])
            except MXNetError:
                final_ok = False

        checks["worker_process_killed"] = \
            workers[0].crash_count >= 1 and \
            workers[0].proc.pid != victim_pid
        checks["zero_lost_futures"] = n_lost == 0 and undone == 0
        checks["all_resolutions_typed"] = n_typed + n_ok == len(records)
        checks["survivor_bit_identical"] = n_bits_bad == 0 and n_ok > 0
        checks["worker_breaker_tripped"] = by_name["w0"]["trips"] >= 1
        checks["respawn_readmitted_by_probe"] = readmitted
        checks["router_ingress_survived"] = edge_alive and final_ok
        # the flight recorder must EXPLAIN the kill: a crash event in
        # the ring, and a failed-over request's trace reading
        # dispatch-on-victim -> WorkerCrashed -> ok-on-survivor under
        # one trace_id
        rec = tracing.recorder()
        explained = _failover_trace(rec.traces(), "w0")
        checks["flight_recorder_captured_kill"] = any(
            e.get("event") in ("crash", "worker_crash")
            for e in rec.events())
        checks["flight_recorder_explains_failover"] = \
            explained is not None
        ok = all(checks.values())
        summary["gates"]["worker_crash_isolation_zero_lost"] = {
            "pass": ok, "checks": checks,
            "requests": len(records), "ok": n_ok,
            "typed_errors": n_typed, "lost": n_lost + undone,
            "victim_pid": victim_pid,
            "respawned_pid": workers[0].proc.pid,
            "worker_restarts": workers[0].n_restarts,
            "w0_trips": by_name["w0"]["trips"],
            "explaining_trace": (explained or {}).get("trace_id")}
        print(f"[chaos] worker: {len(records)} requests, {n_ok} ok, "
              f"{n_typed} typed errors, {n_lost + undone} lost; "
              f"victim pid {victim_pid} -> respawned "
              f"{workers[0].proc.pid}")
        for name, v in checks.items():
            print(f"[chaos]   worker {name}: {v}")

        # -- scrape-fed scaling of the SAME multi-process fleet --------
        ok = _scrape_scale_phase(summary, router, make_worker,
                                 _time) and ok
        return ok
    finally:
        cli.close()
        ing.stop()
        router.stop(drain=False, timeout=60)
        tracing.disable()
        os.environ.pop("MXNET_TRACING", None)


def _scrape_scale_phase(summary, router, make_worker, _time):
    """The controller's only view of the fleet is /metrics scrapes of
    the router host's exporter — no shared memory with the workers it
    scales. Synthetic pressure (the admission controller's predicted
    wait, published as a gauge) must grow the fleet by one REAL worker
    process; quiet must shrink it back after the hold window."""
    from mxnet_tpu import serving, telemetry

    telemetry.enable()
    exporter = telemetry.start_exporter()
    checks = {}
    n0 = router.fleet_size()
    counter = [n0]

    def factory(i):
        counter[0] += 1
        return make_worker(counter[0] + 100)   # unique names

    src = serving.ScrapeFleetSignals(
        exporter.url, slo_s=router.slo_s,
        max_batch=router.grid.max_batch)
    policy = serving.ScalePolicy(
        n0, n0 + 1, up_cooldown_s=0.1, down_utilization=0.5,
        down_hold_s=1.0, down_cooldown_s=0.1)
    ctl = serving.FleetController(router, factory, policy=policy,
                                  signals_source=src)
    real_predicted_wait = router.predicted_wait
    try:
        deadline = _time.time() + 30
        while src() is None and _time.time() < deadline:
            _time.sleep(0.1)    # router monitor publishes its gauges
        router.predicted_wait = lambda: 10.0    # synthetic pressure
        scaled_up = False
        t0 = _time.time()
        deadline = _time.time() + 120
        while _time.time() < deadline:
            if ctl.tick() == "up":
                scaled_up = True
                break
            _time.sleep(0.1)
        t_up = _time.time() - t0
        new = router.fleet_size() - n0
        new_worker_is_process = False
        if scaled_up:
            from mxnet_tpu.serving import remote as _remote
            pids = {w.proc.pid for w in _remote.live_workers()}
            new_worker_is_process = len(pids) >= n0 + 1
        router.predicted_wait = real_predicted_wait     # quiet again
        scaled_down = False
        t1 = _time.time()
        deadline = _time.time() + 60
        while _time.time() < deadline:
            if ctl.tick() == "down":
                scaled_down = True
                break
            _time.sleep(0.1)
        held = _time.time() - t1
        checks["scaled_up_from_scrape"] = scaled_up and new == 1
        checks["new_replica_is_worker_process"] = new_worker_is_process
        checks["scaled_down_after_hold"] = \
            scaled_down and router.fleet_size() == n0
        checks["hold_window_respected"] = held >= 0.9
        ok = all(checks.values())
        summary["gates"]["scrape_fed_scale_multiprocess"] = {
            "pass": ok, "checks": checks,
            "fleet_before": n0, "scrapes": src.n_scrapes,
            "scrape_failures": src.n_failures,
            "scale_up_s": round(t_up, 2), "hold_s": round(held, 2)}
        print(f"[chaos] scrape-scale: {n0} -> {n0 + new} -> "
              f"{router.fleet_size()} workers via {src.n_scrapes} "
              f"scrapes (up in {t_up:.1f}s, held {held:.1f}s)")
        for name, v in checks.items():
            print(f"[chaos]   scrape-scale {name}: {v}")
        return ok
    finally:
        router.predicted_wait = real_predicted_wait
        if ctl.is_running:
            ctl.stop()
        exporter.stop()


def _decode_oracle(net, prompt, n_new, buckets=(8, 16, 32, 64, 128)):
    """Full-recompute greedy completion, padded to length buckets so
    the oracle compiles a handful of shapes instead of one per step
    (causal attention makes suffix padding bit-transparent)."""
    import numpy as np

    import mxnet_tpu as mx

    toks = [int(t) for t in prompt]
    for _ in range(n_new):
        length = len(toks)
        bucket = next(b for b in buckets if b >= length)
        arr = np.zeros((1, bucket), np.int32)
        arr[0, :length] = toks
        logits = net(mx.nd.array(arr, dtype="int32")).asnumpy()
        toks.append(int(np.argmax(logits[0, length - 1])))
    return toks[len(prompt):]


def generate_gate(summary):
    """Gate 9: SIGKILL a replica worker process while it is streaming
    autoregressive completions. A generate does NOT fail over
    mid-stream (replaying it elsewhere would duplicate streamed
    tokens) — the contract under fire here is *typed resolution*:
    every in-flight handle on the victim resolves with the typed
    replica error, its streamed tokens are a clean prefix of the
    oracle completion, its stream is sealed, and the survivor keeps
    serving bit-identical completions throughout."""
    import signal as _signal
    import time as _time

    import numpy as np

    from mxnet_tpu import serving
    from mxnet_tpu.base import MXNetError

    os.environ["MXNET_COMM_RETRY_DELAY"] = "0.01"
    tools_dir = os.path.dirname(os.path.abspath(__file__))
    n_new = 120
    prompts = [np.array([3, 1, 4, 1, 5], np.int32),
               np.array([2, 7, 1, 8, 2, 8, 1], np.int32)]

    net = _decode_net()
    oracles = [_decode_oracle(net, p, n_new) for p in prompts]

    grid = dict(batch_buckets=(1, 2), shape_buckets=[(8,)],
                slo_ms=1000.0, dtype="int32", warmup=False,
                decode_pages=96, page_size=4, len_buckets=(8, 16))

    def make_worker(i):
        return serving.RemoteReplica(
            "chaos_check:_decode_net", name=f"g{i}",
            python_paths=[tools_dir], respawn_backoff_s=0.3,
            spawn_timeout_s=300, **grid)

    workers = [make_worker(i) for i in range(2)]
    router = serving.Router(workers, slo_ms=1000.0,
                            dispatch_timeout_s=5.0)
    t0 = _time.time()
    router.start()
    print(f"[chaos] generate: 2 decode workers up in "
          f"{_time.time() - t0:.1f}s (pids "
          f"{[w.proc.pid for w in workers]})")
    checks = {}
    try:
        # warm the decode path on both workers so the kill lands in
        # steady-state streaming, not in a compile
        for w in workers:
            w.submit_generate(prompts[0], 4).result(timeout=120)

        streamed = [[] for _ in range(8)]
        handles = []
        for i in range(8):
            handles.append(router.submit_generate(
                prompts[i % 2], n_new,
                on_token=lambda _i, t, i=i: streamed[i].append(int(t))))
        _time.sleep(0.05)                   # let streams get going
        victim_pid = workers[0].proc.pid
        os.kill(victim_pid, _signal.SIGKILL)

        n_ok = n_typed = n_lost = n_bits_bad = n_prefix_bad = 0
        unsealed = 0
        for i, h in enumerate(handles):
            want = oracles[i % 2]
            try:
                out = h.result(timeout=120)
            except MXNetError:
                n_typed += 1                # typed = resolved
                got = h.tokens()
                if got != want[:len(got)] or \
                        streamed[i] != want[:len(streamed[i])]:
                    n_prefix_bad += 1
                if h.next_token(len(got), timeout=5) is not None:
                    unsealed += 1           # stream must be sealed
                continue
            except Exception:   # noqa: BLE001 - untyped = lost
                n_lost += 1
                continue
            n_ok += 1
            if list(out) != want or h.tokens() != want or \
                    streamed[i] != want:
                n_bits_bad += 1
        undone = sum(1 for h in handles if not h.future.done())

        # survivor still serves bit-identical completions
        survivor_ok = False
        try:
            out = router.submit_generate(
                prompts[0], n_new).result(timeout=120)
            survivor_ok = list(out) == oracles[0]
        except MXNetError:
            survivor_ok = False

        checks["worker_process_killed"] = workers[0].crash_count >= 1
        checks["crash_hit_inflight_generate"] = n_typed >= 1
        checks["zero_lost_generates"] = n_lost == 0 and undone == 0
        checks["all_resolutions_typed"] = n_typed + n_ok == len(handles)
        checks["completed_bit_identical"] = n_bits_bad == 0 and n_ok >= 1
        checks["casualty_streams_clean_prefix"] = n_prefix_bad == 0
        checks["casualty_streams_sealed"] = unsealed == 0
        checks["survivor_serves_generates"] = survivor_ok
        ok = all(checks.values())
        summary["gates"]["generate_crash_typed_streams"] = {
            "pass": ok, "checks": checks, "generates": len(handles),
            "ok": n_ok, "typed_errors": n_typed,
            "lost": n_lost + undone, "victim_pid": victim_pid}
        print(f"[chaos] generate: {len(handles)} generates, {n_ok} ok, "
              f"{n_typed} typed errors, {n_lost + undone} lost "
              f"(victim pid {victim_pid})")
        for name, v in checks.items():
            print(f"[chaos]   generate {name}: {v}")
        return ok
    finally:
        router.stop(drain=False, timeout=60)


def multitenant_gate(summary):
    """Gate 10: priority preemption at the decode-step boundary.

    Two tenants share ONE replica (one KV-cache pool, one executable
    table): the default tenant (priority 0) saturates the pool with
    long generates, then premium (priority 10) arrivals land. Gates:
    every premium arrival is ADMITTED (never shed by the squatters)
    and completes bit-identical to a single-tenant oracle; preemption
    victims resolve typed :class:`Preempted` with a sealed clean-prefix
    stream (never a torn token); zero lost futures across both
    tenants; and the flight recorder holds the preemption event naming
    victim and beneficiary."""
    import time as _time

    import numpy as np

    from mxnet_tpu import serving, tracing
    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.serving import Preempted

    prompt = np.array([3, 1, 4, 1, 5], np.int32)
    low_new, prem_new = 40, 8
    net_low, net_prem = _decode_net(seed=7), _decode_net(seed=11)
    oracle_low = _decode_oracle(net_low, prompt, low_new)
    oracle_prem = _decode_oracle(net_prem, prompt, prem_new)

    # pool geometry: 39 usable pages; 3 low streams x 12 pages = 36,
    # a premium arrival needs 4 — only preemption can admit it
    srv = serving.Server(
        net_low, batch_buckets=(1, 2), shape_buckets=[(8,)],
        slo_ms=60000.0, dtype="int32", warmup=False, decode_pages=40,
        page_size=4, len_buckets=(8, 16, 32, 64), name="mt0",
        priority=0, weight=1.0)
    srv.register_model("premium", net_prem, slo_class="premium",
                       priority=10, weight=3.0)
    tracing.reset()
    tracing.enable()
    srv.start()
    checks = {}
    try:
        # warm both tenants' decode paths so arrivals land in
        # steady-state decode, not in a compile
        srv.submit_generate(prompt, 2).result(timeout=300)
        srv.submit_generate(prompt, 2, model="premium").result(
            timeout=300)

        low = [srv.submit_generate(prompt, low_new) for _ in range(3)]
        # wait until all three squatters hold pages (free < a premium
        # arrival's need) — a fixed sleep races 40-token completions
        deadline = _time.monotonic() + 60
        while _time.monotonic() < deadline:
            st = srv.stats()
            if st.get("generates_active", 0) >= 3:
                break
            _time.sleep(0.005)
        else:
            raise RuntimeError("low-tier streams never saturated pool")

        prem, shed = [], 0
        for _ in range(4):
            try:
                prem.append(srv.submit_generate(prompt, prem_new,
                                                model="premium"))
            except MXNetError:
                shed += 1
            _time.sleep(0.05)

        n_prem_ok = n_prem_bad = 0
        for h in prem:
            try:
                out = h.result(timeout=300)
            except MXNetError:
                n_prem_bad += 1
                continue
            if list(out) == oracle_prem:
                n_prem_ok += 1
            else:
                n_prem_bad += 1

        n_done = n_preempted = n_torn = unsealed = n_lost = 0
        for h in low:
            try:
                out = h.result(timeout=300)
                n_done += 1
                if list(out) != oracle_low:
                    n_torn += 1
            except Preempted:
                n_preempted += 1
                got = h.tokens()
                if got != oracle_low[:len(got)]:
                    n_torn += 1     # a torn token, not a clean prefix
                if h.next_token(len(got), timeout=2) is not None:
                    unsealed += 1
            except Exception:   # noqa: BLE001 - untyped = lost
                n_lost += 1
        undone = sum(1 for h in low + prem if not h.future.done())

        evs = tracing.events("preempted")
        ev_named = all(
            e.get("victim_model") == "default"
            and e.get("beneficiary_model") == "premium"
            and e.get("victim") is not None
            and e.get("beneficiary") is not None for e in evs)

        checks["premium_all_admitted"] = shed == 0 and len(prem) == 4
        checks["premium_bit_identical"] = (n_prem_ok == len(prem)
                                           and n_prem_bad == 0)
        checks["victims_typed_preempted"] = n_preempted >= 1
        checks["victim_streams_clean_prefix"] = n_torn == 0
        checks["victim_streams_sealed"] = unsealed == 0
        checks["zero_lost_futures"] = n_lost == 0 and undone == 0
        checks["flight_recorder_names_both"] = (len(evs) >= 1
                                                and ev_named)
        checks["stats_count_preemptions"] = (
            srv.stats()["preemptions"] == n_preempted
            and srv.stats()["models"]["default"]["preempted"]
            == n_preempted)
        ok = all(checks.values())
        summary["gates"]["multitenant_priority_preemption"] = {
            "pass": ok, "checks": checks, "premium": len(prem),
            "preempted": n_preempted, "completed_low": n_done,
            "preempt_events": len(evs)}
        print(f"[chaos] multitenant: {len(prem)} premium admitted "
              f"({shed} shed), {n_preempted} victims preempted, "
              f"{n_done} low completed, {len(evs)} recorder events")
        for name, v in checks.items():
            print(f"[chaos]   multitenant {name}: {v}")
        return ok
    finally:
        srv.stop(drain=False, timeout=60)
        tracing.disable()
        tracing.reset()


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--steps", type=int, default=24)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--spec", default=DEFAULT_SPEC,
                    help="fault spec for the chaos run (all-retryable)")
    ap.add_argument("--json", default=None,
                    help="write the result summary to this path")
    ap.add_argument("--skip-elastic", action="store_true",
                    help="skip the subprocess elastic gate (launch.py "
                    "SIGKILL + rejoin)")
    ap.add_argument("--skip-serving", action="store_true",
                    help="skip the serving failover gate (Router "
                    "replica kill mid-traffic)")
    ap.add_argument("--skip-preempt", action="store_true",
                    help="skip the scripted-preemption gate (graceful "
                    "SIGTERM leave/rejoin under launch.py)")
    ap.add_argument("--skip-upgrade", action="store_true",
                    help="skip the rolling-upgrade gate (3-replica "
                    "fleet under traffic, poisoned-build rollback)")
    ap.add_argument("--skip-worker", action="store_true",
                    help="skip the out-of-process worker gate (SIGKILL "
                    "a replica worker process under ingress traffic + "
                    "scrape-fed fleet scaling)")
    ap.add_argument("--skip-multitenant", action="store_true",
                    help="skip the multi-tenant gate (priority "
                         "preemption at the decode-step boundary, "
                         "two tenants on one replica)")
    ap.add_argument("--skip-generate", action="store_true",
                    help="skip the generate gate (SIGKILL a replica "
                    "mid-completion; typed resolution of streaming "
                    "handles, survivor bit-identity)")
    ap.add_argument("--skip-zero", action="store_true",
                    help="skip the ZeRO re-shard gate (SIGKILL under "
                    "sharded optimizer state; rejoin at a different "
                    "world size must re-shard bit-exact)")
    args = ap.parse_args()

    import numpy as np

    from mxnet_tpu import checkpoint, fault, telemetry

    telemetry.enable()
    summary = {"steps": args.steps, "seed": args.seed, "spec": args.spec,
               "gates": {}}
    ok = True

    # -- gate 1: clean baseline ----------------------------------------
    clean_losses, clean_net, _ = run(args.seed, args.steps)
    print(f"[chaos] clean run: {args.steps} steps, "
          f"final loss {clean_losses[-1]:.6f}")

    # -- gate 2: chaos run matches bit-for-bit -------------------------
    with fault.inject(args.spec, seed=args.seed) as stats:
        chaos_losses, chaos_net, _ = run(args.seed, args.steps)
        injected = {site: dict(v) for site, v in stats().items()}
    total_injected = sum(v["injected"] for v in injected.values())
    losses_equal = chaos_losses == clean_losses
    clean_w, chaos_w = weights_of(clean_net), weights_of(chaos_net)
    weights_equal = all(np.array_equal(a, chaos_w[k])
                        for k, a in clean_w.items())
    summary["gates"]["chaos_matches_clean"] = {
        "pass": bool(losses_equal and weights_equal),
        "faults_injected": injected,
        "final_loss_clean": clean_losses[-1],
        "final_loss_chaos": chaos_losses[-1]}
    per_site = ", ".join(
        "{}:{}".format(s, v["injected"]) for s, v in injected.items())
    print(f"[chaos] chaos run: {total_injected} faults injected "
          f"({per_site})")
    print(f"[chaos] losses identical: {losses_equal}; "
          f"weights bit-exact: {weights_equal}")
    if total_injected == 0:
        print("[chaos] WARNING: spec injected nothing — gate is vacuous")
        ok = False
    ok = ok and losses_equal and weights_equal

    # -- gate 3: kill-during-write + bit-exact resume ------------------
    ckpt_dir = tempfile.mkdtemp(prefix="chaos_ckpt_")
    try:
        mgr = checkpoint.CheckpointManager(ckpt_dir, keep_last=2)
        half = args.steps // 2
        full_losses, full_net, _ = run(
            args.seed, args.steps, ckpt_mgr=mgr, ckpt_at=half,
            kill_first_save=True)
        # "crash": rebuild from nothing, restore, replay the tail
        net2, tr2, _, _ = build(args.seed + 1)   # wrong init on purpose
        meta = mgr.restore(block=net2, trainer=tr2)
        resumed_losses, resumed_net, _ = run(
            args.seed, args.steps, net=net2, trainer=tr2,
            start_step=meta["step"] + 1)
        tail_equal = resumed_losses == full_losses[half + 1:]
        full_w, resumed_w = weights_of(full_net), weights_of(resumed_net)
        resumed_weights_equal = all(np.array_equal(a, resumed_w[k])
                                    for k, a in full_w.items())
        summary["gates"]["crash_resume_bit_exact"] = {
            "pass": bool(tail_equal and resumed_weights_equal),
            "resumed_from_step": meta["step"]}
        print(f"[chaos] resume from step {meta['step']}: tail losses "
              f"identical: {tail_equal}; weights bit-exact: "
              f"{resumed_weights_equal}")
        ok = ok and tail_equal and resumed_weights_equal
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)

    # -- gate 4: SIGKILL a worker mid-step, supervised rejoin ----------
    if not args.skip_elastic:
        ok = elastic_gate(summary) and ok

    # -- gate 5: kill a serving replica mid-traffic, zero lost futures -
    if not args.skip_serving:
        ok = serving_gate(summary) and ok

    # -- gate 6: scripted preemption schedule, bit-exact + sustained --
    if not args.skip_preempt:
        ok = preemption_gate(summary) and ok

    # -- gate 7: rolling upgrade under traffic, poisoned-build rollback -
    if not args.skip_upgrade:
        ok = upgrade_gate(summary) and ok

    # -- gate 8: SIGKILL an out-of-process worker under ingress traffic,
    #    then scrape-fed scaling of the multi-process fleet ------------
    if not args.skip_worker:
        ok = worker_gate(summary) and ok

    # -- gate 9: SIGKILL a replica mid-generate — typed resolution of
    #    the streaming handles, survivor keeps completing ---------------
    if not args.skip_generate:
        ok = generate_gate(summary) and ok

    # -- gate 10: two tenants on one fleet — weighted admission and
    #    priority preemption between decode steps --------------------
    if not args.skip_multitenant:
        ok = multitenant_gate(summary) and ok

    # -- gate 11: SIGKILL under ZeRO-sharded optimizer state — every
    #    world transition re-gathers + re-shards the state bit-exact --
    if not args.skip_zero:
        ok = zero_gate(summary) and ok

    retry_counters = {}
    for s in telemetry.snapshot()["metrics"].get(
            "mxnet_retry_total", {}).get("samples", []):
        retry_counters["/".join(s["labels"].values())] = s["value"]
    summary["retry_counters"] = retry_counters
    summary["ok"] = ok
    if args.json:
        with open(args.json, "w") as f:
            json.dump(summary, f, indent=2)
    print(f"[chaos] retries: {retry_counters or 'none'}")
    print(f"[chaos] {'PASS' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
