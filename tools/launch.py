#!/usr/bin/env python
"""Supervised multi-process launcher (reference: ``tools/launch.py`` +
dmlc_tracker, whose tracker restarted dead ps-lite nodes).

The TPU-native cluster is a multi-controller JAX job: every process runs
the same program, rendezvouses through the coordination service, and XLA
collectives ride ICI/DCN — so the launcher exports the rendezvous env
contract (SURVEY.md §5.6.4, the same DMLC_* names the reference's
trainers already read), fans out the command, and then **supervises**:

* **Poll-based wait.** All workers are polled together (never a serial
  ``p.wait()`` on rank 0 while rank 3 is already dead and its siblings
  hang in a collective).
* **Fail-fast mode** (default, ``--max-restarts 0``): the first worker
  to exit non-zero SIGTERMs the rest, escalating to SIGKILL after
  ``--term-window`` seconds, and the launcher exits with the first
  failing rank's code (signal deaths map to ``128+signum``).
* **Elastic mode** (``--max-restarts N``): a dead rank is respawned with
  the same ``DMLC_WORKER_ID`` after a bounded exponential backoff
  (``--restart-backoff``, doubling per restart of that rank, capped at
  30 s), up to N times per rank; workers built on
  ``mxnet_tpu.parallel.elastic.ElasticRunner`` resume bit-exactly from
  their newest checkpoint bundle. Exhausted restarts fall back to
  fail-fast.
* **Preemption is not failure.** A worker exiting with ``--preempt-rc``
  (default 75, ``elastic.PREEMPTED_EXIT_CODE`` — what an
  ``ElasticRunner`` worker exits with after its graceful
  checkpoint-then-leave) is respawned with a FLAT ``--restart-backoff``
  delay and does **not** burn the ``--max-restarts`` failure budget:
  spot capacity reclaim is the steady state of a preemptible fleet,
  not a crash. A separate ``--max-preempt-restarts`` budget (default
  100) bounds runaway preempt-exit loops; both budgets advance the
  worker's ``MXNET_ELASTIC_RESTART`` incarnation.
* **Interrupting the supervisor does not orphan the job.** The
  supervisor installs its own SIGTERM/SIGINT handlers for the duration
  of the run: the signal is forwarded to every worker, reaped with the
  same SIGTERM→SIGKILL escalation window, the exit report (and
  ``--report`` JSON) is still written, and the launcher exits
  ``128+signum`` — so ctrl-C, a CI timeout, or the supervisor's OWN
  preemption tears the whole tree down cleanly.
* **Structured exit report.** A per-worker table (rank, restarts, every
  exit code/signal) on stdout and, with ``--report PATH``, as JSON.

Local mode (this machine, -n workers; smoke tests / 1 host with N chips):

    python tools/launch.py -n 4 python train.py --kv-store dist_sync

Multi-host mode (-H hostfile, one line per host; requires passwordless
ssh, mirroring the reference's ssh launcher):

    python tools/launch.py -n 8 -H hosts --max-restarts 2 python train.py

Caveat (shared with the reference's ssh launcher): signals reach the
LOCAL ssh client, not the remote python — a fail-fast teardown or
restart of an ssh-mode rank can orphan the remote process. Remote
workers should run under the elastic runtime so an orphan is fenced by
its own heartbeat/barrier timeouts; for hard kill guarantees use a
per-host supervisor (one local launch.py per host) instead of ssh mode.

Workers read: DMLC_PS_ROOT_URI / DMLC_PS_ROOT_PORT (coordinator address),
DMLC_NUM_WORKER, DMLC_WORKER_ID — ``mxnet_tpu.kvstore.create('dist_sync')``
bootstraps ``jax.distributed`` from exactly these — plus
MXNET_ELASTIC_COORD_DIR (the ElasticRunner heartbeat/epoch directory)
and MXNET_ELASTIC_RESTART (this incarnation's restart count).
"""
from __future__ import annotations

import argparse
import json
import os
import shlex
import signal
import socket
import subprocess
import sys
import tempfile
import time

_BACKOFF_CAP_S = 30.0


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _signal_name(signum: int) -> str:
    try:
        return signal.Signals(signum).name
    except ValueError:
        return f"signal {signum}"


def _exit_code(returncode: int) -> int:
    """Shell convention: a signal death (Popen returncode -N) is 128+N."""
    return 128 - returncode if returncode < 0 else returncode


class _Worker:
    """One rank's supervision record: how to (re)spawn it, the live
    process handle, and the full exit history for the report."""

    def __init__(self, rank: int, spawn):
        self.rank = rank
        self._spawn = spawn
        self.proc: subprocess.Popen | None = None
        self.restarts = 0          # failure restarts (--max-restarts)
        self.preemptions = 0       # preempt-rc respawns (separate budget)
        self.exits: list[dict] = []
        self.done = False          # exited 0 — never restarted
        self.restart_at: float | None = None   # pending respawn time

    def spawn(self):
        # the incarnation counter covers BOTH budgets: a worker names
        # per-incarnation artifacts (loss logs, reports) by
        # MXNET_ELASTIC_RESTART and a preemption respawn is a new
        # incarnation exactly like a failure restart
        self.proc = self._spawn(self.rank,
                                self.restarts + self.preemptions)
        self.restart_at = None

    def poll(self):
        """Returncode if the live process has exited, else None."""
        return self.proc.poll() if self.proc is not None else None

    def record_exit(self, returncode: int):
        self.exits.append({"returncode": returncode,
                           "exit_code": _exit_code(returncode),
                           "signal": _signal_name(-returncode)
                           if returncode < 0 else None,
                           "time_unix": time.time()})
        self.proc = None

    def report(self) -> dict:
        return {"rank": self.rank, "restarts": self.restarts,
                "preemptions": self.preemptions,
                "done": self.done, "exits": self.exits,
                "final": self.exits[-1]["exit_code"] if self.exits
                else None}


def _terminate_all(workers, term_window: float):
    """SIGTERM every live worker, escalate to SIGKILL after the bounded
    window — a worker ignoring SIGTERM (or wedged in a dead collective)
    cannot wedge the launcher."""
    live = []
    for w in workers:
        if w.proc is None:
            continue
        rc = w.proc.poll()
        if rc is not None:
            # died between the supervision poll and teardown: reap and
            # record it, or the exit report would claim it never exited
            w.record_exit(rc)
            if rc == 0:
                w.done = True
        else:
            live.append(w)
    for w in live:
        try:
            w.proc.send_signal(signal.SIGTERM)
        except OSError:
            pass
    deadline = time.monotonic() + max(0.0, term_window)
    for w in live:
        remaining = deadline - time.monotonic()
        try:
            w.proc.wait(timeout=max(0.05, remaining))
        except subprocess.TimeoutExpired:
            try:
                w.proc.kill()
            except OSError:
                pass
            w.proc.wait()
    for w in live:
        if w.proc is not None:
            w.record_exit(w.proc.returncode)


def _print_report(workers, out=sys.stderr):
    print("[launch] worker exit report:", file=out)
    for w in workers:
        attempts = []
        for e in w.exits:
            attempts.append(e["signal"] or f"exit {e['exit_code']}")
        print(f"[launch]   rank {w.rank}: "
              f"{' -> restart -> '.join(attempts) or 'never exited'}"
              f" (restarts: {w.restarts}, preemptions: "
              f"{w.preemptions})", file=out)


class _SupervisorSignal:
    """Signal latch for the supervision loop: the handler only records
    the signum (async-signal-safe), the loop acts on it — forwarding
    the teardown to the workers through the normal escalation path
    instead of dying and orphaning them."""

    def __init__(self):
        self.signum: int | None = None
        self._old: dict[int, object] = {}

    def install(self, signals=(signal.SIGTERM, signal.SIGINT)):
        for sig in signals:
            try:
                self._old[int(sig)] = signal.signal(
                    sig, lambda signum, frame:
                    setattr(self, "signum", signum))
            except ValueError:
                # not the main thread (supervise() driven from a test
                # harness thread): run unhandled, the loop still works
                pass
        return self

    def restore(self):
        old, self._old = self._old, {}
        for sig, handler in old.items():
            try:
                signal.signal(sig, handler)
            except (ValueError, TypeError, OSError):
                pass


def supervise(workers, *, max_restarts: int, restart_backoff: float,
              term_window: float, poll_interval: float,
              preempt_rc: int = 75, max_preempt_restarts: int = 100,
              log=lambda msg: print(msg, file=sys.stderr)) -> int:
    """The supervision loop (importable for tests). Spawns every worker,
    polls them all, applies the fail-fast / elastic / preemption policy,
    and returns the job's exit code (first failing rank's code, 0 when
    every rank finished clean). An exit with ``preempt_rc`` (<=0
    disables) is a graceful preemption leave: respawned with a flat
    backoff against its own ``max_preempt_restarts`` budget, the
    failure budget untouched — even a ``--max-restarts 0`` fail-fast
    job rides out preemptions. SIGTERM/SIGINT at the supervisor tears
    the job down (forwarded SIGTERM, SIGKILL escalation) and returns
    ``128+signum`` — the caller still writes its report."""
    interrupt = _SupervisorSignal().install()
    for w in workers:
        w.spawn()
    first_fail: int | None = None
    try:
        while True:
            if interrupt.signum is not None:
                log(f"[launch] supervisor got "
                    f"{_signal_name(interrupt.signum)}; terminating "
                    f"workers (window {term_window:g}s)")
                _terminate_all(workers, term_window)
                return 128 + interrupt.signum
            now = time.monotonic()
            for w in workers:
                if w.done or w.proc is None:
                    # pending restart?
                    if (not w.done and w.restart_at is not None
                            and now >= w.restart_at):
                        log(f"[launch] restarting rank {w.rank} "
                            f"(restart #{w.restarts})")
                        w.spawn()
                    continue
                rc = w.poll()
                if rc is None:
                    continue
                w.record_exit(rc)
                if rc == 0:
                    w.done = True
                    continue
                code = _exit_code(rc)
                desc = _signal_name(-rc) if rc < 0 else f"code {rc}"
                if preempt_rc > 0 and code == preempt_rc and \
                        w.preemptions < max_preempt_restarts:
                    # graceful preemption leave: the worker checkpointed
                    # and asked to be respawned. Flat backoff (the
                    # doubling is for FAILING workers; a preempted one
                    # is healthy) and no failure-budget spend.
                    w.preemptions += 1
                    delay = min(restart_backoff, _BACKOFF_CAP_S)
                    w.restart_at = now + delay
                    log(f"[launch] rank {w.rank} preempted (rc "
                        f"{preempt_rc}); respawn "
                        f"#{w.preemptions}/{max_preempt_restarts} "
                        f"in {delay:.1f}s (restart budget untouched)")
                    continue
                if w.restarts < max_restarts:
                    w.restarts += 1
                    delay = min(
                        restart_backoff * (2.0 ** (w.restarts - 1)),
                        _BACKOFF_CAP_S)
                    w.restart_at = now + delay
                    log(f"[launch] rank {w.rank} died ({desc}); "
                        f"restart #{w.restarts}/{max_restarts} "
                        f"in {delay:.1f}s")
                else:
                    mode = "fail-fast" if max_restarts == 0 else \
                        "restarts exhausted"
                    log(f"[launch] rank {w.rank} died ({desc}); {mode}: "
                        f"terminating remaining workers "
                        f"(window {term_window:g}s)")
                    first_fail = code
                    break
            if first_fail is not None:
                _terminate_all(workers, term_window)
                return first_fail
            if all(w.done for w in workers):
                return 0
            time.sleep(poll_interval)
    except KeyboardInterrupt:
        # reachable only when the SIGINT handler could not be installed
        # (non-main thread): same teardown, conventional 130
        log("[launch] interrupted; terminating workers")
        _terminate_all(workers, term_window)
        return 130
    finally:
        interrupt.restore()


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("-n", "--num-workers", type=int, required=True,
                    help="total worker processes")
    ap.add_argument("-H", "--hostfile", default=None,
                    help="one host per line; default: all workers local")
    ap.add_argument("-p", "--port", type=int, default=0,
                    help="coordinator port (default: pick a free one)")
    ap.add_argument("--env", action="append", default=[],
                    metavar="K=V", help="extra env to export to workers")
    ap.add_argument("--max-restarts", type=int, default=0,
                    help="per-rank restart budget (0 = fail-fast: first "
                    "non-zero exit tears the job down)")
    ap.add_argument("--restart-backoff", type=float, default=1.0,
                    help="base restart delay (s); doubles per restart "
                    f"of a rank, capped at {_BACKOFF_CAP_S:g}s")
    ap.add_argument("--preempt-rc", type=int, default=75,
                    help="exit code meaning 'gracefully preempted, "
                    "respawn me' (elastic.PREEMPTED_EXIT_CODE; 0 "
                    "disables preemption handling)")
    ap.add_argument("--max-preempt-restarts", type=int, default=100,
                    help="per-rank preemption respawn budget (separate "
                    "from --max-restarts; preemptions are not failures)")
    ap.add_argument("--term-window", type=float, default=10.0,
                    help="seconds between SIGTERM and SIGKILL when "
                    "tearing the job down")
    ap.add_argument("--poll-interval", type=float, default=0.2,
                    help="supervision poll period (s)")
    ap.add_argument("--coord-dir", default=None,
                    help="shared elastic coordinator dir exported as "
                    "MXNET_ELASTIC_COORD_DIR (default: a fresh tempdir)")
    ap.add_argument("--report", default=None,
                    help="write the per-worker exit report JSON here")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="worker command")
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("no worker command given")
    if args.max_restarts < 0:
        ap.error("--max-restarts must be >= 0")
    if args.max_preempt_restarts < 0:
        ap.error("--max-preempt-restarts must be >= 0")
    cmd = args.command[1:] if args.command[0] == "--" else args.command

    hosts = None
    if args.hostfile:
        with open(args.hostfile) as f:
            hosts = [ln.strip() for ln in f if ln.strip()
                     and not ln.startswith("#")]
        if not hosts:
            ap.error(f"hostfile {args.hostfile} is empty")

    root_uri = hosts[0] if hosts else "127.0.0.1"
    port = args.port or _free_port()
    extra = dict(kv.split("=", 1) for kv in args.env)
    coord_dir = args.coord_dir or tempfile.mkdtemp(prefix="mxnet_elastic_")
    os.makedirs(coord_dir, exist_ok=True)

    def spawn(rank: int, restart_count: int) -> subprocess.Popen:
        env = dict(os.environ, **extra,
                   DMLC_PS_ROOT_URI=root_uri,
                   DMLC_PS_ROOT_PORT=str(port),
                   DMLC_NUM_WORKER=str(args.num_workers),
                   DMLC_WORKER_ID=str(rank),
                   DMLC_ROLE="worker",
                   MXNET_ELASTIC_COORD_DIR=coord_dir,
                   MXNET_ELASTIC_RESTART=str(restart_count))
        if hosts:
            host = hosts[rank % len(hosts)]
            exports = " ".join(
                f"{k}={shlex.quote(env[k])}"
                for k in ("DMLC_PS_ROOT_URI", "DMLC_PS_ROOT_PORT",
                          "DMLC_NUM_WORKER", "DMLC_WORKER_ID",
                          "DMLC_ROLE", "MXNET_ELASTIC_COORD_DIR",
                          "MXNET_ELASTIC_RESTART", *extra))
            remote = f"cd {shlex.quote(os.getcwd())} && " \
                     f"env {exports} {' '.join(map(shlex.quote, cmd))}"
            return subprocess.Popen(["ssh", "-o", "BatchMode=yes", host,
                                     remote])
        return subprocess.Popen(cmd, env=env)

    workers = [_Worker(rank, spawn) for rank in range(args.num_workers)]
    rc = supervise(workers, max_restarts=args.max_restarts,
                   restart_backoff=args.restart_backoff,
                   term_window=args.term_window,
                   poll_interval=args.poll_interval,
                   preempt_rc=args.preempt_rc,
                   max_preempt_restarts=args.max_preempt_restarts)
    _print_report(workers)
    if args.report:
        with open(args.report, "w") as f:
            json.dump({"rc": rc,
                       "mode": "elastic" if args.max_restarts else
                       "fail_fast",
                       "max_restarts": args.max_restarts,
                       "preempt_rc": args.preempt_rc,
                       "max_preempt_restarts": args.max_preempt_restarts,
                       "coord_dir": coord_dir,
                       "workers": [w.report() for w in workers]},
                      f, indent=1)
    return rc


if __name__ == "__main__":
    sys.exit(main())
