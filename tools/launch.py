#!/usr/bin/env python
"""Multi-process launcher (reference: ``tools/launch.py`` + dmlc_tracker).

The reference starts a parameter-server tracker plus ssh/mpi workers. The
TPU-native cluster is a multi-controller JAX job: every process runs the
same program, rendezvouses through the coordination service, and XLA
collectives ride ICI/DCN — so the launcher's whole job is to export the
rendezvous env contract (SURVEY.md §5.6.4, the same DMLC_* names the
reference's trainers already read) and fan out the command.

Local mode (this machine, -n workers; smoke tests / 1 host with N chips):

    python tools/launch.py -n 4 python train.py --kv-store dist_sync

Multi-host mode (-H hostfile, one line per host; requires passwordless
ssh, mirroring the reference's ssh launcher):

    python tools/launch.py -n 8 -H hosts python train.py

Workers read: DMLC_PS_ROOT_URI / DMLC_PS_ROOT_PORT (coordinator address),
DMLC_NUM_WORKER, DMLC_WORKER_ID — ``mxnet_tpu.kvstore.create('dist_sync')``
bootstraps ``jax.distributed`` from exactly these.
"""
from __future__ import annotations

import argparse
import os
import shlex
import signal
import socket
import subprocess
import sys


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("-n", "--num-workers", type=int, required=True,
                    help="total worker processes")
    ap.add_argument("-H", "--hostfile", default=None,
                    help="one host per line; default: all workers local")
    ap.add_argument("-p", "--port", type=int, default=0,
                    help="coordinator port (default: pick a free one)")
    ap.add_argument("--env", action="append", default=[],
                    metavar="K=V", help="extra env to export to workers")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="worker command")
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("no worker command given")
    cmd = args.command[1:] if args.command[0] == "--" else args.command

    hosts = None
    if args.hostfile:
        with open(args.hostfile) as f:
            hosts = [ln.strip() for ln in f if ln.strip()
                     and not ln.startswith("#")]
        if not hosts:
            ap.error(f"hostfile {args.hostfile} is empty")

    root_uri = hosts[0] if hosts else "127.0.0.1"
    port = args.port or _free_port()
    extra = dict(kv.split("=", 1) for kv in args.env)

    procs = []
    try:
        for rank in range(args.num_workers):
            env = dict(os.environ, **extra,
                       DMLC_PS_ROOT_URI=root_uri,
                       DMLC_PS_ROOT_PORT=str(port),
                       DMLC_NUM_WORKER=str(args.num_workers),
                       DMLC_WORKER_ID=str(rank),
                       DMLC_ROLE="worker")
            if hosts:
                host = hosts[rank % len(hosts)]
                exports = " ".join(
                    f"{k}={shlex.quote(env[k])}"
                    for k in ("DMLC_PS_ROOT_URI", "DMLC_PS_ROOT_PORT",
                              "DMLC_NUM_WORKER", "DMLC_WORKER_ID",
                              "DMLC_ROLE", *extra))
                remote = f"cd {shlex.quote(os.getcwd())} && " \
                         f"env {exports} {' '.join(map(shlex.quote, cmd))}"
                p = subprocess.Popen(["ssh", "-o", "BatchMode=yes", host,
                                      remote])
            else:
                p = subprocess.Popen(cmd, env=env)
            procs.append(p)
        rc = 0
        for p in procs:
            rc = p.wait() or rc
        return rc
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            p.wait()
        return 130


if __name__ == "__main__":
    sys.exit(main())
