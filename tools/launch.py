#!/usr/bin/env python
"""Supervised multi-process launcher (reference: ``tools/launch.py`` +
dmlc_tracker, whose tracker restarted dead ps-lite nodes).

The TPU-native cluster is a multi-controller JAX job: every process runs
the same program, rendezvouses through the coordination service, and XLA
collectives ride ICI/DCN — so the launcher exports the rendezvous env
contract (SURVEY.md §5.6.4, the same DMLC_* names the reference's
trainers already read), fans out the command, and then **supervises**:

* **Poll-based wait.** All workers are polled together (never a serial
  ``p.wait()`` on rank 0 while rank 3 is already dead and its siblings
  hang in a collective).
* **Fail-fast mode** (default, ``--max-restarts 0``): the first worker
  to exit non-zero SIGTERMs the rest, escalating to SIGKILL after
  ``--term-window`` seconds, and the launcher exits with the first
  failing rank's code (signal deaths map to ``128+signum``).
* **Elastic mode** (``--max-restarts N``): a dead rank is respawned with
  the same ``DMLC_WORKER_ID`` after a bounded exponential backoff
  (``--restart-backoff``, doubling per restart of that rank, capped at
  30 s), up to N times per rank; workers built on
  ``mxnet_tpu.parallel.elastic.ElasticRunner`` resume bit-exactly from
  their newest checkpoint bundle. Exhausted restarts fall back to
  fail-fast.
* **Structured exit report.** A per-worker table (rank, restarts, every
  exit code/signal) on stdout and, with ``--report PATH``, as JSON.

Local mode (this machine, -n workers; smoke tests / 1 host with N chips):

    python tools/launch.py -n 4 python train.py --kv-store dist_sync

Multi-host mode (-H hostfile, one line per host; requires passwordless
ssh, mirroring the reference's ssh launcher):

    python tools/launch.py -n 8 -H hosts --max-restarts 2 python train.py

Caveat (shared with the reference's ssh launcher): signals reach the
LOCAL ssh client, not the remote python — a fail-fast teardown or
restart of an ssh-mode rank can orphan the remote process. Remote
workers should run under the elastic runtime so an orphan is fenced by
its own heartbeat/barrier timeouts; for hard kill guarantees use a
per-host supervisor (one local launch.py per host) instead of ssh mode.

Workers read: DMLC_PS_ROOT_URI / DMLC_PS_ROOT_PORT (coordinator address),
DMLC_NUM_WORKER, DMLC_WORKER_ID — ``mxnet_tpu.kvstore.create('dist_sync')``
bootstraps ``jax.distributed`` from exactly these — plus
MXNET_ELASTIC_COORD_DIR (the ElasticRunner heartbeat/epoch directory)
and MXNET_ELASTIC_RESTART (this incarnation's restart count).
"""
from __future__ import annotations

import argparse
import json
import os
import shlex
import signal
import socket
import subprocess
import sys
import tempfile
import time

_BACKOFF_CAP_S = 30.0


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _signal_name(signum: int) -> str:
    try:
        return signal.Signals(signum).name
    except ValueError:
        return f"signal {signum}"


def _exit_code(returncode: int) -> int:
    """Shell convention: a signal death (Popen returncode -N) is 128+N."""
    return 128 - returncode if returncode < 0 else returncode


class _Worker:
    """One rank's supervision record: how to (re)spawn it, the live
    process handle, and the full exit history for the report."""

    def __init__(self, rank: int, spawn):
        self.rank = rank
        self._spawn = spawn
        self.proc: subprocess.Popen | None = None
        self.restarts = 0
        self.exits: list[dict] = []
        self.done = False          # exited 0 — never restarted
        self.restart_at: float | None = None   # pending respawn time

    def spawn(self):
        self.proc = self._spawn(self.rank, self.restarts)
        self.restart_at = None

    def poll(self):
        """Returncode if the live process has exited, else None."""
        return self.proc.poll() if self.proc is not None else None

    def record_exit(self, returncode: int):
        self.exits.append({"returncode": returncode,
                           "exit_code": _exit_code(returncode),
                           "signal": _signal_name(-returncode)
                           if returncode < 0 else None,
                           "time_unix": time.time()})
        self.proc = None

    def report(self) -> dict:
        return {"rank": self.rank, "restarts": self.restarts,
                "done": self.done, "exits": self.exits,
                "final": self.exits[-1]["exit_code"] if self.exits
                else None}


def _terminate_all(workers, term_window: float):
    """SIGTERM every live worker, escalate to SIGKILL after the bounded
    window — a worker ignoring SIGTERM (or wedged in a dead collective)
    cannot wedge the launcher."""
    live = []
    for w in workers:
        if w.proc is None:
            continue
        rc = w.proc.poll()
        if rc is not None:
            # died between the supervision poll and teardown: reap and
            # record it, or the exit report would claim it never exited
            w.record_exit(rc)
            if rc == 0:
                w.done = True
        else:
            live.append(w)
    for w in live:
        try:
            w.proc.send_signal(signal.SIGTERM)
        except OSError:
            pass
    deadline = time.monotonic() + max(0.0, term_window)
    for w in live:
        remaining = deadline - time.monotonic()
        try:
            w.proc.wait(timeout=max(0.05, remaining))
        except subprocess.TimeoutExpired:
            try:
                w.proc.kill()
            except OSError:
                pass
            w.proc.wait()
    for w in live:
        if w.proc is not None:
            w.record_exit(w.proc.returncode)


def _print_report(workers, out=sys.stderr):
    print("[launch] worker exit report:", file=out)
    for w in workers:
        attempts = []
        for e in w.exits:
            attempts.append(e["signal"] or f"exit {e['exit_code']}")
        print(f"[launch]   rank {w.rank}: "
              f"{' -> restart -> '.join(attempts) or 'never exited'}"
              f" (restarts: {w.restarts})", file=out)


def supervise(workers, *, max_restarts: int, restart_backoff: float,
              term_window: float, poll_interval: float,
              log=lambda msg: print(msg, file=sys.stderr)) -> int:
    """The supervision loop (importable for tests). Spawns every worker,
    polls them all, applies the fail-fast / elastic policy, and returns
    the job's exit code (first failing rank's code, 0 when every rank
    finished clean)."""
    for w in workers:
        w.spawn()
    first_fail: int | None = None
    try:
        while True:
            now = time.monotonic()
            for w in workers:
                if w.done or w.proc is None:
                    # pending restart?
                    if (not w.done and w.restart_at is not None
                            and now >= w.restart_at):
                        log(f"[launch] restarting rank {w.rank} "
                            f"(restart #{w.restarts})")
                        w.spawn()
                    continue
                rc = w.poll()
                if rc is None:
                    continue
                w.record_exit(rc)
                if rc == 0:
                    w.done = True
                    continue
                code = _exit_code(rc)
                desc = _signal_name(-rc) if rc < 0 else f"code {rc}"
                if w.restarts < max_restarts:
                    w.restarts += 1
                    delay = min(
                        restart_backoff * (2.0 ** (w.restarts - 1)),
                        _BACKOFF_CAP_S)
                    w.restart_at = now + delay
                    log(f"[launch] rank {w.rank} died ({desc}); "
                        f"restart #{w.restarts}/{max_restarts} "
                        f"in {delay:.1f}s")
                else:
                    mode = "fail-fast" if max_restarts == 0 else \
                        "restarts exhausted"
                    log(f"[launch] rank {w.rank} died ({desc}); {mode}: "
                        f"terminating remaining workers "
                        f"(window {term_window:g}s)")
                    first_fail = code
                    break
            if first_fail is not None:
                _terminate_all(workers, term_window)
                return first_fail
            if all(w.done for w in workers):
                return 0
            time.sleep(poll_interval)
    except KeyboardInterrupt:
        log("[launch] interrupted; terminating workers")
        _terminate_all(workers, term_window)
        return 130


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("-n", "--num-workers", type=int, required=True,
                    help="total worker processes")
    ap.add_argument("-H", "--hostfile", default=None,
                    help="one host per line; default: all workers local")
    ap.add_argument("-p", "--port", type=int, default=0,
                    help="coordinator port (default: pick a free one)")
    ap.add_argument("--env", action="append", default=[],
                    metavar="K=V", help="extra env to export to workers")
    ap.add_argument("--max-restarts", type=int, default=0,
                    help="per-rank restart budget (0 = fail-fast: first "
                    "non-zero exit tears the job down)")
    ap.add_argument("--restart-backoff", type=float, default=1.0,
                    help="base restart delay (s); doubles per restart "
                    f"of a rank, capped at {_BACKOFF_CAP_S:g}s")
    ap.add_argument("--term-window", type=float, default=10.0,
                    help="seconds between SIGTERM and SIGKILL when "
                    "tearing the job down")
    ap.add_argument("--poll-interval", type=float, default=0.2,
                    help="supervision poll period (s)")
    ap.add_argument("--coord-dir", default=None,
                    help="shared elastic coordinator dir exported as "
                    "MXNET_ELASTIC_COORD_DIR (default: a fresh tempdir)")
    ap.add_argument("--report", default=None,
                    help="write the per-worker exit report JSON here")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="worker command")
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("no worker command given")
    if args.max_restarts < 0:
        ap.error("--max-restarts must be >= 0")
    cmd = args.command[1:] if args.command[0] == "--" else args.command

    hosts = None
    if args.hostfile:
        with open(args.hostfile) as f:
            hosts = [ln.strip() for ln in f if ln.strip()
                     and not ln.startswith("#")]
        if not hosts:
            ap.error(f"hostfile {args.hostfile} is empty")

    root_uri = hosts[0] if hosts else "127.0.0.1"
    port = args.port or _free_port()
    extra = dict(kv.split("=", 1) for kv in args.env)
    coord_dir = args.coord_dir or tempfile.mkdtemp(prefix="mxnet_elastic_")
    os.makedirs(coord_dir, exist_ok=True)

    def spawn(rank: int, restart_count: int) -> subprocess.Popen:
        env = dict(os.environ, **extra,
                   DMLC_PS_ROOT_URI=root_uri,
                   DMLC_PS_ROOT_PORT=str(port),
                   DMLC_NUM_WORKER=str(args.num_workers),
                   DMLC_WORKER_ID=str(rank),
                   DMLC_ROLE="worker",
                   MXNET_ELASTIC_COORD_DIR=coord_dir,
                   MXNET_ELASTIC_RESTART=str(restart_count))
        if hosts:
            host = hosts[rank % len(hosts)]
            exports = " ".join(
                f"{k}={shlex.quote(env[k])}"
                for k in ("DMLC_PS_ROOT_URI", "DMLC_PS_ROOT_PORT",
                          "DMLC_NUM_WORKER", "DMLC_WORKER_ID",
                          "DMLC_ROLE", "MXNET_ELASTIC_COORD_DIR",
                          "MXNET_ELASTIC_RESTART", *extra))
            remote = f"cd {shlex.quote(os.getcwd())} && " \
                     f"env {exports} {' '.join(map(shlex.quote, cmd))}"
            return subprocess.Popen(["ssh", "-o", "BatchMode=yes", host,
                                     remote])
        return subprocess.Popen(cmd, env=env)

    workers = [_Worker(rank, spawn) for rank in range(args.num_workers)]
    rc = supervise(workers, max_restarts=args.max_restarts,
                   restart_backoff=args.restart_backoff,
                   term_window=args.term_window,
                   poll_interval=args.poll_interval)
    _print_report(workers)
    if args.report:
        with open(args.report, "w") as f:
            json.dump({"rc": rc,
                       "mode": "elastic" if args.max_restarts else
                       "fail_fast",
                       "max_restarts": args.max_restarts,
                       "coord_dir": coord_dir,
                       "workers": [w.report() for w in workers]},
                      f, indent=1)
    return rc


if __name__ == "__main__":
    sys.exit(main())
