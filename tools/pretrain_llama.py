"""Llama pretraining driver — the stretch config (BASELINE.json config[4]).

End-to-end causal-LM pretraining on a TPU mesh with the framework's fused
TrainStep: forward + CE loss + backward + AdamW-family update + the
GSPMD-inserted collectives in ONE compiled executable per step.

    # single chip, 1B-ish proxy, synthetic tokens
    python tools/pretrain_llama.py --config proxy1b --steps 20

    # 8-device virtual mesh (tp x dp), tiny config, real shardings
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python tools/pretrain_llama.py --config tiny --mesh dp=2,tp=2,sp=2

    # full Llama-3-8B dims, AOT compile only (no weights materialized):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python tools/pretrain_llama.py --config 8b --mesh dp=2,tp=4 \
        --compile-only

Data: ``--data synthetic`` (default) draws random token ids host-side once
and reuses the staged device batch (benchmark methodology, PERF.md);
``--data <path.rec>`` streams token records through io.RecordIter.
Checkpointing: ``--save-dir`` writes net .params + trainer state every
``--save-every`` steps via the framework's V3 checkpoint format.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CONFIGS = {
    # test-sized
    "tiny": dict(vocab_size=256, num_layers=2, units=64, hidden_size=128,
                 num_heads=4, num_kv_heads=2, rope_theta=10000.0),
    # ~0.7B single-chip proxy of the 8B recipe (same code path, same
    # ratios: GQA 2:1 over d=128 heads, SwiGLU ~3.5x, untied head)
    "proxy1b": dict(vocab_size=32768, num_layers=10, units=2048,
                    hidden_size=7168, num_heads=16, num_kv_heads=8,
                    rope_theta=500000.0),
    # Llama-3-8B
    "8b": dict(vocab_size=128256, num_layers=32, units=4096,
               hidden_size=14336, num_heads=32, num_kv_heads=8,
               rope_theta=500000.0),
}


def param_count(cfg):
    u, h, v = cfg["units"], cfg["hidden_size"], cfg["vocab_size"]
    d = u // cfg["num_heads"]
    kv = cfg["num_kv_heads"] * d
    per_layer = u * u + u * 2 * kv + u * u + 2 * u * h + h * u + 2 * u
    return cfg["num_layers"] * per_layer + 2 * v * u + u


def parse_mesh(spec):
    axes = {}
    if spec:
        for part in spec.split(","):
            k, v = part.split("=")
            axes[k.strip()] = int(v)
    return axes or {"dp": 1}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default="tiny", choices=sorted(CONFIGS))
    ap.add_argument("--mesh", default="", help="e.g. dp=2,tp=2,sp=2")
    ap.add_argument("--batch", type=int, default=None, help="global batch")
    ap.add_argument("--seq", type=int, default=None)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--weight-decay", type=float, default=0.1)
    ap.add_argument("--remat", nargs="?", const=True, default=None,
                    help="enable remat; optional value picks the policy "
                         "('full' save-nothing, 'dots' keep matmul outputs)")
    ap.add_argument("--no-remat", dest="remat", action="store_false")
    ap.add_argument("--data", default="synthetic")
    ap.add_argument("--save-dir", default=None)
    ap.add_argument("--save-every", type=int, default=1000)
    ap.add_argument("--no-fused-ce", dest="fused_ce",
                    action="store_false", default=True,
                    help="materialize logits + separate CE instead of "
                         "the fused projection+CE head")
    ap.add_argument("--compile-only", action="store_true",
                    help="AOT lower+compile the sharded train step without "
                         "materializing weights (validates the 8B recipe "
                         "on hosts that cannot hold 8B params)")
    ap.add_argument("--dtype", default="bfloat16")
    args = ap.parse_args(argv)

    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import parallel as par
    from mxnet_tpu.callback import device_peak_flops
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo.nlp.llama import (
        LlamaModel, llama_sharding_rules)

    cfg = dict(CONFIGS[args.config])
    n_params = param_count(cfg)
    axes = parse_mesh(args.mesh)
    seq = args.seq or (2048 if args.config != "tiny" else 128)
    batch = args.batch or max(2 * axes.get("dp", 1),
                              4 if args.config == "proxy1b" else 2)
    remat = args.remat if args.remat is not None else args.config != "tiny"

    mesh = par.make_mesh(axes)
    rules = llama_sharding_rules(tp_axis="tp") if "tp" in axes else None
    ring_axis = "sp" if "sp" in axes else None

    net = LlamaModel(**cfg, remat=remat, ring_axis=ring_axis,
                     fused_ce=args.fused_ce)
    loss_fn = (_FusedLossPassthrough() if args.fused_ce
               else _CausalLMLoss(gloss))

    if args.compile_only:
        return _compile_only(jax, mx, par, net, loss_fn, mesh, rules,
                             batch, seq, cfg, args, n_params)

    net.initialize()
    net.cast(args.dtype)

    step = par.TrainStep(
        net, loss_fn, "adamw", mesh=mesh, rules=rules,
        batch_axis=("dp",), seq_axis=("sp" if "sp" in axes else None),
        loss_only=True,
        optimizer_params={"learning_rate": args.lr,
                          "wd": args.weight_decay,
                          "beta1": 0.9, "beta2": 0.95,
                          "multi_precision": True})

    data_iter = _make_data(mx, args.data, batch, seq, cfg["vocab_size"],
                           int_labels=args.fused_ce)
    tokens, labels = next(data_iter)

    def run_step(tokens, labels):
        if args.fused_ce:
            return step((tokens, labels), ())
        return step(tokens, labels)

    t0 = time.time()
    loss, _ = run_step(tokens, labels)
    loss_val = float(loss.asnumpy())
    print(f"step 1: loss {loss_val:.4f} "
          f"(compile+run {time.time() - t0:.0f}s; {n_params / 1e6:.0f}M "
          f"params, mesh {dict(zip(mesh.axis_names, mesh.devices.shape))})",
          flush=True)
    if args.data == "synthetic":
        if args.fused_ce:
            step.stage_batch((tokens, labels), ())
        else:
            step.stage_batch(tokens, labels)

    # Throughput methodology: async dispatch means per-step host timers
    # measure DISPATCH, not device time, and the final fetch's wait
    # carries EVERY queued step's device time — a trailing window that
    # doesn't start from a synced point mis-attributes earlier steps'
    # device work into its own denominator (round 4 found the round-3
    # proxy number undercounted ~2x this way; the jax.profiler trace
    # shows back-to-back 575 ms device steps). So: sync (fetch) at the
    # steady-window boundary, wall-time the remaining steps as one span
    # ending in a fetch — the same synced-span method bench.py uses.
    times = []
    sync_at = min(max(2, args.steps // 2), max(args.steps - 1, 1))
    t_span = None
    span_dt = None
    span_steps = 0
    save_s = 0.0  # checkpoint-write time inside the span, excluded below
    for i in range(2, args.steps + 1):
        if args.data != "synthetic":
            tokens, labels = next(data_iter)
        t0 = time.time()
        loss, _ = run_step(tokens, labels)
        if i == sync_at:
            loss_val = float(loss.asnumpy())  # drain the dispatch queue
            t_span = time.time()
        elif i == args.steps or i % 20 == 0:
            loss_val = float(loss.asnumpy())
        if i > sync_at:
            span_steps += 1
        if i == args.steps and t_span is not None:
            # span ends HERE, at the final fetch — checkpoint saves must
            # not leak into the throughput denominator
            span_dt = time.time() - t_span - save_s
        times.append(time.time() - t0)
        if args.save_dir and i % args.save_every == 0:
            t_save = time.time()
            _save(net, step, args.save_dir, i)
            if t_span is not None and i < args.steps:
                save_s += time.time() - t_save
        if i == args.steps or i % 20 == 0:
            tok_s = batch * seq / (sum(times[-10:]) / len(times[-10:]))
            print(f"step {i}: loss {loss_val:.4f} tokens/s {tok_s:.0f} "
                  f"(rolling dispatch-window; final number is synced-span)",
                  flush=True)
    if args.save_dir and args.steps % args.save_every != 0:
        _save(net, step, args.save_dir, args.steps)

    peak = device_peak_flops()
    if span_dt is not None and span_steps > 0:
        tok_s = batch * seq * span_steps / span_dt
    else:  # --steps 1: only the compile step ran; t0 is its dispatch
        tok_s = batch * seq / max(time.time() - t0, 1e-9)
    mfu = 6.0 * n_params * tok_s / peak if peak else None
    print(json.dumps({
        "config": args.config, "params": n_params, "tokens_per_sec":
        round(tok_s, 1), "mfu": round(mfu, 4) if mfu else None,
        "final_loss": loss_val}))
    return 0


class _FusedLossPassthrough:
    """fused_ce=True: the model already returns per-token loss."""

    def __call__(self, outs, *a):
        return outs[0] if isinstance(outs, (list, tuple)) else outs


class _CausalLMLoss:
    """Next-token CE over (B, L, vocab) logits (shift-by-one)."""

    def __init__(self, gloss):
        self._l = gloss.SoftmaxCrossEntropyLoss()

    def __call__(self, outs, labels):
        logits = outs[0] if isinstance(outs, (list, tuple)) else outs
        b, l, v = logits.shape
        return self._l(logits.reshape(-1, v), labels.reshape(-1))


def _make_data(mx, source, batch, seq, vocab, int_labels=False):
    lab_dtype = np.int32 if int_labels else np.float32
    if source == "synthetic":
        rs = np.random.RandomState(0)
        toks = rs.randint(0, vocab, (batch, seq + 1))

        def gen():
            while True:
                yield (mx.nd.array(toks[:, :-1].astype(np.int32)),
                       mx.nd.array(toks[:, 1:].astype(lab_dtype)))
        return gen()

    from mxnet_tpu import recordio

    def gen_rec():
        while True:
            reader = recordio.MXRecordIO(source, "r")
            buf_t, buf_l = [], []
            while True:
                rec = reader.read()
                if rec is None:
                    break
                arr = np.frombuffer(rec, dtype=np.int32)
                if arr.shape[0] < seq + 1:
                    continue
                buf_t.append(arr[:seq])
                buf_l.append(arr[1:seq + 1])
                if len(buf_t) == batch:
                    yield (mx.nd.array(np.stack(buf_t)),
                           mx.nd.array(np.stack(buf_l).astype(lab_dtype)))
                    buf_t, buf_l = [], []
            reader.close()
    return gen_rec()


def _save(net, step, save_dir, i):
    os.makedirs(save_dir, exist_ok=True)
    net.save_parameters(os.path.join(save_dir, f"llama-{i:07d}.params"))
    # optimizer states via the kvstore-free trainer-state format
    import pickle

    states = [s.asnumpy() for s in step._state_leaf_nds]
    with open(os.path.join(save_dir, f"llama-{i:07d}.states"), "wb") as f:
        pickle.dump({"num_update": step.optimizer.num_update,
                     "leaves": states}, f)
    print(f"saved checkpoint @ step {i} -> {save_dir}", flush=True)


def _compile_only(jax, mx, par, net, loss_fn, mesh, rules, batch, seq, cfg,
                  args, n_params):
    """AOT-compile the full sharded train step on abstract weights.

    Validates that the 8B recipe (shardings x remat x fused TrainStep)
    lowers and compiles for the target mesh without needing a host that
    can hold the weights: the net "initializes" under
    ``gluon.parameter.abstract_init()`` and ``TrainStep.aot_compile``
    runs the normal settle/state/build/lower path on ShapeDtypeStructs.
    """
    import jax.numpy as jnp

    from mxnet_tpu.gluon.parameter import abstract_init

    t0 = time.time()
    with abstract_init():
        net.initialize()
        for p in net.collect_params().values():
            p._dtype = args.dtype
        step = par.TrainStep(
            net, loss_fn, "adamw", mesh=mesh, rules=rules,
            batch_axis=("dp",), seq_axis=("sp" if "sp" in
                                          mesh.axis_names else None),
            loss_only=True,
            optimizer_params={"learning_rate": args.lr,
                              "wd": args.weight_decay,
                              "beta1": 0.9, "beta2": 0.95,
                              "multi_precision": True})
        tok = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
        if args.fused_ce:
            # fused head: labels are the model's second DATA input
            lbl = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
            compiled = step.aot_compile((tok, lbl), ())
        else:
            lbl = jax.ShapeDtypeStruct((batch, seq), jnp.float32)
            compiled = step.aot_compile(tok, lbl)
    try:
        mem = compiled.memory_analysis()
        arg_b = getattr(mem, "argument_size_in_bytes", None)
        tmp_b = getattr(mem, "temp_size_in_bytes", None)
    except Exception:
        arg_b = tmp_b = None
    print(json.dumps({
        "config": args.config, "compile_only": True, "params": n_params,
        "mesh": dict(zip(mesh.axis_names, mesh.devices.shape)),
        "batch": batch, "seq": seq, "remat": bool(net._remat),
        "compile_s": round(time.time() - t0, 1),
        "argument_bytes_per_device": arg_b,
        "temp_bytes_per_device": tmp_b,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
