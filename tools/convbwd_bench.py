"""A/B harness for ResNet conv-backward formulations on TPU.

Round-3 trace: conv-bwd (dW/dX) runs at ~38% of roofline inside XLA —
57.7 ms of the 106.8 ms batch-256 step (PERF.md "ResNet-50: NHWC").
This tool times, per distinct ResNet-50 conv shape, three dW recipes:

  vjp      XLA's own backward (jax.vjp of conv_general_dilated) — baseline
  patches  dW as an explicit im2col matmul: extract input patches
           (lax.conv_general_dilated_patches), one big MXU dot_general
           contracting over (batch x out-positions)
  both     patches-dW + vjp-dX together (what a custom_vjp would run)

Measurement: each candidate runs CHAINED inside lax.scan (the carry feeds
iteration i+1 from i's output) so the axon relay's async-dispatch lies
cancel out (see memory: isolated microbenches through the relay are
noise). Report = ms/iter from one end-to-end timed executable.

Usage:  python tools/convbwd_bench.py [--iters 100] [--batch 256]
"""
from __future__ import annotations

import argparse
import json
import time

import numpy as np

# (name, H, W, Cin, Cout, kh, kw, stride) — ResNet-50 distinct conv shapes
# (NHWC, batch from --batch). Counts in ResNet-50: each shape's multiplicity
# matters for projecting step-time savings; listed as `mult`.
SHAPES = [
    ("stem7x7s2", 224, 224, 3, 64, 7, 7, 2, 1),
    ("s1_1x1a", 56, 56, 64, 64, 1, 1, 1, 3),
    ("s1_3x3", 56, 56, 64, 64, 3, 3, 1, 3),
    ("s1_1x1b", 56, 56, 64, 256, 1, 1, 1, 3),
    ("s1_proj", 56, 56, 256, 64, 1, 1, 1, 2),
    ("s2_down3x3", 56, 56, 128, 128, 3, 3, 2, 1),
    ("s2_3x3", 28, 28, 128, 128, 3, 3, 1, 3),
    ("s2_1x1b", 28, 28, 128, 512, 1, 1, 1, 4),
    ("s2_proj", 28, 28, 512, 128, 1, 1, 1, 3),
    ("s3_down3x3", 28, 28, 256, 256, 3, 3, 2, 1),
    ("s3_3x3", 14, 14, 256, 256, 3, 3, 1, 5),
    ("s3_1x1b", 14, 14, 256, 1024, 1, 1, 1, 6),
    ("s3_proj", 14, 14, 1024, 256, 1, 1, 1, 5),
    ("s4_down3x3", 14, 14, 512, 512, 3, 3, 2, 1),
    ("s4_3x3", 7, 7, 512, 512, 3, 3, 1, 2),
    ("s4_1x1b", 7, 7, 512, 2048, 1, 1, 1, 3),
    ("s4_proj", 7, 7, 2048, 512, 1, 1, 1, 2),
]


def conv_fwd(x, w, stride, pad):
    import jax

    # bf16 in/out like the real bf16 TrainStep (MXU accumulates f32
    # internally either way)
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding=pad,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def dw_patches(x, dy, kh, kw, stride, pad, cin):
    """dW via im2col: patches (N,Ho,Wo,kh*kw*Cin) x dy (N,Ho,Wo,Cout)
    contracted over (N,Ho,Wo) in ONE dot_general on the MXU."""
    import jax
    import jax.numpy as jnp

    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (stride, stride), pad,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    n, ho, wo, _ = patches.shape
    dw = jax.lax.dot_general(
        patches.reshape(n * ho * wo, -1), dy.reshape(n * ho * wo, -1),
        (((0,), (0,)), ((), ())),
        preferred_element_type=np.float32)
    # patches feature order is Cin-major: (Cin, kh, kw) per the jax docs
    return dw.reshape(cin, kh, kw, -1).transpose(1, 2, 0, 3)  # -> HWIO


def bench_one(name, h, w, cin, cout, kh, kw, stride, mult, batch, iters):
    import jax
    import jax.numpy as jnp

    pad = "SAME" if (kh > 1 or stride > 1) else "VALID"
    rs = np.random.RandomState(0)
    x0 = jnp.asarray(rs.randn(batch, h, w, cin), jnp.bfloat16)
    w0 = jnp.asarray(rs.randn(kh, kw, cin, cout) * 0.05, jnp.bfloat16)

    def make_chain(body):
        def chained(x, wgt):
            def tick(carry, _):
                xx, ww = carry
                out = body(xx, ww)
                # data dependence: perturb weights by a tiny function of
                # the result so the scan cannot be parallelized/DCE'd
                ww = ww * (1 + 1e-30 * out.astype(jnp.bfloat16).mean())
                return (xx, ww), ()

            (xx, ww), _ = jax.lax.scan(tick, (x, wgt), None, length=iters)
            return ww

        return jax.jit(chained)

    def vjp_dw(x, wgt):
        y, pull = jax.vjp(lambda w_: conv_fwd(x, w_, stride, pad), wgt)
        (dw,) = pull(jnp.ones_like(y))
        return dw

    def vjp_dx(x, wgt):
        y, pull = jax.vjp(lambda x_: conv_fwd(x_, wgt, stride, pad), x)
        (dx,) = pull(jnp.ones_like(y))
        return dx

    def patches_dw(x, wgt):
        y = conv_fwd(x, wgt, stride, pad)
        return dw_patches(x, jnp.ones_like(y), kh, kw, stride, pad, cin)

    results = {}
    for label, body in (("vjp_dw", vjp_dw), ("patches_dw", patches_dw),
                        ("vjp_dx", vjp_dx)):
        fn = make_chain(body)
        out = fn(x0, w0)
        out.block_until_ready()
        t0 = time.perf_counter()
        out = fn(x0, w0)
        float(jnp.sum(out.astype(jnp.float32)))  # data-dependent fetch
        dt = time.perf_counter() - t0
        results[label] = dt / iters * 1e3  # ms per iteration
    results["mult"] = mult
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=100)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--only", type=str, default=None,
                    help="comma-separated shape-name filter")
    args = ap.parse_args()

    only = set(args.only.split(",")) if args.only else None
    total = {"vjp_dw": 0.0, "patches_dw": 0.0}
    for row in SHAPES:
        if only and row[0] not in only:
            continue
        res = bench_one(*row, batch=args.batch, iters=args.iters)
        print(json.dumps({"shape": row[0], **{k: round(v, 3)
                          for k, v in res.items()}}), flush=True)
        for k in total:
            total[k] += res[k] * res["mult"]
    print(json.dumps({"shape": "TOTAL_weighted",
                      **{k: round(v, 2) for k, v in total.items()}}),
          flush=True)


if __name__ == "__main__":
    main()
