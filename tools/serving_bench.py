#!/usr/bin/env python
"""Serving-stack benchmark — no accelerator required.

Measures the inference serving path (``mxnet_tpu/serving``) on the CPU
oracle, producing the throughput-vs-latency curves ROADMAP item 1 asks
for, plus the two correctness gates:

1. **eager serving** — one dispatch per request through the hybridized
   net: requests/sec + p50/p99 latency. The no-batching baseline every
   serving stack must beat. Each request is padded to the grid's
   smallest batch bucket (2), exactly what a no-batching server over
   the same grid dispatches — and the reason the bit-identity gate can
   be exact: XLA:CPU lowers batch-1 matmuls to a GEMV whose reduction
   order differs in the last ulp from the GEMM used for every batch
   >= 2, while all GEMM-path batch sizes produce bit-identical rows
   (measured here; padding rows are bit-transparent). A grid whose
   smallest bucket is 2 makes a request's bits independent of
   co-batched traffic.
2. **batched serving** — the same net + traffic through
   ``serving.Server`` continuous batching (bucket-padded dynamic
   batches, deadline-aware close): requests/sec, p50/p99, mean batch
   occupancy. Acceptance: throughput >= 3x eager at equal model+traffic,
   outputs BIT-identical to eager per request.
3. **batched + int8** — the net ``quantize_net``-ed (naive calibration)
   behind the same server: the quantized throughput point of the curve.
4. **hot-reload gate** — a server under continuous traffic while the
   checkpoint it serves is replaced AND the old bundle deleted out from
   under it (kill-the-model-file): every in-flight request must resolve
   successfully, outputs flipping from old-weight to new-weight results
   with no failed or dropped request.
5. **multi-replica router** — the same traffic through a 2-replica
   ``serving.Router``: the scale-out throughput point, outputs still
   bit-identical per request (replicas share one grid, so whichever
   replica serves, the bits match).
6. **overload gate** — measure the router's sustainable capacity
   (closed loop), then offer 2x capacity open-loop: shedding must be
   synchronous and typed (``ServerOverloaded`` raised at ``submit``),
   goodput must stay >= 90% of the measured capacity, and accepted-
   request p99 must stay inside the SLO.
7. **scale-up gate** — the control plane's number: time from the scale
   DECISION to the new replica's first served response. Cold = the
   first replica of a never-before-seen architecture (pays the full
   trace + XLA compile per bucket signature); warm = ``add_replica``
   on a live router whose fleet already compiled the grid (the
   compilation service's single-flight executable table turns every
   bucket into a cache hit). Acceptance: warm >= 2x faster than cold —
   autoscaling only works when a scale-up costs seconds, not a
   retrace.
8. **ingress + worker gate** — the bench traffic through the FULL
   out-of-process path: ``IngressClient`` -> socket ``Ingress`` ->
   ``Router`` -> two ``RemoteReplica`` worker PROCESSES, vs an
   in-process router baseline measured IN THE SAME STAGE at matched
   model, SLO, replica count, and offered concurrency. The model is
   ``build_ingress_net`` (serving-realistic: compute is the majority
   of a request — against the stage-1 toy net every request is ~100%
   codec+socket overhead by construction and the ratio measures
   nothing but that). Acceptance: >= 70% of the matched baseline's
   throughput, outputs still bit-identical to the bucket oracle; the
   added p50 latency is decomposed into framing (wire codec CPU),
   socket (ping RTT x two seams), and scheduling (remainder) in the
   JSON.

9. **decode gate** — continuous-batching autoregressive decode
   (paged KV cache, ``Server.submit_generate``) vs the
   BucketingModule-style full-recompute loop the reference API implies
   (every step re-runs the whole sequence, padded to a length bucket
   so compilation amortizes — the strongest honest baseline) on the
   same tiny LLaMA. Both sides drive the SAME workload: four
   concurrent equal-length completions, the baseline advancing all
   four in one batched padded forward per step (its best case —
   batching cannot amortize recompute, only a KV cache can). Reports
   aggregate tokens/s and TTFT for both paths at several generation
   lengths. Acceptance: cached decode >= 5x full-recompute tokens/s
   at 256 generated tokens, every stream's tokens bit-identical to
   the full-recompute argmax at every length, and ZERO
   ``serving_decode`` compile-cache misses during the timed run
   (the zero-steady-state-retrace contract).

10. **multi-tenant isolation gate** — two tenants behind ONE fleet.
   Phase A (noisy neighbor): tenant "batch" is offered 2x its
   admission rate open-loop while tenant "premium" runs a closed loop
   within budget on the same 2-replica router. Acceptance: batch's
   overflow is shed per-tenant, typed (``TenantThrottled``) and
   resolved synchronously (never a failover crawl); premium sheds
   NOTHING on any replica; premium's accepted p99 stays inside the
   SLO close margin despite the neighbor's overload. Phase B
   (weighted fairness): two decode tenants (weights 3:1, same
   architecture, different weights/seeds) each keep 8 streams active
   on one server whose decode round has 4 slots — the weighted-fair
   slot assignment must land each tenant's measured token share
   within 10% of its configured weight share, with ZERO
   ``serving_decode`` compile-cache misses across the measurement
   window (both models resident, zero steady-state retraces).

Emits bench.py's JSON contract — one flushed line per completed stage,
monotonically enriched, ``{"metric", "value", "unit", "vs_baseline"}``
first — so the same last-line-of-stdout drivers parse it.
``vs_baseline`` is the batched-vs-eager speedup against the 3x
acceptance bar (ISSUE 6): >= 1.0 passes. Knobs: SERVING_BENCH_REQUESTS
(default 512), SERVING_BENCH_BATCH (max batch bucket, 32),
SERVING_BENCH_SLO_MS (50), SERVING_BENCH_FEEDERS (submit threads, 4).

Forces JAX_PLATFORMS=cpu when run as a script — but, unlike
comms_bench, NOT the 8-device virtual mesh: a serving replica is one
device, and the virtual split shrinks each device's thread budget,
which changes XLA:CPU's GEMM blocking per batch size and perturbs the
cross-bucket bit-identity this bench gates on (measured: buckets 16/32
drift an ulp from 2/4/8 under the 8-way split, none drift on a whole
device). Importing the module has no side effects (tests borrow the
stage functions).
"""
from __future__ import annotations

import json
import os
import shutil
import sys
import threading
import time

if __name__ == "__main__":
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

import numpy as np

SPEEDUP_BAR = 3.0      # ISSUE 6 acceptance: batched >= 3x eager
SCALEUP_BAR = 2.0      # control plane: warm scale-up >= 2x faster than
                       # a cold replica spawn (decision-to-first-response)
INGRESS_BAR = 0.70     # out-of-process path (ingress + worker processes)
                       # must sustain >= 70% of the in-process router's
                       # measured throughput at matched SLO
DECODE_BAR = 5.0       # paged-KV cached decode >= 5x full-recompute
                       # tokens/s at 256 generated tokens
IN_UNITS = 512
HIDDEN = 256
CLASSES = 10


def _emit(record: dict) -> None:
    print(json.dumps(record), flush=True)


def _pctl(xs, q):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def build_net(seed: int = 0, scale: float = 1.0):
    """A small MLP with deterministic weights — the bench model. Small
    enough that per-request dispatch overhead dominates eager serving
    (the regime batching exists to fix); built twice with the same seed
    it is bit-identical, so eager/batched/int8 all serve THE same model.
    """
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(HIDDEN, activation="relu", in_units=IN_UNITS),
                nn.Dense(HIDDEN, activation="relu", in_units=HIDDEN),
                nn.Dense(CLASSES, in_units=HIDDEN))
    net.initialize()
    rs = np.random.RandomState(seed)
    for p in net.collect_params().values():
        p.set_data(mx.nd.array(
            (rs.randn(*p.shape) * 0.05 * scale).astype(np.float32)))
    net.hybridize()
    return net


def make_traffic(n: int, seed: int = 1):
    rs = np.random.RandomState(seed)
    return [rs.randn(IN_UNITS).astype(np.float32) for _ in range(n)]


MIN_BUCKET = 2      # smallest batch bucket: keeps every dispatch on the
                    # GEMM path -> response bits independent of traffic

# Stage-8 model: the out-of-process overhead share is only meaningful
# against a model whose compute is the majority cost (the regime real
# serving runs in — TF Serving sizes batching the same way). The
# stage-1 net (~30 us/request amortized) measures codec-cost-per-
# microsecond-of-model: through two socket seams EVERY request is
# ~100% overhead by construction and no plumbing can reach the bar.
# This net is ~22 ms per batch-4 on one Eigen thread (memory-bound: a
# batch-2 GEMM costs nearly what batch-4 costs, so batching is almost
# free), ~5 ms/request at the full bucket. Wider was tried and is
# WORSE for the measurement: at ~48 ms/batch the fleet's service rate
# drops far enough that the router's predicted-wait shedding arms
# against the deadline on BOTH sides and the stage measures shed/retry
# dynamics, not the process boundary. Buckets stop at 4: XLA:CPU
# changes its GEMM blocking for this width at batch 8 and the rows
# drift an ulp from the batch-2 oracle (measured), while 2/4 are
# bit-identical.
INGRESS_HIDDEN = 2048
INGRESS_MAX_BATCH = 4
INGRESS_SLO_MS = 150.0


def build_ingress_net(seed: int = 0):
    """The stage-8 serving-realistic model (worker factory:
    ``serving_bench:build_ingress_net``) — same deterministic-weight
    contract as :func:`build_net`, ~400x its per-request compute."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(INGRESS_HIDDEN, activation="relu",
                         in_units=IN_UNITS),
                nn.Dense(INGRESS_HIDDEN, activation="relu",
                         in_units=INGRESS_HIDDEN),
                nn.Dense(CLASSES, in_units=INGRESS_HIDDEN))
    net.initialize()
    rs = np.random.RandomState(seed)
    for p in net.collect_params().values():
        p.set_data(mx.nd.array(
            (rs.randn(*p.shape) * 0.05).astype(np.float32)))
    net.hybridize()
    return net


def _net_rows(net, batch: np.ndarray) -> list:
    """Forward one already-padded batch, return its output rows."""
    import mxnet_tpu as mx

    return list(net(mx.nd.array(batch)).asnumpy())


def eager_single(net, x, min_bucket: int = MIN_BUCKET):
    """One request, no batching: one dispatch padded to the smallest
    batch bucket (what a no-batching server over the grid does)."""
    import mxnet_tpu as mx

    pad = np.zeros((min_bucket,) + x.shape, x.dtype)
    pad[0] = x
    return net(mx.nd.array(pad)).asnumpy()[0]


def eager_stage(net, samples):
    """One dispatch per request: (rps, p50_ms, p99_ms, outputs)."""
    eager_single(net, samples[0])      # warm the min-bucket entry
    outs, lats = [], []
    t_all = time.perf_counter()
    for x in samples:
        t0 = time.perf_counter()
        outs.append(eager_single(net, x))
        lats.append(time.perf_counter() - t0)
    wall = time.perf_counter() - t_all
    return (len(samples) / wall, _pctl(lats, 0.50) * 1e3,
            _pctl(lats, 0.99) * 1e3, outs)


def batched_stage(net, samples, max_batch, slo_ms, feeders=4):
    """The same traffic through Server continuous batching:
    (rps, p50_ms, p99_ms, outputs, mean_occupancy)."""
    from mxnet_tpu import serving

    buckets = [MIN_BUCKET]
    while buckets[-1] < max_batch:
        buckets.append(buckets[-1] * 2)
    srv = serving.Server(net, batch_buckets=buckets,
                         shape_buckets=[(IN_UNITS,)], slo_ms=slo_ms)
    srv.start()
    n = len(samples)
    outs = [None] * n
    lats = [None] * n
    errs = []
    done = threading.Event()
    remaining = [n]
    lock = threading.Lock()

    def feed(lo, hi):
        for i in range(lo, hi):
            t0 = time.perf_counter()

            def cb(fut, i=i, t0=t0):
                try:
                    outs[i] = fut.result()
                    lats[i] = time.perf_counter() - t0
                except Exception as e:  # noqa: BLE001
                    errs.append(e)
                with lock:
                    remaining[0] -= 1
                    if remaining[0] == 0:
                        done.set()
            srv.submit(samples[i]).add_done_callback(cb)

    per = (n + feeders - 1) // feeders
    threads = [threading.Thread(target=feed, args=(k * per,
                                                   min(n, (k + 1) * per)))
               for k in range(feeders)]
    t_all = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    done.wait(120)
    wall = time.perf_counter() - t_all
    stats = srv.stats()
    srv.stop()
    if errs:
        raise errs[0]
    occupancy = n / max(stats["batches"], 1) / max_batch
    return (n / wall, _pctl(lats, 0.50) * 1e3, _pctl(lats, 0.99) * 1e3,
            outs, occupancy)


def router_stage(samples, max_batch, slo_ms, n_replicas=2, feeders=4):
    """The batched-stage traffic through a Router over ``n_replicas``
    fresh replicas of the same net: (rps, p50_ms, p99_ms, outputs,
    per-replica served counts)."""
    router = _make_router(max_batch, slo_ms, n_replicas, tag="router")
    try:
        n = len(samples)
        outs = [None] * n
        lats = [None] * n
        errs = []
        done = threading.Event()
        remaining = [n]
        lock = threading.Lock()

        def feed(lo, hi):
            # closed loop with bounded outstanding per feeder: the
            # router expires queued requests against the per-request
            # deadline (default = SLO), so an unbounded burst on a slow
            # container measures its own queueing, not throughput —
            # overload behavior is stage 6's job, this stage's is the
            # sustainable-rate point
            sem = threading.Semaphore(16)
            for i in range(lo, hi):
                sem.acquire()
                t0 = time.perf_counter()

                def cb(fut, i=i, t0=t0):
                    try:
                        outs[i] = fut.result()
                        lats[i] = time.perf_counter() - t0
                    except Exception as e:  # noqa: BLE001
                        errs.append(e)
                    sem.release()
                    with lock:
                        remaining[0] -= 1
                        if remaining[0] == 0:
                            done.set()
                try:
                    # unlike Server.submit, the Router sheds
                    # SYNCHRONOUSLY — a raise here must be recorded,
                    # not kill the feeder thread and hang the stage
                    fut = router.submit(samples[i])
                except Exception as e:  # noqa: BLE001
                    errs.append(e)
                    sem.release()
                    with lock:
                        remaining[0] -= 1
                        if remaining[0] == 0:
                            done.set()
                    continue
                fut.add_done_callback(cb)

        per = (n + feeders - 1) // feeders
        threads = [threading.Thread(target=feed,
                                    args=(k * per, min(n, (k + 1) * per)))
                   for k in range(feeders)]
        t_all = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        done.wait(120)
        wall = time.perf_counter() - t_all
        if errs:
            raise errs[0]
        served = [r["ok"] for r in router.stats()["replicas"]]
        return (n / wall, _pctl(lats, 0.50) * 1e3,
                _pctl(lats, 0.99) * 1e3, outs, served)
    finally:
        router.stop(timeout=60)


def _make_router(max_batch, slo_ms, n_replicas, tag):
    from mxnet_tpu import serving

    buckets = [MIN_BUCKET]
    while buckets[-1] < max_batch:
        buckets.append(buckets[-1] * 2)
    reps = [serving.Server(build_net(), batch_buckets=buckets,
                           shape_buckets=[(IN_UNITS,)], slo_ms=slo_ms,
                           name=f"{tag}{i}")
            for i in range(n_replicas)]
    return serving.Router(reps, slo_ms=slo_ms).start()


# Overload-gate harness constants: the gate exercises ADMISSION CONTROL
# at a controlled service rate, not raw speed (stages 1-5 measure that).
# A paced model (fixed per-dispatch latency, GIL-releasing) makes the
# capacity and the 2x point deterministic across containers.
OVERLOAD_DISPATCH_MS = 20.0
OVERLOAD_SLO_MS = 100.0
OVERLOAD_MARGIN_MS = 30.0     # close margin sized to absorb one dispatch
                              # (20 ms) plus 2-core scheduling jitter
OVERLOAD_MAX_BATCH = 8


def _paced_block():
    import mxnet_tpu as mx

    class PacedBlock(mx.gluon.Block):
        """Eager block with a fixed dispatch latency — the controlled
        service rate the overload gate is calibrated against."""

        def forward(self, x):
            time.sleep(OVERLOAD_DISPATCH_MS / 1e3)
            return x * 2
    return PacedBlock()


def _make_overload_router(tag, n_replicas=2):
    from mxnet_tpu import serving

    reps = [serving.Server(_paced_block(),
                           batch_buckets=(2, 4, OVERLOAD_MAX_BATCH),
                           shape_buckets=[(IN_UNITS,)],
                           slo_ms=OVERLOAD_SLO_MS,
                           close_margin_ms=OVERLOAD_MARGIN_MS,
                           name=f"{tag}{i}")
            for i in range(n_replicas)]
    return serving.Router(reps, slo_ms=OVERLOAD_SLO_MS).start()


def overload_stage(n_replicas=2, t_capacity=2.0, t_overload=4.0,
                   overload_factor=2.0):
    """Measure sustainable router capacity (pipelined closed loop that
    keeps the batch buckets full), then offer ``overload_factor`` x
    that open-loop, clients demanding ``slo - close margin`` (the
    margin is the completion headroom). Returns the metric dict (keys
    prefixed ``serving_overload_``) plus ``ok``: sheds synchronous +
    typed (``ServerOverloaded`` at ``submit``), goodput >= 90% of
    capacity, accepted p99 within the SLO close margin (p99 - slo <=
    margin)."""
    from mxnet_tpu.serving.router import ServerOverloaded

    import gc

    slo_ms = OVERLOAD_SLO_MS
    x = make_traffic(1, seed=3)[0]
    # the earlier stages leave a large dead object graph (futures,
    # callbacks, padded batches); a GC pause inside the overload window
    # stalls every scheduler thread at once and lands straight in the
    # accepted-latency tail — collect it NOW, outside the measurement
    gc.collect()

    # -- phase 1: capacity, pipelined closed loop ----------------------
    # The SAME router serves phase 2: its service-rate estimator enters
    # the overload window hot, so shedding is armed from the first
    # tick instead of after a cold-start queue bulge.
    router = _make_overload_router("ov", n_replicas)
    stop = threading.Event()
    n_workers, depth = 8, 8          # 64 outstanding: buckets stay full
    counts = [0] * n_workers

    def closed_loop(k):
        while not stop.is_set():
            cl_futs = []
            for _ in range(depth):
                try:
                    cl_futs.append(router.submit(x, deadline_ms=2000))
                except Exception:  # noqa: BLE001 - probe pressure
                    pass
            for f in cl_futs:
                try:
                    f.result(timeout=10)
                    counts[k] += 1
                except Exception:  # noqa: BLE001
                    pass
    threads = [threading.Thread(target=closed_loop, args=(k,))
               for k in range(n_workers)]
    for t in threads:
        t.start()
    time.sleep(t_capacity)
    stop.set()
    for t in threads:
        t.join()
    capacity = sum(counts) / t_capacity
    gc.collect()                 # phase-1 garbage, same reasoning

    # -- phase 2: 2x offered load, open loop ---------------------------
    offered = overload_factor * capacity
    futs = []
    ok_lats = []
    lock = threading.Lock()
    n_shed = n_other_reject = 0
    submit_lats = []
    tick = 0.005
    backlog = 0.0
    n_in_window = [0]
    try:
        t0 = time.perf_counter()
        t_end = t0 + t_overload
        next_tick = t0
        while time.perf_counter() - t0 < t_overload:
            backlog += offered * tick
            burst, backlog = int(backlog), backlog % 1.0
            for _ in range(burst):
                ts = time.perf_counter()
                try:
                    # clients demand slo - margin: the close margin is
                    # the headroom that turns "dispatched by deadline"
                    # into "COMPLETED within the SLO"
                    fut = router.submit(
                        x, deadline_ms=slo_ms - OVERLOAD_MARGIN_MS)
                except ServerOverloaded:
                    n_shed += 1
                    submit_lats.append(time.perf_counter() - ts)
                    continue
                except Exception:  # noqa: BLE001 - typed but not shed
                    n_other_reject += 1
                    continue
                submit_lats.append(time.perf_counter() - ts)

                def cb(f, ts=ts):
                    td = time.perf_counter()
                    if f.exception() is None:
                        with lock:
                            ok_lats.append(td - ts)
                            if td <= t_end:     # goodput counts only
                                n_in_window[0] += 1   # in-window work
                futs.append(fut)
                fut.add_done_callback(cb)
            next_tick += tick
            dt = next_tick - time.perf_counter()
            if dt > 0:
                time.sleep(dt)
        deadline = time.time() + 60
        for f in futs:
            f.result(timeout=max(deadline - time.time(), 1))
    except Exception:  # noqa: BLE001 - errors counted below
        pass
    finally:
        router.stop(timeout=60)
    n_offered = len(futs) + n_shed + n_other_reject
    unresolved = sum(1 for f in futs if not f.done())
    goodput = n_in_window[0] / t_overload
    p99_accept = _pctl(ok_lats, 0.99) * 1e3 if ok_lats else float("inf")
    p99_submit = _pctl(submit_lats, 0.99) * 1e3 if submit_lats else 0.0
    vs_cap = goodput / capacity if capacity else 0.0
    p99_bound = slo_ms + OVERLOAD_MARGIN_MS
    sheds_sync = n_shed > 0 and p99_submit < 10.0 and unresolved == 0
    ok = (sheds_sync and vs_cap >= 0.9
          and p99_accept <= p99_bound and n_other_reject == 0)
    return {
        "serving_overload_capacity_rps": round(capacity, 1),
        "serving_overload_offered_rps": round(offered, 1),
        "serving_overload_requests_offered": n_offered,
        "serving_overload_goodput_rps": round(goodput, 1),
        "serving_overload_goodput_vs_capacity": round(vs_cap, 3),
        "serving_overload_shed_pct": round(100.0 * n_shed
                                           / max(n_offered, 1), 1),
        "serving_overload_accepted_p99_ms": round(p99_accept, 2),
        "serving_overload_p99_bound_ms": p99_bound,
        "serving_overload_submit_p99_ms": round(p99_submit, 3),
        "serving_overload_sheds_synchronous_typed": bool(sheds_sync),
        "serving_overload_gate": bool(ok),
    }, ok


def build_scale_net(seed: int = 0, hidden: int = HIDDEN + 64):
    """A DISTINCT architecture for the scale-up stage: stages 1-6
    already compiled ``build_net``'s bucket signatures in this process,
    so the cold-spawn measurement needs shapes the executable table has
    never seen."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(hidden, activation="relu", in_units=IN_UNITS),
                nn.Dense(CLASSES, in_units=hidden))
    net.initialize()
    rs = np.random.RandomState(seed)
    for p in net.collect_params().values():
        p.set_data(mx.nd.array(
            (rs.randn(*p.shape) * 0.05).astype(np.float32)))
    net.hybridize()
    return net


def scaleup_stage(slo_ms):
    """Scale-decision-to-first-response, cold vs warm (the autoscaler's
    latency): cold = first replica of a fresh architecture (trace +
    compile per bucket), warm = ``Router.add_replica`` once the fleet
    compiled the grid (executable-table hits). Returns (metrics, ok):
    warm must be >= ``SCALEUP_BAR`` x faster and the scaled-up
    replica's first response bit-identical to the fleet's."""
    from mxnet_tpu import serving

    buckets = (MIN_BUCKET, 4, 8)
    x = make_traffic(1, seed=5)[0]

    def mk(name):
        return serving.Server(build_scale_net(),
                              batch_buckets=buckets,
                              shape_buckets=[(IN_UNITS,)],
                              slo_ms=slo_ms, name=name)

    # cold spawn: decision -> first response, nothing compiled yet
    t0 = time.perf_counter()
    first = mk("scale0")
    first.start()
    ref = first.submit(x).result(timeout=300)
    t_cold = time.perf_counter() - t0

    router = serving.Router([first], slo_ms=slo_ms).start()
    try:
        # warm scale-up: the same decision once the fleet is hot —
        # add_replica starts + grid-warms the new replica (single-
        # flight executable table) before it takes traffic
        t0 = time.perf_counter()
        newcomer = mk("scale1")
        router.add_replica(newcomer)
        out = newcomer.submit(x).result(timeout=300)
        t_warm = time.perf_counter() - t0
    finally:
        router.stop(timeout=60)
    identical = np.array_equal(out, ref)
    speedup = t_cold / max(t_warm, 1e-9)
    ok = speedup >= SCALEUP_BAR and identical
    return {
        "serving_scaleup_cold_s": round(t_cold, 3),
        "serving_scaleup_warm_s": round(t_warm, 3),
        "serving_scaleup_speedup": round(speedup, 2),
        "serving_scaleup_bar": SCALEUP_BAR,
        "serving_scaleup_bit_identical": bool(identical),
        "serving_scaleup_gate": bool(ok),
    }, ok


def _framing_overhead_ms(x):
    """Per-request CPU cost of the wire codec alone: encode+decode of
    one submit and one result frame, times the TWO socket seams a
    request crosses (client<->ingress and router<->worker)."""
    from mxnet_tpu.serving import wire

    submit = {"kind": "submit", "id": 1, "sample": x}
    result = {"kind": "result", "id": 1, "ok": True,
              "payload": x[:CLASSES]}
    n = 2000
    t0 = time.perf_counter()
    for _ in range(n):
        h, b = wire.encode_payload(submit)
        wire.decode_payload(h, b)
        h, b = wire.encode_payload(result)
        wire.decode_payload(h, b)
    per_seam = (time.perf_counter() - t0) / n
    return 2.0 * per_seam * 1e3


def _socket_rtt_ms(port, n=400):
    """Round-trip of a minimal ping frame through the ingress: socket +
    handler-thread wakeup with (almost) no framing and no model work.
    One request crosses two such seams."""
    from mxnet_tpu.serving import wire

    sock = wire.connect("127.0.0.1", port, timeout=10)
    try:
        wire.send_frame(sock, {"kind": "ping", "id": 0})
        wire.recv_frame(sock)               # warm the path
        t0 = time.perf_counter()
        for i in range(n):
            wire.send_frame(sock, {"kind": "ping", "id": i})
            wire.recv_frame(sock)
        return (time.perf_counter() - t0) / n * 1e3
    finally:
        sock.close()


def _ingress_drive(argv) -> int:
    """Child mode (``--ingress-drive host:port in.npy out.npz
    outstanding``): one bench CLIENT as its own OS process. Loads its
    sample slice, connects an ``IngressClient``, warms the path, prints
    ``READY``, waits for ``GO`` on stdin, then runs the closed loop with
    bounded outstanding and reports ``DONE <wall_s>``; outputs +
    per-request latencies land in the npz for the parent to aggregate.
    Clients are separate processes for the same reason the workers are:
    that is the deployed topology — and it keeps the client codec off
    the measured process's GIL, so stage 8 measures the ingress+router
    seam, not the bench driver fighting it for the interpreter."""
    import threading

    from mxnet_tpu import serving
    from mxnet_tpu.serving import wire
    from mxnet_tpu.serving.router import ServerOverloaded

    host, port = wire.parse_hostport(argv[0])
    samples = list(np.load(argv[1]))
    out_path = argv[2]
    outstanding = int(argv[3])
    cli = serving.IngressClient(host, port)
    try:
        cli.submit(samples[0]).result(timeout=300)   # warm end-to-end
        sys.stdout.write("READY\n")
        sys.stdout.flush()
        if sys.stdin.readline().strip() != "GO":
            return 2
        m = len(samples)
        outs = [None] * m
        lats = np.zeros(m)
        errs = []
        retries = [0]
        sem = threading.Semaphore(outstanding)
        done = threading.Event()
        remaining = [m]
        lock = threading.Lock()
        t_all = time.perf_counter()

        def finish(i):
            sem.release()
            with lock:
                remaining[0] -= 1
                if remaining[0] == 0:
                    done.set()

        def cb(fut, i, t0, tries):
            # typed backpressure (window_full / shed / queue expiry) is
            # the ingress CONTRACT, not a failure: a real client backs
            # off and resubmits. The retry stays inside the request's
            # latency (measured from the FIRST submit) and its repeat
            # trips consume real capacity, so throughput/p99 remain
            # honest; anything else typed, or a spent budget, fails
            # the stage.
            try:
                outs[i] = fut.result()
                lats[i] = time.perf_counter() - t0
            except ServerOverloaded as e:
                if tries < 8:
                    retries[0] += 1
                    cli.submit(samples[i]).add_done_callback(
                        lambda f, i=i, t0=t0, n=tries + 1:
                        cb(f, i, t0, n))
                    return
                errs.append(f"retry budget spent: {e!r}")
            except Exception as e:  # noqa: BLE001
                errs.append(repr(e))
            finish(i)

        for i in range(m):
            sem.acquire()
            t0 = time.perf_counter()
            cli.submit(samples[i]).add_done_callback(
                lambda f, i=i, t0=t0: cb(f, i, t0, 0))
        if not done.wait(300):
            errs.append("timed out waiting for results")
        wall = time.perf_counter() - t_all
    finally:
        cli.close()
    if errs or any(o is None for o in outs):
        sys.stdout.write(f"ERR {errs[:3]!r}\n")
        sys.stdout.flush()
        return 1
    np.savez(out_path, outs=np.stack(outs), lats=lats,
             retries=retries[0])
    sys.stdout.write(f"DONE {wall:.6f}\n")
    sys.stdout.flush()
    return 0


def _baseline_window(router, samples, feeders, outstanding):
    """One closed-loop traffic window over the stage-8 matched
    IN-PROCESS baseline router: the same model, SLO, traffic, replica
    count, and total offered concurrency the out-of-process path runs.
    The caller owns the router's lifecycle (windows INTERLEAVE with
    the out-of-process windows so both sides sample the same container
    weather — see ingress_stage). Typed sheds are retried the way the
    ingress clients retry them (closed loop: the retry's latency stays
    inside the request's). Returns (rps, p50_ms)."""
    import threading

    from mxnet_tpu.serving.router import ServerOverloaded

    n = len(samples)
    lats = [None] * n
    errs = []
    done = threading.Event()
    remaining = [n]
    lock = threading.Lock()

    def finish():
        with lock:
            remaining[0] -= 1
            if remaining[0] == 0:
                done.set()

    def launch(i, t0, tries, sem):
        def cb(fut, i=i, t0=t0, tries=tries, sem=sem):
            try:
                fut.result()
                lats[i] = time.perf_counter() - t0
            except ServerOverloaded as e:
                if tries < 8:
                    launch(i, t0, tries + 1, sem)
                    return
                errs.append(f"retry budget spent: {e!r}")
            except Exception as e:  # noqa: BLE001
                errs.append(repr(e))
            sem.release()
            finish()
        try:
            router.submit(samples[i]).add_done_callback(cb)
        except ServerOverloaded as e:
            if tries < 8:
                # never sleep here: launch() also runs inside
                # done-callbacks, i.e. on the router/replica
                # completion threads whose throughput is this
                # baseline's denominator — a timer thread owns
                # the backoff instead
                t = threading.Timer(0.002, launch,
                                    args=(i, t0, tries + 1, sem))
                t.daemon = True
                t.start()
                return
            errs.append(f"retry budget spent: {e!r}")
            sem.release()
            finish()

    def feed(lo, hi):
        sem = threading.Semaphore(outstanding)
        for i in range(lo, hi):
            sem.acquire()
            launch(i, time.perf_counter(), 0, sem)

    per = (n + feeders - 1) // feeders
    threads = [threading.Thread(target=feed,
                                args=(k * per, min(n, (k + 1) * per)))
               for k in range(feeders)]
    t_all = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if not done.wait(300):
        errs.append("baseline timed out")
    wall = time.perf_counter() - t_all
    if errs:
        raise RuntimeError(f"ingress baseline failed: {errs[:3]!r}")
    return n / wall, _pctl(lats, 0.50) * 1e3


def ingress_stage(samples, n_workers=2, clients=1, window=64,
                  outstanding=32, feeders=4):
    """Stage 8: the same traffic through the FULL out-of-process path —
    ``IngressClient`` process(es) -> socket ``Ingress`` -> ``Router``
    -> ``RemoteReplica`` worker PROCESSES — against an in-process
    router baseline measured IN THIS STAGE at matched model, SLO,
    replica count, and offered concurrency (``feeders x
    outstanding/feeders`` in-process == ``clients x outstanding``
    through the socket). The model is :func:`build_ingress_net`, not
    the stage-1 toy: the 70% bar asks what the out-of-process
    architecture COSTS, which is only observable when compute is the
    majority of a request (see the INGRESS_* comment). One client
    process with the full window (not N shallow ones): every extra
    process oversubscribes the 2-core container the workers need.
    Returns (metrics, ok): throughput >= ``INGRESS_BAR`` x the matched
    baseline, outputs bit-identical to the bucket-oracle, and the
    added p50 latency decomposed into framing (wire codec CPU), socket
    (ping RTT x two seams), and scheduling (the remainder: batching
    windows, thread wakeups)."""
    import subprocess
    import tempfile

    from mxnet_tpu import serving

    slo_ms = INGRESS_SLO_MS
    # the serving parent (ingress + router) is a thread cooperative:
    # conn readers, the dispatcher, remote reader/writer threads all
    # need the GIL briefly and often. The default 5 ms switch interval
    # lets any one of them sit on it for 5 ms while the dispatcher's
    # queue head burns deadline — a deployed router process tunes this
    # down, and so does the stage (restored on exit; the interpreter
    # default optimizes single-thread throughput, not tail latency)
    prev_swi = sys.getswitchinterval()
    sys.setswitchinterval(1e-3)
    tools_dir = os.path.dirname(os.path.abspath(__file__))
    buckets = [MIN_BUCKET]
    while buckets[-1] < INGRESS_MAX_BATCH:
        buckets.append(buckets[-1] * 2)

    # bucket-oracle: every GEMM bucket in `buckets` produces rows
    # bit-identical to the batch-2-padded eager form (the grid stops
    # at 4 BECAUSE that is where this was measured to hold for this
    # width) — so full real-sample batches at the top bucket are the
    # oracle, 4x cheaper than per-request eager
    oracle_net = build_ingress_net()
    n = len(samples)
    eager_outs = []
    top = INGRESS_MAX_BATCH
    for lo in range(0, n, top):
        chunk = samples[lo:lo + top]
        pad = np.zeros((top, IN_UNITS), np.float32)
        pad[:len(chunk)] = np.stack(chunk)
        eager_outs.extend(_net_rows(oracle_net, pad)[:len(chunk)])

    # the model's GEMMs are memory-bound on one Eigen thread — intra-op
    # XLA threads buy them little, but N workers x a per-process eigen
    # pool oversubscribes the container and starves the parent's frame
    # plumbing (measured: conn threads descheduled past the SLO
    # mid-submit)
    wrk_xla = (os.environ.get("XLA_FLAGS", "")
               + " --xla_cpu_multi_thread_eigen=false").strip()
    workers = [serving.RemoteReplica(
        "serving_bench:build_ingress_net", name=f"wrk{i}",
        batch_buckets=tuple(buckets), shape_buckets=[(IN_UNITS,)],
        slo_ms=slo_ms, python_paths=[tools_dir], spawn_timeout_s=600,
        # deadline-keyed close, matching the in-process baseline: the
        # 5 ms batch-timeout default exists for LIGHT models behind a
        # latency-bound pipeline; this model's GEMM is memory-bound
        # (batch-2 costs what batch-4 costs), so closing early halves
        # goodput at full per-batch price — both sides must run the
        # same close policy or the ratio measures the knob, not the
        # process boundary
        batch_timeout_ms=None,
        env={"XLA_FLAGS": wrk_xla})
        for i in range(n_workers)]
    router = serving.Router(workers, slo_ms=slo_ms)
    t0 = time.perf_counter()
    router.start()              # spawn + AOT-warm both worker processes
    t_spawn = time.perf_counter() - t0
    ing = serving.Ingress(router, window=window).start()
    procs = []
    base_router = None
    try:
        # matched in-process baseline fleet — alive ALONGSIDE the
        # worker fleet so its traffic windows can INTERLEAVE with the
        # ingress windows below: container weather on this box swings
        # 2-3x on a ~minute timescale, so back-to-back base/out pairs
        # sample the same weather where sequential phases would each
        # be hostage to their own. Idle, the off-turn fleet costs only
        # health beacons.
        base_reps = [serving.Server(build_ingress_net(),
                                    batch_buckets=tuple(buckets),
                                    shape_buckets=[(IN_UNITS,)],
                                    slo_ms=slo_ms, name=f"ibase{i}")
                     for i in range(n_workers)]
        base_router = serving.Router(base_reps, slo_ms=slo_ms).start()

        def client_window():
            """One synchronized client-process traffic window:
            (rps, lats, outs, n_retries). Bit-identity is asserted on
            EVERY window's outputs by the caller; only the throughput
            number takes best-of-2 (correctness is not best-of-N)."""
            nonlocal procs
            procs = []
            with tempfile.TemporaryDirectory() as td:
                per = (n + clients - 1) // clients
                slices = []
                for k in range(clients):
                    lo, hi = k * per, min(n, (k + 1) * per)
                    inp = os.path.join(td, f"c{k}_in.npy")
                    np.save(inp, np.stack(samples[lo:hi]))
                    out = os.path.join(td, f"c{k}_out.npz")
                    slices.append((lo, hi, out))
                    procs.append(subprocess.Popen(
                        [sys.executable, os.path.abspath(__file__),
                         "--ingress-drive", f"127.0.0.1:{ing.port}",
                         inp, out, str(outstanding)],
                        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                        text=True))
                for p in procs:     # all connected + path warm
                    line = p.stdout.readline().strip()
                    if line != "READY":
                        raise RuntimeError(
                            f"ingress bench client failed before GO: "
                            f"{line!r} (rc={p.poll()})")
                for p in procs:     # one synchronized traffic window
                    p.stdin.write("GO\n")
                    p.stdin.flush()
                walls = []
                for p in procs:
                    line = p.stdout.readline().strip()
                    if not line.startswith("DONE "):
                        raise RuntimeError(
                            f"ingress bench client failed: {line!r}")
                    walls.append(float(line.split()[1]))
                for p in procs:
                    p.wait(60)
                outs = [None] * n
                lats = []
                n_retries = 0
                for lo, hi, out in slices:
                    with np.load(out) as z:
                        outs[lo:hi] = list(z["outs"])
                        lats.extend(z["lats"].tolist())
                        n_retries += int(z["retries"])
            # every client ran its slice concurrently from one GO: the
            # window is the slowest client's wall
            return n / max(walls), lats, outs, n_retries

        # INTERLEAVED, PAIRED rounds: (base, out) x 3, gate on the
        # best per-round ratio. Container weather on this box swings
        # 2-3x on a ~minute timescale — unpaired best-of-N still
        # compares windows a minute apart, but a base window and the
        # out window RIGHT AFTER it share their weather, so their
        # ratio cancels it; the best pair asks "does the architecture
        # sustain the bar in matched conditions", which is the
        # question. (Correctness is never best-of-N: identity is
        # asserted on EVERY out window's outputs below.)
        base_runs, runs = [], []
        for _round in range(3):
            base_runs.append(_baseline_window(
                base_router, samples, feeders,
                max(outstanding // feeders, 1)))
            runs.append(client_window())
        pair_ratios = [r[0] / b[0] for b, r in zip(base_runs, runs)]
        best = max(range(3), key=lambda i: pair_ratios[i])
        inproc_rps, inproc_p50_ms = base_runs[best]
        all_outs = [r[2] for r in runs]
        rps, lats, outs, n_retries = runs[best]
        p50 = _pctl(lats, 0.50) * 1e3
        p99 = _pctl(lats, 0.99) * 1e3

        # overhead decomposition of the added p50 latency
        framing_ms = _framing_overhead_ms(samples[0])
        socket_ms = 2.0 * _socket_rtt_ms(ing.port)
        total_ms = max(p50 - inproc_p50_ms, 0.0)
        sched_ms = max(total_ms - framing_ms - socket_ms, 0.0)

        identical = all(np.array_equal(a, b)
                        for run_outs in all_outs
                        for a, b in zip(eager_outs, run_outs))
        vs_inproc = rps / inproc_rps if inproc_rps else 0.0
        ok = vs_inproc >= INGRESS_BAR and identical
        return {
            "serving_ingress_rps": round(rps, 1),
            "serving_ingress_p50_ms": round(p50, 3),
            "serving_ingress_p99_ms": round(p99, 3),
            "serving_ingress_inproc_rps": round(inproc_rps, 1),
            "serving_ingress_inproc_p50_ms": round(inproc_p50_ms, 3),
            "serving_ingress_vs_inproc": round(vs_inproc, 3),
            "serving_ingress_round_ratios": [round(x, 3)
                                             for x in pair_ratios],
            "serving_ingress_bar": INGRESS_BAR,
            "serving_ingress_model":
                f"mlp{IN_UNITS}-{INGRESS_HIDDEN}x3",
            "serving_ingress_slo_ms": slo_ms,
            "serving_ingress_max_batch": INGRESS_MAX_BATCH,
            "serving_ingress_bit_identical": bool(identical),
            "serving_ingress_worker_spawn_s": round(t_spawn, 2),
            "serving_ingress_rejected": ing.stats()["rejected"],
            "serving_ingress_client_retries": n_retries,
            "serving_ingress_overhead_p50_ms": round(total_ms, 3),
            "serving_ingress_overhead_framing_ms": round(framing_ms, 3),
            "serving_ingress_overhead_socket_ms": round(socket_ms, 3),
            "serving_ingress_overhead_scheduling_ms": round(sched_ms, 3),
            "serving_ingress_gate": bool(ok),
        }, ok
    finally:
        sys.setswitchinterval(prev_swi)
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        ing.stop()
        router.stop(drain=False, timeout=60)
        if base_router is not None:
            base_router.stop(drain=False, timeout=60)


def quantized_net(samples, calib_batches=4, batch=32):
    """build_net() again (same weights), int8-quantized with naive
    calibration over the bench traffic."""
    import mxnet_tpu as mx
    from mxnet_tpu.contrib.quantization import quantize_net

    net = build_net()
    calib = [mx.nd.array(np.stack(samples[i * batch:(i + 1) * batch]))
             for i in range(calib_batches)]
    quantize_net(net, calib_data=calib, calib_mode="naive")
    net.hybridize()
    return net


def reload_stage(workdir, n_requests=200, slo_ms=50):
    """Kill-the-model-file hot reload under load: returns
    (all_served, n_old_weight_outputs, n_new_weight_outputs)."""
    import mxnet_tpu as mx
    from mxnet_tpu import serving

    mgr = mx.checkpoint.CheckpointManager(workdir, keep_last=1)
    mgr.save(0, params=build_net(seed=0))

    def factory(path):
        net = build_net(seed=0)
        net.load_parameters(os.path.join(path, "params.params"))
        net.hybridize()
        return net

    old = factory(mgr.path(0))
    new_ref = build_net(seed=0, scale=2.0)
    x = make_traffic(1, seed=9)[0]
    ref_old = eager_single(old, x)
    ref_new = eager_single(new_ref, x)

    srv = serving.Server(old, batch_buckets=(MIN_BUCKET, 4, 8),
                         shape_buckets=[(IN_UNITS,)], slo_ms=slo_ms)
    srv.start()
    srv.enable_hot_reload(mgr, factory, interval_s=0.02)
    futs = []
    swapped = False
    for i in range(n_requests):
        futs.append(srv.submit(x))
        if i == n_requests // 3 and not swapped:
            # the kill: commit new weights, then delete the bundle the
            # live model was loaded from (retention keep_last=1 does the
            # delete; belt-and-braces remove any survivor explicitly)
            mgr.save(1, params=new_ref)
            old_path = mgr.path(0)
            if os.path.isdir(old_path):
                shutil.rmtree(old_path, ignore_errors=True)
            swapped = True
        time.sleep(0.002)
    deadline = time.time() + 30
    while srv.loaded_step != 1 and time.time() < deadline:
        time.sleep(0.01)
        futs.append(srv.submit(x))
    n_old = n_new = n_fail = 0
    for f in futs:
        try:
            out = f.result(timeout=30)
        except Exception:  # noqa: BLE001
            n_fail += 1
            continue
        if np.array_equal(out, ref_old):
            n_old += 1
        elif np.array_equal(out, ref_new):
            n_new += 1
        else:
            n_fail += 1
    srv.stop()
    ok = n_fail == 0 and n_new > 0 and srv.loaded_step == 1
    return ok, n_old, n_new


def build_decode_llama(seed: int = 7):
    """A 2-layer LLaMA for the decode gate: big enough that a forward
    pass costs real compute (so full-recompute's O(L^2) shows), small
    enough to decode hundreds of tokens on CPU in seconds."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.nlp import LlamaModel

    mx.random.seed(seed)
    net = LlamaModel(vocab_size=256, num_layers=2, units=128,
                     hidden_size=256, num_heads=4, num_kv_heads=2,
                     rope_theta=10000.0, eps=1e-6)
    net.initialize()
    net(mx.nd.zeros((1, 2), dtype="int32"))    # materialize shapes
    net.hybridize()
    return net


_DECODE_LEN_BUCKETS = (16, 32)
_DECODE_FULL_BUCKETS = (16, 32, 64, 128, 288)  # full-recompute pads here


def _full_recompute_decode(net, prompts, n_new):
    """The BucketingModule-style baseline: every step re-runs the WHOLE
    sequence padded to a length bucket (compiles amortize across steps;
    causal attention makes suffix padding bit-transparent, so the
    argmax chain matches the unpadded loop). All streams advance in ONE
    batched forward per step — the baseline gets the same batch width
    as the cached side, its best case. It still pays O(length) compute
    per emitted token, which is the whole point: batching cannot
    amortize recompute, only a KV cache can. Returns
    ``(tokens (B, n_new), ttft_s, elapsed_s)``."""
    import mxnet_tpu as mx

    toks = [list(int(t) for t in p) for p in prompts]
    t0 = time.perf_counter()
    ttft = None
    for _ in range(n_new):
        length = len(toks[0])          # equal-length streams
        bucket = next(b for b in _DECODE_FULL_BUCKETS if b >= length)
        arr = np.zeros((len(toks), bucket), np.int32)
        for i, row in enumerate(toks):
            arr[i, :length] = row
        logits = net(mx.nd.array(arr, dtype="int32")).asnumpy()
        for i, row in enumerate(toks):
            row.append(int(np.argmax(logits[i, length - 1])))
        if ttft is None:
            ttft = time.perf_counter() - t0
    n0 = len(prompts[0])
    return (np.asarray([row[n0:] for row in toks], np.int32), ttft,
            time.perf_counter() - t0)


def decode_stage(lengths=(32, 128, 256), streams=4):
    """Stage 9: cached decode vs full recompute, both driving the same
    ``streams`` concurrent equal-length completions. The single batch
    bucket (``streams``) keeps every decode step on ONE ``(streams, 1)``
    executable — short batches pad with bit-transparent scratch rows —
    so the cached side reads the weights once per step for ``streams``
    tokens while the baseline re-computes every stream's whole prefix.
    Returns ``(record_fragment, ok)``."""
    from mxnet_tpu import serving, telemetry

    net = build_decode_llama()
    prompts = [np.array(p, np.int32) for p in (
        [3, 1, 4, 1, 5, 9, 2, 6],
        [2, 7, 1, 8, 2, 8, 1, 8],
        [1, 1, 2, 3, 5, 8, 13, 21],
        [6, 2, 8, 3, 1, 8, 5, 3],
    )][:streams]
    pages_per = -(-(len(prompts[0]) + max(lengths)) // 16)  # ceil

    srv = serving.Server(
        net, batch_buckets=(streams,), shape_buckets=[(8,)],
        slo_ms=1000.0, dtype="int32", warmup=False,
        decode_pages=streams * pages_per + 1, page_size=16,
        len_buckets=_DECODE_LEN_BUCKETS,
        max_generate_tokens=prompts[0].size + max(lengths),
        name="decode_bench")
    srv.start()
    try:
        # warm both paths: every executable either path will touch
        # (full recompute walks several length buckets — compiling
        # inside its timed run would hand the cached path a free win)
        import mxnet_tpu as mx

        srv.submit_generate(prompts[0], 4).result(timeout=600)
        for b in _DECODE_FULL_BUCKETS:
            net(mx.nd.zeros((len(prompts), b), dtype="int32"))

        telemetry_was = telemetry.enabled()
        if not telemetry_was:
            telemetry.enable()

        def misses():
            snap = telemetry.snapshot()["metrics"].get(
                "mxnet_jit_cache_total", {"samples": []})
            return sum(s["value"] for s in snap["samples"]
                       if s["labels"].get("cache") == "serving_decode"
                       and s["labels"].get("result") == "miss")

        frag = {}
        ok = True
        for n_new in lengths:
            full_toks, full_ttft, full_s = _full_recompute_decode(
                net, prompts, n_new)
            n_total = len(prompts) * n_new
            m0 = misses()
            first = []
            t0 = time.perf_counter()
            handles = [
                srv.submit_generate(
                    p, n_new,
                    on_token=lambda i, t: first.append(
                        time.perf_counter()) if not first else None)
                for p in prompts]
            cached_toks = [h.result(timeout=600) for h in handles]
            cached_s = time.perf_counter() - t0
            retraced = misses() - m0
            identical = all(
                np.array_equal(c, f) for c, f in zip(cached_toks,
                                                     full_toks))
            speedup = (n_total / cached_s) / (n_total / full_s)
            frag.update({
                f"serving_decode_{n_new}_cached_tok_s":
                    round(n_total / cached_s, 1),
                f"serving_decode_{n_new}_full_tok_s":
                    round(n_total / full_s, 1),
                f"serving_decode_{n_new}_speedup": round(speedup, 2),
                f"serving_decode_{n_new}_cached_ttft_ms":
                    round((first[0] - t0) * 1e3, 3),
                f"serving_decode_{n_new}_full_ttft_ms":
                    round(full_ttft * 1e3, 3),
                f"serving_decode_{n_new}_bit_identical": bool(identical),
            })
            frag[f"serving_decode_{n_new}_retraces"] = int(retraced)
            ok = ok and identical and retraced == 0
            if n_new == max(lengths):
                frag["serving_decode_speedup_at_max_len"] = round(
                    speedup, 2)
                ok = ok and speedup >= DECODE_BAR
        if not telemetry_was:
            telemetry.disable()
            telemetry.reset()
        frag["serving_decode_gate"] = bool(ok)
        return frag, ok
    finally:
        srv.stop()


MT_RATE = 60.0          # tenant "batch" admission rate PER REPLICA
MT_OVERLOAD_FACTOR = 2.0
MT_SHARE_TOL = 0.10     # token share within 10% of the weight share


def _mt_overload_phase(t_window=3.0):
    """Phase A of the multi-tenant gate: tenant ``batch`` offered 2x
    its fleet-aggregate admission rate open-loop, tenant ``premium``
    closed-loop within budget, both on ONE 2-replica paced router.
    Returns the metric fragment plus ``ok``."""
    from mxnet_tpu import serving

    slo_ms = OVERLOAD_SLO_MS
    margin = OVERLOAD_MARGIN_MS
    x = make_traffic(1, seed=5)[0]
    reps = [serving.Server(_paced_block(),
                           batch_buckets=(2, 4, OVERLOAD_MAX_BATCH),
                           shape_buckets=[(IN_UNITS,)],
                           slo_ms=slo_ms, close_margin_ms=margin,
                           name=f"mt_ov{i}")
            for i in range(2)]
    router = serving.Router(reps, slo_ms=slo_ms).start()
    try:
        # the rate limit is per replica; least-loaded dispatch spreads
        # a tenant across the fleet, so the aggregate admission rate
        # is n_replicas x rate — the overload factor applies to THAT
        router.register_model("batch", _paced_block,
                              slo_class="batch", priority=0,
                              weight=1.0, rate_limit=MT_RATE, burst=8)
        router.register_model("premium", _paced_block,
                              slo_class="premium", priority=5,
                              weight=3.0)
        # warm both tenants' executables outside the window
        for m in ("batch", "premium"):
            router.submit(x, deadline_ms=2000,
                          model=m).result(timeout=60)

        lock = threading.Lock()
        prem_lats, prem_rejects = [], [0]
        stop = threading.Event()

        def premium_loop():
            while not stop.is_set():
                ts = time.perf_counter()
                try:
                    fut = router.submit(x, deadline_ms=slo_ms - margin,
                                        model="premium")
                    fut.result(timeout=10)
                except Exception:  # noqa: BLE001 - isolation breach,
                    prem_rejects[0] += 1    # counted against the gate
                    continue
                with lock:
                    prem_lats.append(time.perf_counter() - ts)
        prem_threads = [threading.Thread(target=premium_loop)
                        for _ in range(4)]
        for t in prem_threads:
            t.start()

        offered = MT_OVERLOAD_FACTOR * MT_RATE * len(reps)
        futs, shed_lats = [], []
        n_ok = [0]
        n_shed = [0]
        n_other = [0]
        tick, backlog = 0.005, 0.0
        t0 = time.perf_counter()
        next_tick = t0
        while time.perf_counter() - t0 < t_window:
            backlog += offered * tick
            burst, backlog = int(backlog), backlog % 1.0
            for _ in range(burst):
                ts = time.perf_counter()
                try:
                    fut = router.submit(x, deadline_ms=slo_ms - margin,
                                        model="batch")
                except serving.TenantThrottled:
                    # server-side throttle surfaced synchronously at
                    # submit (single-replica direct path)
                    n_shed[0] += 1
                    shed_lats.append(time.perf_counter() - ts)
                    continue
                except Exception:  # noqa: BLE001 - untyped = breach
                    n_other[0] += 1
                    continue

                def cb(f, ts=ts):
                    dt = time.perf_counter() - ts
                    exc = f.exception()
                    with lock:
                        if exc is None:
                            n_ok[0] += 1
                        elif isinstance(exc, serving.TenantThrottled):
                            # routed shed: typed, resolved terminally
                            # (no sibling retry multiplying the rate)
                            n_shed[0] += 1
                            shed_lats.append(dt)
                        else:
                            n_other[0] += 1
                futs.append(fut)
                fut.add_done_callback(cb)
            next_tick += tick
            dt = next_tick - time.perf_counter()
            if dt > 0:
                time.sleep(dt)
        deadline = time.time() + 60
        for f in futs:
            try:
                f.result(timeout=max(deadline - time.time(), 1))
            except Exception:  # noqa: BLE001 - counted in cb
                pass
        stop.set()
        for t in prem_threads:
            t.join()
        prem_shed = sum(r.stats()["models"]["premium"]["shed"]
                        for r in reps)
        batch_shed = sum(r.stats()["models"]["batch"]["shed"]
                         for r in reps)
    finally:
        router.stop(timeout=60)
    admitted_rps = n_ok[0] / t_window
    p99_prem = _pctl(prem_lats, 0.99) * 1e3 if prem_lats \
        else float("inf")
    p99_shed = _pctl(shed_lats, 0.99) * 1e3 if shed_lats else 0.0
    # 2x SLO, not slo+margin: the gate is ISOLATION (an unconfined
    # 2x-overload backlog pushes premium p99 into seconds or deadline
    # rejects, both asserted separately), not the single-tenant SLO
    # already gated by the overload stage — and a warm 10-stage run
    # adds tens of ms of scheduler jitter a tight bound would flake on.
    p99_bound = 2.0 * slo_ms
    sheds_typed_sync = (n_shed[0] > 0 and n_other[0] == 0
                        and p99_shed < 50.0)
    confined = prem_rejects[0] == 0 and prem_shed == 0 \
        and batch_shed > 0
    ok = (sheds_typed_sync and confined and p99_prem <= p99_bound
          and len(prem_lats) > 0)
    return {
        "serving_multitenant_batch_offered_rps": round(offered, 1),
        "serving_multitenant_batch_admitted_rps":
            round(admitted_rps, 1),
        "serving_multitenant_batch_shed": n_shed[0],
        "serving_multitenant_batch_shed_p99_ms": round(p99_shed, 3),
        "serving_multitenant_untyped_errors": n_other[0],
        "serving_multitenant_premium_requests": len(prem_lats),
        "serving_multitenant_premium_rejects": prem_rejects[0],
        "serving_multitenant_premium_p99_ms": round(p99_prem, 2),
        "serving_multitenant_premium_p99_bound_ms": p99_bound,
        "serving_multitenant_shed_confined_to_batch": bool(confined),
        "serving_multitenant_sheds_synchronous_typed":
            bool(sheds_typed_sync),
    }, ok


def _mt_fairness_phase(streams=8, n_new=160):
    """Phase B of the multi-tenant gate: two decode tenants (weights
    3:1) keep ``streams`` completions each active on one server whose
    decode round has 4 slots. Measures each tenant's token share over
    a steady-state window plus the ``serving_decode`` compile-cache
    miss delta across it. Returns the metric fragment plus ``ok``."""
    from mxnet_tpu import serving, telemetry

    net_a = build_decode_llama(seed=7)
    net_b = build_decode_llama(seed=11)
    prompt = np.array([3, 1, 4, 1, 5, 9, 2, 6], np.int32)
    page_size = 16
    pages_per = -(-(prompt.size + n_new) // page_size)   # ceil
    srv = serving.Server(
        net_a, batch_buckets=(4,), shape_buckets=[(8,)],
        slo_ms=600000.0, dtype="int32", warmup=False,
        decode_pages=2 * streams * pages_per + 1, page_size=page_size,
        len_buckets=_DECODE_LEN_BUCKETS,
        max_generate_tokens=prompt.size + n_new,
        name="mt_dec", weight=1.0)
    telemetry_was = telemetry.enabled()
    if not telemetry_was:
        telemetry.enable()
    srv.start()
    try:
        srv.register_model("fast", net_b, slo_class="premium",
                           priority=0, weight=3.0)
        # warm both tenants' prefill + decode executables
        srv.submit_generate(prompt, 4).result(timeout=600)
        srv.submit_generate(prompt, 4, model="fast").result(timeout=600)

        def misses():
            snap = telemetry.snapshot()["metrics"].get(
                "mxnet_jit_cache_total", {"samples": []})
            return sum(s["value"] for s in snap["samples"]
                       if s["labels"].get("cache") == "serving_decode"
                       and s["labels"].get("result") == "miss")

        def tokens():
            ms = srv.stats()["models"]
            return (ms["default"]["tokens"], ms["fast"]["tokens"])

        handles = []
        for _ in range(streams):
            handles.append(srv.submit_generate(prompt, n_new))
            handles.append(srv.submit_generate(prompt, n_new,
                                               model="fast"))
        # snap1 once every stream is admitted and past prefill (the
        # window must contain only steady-state decode rounds); snap2
        # well before the first stream can complete, so BOTH tenants
        # stay saturated across the whole window
        base = tokens()
        deadline = time.time() + 300
        while time.time() < deadline:
            st = srv.stats()
            cur = tokens()
            if (st["generates_active"] == 2 * streams
                    and cur[0] + cur[1] - base[0] - base[1] >= 96):
                break
            time.sleep(0.01)
        else:
            raise RuntimeError("multitenant decode streams never "
                               "reached steady state")
        a1, b1 = tokens()
        m1 = misses()
        while time.time() < deadline:
            a2, b2 = tokens()
            if (a2 - a1) + (b2 - b1) >= 400:
                break
            time.sleep(0.01)
        a2, b2 = tokens()
        m2 = misses()
        share_fast = (b2 - b1) / max((a2 - a1) + (b2 - b1), 1)
        expected = 3.0 / 4.0
        share_err = abs(share_fast - expected) / expected
        retraces = int(m2 - m1)
        ok = share_err <= MT_SHARE_TOL and retraces == 0
        return {
            "serving_multitenant_fast_token_share":
                round(share_fast, 4),
            "serving_multitenant_fast_weight_share": expected,
            "serving_multitenant_share_err": round(share_err, 4),
            "serving_multitenant_window_tokens":
                int((a2 - a1) + (b2 - b1)),
            "serving_multitenant_steady_retraces": retraces,
        }, ok
    finally:
        srv.stop(drain=False)
        if not telemetry_was:
            telemetry.disable()
            telemetry.reset()


def multitenant_stage():
    """Stage 10: multi-tenant isolation — noisy-neighbor overload
    confinement (phase A) + weighted-fair decode token share with zero
    steady-state retraces (phase B). Returns ``(fragment, ok)``."""
    frag_a, ok_a = _mt_overload_phase()
    frag_b, ok_b = _mt_fairness_phase()
    frag = {}
    frag.update(frag_a)
    frag.update(frag_b)
    ok = ok_a and ok_b
    frag["serving_multitenant_gate"] = bool(ok)
    return frag, ok


def main():
    import tempfile

    if len(sys.argv) > 1 and sys.argv[1] == "--ingress-drive":
        return _ingress_drive(sys.argv[2:])

    from mxnet_tpu.telemetry import pop_telemetry_out_flag

    sys.argv[1:], telemetry_out = pop_telemetry_out_flag(sys.argv[1:])
    if telemetry_out:
        from mxnet_tpu import telemetry

        telemetry.enable()

    n = int(os.environ.get("SERVING_BENCH_REQUESTS", "512"))
    max_batch = int(os.environ.get("SERVING_BENCH_BATCH", "32"))
    slo_ms = float(os.environ.get("SERVING_BENCH_SLO_MS", "50"))
    feeders = int(os.environ.get("SERVING_BENCH_FEEDERS", "4"))

    net = build_net()
    samples = make_traffic(n)

    eager_rps, eager_p50, eager_p99, eager_outs = eager_stage(net, samples)
    bat_rps, bat_p50, bat_p99, bat_outs, occ = batched_stage(
        net, samples, max_batch, slo_ms, feeders)
    speedup = bat_rps / eager_rps
    record = {
        "metric": "serving_batched_speedup_vs_eager",
        "value": round(speedup, 2),
        "unit": "x",
        "vs_baseline": round(speedup / SPEEDUP_BAR, 4),
        "serving_requests": n,
        "serving_max_batch": max_batch,
        "serving_slo_ms": slo_ms,
        "serving_eager_rps": round(eager_rps, 1),
        "serving_eager_p50_ms": round(eager_p50, 3),
        "serving_eager_p99_ms": round(eager_p99, 3),
        "serving_batched_rps": round(bat_rps, 1),
        "serving_batched_p50_ms": round(bat_p50, 3),
        "serving_batched_p99_ms": round(bat_p99, 3),
        "serving_batch_occupancy": round(occ, 3),
    }
    _emit(record)

    qnet = quantized_net(samples)
    q_rps, q_p50, q_p99, _q_outs, _ = batched_stage(
        qnet, samples, max_batch, slo_ms, feeders)
    record.update({
        "serving_int8_rps": round(q_rps, 1),
        "serving_int8_p50_ms": round(q_p50, 3),
        "serving_int8_p99_ms": round(q_p99, 3),
        "serving_int8_speedup_vs_eager": round(q_rps / eager_rps, 2),
    })
    _emit(record)

    identical = all(np.array_equal(a, b)
                    for a, b in zip(eager_outs, bat_outs))
    workdir = tempfile.mkdtemp(prefix="serving_bench_ckpt_")
    try:
        reload_ok, n_old, n_new = reload_stage(workdir, slo_ms=slo_ms)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    record.update({
        "serving_batched_bit_identical": bool(identical),
        "serving_reload_inflight_ok": bool(reload_ok),
        "serving_reload_old_weight_responses": n_old,
        "serving_reload_new_weight_responses": n_new,
    })
    _emit(record)

    # stage 5: multi-replica router throughput + bit-identity
    r_rps, r_p50, r_p99, r_outs, served = router_stage(
        samples, max_batch, slo_ms, feeders=feeders)
    router_identical = all(np.array_equal(a, b)
                           for a, b in zip(eager_outs, r_outs))
    record.update({
        "serving_router_rps": round(r_rps, 1),
        "serving_router_p50_ms": round(r_p50, 3),
        "serving_router_p99_ms": round(r_p99, 3),
        "serving_router_replica_served": served,
        "serving_router_bit_identical": bool(router_identical),
    })
    _emit(record)

    # stage 6: overload — capacity, 2x offered load, shed + goodput gate
    overload, overload_ok = overload_stage()
    record.update(overload)
    _emit(record)

    # stage 7: scale-up decision-to-first-response, warm vs cold
    scaleup, scaleup_ok = scaleup_stage(slo_ms)
    record.update(scaleup)
    _emit(record)

    # stage 8: the full out-of-process path (ingress + worker
    # processes) vs a matched in-process baseline measured in-stage
    ingress, ingress_ok = ingress_stage(samples)
    record.update(ingress)
    _emit(record)

    # stage 9: continuous-batching decode vs full recompute
    decode, decode_ok = decode_stage()
    record.update(decode)
    _emit(record)

    # stage 10: two tenants on one fleet — overload confinement,
    # weighted-fair token share, zero steady-state retraces
    multitenant, mt_ok = multitenant_stage()
    record.update(multitenant)
    _emit(record)

    if telemetry_out:
        from mxnet_tpu import telemetry

        telemetry.write_snapshot(telemetry_out)
    return 0 if (identical and reload_ok and speedup >= SPEEDUP_BAR
                 and router_identical and overload_ok
                 and scaleup_ok and ingress_ok and decode_ok
                 and mt_ok) else 1


if __name__ == "__main__":
    sys.exit(main())
