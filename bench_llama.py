"""Benchmark: Llama pretrain proxy (~0.7B, Llama-3-8B recipe) on one chip.

Prints ONE JSON line {"metric", "value", "unit", "mfu"}. The model is
CONFIGS['proxy1b'] from tools/pretrain_llama.py — same blocks, same fused
TrainStep + AdamW path, same remat policy as the 8B stretch config
(BASELINE.json config[4]); only depth/width are scaled so weights + Adam
state fit one v5e chip. MFU = 6 * N * tokens_per_sec / peak_flops.

The full-size recipe artifact is produced by
``tools/pretrain_llama.py --config 8b --compile-only`` (AOT compile of the
sharded step on a virtual mesh; results recorded in PERF.md).
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main():
    import jax

    from tools.pretrain_llama import main as pretrain_main

    platform = jax.devices()[0].platform
    if platform == "cpu":
        args = ["--config", "tiny", "--steps", "3"]
    else:
        # no-remat: the 0.7B proxy's full activations fit one v5e at
        # batch 8, and dropping the blanket recompute gained ~11%
        # device-side. Remat is a MEMORY policy — the 8B stretch config
        # keeps it (tools/pretrain_llama --config 8b), the proxy
        # benchmarks the unconstrained step. 16 steps: sync at 8,
        # synced-span over the last 8 (~5 s device; PERF.md round 4 on
        # why the span MUST start from a synced fetch).
        args = ["--config", "proxy1b", "--steps", "16", "--batch", "8",
                "--seq", "2048", "--no-remat"]
    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = pretrain_main(args)
    if rc:
        return rc
    rec = json.loads(buf.getvalue().strip().splitlines()[-1])
    print(json.dumps({
        "metric": "llama_proxy_pretrain_tokens_per_sec_per_chip",
        "value": rec["tokens_per_sec"],
        "unit": "tokens/sec",
        "params": rec["params"],
        "mfu": rec["mfu"],
        "final_loss": rec["final_loss"],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
