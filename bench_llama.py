"""Benchmark: Llama pretrain proxy (~0.7B, Llama-3-8B recipe) on one chip.

Prints a JSON line after EVERY completed stage (flushed), monotonically
enriched — the bench.py artifact contract from PERF.md round 4 (a timeout
must not lose a finished stage's numbers):

    stage 1  config               -> line 1 (model/config keys)
    stage 2  pretrain proxy run   -> line 2 (adds value/mfu/params/
             final_loss — the contract keys)
    stage 3  fused-kernel adoption-> line 3 (pallas dispatch counts when
             telemetry is on)

The model is CONFIGS['proxy1b'] from tools/pretrain_llama.py — same
blocks, same fused TrainStep + AdamW path, same remat policy as the 8B
stretch config (BASELINE.json config[4]); only depth/width are scaled so
weights + Adam state fit one v5e chip. MFU = 6 * N * tokens_per_sec /
peak_flops. MXNET_PALLAS_FUSED (default ON here) routes the RMSNorm
sweeps through the fused Pallas layer kernels on TPU.

The full-size recipe artifact is produced by
``tools/pretrain_llama.py --config 8b --compile-only`` (AOT compile of the
sharded step on a virtual mesh; results recorded in PERF.md).
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

MFU_TARGET = 0.65            # ISSUE 7 acceptance bar


def _emit(record: dict) -> None:
    print(json.dumps(record), flush=True)


def main():
    os.environ.setdefault("MXNET_PALLAS_FUSED", "1")
    if os.environ.get("BENCH_LLAMA_FUSED_LAYERS") == "0":
        os.environ["MXNET_PALLAS_FUSED"] = "0"
    import jax

    from tools.pretrain_llama import main as pretrain_main

    platform = jax.devices()[0].platform
    if platform == "cpu":
        args = ["--config", "tiny", "--steps", "3"]
    else:
        # no-remat: the 0.7B proxy's full activations fit one v5e at
        # batch 8, and dropping the blanket recompute gained ~11%
        # device-side. Remat is a MEMORY policy — the 8B stretch config
        # keeps it (tools/pretrain_llama --config 8b), the proxy
        # benchmarks the unconstrained step. 16 steps: sync at 8,
        # synced-span over the last 8 (~5 s device; PERF.md round 4 on
        # why the span MUST start from a synced fetch).
        args = ["--config", "proxy1b", "--steps", "16", "--batch", "8",
                "--seq", "2048", "--no-remat"]
    record = {
        "metric": "llama_proxy_pretrain_tokens_per_sec_per_chip",
        "unit": "tokens/sec",
        "llama_config": args[1],
        "llama_fused_layers": os.environ["MXNET_PALLAS_FUSED"] == "1",
        "llama_mfu_target": MFU_TARGET,
    }
    _emit(record)  # stage 1 — config survives a timeout
    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = pretrain_main(args)
    if rc:
        return rc
    rec = json.loads(buf.getvalue().strip().splitlines()[-1])
    mfu = rec.get("mfu")
    record.update({
        "value": rec["tokens_per_sec"],
        "params": rec["params"],
        "mfu": mfu,
        "final_loss": rec["final_loss"],
        "llama_mfu_vs_target": round(mfu / MFU_TARGET, 4)
        if isinstance(mfu, (int, float)) else None,
    })
    _emit(record)  # stage 2 — the contract keys are on stdout

    from mxnet_tpu import telemetry

    if telemetry.enabled():
        fam = telemetry.snapshot()["metrics"].get(
            "mxnet_pallas_dispatch_total")
        record["llama_pallas_dispatch"] = {
            s["labels"]["kernel"]: s["value"]
            for s in (fam["samples"] if fam else ())}
        _emit(record)  # stage 3 — kernel-adoption counters
    return 0


if __name__ == "__main__":
    sys.exit(main())
