"""Benchmark: ResNet-50 training throughput (images/sec/chip).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline = 800 img/s (the reference's headline ResNet-50 fp16 number on one
V100 — BASELINE.md "Upstream MXNet published figures"). Runs the fused
TrainStep (forward+loss+backward+optimizer in one XLA executable) in
bfloat16 on whatever accelerator jax exposes (one TPU chip under the
driver; CPU fallback works but is slow).

Methodology (PERF.md has the full story): synthetic data is staged on the
device once before the timed loop, mirroring the reference's synthetic-data
benchmark mode (`example/image-classification/benchmark_score.py` uses
`mx.io.NDArrayIter` on pre-generated arrays). Input H2D transfer overlap is
the data pipeline's job (io.PrefetchingIter), not the step's; in this
environment the single TPU chip sits behind a network relay whose H2D
bandwidth (~50 MB/s) would otherwise dominate and measure the tunnel, not
the framework.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

BASELINE_IMG_S = 800.0  # reference ResNet-50 fp16, 1x V100 (BASELINE.md)


def main():
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import parallel as par
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo import vision

    platform = jax.devices()[0].platform
    batch = 256 if platform != "cpu" else 8
    steps = 30 if platform != "cpu" else 3

    # channels-last internally (NCHW stays at the API edge — the model
    # transposes its input once); kills the activation relayouts XLA
    # otherwise inserts around every NCHW conv. See PERF.md round 3.
    net = vision.resnet50_v1(layout="NHWC")
    net.initialize()
    net.cast("bfloat16")

    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.randn(batch, 3, 224, 224).astype(np.float32)) \
        .astype("bfloat16")
    y = mx.nd.array(rs.randint(0, 1000, (batch,)).astype(np.float32))

    mesh = par.make_mesh({"dp": 1}, devices=jax.devices()[:1])
    step = par.TrainStep(net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
                         mesh=mesh,
                         optimizer_params={"learning_rate": 0.1,
                                           "momentum": 0.9,
                                           "multi_precision": True})
    # warmup: compile + first step
    loss, _ = step(x, y)
    loss.asnumpy()
    # stage the synthetic batch on device with the step's input sharding
    step.stage_batch(x, y)
    loss, _ = step(x, y)
    loss.asnumpy()

    t0 = time.perf_counter()
    for _ in range(steps):
        loss, _ = step(x, y)
    loss.asnumpy()  # sync
    dt = time.perf_counter() - t0

    img_s = batch * steps / dt
    record = {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(img_s, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 4),
    }
    record.update(_bert_extra())
    record.update(_llama_extra())
    print(json.dumps(record))


def _bert_extra():
    """Secondary headline: BERT-base seq-512 training (bench_bert.py), as
    extra keys so the driver's one-JSON-line contract holds."""
    import json as _json
    import os
    import subprocess

    if os.environ.get("BENCH_SKIP_BERT"):
        return {}
    try:
        out = subprocess.run(
            [sys.executable, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "bench_bert.py")],
            capture_output=True, text=True, timeout=1200)
        line = out.stdout.strip().splitlines()[-1]
        rec = _json.loads(line)
        return {
            "bert_samples_per_sec_per_chip": rec["value"],
            "bert_vs_baseline": rec["vs_baseline"],
            "bert_mfu": rec.get("mfu"),
        }
    except Exception:
        return {}


def _llama_extra():
    """Third headline: Llama pretrain proxy (bench_llama.py)."""
    import json as _json
    import os
    import subprocess

    if os.environ.get("BENCH_SKIP_LLAMA"):
        return {}
    try:
        out = subprocess.run(
            [sys.executable, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "bench_llama.py")],
            capture_output=True, text=True, timeout=1500)
        line = out.stdout.strip().splitlines()[-1]
        rec = _json.loads(line)
        return {
            "llama_proxy_tokens_per_sec_per_chip": rec["value"],
            "llama_proxy_params": rec.get("params"),
            "llama_proxy_mfu": rec.get("mfu"),
        }
    except Exception:
        return {}


if __name__ == "__main__":
    sys.exit(main())
