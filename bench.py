"""Benchmark: ResNet-50 training throughput (images/sec/chip).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline = 800 img/s (the reference's headline ResNet-50 fp16 number on one
V100 — BASELINE.md "Upstream MXNet published figures"). Runs the fused
TrainStep (forward+loss+backward+optimizer in one XLA executable) in
bfloat16 on whatever accelerator jax exposes (one TPU chip under the
driver; CPU fallback works but is slow).

Methodology (PERF.md has the full story): synthetic data is staged on the
device once before the timed loop, mirroring the reference's synthetic-data
benchmark mode (`example/image-classification/benchmark_score.py` uses
`mx.io.NDArrayIter` on pre-generated arrays). Input H2D transfer overlap is
the data pipeline's job (io.PrefetchingIter), not the step's; in this
environment the single TPU chip sits behind a network relay whose H2D
bandwidth (~50 MB/s) would otherwise dominate and measure the tunnel, not
the framework.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

BASELINE_IMG_S = 800.0  # reference ResNet-50 fp16, 1x V100 (BASELINE.md)


def main():
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import parallel as par
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo import vision

    platform = jax.devices()[0].platform
    batch = 256 if platform != "cpu" else 8
    steps = 30 if platform != "cpu" else 3

    # channels-last internally (NCHW stays at the API edge — the model
    # transposes its input once); kills the activation relayouts XLA
    # otherwise inserts around every NCHW conv. See PERF.md round 3.
    net = vision.resnet50_v1(layout="NHWC")
    net.initialize()
    net.cast("bfloat16")

    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.randn(batch, 3, 224, 224).astype(np.float32)) \
        .astype("bfloat16")
    y = mx.nd.array(rs.randint(0, 1000, (batch,)).astype(np.float32))

    mesh = par.make_mesh({"dp": 1}, devices=jax.devices()[:1])
    step = par.TrainStep(net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
                         mesh=mesh,
                         optimizer_params={"learning_rate": 0.1,
                                           "momentum": 0.9,
                                           "multi_precision": True})
    # warmup: compile + first step
    loss, _ = step(x, y)
    loss.asnumpy()
    # stage the synthetic batch on device with the step's input sharding
    step.stage_batch(x, y)
    loss, _ = step(x, y)
    loss.asnumpy()

    t0 = time.perf_counter()
    for _ in range(steps):
        loss, _ = step(x, y)
    loss.asnumpy()  # sync
    dt = time.perf_counter() - t0

    img_s = batch * steps / dt
    record = {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(img_s, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 4),
    }
    try:
        # degrade to the synthetic-only record on any pipeline failure —
        # the driver's one-JSON-line contract must survive
        record.update(_real_data_extra(step, batch, steps))
    except Exception:
        pass
    # release this process's step/model buffers before the BERT/Llama
    # subprocesses run — the chip's HBM is shared with children, and the
    # resident ResNet state otherwise costs them batch-size headroom
    # (measured: in-chain BERT 264 vs 273 samples/s standalone)
    del step, net, x, y
    import gc

    gc.collect()
    record.update(_bert_extra())
    record.update(_llama_extra())
    print(json.dumps(record))


def _real_data_extra(step, batch, steps, img_size=224, n_images=2048):
    """Real-data mode (VERDICT round-2 #5): the SAME TrainStep fed by the
    full input pipeline — JPEG recordio on disk -> ImageRecordIter
    (decode + random-crop + mirror + normalize on host workers) ->
    PrefetchingIter overlap -> per-step device_put. Reported as extra
    keys next to the synthetic number so the pipeline cost is visible.
    Opt out with BENCH_SKIP_REALDATA=1.
    """
    import os
    import tempfile
    import numpy as np

    if os.environ.get("BENCH_SKIP_REALDATA"):
        return {}
    import mxnet_tpu as mx
    from mxnet_tpu import io as mxio, recordio

    rec_path = os.path.join(tempfile.gettempdir(),
                            f"bench_imgs_{img_size}_{n_images}.rec")
    if not os.path.exists(rec_path):
        # synthetic JPEGs, written once through the real recordio writer
        rs = np.random.RandomState(0)
        writer = recordio.MXRecordIO(rec_path, "w")
        for i in range(n_images):
            img = rs.randint(0, 256, (img_size, img_size, 3), np.uint8)
            header = recordio.IRHeader(0, float(i % 1000), i, 0)
            writer.write(recordio.pack_img(header, img, quality=90))
        writer.close()

    it = mxio.ImageRecordIter(
        path_imgrec=rec_path, data_shape=(3, img_size, img_size),
        batch_size=batch, rand_crop=False, rand_mirror=True,
        mean_r=123.68, mean_g=116.78, mean_b=103.94,
        std_r=58.4, std_g=57.1, std_b=57.4)
    pf = mxio.PrefetchingIter(it)

    def next_batch():
        try:
            b = next(pf)
        except StopIteration:
            pf.reset()
            b = next(pf)
        return (b.data[0].astype("bfloat16"),
                b.label[0].reshape((-1,)).astype("float32"))

    # warm (decoders + any reshape recompile), then timed
    x, y = next_batch()
    loss, _ = step(x, y)
    loss.asnumpy()
    t0 = time.perf_counter()
    for _ in range(steps):
        x, y = next_batch()
        loss, _ = step(x, y)
    loss.asnumpy()
    dt = time.perf_counter() - t0
    img_s = batch * steps / dt
    return {"real_data_images_per_sec_per_chip": round(img_s, 2)}


def _bert_extra():
    """Secondary headline: BERT-base seq-512 training (bench_bert.py), as
    extra keys so the driver's one-JSON-line contract holds."""
    import json as _json
    import os
    import subprocess

    if os.environ.get("BENCH_SKIP_BERT"):
        return {}
    try:
        out = subprocess.run(
            [sys.executable, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "bench_bert.py")],
            capture_output=True, text=True, timeout=1200)
        line = out.stdout.strip().splitlines()[-1]
        rec = _json.loads(line)
        return {
            "bert_samples_per_sec_per_chip": rec["value"],
            "bert_vs_baseline": rec["vs_baseline"],
            "bert_mfu": rec.get("mfu"),
        }
    except Exception:
        return {}


def _llama_extra():
    """Third headline: Llama pretrain proxy (bench_llama.py)."""
    import json as _json
    import os
    import subprocess

    if os.environ.get("BENCH_SKIP_LLAMA"):
        return {}
    try:
        out = subprocess.run(
            [sys.executable, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "bench_llama.py")],
            capture_output=True, text=True, timeout=1500)
        line = out.stdout.strip().splitlines()[-1]
        rec = _json.loads(line)
        return {
            "llama_proxy_tokens_per_sec_per_chip": rec["value"],
            "llama_proxy_params": rec.get("params"),
            "llama_proxy_mfu": rec.get("mfu"),
        }
    except Exception:
        return {}


if __name__ == "__main__":
    sys.exit(main())
