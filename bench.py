"""Benchmark: ResNet-50 training throughput (images/sec/chip) + extras.

Prints a JSON line {"metric", "value", "unit", "vs_baseline", ...extras}
after EVERY completed stage (flushed), monotonically enriched:

    stage 1  ResNet-50 synthetic   -> line 1 (the required contract keys)
    stage 2  eager-vs-bulk chain   -> line 2 (adds bulk_* — dispatch
             microbench of engine.bulk fused segments; cheap, runs first)
    stage 2.5 comms exchange       -> line 3 (adds comms_* — per-key vs
             bucketed vs bucketed+2bit gradient exchange on the
             ResNet-50-scale param set; dispatch counts + loss gate)
    stage 2.6 optimizer sweep      -> adds opt_sweep_* /
             optimizer_dispatches_per_step (fused multi-tensor sweep vs
             per-param updater loop on the same param set; BENCH_r06)
    stage 3  BERT-base subprocess  -> line 4 (adds bert_*)
    stage 4  Llama proxy subprocess-> line 5 (adds llama_proxy_*)
    stage 5  ResNet-50 real-data   -> line 6 (adds real_data_*)

    Stages are ordered by information value (BASELINE.json tracks resnet,
    bert, llama MFU; real-data measures the host pipeline on a 1-core
    container and is the least portable number), so a tight budget truncates
    from the bottom.

A driver that reads the LAST line of stdout always gets the richest
complete record even if it kills the process mid-chain (round 3's
all-or-nothing print lost the whole round to a timeout: BENCH_r03.json
rc=124, parsed=null). Because every completed stage leaves a full valid
line behind, an external timeout can never erase earlier results — so
BENCH_BUDGET_S (default 1800s) only prevents pointless stage starts,
not data loss, and subprocess timeouts are clamped to the remaining
budget. Stage failures are recorded as <stage>_error keys instead of
silently dropping the metric.

Baseline = 800 img/s (the reference's headline ResNet-50 fp16 number on
one V100 — BASELINE.md "Upstream MXNet published figures"). Runs the
fused TrainStep (forward+loss+backward+optimizer in one XLA executable)
in bfloat16 on whatever accelerator jax exposes.

Methodology (PERF.md has the full story): synthetic data is staged on the
device once before the timed loop, mirroring the reference's synthetic-data
benchmark mode (`example/image-classification/benchmark_score.py` uses
`mx.io.NDArrayIter` on pre-generated arrays). Input H2D transfer overlap is
the data pipeline's job (io.DeviceFeedIter — stage 5 runs the full async
path: process decode workers -> shm -> async sharded device_put of uint8
-> on-device normalize), not the step's; in this environment the single
TPU chip sits behind a network relay whose H2D bandwidth (~50 MB/s) would
otherwise dominate and measure the tunnel, not the framework.

Env knobs: BENCH_BUDGET_S (float, default 1800), BENCH_SKIP_REALDATA,
BENCH_SKIP_BERT, BENCH_SKIP_LLAMA, BENCH_SKIP_BULK, BENCH_SKIP_COMMS,
BENCH_BERT_TIMEOUT_S, BENCH_LLAMA_TIMEOUT_S, MXNET_KV_BUCKET_MB.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINE_IMG_S = 800.0  # reference ResNet-50 fp16, 1x V100 (BASELINE.md)
# transformer MFU regression bars (ISSUE 7): the next BENCH round gates
# bert_mfu_vs_target / llama_proxy_mfu_vs_target >= 1.0. The target
# constants live in bench_bert.py / bench_llama.py (single source); the
# extras below surface the children's target/ratio keys verbatim.

_T0 = time.perf_counter()


def _budget_s() -> float:
    return float(os.environ.get("BENCH_BUDGET_S", "1800"))


def _remaining_s() -> float:
    return _budget_s() - (time.perf_counter() - _T0)


def _emit(record: dict) -> None:
    """Print the current (enriched) record as one flushed JSON line."""
    print(json.dumps(record), flush=True)


def _write_telemetry(path: "str | None") -> None:
    if not path:
        return
    from mxnet_tpu import telemetry

    telemetry.write_snapshot(path)


def main():
    # --telemetry-out PATH: enable mx.telemetry for the run and write a
    # JSON snapshot after every stage, so BENCH_r*.json rounds carry
    # op-mix and cache-hit data
    from mxnet_tpu.telemetry import pop_telemetry_out_flag

    sys.argv[1:], telemetry_out = pop_telemetry_out_flag(sys.argv[1:])
    if telemetry_out:
        from mxnet_tpu import telemetry

        telemetry.enable()
        global _TELEMETRY_OUT
        _TELEMETRY_OUT = telemetry_out
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import parallel as par
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo import vision

    platform = jax.devices()[0].platform
    batch = int(os.environ.get("BENCH_RESNET_BATCH",
                               256 if platform != "cpu" else 8))
    steps = 30 if platform != "cpu" else 3

    step = _make_resnet_step(batch)
    x, y = _make_resnet_batch(batch)
    # warmup: compile + first step
    loss, _ = step(x, y)
    loss.asnumpy()
    # stage the synthetic batch on device with the step's input sharding
    step.stage_batch(x, y)
    loss, _ = step(x, y)
    loss.asnumpy()

    t0 = time.perf_counter()
    for _ in range(steps):
        loss, _ = step(x, y)
    loss.asnumpy()  # sync
    dt = time.perf_counter() - t0

    img_s = batch * steps / dt
    record = {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(img_s, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 4),
    }
    _emit(record)  # stage 1 complete — contract keys are now on stdout
    # snapshot after every stage, matching the incremental-emit contract:
    # a mid-chain kill still leaves the latest telemetry on disk. This
    # file covers THIS process (resnet + real-data stages); the BERT/
    # Llama subprocess stages write their own <PATH>.<script>.json via
    # MXNET_TELEMETRY_OUT (see _run_sub)
    _write_telemetry(telemetry_out)

    if _remaining_s() > 30:
        try:
            record.update(_bulk_extra())
        except Exception as e:
            record["bulk_error"] = repr(e)[:200]
    else:
        record["bulk_skipped"] = "budget"
    _emit(record)
    _write_telemetry(telemetry_out)

    if _remaining_s() > 30:
        try:
            record.update(_comms_extra())
        except Exception as e:
            record["comms_error"] = repr(e)[:200]
    else:
        record["comms_skipped"] = "budget"
    _emit(record)
    _write_telemetry(telemetry_out)

    # stage 2.6: fused multi-tensor optimizer sweep microbench (ISSUE 11
    # / BENCH_r06: optimizer-phase dispatch collapse + sweep time)
    if _remaining_s() > 30:
        try:
            record.update(_optimizer_extra())
        except Exception as e:
            record["opt_sweep_error"] = repr(e)[:200]
    else:
        record["opt_sweep_skipped"] = "budget"
    _emit(record)
    _write_telemetry(telemetry_out)

    # stage 2.7: compilation-service cold start (subprocess matrix —
    # cold / warm-disk / warm-manifest, train + serve; CPU children, no
    # accelerator contention with this process)
    if _remaining_s() > 120:
        try:
            record.update(_coldstart_extra())
        except Exception as e:
            record["coldstart_error"] = repr(e)[:200]
    else:
        record["coldstart_skipped"] = "budget"
    _emit(record)
    _write_telemetry(telemetry_out)

    # release this process's step/model buffers before the BERT/Llama
    # subprocesses run — the chip's HBM is shared with children, and the
    # resident ResNet state otherwise costs them batch-size headroom
    # (measured: in-chain BERT 264 vs 273 samples/s standalone)
    del step, x, y
    import gc

    gc.collect()

    for name, fn in (("bert", _bert_extra), ("llama", _llama_extra)):
        if _remaining_s() > 60:
            record.update(fn())
        else:
            record[name + "_skipped"] = "budget"
        _emit(record)
        _write_telemetry(telemetry_out)

    if _remaining_s() > 60:
        try:
            record.update(_real_data_extra(batch))
        except Exception as e:  # keep the chain alive, keep the failure visible
            record["real_data_error"] = repr(e)[:200]
    else:
        record["real_data_skipped"] = "budget"
    _emit(record)
    _write_telemetry(telemetry_out)
    return 0


def _make_resnet_step(batch):
    """Build the bf16 NHWC ResNet-50 TrainStep.

    channels-last internally (NCHW stays at the API edge — the model
    transposes its input once); kills the activation relayouts XLA
    otherwise inserts around every NCHW conv. See PERF.md round 3.
    """
    import jax
    from mxnet_tpu import parallel as par
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.resnet50_v1(layout="NHWC")
    net.initialize()
    net.cast("bfloat16")
    mesh = par.make_mesh({"dp": 1}, devices=jax.devices()[:1])
    return par.TrainStep(net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
                         mesh=mesh,
                         optimizer_params={"learning_rate": 0.1,
                                           "momentum": 0.9,
                                           "multi_precision": True})


def _make_resnet_batch(batch):
    import mxnet_tpu as mx

    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.randn(batch, 3, 224, 224).astype(np.float32)) \
        .astype("bfloat16")
    y = mx.nd.array(rs.randint(0, 1000, (batch,)).astype(np.float32))
    return x, y


def _bulk_extra(chain_len=64, reps=10):
    """Eager-vs-bulk op-chain microbench (engine.bulk fused segments).

    The number the bulking work exists to move: per-op host dispatch time
    of an imperative elementwise chain, eager (one single-op jit dispatch
    per op) vs inside ``engine.bulk`` (whole chain = ONE fused XLA
    dispatch). Also reports the XLA-dispatch reduction and the
    fused-segment cache hit rate over the timed reps — steady state
    should be all hits (CachedOp-style signature reuse). Opt out with
    BENCH_SKIP_BULK=1.
    """
    if os.environ.get("BENCH_SKIP_BULK"):
        return {}
    import mxnet_tpu as mx
    from mxnet_tpu import engine, telemetry

    n = chain_len
    x = mx.nd.array(
        np.random.RandomState(0).rand(256, 256).astype(np.float32))

    def chain(v):
        for _ in range(n // 2):
            v = v * 1.01 + 0.01  # n//2 muls + n//2 adds = n ops
        return v

    def dispatches():
        fam = telemetry.snapshot()["metrics"].get(
            "mxnet_xla_dispatch_total")
        return sum(s["value"] for s in fam["samples"]) if fam else 0.0

    def fused_cache():
        fam = telemetry.snapshot()["metrics"].get("mxnet_jit_cache_total")
        hits = misses = 0.0
        for s in (fam["samples"] if fam else ()):
            if s["labels"].get("cache") == "fused_segment":
                if s["labels"].get("result") == "hit":
                    hits = s["value"]
                else:
                    misses = s["value"]
        return hits, misses

    # counters are read as before/after deltas so a --telemetry-out run's
    # accumulated registry is never reset mid-chain
    was_enabled = telemetry.enabled()
    telemetry.enable()
    try:
        # warm both paths (per-op jit cache / fused-segment compile)
        chain(x).wait_to_read()
        with engine.bulk(n):
            out_w = chain(x)
        out_w.wait_to_read()

        d0 = dispatches()
        t0 = time.perf_counter()
        for _ in range(reps):
            out_e = chain(x)
        out_e.wait_to_read()
        eager_s = time.perf_counter() - t0
        eager_disp = dispatches() - d0

        h0, m0 = fused_cache()
        d0 = dispatches()
        t0 = time.perf_counter()
        for _ in range(reps):
            with engine.bulk(n):
                out_b = chain(x)
            out_b.wait_to_read()
        bulk_s = time.perf_counter() - t0
        bulk_disp = dispatches() - d0
        h1, m1 = fused_cache()
    finally:
        if not was_enabled:
            telemetry.disable()

    total_ops = n * reps
    hit, mis = h1 - h0, m1 - m0
    return {
        "bulk_chain_ops": n,
        "bulk_eager_dispatch_us_per_op": round(eager_s / total_ops * 1e6, 2),
        "bulk_fused_dispatch_us_per_op": round(bulk_s / total_ops * 1e6, 2),
        "bulk_speedup_vs_eager": round(eager_s / bulk_s, 3),
        "bulk_xla_dispatch_reduction": round(eager_disp / max(bulk_disp, 1.0), 1),
        "bulk_fused_cache_hit_rate": round(hit / max(hit + mis, 1.0), 4),
        # rtol 1e-5: XLA contracts mul+add to FMA inside the fused module
        # (one rounding instead of two) — same class of difference as any
        # jit-vs-op-by-op comparison
        "bulk_allclose_eager": bool(np.allclose(out_b.asnumpy(),
                                                out_e.asnumpy(), rtol=1e-5)),
    }


def _resnet50_param_shapes():
    """The comms/optimizer microbench param set, loaded once from
    tools/comms_bench.py (import is side-effect free)."""
    global _RESNET_SHAPES
    if _RESNET_SHAPES is None:
        import importlib.util as ilu

        spec = ilu.spec_from_file_location(
            "comms_bench", os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "tools",
                "comms_bench.py"))
        cb = ilu.module_from_spec(spec)
        spec.loader.exec_module(cb)
        _RESNET_SHAPES = cb.resnet50_param_shapes()
    return _RESNET_SHAPES


_RESNET_SHAPES = None


def _comms_extra(copies=2, reps=3):
    """Gradient-exchange microbench (stage 2.5): per-key vs bucketed vs
    bucketed+2bit on the ResNet-50-scale parameter set (ISSUE 5).

    The per-key path reduces each of the 161 parameters with its own
    dispatch (the reference KVStore shape); the bucketed fused
    ``pushpull`` coalesces them into ~25 MB flat buckets — one reduce
    per bucket. Reports the collective-dispatch reduction (from the
    telemetry counters), wall time per exchange for the three variants,
    and the trainer-level loss bit-identity gate (bucketed uncompressed
    must match per-key BIT-exactly). Single-chip note: with one device
    the 'collective' is the store's fused aggregation — the dispatch
    counts and the tax they model are the same, only the wire is
    missing. ``tools/comms_bench.py`` runs the identical measurement
    over a real multi-device psum mesh on the CPU oracle. Opt out with
    BENCH_SKIP_COMMS=1.
    """
    if os.environ.get("BENCH_SKIP_COMMS"):
        return {}
    import mxnet_tpu as mx
    from mxnet_tpu import kvstore as kvmod, telemetry
    from mxnet_tpu.kvstore import bucket_cap_bytes

    shapes = _resnet50_param_shapes()
    cap = bucket_cap_bytes()

    def collectives():
        fam = telemetry.snapshot()["metrics"].get(
            "mxnet_kvstore_collective_dispatch_total")
        return sum(s["value"] for s in (fam["samples"] if fam else ()))

    def run_variant(bucket_bytes, compression=None):
        store = kvmod.create("device")
        store._bucket_bytes = bucket_bytes
        if compression is not None:
            store.set_gradient_compression(compression)
        rs = np.random.RandomState(0)
        keys = list(range(len(shapes)))
        vals, outs = [], []
        for sh in shapes:
            g = mx.nd.array(rs.randn(*sh).astype(np.float32))
            vals.append([g, g * 1.5])          # two copies, one device
            outs.append([mx.nd.zeros(sh), mx.nd.zeros(sh)])
        for k, sh in zip(keys, shapes):
            store.init(k, mx.nd.zeros(sh))
        pr = [-k for k in keys]

        def exchange():
            store.pushpull(keys, vals, out=outs, priority=pr)
            mx.nd.waitall()

        exchange()                              # warm compiles
        c0 = collectives()
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            exchange()
            times.append(time.perf_counter() - t0)
        per_step = (collectives() - c0) / reps
        times.sort()
        return per_step, times[len(times) // 2] * 1e3

    was_enabled = telemetry.enabled()
    telemetry.enable()
    try:
        perkey_n, perkey_ms = run_variant(0)
        bucket_n, bucket_ms = run_variant(cap)
        _, bucket2bit_ms = run_variant(
            cap, compression={"type": "2bit", "threshold": 0.5})
    finally:
        if not was_enabled:
            telemetry.disable()
    identical = _comms_loss_bit_identity()
    return {
        "comms_params": len(shapes),
        "comms_bucket_mb": round(cap / (1 << 20), 3),
        "comms_perkey_collectives_per_step": round(perkey_n, 1),
        "comms_bucketed_collectives_per_step": round(bucket_n, 1),
        "comms_dispatch_reduction": round(
            perkey_n / max(bucket_n, 1.0), 1),
        "comms_perkey_ms_per_step": round(perkey_ms, 2),
        "comms_bucketed_ms_per_step": round(bucket_ms, 2),
        "comms_bucketed_2bit_ms_per_step": round(bucket2bit_ms, 2),
        "comms_bucketed_loss_bit_identical": bool(identical),
    }


def _comms_loss_bit_identity(steps=4):
    """Trainer-level gate on THIS device: a small net trained through
    kvstore='tpu_sync' with the per-key path (MXNET_KV_BUCKET_MB=0) and
    the bucketed path must produce bit-identical losses and weights."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.loss import L2Loss

    def run(bucket_mb):
        prev = os.environ.get("MXNET_KV_BUCKET_MB")
        os.environ["MXNET_KV_BUCKET_MB"] = str(bucket_mb)
        try:
            mx.random.seed(0)
            net = nn.Dense(16, in_units=32)
            net.initialize()
            rs = np.random.RandomState(7)
            net.weight.set_data(mx.nd.array(
                rs.randn(16, 32).astype(np.float32)))
            net.bias.set_data(mx.nd.zeros(16))
            tr = gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.05},
                               kvstore="tpu_sync")
            loss_fn = L2Loss()
            rs2 = np.random.RandomState(11)
            x = mx.nd.array(rs2.randn(8, 32).astype(np.float32))
            y = mx.nd.array(rs2.randn(8, 16).astype(np.float32))
            losses = []
            for _ in range(steps):
                with autograd.record():
                    loss = loss_fn(net(x), y)
                loss.backward()
                tr.step(8)
                losses.append(float(loss.asnumpy().sum()))
            return losses, net.weight.data().asnumpy()
        finally:
            # restore, don't erase: MXNET_KV_BUCKET_MB is a documented
            # bench knob and later stages/subprocesses must see it
            if prev is None:
                os.environ.pop("MXNET_KV_BUCKET_MB", None)
            else:
                os.environ["MXNET_KV_BUCKET_MB"] = prev

    losses_pk, w_pk = run(0)
    losses_bk, w_bk = run(25)
    return losses_pk == losses_bk and bool(np.array_equal(w_pk, w_bk))


def _optimizer_extra(reps=3):
    """Optimizer-sweep microbench (stage 2.6): the eager optimizer phase
    on the ResNet-50-scale parameter set, per-param updater loop vs the
    horizontally-fused multi-tensor sweep (ISSUE 11; first measured in
    BENCH_r06).

    Reports ``optimizer_dispatches_per_step`` for both paths (from the
    ``mxnet_optimizer_dispatch_total`` counters — the O(params) ->
    O(dtype buckets) collapse is the number this engine exists to move),
    median wall time per optimizer phase, and the bit-identity gate
    (fused Adam must match the per-param reference EXACTLY). Opt out
    with BENCH_SKIP_OPTSWEEP=1.
    """
    if os.environ.get("BENCH_SKIP_OPTSWEEP"):
        return {}
    import mxnet_tpu as mx
    from mxnet_tpu import optimizer as opt_mod, telemetry
    from mxnet_tpu.optimizer import multi_tensor as mt

    shapes = _resnet50_param_shapes()
    rs = np.random.RandomState(0)
    host_w = [rs.randn(*s).astype(np.float32) for s in shapes]
    host_g = [rs.randn(*s).astype(np.float32) for s in shapes]

    def dispatches():
        fam = telemetry.snapshot()["metrics"].get(
            "mxnet_optimizer_dispatch_total")
        return {s["labels"]["path"]: s["value"]
                for s in (fam["samples"] if fam else ())}

    def run_path(fused):
        prev = os.environ.get("MXNET_FUSED_OPTIMIZER")
        os.environ["MXNET_FUSED_OPTIMIZER"] = "1" if fused else "0"
        try:
            o = opt_mod.create("adam", learning_rate=1e-3)
            o.rescale_grad = 1.0 / 256
            upd = opt_mod.get_updater(o)
            ws = [mx.nd.array(w) for w in host_w]
            gs = [mx.nd.array(g) for g in host_g]
            items = [(i, w, g) for i, (w, g) in enumerate(zip(ws, gs))]

            def sweep():
                if fused:
                    assert mt.eager_fused_update(o, upd, items)
                else:
                    for i, w, g in items:
                        telemetry.record_optimizer_dispatch("per_param")
                        upd(i, g, w)
                mx.nd.waitall()

            sweep()                      # warm: states + compiles
            d0 = dispatches()
            times = []
            for _ in range(reps):
                t0 = time.perf_counter()
                sweep()
                times.append(time.perf_counter() - t0)
            d1 = dispatches()
            per_step = sum(d1.values()) - sum(d0.values())
            times.sort()
            return (per_step / reps, times[len(times) // 2] * 1e3,
                    [w.asnumpy() for w in ws])
        finally:
            if prev is None:
                os.environ.pop("MXNET_FUSED_OPTIMIZER", None)
            else:
                os.environ["MXNET_FUSED_OPTIMIZER"] = prev

    was_enabled = telemetry.enabled()
    telemetry.enable()
    try:
        fused_n, fused_ms, fused_w = run_path(True)
        perparam_n, perparam_ms, perparam_w = run_path(False)
    finally:
        if not was_enabled:
            telemetry.disable()
    identical = all(np.array_equal(a, b)
                    for a, b in zip(fused_w, perparam_w))
    return {
        "opt_sweep_params": len(shapes),
        "optimizer_dispatches_per_step": round(fused_n, 1),
        "optimizer_dispatches_per_step_unfused": round(perparam_n, 1),
        "opt_sweep_dispatch_reduction": round(
            perparam_n / max(fused_n, 1.0), 1),
        "opt_sweep_fused_ms_per_step": round(fused_ms, 2),
        "opt_sweep_perparam_ms_per_step": round(perparam_ms, 2),
        "opt_sweep_speedup": round(perparam_ms / max(fused_ms, 1e-9), 2),
        "opt_sweep_bit_identical": bool(identical),
    }


def _real_data_extra(batch, steps=10, img_size=224, n_images=2048):
    """Real-data mode (VERDICT round-2 #5, round-4 #3): the same fused
    TrainStep fed by the full async input pipeline (PERF.md round 7) —
    JPEG recordio on disk -> ImageIter with PROCESS decode workers
    (decode + crop + mirror on uint8, shm transport) ->
    io.DeviceFeedIter (async sharded device_put of quarter-size uint8
    batches, normalize+bf16 cast ON DEVICE) -> pre-sharded no-op step
    entry.

    Methodology unchanged from round 5: THREE timed windows, median with
    spread, plus the host-only producer rate and the device-only step
    rate (busy%% = median / device-only). New: the bit-identity key —
    one serial-decoded batch must equal the process-decoded batch under
    the same seed (the acceptance contract for moving decode off-process).
    Opt out with BENCH_SKIP_REALDATA=1; MXNET_DATA_WORKERS overrides the
    decode worker count (default: all cores).
    """
    import tempfile

    if os.environ.get("BENCH_SKIP_REALDATA"):
        return {}
    from mxnet_tpu import image as mximg, io as mxio, recordio

    n_workers = int(os.environ.get(
        "MXNET_DATA_WORKERS",
        os.environ.get("BENCH_REALDATA_THREADS", str(os.cpu_count() or 2))))

    rec_path = os.path.join(tempfile.gettempdir(),
                            f"bench_imgs_{img_size}_{n_images}.rec")
    if not os.path.exists(rec_path):
        # synthetic JPEGs, written once through the real recordio writer
        rs = np.random.RandomState(0)
        writer = recordio.MXRecordIO(rec_path, "w")
        for i in range(n_images):
            img = rs.randint(0, 256, (img_size, img_size, 3), np.uint8)
            header = recordio.IRHeader(0, float(i % 1000), i, 0)
            writer.write(recordio.pack_img(header, img, quality=90))
        writer.close()

    # host augmenters stay on uint8 (crop + mirror); normalization moved
    # onto the device so the wire carries 1/4 the bytes of the old f32
    # host-normalized batch
    def make_iter(mode, workers):
        return mximg.ImageIter(
            batch_size=batch, data_shape=(3, img_size, img_size),
            path_imgrec=rec_path, seed=0, dtype="uint8",
            worker_mode=mode, preprocess_threads=workers,
            aug_list=[mximg.CenterCropAug((img_size, img_size)),
                      mximg.HorizontalFlipAug(0.5)])

    # bit-identity gate: same seed, serial vs process workers
    it_a, it_b = make_iter("serial", 1), make_iter("process", n_workers)
    ba, bb = it_a.next(), it_b.next()
    identical = bool(
        np.array_equal(ba.data[0].asnumpy(), bb.data[0].asnumpy())
        and np.array_equal(ba.label[0].asnumpy(), bb.label[0].asnumpy()))
    it_a.close()
    it_b.close()

    step = _make_resnet_step(batch)
    it = make_iter("process", n_workers)
    feed = mxio.DeviceFeedIter(
        it, step=step, depth=2,
        device_transform=mxio.make_normalize_transform(
            [123.68, 116.78, 103.94], [58.4, 57.1, 57.4], "bfloat16"),
        name="bench_real_data")

    def next_batch():
        try:
            b = next(feed)
        except StopIteration:
            feed.reset()
            b = next(feed)
        return b.data[0], b.label[0]

    try:
        # warm (decoders + step compile on the fed shapes)
        x, y = next_batch()
        loss, _ = step(x, y)
        loss.asnumpy()

        # reference 1: device-only step rate on a staged batch
        step.stage_batch(x, y)
        loss, _ = step(x, y)
        loss.asnumpy()
        t0 = time.perf_counter()
        for _ in range(steps):
            loss, _ = step(x, y)
        loss.asnumpy()
        dev_img_s = batch * steps / (time.perf_counter() - t0)

        # reference 2: host-side producer rate (decode + async device
        # dispatch, no step). Drain the prefetch queue first — it filled
        # while the device-only loop ran with nobody consuming, and
        # pre-buffered batches would inflate the producer-bound rate
        for _ in range(3):
            next_batch()
        t0 = time.perf_counter()
        for _ in range(steps):
            next_batch()
        host_img_s = batch * steps / (time.perf_counter() - t0)

        # three measured windows of the full pipeline+train loop
        rates = []
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(steps):
                xb, yb = next_batch()
                loss, _ = step(xb, yb)
            loss.asnumpy()
            rates.append(batch * steps / (time.perf_counter() - t0))
    finally:
        feed.close()  # closes the ImageIter decode pool through it
    rates.sort()
    med = rates[1]
    return {
        "real_data_images_per_sec_per_chip": round(med, 2),
        "real_data_window_min_max": [round(rates[0], 2),
                                     round(rates[2], 2)],
        "real_data_host_pipeline_images_per_sec": round(host_img_s, 2),
        "real_data_device_only_images_per_sec": round(dev_img_s, 2),
        # fraction of each real-data step the device is actually busy
        "real_data_device_busy_pct": round(100.0 * med / dev_img_s, 1),
        "real_data_preprocess_threads": n_workers,
        "real_data_pipeline": "process-workers+uint8-shm+device-feed",
        "real_data_worker_batches_bit_identical": identical,
    }


_TELEMETRY_OUT = None  # set by main() when --telemetry-out is given


def _run_sub(script, timeout_s):
    """Run a bench subprocess, return its last-stdout-line JSON record.

    With --telemetry-out, the child gets MXNET_TELEMETRY_OUT so its own
    telemetry lands in a per-stage sibling file (the parent's snapshot
    cannot see a subprocess's registry)."""
    import subprocess

    env = None
    if _TELEMETRY_OUT:
        stem = os.path.splitext(script)[0]
        env = dict(os.environ, MXNET_TELEMETRY="1",
                   MXNET_TELEMETRY_OUT=f"{_TELEMETRY_OUT}.{stem}.json")
    try:
        out = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          script)],
            capture_output=True, text=True, timeout=timeout_s, env=env)
        stdout = out.stdout
    except subprocess.TimeoutExpired as e:
        # the children emit a flushed JSON line per completed stage
        # precisely so a timeout cannot erase finished numbers — salvage
        # the last complete line from the killed child's stdout
        stdout = e.stdout
        if isinstance(stdout, bytes):
            stdout = stdout.decode("utf-8", "replace")
        for line in reversed((stdout or "").strip().splitlines()):
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            rec["timeout"] = True   # extras surface this per stage
            return rec
        raise
    line = stdout.strip().splitlines()[-1]
    return json.loads(line)


def _coldstart_extra():
    """Stage 2.7: cold-start-to-first-step / first-response, cold vs
    warm disk cache vs warm + signature manifest (ROADMAP item 5's
    acceptance metric; tools/coldstart_bench.py)."""
    if os.environ.get("BENCH_SKIP_COLDSTART"):
        return {}
    cap = float(os.environ.get("BENCH_COLDSTART_TIMEOUT_S", "600"))
    rec = _run_sub(os.path.join("tools", "coldstart_bench.py"),
                   min(cap, max(_remaining_s(), 60)))
    return {k: v for k, v in rec.items() if k.startswith("coldstart_")}


def _bert_extra():
    """Secondary headline: BERT-base seq-512 training (bench_bert.py)."""
    if os.environ.get("BENCH_SKIP_BERT"):
        return {}
    cap = float(os.environ.get("BENCH_BERT_TIMEOUT_S", "1200"))
    try:
        rec = _run_sub("bench_bert.py", min(cap, max(_remaining_s(), 60)))
        # .get: a timeout-salvaged stage-1 record has config but no
        # value yet — keep whatever keys the child completed
        out = {
            "bert_samples_per_sec_per_chip": rec.get("value"),
            "bert_vs_baseline": rec.get("vs_baseline"),
            # regression keys the next BENCH round gates on (ISSUE 7
            # targets): the child is the single source of the target
            # constant and the vs-target ratio — no duplicate to drift
            "bert_mfu": rec.get("mfu"),
            "bert_mfu_target": rec.get("bert_mfu_target"),
            "bert_mfu_vs_target": rec.get("bert_mfu_vs_target"),
        }
        if rec.get("timeout"):
            out["bert_timeout"] = True
        return out
    except Exception as e:
        return {"bert_error": repr(e)[:200]}


def _llama_extra():
    """Third headline: Llama pretrain proxy (bench_llama.py)."""
    if os.environ.get("BENCH_SKIP_LLAMA"):
        return {}
    cap = float(os.environ.get("BENCH_LLAMA_TIMEOUT_S", "1500"))
    try:
        rec = _run_sub("bench_llama.py", min(cap, max(_remaining_s(), 60)))
        out = {
            "llama_proxy_tokens_per_sec_per_chip": rec.get("value"),
            "llama_proxy_params": rec.get("params"),
            "llama_proxy_mfu": rec.get("mfu"),
            "llama_proxy_mfu_target": rec.get("llama_mfu_target"),
            "llama_proxy_mfu_vs_target": rec.get("llama_mfu_vs_target"),
        }
        if rec.get("timeout"):
            out["llama_timeout"] = True
        return out
    except Exception as e:
        return {"llama_error": repr(e)[:200]}


if __name__ == "__main__":
    sys.exit(main())
