"""Define a custom operator in Python and train through it (reference:
example/numpy-ops/custom_softmax.py — the CustomOp/CustomOpProp ABI).

The op runs eagerly AND inside hybridized (jit-compiled) graphs: forward
executes via pure_callback, the user-defined backward is wired in with
custom_vjp.

Usage:
  python examples/custom_op.py
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn


class Softmax(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        e = np.exp(x - x.max(axis=1, keepdims=True))
        self.assign(out_data[0], req[0], mx.nd.array(e / e.sum(axis=1,
                                                               keepdims=True)))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0].asnumpy()
        dy = out_grad[0].asnumpy()
        dx = y * (dy - (dy * y).sum(axis=1, keepdims=True))
        self.assign(in_grad[0], req[0], mx.nd.array(dx))


@mx.operator.register("my_softmax")
class SoftmaxProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return Softmax()


def main():
    x = mx.nd.array(np.random.RandomState(0).randn(4, 10).astype(np.float32))
    x.attach_grad()
    with autograd.record():
        y = mx.nd.Custom(x, op_type="my_softmax")
        loss = -(y[:, 3].log()).mean()
    loss.backward()
    print("custom softmax row sums:", y.sum(axis=1).asnumpy())
    print("grad norm:", float((x.grad ** 2).sum().asnumpy()) ** 0.5)

    # the same op inside a hybridized block
    class Head(gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return F.Custom(x, op_type="my_softmax")

    net = Head()
    net.hybridize()
    out = net(x)
    np.testing.assert_allclose(out.asnumpy(), y.asnumpy(), rtol=1e-5)
    print("hybridized Custom op matches eager")


if __name__ == "__main__":
    main()
