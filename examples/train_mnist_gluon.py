"""Train LeNet on MNIST with the Gluon API.

The canonical first-contact example (reference:
example/gluon/mnist/mnist.py): dataset -> DataLoader -> HybridBlock ->
Trainer -> evaluation loop. Runs on whatever accelerator jax exposes;
synthesizes MNIST-shaped data when the real dataset is unreachable
(zero-egress environments).

Usage:
  python examples/train_mnist_gluon.py --epochs 2 --batch-size 64
"""
import argparse
import time

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn


def build_net():
    net = nn.HybridSequential()
    net.add(
        nn.Conv2D(20, kernel_size=5, activation="relu"),
        nn.MaxPool2D(pool_size=2, strides=2),
        nn.Conv2D(50, kernel_size=5, activation="relu"),
        nn.MaxPool2D(pool_size=2, strides=2),
        nn.Flatten(),
        nn.Dense(500, activation="relu"),
        nn.Dense(10),
    )
    return net


def load_data(batch_size):
    # MNIST falls back to a learnable synthetic surrogate by itself when
    # the download files are absent (zero-egress environments); the
    # `synthetic` attribute reports which mode is active
    train = gluon.data.vision.MNIST(train=True)
    test = gluon.data.vision.MNIST(train=False)
    if train.synthetic:
        print("MNIST files not found; using the synthetic surrogate")
    tf = gluon.data.vision.transforms.ToTensor()
    train = train.transform_first(tf)
    test = test.transform_first(tf)
    return (gluon.data.DataLoader(train, batch_size, shuffle=True),
            gluon.data.DataLoader(test, batch_size))


def evaluate(net, loader):
    metric = mx.metric.Accuracy()
    for x, y in loader:
        metric.update([y], [net(x)])
    return metric.get()[1]


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.02)
    args = ap.parse_args()

    net = build_net()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    train_loader, test_loader = load_data(args.batch_size)

    for epoch in range(args.epochs):
        t0 = time.time()
        metric = mx.metric.Accuracy()
        for x, y in train_loader:
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(x.shape[0])
            metric.update([y], [out])
        print(f"epoch {epoch}: train acc {metric.get()[1]:.4f} "
              f"({time.time() - t0:.1f}s)")
    print(f"test acc: {evaluate(net, test_loader):.4f}")


if __name__ == "__main__":
    main()
