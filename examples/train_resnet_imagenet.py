"""ImageNet-style ResNet-50 training (reference:
example/image-classification/train_imagenet.py).

Demonstrates the full production path: ImageRecordIter over a RecordIO
file (build one with tools/im2rec.py), NHWC layout for the TPU MXU,
bfloat16 compute with multi-precision SGD, the fused TrainStep (forward+
loss+backward+optimizer in ONE XLA executable), data-parallel mesh
sharding, and Speedometer/MFU reporting. With --synthetic it runs
anywhere (the benchmark_score.py mode).

Usage:
  python examples/train_resnet_imagenet.py --synthetic --batch-size 64
  python examples/train_resnet_imagenet.py --rec train.rec --batch-size 256
"""
import argparse
import time

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import parallel as par
from mxnet_tpu.gluon import loss as gloss
from mxnet_tpu.gluon.model_zoo import vision


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rec", help="RecordIO file from tools/im2rec.py")
    ap.add_argument("--synthetic", action="store_true")
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--lr", type=float, default=0.1)
    args = ap.parse_args()

    import jax

    net = vision.resnet50_v1(classes=1000, layout="NHWC")
    net.initialize(mx.init.Xavier())
    net.cast("bfloat16")

    mesh = par.make_mesh({"dp": len(jax.devices())})
    step = par.TrainStep(
        net, gloss.SoftmaxCrossEntropyLoss(), "sgd", mesh=mesh,
        optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                          "multi_precision": True})

    if args.synthetic:
        rs = np.random.RandomState(0)
        x = mx.nd.array(rs.uniform(-1, 1, (args.batch_size, 3, 224, 224))
                        .astype(np.float32)).astype("bfloat16")
        y = mx.nd.array(rs.randint(0, 1000, (args.batch_size,))
                        .astype(np.float32))
        batches = ((x, y) for _ in range(args.steps))
    else:
        # the async input pipeline end to end: process decode workers on
        # uint8 (MXNET_DATA_WORKERS to size the pool), batches staged
        # onto the mesh ahead of the step with the step's own input
        # sharding, normalize/bf16-cast on device (README "Input
        # pipeline"). shuffle=True would need a .idx file
        # (path_imgidx=...; build one with tools/im2rec.py).
        it = mx.io.ImageRecordIter(
            path_imgrec=args.rec, data_shape=(3, 224, 224),
            batch_size=args.batch_size, rand_mirror=True,
            preprocess_threads=4, dtype="uint8")
        it = mx.io.DeviceFeedIter(
            it, step=step, depth=2,
            device_transform=mx.io.make_normalize_transform(
                [123.68, 116.78, 103.94], [58.4, 57.1, 57.4], "bfloat16"))
        batches = ((b.data[0], b.label[0]) for b in it)

    t0, seen = time.time(), 0
    for i, (x, y) in enumerate(batches):
        if i >= args.steps:
            break
        loss, _ = step(x, y)
        seen += x.shape[0]
        if i == 0:
            loss.asnumpy()  # sync the compile out of the timed window
            t0, seen = time.time(), 0
    loss.asnumpy()
    dt = time.time() - t0
    print(f"{seen / dt:.1f} images/sec  (loss {float(loss.asnumpy()):.3f})")


if __name__ == "__main__":
    main()
