"""Multi-device / multi-host data-parallel training.

Single process, all local devices: the mesh shards the batch (GSPMD
inserts the gradient all-reduce over ICI); run as-is.

Multi-host (a TPU pod or several hosts over DCN): launch one process per
host with tools/launch.py — it sets the DMLC_* bootstrap env vars and
each process calls the same code; kvstore "dist_sync" wires
jax.distributed underneath:

  python tools/launch.py -n 2 --launcher local \
      python examples/distributed_data_parallel.py --kvstore dist_sync

On CPU containers, test with a virtual 8-device mesh:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/distributed_data_parallel.py
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel as par
from mxnet_tpu.gluon import nn, loss as gloss


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--kvstore", default=None,
                    help="dist_sync for multi-host; default = in-graph psum")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args()

    import jax

    if args.kvstore:
        kv = mx.kv.create(args.kvstore)
        print(f"rank {kv.rank}/{kv.num_workers}")

    net = nn.HybridSequential()
    net.add(nn.Dense(256, activation="relu"), nn.Dense(10))
    net.initialize(mx.init.Xavier())

    # dp mesh over every local device; TrainStep shards the batch axis and
    # GSPMD adds the psum — no explicit collective code
    mesh = par.make_mesh({"dp": len(jax.devices())})
    step = par.TrainStep(net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
                         mesh=mesh,
                         optimizer_params={"learning_rate": 0.1})

    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.rand(args.batch_size, 784).astype(np.float32))
    y = mx.nd.array(rs.randint(0, 10, (args.batch_size,)).astype(np.float32))
    for i in range(args.steps):
        loss, _ = step(x, y)
    print("final loss:", float(loss.asnumpy()))


if __name__ == "__main__":
    main()
