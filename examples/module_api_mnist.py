"""Train an MLP with the legacy Module/Symbol API (reference:
example/image-classification/train_mnist.py).

The symbolic path a user migrating old MXNet scripts needs: mx.sym graph
composition -> Module.fit with an eval metric, checkpoint callback, and
Speedometer — unchanged call signatures over the TPU-native executor.

Usage:
  python examples/module_api_mnist.py --epochs 2
"""
import argparse

import numpy as np

import mxnet_tpu as mx


def build_symbol():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=128, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=64, name="fc2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc3")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def synth_iter(batch_size, n=2048, seed=0):
    rs = np.random.RandomState(seed)
    y = rs.randint(0, 10, n).astype(np.float32)
    x = rs.rand(n, 784).astype(np.float32) * 0.1
    for i, lab in enumerate(y.astype(int)):
        x[i, lab * 78:lab * 78 + 78] += 0.9
    return mx.io.NDArrayIter(data=x, label=y, batch_size=batch_size,
                             shuffle=True)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args()

    train = synth_iter(args.batch_size)
    val = synth_iter(args.batch_size, n=512, seed=1)

    mod = mx.mod.Module(build_symbol(), data_names=["data"],
                        label_names=["softmax_label"])
    # SoftmaxOutput gradients are per-sample SUMS (reference default
    # normalization='null'), so the learning rate must absorb the batch
    # size — lr 0.1 with momentum diverges at batch 64
    mod.fit(train, eval_data=val, optimizer="sgd",
            initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.02 / args.batch_size,
                              "momentum": 0.9},
            eval_metric="acc", num_epoch=args.epochs,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 20))
    score = mod.score(val, mx.metric.Accuracy())
    print("validation:", score)


if __name__ == "__main__":
    main()
