"""Root pytest conftest: force the CPU oracle environment.

The container pins ``JAX_PLATFORMS=axon`` (one real TPU chip behind a
single-client tunnel) via a sitecustomize on ``PYTHONPATH``; that
registration happens at interpreter start and can hang jax init even when
tests only want CPU. Tests must run on the virtual 8-device CPU mesh
(SURVEY.md §4: CPU is the oracle device; the fake cluster is
``--xla_force_host_platform_device_count``), so we re-exec pytest once with
a clean environment. The re-exec lives in ``pytest_configure`` so pytest's
fd-level capture can be stopped first (otherwise the new process writes
into the old capture temp file and the output vanishes).
"""
import os
import sys


def _needs_reexec() -> bool:
    if os.environ.get("MXNET_TPU_TEST_NO_REEXEC"):
        return False
    return os.environ.get("JAX_PLATFORMS") != "cpu" or bool(os.environ.get("PYTHONPATH"))


def pytest_configure(config):
    if not _needs_reexec():
        return
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        try:
            capman.stop_global_capturing()
        except Exception:
            pass
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # Stash the original PYTHONPATH so tests that spawn driver-like
    # subprocesses (tests/test_graft_entry.py) can restore the container's
    # sitecustomize environment.
    env["MXNET_TPU_ORIG_PYTHONPATH"] = env.get("PYTHONPATH", "")
    env["PYTHONPATH"] = ""
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
    env["MXNET_TPU_TEST_NO_REEXEC"] = "1"
    sys.stdout.flush()
    sys.stderr.flush()
    os.execve(sys.executable, [sys.executable, "-m", "pytest"] + sys.argv[1:], env)
