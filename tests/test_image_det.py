"""ImageDetIter / detection augmenter tests (reference:
tests/python/unittest/test_image.py::TestImageDetIter).

Oracle: box algebra — flips/crops/pads must keep boxes consistent with
the pixels they cover; the iterator must pad labels to a fixed block.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import image as img_mod, recordio
from mxnet_tpu.base import MXNetError


def _png(arr):
    """Minimal uncompressed image container: use pack_img's jpeg? —
    encode via PIL-free path: mx.image.imdecode consumes raw encodings;
    recordio.pack_img handles encoding."""
    return arr


def _make_det_rec(tmp_path, n=8, size=24, max_objs=3, seed=0):
    rs = onp.random.RandomState(seed)
    rec_path = str(tmp_path / "det.rec")
    idx_path = str(tmp_path / "det.idx")
    rec = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(n):
        im = rs.randint(0, 255, (size, size, 3)).astype("uint8")
        k = rs.randint(1, max_objs + 1)
        objs = []
        for _ in range(k):
            x1, y1 = rs.uniform(0, 0.5, 2)
            objs.append([rs.randint(0, 4), x1, y1,
                         x1 + rs.uniform(0.2, 0.45),
                         y1 + rs.uniform(0.2, 0.45)])
        label = onp.concatenate([[2, 5], onp.asarray(objs).ravel()]) \
            .astype("float32")
        header = recordio.IRHeader(0, label, i, 0)
        rec.write_idx(i, recordio.pack_img(header, im, quality=95))
    rec.close()
    return rec_path, idx_path


class TestDetAugmenters:
    def test_hflip_boxes(self):
        im = onp.zeros((10, 10, 3), "uint8")
        label = onp.array([[1, 0.1, 0.2, 0.4, 0.6],
                           [-1, -1, -1, -1, -1]], "float32")
        aug = img_mod.DetHorizontalFlipAug(p=1.0)
        _im2, l2 = aug(im, label)
        onp.testing.assert_allclose(l2[0], [1, 0.6, 0.2, 0.9, 0.6],
                                    rtol=1e-6)
        assert (l2[1] == -1).all()

    def test_random_crop_keeps_covered_boxes(self):
        onp.random.seed(1)
        im = onp.zeros((20, 20, 3), "uint8")
        label = onp.array([[0, 0.3, 0.3, 0.7, 0.7]], "float32")
        aug = img_mod.DetRandomCropAug(min_object_covered=0.5,
                                       area_range=(0.5, 1.0))
        for _ in range(5):
            out, l2 = aug(im, label.copy())
            kept = l2[l2[:, 0] >= 0]
            if len(kept):
                assert (kept[:, 1:5] >= 0).all() and \
                    (kept[:, 1:5] <= 1).all()

    def test_random_pad_shrinks_boxes(self):
        im = onp.full((10, 10, 3), 255, "uint8")
        label = onp.array([[0, 0.0, 0.0, 1.0, 1.0]], "float32")
        aug = img_mod.DetRandomPadAug(area_range=(2.0, 2.5))
        out, l2 = aug(im, label.copy())
        w = l2[0, 3] - l2[0, 1]
        h = l2[0, 4] - l2[0, 2]
        assert w < 1.0 and h < 1.0          # box shrank on bigger canvas
        assert out.shape[0] >= 10 and out.shape[1] >= 10


class TestImageDetIter:
    def test_batches_and_label_padding(self, tmp_path):
        rec, idx = _make_det_rec(tmp_path)
        it = img_mod.ImageDetIter(
            batch_size=4, data_shape=(3, 16, 16), path_imgrec=rec,
            path_imgidx=idx,
            aug_list=img_mod.CreateDetAugmenter((3, 16, 16)))
        assert it.label_shape[0] >= 1 and it.label_shape[1] == 5
        nb = 0
        for batch in it:
            assert batch.data[0].shape == (4, 3, 16, 16)
            lab = batch.label[0].asnumpy()
            assert lab.shape == (4,) + it.label_shape
            valid = lab[lab[:, :, 0] >= 0]
            assert len(valid)                      # real objects present
            assert (valid[:, 1:5] >= 0).all()
            nb += 1
        assert nb == 2
        it.reset()
        assert next(iter(it)) is not None

    def test_mirror_pipeline_and_reshape(self, tmp_path):
        rec, idx = _make_det_rec(tmp_path, seed=2)
        it = img_mod.ImageDetIter(
            batch_size=2, data_shape=(3, 16, 16), path_imgrec=rec,
            path_imgidx=idx,
            aug_list=img_mod.CreateDetAugmenter((3, 16, 16),
                                                rand_mirror=True,
                                                rand_crop=0.5, mean=True,
                                                std=True))
        batch = next(iter(it))
        assert onp.isfinite(batch.data[0].asnumpy()).all()
        it.reshape(data_shape=(3, 20, 20))
        assert it.provide_data[0].shape == (2, 3, 20, 20)

    def test_bad_label_rejected(self):
        with pytest.raises(MXNetError, match="object_width"):
            img_mod.ImageDetIter._parse_label(
                onp.array([2, 3, 0, 0.1, 0.2], "float32"))


class TestAugmenterTail:
    """Round-4 augmenter surface tail: SequentialAug, RandomOrderAug,
    HueJitterAug (YIQ rotation), scale_down."""

    def _img(self):
        return mx.nd.array(
            onp.random.RandomState(0).rand(8, 8, 3).astype("f") * 255)

    def test_sequential_and_random_order(self):
        img = self._img()
        seq = mx.image.SequentialAug([mx.image.BrightnessJitterAug(0.1),
                                      mx.image.ContrastJitterAug(0.1)])
        assert seq(img).shape == (8, 8, 3)
        ro = mx.image.RandomOrderAug([mx.image.CastAug()])
        assert ro(img).shape == (8, 8, 3)

    def test_hue_jitter_identity_at_zero(self):
        img = self._img()
        h = mx.image.HueJitterAug(0.0)
        # the rounded 3-decimal YIQ constants give ~0.25% residual — the
        # same constants (and residual) as the reference implementation
        onp.testing.assert_allclose(h(img).asnumpy(), img.asnumpy(),
                                    atol=1.0)
        h2 = mx.image.HueJitterAug(0.4)
        out = h2(img).asnumpy()
        assert out.shape == (8, 8, 3) and onp.isfinite(out).all()

    def test_scale_down(self):
        assert mx.image.scale_down((100, 100), (8, 6)) == (8, 6)
        w, h = mx.image.scale_down((4, 4), (8, 6))
        assert w <= 4 and h <= 4
