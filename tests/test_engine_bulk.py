"""Bulked (lazy) imperative execution: ``mx.engine.bulk`` (reference:
``python/mxnet/engine.py :: bulk`` + ThreadedEngine op bulking).

Covers: eager-equivalence over mixed op chains, every flush trigger
(sync point, size cap, non-recordable op, scope exit, nested scope),
fused-segment cache behaviour, NaiveEngine interplay, flush-time
exception attribution, and thread isolation of the recorder.
"""
import threading

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import engine, telemetry
from mxnet_tpu.ops import registry


@pytest.fixture
def tel():
    """Telemetry enabled for the test, cleanly reset around it."""
    telemetry.enable()
    telemetry.reset()
    yield telemetry
    telemetry.disable()
    telemetry.reset()


def _counter(name, **labels):
    """Sum of a counter family's samples matching the given labels."""
    fam = telemetry.snapshot()["metrics"].get(name)
    if fam is None:
        return 0.0
    total = 0.0
    for s in fam["samples"]:
        if all(s["labels"].get(k) == v for k, v in labels.items()):
            total += s["value"]
    return total


def _chain(x, y):
    """A mixed op chain: elementwise, scalar, reduction-with-keepdims,
    matmul, transpose — enough variety to exercise wiring and avals."""
    z = (x + y) * 2.0 - y / 3.0
    z = z.exp().log() + z.square().sqrt()
    m = z.mean(axis=1, keepdims=True)
    z = z - m
    w = z.dot(z, transpose_b=True)
    return (w + w.T).sum(axis=0)


class TestEagerEquivalence:
    def test_mixed_chain_matches_eager(self):
        x = mx.nd.array(onp.random.rand(5, 7).astype(onp.float32) + 0.5)
        y = mx.nd.array(onp.random.rand(5, 7).astype(onp.float32) + 0.5)
        ref = _chain(x, y).asnumpy()
        with engine.bulk(64):
            out = _chain(x, y)
            assert engine.is_pending(out._data)
            got = out.asnumpy()
        onp.testing.assert_allclose(got, ref, rtol=1e-6)

    def test_elementwise_chain_bit_identical(self):
        x = mx.nd.array(onp.random.rand(4, 4).astype(onp.float32))
        ref = x
        for _ in range(16):
            ref = ref * 1.5 + 0.25
        ref = ref.asnumpy()
        with engine.bulk(64):
            z = x
            for _ in range(16):
                z = z * 1.5 + 0.25
            got = z.asnumpy()
        onp.testing.assert_array_equal(got, ref)

    def test_creation_ops_run_eagerly_without_flushing(self, tel):
        x = mx.nd.ones((3, 3))
        with engine.bulk(64):
            z = x + 1.0
            w = mx.nd.zeros((3, 3))  # no dataflow into the segment
            assert not engine.is_pending(w._data)
            assert engine.is_pending(z._data)  # ...and no flush either
            z = z + w
            got = z.asnumpy()
        onp.testing.assert_array_equal(got, onp.full((3, 3), 2.0))

    def test_random_samplers_run_eagerly_in_bulk(self, tel):
        # zero-tensor rng ops are creation ops: the leading PRNG-key arg
        # must not make them recordable (they'd crash imperative_invoke's
        # device_put creation branch with a PendingValue)
        with engine.bulk(64):
            r = mx.nd.random.uniform(shape=(4,))
            assert not engine.is_pending(r._data)
            z = r * 2.0  # ...but chains ON the sample do record
            assert engine.is_pending(z._data)
            got = z.asnumpy()
        onp.testing.assert_array_equal(got, r.asnumpy() * 2.0)

    def test_inplace_loop_stays_bulked(self, tel):
        a = mx.nd.zeros((2, 2))
        with engine.bulk(64):
            for _ in range(10):
                a += 1.0
            got = a.asnumpy()
        onp.testing.assert_array_equal(got, onp.full((2, 2), 10.0))
        assert _counter("mxnet_xla_dispatch_total", kind="fused_segment") == 1

    def test_out_kwarg_stays_bulked(self, tel):
        x = mx.nd.ones((3,))
        dst = mx.nd.zeros((3,))
        with engine.bulk(64):
            mx.nd.broadcast_add(x, x, out=dst)
            mx.nd.broadcast_mul(dst, dst, out=dst)
            got = dst.asnumpy()
        onp.testing.assert_array_equal(got, onp.full((3,), 4.0))
        assert _counter("mxnet_xla_dispatch_total", kind="fused_segment") == 1


class TestFlushTriggers:
    def test_sync_point_flushes(self, tel):
        x = mx.nd.ones((2, 2))
        with engine.bulk(64):
            z = x * 3.0
            z.asnumpy()  # sync point mid-scope
            assert _counter("mxnet_bulk_flush_total", reason="sync") == 1
            assert not engine.is_pending(z._data)

    def test_wait_to_read_and_waitall_flush(self, tel):
        x = mx.nd.ones((2, 2))
        with engine.bulk(64):
            z = x + 1.0
            z.wait_to_read()
            assert not engine.is_pending(z._data)
            w = x + 2.0
            mx.nd.waitall()
            assert not engine.is_pending(w._data)
        assert _counter("mxnet_bulk_flush_total", reason="sync") == 2

    def test_repr_is_a_sync_point(self):
        x = mx.nd.ones((2,))
        with engine.bulk(64):
            z = x + 1.0
            assert "2x" not in repr(z)  # shape 2, just materialize
            assert not engine.is_pending(z._data)

    def test_size_cap_flushes(self, tel):
        x = mx.nd.ones((2, 2))
        with engine.bulk(4):
            z = x
            for _ in range(8):
                z = z + 1.0
            got = z.asnumpy()
        onp.testing.assert_array_equal(got, onp.full((2, 2), 9.0))
        assert _counter("mxnet_bulk_flush_total", reason="size") == 2

    def test_eager_only_op_flushes_then_runs(self, tel):
        data = mx.nd.array(onp.arange(6, dtype=onp.float32).reshape(3, 2))
        mask = mx.nd.array(onp.array([1.0, 0.0, 1.0], dtype=onp.float32))
        with engine.bulk(64):
            z = data * 2.0
            # boolean_mask is eager_only (dynamic output shape)
            kept = mx.nd.contrib.boolean_mask(z, mask)
            assert _counter("mxnet_bulk_flush_total",
                            reason="unrecordable") == 1
            got = kept.asnumpy()
        onp.testing.assert_array_equal(
            got, onp.array([[0.0, 2.0], [8.0, 10.0]], dtype=onp.float32))

    def test_scope_exit_flushes(self, tel):
        x = mx.nd.ones((2, 2))
        with engine.bulk(64):
            z = x * 5.0
            assert engine.is_pending(z._data)
        assert _counter("mxnet_bulk_flush_total", reason="scope_exit") == 1
        onp.testing.assert_array_equal(z.asnumpy(), onp.full((2, 2), 5.0))

    def test_autograd_recording_is_unrecordable(self, tel):
        from mxnet_tpu import autograd

        x = mx.nd.ones((2, 2))
        x.attach_grad()
        with engine.bulk(64):
            pre = x * 2.0  # recorded into the segment
            with autograd.record():
                y = (x * x).sum()
            y.backward()
            assert _counter("mxnet_bulk_flush_total",
                            reason="unrecordable") >= 1
        onp.testing.assert_array_equal(x.grad.asnumpy(),
                                       onp.full((2, 2), 2.0))
        onp.testing.assert_array_equal(pre.asnumpy(), onp.full((2, 2), 2.0))


class TestNestedScopes:
    def test_nested_scope_flushes_outer_and_restores(self, tel):
        x = mx.nd.ones((2, 2))
        with engine.bulk(64):
            a = x + 1.0
            with engine.bulk(8):
                assert _counter("mxnet_bulk_flush_total",
                                reason="nested_scope") == 1
                assert not engine.is_pending(a._data)
                b = a * 2.0
                assert engine.is_pending(b._data)
            # inner exit flushed; outer scope active again
            assert _counter("mxnet_bulk_flush_total",
                            reason="scope_exit") == 1
            assert not engine.is_pending(b._data)
            c = b + 0.5
            assert engine.is_pending(c._data)
        onp.testing.assert_array_equal(c.asnumpy(), onp.full((2, 2), 4.5))

    def test_size_validation(self):
        for bad in (0, -3):
            with pytest.raises(ValueError, match=">= 1"):
                with engine.bulk(bad):
                    pass
        for bad in ("8", 2.0, True, None):
            with pytest.raises(ValueError, match="int"):
                with engine.bulk(bad):
                    pass


class TestFusedCache:
    def test_structurally_identical_segments_hit(self, tel):
        registry.fused_segment_cache_clear()
        x = mx.nd.array(onp.random.rand(6, 6).astype(onp.float32))

        def run():
            with engine.bulk(64):
                z = x
                for _ in range(5):
                    z = z * 1.1 + 0.1
                return z.asnumpy()

        r1, r2 = run(), run()
        onp.testing.assert_array_equal(r1, r2)
        assert _counter("mxnet_jit_cache_total",
                        cache="fused_segment", result="miss") == 1
        assert _counter("mxnet_jit_cache_total",
                        cache="fused_segment", result="hit") == 1

    def test_dispatch_reduction_on_long_chain(self, tel):
        """Acceptance: a >=32-op chain bulked into >=4x fewer dispatches,
        allclose to eager."""
        x = mx.nd.array(onp.random.rand(8, 8).astype(onp.float32))

        def chain(v):
            for i in range(32):
                v = v * 1.01 + 0.01
            return v

        ref = chain(x).asnumpy()
        telemetry.reset()
        eager_out = chain(x).asnumpy()
        eager_n = (_counter("mxnet_xla_dispatch_total", kind="eager_op")
                   + _counter("mxnet_xla_dispatch_total",
                              kind="eager_uncached"))
        telemetry.reset()
        with engine.bulk(64):
            bulk_out = chain(x).asnumpy()
        bulk_n = (_counter("mxnet_xla_dispatch_total", kind="fused_segment")
                  + _counter("mxnet_xla_dispatch_total", kind="eager_op")
                  + _counter("mxnet_xla_dispatch_total",
                             kind="eager_uncached"))
        assert eager_n == 64  # 32 muls + 32 adds
        assert bulk_n >= 1
        assert eager_n / bulk_n >= 4.0
        # rtol 1e-5: XLA may contract mul+add to FMA inside the fused
        # module — one rounding instead of two per chain link
        onp.testing.assert_allclose(bulk_out, ref, rtol=1e-5)
        onp.testing.assert_allclose(bulk_out, eager_out, rtol=1e-5)


class TestNaiveEngine:
    def test_naive_engine_executes_immediately(self, tel):
        engine.set_engine_type("NaiveEngine")
        try:
            x = mx.nd.ones((2, 2))
            with engine.bulk(64):
                z = x + 1.0
                # NaiveEngine is fully synchronous: nothing is deferred
                assert not engine.is_pending(z._data)
            onp.testing.assert_array_equal(z.asnumpy(),
                                           onp.full((2, 2), 2.0))
            assert _counter("mxnet_xla_dispatch_total",
                            kind="fused_segment") == 0
        finally:
            engine.set_engine_type("ThreadedEnginePerDevice")


class TestExceptionPropagation:
    def test_flush_error_names_originating_op(self):
        from mxnet_tpu.base import MXNetError

        registry.fused_segment_cache_clear()
        x = mx.nd.array(onp.random.rand(3, 5).astype(onp.float32))
        with engine.bulk(64):
            z = x + 1.0
            seg = engine.current_bulk_scope().segment
            # simulate an op whose lowering fails only at flush time (e.g.
            # a platform-gated kernel): poison the recorded node's fn
            def boom(*a, **kw):
                raise RuntimeError("lowering exploded")

            seg.nodes[0] = engine._SegmentNode(
                seg.nodes[0].name, boom, seg.nodes[0].attr_items,
                seg.nodes[0].input_specs, seg.nodes[0].n_out,
                seg.nodes[0].out_is_seq, seg.nodes[0].sig)
            with pytest.raises(MXNetError, match=r"op #0.*_plus_scalar"):
                z.asnumpy()

    def test_failed_segment_rethrows_for_every_pending(self):
        from mxnet_tpu.base import MXNetError

        registry.fused_segment_cache_clear()
        x = mx.nd.array(onp.random.rand(4, 9).astype(onp.float32))
        with engine.bulk(64):
            z1 = x + 1.0
            z2 = z1 * 2.0
            seg = engine.current_bulk_scope().segment

            def boom(*a, **kw):
                raise RuntimeError("lowering exploded")

            seg.nodes[0] = engine._SegmentNode(
                seg.nodes[0].name, boom, seg.nodes[0].attr_items,
                seg.nodes[0].input_specs, seg.nodes[0].n_out,
                seg.nodes[0].out_is_seq, seg.nodes[0].sig)
            with pytest.raises(MXNetError, match="op #0"):
                z1.asnumpy()
            # the sibling pending re-raises the stored failure, not a
            # generic engine-bug error (ThreadedVar ExceptionRef contract)
            with pytest.raises(MXNetError, match="failed"):
                z2.asnumpy()

    def test_shape_errors_surface_eagerly_at_call_site(self):
        # abstract eval fails at record time -> the op runs (and raises)
        # eagerly, naming the real failure, not at some later flush
        x = mx.nd.ones((2, 3))
        y = mx.nd.ones((4, 5))
        with engine.bulk(64):
            with pytest.raises(Exception):
                (x + 1.0).dot(y)


class TestThreadIsolation:
    def test_other_threads_execute_eagerly(self):
        x = mx.nd.ones((2, 2))
        results = {}

        def worker():
            w = x * 7.0
            results["pending"] = engine.is_pending(w._data)
            results["val"] = w.asnumpy()

        with engine.bulk(64):
            z = x + 1.0  # main thread records...
            t = threading.Thread(target=worker)
            t.start()
            t.join()
            assert engine.is_pending(z._data)  # ...and stays recorded
        assert results["pending"] is False
        onp.testing.assert_array_equal(results["val"],
                                       onp.full((2, 2), 7.0))

    def test_cross_thread_force_of_pending_value(self):
        x = mx.nd.ones((2, 2))
        results = {}
        with engine.bulk(64):
            z = x + 41.0
            assert engine.is_pending(z._data)

            def reader():
                # a pending array handed across threads: reading it must
                # flush the owning (other-thread) segment safely
                results["val"] = z.asnumpy()

            t = threading.Thread(target=reader)
            t.start()
            t.join()
        onp.testing.assert_array_equal(results["val"],
                                       onp.full((2, 2), 42.0))

    def test_concurrent_scopes_are_independent(self, tel):
        errs = []

        def worker(seed):
            try:
                a = mx.nd.array(onp.full((2, 2), float(seed),
                                         dtype=onp.float32))
                with engine.bulk(16):
                    z = a
                    for _ in range(6):
                        z = z + 1.0
                    got = z.asnumpy()
                onp.testing.assert_array_equal(
                    got, onp.full((2, 2), float(seed) + 6.0))
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
