"""mx.contrib.text tests (reference:
tests/python/unittest/test_contrib_text.py — vocab ordering, embedding
loading, composite concat)."""
from collections import Counter

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.contrib import text


class TestVocabulary:
    def test_ordering_and_lookup(self):
        counter = text.utils.count_tokens_from_str(
            "a b b c c c\nd d d d", to_lower=False)
        v = text.Vocabulary(counter, unknown_token="<unk>",
                            reserved_tokens=["<pad>"])
        # unk, reserved, then frequency-desc with alphabetical ties
        assert v.idx_to_token == ["<unk>", "<pad>", "d", "c", "b", "a"]
        assert v.to_indices("c") == 3
        assert v.to_indices(["b", "zzz"]) == [4, 0]
        assert v.to_tokens([2, 3]) == ["d", "c"]
        assert "d" in v and "zzz" not in v
        with pytest.raises(ValueError):
            v.to_tokens(99)

    def test_limits(self):
        counter = Counter({"a": 5, "b": 3, "c": 1})
        v = text.Vocabulary(counter, most_freq_count=1, min_freq=2)
        assert v.idx_to_token == ["<unk>", "a"]
        with pytest.raises(ValueError):
            text.Vocabulary(counter, reserved_tokens=["<unk>"])


class TestEmbedding:
    def _write_vectors(self, tmp_path):
        p = tmp_path / "vec.txt"
        p.write_text("hello 1.0 2.0 3.0\n"
                     "world 4.0 5.0 6.0\n"
                     "hello 9.0 9.0 9.0\n")       # duplicate: skipped
        return str(p)

    def test_custom_embedding(self, tmp_path):
        emb = text.CustomEmbedding(self._write_vectors(tmp_path))
        assert len(emb) == 3 and emb.vec_len == 3
        onp.testing.assert_allclose(
            emb.get_vecs_by_tokens("world").asnumpy(), [4.0, 5.0, 6.0])
        got = emb.get_vecs_by_tokens(["hello", "nope"]).asnumpy()
        onp.testing.assert_allclose(got[0], [1.0, 2.0, 3.0])
        onp.testing.assert_allclose(got[1], [0.0, 0.0, 0.0])  # unk
        emb.update_token_vectors("hello", mx.nd.array([7.0, 7.0, 7.0]))
        onp.testing.assert_allclose(
            emb.get_vecs_by_tokens("hello").asnumpy(), [7.0, 7.0, 7.0])

    def test_registry_and_composite(self, tmp_path):
        path = self._write_vectors(tmp_path)
        emb = text.create("customembedding", pretrained_file_path=path)
        assert isinstance(emb, text.CustomEmbedding)
        with pytest.raises(MXNetError, match="offline"):
            text.create("glove")
        assert text.get_pretrained_file_names() == {}

        vocab = text.Vocabulary(Counter({"hello": 2, "world": 1}))
        comp = text.CompositeEmbedding(vocab, [emb, emb])
        assert comp.vec_len == 6
        onp.testing.assert_allclose(
            comp.get_vecs_by_tokens("world").asnumpy(),
            [4.0, 5.0, 6.0, 4.0, 5.0, 6.0])

    def test_embedding_feeds_gluon(self, tmp_path):
        from mxnet_tpu.gluon import nn

        emb = text.CustomEmbedding(self._write_vectors(tmp_path))
        layer = nn.Embedding(len(emb), emb.vec_len)
        layer.initialize()
        layer.weight.set_data(emb.idx_to_vec)
        out = layer(mx.nd.array([1, 2], dtype="int32")).asnumpy()
        onp.testing.assert_allclose(out[0], [1.0, 2.0, 3.0])
