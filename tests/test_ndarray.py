"""NDArray semantics tests (reference: tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def test_creation():
    x = mx.nd.zeros((2, 3))
    assert x.shape == (2, 3)
    assert x.dtype == np.float32
    assert (x.asnumpy() == 0).all()
    y = mx.nd.ones((4,), dtype="int32")
    assert y.dtype == np.int32
    z = mx.nd.full((2, 2), 3.5)
    assert (z.asnumpy() == 3.5).all()
    a = mx.nd.array([[1, 2], [3, 4]], dtype="float32")
    assert a.shape == (2, 2)
    assert a.size == 4
    assert a.ndim == 2


def test_from_numpy_default_dtype():
    # float64 numpy defaults to float32 NDArray, like MXNet
    a = mx.nd.array(np.array([1.0, 2.0]))
    assert a.dtype == np.float32
    b = mx.nd.array(np.array([1, 2], dtype=np.int64))
    assert b.dtype == np.int64


def test_arithmetic():
    x = mx.nd.array([[1, 2], [3, 4]])
    y = mx.nd.array([[10, 20], [30, 40]])
    assert np.allclose((x + y).asnumpy(), [[11, 22], [33, 44]])
    assert np.allclose((y - x).asnumpy(), [[9, 18], [27, 36]])
    assert np.allclose((x * y).asnumpy(), [[10, 40], [90, 160]])
    assert np.allclose((y / x).asnumpy(), [[10, 10], [10, 10]])
    assert np.allclose((x + 1).asnumpy(), [[2, 3], [4, 5]])
    assert np.allclose((2 * x).asnumpy(), [[2, 4], [6, 8]])
    assert np.allclose((1 - x).asnumpy(), [[0, -1], [-2, -3]])
    assert np.allclose((8 / x).asnumpy(), [[8, 4], [8 / 3, 2]])
    assert np.allclose((x ** 2).asnumpy(), [[1, 4], [9, 16]])
    assert np.allclose((-x).asnumpy(), [[-1, -2], [-3, -4]])


def test_inplace_arithmetic():
    x = mx.nd.ones((2, 2))
    x += 1
    assert (x.asnumpy() == 2).all()
    x *= 3
    assert (x.asnumpy() == 6).all()
    x /= 2
    assert (x.asnumpy() == 3).all()
    x -= 1
    assert (x.asnumpy() == 2).all()


def test_comparison_ops():
    x = mx.nd.array([1, 2, 3])
    y = mx.nd.array([3, 2, 1])
    assert np.allclose((x == y).asnumpy(), [0, 1, 0])
    assert np.allclose((x != y).asnumpy(), [1, 0, 1])
    assert np.allclose((x > y).asnumpy(), [0, 0, 1])
    assert np.allclose((x >= 2).asnumpy(), [0, 1, 1])
    assert np.allclose((x < y).asnumpy(), [1, 0, 0])


def test_indexing_read():
    x = mx.nd.array(np.arange(24).reshape(2, 3, 4))
    assert np.allclose(x[0].asnumpy(), np.arange(12).reshape(3, 4))
    assert np.allclose(x[1, 2].asnumpy(), [20, 21, 22, 23])
    assert np.allclose(x[0, 1, 2].asnumpy(), 6)
    assert np.allclose(x[:, 1].asnumpy(), [[4, 5, 6, 7], [16, 17, 18, 19]])
    assert np.allclose(x[0, :, 1:3].asnumpy(), [[1, 2], [5, 6], [9, 10]])


def test_setitem():
    x = mx.nd.zeros((3, 3))
    x[1] = 1
    assert np.allclose(x.asnumpy()[1], 1)
    x[0, 2] = 5
    assert x.asnumpy()[0, 2] == 5
    x[:] = 9
    assert (x.asnumpy() == 9).all()
    x[0:2, 0:2] = mx.nd.ones((2, 2)) * 7
    assert (x.asnumpy()[:2, :2] == 7).all()


def test_view_write_through():
    # MXNet: x[i:j] returns a view; writes propagate to the base array
    x = mx.nd.array(np.arange(6).reshape(2, 3))
    v = x[0]
    v[:] = -1
    assert np.allclose(x.asnumpy()[0], -1)
    # and base writes are visible through the view
    x[0, 1] = 42
    assert v.asnumpy()[1] == 42


def test_reshape_view():
    x = mx.nd.array(np.arange(6))
    r = x.reshape(2, 3)
    assert r.shape == (2, 3)
    r[0, 0] = 99
    assert x.asnumpy()[0] == 99
    # magic reshape values (reference: matrix_op.cc::ReshapeShape)
    y = mx.nd.zeros((2, 3, 4))
    assert mx.nd.Reshape(y, shape=(0, -1)).shape == (2, 12)
    assert mx.nd.Reshape(y, shape=(-2,)).shape == (2, 3, 4)
    assert mx.nd.Reshape(y, shape=(-3, 4)).shape == (6, 4)
    assert mx.nd.Reshape(y, shape=(-4, 1, 2, -2)).shape == (1, 2, 3, 4)


def test_astype_copy():
    x = mx.nd.array([1.5, 2.5])
    y = x.astype("int32")
    assert y.dtype == np.int32
    c = x.copy()
    c[0] = 100
    assert x.asnumpy()[0] == 1.5


def test_scalar_conversions():
    x = mx.nd.array([3.5])
    assert float(x) == 3.5
    assert x.asscalar() == 3.5
    with pytest.raises(Exception):
        mx.nd.ones((2,)).asscalar()


def test_wait_and_waitall():
    x = mx.nd.ones((10, 10))
    y = x * 2
    y.wait_to_read()
    mx.nd.waitall()
    assert (y.asnumpy() == 2).all()


def test_out_kwarg():
    x = mx.nd.array([1.0, 2.0])
    out = mx.nd.zeros((2,))
    mx.nd.sqrt(x, out=out)
    assert np.allclose(out.asnumpy(), np.sqrt([1.0, 2.0]))


def test_save_load_roundtrip(tmp_path):
    fname = str(tmp_path / "test.params")
    d = {"arg:w": mx.nd.random.normal(shape=(3, 4)),
         "aux:b": mx.nd.ones((5,), dtype="int32")}
    mx.nd.save(fname, d)
    back = mx.nd.load(fname)
    assert set(back) == set(d)
    for k in d:
        assert back[k].dtype == d[k].dtype
        assert np.allclose(back[k].asnumpy(), d[k].asnumpy())
    # list save
    mx.nd.save(fname, [d["arg:w"]])
    lst = mx.nd.load(fname)
    assert isinstance(lst, list) and len(lst) == 1


def test_save_load_bfloat16(tmp_path):
    import ml_dtypes

    fname = str(tmp_path / "bf16.params")
    x = mx.nd.array(np.array([1.0, 2.0, 3.0]), dtype="bfloat16")
    mx.nd.save(fname, {"x": x})
    back = mx.nd.load(fname)["x"]
    assert back.dtype == ml_dtypes.bfloat16
    assert np.allclose(back.asnumpy().astype(np.float32), [1, 2, 3])


def test_context_movement():
    x = mx.nd.ones((2, 2), ctx=mx.cpu(0))
    assert x.context == mx.cpu(0)
    y = x.as_in_context(mx.cpu(0))
    assert y is x
    z = x.copyto(mx.cpu(0))
    assert z is not x
    assert np.allclose(z.asnumpy(), x.asnumpy())


def test_dlpack_interchange():
    import jax.numpy as jnp

    x = mx.nd.array([1.0, 2.0])
    j = jnp.from_dlpack(x)
    assert np.allclose(np.asarray(j), [1.0, 2.0])


def test_concat_split_stack():
    a = mx.nd.ones((2, 3))
    b = mx.nd.zeros((2, 3))
    c = mx.nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    parts = mx.nd.split(c, num_outputs=2, axis=0)
    assert len(parts) == 2 and np.allclose(parts[0].asnumpy(), 1)
    s = mx.nd.stack(a, b, axis=0)
    assert s.shape == (2, 2, 3)


def test_broadcast_ops():
    x = mx.nd.ones((2, 1, 3))
    y = mx.nd.ones((1, 4, 3))
    assert mx.nd.broadcast_add(x, y).shape == (2, 4, 3)
    assert mx.nd.broadcast_to(mx.nd.ones((1, 3)), shape=(5, 3)).shape == (5, 3)
    # elemwise_add enforces strict shapes (reference semantics)
    with pytest.raises(Exception):
        mx.nd.elemwise_add(mx.nd.ones((2, 3)), mx.nd.ones((3,))).wait_to_read()


def test_take_pick_onehot():
    x = mx.nd.array(np.arange(12).reshape(3, 4))
    idx = mx.nd.array([0, 2], dtype="int32")
    assert np.allclose(mx.nd.take(x, idx).asnumpy(), [[0, 1, 2, 3], [8, 9, 10, 11]])
    picked = mx.nd.pick(x, mx.nd.array([1, 0, 3]), axis=1)
    assert np.allclose(picked.asnumpy(), [1, 4, 11])
    oh = mx.nd.one_hot(mx.nd.array([0, 2]), depth=3)
    assert np.allclose(oh.asnumpy(), [[1, 0, 0], [0, 0, 1]])


def test_reductions_match_numpy():
    a = np.random.randn(3, 4, 5).astype(np.float32)
    x = mx.nd.array(a)
    # atol for near-zero means/sums: f32 accumulation order differs
    # between XLA and numpy
    assert np.allclose(x.sum().asnumpy(), a.sum(), rtol=1e-5, atol=1e-5)
    assert np.allclose(mx.nd.sum(x, axis=1).asnumpy(), a.sum(axis=1),
                       rtol=1e-5, atol=1e-5)
    assert np.allclose(mx.nd.mean(x, axis=(0, 2)).asnumpy(),
                       a.mean(axis=(0, 2)), rtol=1e-5, atol=1e-5)
    assert np.allclose(mx.nd.max(x, axis=2, keepdims=True).asnumpy(),
                       a.max(axis=2, keepdims=True))
    assert np.allclose(mx.nd.norm(x).asnumpy(), np.linalg.norm(a.ravel()),
                       rtol=1e-5, atol=1e-6)
    # exclude semantics
    assert np.allclose(mx.nd.sum(x, axis=1, exclude=True).asnumpy(),
                       a.sum(axis=(0, 2)), rtol=1e-5, atol=1e-5)


def test_dot():
    a = np.random.randn(3, 4).astype(np.float32)
    b = np.random.randn(4, 5).astype(np.float32)
    assert np.allclose(mx.nd.dot(mx.nd.array(a), mx.nd.array(b)).asnumpy(),
                       a @ b, rtol=1e-4, atol=1e-5)
    # transpose flags
    assert np.allclose(
        mx.nd.dot(mx.nd.array(a), mx.nd.array(b.T), transpose_b=True).asnumpy(),
        a @ b, rtol=1e-4, atol=1e-5)
    # batch_dot
    x = np.random.randn(2, 3, 4).astype(np.float32)
    y = np.random.randn(2, 4, 5).astype(np.float32)
    assert np.allclose(mx.nd.batch_dot(mx.nd.array(x), mx.nd.array(y)).asnumpy(),
                       x @ y, rtol=1e-4, atol=1e-5)


def test_bfloat16_matmul():
    # TPU-first: bf16 is a first-class dtype
    x = mx.nd.ones((4, 4), dtype="bfloat16")
    y = mx.nd.dot(x, x)
    assert str(y.dtype) == "bfloat16"
    assert np.allclose(y.asnumpy().astype(np.float32), 4.0)


def test_attach_grad_detach():
    x = mx.nd.ones((2,))
    x.attach_grad()
    assert x.grad is not None and (x.grad.asnumpy() == 0).all()
    d = x.detach()
    assert getattr(d, "_grad_req") == "null"


def test_iter_len():
    x = mx.nd.array([[1, 2], [3, 4], [5, 6]])
    assert len(x) == 3
    rows = [r.asnumpy() for r in x]
    assert len(rows) == 3 and np.allclose(rows[2], [5, 6])


class TestLinalgTail:
    """Round-4 linalg long tail (reference: la_op.cc gelqf/syevd/potri/
    trmm/sumlogdiag/... kernels) vs the numpy oracle."""

    def _spd(self, n=4, seed=0):
        rs = np.random.RandomState(seed)
        m = rs.randn(n, n).astype("float32")
        return m @ m.T + n * np.eye(n, dtype="float32")

    def test_gelqf_reconstructs(self):
        rs = np.random.RandomState(1)
        a = rs.randn(3, 5).astype("float32")
        L, Q = mx.nd.linalg_gelqf(mx.nd.array(a))
        l, q = L.asnumpy(), Q.asnumpy()
        np.testing.assert_allclose(l @ q, a, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(q @ q.T, np.eye(3), rtol=1e-4,
                                    atol=1e-5)
        assert np.allclose(l, np.tril(l), atol=1e-5)

    def test_syevd_reconstructs(self):
        a = self._spd()
        U, L = mx.nd.linalg_syevd(mx.nd.array(a))
        u, lam = U.asnumpy(), L.asnumpy()
        np.testing.assert_allclose(u.T @ np.diag(lam) @ u, a, rtol=1e-3,
                                    atol=1e-3)
        np.testing.assert_allclose(u @ a @ u.T, np.diag(lam), rtol=1e-3,
                                    atol=1e-3)

    def test_potri_matches_inverse(self):
        a = self._spd(seed=2)
        chol = np.linalg.cholesky(a).astype("float32")
        got = mx.nd.linalg_potri(mx.nd.array(chol)).asnumpy()
        np.testing.assert_allclose(got, np.linalg.inv(a), rtol=1e-2,
                                    atol=1e-3)

    def test_trmm_sumlogdiag_diag_ops(self):
        rs = np.random.RandomState(3)
        a = np.tril(rs.randn(4, 4)).astype("float32")
        b = rs.randn(4, 4).astype("float32")
        np.testing.assert_allclose(
            mx.nd.linalg_trmm(mx.nd.array(a), mx.nd.array(b),
                              alpha=2.0).asnumpy(),
            2.0 * a @ b, rtol=1e-5)
        spd = self._spd(seed=4)
        chol = np.linalg.cholesky(spd).astype("float32")
        np.testing.assert_allclose(
            float(mx.nd.linalg_sumlogdiag(mx.nd.array(chol)).asnumpy()),
            np.log(np.diag(chol)).sum(), rtol=1e-5)
        np.testing.assert_allclose(
            mx.nd.linalg_extractdiag(mx.nd.array(b)).asnumpy(),
            np.diag(b), rtol=1e-6)
        v = rs.randn(4).astype("float32")
        np.testing.assert_allclose(
            mx.nd.linalg_makediag(mx.nd.array(v)).asnumpy(), np.diag(v),
            rtol=1e-6)

    def test_det_inverse_slogdet(self):
        a = self._spd(seed=5)
        np.testing.assert_allclose(
            float(mx.nd.linalg_det(mx.nd.array(a)).asnumpy()),
            np.linalg.det(a), rtol=1e-3)
        np.testing.assert_allclose(
            mx.nd.linalg_inverse(mx.nd.array(a)).asnumpy(),
            np.linalg.inv(a), rtol=1e-2, atol=1e-4)
        sign, logdet = mx.nd.linalg_slogdet(mx.nd.array(a))
        ws, wl = np.linalg.slogdet(a)
        assert float(sign.asnumpy()) == ws
        np.testing.assert_allclose(float(logdet.asnumpy()), wl, rtol=1e-4)


class TestFluentMethodSurface:
    """Round-4: the reference's fluent method forms (x.sin(), x.sort(),
    x.broadcast_to(...)) — one forwarding layer over the op registry."""

    def test_unary_fluent_match_free_functions(self):
        a = mx.nd.array([[4.0, 1.0], [2.0, 3.0]])
        np.testing.assert_allclose(a.sin().asnumpy(),
                                    np.sin(a.asnumpy()), rtol=1e-6)
        np.testing.assert_allclose(a.sort().asnumpy(),
                                    np.sort(a.asnumpy()), rtol=1e-6)
        np.testing.assert_allclose(a.floor().asnumpy(),
                                    np.floor(a.asnumpy()))
        np.testing.assert_allclose(a.rsqrt().asnumpy(),
                                    1 / np.sqrt(a.asnumpy()), rtol=1e-6)
        assert a.zeros_like().asnumpy().sum() == 0
        assert a.relu().shape == a.sigmoid().shape == (2, 2)

    def test_shape_fluent(self):
        assert mx.nd.ones((1, 2)).broadcast_to((3, 2)).shape == (3, 2)
        assert mx.nd.ones((1, 2)).broadcast_like(
            mx.nd.zeros((3, 2))).shape == (3, 2)
        assert mx.nd.ones((4, 4)).slice_like(
            mx.nd.zeros((2, 3))).shape == (2, 3)
        parts = mx.nd.ones((2, 4)).split(num_outputs=2, axis=1)
        assert len(parts) == 2 and parts[0].shape == (2, 2)
        a = mx.nd.array([[4.0, 1.0], [2.0, 3.0]])
        np.testing.assert_allclose(
            a.pick(mx.nd.array([0.0, 1.0])).asnumpy(), [4.0, 3.0])

    def test_fluent_grads_flow(self):
        from mxnet_tpu import autograd
        a = mx.nd.array([0.3, 0.7])
        a.attach_grad()
        with autograd.record():
            loss = a.sin().sum()
        loss.backward()
        np.testing.assert_allclose(a.grad.asnumpy(),
                                    np.cos(a.asnumpy()), rtol=1e-6)
