"""Deformable conv / correlation / PSROIPooling (op long-tail,
VERDICT round-2 missing #4). Oracles: zero-offset deformable conv ==
plain Convolution; correlation at zero displacement == channel-mean
product; PSROIPooling channel routing."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_deformable_conv_zero_offset_matches_convolution():
    rs = onp.random.RandomState(0)
    x = mx.nd.array(rs.randn(2, 4, 9, 9).astype(onp.float32))
    w = mx.nd.array(rs.randn(6, 4, 3, 3).astype(onp.float32))
    off = mx.nd.array(onp.zeros((2, 2 * 9, 9, 9), onp.float32))
    ref = nd.Convolution(x, w, None, kernel=(3, 3), num_filter=6,
                         pad=(1, 1), no_bias=True)
    got = nd.contrib.DeformableConvolution(
        x, off, w, kernel=(3, 3), num_filter=6, pad=(1, 1), no_bias=True)
    onp.testing.assert_allclose(got.asnumpy(), ref.asnumpy(),
                                rtol=1e-4, atol=1e-4)


def test_deformable_conv_integer_offset_shifts_sampling():
    rs = onp.random.RandomState(1)
    x = mx.nd.array(rs.randn(1, 2, 8, 8).astype(onp.float32))
    w = mx.nd.array(onp.ones((1, 2, 1, 1), onp.float32))
    # constant (dy, dx) = (0, 1): sampling shifts one column right
    off = onp.zeros((1, 2, 8, 8), onp.float32)
    off[:, 1] = 1.0
    got = nd.contrib.DeformableConvolution(
        x, mx.nd.array(off), w, kernel=(1, 1), num_filter=1, no_bias=True)
    ref = x.asnumpy().sum(axis=1, keepdims=True)
    onp.testing.assert_allclose(got.asnumpy()[:, :, :, :-1],
                                ref[:, :, :, 1:], rtol=1e-5, atol=1e-5)
    # border samples past the edge read zero
    onp.testing.assert_allclose(got.asnumpy()[:, :, :, -1], 0.0,
                                atol=1e-6)


def test_correlation_zero_displacement_channel_mean():
    rs = onp.random.RandomState(2)
    a = mx.nd.array(rs.randn(1, 3, 6, 6).astype(onp.float32))
    b = mx.nd.array(rs.randn(1, 3, 6, 6).astype(onp.float32))
    out = nd.Correlation(a, b, kernel_size=1, max_displacement=0,
                         stride1=1, stride2=1, pad_size=0)
    want = (a.asnumpy() * b.asnumpy()).mean(axis=1, keepdims=True)
    assert out.shape == (1, 1, 6, 6)
    onp.testing.assert_allclose(out.asnumpy(), want, rtol=1e-5, atol=1e-5)


def test_correlation_displacement_volume_shape():
    rs = onp.random.RandomState(3)
    a = mx.nd.array(rs.randn(2, 4, 12, 12).astype(onp.float32))
    b = mx.nd.array(rs.randn(2, 4, 12, 12).astype(onp.float32))
    out = nd.Correlation(a, b, kernel_size=1, max_displacement=2,
                         stride1=1, stride2=1, pad_size=2)
    assert out.shape[1] == 25  # (2*2+1)^2 displacement volume


def test_psroipooling_routes_channel_groups():
    # data where channel group (gy, gx) holds the constant gy*10+gx:
    # each output bin must read ITS OWN group's constant
    ps = 3
    od = 2
    data = onp.zeros((1, od * ps * ps, 12, 12), onp.float32)
    for o in range(od):
        for gy in range(ps):
            for gx in range(ps):
                cidx = o * ps * ps + gy * ps + gx
                data[0, cidx] = gy * 10 + gx + 100 * o
    rois = mx.nd.array(onp.array([[0, 0, 0, 11, 11]], onp.float32))
    out = nd.contrib.PSROIPooling(mx.nd.array(data), rois,
                                  spatial_scale=1.0, output_dim=od,
                                  pooled_size=ps)
    got = out.asnumpy()
    assert got.shape == (1, od, ps, ps)
    for o in range(od):
        for gy in range(ps):
            for gx in range(ps):
                assert got[0, o, gy, gx] == pytest.approx(
                    gy * 10 + gx + 100 * o, abs=1e-4)


def test_deformable_conv_gradients_flow():
    import jax

    from mxnet_tpu.ops.deformable import deformable_convolution

    rs = onp.random.RandomState(4)
    x = rs.randn(1, 2, 6, 6).astype(onp.float32)
    w = rs.randn(3, 2, 3, 3).astype(onp.float32)
    off = rs.randn(1, 18, 6, 6).astype(onp.float32) * 0.3

    def loss(x, off, w):
        return (deformable_convolution(
            x, off, w, kernel=(3, 3), num_filter=3, pad=(1, 1),
            no_bias=True) ** 2).sum()

    g = jax.grad(loss, argnums=(0, 1, 2))(x, off, w)
    for gi, nm in zip(g, ("x", "off", "w")):
        assert float(onp.abs(onp.asarray(gi)).sum()) > 0, nm
