"""Multi-replica serving router (mxnet_tpu/serving/router.py +
health.py): circuit breaker cycle, least-loaded dispatch, failover
bit-identity at matched buckets, shed-vs-queue admission boundary,
hung-dispatch detection, scheduler-liveness watchdog, zero-lost-future
invariant under ``serving.replica`` faults.

Bitwise comparisons follow the test_serving.py discipline: matched
batch buckets only (the same compiled executable) — replicas share one
grid precisely so a failover cannot change a response's bits.
"""
import os
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import fault, serving, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.serving.health import (
    CLOSED, HALF_OPEN, OPEN, CircuitBreaker, Heartbeat,
)
from mxnet_tpu.serving.router import (
    FailoverExhausted, ReplicaFault, Router, ServerOverloaded,
)

pytestmark = pytest.mark.serving


def make_net(in_units=8, units=4, seed=0):
    net = nn.Dense(units, in_units=in_units)
    net.initialize()
    rs = np.random.RandomState(seed)
    net.weight.set_data(mx.nd.array(
        rs.randn(units, in_units).astype(np.float32)))
    net.bias.set_data(mx.nd.array(rs.randn(units).astype(np.float32)))
    net.hybridize()
    return net


def make_replicas(n=2, slo_ms=30, seed=0, **kw):
    return [serving.Server(make_net(seed=seed), batch_buckets=(2, 4),
                           shape_buckets=[(8,)], slo_ms=slo_ms,
                           name=f"rep{i}", **kw)
            for i in range(n)]


def traffic(n=16):
    return [np.random.RandomState(100 + i).randn(8).astype(np.float32)
            for i in range(n)]


def single_replica_reference(xs):
    """The bit-identity oracle: one Server over the same grid."""
    srv = serving.Server(make_net(), batch_buckets=(2, 4),
                         shape_buckets=[(8,)], slo_ms=30).start()
    try:
        return [srv.submit(x).result(timeout=30) for x in xs]
    finally:
        srv.stop()


@pytest.fixture(autouse=True)
def _fast_retry(monkeypatch):
    monkeypatch.setenv("MXNET_COMM_RETRY_DELAY", "0.01")


# ---------------------------------------------------------------------------
# health.py: CircuitBreaker + Heartbeat units
# ---------------------------------------------------------------------------

class TestCircuitBreaker:
    def _brk(self, **kw):
        self.now = [0.0]
        kw.setdefault("failure_threshold", 3)
        kw.setdefault("cooldown_s", 1.0)
        return CircuitBreaker("b", time_fn=lambda: self.now[0], **kw)

    def test_closed_admits_and_failures_below_threshold_stay_closed(self):
        b = self._brk()
        assert b.state == CLOSED and b.admit()
        b.record_failure()
        b.record_failure()
        assert b.state == CLOSED and b.admit()
        b.record_success()          # success resets the streak
        b.record_failure()
        b.record_failure()
        assert b.state == CLOSED

    def test_threshold_trips_open_and_open_refuses(self):
        b = self._brk()
        for _ in range(3):
            b.record_failure()
        assert b.state == OPEN and not b.admit()
        assert b.n_trips == 1

    def test_open_half_open_close_cycle(self):
        b = self._brk()
        for _ in range(3):
            b.record_failure()
        self.now[0] = 0.5
        assert not b.admit()                 # cooldown not elapsed
        self.now[0] = 1.01
        assert b.state == HALF_OPEN
        assert b.admit()                     # THE probe
        assert not b.admit()                 # only one probe at a time
        b.record_success()
        assert b.state == CLOSED and b.admit()
        assert b.describe()["cooldown_s"] == 1.0   # streak reset

    def test_probe_failure_reopens_with_doubled_cooldown(self):
        b = self._brk()
        for _ in range(3):
            b.record_failure()
        self.now[0] = 1.01
        assert b.admit()
        b.record_failure()                   # probe failed
        assert b.state == OPEN and b.n_trips == 2
        self.now[0] = 2.5                    # 1.01 + 1.49 < 2x cooldown
        assert b.state == OPEN
        self.now[0] = 3.02                   # past the doubled cooldown
        assert b.state == HALF_OPEN

    def test_hang_trips_immediately(self):
        b = self._brk()
        b.record_hang()
        assert b.state == OPEN and b.n_trips == 1

    def test_release_probe_frees_the_slot(self):
        b = self._brk()
        b.record_hang()
        self.now[0] = 1.01
        assert b.admit() and not b.admit()
        b.release_probe()
        assert b.admit()

    def test_late_failure_while_open_is_ignored(self):
        b = self._brk()
        b.record_hang()
        b.record_failure()                   # late verdict, no new trip
        assert b.n_trips == 1

    def test_validation(self):
        with pytest.raises(MXNetError, match="threshold"):
            CircuitBreaker(failure_threshold=0)
        with pytest.raises(MXNetError, match="cooldown"):
            CircuitBreaker(cooldown_s=0)


def test_heartbeat_staleness():
    hb = Heartbeat()
    assert not hb.stale(0.2)
    time.sleep(0.25)
    assert hb.stale(0.2)
    hb.touch()
    assert not hb.stale(0.2)


# ---------------------------------------------------------------------------
# Router construction / validation
# ---------------------------------------------------------------------------

class TestRouterValidation:
    def test_needs_replicas(self):
        with pytest.raises(MXNetError, match="at least one"):
            Router([])

    def test_grids_must_match(self):
        a = serving.Server(make_net(), batch_buckets=(2, 4),
                           shape_buckets=[(8,)], name="a")
        b = serving.Server(make_net(), batch_buckets=(2, 8),
                           shape_buckets=[(8,)], name="b")
        with pytest.raises(MXNetError, match="different bucket grid"):
            Router([a, b])

    def test_names_must_be_unique(self):
        a = serving.Server(make_net(), batch_buckets=(2,),
                           shape_buckets=[(8,)], name="same")
        b = serving.Server(make_net(), batch_buckets=(2,),
                           shape_buckets=[(8,)], name="same")
        with pytest.raises(MXNetError, match="unique"):
            Router([a, b])

    def test_knob_validation(self):
        rep = make_replicas(1)
        with pytest.raises(MXNetError, match="max_queue"):
            Router(rep, max_queue=0)
        with pytest.raises(MXNetError, match="retry_budget"):
            Router(rep, retry_budget=-1)
        with pytest.raises(MXNetError, match="dispatch timeout"):
            Router(rep, dispatch_timeout_s=0.05)
        with pytest.raises(MXNetError, match="watchdog"):
            Router(rep, watchdog_timeout_s=0)

    def test_submit_rejects_unfit_shape_synchronously(self):
        with Router(make_replicas(2), slo_ms=100) as router:
            with pytest.raises(MXNetError, match="no shape bucket"):
                router.submit(np.zeros((9,), np.float32))

    def test_submit_when_stopped_raises(self):
        router = Router(make_replicas(2))
        with pytest.raises(MXNetError, match="not running"):
            router.submit(np.zeros((8,), np.float32))


# ---------------------------------------------------------------------------
# routing: results, bit-identity, least-loaded spread
# ---------------------------------------------------------------------------

class TestRouting:
    def test_results_bit_identical_to_single_replica(self):
        xs = traffic(24)
        refs = single_replica_reference(xs)
        with Router(make_replicas(3), slo_ms=100) as router:
            futs = [router.submit(x) for x in xs]
            outs = [f.result(timeout=30) for f in futs]
        assert all(np.array_equal(a, b) for a, b in zip(outs, refs))

    def test_load_spreads_across_replicas(self):
        xs = traffic(48)
        with Router(make_replicas(2, slo_ms=10), slo_ms=100) as router:
            futs = [router.submit(x) for x in xs]
            for f in futs:
                f.result(timeout=30)
            served = [r["ok"] for r in router.stats()["replicas"]]
        assert all(n > 0 for n in served), served
        assert sum(served) == len(xs)

    def test_context_manager_and_stats(self):
        with Router(make_replicas(2), slo_ms=100) as router:
            router.submit(traffic(1)[0]).result(timeout=30)
            st = router.stats()
            assert st["running"] and st["ok"] == 1 and not st["wedged"]
        assert not router.is_running
        assert serving.live_routers() == []

    def test_stop_no_drain_fails_queued_typed(self):
        # wedge both replicas so submissions stay queued at the router
        # long enough to be failed by stop(drain=False)
        with fault.inject("serving.replica=latency:0.5"):
            router = Router(make_replicas(2, warmup=False),
                            slo_ms=2000).start()
            futs = [router.submit(x) for x in traffic(6)]
            router.stop(drain=False, timeout=10)
        resolved = 0
        for f in futs:
            try:
                f.result(timeout=10)
                resolved += 1
            except MXNetError:
                resolved += 1
        assert resolved == len(futs)


# ---------------------------------------------------------------------------
# admission control: shed-vs-queue boundary
# ---------------------------------------------------------------------------

class TestAdmission:
    def test_queue_full_sheds_synchronously_typed(self):
        with fault.inject("serving.replica=latency:0.6"):
            router = Router(make_replicas(1, warmup=False), slo_ms=5000,
                            max_queue=3).start()
            try:
                for x in traffic(3):
                    router.submit(x)
                t0 = time.perf_counter()
                with pytest.raises(ServerOverloaded, match="queue full"):
                    router.submit(traffic(1)[0])
                assert time.perf_counter() - t0 < 0.1   # synchronous
                assert router.stats()["shed"] == 1
            finally:
                router.stop(drain=False, timeout=10)

    def test_below_bound_admits(self):
        with Router(make_replicas(2), slo_ms=100, max_queue=3) as router:
            assert router.submit(traffic(1)[0]).result(timeout=30) \
                is not None

    def test_predicted_wait_shed_is_typed_and_counted(self, monkeypatch):
        was = telemetry.enabled()
        telemetry.reset()
        telemetry.enable()
        try:
            with Router(make_replicas(2), slo_ms=100) as router:
                # force the saturated regime with a slow measured rate
                # (_shed_arm_pending is a property now — fleet-size
                # dependent — so patch it at the class)
                monkeypatch.setattr(Router, "_shed_arm_pending",
                                    property(lambda self: -1))
                monkeypatch.setattr(router, "_predicted_wait_locked",
                                    lambda pending: 9.9)
                with pytest.raises(ServerOverloaded,
                                   match="predicted queue wait"):
                    router.submit(traffic(1)[0])
                assert router.stats()["shed"] == 1
            text = telemetry.prom_text()
            assert 'mxnet_serving_shed_total{reason="predicted_wait"} 1' \
                in text
        finally:
            telemetry.reset()
            if not was:
                telemetry.disable()

    def test_unsaturated_burst_is_not_shed(self):
        """The predicted-wait shed only arms under saturation: a burst
        into an idle router must be admitted even when the measured
        completion rate is low (it measures demand, not capacity)."""
        with Router(make_replicas(2), slo_ms=60) as router:
            xs = traffic(16)
            futs = [router.submit(x) for x in xs]   # idle burst: all in
            for f in futs:
                f.result(timeout=30)
            time.sleep(0.1)
            futs = [router.submit(x) for x in xs]   # again, post-stats
            for f in futs:
                f.result(timeout=30)
            assert router.stats()["shed"] == 0

    def test_predicted_wait_math(self):
        router = Router(make_replicas(1), slo_ms=100)
        now = time.perf_counter()
        # 16 completions 10 ms apart ending now: rate 100/s
        router._done_ts.extend(now - 0.01 * (15 - i) for i in range(16))
        w = router._predicted_wait_locked(pending=10)
        assert 0.05 < w < 0.25, w
        # fewer than 8 recent completions: no estimate
        router._done_ts.clear()
        router._done_ts.extend([now - 0.001] * 7)
        assert router._predicted_wait_locked(pending=100) == 0.0


# ---------------------------------------------------------------------------
# failover: crash, hang, budget, zero-lost-future invariant
# ---------------------------------------------------------------------------

class TestFailover:
    def test_replica_fault_fails_over_bit_identically(self):
        xs = traffic(20)
        refs = single_replica_reference(xs)
        with Router(make_replicas(2), slo_ms=200) as router:
            with fault.inject("serving.replica.0=every:1"):
                futs = [router.submit(x) for x in xs]
                outs = [f.result(timeout=30) for f in futs]
            st = router.stats()
        assert all(np.array_equal(a, b) for a, b in zip(outs, refs))
        assert st["failovers"] > 0
        by_name = {r["name"]: r for r in st["replicas"]}
        assert by_name["rep0"]["state"] == OPEN
        assert by_name["rep0"]["trips"] >= 1

    def test_breaker_trip_evicts_queued_flights_promptly(self):
        """When a replica's breaker opens, flights still sitting in its
        BATCH QUEUE (a non-full bucket's remainder) must fail over
        immediately — not ride the sick replica's deadline-close and
        retry with no deadline left. A long SLO makes the stranding
        unmistakable: without eviction the remainder serves only at
        ~deadline-close (>= slo/2 in); with it everything resolves
        early."""
        # 16 requests: however many land on rep0 before its breaker
        # trips, they ALL end up at rep1 = four FULL 4-batches (every
        # close is bucket-full, none is deadline-keyed) — so a fast
        # finish is only possible if the trip evicts rep0's remainder
        xs = traffic(16)
        refs = single_replica_reference(xs)
        with Router(make_replicas(2, slo_ms=3000), slo_ms=3000) as router:
            with fault.inject("serving.replica.0=every:1"):
                t0 = time.perf_counter()
                futs = [router.submit(x) for x in xs]
                outs = [f.result(timeout=30) for f in futs]
                elapsed = time.perf_counter() - t0
            st = router.stats()
        assert all(np.array_equal(a, b) for a, b in zip(outs, refs))
        assert elapsed < 1.5, \
            f"remainder flights rode the tripped replica's deadline-" \
            f"close ({elapsed:.2f}s for a 3s SLO) instead of failing " \
            "over at the breaker trip"
        by_name = {r["name"]: r for r in st["replicas"]}
        assert by_name["rep0"]["state"] == OPEN

    def test_hung_replica_detected_and_failed_over(self):
        xs = traffic(12)
        refs = single_replica_reference(xs)
        router = Router(make_replicas(2), slo_ms=3000,
                        dispatch_timeout_s=0.3).start()
        try:
            with fault.inject("serving.replica.0=latency:1.2"):
                futs = [router.submit(x) for x in xs]
                outs = [f.result(timeout=30) for f in futs]
                st = router.stats()
            assert all(np.array_equal(a, b)
                       for a, b in zip(outs, refs))
            by_name = {r["name"]: r for r in st["replicas"]}
            assert by_name["rep0"]["trips"] >= 1
            time.sleep(1.3)         # let the latency sleeps drain
        finally:
            router.stop(timeout=30)

    def test_breaker_reopens_then_probe_readmits(self):
        """The full integration cycle: fault trips rep0 OPEN; after the
        cooldown a HALF_OPEN probe carries a real request; once the
        fault is cleared the probe succeeds and rep0 serves again."""
        xs = traffic(8)
        with Router(make_replicas(2, slo_ms=15), slo_ms=100) as router:
            with fault.inject("serving.replica.0=every:1"):
                futs = [router.submit(x) for x in xs]
                for f in futs:
                    f.result(timeout=30)
                by_name = {r["name"]: r
                           for r in router.stats()["replicas"]}
                assert by_name["rep0"]["state"] == OPEN
            # fault cleared; cooldown (1 s default) then probe
            deadline = time.time() + 10
            served_by_rep0 = 0
            while time.time() < deadline:
                time.sleep(0.2)
                for x in xs:
                    router.submit(x).result(timeout=30)
                by_name = {r["name"]: r
                           for r in router.stats()["replicas"]}
                if by_name["rep0"]["state"] == CLOSED and \
                        by_name["rep0"]["ok"] > 0:
                    served_by_rep0 = by_name["rep0"]["ok"]
                    break
            assert served_by_rep0 > 0, router.stats()

    def test_budget_exhaustion_is_typed_not_lost(self, monkeypatch):
        """Every replica failing persistently (breakers held open-proof
        so the budget, not the breaker, is what runs out): every future
        resolves FailoverExhausted naming the attempts — never hangs."""
        monkeypatch.setenv("MXNET_SERVING_BREAKER_FAILURES", "1000")
        xs = traffic(10)
        with Router(make_replicas(2), slo_ms=400,
                    retry_budget=1) as router:
            with fault.inject("serving.replica=every:1"):
                futs = [router.submit(x) for x in xs]
                outcomes = []
                for f in futs:
                    try:
                        f.result(timeout=30)
                        outcomes.append("ok")
                    except FailoverExhausted as e:
                        assert "retry budget 1 spent" in str(e)
                        outcomes.append("exhausted")
                    except ServerOverloaded:
                        outcomes.append("expired")
        assert len(outcomes) == len(xs)
        assert outcomes.count("exhausted") == len(xs)

    def test_all_breakers_open_expires_typed(self):
        """When every breaker trips before a request's retries, queued
        requests expire TYPED at their deadline instead of hanging on a
        fleet with no healthy replica."""
        xs = traffic(10)
        with Router(make_replicas(2), slo_ms=400,
                    retry_budget=1) as router:
            with fault.inject("serving.replica=every:1"):
                futs = [router.submit(x) for x in xs]
                outcomes = []
                for f in futs:
                    try:
                        f.result(timeout=30)
                        outcomes.append("ok")
                    except FailoverExhausted:
                        outcomes.append("exhausted")
                    except ServerOverloaded:
                        outcomes.append("expired")
        assert len(outcomes) == len(xs)
        assert "ok" not in outcomes
        assert "expired" in outcomes

    def test_replica_fault_error_is_not_retried_inside_replica(self):
        """ReplicaFault is non-transient by design: the replica's own
        serving.dispatch retry must not resurrect a killed replica —
        recovery belongs to the router."""
        assert not fault.is_transient(ReplicaFault("killed"))

    def test_route_fault_burns_budget_not_replica_health(self):
        xs = traffic(6)
        with Router(make_replicas(2), slo_ms=300) as router:
            with fault.inject("serving.route=nth:2"):
                futs = [router.submit(x) for x in xs]
                for f in futs:
                    f.result(timeout=30)
            st = router.stats()
        assert all(r["state"] == CLOSED for r in st["replicas"])
        assert st["ok"] == len(xs)

    def test_zero_lost_futures_under_mixed_chaos(self):
        """The tentpole invariant, small-scale: every submitted future
        resolves (result or typed error) under a p-fault storm."""
        xs = traffic(40)
        with Router(make_replicas(2), slo_ms=300) as router:
            with fault.inject("serving.replica=p:0.3;serving.route=p:0.1",
                              seed=7):
                futs = []
                for x in xs:
                    try:
                        futs.append(router.submit(x))
                    except ServerOverloaded:
                        pass        # synchronous shed = resolved too
                done = 0
                for f in futs:
                    try:
                        f.result(timeout=30)
                        done += 1
                    except MXNetError:
                        done += 1
        assert done == len(futs)


# ---------------------------------------------------------------------------
# scheduler-liveness watchdog
# ---------------------------------------------------------------------------

class TestWatchdog:
    def test_wedged_dispatcher_fails_futures_and_stops_admission(
            self, monkeypatch):
        wedge = threading.Event()
        router = Router(make_replicas(2), slo_ms=5000,
                        watchdog_timeout_s=0.3).start()
        try:
            monkeypatch.setattr(
                router, "_pick_replica",
                lambda: (wedge.wait(30), None)[1])
            # any enabled fault spec makes submit's inline fast path
            # stand down, so routing runs on the DISPATCHER — the
            # thread this test wedges (chaos's contract: the wedge is
            # contained by the watchdog, not exported to submitters);
            # nth:10**6 never actually fires
            with fault.inject("serving.route=nth:1000000"):
                futs = [router.submit(x) for x in traffic(3)]
                deadline = time.time() + 10
                while time.time() < deadline \
                        and not router.stats()["wedged"]:
                    time.sleep(0.05)
                assert router.stats()["wedged"]
                for f in futs:
                    with pytest.raises(MXNetError, match="watchdog"):
                        f.result(timeout=10)
                with pytest.raises(MXNetError, match="not running"):
                    router.submit(traffic(1)[0])
        finally:
            wedge.set()             # release the dispatcher thread
            router.stop(drain=False, timeout=10)

    def test_healthy_router_never_trips_watchdog(self):
        with Router(make_replicas(2), slo_ms=100,
                    watchdog_timeout_s=0.3) as router:
            time.sleep(0.8)         # idle loop touches the heartbeat
            router.submit(traffic(1)[0]).result(timeout=30)
            assert not router.stats()["wedged"]


# ---------------------------------------------------------------------------
# fault spec: dotted sub-sites
# ---------------------------------------------------------------------------

class TestSubSites:
    def test_parse_spec_accepts_replica_subsite(self):
        pols = fault.parse_spec("serving.replica.0=once")
        assert "serving.replica.0" in pols

    def test_parse_spec_still_rejects_unknown(self):
        with pytest.raises(MXNetError, match="unknown fault site"):
            fault.parse_spec("serving.replicaX=once")
        with pytest.raises(MXNetError, match="unknown fault site"):
            fault.parse_spec("bogus.site=once")
        # sub-sites exist only for families that check them, and the
        # suffix must be an instance INDEX — a name would install
        # silently and never fire
        with pytest.raises(MXNetError, match="unknown fault site"):
            fault.parse_spec("kvstore.push.0=once")
        with pytest.raises(MXNetError, match="unknown fault site"):
            fault.parse_spec("serving.replica.rep0=once")

    def test_has_policy_is_exact(self):
        with fault.inject("serving.replica.1=once"):
            assert fault.has_policy("serving.replica.1")
            assert not fault.has_policy("serving.replica")
            assert not fault.has_policy("serving.replica.0")

    def test_subsite_targets_exactly_one_replica(self):
        xs = traffic(12)
        with Router(make_replicas(2), slo_ms=200) as router:
            with fault.inject("serving.replica.1=every:1"):
                futs = [router.submit(x) for x in xs]
                for f in futs:
                    f.result(timeout=30)
            by_name = {r["name"]: r for r in router.stats()["replicas"]}
        assert by_name["rep1"]["trips"] >= 1
        assert by_name["rep0"]["trips"] == 0
        assert by_name["rep0"]["state"] == CLOSED


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------

class TestRouterTelemetry:
    def test_health_shed_failover_metrics_exported(self):
        was = telemetry.enabled()
        telemetry.reset()
        telemetry.enable()
        try:
            xs = traffic(10)
            with Router(make_replicas(2), slo_ms=200,
                        max_queue=4096) as router:
                with fault.inject("serving.replica.0=every:1"):
                    futs = [router.submit(x) for x in xs]
                    for f in futs:
                        f.result(timeout=30)
                time.sleep(0.2)     # a monitor tick publishes gauges
                text = telemetry.prom_text()
            assert 'mxnet_serving_replica_healthy{replica="rep0"} 0' \
                in text
            assert 'mxnet_serving_replica_healthy{replica="rep1"} 1' \
                in text
            assert "mxnet_serving_failover_total" in text
            assert "mxnet_serving_route_retry_total" in text
            assert "mxnet_serving_breaker_transitions_total" in text
            assert "mxnet_serving_router_queue_wait_seconds" in text
        finally:
            telemetry.reset()
            if not was:
                telemetry.disable()
