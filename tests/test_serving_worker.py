"""Out-of-process serving (mxnet_tpu/serving/{wire,worker,remote,
ingress}.py + the scrape-fed control plane): frame protocol safety
(half-written frames discarded, never mis-parsed), ingress backpressure
as synchronous typed error frames, crash-isolated replica workers
(connection drop / waitpid = typed failure + breaker trip + respawn +
half-open re-admission), and FleetController decisions fed from
/metrics scrapes.

Worker-process semantics are covered two ways: a protocol-faithful
FAKE worker (a thread speaking the wire protocol through the
``RemoteReplica._spawn`` seam — every failure mode, no interpreter
spawn cost) for the tier-1 suite, and one real-subprocess end-to-end
test marked ``slow`` (``tools/chaos_check.py`` gate 8 exercises the
real thing under traffic).
"""
import os
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import fault, serving, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.serving import wire
from mxnet_tpu.serving.health import CLOSED
from mxnet_tpu.serving.router import FailoverExhausted, ServerOverloaded

pytestmark = pytest.mark.serving

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
if FIXTURES not in sys.path:
    sys.path.insert(0, FIXTURES)

import worker_factory  # noqa: E402  (the fixtures dir is the point)


def traffic(n=16, dim=8):
    return [np.random.RandomState(100 + i).randn(dim).astype(np.float32)
            for i in range(n)]


def wait_until(pred, timeout=10.0, interval=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture(autouse=True)
def _fast_knobs(monkeypatch):
    monkeypatch.setenv("MXNET_COMM_RETRY_DELAY", "0.01")
    monkeypatch.setenv("MXNET_SERVING_BREAKER_FAILURES", "2")
    monkeypatch.setenv("MXNET_SERVING_BREAKER_COOLDOWN", "0.25")


# ---------------------------------------------------------------------------
# wire.py: framing + payload codec + typed error mapping
# ---------------------------------------------------------------------------

class TestWire:
    def test_payload_round_trip_nested(self):
        obj = {"kind": "result", "id": 7, "ok": True,
               "payload": [np.arange(6, dtype=np.float32).reshape(2, 3),
                           ("s", np.float64(2.5), None,
                            {"k": np.int32(9), "f": 1.25, "b": True})]}
        h, b = wire.encode_payload(obj)
        back = wire.decode_payload(h, b)
        arr = back["payload"][0]
        assert arr.dtype == np.float32 and \
            np.array_equal(arr, obj["payload"][0])
        tail = back["payload"][1]
        assert isinstance(tail, tuple) and tail[0] == "s"
        assert tail[1] == 2.5 and tail[2] is None
        assert tail[3]["k"] == 9 and isinstance(tail[3]["k"], np.int32)

    def test_payload_rejects_unencodable(self):
        with pytest.raises(wire.FrameError):
            wire.encode_payload({"kind": "x", "bad": object()})

    def test_frame_round_trip_over_socketpair(self):
        a, b = socket.socketpair()
        try:
            sent = {"kind": "submit", "id": 1,
                    "sample": np.ones(4, np.float32)}
            wire.send_frame(a, sent)
            got = wire.recv_frame(b)
            assert got["kind"] == "submit" and got["id"] == 1
            assert np.array_equal(got["sample"], sent["sample"])
        finally:
            a.close()
            b.close()

    def test_half_written_frame_discarded_not_misparsed(self):
        """A peer that dies mid-sendall leaves a truncated tail: the
        reader must see ConnectionClosed for it — after cleanly
        delivering every COMPLETE frame before it."""
        a, b = socket.socketpair()
        try:
            wire.send_frame(a, {"kind": "health", "age": 0.0})
            h, body = wire.encode_payload({"kind": "result", "id": 5,
                                           "ok": True, "payload": 1})
            raw = wire._HEADER.pack(wire.MAGIC, len(h), len(body)) \
                + h + body
            a.sendall(raw[: len(raw) // 2])
            a.close()
            assert wire.recv_frame(b)["kind"] == "health"
            with pytest.raises(wire.ConnectionClosed):
                wire.recv_frame(b)
        finally:
            b.close()

    def test_bad_magic_is_frame_error(self):
        a, b = socket.socketpair()
        try:
            a.sendall(b"NOPE" + b"\x00" * 8)
            with pytest.raises(wire.FrameError):
                wire.recv_frame(b)
        finally:
            a.close()
            b.close()

    def test_absurd_length_is_frame_error(self):
        a, b = socket.socketpair()
        try:
            a.sendall(struct.pack("!4sII", wire.MAGIC, 1 << 30, 1 << 30))
            with pytest.raises(wire.FrameError):
                wire.recv_frame(b)
        finally:
            a.close()
            b.close()

    def test_typed_error_mapping_round_trips(self):
        for exc, etype, back_type in (
                (ServerOverloaded("full"), "overloaded",
                 ServerOverloaded),
                (FailoverExhausted("spent"), "failover_exhausted",
                 FailoverExhausted),
                (fault.FaultInjected("s", 1), "fault_injected",
                 MXNetError),
                (MXNetError("x"), "mxnet_error", MXNetError),
                (RuntimeError("y"), "internal", MXNetError)):
            name, msg = wire.encode_error(exc)
            assert name == etype
            got = wire.decode_error(name, msg)
            assert isinstance(got, back_type)

    def test_fault_sites_registered(self):
        assert "serving.ingress" in fault.SITES
        assert "worker.spawn" in fault.SITES
        # the indexed sub-site form parses (the PR-9 contract)
        spec = fault.parse_spec("worker.spawn.0=once")
        assert "worker.spawn.0" in spec
        with pytest.raises(MXNetError):
            fault.parse_spec("kvstore.push.0=once")

    def test_writer_preserves_order_under_concurrent_senders(self):
        """The inline fast path must never reorder frames: whatever
        interleaving of inline writes and writer-thread drains happens,
        each sender thread's ids arrive in its send() order."""
        a, b = socket.socketpair()
        w = wire.FrameWriter(a, name="t-order")
        per, senders = 200, 4
        try:
            def feed(tid):
                for i in range(per):
                    w.send({"kind": "result", "id": tid * per + i,
                            "ok": True})
            ths = [threading.Thread(target=feed, args=(t,))
                   for t in range(senders)]
            for t in ths:
                t.start()
            got = {t: [] for t in range(senders)}
            rf = wire.reader(b)
            for _ in range(per * senders):
                fid = wire.recv_frame(rf)["id"]
                got[fid // per].append(fid % per)
            for t in ths:
                t.join()
            for tid in range(senders):
                assert got[tid] == list(range(per)), \
                    f"sender {tid} frames reordered"
        finally:
            w.close(flush=False, timeout=2)
            a.close()
            b.close()

    def test_poisoned_writer_raises_frame_error_not_connection_closed(
            self):
        """An unencodable payload poisons the stream; later sends must
        raise FrameError — NOT ConnectionClosed — so a worker can tell
        'parent went away' (swallow, exit clean) from 'this stream can
        never speak again' (die loud, get respawned)."""
        a, b = socket.socketpair()
        w = wire.FrameWriter(a, name="t-poison")
        try:
            with pytest.raises(wire.FrameError):
                w.send({"kind": "x", "bad": object()})
            with pytest.raises(wire.FrameError) as ei:
                w.send({"kind": "result", "id": 1, "ok": True})
            assert not isinstance(ei.value, wire.ConnectionClosed)
        finally:
            w.close(flush=False, timeout=2)
            a.close()
            b.close()

    def test_writer_never_blocks_caller_on_full_socket(self):
        """send() into a peer that is not reading must return
        immediately (inline path defers to the writer thread once the
        socket buffer fills) — the dispatcher-never-blocks contract."""
        a, b = socket.socketpair()
        for s in (a, b):
            for opt in (socket.SO_SNDBUF, socket.SO_RCVBUF):
                s.setsockopt(socket.SOL_SOCKET, opt, 4096)
        w = wire.FrameWriter(a, name="t-noblock")
        try:
            payload = {"kind": "submit", "id": 0,
                       "sample": np.zeros(8192, np.float32)}
            t0 = time.monotonic()
            for i in range(16):     # ~0.5 MB >> the 4 KB buffers
                w.send(dict(payload, id=i))
            assert time.monotonic() - t0 < 1.0, \
                "send() blocked on a full socket buffer"
            # and the frames all arrive intact once the peer reads
            rf = wire.reader(b)
            ids = sorted(wire.recv_frame(rf)["id"] for _ in range(16))
            assert ids == list(range(16))
        finally:
            w.close(flush=False, timeout=2)
            a.close()
            b.close()


# ---------------------------------------------------------------------------
# ingress.py: socket edge over an in-process router
# ---------------------------------------------------------------------------

def make_router(n=2, slo_ms=50, **kw):
    reps = [serving.Server(worker_factory.tiny_net(),
                           batch_buckets=(2, 4), shape_buckets=[(8,)],
                           slo_ms=slo_ms, name=f"rep{i}", **kw)
            for i in range(n)]
    return serving.Router(reps, slo_ms=slo_ms).start()


def make_paced_router(dispatch_ms=60.0, slo_ms=2000):
    srv = serving.Server(worker_factory.paced_block(dispatch_ms),
                         batch_buckets=(2,), shape_buckets=[(8,)],
                         slo_ms=slo_ms, warmup=False, name="paced0")
    return serving.Router([srv], slo_ms=slo_ms).start()


class TestIngress:
    def test_results_bit_identical_through_the_socket(self):
        router = make_router()
        try:
            with serving.Ingress(router, window=64) as ing, \
                    serving.IngressClient("127.0.0.1", ing.port) as cli:
                xs = traffic(12)
                outs = [cli.submit(x).result(timeout=15) for x in xs]
                refs = [router.submit(x).result(timeout=15) for x in xs]
                assert all(np.array_equal(a, b)
                           for a, b in zip(outs, refs))
        finally:
            router.stop(timeout=30)

    def test_window_backpressure_is_typed_and_synchronous(self):
        """A submit past the per-connection window must come back as a
        typed ServerOverloaded error frame IMMEDIATELY — while the
        window's own requests are still in flight — not as a timeout
        or a dropped connection."""
        router = make_paced_router(dispatch_ms=120.0)
        try:
            with serving.Ingress(router, window=2) as ing, \
                    serving.IngressClient("127.0.0.1", ing.port) as cli:
                xs = traffic(3)
                f1, f2 = cli.submit(xs[0]), cli.submit(xs[1])
                # both window slots taken (in flight at the ingress)
                time.sleep(0.05)
                t0 = time.perf_counter()
                f3 = cli.submit(xs[2])
                with pytest.raises(ServerOverloaded):
                    f3.result(timeout=5)
                dt = time.perf_counter() - t0
                assert dt < 0.1, \
                    f"overload frame took {dt:.3f}s (not synchronous)"
                assert not f1.done(), \
                    "window requests should still be in flight"
                assert f1.result(timeout=15) is not None
                assert f2.result(timeout=15) is not None
        finally:
            router.stop(timeout=30)

    def test_router_shed_maps_to_typed_overload_frame(self):
        """The Router's own synchronous admission shed (queue full)
        crosses the wire as the same typed ServerOverloaded."""
        router = make_paced_router(dispatch_ms=120.0)
        router.max_queue = 1
        try:
            with serving.Ingress(router, window=32) as ing, \
                    serving.IngressClient("127.0.0.1", ing.port) as cli:
                xs = traffic(6)
                futs = [cli.submit(x) for x in xs]
                outcomes = []
                for f in futs:
                    try:
                        f.result(timeout=20)
                        outcomes.append("ok")
                    except ServerOverloaded:
                        outcomes.append("shed")
                assert "shed" in outcomes
                assert all(o in ("ok", "shed") for o in outcomes)
        finally:
            router.stop(timeout=30)

    def test_client_disconnect_mid_request_ingress_survives(self):
        router = make_paced_router(dispatch_ms=100.0)
        try:
            ing = serving.Ingress(router, window=8).start()
            try:
                cli = serving.IngressClient("127.0.0.1", ing.port)
                cli.submit(traffic(1)[0])
                cli.close()         # walk away with a request in flight
                # the edge keeps serving: a fresh connection works and
                # the abandoned request's result is discarded, not an
                # ingress crash
                with serving.IngressClient("127.0.0.1",
                                           ing.port) as cli2:
                    out = cli2.submit(traffic(1)[0]).result(timeout=15)
                    assert out is not None
                assert ing.is_running
            finally:
                ing.stop()
        finally:
            router.stop(timeout=30)

    def test_ingress_stop_resolves_client_futures_typed(self):
        router = make_paced_router(dispatch_ms=150.0)
        try:
            ing = serving.Ingress(router, window=8).start()
            cli = serving.IngressClient("127.0.0.1", ing.port)
            futs = [cli.submit(x) for x in traffic(2)]
            ing.stop()
            for f in futs:
                with pytest.raises(MXNetError):   # IngressDisconnected
                    f.result(timeout=5)           # typed, never a hang
            cli.close()
        finally:
            router.stop(timeout=30)

    def test_garbage_stream_closes_connection_only(self):
        router = make_router()
        try:
            with serving.Ingress(router, window=8) as ing:
                raw = socket.create_connection(("127.0.0.1", ing.port))
                raw.sendall(b"\xde\xad\xbe\xef" * 8)
                raw.close()
                # a second, half-written-frame client
                raw2 = socket.create_connection(("127.0.0.1", ing.port))
                h, b = wire.encode_payload(
                    {"kind": "submit", "id": 1,
                     "sample": np.ones(8, np.float32)})
                partial = wire._HEADER.pack(wire.MAGIC, len(h),
                                            len(b)) + h
                raw2.sendall(partial[: len(partial) - 4])
                raw2.close()
                # the edge survives both and keeps serving
                with serving.IngressClient("127.0.0.1",
                                           ing.port) as cli:
                    assert cli.submit(traffic(1)[0]).result(
                        timeout=15) is not None
        finally:
            router.stop(timeout=30)

    def test_ingress_fault_site_rejects_typed(self):
        router = make_router()
        try:
            with serving.Ingress(router, window=8) as ing, \
                    serving.IngressClient("127.0.0.1", ing.port) as cli:
                with fault.inject("serving.ingress=once"):
                    f1 = cli.submit(traffic(1)[0])
                    with pytest.raises(MXNetError):
                        f1.result(timeout=5)
                    assert cli.submit(traffic(2)[1]).result(
                        timeout=15) is not None
                assert ing.n_rejected >= 1
        finally:
            router.stop(timeout=30)

    def test_ingress_metrics_exported(self):
        telemetry.enable()
        try:
            telemetry.reset()
            router = make_paced_router(dispatch_ms=60.0)
            try:
                with serving.Ingress(router, window=1) as ing, \
                        serving.IngressClient("127.0.0.1",
                                              ing.port) as cli:
                    f1 = cli.submit(traffic(1)[0])
                    time.sleep(0.03)
                    f2 = cli.submit(traffic(2)[1])   # past the window
                    with pytest.raises(ServerOverloaded):
                        f2.result(timeout=5)
                    f1.result(timeout=15)
                    txt = telemetry.prom_text()
                    assert 'mxnet_ingress_connections{state="open"}' \
                        in txt
                    assert 'mxnet_ingress_rejected_total' \
                        '{reason="window_full"} 1' in txt
                    assert 'mxnet_ingress_requests_total' \
                        '{outcome="ok"}' in txt
            finally:
                router.stop(timeout=30)
        finally:
            telemetry.disable()
            telemetry.reset()


# ---------------------------------------------------------------------------
# remote.py against a protocol-faithful fake worker (the _spawn seam)
# ---------------------------------------------------------------------------

class FakeProc:
    """Stand-in for subprocess.Popen: poll/wait/terminate/kill backed
    by an Event, so waitpid semantics are testable without an exec."""

    _next_pid = [50000]

    def __init__(self):
        self._rc = None
        self._done = threading.Event()
        FakeProc._next_pid[0] += 1
        self.pid = FakeProc._next_pid[0]
        self.on_terminate = None

    def poll(self):
        return self._rc

    def wait(self, timeout=None):
        if not self._done.wait(timeout):
            raise subprocess.TimeoutExpired("fake-worker", timeout)
        return self._rc

    def exit(self, rc):
        if self._rc is None:
            self._rc = rc
            self._done.set()

    def terminate(self):
        if self.on_terminate is not None:
            self.on_terminate()
        self.exit(-15)

    kill = terminate


class FakeWorker:
    """A thread speaking the worker wire protocol. ``mode``:
    ``"echo"`` serves ``sample * 2``; ``"drop_after_submit"`` closes
    the connection (no result) on the first submit;
    ``"torn_frame_after_submit"`` writes HALF a result frame then
    dies; ``"hold"`` accepts submits and never answers (hung worker:
    health frames keep flowing with a growing scheduler age)."""

    def __init__(self, rep, mode="echo"):
        self.rep = rep
        self.mode = mode
        self.proc = FakeProc()
        self.stop_health = threading.Event()

    def spawn(self, port):
        threading.Thread(target=self._run, args=(port,),
                         daemon=True).start()
        return self.proc

    def _run(self, port):
        sock = wire.connect("127.0.0.1", port, timeout=10)
        self.proc.on_terminate = sock.close
        send_lock = threading.Lock()
        grid = self.rep.grid
        t_start = time.monotonic()

        def send(frame):
            with send_lock:
                wire.send_frame(sock, frame)

        send({"kind": "hello", "name": self.rep.name,
              "pid": self.proc.pid,
              "batch_buckets": list(grid.batch_buckets),
              "shape_buckets": [list(s) for s in grid.shape_buckets]
              if grid.shape_buckets else None,
              "slo_ms": self.rep.slo_s * 1e3, "metrics_port": None})

        def health_loop():
            while not self.stop_health.wait(0.02):
                age = (time.monotonic() - t_start
                       if self.mode == "hold" else 0.0)
                try:
                    send({"kind": "health", "age": age,
                          "queue_depth": 0, "requests": 0,
                          "batches": 0, "errors": 0})
                except OSError:
                    return

        threading.Thread(target=health_loop, daemon=True).start()
        try:
            while True:
                frame = wire.recv_frame(sock)
                if frame["kind"] == "submit":
                    if self.mode == "drop_after_submit":
                        sock.close()
                        self.proc.exit(-9)
                        return
                    if self.mode == "torn_frame_after_submit":
                        h, b = wire.encode_payload(
                            {"kind": "result", "id": frame["id"],
                             "ok": True,
                             "payload": np.ones(64, np.float32)})
                        raw = wire._HEADER.pack(
                            wire.MAGIC, len(h), len(b)) + h + b
                        with send_lock:
                            sock.sendall(raw[: len(raw) // 2])
                            sock.close()
                        self.proc.exit(-9)
                        return
                    if self.mode == "hold":
                        continue
                    send({"kind": "result", "id": frame["id"],
                          "ok": True,
                          "payload": frame["sample"] * 2})
                elif frame["kind"] == "stop":
                    send({"kind": "bye"})
                    sock.close()
                    self.proc.exit(0)
                    return
        except (wire.FrameError, OSError):
            self.proc.exit(self.proc._rc if self.proc._rc is not None
                           else -9)
        finally:
            self.stop_health.set()


def fake_remote(mode="echo", name="w0", respawn=True, **kw):
    """A RemoteReplica whose spawns produce FakeWorkers (list of all
    incarnations returned for inspection)."""
    kw.setdefault("batch_buckets", (2, 4))
    kw.setdefault("shape_buckets", [(8,)])
    kw.setdefault("slo_ms", 50)
    kw.setdefault("respawn_backoff_s", 0.05)
    rep = serving.RemoteReplica("worker_factory:tiny_net", name=name,
                                python_paths=[FIXTURES],
                                respawn=respawn, **kw)
    incarnations = []

    def spawn(port):
        w = FakeWorker(rep, mode=mode)
        incarnations.append(w)
        return w.spawn(port)

    rep._spawn = spawn
    return rep, incarnations


class TestRemoteReplica:
    def test_submit_resolves_through_fake_worker(self):
        rep, _ = fake_remote()
        rep.start()
        try:
            x = traffic(1)[0]
            out = rep.submit(x).result(timeout=10)
            assert np.array_equal(out, x * 2)
            assert rep.is_running and rep.crash_count == 0
        finally:
            rep.stop()
        assert not rep.is_running

    def test_connection_drop_mid_request_resolves_typed(self):
        rep, _ = fake_remote(mode="drop_after_submit", respawn=False)
        rep.start()
        try:
            fut = rep.submit(traffic(1)[0])
            with pytest.raises(serving.WorkerCrashed):
                fut.result(timeout=10)      # typed, never a hang
            wait_until(lambda: not rep.is_running, 5,
                       msg="handle marks worker down")
            assert rep.crash_count == 1
            with pytest.raises(MXNetError):
                rep.submit(traffic(1)[0])   # down = synchronous typed
        finally:
            rep.stop()

    def test_half_written_result_frame_is_discarded(self):
        """A worker that dies mid-result leaves a torn frame: the
        request resolves WorkerCrashed — it must never resolve with a
        mis-parsed payload."""
        rep, _ = fake_remote(mode="torn_frame_after_submit",
                             respawn=False)
        rep.start()
        try:
            fut = rep.submit(traffic(1)[0])
            with pytest.raises(serving.WorkerCrashed):
                fut.result(timeout=10)
            assert rep.crash_count == 1
        finally:
            rep.stop()

    def test_waitpid_detects_death_without_socket_close(self):
        """The second unambiguous signal: the process is reaped while
        the socket happens to stay open (fake keeps it) — waitpid
        alone must fail the in-flight future typed."""
        rep, workers = fake_remote(mode="hold", respawn=False)
        rep.start()
        try:
            fut = rep.submit(traffic(1)[0])
            workers[0].proc.exit(-9)        # reaped, socket untouched
            with pytest.raises(serving.WorkerCrashed):
                fut.result(timeout=10)
            assert rep.crash_count == 1
        finally:
            workers[0].stop_health.set()
            rep.stop()

    def test_respawn_backoff_and_restart_metric(self):
        telemetry.enable()
        try:
            telemetry.reset()
            rep, workers = fake_remote(mode="echo")
            rep.start()
            try:
                workers[0].proc.on_terminate()   # kill the connection
                workers[0].proc.exit(-9)
                wait_until(lambda: rep.is_running and
                           rep.n_restarts == 1, 10,
                           msg="respawn re-establishes the worker")
                out = rep.submit(traffic(1)[0]).result(timeout=10)
                assert out is not None
                assert 'mxnet_worker_restarts_total{replica="w0"} 1' \
                    in telemetry.prom_text()
            finally:
                rep.stop()
        finally:
            telemetry.disable()
            telemetry.reset()

    def test_respawn_budget_bounds_failed_attempts(self):
        """A permanently-broken spawn path must reach a terminal state:
        max_respawns bounds FAILED attempts, not only successes."""
        rep, workers = fake_remote(mode="echo", max_respawns=2,
                                   respawn_backoff_s=0.01)
        rep.start()
        try:
            def broken_spawn(port):
                raise RuntimeError("factory module deleted")
            rep._spawn = broken_spawn
            workers[0].proc.on_terminate()      # crash the worker
            workers[0].proc.exit(-9)
            wait_until(lambda: rep._respawner is not None and
                       not rep._respawner.is_alive(), 10,
                       msg="respawner gives up after the budget")
            assert rep.n_restarts == 0 and not rep.is_running
        finally:
            rep.stop()

    def test_rolling_upgrade_refuses_remote_fleet_typed(self):
        """rolling_upgrade over out-of-process workers must refuse
        typed BEFORE anything swaps (RemoteReplica has no in-place
        swap_model), not die with an AttributeError mid-rollout."""
        rep, _ = fake_remote()
        router = serving.Router([rep], slo_ms=50).start()
        try:
            with pytest.raises(MXNetError, match="swap_model"):
                serving.rolling_upgrade(router, lambda srv: None)
        finally:
            router.stop(drain=False, timeout=30)

    def test_spawn_fault_site_and_indexed_subsite(self):
        rep, _ = fake_remote(respawn=False)
        with fault.inject("worker.spawn=once"):
            with pytest.raises(fault.FaultInjected):
                rep.start()
        # the indexed sub-site targets exactly this worker's spawns
        rep2, _ = fake_remote(name="w1", respawn=False)
        other = f"worker.spawn.{rep2.worker_index + 1000}"
        with fault.inject(f"{other}=once"):
            rep2.start()                    # someone else's index
            rep2.stop()
        rep3, _ = fake_remote(name="w2", respawn=False)
        with fault.inject(f"worker.spawn.{rep3.worker_index}=once"):
            with pytest.raises(fault.FaultInjected):
                rep3.start()

    def test_router_failover_crash_trip_and_readmission(self):
        """The whole loop at router level: a crashed worker's in-flight
        requests fail over typed (zero lost), its breaker trips
        IMMEDIATELY on the crash signal (no failure-threshold grace),
        and the respawned worker is re-admitted via half-open probe."""
        rep, workers = fake_remote(mode="echo",
                                   respawn_backoff_s=0.05)
        sibling = serving.Server(worker_factory.tiny_net(),
                                 batch_buckets=(2, 4),
                                 shape_buckets=[(8,)], slo_ms=50,
                                 name="local0")
        router = serving.Router([rep, sibling], slo_ms=200,
                                dispatch_timeout_s=2.0).start()
        try:
            xs = traffic(8)
            futs = [router.submit(x) for x in xs]
            workers[0].proc.on_terminate()          # SIGKILL stand-in
            workers[0].proc.exit(-9)
            futs += [router.submit(x) for x in xs]
            resolved = 0
            for f in futs:
                try:
                    f.result(timeout=20)
                    resolved += 1
                except MXNetError:
                    resolved += 1           # typed counts as resolved
            assert resolved == len(futs)    # zero lost futures
            wait_until(lambda: {r["name"]: r for r in
                                router.stats()["replicas"]
                                }["w0"]["trips"] >= 1, 10,
                       msg="crash trips the breaker")
            # respawn + half-open probe re-admission under traffic
            ok0 = {r["name"]: r for r in
                   router.stats()["replicas"]}["w0"]["ok"]

            def readmitted():
                try:
                    router.submit(traffic(1)[0]).result(timeout=5)
                except MXNetError:
                    pass
                st = {r["name"]: r
                      for r in router.stats()["replicas"]}["w0"]
                return st["state"] == CLOSED and st["ok"] > ok0
            wait_until(readmitted, 20,
                       msg="respawned worker re-admitted by probe")
        finally:
            router.stop(drain=False, timeout=30)


# ---------------------------------------------------------------------------
# scrape-fed control plane
# ---------------------------------------------------------------------------

class TestScrapeFedController:
    def test_scrape_signals_read_router_gauges(self):
        telemetry.enable()
        try:
            telemetry.reset()
            router = make_router(n=2)
            exporter = telemetry.start_exporter()
            try:
                src = serving.ScrapeFleetSignals(
                    exporter.url, slo_s=router.slo_s,
                    max_batch=router.grid.max_batch)
                wait_until(lambda: src() is not None, 10,
                           msg="router monitor publishes its gauges")
                s = src()
                assert s.n_replicas == 2
                assert s.queue_depth == 0 and s.inflight == 0
                assert s.slo_s == router.slo_s
                # a shed bumps the counter; the NEXT scrape sees the
                # delta exactly once
                telemetry.record_serving_shed("queue_full")
                s2 = src()
                assert s2.shed_delta == 1
                assert src().shed_delta == 0
            finally:
                exporter.stop()
                router.stop(timeout=30)
        finally:
            telemetry.disable()
            telemetry.reset()

    def test_failed_scrape_skips_the_tick(self):
        src = serving.ScrapeFleetSignals(
            "http://127.0.0.1:9/metrics", slo_s=0.05, max_batch=4,
            timeout_s=0.2)
        assert src() is None
        router = make_router(n=1)
        try:
            ctl = serving.FleetController(
                router, lambda i: None, signals_source=src,
                policy=serving.ScalePolicy(1, 3))
            assert ctl.tick() is None       # no data, no action
            assert ctl.n_scale_up == 0 and ctl.n_scale_failed == 0
        finally:
            router.stop(timeout=30)

    def test_scrape_fed_scale_up_then_down(self):
        """End-to-end control loop with the signal path over HTTP: the
        controller sees pressure only through /metrics scrapes, scales
        the fleet up, and scales back down after the hold window."""
        telemetry.enable()
        try:
            telemetry.reset()
            router = make_router(n=1)
            exporter = telemetry.start_exporter()
            try:
                def factory(i):
                    return serving.Server(
                        worker_factory.tiny_net(),
                        batch_buckets=(2, 4), shape_buckets=[(8,)],
                        slo_ms=50, name=f"scaled{i}")

                src = serving.ScrapeFleetSignals(
                    exporter.url, slo_s=router.slo_s,
                    max_batch=router.grid.max_batch)
                policy = serving.ScalePolicy(
                    1, 2, up_cooldown_s=0.1, down_utilization=0.5,
                    down_hold_s=0.4, down_cooldown_s=0.1)
                ctl = serving.FleetController(
                    router, factory, policy=policy,
                    signals_source=src)
                wait_until(lambda: src() is not None, 10,
                           msg="gauges published")
                # synthetic pressure: the admission controller's
                # predicted wait, surfaced ONLY through the scrape
                router.predicted_wait = lambda: 10.0
                wait_until(lambda: ctl.tick() == "up", 10, 0.05,
                           msg="scrape-fed scale-up")
                assert router.fleet_size() == 2
                router.predicted_wait = lambda: 0.0
                t0 = time.monotonic()
                wait_until(lambda: ctl.tick() == "down", 15, 0.05,
                           msg="scale-down after the hold window")
                assert time.monotonic() - t0 >= 0.3   # held, not eager
                assert router.fleet_size() == 1
            finally:
                exporter.stop()
                router.stop(timeout=30)
        finally:
            telemetry.disable()
            telemetry.reset()


# ---------------------------------------------------------------------------
# the real thing: one subprocess worker end to end (slow; chaos gate 8
# drives the full kill-under-traffic scenario)
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestRealWorkerProcess:
    def test_spawn_serve_sigkill_respawn(self):
        import signal as _signal

        rep = serving.RemoteReplica(
            "worker_factory:tiny_net", name="real0",
            batch_buckets=(2, 4), shape_buckets=[(8,)], slo_ms=50,
            python_paths=[FIXTURES], respawn_backoff_s=0.2,
            spawn_timeout_s=300)
        rep.start()
        try:
            x = traffic(1)[0]
            out = rep.submit(x).result(timeout=60)
            oracle = serving.Server(
                worker_factory.tiny_net(), batch_buckets=(2, 4),
                shape_buckets=[(8,)], slo_ms=50, name="oracle").start()
            try:
                ref = oracle.submit(x).result(timeout=60)
            finally:
                oracle.stop()
            assert np.array_equal(out, ref)

            fut = rep.submit(x)
            os.kill(rep.proc.pid, _signal.SIGKILL)
            with pytest.raises(serving.WorkerCrashed):
                fut.result(timeout=30)
            wait_until(lambda: rep.is_running, 120, 0.1,
                       msg="worker respawned")
            assert rep.n_restarts == 1
            out2 = rep.submit(x).result(timeout=60)
            assert np.array_equal(out2, ref)
        finally:
            rep.stop()
        assert rep.proc.poll() is not None

    def test_trace_propagates_across_the_process_boundary(self):
        """One traced request through a REAL worker subprocess: the
        span context rides the submit frame header, the worker's
        batch.wait/dispatch spans come home on the result frame, and
        the merged trace carries one trace_id across two pids plus the
        reconstructed wire.return leg."""
        from mxnet_tpu import tracing

        rep = serving.RemoteReplica(
            "worker_factory:tiny_net", name="traced0",
            batch_buckets=(2, 4), shape_buckets=[(8,)], slo_ms=50,
            python_paths=[FIXTURES], spawn_timeout_s=300,
            env={"MXNET_TRACING": "1"})
        tracing.reset()         # clean ring: this test counts traces
        tracing.enable()
        try:
            rep.start()
            router = serving.Router([rep], slo_ms=5000).start()
            try:
                x = traffic(1)[0]
                router.submit(x).result(timeout=60)
                wait_until(
                    lambda: any(
                        r["status"] == "ok"
                        for r in tracing.recorder().traces()),
                    30, msg="router seals the merged trace")
            finally:
                router.stop(timeout=60)
            recs = [r for r in tracing.recorder().traces()
                    if r["status"] == "ok"]
            assert len(recs) == 1
            rec = recs[0]
            spans = rec["spans"]
            names = {s["name"] for s in spans}
            # router-side stages AND worker-side stages in ONE record
            assert {"request", "router.queue", "router.attempt",
                    "batch.wait", "dispatch", "wire.return"} <= names
            pids = {s["pid"] for s in spans}
            assert len(pids) == 2, f"expected two pids, got {pids}"
            procs = {s["proc"] for s in spans}
            assert "traced0" in procs   # worker set_process_name
            assert all(s["trace_id"] == rec["trace_id"] for s in spans)
            # worker-side spans hang off the router's attempt span
            # via the wire context, not off a disconnected root
            attempt = [s for s in spans
                       if s["name"] == "router.attempt"][0]
            worker_side = [s for s in spans if s["pid"] != os.getpid()]
            assert worker_side
            assert any(s.get("parent_id") == attempt["span_id"]
                       for s in worker_side)
        finally:
            rep.stop()
            tracing.reset()
