"""Elastic multi-host runtime tests (mxnet_tpu/parallel/elastic.py,
tools/launch.py supervision, bounded kvstore barriers).

The acceptance contract this file proves:

* heartbeat expiry marks a rank dead (and only expiry — fresh ranks
  stay members), counted by ``mxnet_elastic_heartbeat_miss_total``;
* a membership epoch transition (checkpoint → teardown → re-bootstrap →
  restore) is bit-exact: the loss trajectory with dead/rejoin epochs
  forced mid-run is identical to an uninterrupted run, and the epoch id
  lands in telemetry and the bundle tag;
* a restarted worker resumes from its newest bundle (same trajectory as
  never having died) — the ``tools/chaos_check.py`` elastic gate proves
  the same through real SIGKILL + ``tools/launch.py --max-restarts``;
* the launcher supervises: fail-fast SIGTERMs siblings within the
  bounded window (even when they ignore SIGTERM), elastic mode restarts
  with bounded backoff up to ``--max-restarts``, the first failing
  rank's exit code propagates, and the exit report is structured;
* ``KVStore.barrier`` / ``_barrier_before_exit`` are bounded: a dead
  worker surfaces as a typed ``BarrierTimeoutError`` naming the site
  and the missing ranks, never an unbounded hang.
"""
import importlib.util
import os
import signal
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, fault, gluon, telemetry
from mxnet_tpu import kvstore as kv
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.kvstore.kvstore import _cross_process_barrier
from mxnet_tpu.parallel import elastic

pytestmark = pytest.mark.elastic

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _rebase_barrier_epoch_to_zero():
    """Epoch transitions re-base the kvstore barrier-sequence epoch (a
    process-wide global); reset it so tests stay order-independent."""
    yield
    kv.reset_barrier_epoch(0)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _metric_value(name, **labels):
    m = telemetry.snapshot()["metrics"].get(name)
    if not m:
        return 0.0
    for s in m.get("samples", []):
        if all(s.get("labels", {}).get(k) == v for k, v in labels.items()):
            return s["value"]
    return 0.0


def make_model(seed=3):
    mx.random.seed(seed)
    net = nn.Dense(4, in_units=8)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.05}, kvstore="tpu_sync")
    x = mx.nd.array(np.random.RandomState(0).randn(8, 8).astype(np.float32))
    y = mx.nd.array(np.random.RandomState(1).randn(8, 4).astype(np.float32))
    return net, trainer, x, y


def make_step_fn(net, trainer, x, y):
    def step_fn(step, membership):
        with autograd.record():
            loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        trainer.step(8)
        return float(loss.asnumpy())
    return step_fn


def weights_of(net):
    return {name: p.data().asnumpy()
            for name, p in net._collect_params_with_prefix().items()}


def plain_run(steps, seed=3):
    """The oracle: the same training loop with no runner at all."""
    net, trainer, x, y = make_model(seed)
    fn = make_step_fn(net, trainer, x, y)
    return [fn(s, None) for s in range(steps)], net


# ---------------------------------------------------------------------------
# heartbeat board + membership
# ---------------------------------------------------------------------------

class TestHeartbeatBoard:
    def test_register_touch_alive(self, tmp_path):
        board = elastic.HeartbeatBoard(str(tmp_path))
        board.register(0)
        board.register(3, extra={"note": "hi"})
        assert board.alive(timeout=60.0) == [0, 3]
        info = board.read(3)
        assert info["rank"] == 3 and info["pid"] == os.getpid()
        assert info["note"] == "hi" and info["host"]

    def test_stale_rank_expires(self, tmp_path):
        board = elastic.HeartbeatBoard(str(tmp_path))
        board.register(0)
        board.register(1)
        old = time.time() - 100.0
        os.utime(board.path(1), (old, old))
        assert board.alive(timeout=5.0) == [0]
        board.touch(1)          # a touch resurrects it
        assert board.alive(timeout=5.0) == [0, 1]

    def test_read_corrupt_file_is_empty_dict(self, tmp_path):
        board = elastic.HeartbeatBoard(str(tmp_path))
        with open(board.path(2), "w") as f:
            f.write("{not json")
        assert board.read(2) == {}
        assert board.read(9) == {}   # never registered


class TestMembership:
    def test_dense_rank_over_survivors(self):
        m = elastic.Membership(epoch=2, rank=1, world_size=2,
                               members=(0, 3), launch_rank=3)
        assert m.owns(1) and not m.owns(0)
        assert list(m.shard_indices(6)) == [1, 3, 5]

    def test_shard_reassignment_covers_stream(self):
        # every sample has exactly one owner at every membership
        for members in [(0, 1, 2), (0, 2), (2,)]:
            owners = []
            for dense, launch in enumerate(members):
                m = elastic.Membership(epoch=1, rank=dense,
                                       world_size=len(members),
                                       members=members,
                                       launch_rank=launch)
                owners.append({i for i in range(12) if m.owns(i)})
            assert set().union(*owners) == set(range(12))
            assert sum(len(o) for o in owners) == 12


# ---------------------------------------------------------------------------
# ElasticRunner — supervised loop, rejoin, epoch protocol
# ---------------------------------------------------------------------------

class TestElasticRunner:
    def test_run_saves_and_stops_heartbeat(self, tmp_path):
        net, trainer, x, y = make_model()
        runner = elastic.ElasticRunner(
            str(tmp_path), params=net, trainer=trainer, world_size=1,
            rank=0, save_every=2, heartbeat_interval=0.05)
        losses = runner.run(make_step_fn(net, trainer, x, y), 4)
        assert len(losses) == 4
        assert not runner.heartbeat_running()
        assert elastic.live_runners() == []
        # bundles at steps 1 and 3, tagged with the elastic epoch
        assert runner.ckpt.steps() == [3, 1]
        tag = runner.ckpt.load(3)["extra"]["elastic"]
        assert tag["epoch"] == 0 and tag["members"] == [0]

    def test_rejoin_resumes_bit_exact(self, tmp_path):
        full_losses, full_net = plain_run(8)
        # first incarnation: 4 steps, bundle per step, then "dies"
        net, trainer, x, y = make_model()
        r1 = elastic.ElasticRunner(
            str(tmp_path), params=net, trainer=trainer, world_size=1,
            rank=0, save_every=1, heartbeat_interval=0.05)
        head = r1.run(make_step_fn(net, trainer, x, y), 4)
        # restarted incarnation: WRONG init on purpose; restore must win
        net2, trainer2, x2, y2 = make_model(seed=99)
        telemetry.enable()
        try:
            restarts0 = _metric_value("mxnet_elastic_worker_restarts_total")
            r2 = elastic.ElasticRunner(
                str(tmp_path), params=net2, trainer=trainer2,
                world_size=1, rank=0, save_every=1,
                heartbeat_interval=0.05)
            r2.start()
            assert r2.resumed_from == 3 and r2.start_step == 4
            assert _metric_value(
                "mxnet_elastic_worker_restarts_total") == restarts0 + 1
            tail = r2.run(make_step_fn(net2, trainer2, x2, y2), 8)
        finally:
            telemetry.disable()
        assert head + tail == full_losses
        full_w, resumed_w = weights_of(full_net), weights_of(net2)
        assert all(np.array_equal(v, resumed_w[k])
                   for k, v in full_w.items())

    def test_epoch_transitions_dead_then_rejoin_bit_exact(self, tmp_path):
        baseline, baseline_net = plain_run(8)
        net, trainer, x, y = make_model()
        board = elastic.HeartbeatBoard(str(tmp_path))
        sib = board.register(1)
        future = time.time() + 1e6
        os.utime(sib, (future, future))       # sibling "alive"
        calls = []
        events = []
        runner = elastic.ElasticRunner(
            str(tmp_path), params=net, trainer=trainer, world_size=2,
            rank=0, heartbeat_interval=0.05, heartbeat_timeout=1.0,
            join_timeout=0.2, distributed=True,
            bootstrap_fn=lambda m: calls.append(("boot", m.world_size,
                                                 m.rank)),
            shutdown_fn=lambda: calls.append(("down",)),
            on_epoch=lambda m, rec: events.append(rec))
        inner = make_step_fn(net, trainer, x, y)

        def step_fn(step, m):
            out = inner(step, m)
            if step == 3:       # sibling dies...
                old = time.time() - 100.0
                os.utime(sib, (old, old))
            elif step == 5:     # ...and rejoins (fresh registration;
                board.register(1)   # pinned future-fresh: a real worker
                os.utime(sib, (future, future))  # would keep touching)
            return out

        telemetry.enable()
        try:
            losses = runner.run(step_fn, 8)
            epoch_gauge = _metric_value("mxnet_elastic_membership_epoch")
            miss = _metric_value("mxnet_elastic_heartbeat_miss_total",
                                 rank="1")
        finally:
            telemetry.disable()
        # two transitions: rank 1 left (world 2->1), then rejoined (1->2)
        assert [e["left"] for e in events] == [[1], []]
        assert [e["joined"] for e in events] == [[], [1]]
        assert [e["world_size"] for e in events] == [1, 2]
        assert [e["epoch"] for e in events] == [1, 2]
        assert epoch_gauge == 2.0 and miss == 1.0
        # teardown before re-bootstrap, at the right world sizes/ranks
        assert calls == [("down",), ("boot", 1, 0),
                         ("down",), ("boot", 2, 0)]
        # the whole point: epochs cost NOTHING numerically
        assert losses == baseline
        base_w, w = weights_of(baseline_net), weights_of(net)
        assert all(np.array_equal(v, w[k]) for k, v in base_w.items())
        # the transition bundle carries the new epoch + member set
        tag = runner.ckpt.load()["extra"]["elastic"]
        assert tag["epoch"] in (1, 2) and 0 in tag["members"]

    def test_degraded_world_reassigns_shards(self, tmp_path):
        net, trainer, x, y = make_model()
        board = elastic.HeartbeatBoard(str(tmp_path))
        sib = board.register(1)
        future = time.time() + 1e6
        os.utime(sib, (future, future))
        runner = elastic.ElasticRunner(
            str(tmp_path), params=net, trainer=trainer, world_size=2,
            rank=0, heartbeat_interval=0.05, heartbeat_timeout=1.0,
            join_timeout=0.2, distributed=False)
        seen = []

        def step_fn(step, m):
            seen.append((m.world_size, list(m.shard_indices(4))))
            if step == 1:
                old = time.time() - 100.0
                os.utime(sib, (old, old))
            return 0.0

        runner.run(step_fn, 4)
        # world 2: rank 0 owns [0, 2]; degraded world 1: owns all
        assert seen[0] == (2, [0, 2])
        assert seen[-1] == (1, [0, 1, 2, 3])

    def test_distributed_rejoin_handshake(self, tmp_path):
        """A restarted rank in REAL distributed mode must enter the
        SAME re-bootstrap rendezvous the survivors opened for its join:
        it waits for a committed membership that names it (the epoch
        record published before the survivors' blocking bootstrap) and
        bootstraps at that epoch — same epoch, same coordinator port."""
        import json as _json

        from mxnet_tpu.checkpoint import atomic_write

        net, trainer, x, y = make_model()
        r1 = elastic.ElasticRunner(
            str(tmp_path), params=net, trainer=trainer, world_size=2,
            rank=1, save_every=1, heartbeat_interval=0.05,
            heartbeat_timeout=1.0, join_timeout=0.1, distributed=False)
        r1.run(make_step_fn(net, trainer, x, y), 2)   # bundles @ epoch 0
        # fake the survivor (rank 0) having committed the join at epoch 3
        board = elastic.HeartbeatBoard(str(tmp_path))
        sib = board.register(0)
        os.utime(sib, (time.time() + 1e6,) * 2)
        # a stray fresh heartbeat NOT in the committed membership: the
        # rejoiner must adopt the COMMITTED set, not its alive snapshot
        # (a world-size disagreement would wedge the rendezvous)
        stray = board.register(7)
        os.utime(stray, (time.time() + 1e6,) * 2)
        atomic_write(os.path.join(str(tmp_path), "EPOCH"), _json.dumps(
            {"epoch": 3, "members": [0, 1]}).encode("utf-8"))
        boots = []
        net2, trainer2, _, _ = make_model(seed=9)
        r2 = elastic.ElasticRunner(
            str(tmp_path), params=net2, trainer=trainer2, world_size=2,
            rank=1, heartbeat_interval=0.05, heartbeat_timeout=5.0,
            join_timeout=1.0, distributed=True,
            bootstrap_fn=lambda m: boots.append(
                (m.epoch, m.world_size, m.rank)),
            shutdown_fn=lambda: None)
        r2.start()
        try:
            assert r2.resumed_from == 1
            assert boots == [(3, 2, 1)]
            assert r2.membership.members == (0, 1)
        finally:
            r2.stop()

    def test_concurrent_survivor_transitions_agree_on_epoch(self, tmp_path):
        """With >= 2 survivors, the first to transition publishes E+1;
        a survivor that reads that record must ADOPT E+1 for the same
        member set, not compute E+2 — divergent epochs derive different
        coordinator ports and wedge both re-bootstrap rendezvous."""
        from mxnet_tpu.kvstore import kvstore as kvmod

        board = elastic.HeartbeatBoard(str(tmp_path))
        for r in (0, 1, 2):
            os.utime(board.register(r), (time.time() + 1e6,) * 2)
        doomed = board.path(2)
        boots = {0: [], 1: []}
        runners = {}
        for r in (0, 1):
            runners[r] = elastic.ElasticRunner(
                str(tmp_path), world_size=3, rank=r,
                heartbeat_interval=0.05, heartbeat_timeout=1.0,
                join_timeout=0.5, distributed=True,
                bootstrap_fn=lambda m, r=r: boots[r].append(
                    (m.epoch, m.world_size, m.rank)),
                shutdown_fn=lambda: None)
            runners[r].start()
        try:
            assert runners[0].membership.members == (0, 1, 2)
            assert runners[1].membership.members == (0, 1, 2)
            old = time.time() - 100.0
            os.utime(doomed, (old, old))
            m0 = runners[0].check_membership()  # commits epoch 1
            m1 = runners[1].check_membership()  # must adopt, not take 2
        finally:
            runners[0].stop()
            runners[1].stop()
        assert m0.epoch == m1.epoch == 1
        assert m0.members == m1.members == (0, 1)
        assert (m0.rank, m0.world_size) == (0, 2)
        assert (m1.rank, m1.world_size) == (1, 2)
        # both re-bootstrapped at the SAME epoch (same derived port)
        assert boots[0] == [(1, 2, 0)] and boots[1] == [(1, 2, 1)]
        # and the barrier keyspace re-based to the committed epoch
        assert kvmod._BARRIER_EPOCH == 1

    def test_rejoiner_adopts_survivor_committed_step_and_state(
            self, tmp_path):
        """The join commit record carries the survivors' last completed
        step; a distributed rejoiner reconciles to it instead of
        replaying its own (older) bundle tail against peers that moved
        on — and it must adopt the survivors' STATE along with the step
        (the survivors checkpointed at exactly that step before
        publishing; replicated data-parallel state), else every
        allreduce would pair its stale weights with theirs."""
        import json as _json

        from mxnet_tpu.checkpoint import CheckpointManager, atomic_write

        net, trainer, x, y = make_model()
        r1 = elastic.ElasticRunner(
            str(tmp_path), params=net, trainer=trainer, world_size=2,
            rank=1, save_every=1, heartbeat_interval=0.05,
            heartbeat_timeout=1.0, join_timeout=0.1, distributed=False)
        r1.run(make_step_fn(net, trainer, x, y), 2)   # own bundles @ 0, 1
        # survivor rank 0: trained to step 9 and checkpointed there at
        # the join transition (what _transition does before publishing)
        netA, trainerA, xA, yA = make_model()
        fnA = make_step_fn(netA, trainerA, xA, yA)
        for s in range(10):
            fnA(s, None)
        surv = CheckpointManager(
            os.path.join(str(tmp_path), "ckpts"), prefix="r0")
        surv.save(9, params=netA, trainer=trainerA)
        board = elastic.HeartbeatBoard(str(tmp_path))
        os.utime(board.register(0), (time.time() + 1e6,) * 2)
        atomic_write(os.path.join(str(tmp_path), "EPOCH"), _json.dumps(
            {"epoch": 2, "members": [0, 1],
             "step": 9}).encode("utf-8"))
        net2, trainer2, _, _ = make_model(seed=9)
        r2 = elastic.ElasticRunner(
            str(tmp_path), params=net2, trainer=trainer2, world_size=2,
            rank=1, heartbeat_interval=0.05, heartbeat_timeout=5.0,
            join_timeout=1.0, distributed=True,
            bootstrap_fn=lambda m: None, shutdown_fn=lambda: None)
        r2.start()
        try:
            assert r2.adopted_step == 9 and r2.start_step == 10
            assert r2.resumed_from == 9   # the survivor's bundle won
            assert r2.membership.epoch == 2
            assert r2.membership.members == (0, 1)
            w_a, w_2 = weights_of(netA), weights_of(net2)
            assert all(np.array_equal(v, w_2[k])
                       for k, v in w_a.items())
        finally:
            r2.stop()

    def test_rejoiner_falls_back_when_commit_is_behind_it(self, tmp_path):
        """The victim can save RIGHT before dying while the survivors
        commit the join still mid-step, i.e. at a step behind the
        victim's newest bundle — reconciliation must align that
        direction too (replay from the rejoiner's OWN bundle at the
        committed step), or the schedules drift apart just the same."""
        import json as _json

        from mxnet_tpu.checkpoint import atomic_write

        net, trainer, x, y = make_model()
        r1 = elastic.ElasticRunner(
            str(tmp_path), params=net, trainer=trainer, world_size=2,
            rank=1, save_every=1, heartbeat_interval=0.05,
            heartbeat_timeout=1.0, join_timeout=0.1, distributed=False)
        r1.run(make_step_fn(net, trainer, x, y), 3)   # bundles @ 0, 1, 2
        ref_net, ref_trainer, _, _ = make_model(seed=7)
        r1.ckpt.restore(block=ref_net, trainer=ref_trainer, step=0)
        board = elastic.HeartbeatBoard(str(tmp_path))
        os.utime(board.register(0), (time.time() + 1e6,) * 2)
        atomic_write(os.path.join(str(tmp_path), "EPOCH"), _json.dumps(
            {"epoch": 2, "members": [0, 1],
             "step": 0}).encode("utf-8"))
        net2, trainer2, _, _ = make_model(seed=9)
        r2 = elastic.ElasticRunner(
            str(tmp_path), params=net2, trainer=trainer2, world_size=2,
            rank=1, heartbeat_interval=0.05, heartbeat_timeout=5.0,
            join_timeout=1.0, distributed=True,
            bootstrap_fn=lambda m: None, shutdown_fn=lambda: None)
        r2.start()
        try:
            assert r2.adopted_step == 0 and r2.start_step == 1
            assert r2.resumed_from == 0
            w_r, w_2 = weights_of(ref_net), weights_of(net2)
            assert all(np.array_equal(v, w_2[k])
                       for k, v in w_r.items())
        finally:
            r2.stop()

    def test_rejoiner_warns_when_committed_step_unreachable(
            self, tmp_path):
        """No bundle at the committed step anywhere (custom checkpoint
        layout): the step count is still adopted so the schedules
        align, but LOUDLY — silently pairing stale weights with the
        survivors' in every allreduce would be undebuggable."""
        import json as _json

        from mxnet_tpu.checkpoint import atomic_write

        net, trainer, x, y = make_model()
        r1 = elastic.ElasticRunner(
            str(tmp_path), params=net, trainer=trainer, world_size=2,
            rank=1, save_every=1, heartbeat_interval=0.05,
            heartbeat_timeout=1.0, join_timeout=0.1, distributed=False)
        r1.run(make_step_fn(net, trainer, x, y), 2)   # bundles @ 0, 1
        board = elastic.HeartbeatBoard(str(tmp_path))
        os.utime(board.register(0), (time.time() + 1e6,) * 2)
        atomic_write(os.path.join(str(tmp_path), "EPOCH"), _json.dumps(
            {"epoch": 2, "members": [0, 1],
             "step": 9}).encode("utf-8"))   # no bundle @ 9 exists
        net2, trainer2, _, _ = make_model(seed=9)
        r2 = elastic.ElasticRunner(
            str(tmp_path), params=net2, trainer=trainer2, world_size=2,
            rank=1, heartbeat_interval=0.05, heartbeat_timeout=5.0,
            join_timeout=1.0, distributed=True,
            bootstrap_fn=lambda m: None, shutdown_fn=lambda: None)
        with pytest.warns(RuntimeWarning, match="committed step 9"):
            r2.start()
        try:
            assert r2.adopted_step == 9 and r2.start_step == 10
            assert r2.resumed_from == 1   # stale state kept, loudly
        finally:
            r2.stop()

    def test_rebootstrap_honors_timeout_optout(self, tmp_path,
                                               monkeypatch):
        """MXNET_KV_BARRIER_TIMEOUT <= 0 (the documented unbounded
        opt-out) must map to the same ~24-day bound as the first
        bootstrap, not a guaranteed-to-fail 1-second fuse on the
        elastic re-bootstrap rendezvous."""
        import jax

        captured = {}
        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda **kw: captured.update(kw))
        monkeypatch.setenv("MXNET_KV_BARRIER_TIMEOUT", "0")
        monkeypatch.delenv("MXNET_KV_BOOTSTRAP_TIMEOUT", raising=False)
        monkeypatch.setenv("DMLC_PS_ROOT_PORT", "9091")
        runner = elastic.ElasticRunner(str(tmp_path), world_size=1,
                                       rank=0)
        runner.board.register(0)
        m = elastic.Membership(epoch=2, rank=0, world_size=1,
                               members=(0,), launch_rank=0)
        runner._default_bootstrap(m)
        assert captured["initialization_timeout"] == 2**31 // 1000
        assert captured["num_processes"] == 1
        # coordinator port still advances with the epoch (base + 1 + e)
        assert captured["coordinator_address"].endswith(":9094")

    def test_heartbeat_fault_site_retried(self, tmp_path):
        runner = elastic.ElasticRunner(str(tmp_path), world_size=1,
                                       rank=0)
        runner.board.register(0)
        with fault.inject("elastic.heartbeat=once") as stats:
            runner.heartbeat()     # first touch fails, retry wins
            assert stats()["elastic.heartbeat"]["injected"] == 1

    def test_rejoin_fault_site_retried(self, tmp_path):
        net, trainer, x, y = make_model()
        runner = elastic.ElasticRunner(
            str(tmp_path), params=net, trainer=trainer, world_size=1,
            rank=0, save_every=1, heartbeat_interval=0.05)
        runner.run(make_step_fn(net, trainer, x, y), 2)
        with fault.inject("elastic.rejoin=once") as stats:
            meta = runner._restore()
            assert stats()["elastic.rejoin"]["injected"] == 1
        assert meta["step"] == 1

    def test_context_manager_and_validation(self, tmp_path):
        with pytest.raises(MXNetError, match="rank"):
            elastic.ElasticRunner(str(tmp_path), world_size=2, rank=5)
        with pytest.raises(MXNetError, match="interval"):
            elastic.ElasticRunner(str(tmp_path), world_size=1, rank=0,
                                  heartbeat_interval=0.0)
        with elastic.ElasticRunner(str(tmp_path), world_size=1,
                                   rank=0) as r:
            assert r.heartbeat_running()
            assert elastic.live_runners() == [r]
        assert not r.heartbeat_running()


# ---------------------------------------------------------------------------
# bounded barriers
# ---------------------------------------------------------------------------

class TestBoundedBarrier:
    def test_local_barrier_timeout_names_site(self, monkeypatch):
        import mxnet_tpu.ndarray as ndmod

        monkeypatch.setattr(ndmod, "waitall", lambda: time.sleep(1.0))
        store = kv.create("local")
        with pytest.raises(kv.BarrierTimeoutError,
                           match=r"kvstore\.barrier\[exit\]"):
            store.barrier(site="exit", timeout=0.1)

    def test_timeout_env_knob(self, monkeypatch):
        import mxnet_tpu.ndarray as ndmod

        monkeypatch.setattr(ndmod, "waitall", lambda: time.sleep(1.0))
        monkeypatch.setenv("MXNET_KV_BARRIER_TIMEOUT", "0.1")
        store = kv.create("tpu_sync")
        with pytest.raises(kv.BarrierTimeoutError,
                           match="MXNET_KV_BARRIER_TIMEOUT"):
            store.barrier()

    def test_unbounded_optout_and_clean_pass(self, monkeypatch):
        store = kv.create("tpu_sync")
        store.barrier()                      # drains instantly: passes
        monkeypatch.setenv("MXNET_KV_BARRIER_TIMEOUT", "0")
        store.barrier(site="legacy")         # <= 0: unbounded path

    def test_cross_process_barrier_rendezvous(self):
        class Stub:
            def __init__(self):
                self.d = {}

            def key_value_set(self, k, v):
                if k in self.d:
                    raise RuntimeError(f"ALREADY_EXISTS: {k}")
                self.d[k] = v

            def key_value_dir_get(self, p):
                return [(k, v) for k, v in self.d.items()
                        if k.startswith(p)]

        c = Stub()
        c.key_value_set("mxnet_tpu/barrier/step/1/1", "1")
        assert _cross_process_barrier(c, "step", 1, 0, 2,
                                      timeout=1.0) == [0, 1]
        # re-announcing our own key (a retried attempt) is not an error
        assert _cross_process_barrier(c, "step", 1, 0, 2,
                                      timeout=1.0) == [0, 1]

    def test_cross_process_barrier_names_missing_ranks(self):
        class Stub:
            def __init__(self):
                self.d = {}

            def key_value_set(self, k, v):
                self.d[k] = v

            def key_value_dir_get(self, p):
                return [(k, v) for k, v in self.d.items()
                        if k.startswith(p)]

        with pytest.raises(kv.BarrierTimeoutError) as ei:
            _cross_process_barrier(Stub(), "exit", 4, 0, 3, timeout=0.15)
        msg = str(ei.value)
        assert "kvstore.barrier[exit]" in msg
        assert "missing ranks [1, 2]" in msg and "arrived: [0]" in msg

    def test_barrier_seq_rebases_on_elastic_epoch(self):
        """Per-site sequence numbers live in process memory, so a
        restarted rank would announce seq 1 against the survivors'
        seq k+1 forever; re-basing every rank's counters at each
        membership epoch (epoch-tagged key namespace, sequences back
        to 1) makes them meet again after a restart."""
        kv.reset_barrier_epoch(0)
        store = kv.create("tpu_sync")
        ns = store._barrier_ns
        assert store._next_barrier_seq("user") == (1, f"e0/s{ns}/")
        assert store._next_barrier_seq("user") == (2, f"e0/s{ns}/")
        assert store._next_barrier_seq("exit") == (1, f"e0/s{ns}/")
        kv.reset_barrier_epoch(4)   # what the elastic transition does
        assert store._next_barrier_seq("user") == (1, f"e4/s{ns}/")
        assert store._next_barrier_seq("exit") == (1, f"e4/s{ns}/")
        # a store created AFTER the transition (restarted rank) agrees
        fresh = kv.create("tpu_sync")
        seq, key_ns = fresh._next_barrier_seq("user")
        assert seq == 1 and key_ns.startswith("e4/")

    def test_bootstrap_timeout_mapping(self, monkeypatch):
        """<= 0 (the documented unbounded opt-out) maps to ~24 days at
        EVERY bootstrap site, and fractions round up, never to an
        instant-failure 1 s rendezvous."""
        from mxnet_tpu.kvstore.kvstore import _bootstrap_timeout_s

        monkeypatch.delenv("MXNET_KV_BOOTSTRAP_TIMEOUT", raising=False)
        monkeypatch.setenv("MXNET_KV_BARRIER_TIMEOUT", "0")
        assert _bootstrap_timeout_s() == 2**31 // 1000
        monkeypatch.setenv("MXNET_KV_BARRIER_TIMEOUT", "0.5")
        assert _bootstrap_timeout_s() == 1
        monkeypatch.setenv("MXNET_KV_BOOTSTRAP_TIMEOUT", "2.3")
        assert _bootstrap_timeout_s() == 3
        monkeypatch.setenv("MXNET_KV_BOOTSTRAP_TIMEOUT", "-1")
        assert _bootstrap_timeout_s() == 2**31 // 1000

    def test_barrier_fault_site(self):
        store = kv.create("tpu_sync")
        with fault.inject("kvstore.barrier=once"):
            with pytest.raises(fault.FaultInjected):
                store.barrier()
        store.barrier()

    def test_exit_barrier_never_wedges_or_raises(self, monkeypatch):
        store = kv.create("local")
        assert store._barrier_before_exit() is True
        import mxnet_tpu.ndarray as ndmod

        monkeypatch.setattr(ndmod, "waitall", lambda: time.sleep(1.0))
        monkeypatch.setenv("MXNET_KV_EXIT_BARRIER_TIMEOUT", "0.1")
        t0 = time.monotonic()
        with pytest.warns(RuntimeWarning, match="exit barrier"):
            assert store._barrier_before_exit() is False
        assert time.monotonic() - t0 < 5.0


# ---------------------------------------------------------------------------
# tools/launch.py supervision (subprocess smoke workers — no jax import)
# ---------------------------------------------------------------------------

def _launch_mod():
    spec = importlib.util.spec_from_file_location(
        "mxnet_tpu_test_launch",
        os.path.join(REPO_ROOT, "tools", "launch.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _run_launch(tmp_path, worker_src, n=2, extra_args=()):
    mod = _launch_mod()
    script = tmp_path / "worker.py"
    script.write_text(worker_src)
    report = tmp_path / "report.json"
    rc = mod.main(["-n", str(n), "--poll-interval", "0.02",
                   "--report", str(report),
                   "--coord-dir", str(tmp_path / "coord"),
                   *extra_args, "--", sys.executable, str(script)])
    import json

    with open(report) as f:
        return rc, json.load(f)


class TestLauncherSupervision:
    def test_clean_run_exits_zero(self, tmp_path):
        rc, rep = _run_launch(tmp_path, "import sys; sys.exit(0)\n")
        assert rc == 0 and rep["rc"] == 0
        assert all(w["final"] == 0 and w["restarts"] == 0
                   for w in rep["workers"])

    def test_fail_fast_terminates_siblings_and_propagates(self, tmp_path):
        src = (
            "import os, sys, time\n"
            "if os.environ['DMLC_WORKER_ID'] == '1':\n"
            "    time.sleep(0.1); sys.exit(7)\n"
            "time.sleep(60)\n")
        t0 = time.monotonic()
        rc, rep = _run_launch(tmp_path, src,
                              extra_args=["--term-window", "2"])
        assert rc == 7
        assert time.monotonic() - t0 < 30
        by_rank = {w["rank"]: w for w in rep["workers"]}
        assert by_rank[1]["final"] == 7
        assert by_rank[0]["exits"][-1]["signal"] == "SIGTERM"
        assert rep["mode"] == "fail_fast"

    def test_dead_worker_never_wedges_even_ignoring_sigterm(self, tmp_path):
        # rank 0 simulates "stuck in a dead collective": SIGTERM ignored
        src = (
            "import os, signal, sys, time\n"
            "if os.environ['DMLC_WORKER_ID'] == '0':\n"
            "    signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
            "    time.sleep(60)\n"
            "time.sleep(0.1); sys.exit(9)\n")
        t0 = time.monotonic()
        rc, rep = _run_launch(tmp_path, src,
                              extra_args=["--term-window", "0.5"])
        assert rc == 9
        assert time.monotonic() - t0 < 30       # SIGKILL escalation won
        by_rank = {w["rank"]: w for w in rep["workers"]}
        assert by_rank[0]["exits"][-1]["signal"] == "SIGKILL"

    def test_elastic_restart_with_backoff(self, tmp_path):
        # every rank fails its first incarnation, succeeds after restart
        src = (
            "import os, sys\n"
            "m = os.path.join(os.environ['MXNET_ELASTIC_COORD_DIR'],\n"
            "                 'm-' + os.environ['DMLC_WORKER_ID'])\n"
            "assert os.environ['MXNET_ELASTIC_RESTART'] == \\\n"
            "    ('1' if os.path.exists(m) else '0')\n"
            "if not os.path.exists(m):\n"
            "    open(m, 'w').close(); sys.exit(3)\n"
            "sys.exit(0)\n")
        rc, rep = _run_launch(
            tmp_path, src,
            extra_args=["--max-restarts", "2",
                        "--restart-backoff", "0.05"])
        assert rc == 0 and rep["mode"] == "elastic"
        assert all(w["restarts"] == 1 and w["final"] == 0
                   for w in rep["workers"])
        assert all(w["exits"][0]["exit_code"] == 3
                   for w in rep["workers"])

    def test_restart_budget_exhausted_propagates_code(self, tmp_path):
        src = "import sys; sys.exit(5)\n"
        rc, rep = _run_launch(
            tmp_path, src, n=1,
            extra_args=["--max-restarts", "1",
                        "--restart-backoff", "0.05"])
        assert rc == 5
        w = rep["workers"][0]
        assert w["restarts"] == 1 and len(w["exits"]) == 2

    def test_signal_death_maps_to_128_plus_signum(self, tmp_path):
        src = ("import os, signal\n"
               "os.kill(os.getpid(), signal.SIGKILL)\n")
        rc, rep = _run_launch(tmp_path, src, n=1)
        assert rc == 128 + int(signal.SIGKILL)
        assert rep["workers"][0]["exits"][0]["signal"] == "SIGKILL"


# ---------------------------------------------------------------------------
# preemption: graceful checkpoint-then-leave (the control plane's
# training half — spot reclaim as the common case, not a failure)
# ---------------------------------------------------------------------------

class TestPreemption:
    def _runner(self, tmp_path, net, trainer, **kw):
        kw.setdefault("save_every", 0)
        kw.setdefault("heartbeat_interval", 0.05)
        return elastic.ElasticRunner(
            str(tmp_path), params=net, trainer=trainer, world_size=1,
            rank=0, **kw)

    def test_graceful_leave_checkpoints_and_retires_heartbeat(
            self, tmp_path):
        net, trainer, x, y = make_model()
        runner = self._runner(tmp_path, net, trainer)
        fn = make_step_fn(net, trainer, x, y)

        def step_fn(step, m):
            if step == 3:
                runner.request_preemption("test notice")
            return fn(step, m)
        telemetry.enable()
        try:
            pre0 = _metric_value("mxnet_elastic_preemptions_total")
            with pytest.raises(elastic.Preempted) as ei:
                runner.run(step_fn, 8)
            assert _metric_value(
                "mxnet_elastic_preemptions_total") == pre0 + 1
        finally:
            telemetry.disable()
        # the flag is checked at the NEXT step boundary: step 3 ran to
        # completion, the leave committed it
        assert ei.value.step == 3
        assert ei.value.exit_code == elastic.PREEMPTED_EXIT_CODE == 75
        # save_every=0: the graceful-leave bundle is the ONLY bundle
        assert runner.ckpt.steps() == [3]
        # fast leave: the heartbeat file is UNLINKED, not left to
        # go stale
        assert not os.path.exists(runner.board.path(0))
        assert not runner.heartbeat_running()

    def test_preempted_resume_is_bit_exact(self, tmp_path):
        baseline, baseline_net = plain_run(8)
        net, trainer, x, y = make_model()
        r1 = self._runner(tmp_path, net, trainer)
        fn1 = make_step_fn(net, trainer, x, y)
        head = []

        def step_fn(step, m):
            loss = fn1(step, m)
            head.append(loss)
            if step == 3:
                r1.request_preemption()
            return loss
        with pytest.raises(elastic.Preempted):
            r1.run(step_fn, 8)
        # the respawned incarnation (wrong init on purpose) resumes
        # from the graceful-leave bundle
        net2, trainer2, x2, y2 = make_model(seed=99)
        r2 = self._runner(tmp_path, net2, trainer2)
        r2.start()
        assert r2.resumed_from == 3 and r2.start_step == 4
        tail = r2.run(make_step_fn(net2, trainer2, x2, y2), 8)
        assert head + tail == baseline
        full_w, resumed_w = weights_of(baseline_net), weights_of(net2)
        assert all(np.array_equal(v, resumed_w[k])
                   for k, v in full_w.items())

    def test_sigterm_handler_drives_graceful_leave(self, tmp_path):
        net, trainer, x, y = make_model()
        runner = self._runner(tmp_path, net, trainer)
        old = signal.getsignal(signal.SIGTERM)
        runner.install_preemption_handler()
        fn = make_step_fn(net, trainer, x, y)

        def step_fn(step, m):
            loss = fn(step, m)
            if step == 2:
                # the reclaim notice arrives MID-step; this step still
                # completes and the leave lands at the boundary
                os.kill(os.getpid(), signal.SIGTERM)
            return loss
        try:
            with pytest.raises(elastic.Preempted) as ei:
                runner.run(step_fn, 8)
            assert ei.value.step == 2
            assert "SIGTERM" in str(ei.value)
        finally:
            runner.stop()
        # stop() restored the previous handler
        assert signal.getsignal(signal.SIGTERM) == old

    def test_handler_rearmed_across_runner_phases(self, tmp_path):
        """run() stops the runner on the way out (restoring OS
        handlers); a one-time install_preemption_handler() must still
        cover the NEXT run() of the same runner — multi-phase training
        stays preemption-protected between the phases."""
        net, trainer, x, y = make_model()
        runner = self._runner(tmp_path, net, trainer, save_every=1)
        old = signal.getsignal(signal.SIGTERM)
        runner.install_preemption_handler()
        fn = make_step_fn(net, trainer, x, y)
        try:
            runner.run(fn, 2)                  # phase 1, no preemption
            # phase 1's stop() restored the OS handler...
            assert signal.getsignal(signal.SIGTERM) == old

            def step_fn(step, m):
                loss = fn(step, m)
                if step == 3:
                    os.kill(os.getpid(), signal.SIGTERM)
                return loss
            # ...but phase 2 re-arms it and the notice still lands
            with pytest.raises(elastic.Preempted) as ei:
                runner.run(step_fn, 6)
            assert ei.value.step == 3
        finally:
            runner.stop()
        assert signal.getsignal(signal.SIGTERM) == old

    def test_preemption_before_start_leaves_at_first_boundary(
            self, tmp_path):
        net, trainer, x, y = make_model()
        runner = self._runner(tmp_path, net, trainer)
        runner.request_preemption("early notice")
        assert runner.preemption_requested
        with pytest.raises(elastic.Preempted) as ei:
            runner.run(make_step_fn(net, trainer, x, y), 8)
        # nothing completed yet: nothing to checkpoint, step is -1
        assert ei.value.step == -1
        assert runner.ckpt.steps() == []

    def test_siblings_see_fast_leave_immediately(self, tmp_path):
        board = elastic.HeartbeatBoard(str(tmp_path))
        board.register(0)
        board.register(1)
        assert board.alive(timeout=60) == [0, 1]
        board.remove(1)
        # no staleness wait: the unlink IS the leave signal
        assert board.alive(timeout=60) == [0]
        board.remove(1)                     # idempotent


class TestLauncherPreemption:
    def test_preempt_exit_respawns_outside_failure_budget(
            self, tmp_path):
        # first incarnation exits 75 (graceful leave), second exits 0 —
        # under --max-restarts 0 (fail-fast) the job must still succeed
        src = (
            "import os, sys\n"
            "m = os.path.join(os.environ['MXNET_ELASTIC_COORD_DIR'],\n"
            "                 'p-' + os.environ['DMLC_WORKER_ID'])\n"
            "if not os.path.exists(m):\n"
            "    open(m, 'w').close(); sys.exit(75)\n"
            "assert os.environ['MXNET_ELASTIC_RESTART'] == '1'\n"
            "sys.exit(0)\n")
        rc, rep = _run_launch(
            tmp_path, src,
            extra_args=["--max-restarts", "0",
                        "--restart-backoff", "0.05"])
        assert rc == 0
        assert all(w["preemptions"] == 1 and w["restarts"] == 0
                   and w["final"] == 0 for w in rep["workers"])
        assert all(w["exits"][0]["exit_code"] == 75
                   for w in rep["workers"])

    def test_preempt_budget_exhausted_becomes_failure(self, tmp_path):
        src = "import sys; sys.exit(75)\n"
        rc, rep = _run_launch(
            tmp_path, src, n=1,
            extra_args=["--max-restarts", "0",
                        "--max-preempt-restarts", "2",
                        "--restart-backoff", "0.05"])
        assert rc == 75             # budget spent -> ordinary failure
        w = rep["workers"][0]
        assert w["preemptions"] == 2 and len(w["exits"]) == 3

    def test_preempt_rc_zero_disables_preemption_handling(
            self, tmp_path):
        src = "import sys; sys.exit(75)\n"
        rc, rep = _run_launch(
            tmp_path, src, n=1,
            extra_args=["--preempt-rc", "0"])
        assert rc == 75             # plain fail-fast
        assert rep["workers"][0]["preemptions"] == 0

    def test_supervisor_sigterm_forwards_reaps_and_reports(
            self, tmp_path):
        """An interrupted supervisor must not orphan its workers: the
        signal is forwarded (workers see SIGTERM and exit clean), the
        report JSON is still written, and the launcher exits
        128+signum."""
        import threading

        src = (
            "import signal, sys, time\n"
            "signal.signal(signal.SIGTERM,\n"
            "              lambda s, f: sys.exit(0))\n"
            "time.sleep(60)\n")
        # the in-process launcher installs its handlers in THIS (main)
        # thread; a timer delivers the signal mid-supervision
        before = signal.getsignal(signal.SIGTERM)
        timer = threading.Timer(
            1.0, lambda: os.kill(os.getpid(), signal.SIGTERM))
        timer.start()
        t0 = time.monotonic()
        try:
            rc, rep = _run_launch(tmp_path, src,
                                  extra_args=["--term-window", "5"])
        finally:
            timer.cancel()
        assert rc == 128 + int(signal.SIGTERM)
        assert time.monotonic() - t0 < 30      # no 60 s worker wait
        assert rep["rc"] == rc
        # forwarded SIGTERM, workers exited clean (0), none orphaned
        assert all(w["exits"][-1]["exit_code"] == 0
                   for w in rep["workers"])
        # the supervisor restored the previous handlers on the way out
        assert signal.getsignal(signal.SIGTERM) == before
