"""ZeRO-sharded optimizer state (mxnet_tpu/optimizer/zero.py).

The contract under test: carving each fused optimizer bucket into
per-rank shards (reduce-scatter -> shard-local sweep -> allgather) must
be BIT-IDENTICAL to the replicated fused path — same losses, same
weights, down to the last ULP — while holding ~1/world of the optimizer
state per rank. On top of that, per-rank shard bundles saved at world N
must re-shard into ANY world M at elastic rejoin, bit-exact.

The update clock: the replicated eager path keeps one count stream PER
DEVICE (Optimizer._set_current_context), so a param on N contexts
advances t once per step on each replica — the same t the sharded
sweep's single advance sees. That is what makes t-dependent updates
(adam bias correction) bit-comparable across all of replicated, zero1
and zero2 at any context count, and what keeps the replicated device
copies identical to EACH OTHER (TestBitIdentity guards both).
"""
import json
import os
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.checkpoint import CheckpointManager
from mxnet_tpu.gluon import nn
from mxnet_tpu.kvstore.bucketing import bucket_cap_bytes
from mxnet_tpu.optimizer import zero as zero_mod
from mxnet_tpu.parallel import elastic

pytestmark = pytest.mark.zero

SGD_MOM = ("sgd", {"learning_rate": 0.05, "momentum": 0.9, "wd": 0.01})
ADAM = ("adam", {"learning_rate": 0.01})


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _metric(name, **labels):
    m = telemetry.snapshot()["metrics"].get(name)
    if not m:
        return 0.0
    for s in m.get("samples", []):
        if all(s.get("labels", {}).get(k) == v for k, v in labels.items()):
            return s["value"]
    return 0.0


def _hist_count(name, **labels):
    m = telemetry.snapshot()["metrics"].get(name)
    if not m:
        return 0
    for s in m.get("samples", []):
        if all(s.get("labels", {}).get(k) == v for k, v in labels.items()):
            return s["count"]
    return 0


def make_model(seed, nctx, opt_name, opt_kw, partition=None,
               kvstore="tpu_sync", **tkw):
    """Two-layer net with every shape explicit (deferred init would skip
    the seeding loop) and weights seeded by STABLE prefix-relative name
    — gluon's global name counters differ across instances."""
    if nctx > 1:
        import jax

        if jax.device_count() < nctx:
            pytest.skip(
                f"needs {nctx} virtual CPU devices "
                "(XLA_FLAGS=--xla_force_host_platform_device_count)")
    ctxs = [mx.cpu(i) for i in range(nctx)]
    net = nn.HybridSequential()
    net.add(nn.Dense(37, in_units=13))
    net.add(nn.Dense(5, in_units=37))
    net.initialize(ctx=ctxs)
    rs = np.random.RandomState(seed)
    for _, p in sorted(net._collect_params_with_prefix().items()):
        p.set_data(mx.nd.array(
            rs.uniform(-1, 1, p.shape).astype(np.float32)))
    tr = gluon.Trainer(net.collect_params(), opt_name, dict(opt_kw),
                       kvstore=kvstore, partition=partition, **tkw)
    return net, tr, ctxs


def _batch(step, data_seed):
    rs = np.random.RandomState(data_seed * 1000 + step)
    x = rs.uniform(-1, 1, (8, 13)).astype(np.float32)
    y = rs.uniform(-1, 1, (8, 5)).astype(np.float32)
    return x, y


def train_step(net, tr, ctxs, step, data_seed):
    xh, yh = _batch(step, data_seed)
    n = len(ctxs)
    per = 8 // n
    loss_fn = gluon.loss.L2Loss()
    xs = [mx.nd.array(xh[i * per:(i + 1) * per]).as_in_context(c)
          for i, c in enumerate(ctxs)]
    ys = [mx.nd.array(yh[i * per:(i + 1) * per]).as_in_context(c)
          for i, c in enumerate(ctxs)]
    with autograd.record():
        ls = [loss_fn(net(a), b) for a, b in zip(xs, ys)]
        for l in ls:
            l.backward()
    tr.step(8)
    return sum(float(l.sum().asnumpy()) for l in ls)


def train(net, tr, ctxs, steps, data_seed=11, start=0):
    return [train_step(net, tr, ctxs, s, data_seed)
            for s in range(start, start + steps)]


def weights_of(net, ctx):
    return {k: p.data(ctx).asnumpy()
            for k, p in net._collect_params_with_prefix().items()}


def assert_same(tag, losses_a, losses_b, wa, wb):
    assert losses_a == losses_b, \
        f"{tag}: losses diverge {losses_a} vs {losses_b}"
    for k in wa:
        assert np.array_equal(wa[k], wb[k]), \
            f"{tag}: weight {k} differs by " \
            f"{np.abs(wa[k] - wb[k]).max()}"


# ---------------------------------------------------------------------------
# bit-identity: sharded sweep vs replicated fused path
# ---------------------------------------------------------------------------

class TestBitIdentity:
    def test_zero1_matches_replicated_sgd_momentum(self):
        net0, tr0, cx0 = make_model(3, 2, *SGD_MOM)
        net1, tr1, cx1 = make_model(3, 2, *SGD_MOM, partition="zero1")
        l0 = train(net0, tr0, cx0, 6)
        l1 = train(net1, tr1, cx1, 6)
        assert tr1.partition == "zero1" and tr1._zero.world == 2
        assert_same("zero1 vs replicated", l0, l1,
                    weights_of(net0, cx0[0]), weights_of(net1, cx1[0]))

    def test_zero2_matches_replicated_sgd_momentum(self):
        net0, tr0, cx0 = make_model(3, 2, *SGD_MOM)
        net2, tr2, cx2 = make_model(3, 2, *SGD_MOM, partition="zero2")
        l0 = train(net0, tr0, cx0, 6)
        l2 = train(net2, tr2, cx2, 6)
        assert_same("zero2 vs replicated", l0, l2,
                    weights_of(net0, cx0[0]), weights_of(net2, cx2[0]))

    def test_zero1_matches_zero2_adam_multictx(self):
        net1, tr1, cx1 = make_model(5, 4, *ADAM, partition="zero1")
        net2, tr2, cx2 = make_model(5, 4, *ADAM, partition="zero2")
        l1 = train(net1, tr1, cx1, 5)
        l2 = train(net2, tr2, cx2, 5)
        assert_same("zero1 vs zero2 (adam)", l1, l2,
                    weights_of(net1, cx1[0]), weights_of(net2, cx2[0]))

    def test_zero1_matches_replicated_adam_single_ctx(self):
        net0, tr0, cx0 = make_model(7, 1, *ADAM)
        net1, tr1, cx1 = make_model(7, 1, *ADAM, partition="zero1")
        l0 = train(net0, tr0, cx0, 5)
        l1 = train(net1, tr1, cx1, 5)
        assert_same("zero1 vs replicated (adam 1ctx)", l0, l1,
                    weights_of(net0, cx0[0]), weights_of(net1, cx1[0]))

    @pytest.mark.parametrize("nctx", [2, 4])
    def test_zero1_matches_replicated_adam_multictx(self, nctx):
        """The t-clock case: adam's bias correction reads the per-index
        update count, so this only holds because the replicated path
        keeps one count stream per device (a shared clock hands ctx0
        t=1,N+1,... and ctx1 t=2,N+2,... — replicas drift apart and
        nothing matches the sharded sweep's once-per-step advance)."""
        net0, tr0, cx0 = make_model(3, nctx, *ADAM)
        net1, tr1, cx1 = make_model(3, nctx, *ADAM, partition="zero1")
        l0 = train(net0, tr0, cx0, 6)
        l1 = train(net1, tr1, cx1, 6)
        assert_same(f"zero1 vs replicated (adam {nctx}ctx)", l0, l1,
                    weights_of(net0, cx0[0]), weights_of(net1, cx1[0]))

    def test_replicated_adam_device_copies_stay_identical(self):
        """Replicated multi-device adam must agree with ITSELF: after
        any number of steps every context holds the same bits (the
        per-device count streams advance in lockstep)."""
        net, tr, cxs = make_model(3, 4, *ADAM)
        train(net, tr, cxs, 4)
        t0 = tr._optimizer._all_index_update_counts[0]
        assert all(v == 4 for v in t0.values()), t0
        assert all(tr._optimizer._all_index_update_counts[ci] == t0
                   for ci in range(1, 4))
        w0 = weights_of(net, cxs[0])
        for c in cxs[1:]:
            wc = weights_of(net, c)
            for k in w0:
                assert np.array_equal(w0[k], wc[k]), \
                    f"replicated copies diverged on {k} at {c}"


# ---------------------------------------------------------------------------
# hierarchical topology-aware dispatch
# ---------------------------------------------------------------------------

class TestHierarchical:
    def test_bucketed_one_interhost_dispatch_per_bucket(self):
        """With a 2-host topology every fused gradient bucket must run
        exactly ONE inter-host collective — the 'hierarchical' dispatch
        count equals the bucket count, with zero flat-'bucketed'
        dispatches — and stay bit-identical to the flat mesh."""
        netf, trf, cxf = make_model(3, 4, *SGD_MOM)
        lf = train(netf, trf, cxf, 3)
        neth, trh, cxh = make_model(3, 4, *SGD_MOM)
        trh._init_kvstore()
        trh._kvstore.set_topology(2)
        telemetry.enable()
        try:
            lh = train(neth, trh, cxh, 3)
            hier = _metric("mxnet_kvstore_collective_dispatch_total",
                           path="hierarchical")
            flat = _metric("mxnet_kvstore_collective_dispatch_total",
                           path="bucketed")
            nbuckets = _hist_count("mxnet_kvstore_bucket_bytes")
        finally:
            telemetry.disable()
        assert hier > 0 and flat == 0
        assert hier == nbuckets          # exactly one per bucket
        assert hier % 3 == 0             # same bucket count every step
        assert_same("hierarchical vs flat", lf, lh,
                    weights_of(netf, cxf[0]), weights_of(neth, cxh[0]))

    def test_zero1_hierarchical_matches_flat(self):
        netf, trf, cxf = make_model(3, 4, *SGD_MOM, partition="zero1")
        lf = train(netf, trf, cxf, 4)
        neth, trh, cxh = make_model(3, 4, *SGD_MOM, partition="zero1")
        trh._init_kvstore()
        trh._kvstore.set_topology(2)
        # engine planned over the flat mesh at init — re-plan over the
        # factored one (what a real job sets via MXNET_KV_HOSTS before
        # the first step)
        trh._zero._ready = False
        trh._zero._buckets = []
        trh._zero.ensure_ready()
        telemetry.enable()
        try:
            lh = train(neth, trh, cxh, 4)
            nzero = _metric("mxnet_kvstore_collective_dispatch_total",
                            path="zero")
        finally:
            telemetry.disable()
        assert nzero == 4 * len(trh._zero._buckets)
        assert_same("zero1 hierarchical vs flat", lf, lh,
                    weights_of(netf, cxf[0]), weights_of(neth, cxh[0]))


# ---------------------------------------------------------------------------
# per-rank state footprint
# ---------------------------------------------------------------------------

class TestStateBytes:
    def test_zero1_state_bytes_at_most_one_world_th(self):
        telemetry.enable()
        try:
            net, tr, cxs = make_model(3, 4, *ADAM, partition="zero1")
            tr._init_kvstore()
            per_rank = _metric("mxnet_optimizer_state_bytes",
                               mode="zero1")
            replicated = _metric("mxnet_optimizer_state_bytes",
                                 mode="replicated")
        finally:
            telemetry.disable()
        world = tr._zero.world
        assert world == 4 and per_rank > 0 and replicated > 0
        # ceil-div sharding: per-rank holds at most 1/world of the
        # replicated bytes plus one bucket of padding slack
        assert per_rank <= replicated / world + bucket_cap_bytes()

    def test_replicated_gauge_from_eager_plan(self):
        net, tr, cxs = make_model(3, 1, *ADAM)
        telemetry.enable()
        try:
            train(net, tr, cxs, 1)
            replicated = _metric("mxnet_optimizer_state_bytes",
                                 mode="replicated")
        finally:
            telemetry.disable()
        # adam: exp_avg + exp_avg_sq over every fused param
        nelem = sum(int(np.prod(p.shape))
                    for p in net.collect_params().values())
        assert replicated == 2 * nelem * 4


# ---------------------------------------------------------------------------
# fallback: families/params outside the sharded sweep
# ---------------------------------------------------------------------------

class TestFallback:
    def test_unsupported_family_warns_and_trains_replicated(self):
        telemetry.enable()
        try:
            with pytest.warns(UserWarning,
                              match="outside the sharded sweep"):
                net, tr, cxs = make_model(
                    3, 1, "lamb", {"learning_rate": 0.01},
                    partition="zero1")
                tr._init_kvstore()
            nfall = _metric("mxnet_kvstore_bucket_fallback_total",
                            reason=zero_mod.FALLBACK_FAMILY)
        finally:
            telemetry.disable()
        assert tr.partition is None          # engine never engaged
        assert nfall == sum(1 for p in net.collect_params().values()
                            if p.grad_req != "null")
        losses = train(net, tr, cxs, 2)      # training still works
        assert losses[1] == losses[1]        # finite

    def test_sparse_grad_param_falls_back_per_param(self):
        net, tr, cxs = make_model(3, 1, *SGD_MOM, partition="zero1")
        params = list(net.collect_params().values())
        params[0].grad_stype = "row_sparse"
        telemetry.enable()
        try:
            with pytest.warns(UserWarning, match="ZeRO sharded sweep"):
                tr._init_kvstore()
            nfall = _metric("mxnet_kvstore_bucket_fallback_total",
                            reason=zero_mod.FALLBACK_SPARSE)
        finally:
            telemetry.disable()
        assert tr.partition == "zero1"       # engine active for the rest
        assert nfall == 1
        reasons = set(tr._zero.fallback_reasons.values())
        assert reasons == {zero_mod.FALLBACK_SPARSE}
        # the sparse param is NOT in the sharded buckets but still trains
        idx = [i for i, p in enumerate(tr._params)
               if p is params[0]][0]
        assert idx not in tr._zero.eligible_indices()
        before = params[0].data(cxs[0]).asnumpy().copy()
        train(net, tr, cxs, 1)
        assert not np.array_equal(before, params[0].data(cxs[0]).asnumpy())


# ---------------------------------------------------------------------------
# identity resolution + manifests
# ---------------------------------------------------------------------------

class TestIdentity:
    def test_env_identity_and_manifest(self, monkeypatch):
        monkeypatch.setenv("MXNET_ZERO_WORLD", "4")
        monkeypatch.setenv("MXNET_ZERO_RANK", "2")
        net, tr, cxs = make_model(3, 1, *ADAM, partition="zero1",
                                  kvstore="device")
        tr._init_kvstore()
        assert tr._zero.world == 4 and tr._zero.rank == 2
        man = tr.partition_manifest()
        assert man["mode"] == "zero1" and man["world"] == 4 \
            and man["rank"] == 2

    def test_partition_env_engages_engine(self, monkeypatch):
        monkeypatch.setenv("MXNET_ZERO_PARTITION", "zero2")
        net, tr, cxs = make_model(3, 2, *SGD_MOM)
        tr._init_kvstore()
        assert tr.partition == "zero2"

    def test_update_on_kvstore_conflicts(self):
        net, tr, cxs = make_model(3, 1, *SGD_MOM, partition="zero1",
                                  update_on_kvstore=True)
        with pytest.raises(MXNetError, match="update_on_kvstore"):
            tr._init_kvstore()

    def test_checkpoint_bundle_carries_partition_manifest(self, tmp_path):
        net, tr, cxs = make_model(3, 1, *ADAM, partition="zero1",
                                  kvstore="device", partition_world=2,
                                  partition_rank=0)
        train(net, tr, cxs, 1)
        mgr = CheckpointManager(str(tmp_path), prefix="r0")
        mgr.save(0, params=net, trainer=tr)
        man = mgr.partition_manifest(0)
        assert man["mode"] == "zero1" and man["world"] == 2
        assert mgr.load(0)["zero"] == man


# ---------------------------------------------------------------------------
# sharded serialization: strict round-trip + typed mismatches
# ---------------------------------------------------------------------------

class TestSaveLoad:
    def test_strict_roundtrip_bit_exact(self, tmp_path):
        net, tr, cxs = make_model(3, 2, *SGD_MOM, partition="zero1")
        train(net, tr, cxs, 3)
        f = str(tmp_path / "states")
        tr.save_states(f)
        w_at_save = weights_of(net, cxs[0])
        cont_a = train(net, tr, cxs, 2, start=3)
        # rewind weights + states, replay: must be bit-identical
        for k, p in net._collect_params_with_prefix().items():
            p.set_data(mx.nd.array(w_at_save[k]))
        tr.load_states(f)
        cont_b = train(net, tr, cxs, 2, start=3)
        assert cont_a == cont_b

    def test_unpartitioned_load_of_sharded_file_raises(self, tmp_path):
        net, tr, cxs = make_model(3, 2, *SGD_MOM, partition="zero1")
        train(net, tr, cxs, 1)
        f = str(tmp_path / "sharded")
        tr.save_states(f)
        net0, tr0, cx0 = make_model(3, 2, *SGD_MOM)
        tr0._init_kvstore()
        with pytest.raises(MXNetError) as ei:
            tr0.load_states(f)
        # the error names BOTH plans
        assert "zero1" in str(ei.value) \
            and "unpartitioned" in str(ei.value)

    def test_sharded_load_of_replicated_file_raises(self, tmp_path):
        net0, tr0, cx0 = make_model(3, 2, *SGD_MOM)
        train(net0, tr0, cx0, 1)
        f = str(tmp_path / "replicated")
        tr0.save_states(f)
        net, tr, cxs = make_model(3, 2, *SGD_MOM, partition="zero1")
        train(net, tr, cxs, 1)
        with pytest.raises(MXNetError) as ei:
            tr.load_states(f)
        assert "zero1" in str(ei.value)

    def test_missing_source_rank_raises_typed(self, tmp_path):
        net, tr, cxs = make_model(3, 1, *ADAM, partition="zero1",
                                  kvstore="device", partition_world=4,
                                  partition_rank=0)
        train(net, tr, cxs, 2)
        f = str(tmp_path / "r0-only")
        tr.save_states(f)
        net2, tr2, cx2 = make_model(3, 1, *ADAM, partition="zero1",
                                    kvstore="device", partition_world=2,
                                    partition_rank=0)
        tr2._init_kvstore()
        with pytest.raises(zero_mod.PartitionMismatchError,
                           match="rank"):
            tr2.load_states_resharded([f])


# ---------------------------------------------------------------------------
# N -> M re-sharding (the elastic rejoin path)
# ---------------------------------------------------------------------------

def _virtual_model(seed, world, rank=0):
    return make_model(seed, 1, *ADAM, partition="zero1",
                      kvstore="device", partition_world=world,
                      partition_rank=rank)


def _save_rank_shards(tr, out_paths, world):
    """One sharded-envelope state file per source rank (the engine in
    virtual mode serializes only its OWN shard, like N real workers)."""
    for r, f in enumerate(out_paths):
        tr.zero_reconfigure(r, world)
        tr.save_states(f)
    tr.zero_reconfigure(0, world)


class TestReshard:
    @pytest.mark.parametrize("m", [3, 5, 1])
    def test_world_4_reshards_bit_exact(self, tmp_path, m):
        neta, tra, cxa = _virtual_model(3, world=4)
        train(neta, tra, cxa, 4, data_seed=31)
        files = [str(tmp_path / f"rank{r}") for r in range(4)]
        _save_rank_shards(tra, files, 4)
        wa = weights_of(neta, cxa[0])

        netb, trb, cxb = _virtual_model(99, world=m, rank=min(1, m - 1))
        for k, p in netb._collect_params_with_prefix().items():
            p.set_data(mx.nd.array(wa[k]))
        trb._init_kvstore()
        trb.load_states_resharded(files)

        la = train(neta, tra, cxa, 3, data_seed=31, start=4)
        lb = train(netb, trb, cxb, 3, data_seed=31, start=4)
        assert_same(f"reshard 4->{m}", la, lb,
                    weights_of(neta, cxa[0]), weights_of(netb, cxb[0]))


class TestElasticReshard:
    """A rank that rejoins an elastic job at a DIFFERENT world size must
    gather every old-world shard bundle and re-shard it into the new
    plan bit-exactly — losses and weights match the uninterrupted run."""

    N = 3

    @pytest.mark.parametrize("m", [2, 4, 1])
    def test_rejoin_resharded_bit_exact(self, tmp_path, m):
        head, total = 3, 6
        # oracle: the uninterrupted run (virtual-mode numerics are
        # world-independent — sharding only shapes serialization)
        neto, tro, cxo = _virtual_model(3, world=self.N)
        oracle = train(neto, tro, cxo, total, data_seed=77)

        # incarnation 1 at world N: run `head` steps, then each rank
        # writes its bundle (params + its OWN state shard)
        net1, tr1, cx1 = _virtual_model(3, world=self.N)
        got = train(net1, tr1, cx1, head, data_seed=77)
        assert got == oracle[:head]
        ckpt_dir = os.path.join(str(tmp_path), "ckpts")
        for r in range(self.N):
            tr1.zero_reconfigure(r, self.N)
            CheckpointManager(ckpt_dir, prefix=f"r{r}").save(
                head - 1, params=net1, trainer=tr1,
                extra={"elastic": {"epoch": 0,
                                   "members": list(range(self.N)),
                                   "launch_rank": r}})

        # incarnation 2 at world M: WRONG init on purpose; the rejoin
        # restore (params from r0, state re-gathered from r0..r{N-1})
        # must win
        net2, tr2, cx2 = _virtual_model(99, world=m)
        board = elastic.HeartbeatBoard(str(tmp_path))
        future = time.time() + 1e6
        for r in range(1, m):
            os.utime(board.register(r), (future, future))
        runner = elastic.ElasticRunner(
            str(tmp_path), params=net2, trainer=tr2, world_size=m,
            rank=0, heartbeat_interval=0.05, heartbeat_timeout=60.0,
            join_timeout=0.2, distributed=False)
        tail = runner.run(
            lambda step, _m: train_step(net2, tr2, cx2, step, 77),
            total)
        assert runner.resumed_from == head - 1
        assert tr2._zero.world == m
        assert tail == oracle[head:]
        wo, w2 = weights_of(neto, cxo[0]), weights_of(net2, cx2[0])
        for k in wo:
            assert np.array_equal(wo[k], w2[k]), f"weight {k} diverged"
