"""Control-flow op tests (reference:
tests/python/unittest/test_contrib_control_flow.py).

Each op is checked eager (python-loop path), under autograd, and
hybridized (lax lowering inside one jit executable) against a numpy
oracle.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import HybridBlock


class TestForeach:
    def test_cumsum_eager(self):
        data = mx.nd.array(onp.arange(12.0).reshape(4, 3))
        init = mx.nd.zeros((3,))

        def body(x, s):
            new = s[0] + x
            return new, [new]

        outs, states = mx.nd.contrib.foreach(body, data, [init])
        want = onp.cumsum(onp.arange(12.0).reshape(4, 3), axis=0)
        onp.testing.assert_allclose(outs.asnumpy(), want)
        onp.testing.assert_allclose(states[0].asnumpy(), want[-1])

    def test_autograd(self):
        data = mx.nd.array(onp.ones((3, 2)))
        data.attach_grad()
        init = mx.nd.zeros((2,))

        def body(x, s):
            new = s[0] + 2.0 * x
            return new, [new]

        with autograd.record():
            outs, _ = mx.nd.contrib.foreach(body, data, [init])
            loss = outs.sum()
        loss.backward()
        # out_i = 2*sum_{j<=i} x_j; dloss/dx_j = 2*(n - j)
        onp.testing.assert_allclose(data.grad.asnumpy(),
                                    onp.array([[6., 6.], [4., 4.],
                                               [2., 2.]]))

    def test_hybridized_scan(self):
        class Cum(HybridBlock):
            def hybrid_forward(self, F, data, init):
                out, states = F.contrib.foreach(
                    lambda x, s: (s[0] + x, [s[0] + x]), data, [init])
                return out, states[0]

        net = Cum()
        net.hybridize()
        data = mx.nd.array(onp.arange(10.0).reshape(5, 2))
        init = mx.nd.zeros((2,))
        out, last = net(data, init)
        want = onp.cumsum(onp.arange(10.0).reshape(5, 2), axis=0)
        onp.testing.assert_allclose(out.asnumpy(), want)
        onp.testing.assert_allclose(last.asnumpy(), want[-1])

    def test_multi_input_output(self):
        a = mx.nd.array(onp.ones((4, 2)))
        b = mx.nd.array(onp.full((4, 2), 2.0))

        def body(xs, s):
            x, y = xs
            new = s[0] + x * y
            return [new, x - y], [new]

        outs, states = mx.nd.contrib.foreach(body, [a, b],
                                             [mx.nd.zeros((2,))])
        onp.testing.assert_allclose(outs[0].asnumpy()[-1], [8.0, 8.0])
        onp.testing.assert_allclose(outs[1].asnumpy()[0], [-1.0, -1.0])


class TestWhileLoop:
    def test_eager_accumulate(self):
        def cond(i, s):
            return i < 5

        def func(i, s):
            return s + i, [i + 1, s + i]

        outs, (i_fin, s_fin) = mx.nd.contrib.while_loop(
            cond, func, [mx.nd.array([0.0]), mx.nd.array([0.0])],
            max_iterations=10)
        assert float(i_fin.asnumpy()) == 5.0
        assert float(s_fin.asnumpy()) == 10.0   # 0+1+2+3+4
        assert outs.shape[0] == 5               # actual trip count eagerly

    def test_requires_max_iterations(self):
        with pytest.raises(MXNetError, match="max_iterations"):
            mx.nd.contrib.while_loop(lambda i: i < 1,
                                     lambda i: (i, [i + 1]),
                                     [mx.nd.array([0.0])])

    def test_hybridized_fixed_shape(self):
        class Pow(HybridBlock):
            def hybrid_forward(self, F, x, n):
                out, (acc, i) = F.contrib.while_loop(
                    lambda acc, i: i < n.reshape(()),
                    lambda acc, i: (acc * x, [acc * x, i + 1]),
                    [F.ones_like(x), F.zeros((1,))],
                    max_iterations=8)
                return acc

        net = Pow()
        net.hybridize()
        x = mx.nd.array([2.0])
        for n, want in ((3, 8.0), (5, 32.0)):
            got = float(net(x, mx.nd.array([float(n)])).asnumpy())
            assert got == want, (n, got)

    def test_autograd_through_loop(self):
        x = mx.nd.array([3.0])
        x.attach_grad()
        with autograd.record():
            outs, (acc,) = mx.nd.contrib.while_loop(
                lambda a: a < 100.0, lambda a: (a, [a * x]),
                [x * 1.0], max_iterations=10)
            loss = acc.sum()
        loss.backward()
        # acc = x^k first exceeding 100 -> x^5=243; dacc/dx = 5x^4
        onp.testing.assert_allclose(x.grad.asnumpy(), [5 * 3.0 ** 4])


class TestCond:
    def test_eager_branch(self):
        x = mx.nd.array([2.0])
        out = mx.nd.contrib.cond(x.sum() > 1.0,
                                 lambda: x * 10.0, lambda: x - 1.0)
        assert float(out.asnumpy()) == 20.0
        out = mx.nd.contrib.cond(x.sum() < 1.0,
                                 lambda: x * 10.0, lambda: x - 1.0)
        assert float(out.asnumpy()) == 1.0

    def test_autograd_chosen_branch(self):
        x = mx.nd.array([4.0])
        x.attach_grad()
        with autograd.record():
            out = mx.nd.contrib.cond(x.sum() > 0.0,
                                     lambda: x * x, lambda: x)
        out.backward()
        onp.testing.assert_allclose(x.grad.asnumpy(), [8.0])

    def test_hybridized_lax_cond(self):
        class AbsLike(HybridBlock):
            def hybrid_forward(self, F, x):
                return F.contrib.cond(x.sum() >= 0.0,
                                      lambda: x * 1.0, lambda: -x)

        net = AbsLike()
        net.hybridize()
        assert float(net(mx.nd.array([-3.0])).asnumpy()) == 3.0
        assert float(net(mx.nd.array([5.0])).asnumpy()) == 5.0


class TestReviewRegressions:
    def test_foreach_zero_length(self):
        out, states = mx.nd.contrib.foreach(
            lambda x, s: (x * 2, [s[0] + x]),
            mx.nd.array(onp.zeros((0, 3), "float32")), [mx.nd.ones((3,))])
        assert out.shape == (0, 3)
        onp.testing.assert_allclose(states[0].asnumpy(), onp.ones(3))

    def test_cond_mismatched_structures_traced(self):
        from mxnet_tpu.gluon import HybridBlock

        class Bad(HybridBlock):
            def hybrid_forward(self, F, x):
                return F.contrib.cond(x.sum() > 0,
                                      lambda: (x, x),
                                      lambda: [x, x])

        net = Bad()
        net.hybridize()
        with pytest.raises(MXNetError, match="same structure"):
            net(mx.nd.array([1.0]))

    def test_cond_traced_container_follows_then(self):
        from mxnet_tpu.gluon import HybridBlock

        class Pair(HybridBlock):
            def hybrid_forward(self, F, x):
                return F.contrib.cond(x.sum() > 0,
                                      lambda: [x * 2, x],
                                      lambda: [x, x * 2])

        net = Pair()
        net.hybridize()
        out = net(mx.nd.array([1.0]))
        assert isinstance(out, list) and len(out) == 2
