"""Autograd tests (reference: tests/python/unittest/test_autograd.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd


def test_simple_grad():
    x = mx.nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert np.allclose(x.grad.asnumpy(), 2 * np.array([1.0, 2.0, 3.0]))


def test_chain_rule():
    x = mx.nd.array([0.5, -0.5])
    x.attach_grad()
    with autograd.record():
        y = mx.nd.exp(mx.nd.sin(x)).sum()
    y.backward()
    expect = np.exp(np.sin([0.5, -0.5])) * np.cos([0.5, -0.5])
    assert np.allclose(x.grad.asnumpy(), expect, rtol=1e-5)


def test_multiple_inputs():
    a = mx.nd.array([2.0])
    b = mx.nd.array([3.0])
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        out = a * b + a
    out.backward()
    assert np.allclose(a.grad.asnumpy(), [4.0])  # b + 1
    assert np.allclose(b.grad.asnumpy(), [2.0])  # a


def test_head_gradient():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
    y.backward(mx.nd.array([10.0, 100.0]))
    assert np.allclose(x.grad.asnumpy(), [20.0, 200.0])


def test_grad_add_req():
    x = mx.nd.array([1.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = x * 2
        y.backward()
    assert np.allclose(x.grad.asnumpy(), [6.0])


def test_recording_flags():
    assert not autograd.is_recording()
    assert not autograd.is_training()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
        with autograd.pause():
            assert not autograd.is_recording()
            assert not autograd.is_training()
        with autograd.predict_mode():
            assert autograd.is_recording()
            assert not autograd.is_training()
    with autograd.train_mode():
        assert autograd.is_training()
        assert not autograd.is_recording()


def test_detach_blocks_grad():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    # z = const(4) * x, so dz/dx = 4
    assert np.allclose(x.grad.asnumpy(), [4.0])


def test_stop_gradient_op():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = mx.nd.BlockGrad(x * x) * x
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [4.0])


def test_autograd_grad_function():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = (x ** 3).sum()
    (gx,) = autograd.grad(y, [x])
    assert np.allclose(gx.asnumpy(), 3 * np.array([1.0, 4.0]))


def test_mutation_during_record_raises():
    # reference parity: in-place writes to tape-held arrays inside record()
    # are rejected (a silent stale-tape gradient otherwise)
    x = mx.nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        with pytest.raises(mx.MXNetError):
            x[:] = 100.0
        with pytest.raises(mx.MXNetError):
            x += 1
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [6.0])


def test_mutation_outside_record_is_safe():
    # VJP captures values at record time: mutating an input after the record
    # scope closes must not corrupt the backward.
    x = mx.nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    x[:] = 100.0
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [6.0])


def test_multi_output_op_grad():
    x = mx.nd.array([[1.0, 5.0, 2.0]])
    x.attach_grad()
    with autograd.record():
        vals, idx = mx.nd.topk(x, k=2, ret_typ="both")
        loss = vals.sum()
    loss.backward()
    # grads flow to the top-2 positions
    assert np.allclose(x.grad.asnumpy(), [[0.0, 1.0, 1.0]])


def test_softmax_output_fused_grad():
    # reference: SoftmaxOutput backward = (softmax - onehot) * grad_scale
    data = mx.nd.array([[1.0, 2.0, 3.0]])
    label = mx.nd.array([2.0])
    data.attach_grad()
    with autograd.record():
        prob = mx.nd.SoftmaxOutput(data, label)
    prob.backward()
    sm = np.exp([1, 2, 3]) / np.exp([1, 2, 3]).sum()
    expect = sm - np.array([0, 0, 1])
    assert np.allclose(data.grad.asnumpy(), [expect], rtol=1e-5)


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = mx.nd.sigmoid(x)
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self.saved_tensors
            return dy * y * (1 - y)

    x = mx.nd.array([0.0, 1.0])
    x.attach_grad()
    f = Sigmoid()
    with autograd.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + np.exp(-np.array([0.0, 1.0])))
    assert np.allclose(x.grad.asnumpy(), s * (1 - s), rtol=1e-5)


def test_finite_difference_oracle():
    # reference: test_utils.check_numeric_gradient — FD vs autograd
    rng = np.random.RandomState(0)
    a = rng.randn(3, 3).astype(np.float32)
    x = mx.nd.array(a)
    x.attach_grad()
    with autograd.record():
        y = (mx.nd.tanh(mx.nd.dot(x, x)) * 0.5).sum()
    y.backward()
    eps = 1e-3
    fd = np.zeros_like(a)
    for i in range(3):
        for j in range(3):
            ap = a.copy(); ap[i, j] += eps
            am = a.copy(); am[i, j] -= eps
            fp = (np.tanh(ap @ ap) * 0.5).sum()
            fm = (np.tanh(am @ am) * 0.5).sum()
            fd[i, j] = (fp - fm) / (2 * eps)
    assert np.allclose(x.grad.asnumpy(), fd, rtol=1e-2, atol=1e-3)


def test_training_flag_drives_dropout():
    x = mx.nd.ones((100, 100))
    with autograd.record(train_mode=True):
        y = mx.nd.Dropout(x, p=0.5)
    assert not np.allclose(y.asnumpy(), 1.0)  # masked
    with autograd.record(train_mode=False):
        y2 = mx.nd.Dropout(x, p=0.5)
    assert np.allclose(y2.asnumpy(), 1.0)  # identity in predict mode
    y3 = mx.nd.Dropout(x, p=0.5, mode="always")
    assert not np.allclose(y3.asnumpy(), 1.0)


class TestCreateGraph:
    """Higher-order autograd (reference: autograd.grad(create_graph=True)).

    The reverse sweep re-linearizes each node's stored pure primal with
    its float inputs live on the tape, so produced gradients are
    differentiable again — including through the primal path (d/dx of
    cos(x)*ct needs x as an input of the grad op, not a closure constant).
    """

    def test_second_derivative_sin(self):
        x = mx.nd.array([0.3, 1.1, -0.7])
        x.attach_grad()
        with autograd.record():
            y = mx.nd.sin(x)
            dx = autograd.grad(y, [x], create_graph=True)[0]
            loss = dx.sum()
        loss.backward()
        np.testing.assert_allclose(x.grad.asnumpy(), -np.sin(x.asnumpy()),
                                   rtol=1e-5)

    def test_gradient_penalty(self):
        w = mx.nd.array([[2.0]])
        w.attach_grad()
        xv = mx.nd.array([[3.0]])
        with autograd.record():
            y = mx.nd.dot(xv, w) * mx.nd.dot(xv, w)
            g = autograd.grad(y, [w], create_graph=True)[0]
            pen = (g * g).sum()
        pen.backward()
        np.testing.assert_allclose(g.asnumpy(), [[36.0]], rtol=1e-5)
        np.testing.assert_allclose(w.grad.asnumpy(), [[1296.0]], rtol=1e-5)

    def test_third_order(self):
        x = mx.nd.array([2.0])
        x.attach_grad()
        with autograd.record():
            y = x * x * x * x
            g1 = autograd.grad(y, [x], create_graph=True)[0]
            g2 = autograd.grad(g1, [x], create_graph=True)[0]
            s = g2.sum()
        s.backward()
        np.testing.assert_allclose(x.grad.asnumpy(), [48.0], rtol=1e-5)

    def test_create_graph_false_unchanged(self):
        x = mx.nd.array([1.0, 2.0])
        x.attach_grad()
        with autograd.record():
            z = (x * x).sum()
        gz = autograd.grad(z, [x])
        np.testing.assert_allclose(gz[0].asnumpy(), [2.0, 4.0])

    def test_gradient_penalty_through_dense(self):
        """out = sum(x W^T + b) => d(out)/dx_i = W row; gp = sum_i |W|^2
        over 4 rows = 4|W|^2, so d(gp)/dW = 8 W exactly."""
        from mxnet_tpu.gluon import nn
        net = nn.Dense(1, in_units=2)
        net.initialize(mx.init.Xavier())
        xi = mx.nd.array(np.random.RandomState(0).randn(4, 2).astype("f"))
        xi.attach_grad()
        with autograd.record():
            out = net(xi).sum()
            gi = autograd.grad(out, [xi], create_graph=True)[0]
            gp = (gi * gi).sum()
        gp.backward()
        w = net.weight.data().asnumpy()
        np.testing.assert_allclose(net.weight.grad().asnumpy(), 8 * w,
                                   rtol=1e-4)

    def test_custom_function_closure_fallback_under_create_graph(self):
        """A custom Function has no stored pure primal, so create_graph
        falls back to the closure pullback: first-order gradients flow
        (and stay on the tape), but sensitivity to the Function's saved
        primals is invisible — matching the reference contract that a
        custom Function is only twice-differentiable if written so."""
        class Sq(autograd.Function):
            def forward(self, x):
                self.save_for_backward(x)
                return x * x

            def backward(self, dy):
                (x,) = self.saved_tensors
                return 2 * x * dy

        import pytest

        x = mx.nd.array([3.0])
        x.attach_grad()
        with autograd.record():
            y = Sq()(x)
            # the fallback is LOUD: zero saved-primal sensitivity is a
            # contract, not a silent surprise (ADVICE r5)
            with pytest.warns(RuntimeWarning,
                              match="saved primals.*silently ZERO"):
                g = autograd.grad(y, [x], create_graph=True)[0]
            assert abs(float(g.asnumpy()[0]) - 6.0) < 1e-6
            # g is live on the tape: downstream use is differentiable
            z = (g * g).sum()
        z.backward()
        # dz/dx flows only through the cotangent chain; the saved-primal
        # path is a closure constant, so the attached grad is 0 here —
        # the contract is "no crash, first-order correct", not d2y/dx2
        assert x.grad is not None

    def test_create_graph_rejects_inplace_mutation(self):
        """In-place writes INSIDE record() are already refused at the
        NDArray layer; a write after the scope closes is legal, but
        create_graph would then re-linearize at the mutated value — the
        version-counter guard refuses instead of silently diverging from
        the stored-closure first-order result."""
        x = mx.nd.array([3.0])
        x.attach_grad()
        with autograd.record():
            y = x * x
        x[:] = 100.0
        # first-order path: immune (stored closure) — still 2*3
        g = autograd.grad(y, [x])
        np.testing.assert_allclose(g[0].asnumpy(), [6.0])
        with pytest.raises(Exception, match="mutated"):
            autograd.grad(y, [x], create_graph=True)
