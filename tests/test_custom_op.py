"""Python ``Custom`` op tests.

Reference strategy: ``tests/python/unittest/test_operator.py::test_custom_op``
— register a CustomOpProp, run it eagerly, through autograd, inside a
hybridized block (traced/jitted graph), and from a Symbol graph.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.base import MXNetError


class _Sigmoid(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        self.assign(out_data[0], req[0], mx.nd.array(1.0 / (1.0 + np.exp(-x))))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0].asnumpy()
        gy = out_grad[0].asnumpy()
        self.assign(in_grad[0], req[0], mx.nd.array(gy * y * (1.0 - y)))


@mx.operator.register("test_sigmoid")
class _SigmoidProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return _Sigmoid()


class _ScaleShift(mx.operator.CustomOp):
    """Two inputs, attr-parameterized: out = scale * x + b."""

    def __init__(self, scale):
        self.scale = scale

    def forward(self, is_train, req, in_data, out_data, aux):
        x, b = in_data[0].asnumpy(), in_data[1].asnumpy()
        self.assign(out_data[0], req[0], mx.nd.array(self.scale * x + b))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        gy = out_grad[0].asnumpy()
        self.assign(in_grad[0], req[0], mx.nd.array(self.scale * gy))
        self.assign(in_grad[1], req[1], mx.nd.array(gy))


@mx.operator.register("test_scale_shift")
class _ScaleShiftProp(mx.operator.CustomOpProp):
    def __init__(self, scale=1.0):
        super().__init__(need_top_grad=True)
        self.scale = float(scale)

    def list_arguments(self):
        return ["data", "bias"]

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return _ScaleShift(self.scale)


def test_custom_eager_forward():
    x = mx.nd.array(np.linspace(-3, 3, 12).reshape(3, 4).astype(np.float32))
    y = mx.nd.Custom(x, op_type="test_sigmoid")
    np.testing.assert_allclose(
        y.asnumpy(), 1 / (1 + np.exp(-x.asnumpy())), rtol=1e-6)


def test_custom_autograd_uses_user_backward():
    x = mx.nd.array(np.array([[0.5, -1.0], [2.0, 0.0]], np.float32))
    x.attach_grad()
    with autograd.record():
        y = mx.nd.Custom(x, op_type="test_sigmoid")
        loss = (y * y).sum()
    loss.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    want = 2 * s * s * (1 - s)  # d(y^2)/dx through the user's backward
    np.testing.assert_allclose(x.grad.asnumpy(), want, rtol=1e-5)


def test_custom_attrs_flow_to_prop():
    x = mx.nd.ones((2, 3))
    b = mx.nd.full((2, 3), 0.5)
    y = mx.nd.Custom(x, b, op_type="test_scale_shift", scale=3.0)
    np.testing.assert_allclose(y.asnumpy(), 3.5 * np.ones((2, 3)), rtol=1e-6)


def test_custom_hybridized_training():
    """Train a hybridized block containing a Custom op: the graph is traced
    and jitted, the custom forward/backward run as host callbacks."""
    from mxnet_tpu.gluon import nn

    class Net(mx.gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.fc = nn.Dense(4)

        def hybrid_forward(self, F, x):
            h = self.fc(x)
            return F.Custom(h, op_type="test_sigmoid")

    net = Net()
    net.initialize()
    net.hybridize()
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.5})
    x = mx.nd.array(np.random.RandomState(0).randn(8, 5).astype(np.float32))
    losses = []
    for _ in range(3):
        with autograd.record():
            y = net(x)
            loss = (y * y).mean()
        loss.backward()
        trainer.step(1)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0]  # user backward produced usable grads


def test_custom_symbol_graph():
    data = mx.sym.Variable("data")
    out = mx.sym.Custom(data, op_type="test_sigmoid", name="sig")
    ex = out.simple_bind(mx.cpu(), data=(2, 3))
    x = np.random.RandomState(1).randn(2, 3).astype(np.float32)
    (y,) = ex.forward(is_train=True, data=mx.nd.array(x))
    np.testing.assert_allclose(y.asnumpy(), 1 / (1 + np.exp(-x)), rtol=1e-5)
    ex.backward(mx.nd.ones((2, 3)))
    s = 1 / (1 + np.exp(-x))
    np.testing.assert_allclose(ex.grad_arrays[0].asnumpy(), s * (1 - s),
                               rtol=1e-5)


def test_custom_unregistered_raises():
    with pytest.raises(MXNetError, match="not registered"):
        mx.nd.Custom(mx.nd.ones((2,)), op_type="nope")
