"""mx.np / mx.npx frontend tests (reference:
tests/python/unittest/test_numpy_op.py + test_numpy_ndarray.py).

Oracle = real NumPy on the same values; autograd checked through the tape.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
np = mx.np
npx = mx.npx


def _rs():
    return onp.random.RandomState(0)


class TestNdarray:
    def test_round_trip_and_types(self):
        x = mx.nd.ones((2, 3))
        xn = x.as_np_ndarray()
        assert isinstance(xn, np.ndarray)
        back = xn.as_nd_ndarray()
        assert type(back) is mx.NDArray
        onp.testing.assert_allclose(back.asnumpy(), onp.ones((2, 3)))

    def test_default_dtype_is_float32(self):
        x = np.array([1.0, 2.0])
        assert str(x.dtype) == "float32"
        z = np.zeros((2, 2))
        assert str(z.dtype) == "float32"

    def test_operators_match_numpy(self):
        a = _rs().randn(3, 4).astype("float32")
        b = _rs().rand(3, 4).astype("float32") + 1.0
        xa, xb = np.array(a), np.array(b)
        for op in ["__add__", "__sub__", "__mul__", "__truediv__",
                   "__pow__", "__floordiv__", "__mod__"]:
            want = getattr(a, op)(b)
            got = getattr(xa, op)(xb)
            onp.testing.assert_allclose(got.asnumpy(), want, rtol=1e-5,
                                        err_msg=op)
        onp.testing.assert_allclose((2.0 - xa).asnumpy(), 2.0 - a, rtol=1e-6)
        onp.testing.assert_allclose((xa @ xb.T).asnumpy(), a @ b.T,
                                    rtol=1e-5)
        assert ((xa > xb).asnumpy() == (a > b)).all()

    def test_true_division_int(self):
        x = np.array([1, 2, 3], dtype="int32")
        out = x / 2
        assert "float" in str(out.dtype)

    def test_reductions(self):
        a = _rs().randn(4, 5).astype("float32")
        x = np.array(a)
        for name in ["sum", "mean", "max", "min", "prod", "std", "var"]:
            onp.testing.assert_allclose(
                getattr(x, name)().asnumpy(), getattr(a, name)(),
                rtol=1e-4, err_msg=name)
            onp.testing.assert_allclose(
                getattr(x, name)(axis=1).asnumpy(),
                getattr(a, name)(axis=1), rtol=1e-4, err_msg=name)
        onp.testing.assert_allclose(
            x.sum(axis=(0, 1), keepdims=True).asnumpy(),
            a.sum(axis=(0, 1), keepdims=True), rtol=1e-5)
        assert int(x.argmax()) == int(a.argmax())

    def test_indexing_basic_and_advanced(self):
        a = _rs().randn(5, 6).astype("float32")
        x = np.array(a)
        onp.testing.assert_allclose(x[1:4, ::2].asnumpy(), a[1:4, ::2])
        mask = a[:, 0] > 0
        got = x[np.array(mask)]
        onp.testing.assert_allclose(got.asnumpy(), a[mask])
        idx = onp.array([0, 2, 4])
        onp.testing.assert_allclose(x[np.array(idx, dtype="int32")].asnumpy(),
                                    a[idx])

    def test_shape_manipulation(self):
        a = _rs().randn(2, 3, 4).astype("float32")
        x = np.array(a)
        onp.testing.assert_allclose(x.T.asnumpy(), a.T)
        onp.testing.assert_allclose(x.reshape(6, 4).asnumpy(),
                                    a.reshape(6, 4))
        onp.testing.assert_allclose(x.transpose(2, 0, 1).asnumpy(),
                                    a.transpose(2, 0, 1))
        onp.testing.assert_allclose(np.expand_dims(x, 1).asnumpy(),
                                    onp.expand_dims(a, 1))
        onp.testing.assert_allclose(np.moveaxis(x, 0, -1).asnumpy(),
                                    onp.moveaxis(a, 0, -1))


class TestFunctions:
    def test_creation(self):
        onp.testing.assert_allclose(np.arange(2, 10, 2).asnumpy(),
                                    onp.arange(2, 10, 2, dtype="float32"))
        onp.testing.assert_allclose(np.linspace(0, 1, 5).asnumpy(),
                                    onp.linspace(0, 1, 5, dtype="float32"))
        onp.testing.assert_allclose(np.eye(3, k=1).asnumpy(),
                                    onp.eye(3, k=1))
        onp.testing.assert_allclose(np.full((2, 2), 7.0).asnumpy(),
                                    onp.full((2, 2), 7.0))

    def test_unary_family(self):
        a = _rs().rand(3, 3).astype("float32") + 0.1
        x = np.array(a)
        for name in ["exp", "log", "sqrt", "sin", "cos", "tanh", "abs",
                     "floor", "ceil", "square", "sign"]:
            onp.testing.assert_allclose(
                getattr(np, name)(x).asnumpy(),
                getattr(onp, name if name != "abs" else "abs")(a),
                rtol=1e-5, atol=1e-6, err_msg=name)

    def test_binary_and_logic(self):
        a = _rs().randn(3, 3).astype("float32")
        b = _rs().rand(3, 3).astype("float32")
        x, y = np.array(a), np.array(b)
        onp.testing.assert_allclose(np.maximum(x, y).asnumpy(),
                                    onp.maximum(a, b))
        onp.testing.assert_allclose(np.where(x > 0, x, y).asnumpy(),
                                    onp.where(a > 0, a, b))
        assert bool(np.isfinite(x).all())

    def test_concat_stack_split(self):
        a = _rs().randn(2, 3).astype("float32")
        x = np.array(a)
        onp.testing.assert_allclose(np.concatenate([x, x], axis=1).asnumpy(),
                                    onp.concatenate([a, a], axis=1))
        onp.testing.assert_allclose(np.stack([x, x]).asnumpy(),
                                    onp.stack([a, a]))
        parts = np.split(np.array(onp.arange(12.0)), 3)
        assert len(parts) == 3 and parts[0].shape == (4,)

    def test_einsum_tensordot(self):
        a = _rs().randn(2, 3).astype("float32")
        b = _rs().randn(3, 4).astype("float32")
        onp.testing.assert_allclose(
            np.einsum("ij,jk->ik", np.array(a), np.array(b)).asnumpy(),
            onp.einsum("ij,jk->ik", a, b), rtol=1e-5)
        onp.testing.assert_allclose(
            np.tensordot(np.array(a), np.array(b), axes=([1], [0])).asnumpy(),
            onp.tensordot(a, b, axes=([1], [0])), rtol=1e-5)

    def test_linalg(self):
        a = _rs().randn(3, 3).astype("float32")
        spd = a @ a.T + 3 * onp.eye(3, dtype="float32")
        x = np.array(spd)
        onp.testing.assert_allclose(np.linalg.norm(x).asnumpy(),
                                    onp.linalg.norm(spd), rtol=1e-5)
        onp.testing.assert_allclose(
            (np.linalg.inv(x) @ x).asnumpy(), onp.eye(3),
            rtol=1e-3, atol=1e-3)
        l = np.linalg.cholesky(x)
        onp.testing.assert_allclose((l @ l.T).asnumpy(), spd, rtol=1e-4)

    def test_random(self):
        mx.random.seed(7)
        u = np.random.uniform(2.0, 3.0, size=(100,))
        assert 2.0 <= float(u.min()) and float(u.max()) <= 3.0
        n = np.random.normal(0.0, 1.0, size=(500,))
        assert abs(float(n.mean())) < 0.3
        r = np.random.randint(0, 5, size=(50,))
        vals = set(onp.unique(r.asnumpy()).tolist())
        assert vals <= {0, 1, 2, 3, 4}


class TestAutograd:
    def test_grad_through_np_ops(self):
        a = _rs().randn(3, 3).astype("float32")
        x = np.array(a)
        x.attach_grad()
        with autograd.record():
            y = (x * x).sum()
        y.backward()
        onp.testing.assert_allclose(x.grad.asnumpy(), 2 * a, rtol=1e-5)

    def test_grad_mixed_chain(self):
        a = _rs().rand(4).astype("float32") + 0.5
        x = np.array(a)
        x.attach_grad()
        with autograd.record():
            y = np.log(x).sum() + (x ** 2).mean()
        y.backward()
        want = 1.0 / a + 2 * a / 4
        onp.testing.assert_allclose(x.grad.asnumpy(), want, rtol=1e-5)


class TestNpx:
    def test_activations(self):
        a = _rs().randn(3, 4).astype("float32")
        x = np.array(a)
        onp.testing.assert_allclose(npx.relu(x).asnumpy(),
                                    onp.maximum(a, 0))
        s = npx.softmax(x).asnumpy()
        onp.testing.assert_allclose(s.sum(-1), onp.ones(3), rtol=1e-5)
        onp.testing.assert_allclose(npx.log_softmax(x).asnumpy(),
                                    onp.log(s), rtol=1e-4, atol=1e-5)

    def test_one_hot_topk_pick(self):
        x = np.array(onp.array([0.0, 2.0, 1.0]))
        oh = npx.one_hot(x, 3)
        onp.testing.assert_allclose(oh.asnumpy(), onp.eye(3)[[0, 2, 1]])
        data = np.array(onp.array([[1.0, 3.0, 2.0], [9.0, 7.0, 8.0]]))
        idx = npx.topk(data, k=2)
        assert idx.asnumpy().tolist() == [[1.0, 2.0], [0.0, 2.0]]

    def test_set_np_flags(self):
        assert not npx.is_np_array()
        npx.set_np()
        assert npx.is_np_array()
        npx.reset_np()
        assert not npx.is_np_array()

        @npx.use_np
        def inner():
            return npx.is_np_array()

        assert inner() and not npx.is_np_array()

    def test_npx_save_load(self, tmp_path):
        f = str(tmp_path / "arrs")
        x = np.array(onp.arange(6.0).reshape(2, 3))
        npx.save(f, {"w": x})
        loaded = npx.load(f)
        assert isinstance(loaded["w"], np.ndarray)
        onp.testing.assert_allclose(loaded["w"].asnumpy(), x.asnumpy())


class TestReviewFindings:
    """Round-2 review regressions for the np frontend."""

    def test_where_single_arg_tuple(self):
        c = np.array(onp.array([[True, False], [False, True]]))
        idx = np.where(c)
        assert isinstance(idx, (tuple, list)) and len(idx) == 2
        assert idx[0].asnumpy().tolist() == [0.0, 1.0]
        assert idx[1].asnumpy().tolist() == [0.0, 1.0]

    def test_eq_none(self):
        x = np.array([1.0])
        assert (x == None) is False  # noqa: E711
        assert (x != None) is True   # noqa: E711

    def test_atleast_1d_scalar(self):
        out = np.atleast_1d(5.0)
        assert out.shape == (1,)

    def test_random_list_size(self):
        u = np.random.uniform(size=[2, 3])
        assert u.shape == (2, 3)

    def test_softmax_length_masked(self):
        x = np.array(onp.zeros((2, 4), dtype="float32"))
        out = npx.softmax(x, axis=-1, length=np.array(
            onp.array([2, 4], dtype="int32")))
        got = out.asnumpy()
        onp.testing.assert_allclose(got[0], [0.5, 0.5, 0.0, 0.0], atol=1e-6)
        onp.testing.assert_allclose(got[1], [0.25] * 4, atol=1e-6)

    def test_leaky_relu_act_types(self):
        x = np.array(onp.array([-1.0, 1.0], dtype="float32"))
        onp.testing.assert_allclose(
            npx.leaky_relu(x, slope=0.1).asnumpy(), [-0.1, 1.0], rtol=1e-5)
        elu = npx.leaky_relu(x, act_type="elu", slope=1.0).asnumpy()
        onp.testing.assert_allclose(elu, [onp.expm1(-1.0), 1.0], rtol=1e-5)
        with pytest.raises(mx.MXNetError, match="act_type"):
            npx.leaky_relu(x, act_type="bogus")
