"""mx.np / mx.npx frontend tests (reference:
tests/python/unittest/test_numpy_op.py + test_numpy_ndarray.py).

Oracle = real NumPy on the same values; autograd checked through the tape.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
np = mx.np
npx = mx.npx


def _rs():
    return onp.random.RandomState(0)


class TestNdarray:
    def test_round_trip_and_types(self):
        x = mx.nd.ones((2, 3))
        xn = x.as_np_ndarray()
        assert isinstance(xn, np.ndarray)
        back = xn.as_nd_ndarray()
        assert type(back) is mx.NDArray
        onp.testing.assert_allclose(back.asnumpy(), onp.ones((2, 3)))

    def test_default_dtype_is_float32(self):
        x = np.array([1.0, 2.0])
        assert str(x.dtype) == "float32"
        z = np.zeros((2, 2))
        assert str(z.dtype) == "float32"

    def test_operators_match_numpy(self):
        a = _rs().randn(3, 4).astype("float32")
        b = _rs().rand(3, 4).astype("float32") + 1.0
        xa, xb = np.array(a), np.array(b)
        for op in ["__add__", "__sub__", "__mul__", "__truediv__",
                   "__pow__", "__floordiv__", "__mod__"]:
            want = getattr(a, op)(b)
            got = getattr(xa, op)(xb)
            onp.testing.assert_allclose(got.asnumpy(), want, rtol=1e-5,
                                        err_msg=op)
        onp.testing.assert_allclose((2.0 - xa).asnumpy(), 2.0 - a, rtol=1e-6)
        onp.testing.assert_allclose((xa @ xb.T).asnumpy(), a @ b.T,
                                    rtol=1e-5)
        assert ((xa > xb).asnumpy() == (a > b)).all()

    def test_true_division_int(self):
        x = np.array([1, 2, 3], dtype="int32")
        out = x / 2
        assert "float" in str(out.dtype)

    def test_reductions(self):
        a = _rs().randn(4, 5).astype("float32")
        x = np.array(a)
        for name in ["sum", "mean", "max", "min", "prod", "std", "var"]:
            onp.testing.assert_allclose(
                getattr(x, name)().asnumpy(), getattr(a, name)(),
                rtol=1e-4, err_msg=name)
            onp.testing.assert_allclose(
                getattr(x, name)(axis=1).asnumpy(),
                getattr(a, name)(axis=1), rtol=1e-4, err_msg=name)
        onp.testing.assert_allclose(
            x.sum(axis=(0, 1), keepdims=True).asnumpy(),
            a.sum(axis=(0, 1), keepdims=True), rtol=1e-5)
        assert int(x.argmax()) == int(a.argmax())

    def test_indexing_basic_and_advanced(self):
        a = _rs().randn(5, 6).astype("float32")
        x = np.array(a)
        onp.testing.assert_allclose(x[1:4, ::2].asnumpy(), a[1:4, ::2])
        mask = a[:, 0] > 0
        got = x[np.array(mask)]
        onp.testing.assert_allclose(got.asnumpy(), a[mask])
        idx = onp.array([0, 2, 4])
        onp.testing.assert_allclose(x[np.array(idx, dtype="int32")].asnumpy(),
                                    a[idx])

    def test_shape_manipulation(self):
        a = _rs().randn(2, 3, 4).astype("float32")
        x = np.array(a)
        onp.testing.assert_allclose(x.T.asnumpy(), a.T)
        onp.testing.assert_allclose(x.reshape(6, 4).asnumpy(),
                                    a.reshape(6, 4))
        onp.testing.assert_allclose(x.transpose(2, 0, 1).asnumpy(),
                                    a.transpose(2, 0, 1))
        onp.testing.assert_allclose(np.expand_dims(x, 1).asnumpy(),
                                    onp.expand_dims(a, 1))
        onp.testing.assert_allclose(np.moveaxis(x, 0, -1).asnumpy(),
                                    onp.moveaxis(a, 0, -1))


class TestFunctions:
    def test_creation(self):
        onp.testing.assert_allclose(np.arange(2, 10, 2).asnumpy(),
                                    onp.arange(2, 10, 2, dtype="float32"))
        onp.testing.assert_allclose(np.linspace(0, 1, 5).asnumpy(),
                                    onp.linspace(0, 1, 5, dtype="float32"))
        onp.testing.assert_allclose(np.eye(3, k=1).asnumpy(),
                                    onp.eye(3, k=1))
        onp.testing.assert_allclose(np.full((2, 2), 7.0).asnumpy(),
                                    onp.full((2, 2), 7.0))

    def test_unary_family(self):
        a = _rs().rand(3, 3).astype("float32") + 0.1
        x = np.array(a)
        for name in ["exp", "log", "sqrt", "sin", "cos", "tanh", "abs",
                     "floor", "ceil", "square", "sign"]:
            onp.testing.assert_allclose(
                getattr(np, name)(x).asnumpy(),
                getattr(onp, name if name != "abs" else "abs")(a),
                rtol=1e-5, atol=1e-6, err_msg=name)

    def test_binary_and_logic(self):
        a = _rs().randn(3, 3).astype("float32")
        b = _rs().rand(3, 3).astype("float32")
        x, y = np.array(a), np.array(b)
        onp.testing.assert_allclose(np.maximum(x, y).asnumpy(),
                                    onp.maximum(a, b))
        onp.testing.assert_allclose(np.where(x > 0, x, y).asnumpy(),
                                    onp.where(a > 0, a, b))
        assert bool(np.isfinite(x).all())

    def test_concat_stack_split(self):
        a = _rs().randn(2, 3).astype("float32")
        x = np.array(a)
        onp.testing.assert_allclose(np.concatenate([x, x], axis=1).asnumpy(),
                                    onp.concatenate([a, a], axis=1))
        onp.testing.assert_allclose(np.stack([x, x]).asnumpy(),
                                    onp.stack([a, a]))
        parts = np.split(np.array(onp.arange(12.0)), 3)
        assert len(parts) == 3 and parts[0].shape == (4,)

    def test_einsum_tensordot(self):
        a = _rs().randn(2, 3).astype("float32")
        b = _rs().randn(3, 4).astype("float32")
        onp.testing.assert_allclose(
            np.einsum("ij,jk->ik", np.array(a), np.array(b)).asnumpy(),
            onp.einsum("ij,jk->ik", a, b), rtol=1e-5)
        onp.testing.assert_allclose(
            np.tensordot(np.array(a), np.array(b), axes=([1], [0])).asnumpy(),
            onp.tensordot(a, b, axes=([1], [0])), rtol=1e-5)

    def test_linalg(self):
        a = _rs().randn(3, 3).astype("float32")
        spd = a @ a.T + 3 * onp.eye(3, dtype="float32")
        x = np.array(spd)
        onp.testing.assert_allclose(np.linalg.norm(x).asnumpy(),
                                    onp.linalg.norm(spd), rtol=1e-5)
        onp.testing.assert_allclose(
            (np.linalg.inv(x) @ x).asnumpy(), onp.eye(3),
            rtol=1e-3, atol=1e-3)
        l = np.linalg.cholesky(x)
        onp.testing.assert_allclose((l @ l.T).asnumpy(), spd, rtol=1e-4)

    def test_random(self):
        mx.random.seed(7)
        u = np.random.uniform(2.0, 3.0, size=(100,))
        assert 2.0 <= float(u.min()) and float(u.max()) <= 3.0
        n = np.random.normal(0.0, 1.0, size=(500,))
        assert abs(float(n.mean())) < 0.3
        r = np.random.randint(0, 5, size=(50,))
        vals = set(onp.unique(r.asnumpy()).tolist())
        assert vals <= {0, 1, 2, 3, 4}


class TestAutograd:
    def test_grad_through_np_ops(self):
        a = _rs().randn(3, 3).astype("float32")
        x = np.array(a)
        x.attach_grad()
        with autograd.record():
            y = (x * x).sum()
        y.backward()
        onp.testing.assert_allclose(x.grad.asnumpy(), 2 * a, rtol=1e-5)

    def test_grad_mixed_chain(self):
        a = _rs().rand(4).astype("float32") + 0.5
        x = np.array(a)
        x.attach_grad()
        with autograd.record():
            y = np.log(x).sum() + (x ** 2).mean()
        y.backward()
        want = 1.0 / a + 2 * a / 4
        onp.testing.assert_allclose(x.grad.asnumpy(), want, rtol=1e-5)


class TestNpx:
    def test_activations(self):
        a = _rs().randn(3, 4).astype("float32")
        x = np.array(a)
        onp.testing.assert_allclose(npx.relu(x).asnumpy(),
                                    onp.maximum(a, 0))
        s = npx.softmax(x).asnumpy()
        onp.testing.assert_allclose(s.sum(-1), onp.ones(3), rtol=1e-5)
        onp.testing.assert_allclose(npx.log_softmax(x).asnumpy(),
                                    onp.log(s), rtol=1e-4, atol=1e-5)

    def test_one_hot_topk_pick(self):
        x = np.array(onp.array([0.0, 2.0, 1.0]))
        oh = npx.one_hot(x, 3)
        onp.testing.assert_allclose(oh.asnumpy(), onp.eye(3)[[0, 2, 1]])
        data = np.array(onp.array([[1.0, 3.0, 2.0], [9.0, 7.0, 8.0]]))
        idx = npx.topk(data, k=2)
        assert idx.asnumpy().tolist() == [[1.0, 2.0], [0.0, 2.0]]

    def test_set_np_flags(self):
        assert not npx.is_np_array()
        npx.set_np()
        assert npx.is_np_array()
        npx.reset_np()
        assert not npx.is_np_array()

        @npx.use_np
        def inner():
            return npx.is_np_array()

        assert inner() and not npx.is_np_array()

    def test_npx_save_load(self, tmp_path):
        f = str(tmp_path / "arrs")
        x = np.array(onp.arange(6.0).reshape(2, 3))
        npx.save(f, {"w": x})
        loaded = npx.load(f)
        assert isinstance(loaded["w"], np.ndarray)
        onp.testing.assert_allclose(loaded["w"].asnumpy(), x.asnumpy())


class TestReviewFindings:
    """Round-2 review regressions for the np frontend."""

    def test_where_single_arg_tuple(self):
        c = np.array(onp.array([[True, False], [False, True]]))
        idx = np.where(c)
        assert isinstance(idx, (tuple, list)) and len(idx) == 2
        assert idx[0].asnumpy().tolist() == [0.0, 1.0]
        assert idx[1].asnumpy().tolist() == [0.0, 1.0]

    def test_eq_none(self):
        x = np.array([1.0])
        assert (x == None) is False  # noqa: E711
        assert (x != None) is True   # noqa: E711

    def test_atleast_1d_scalar(self):
        out = np.atleast_1d(5.0)
        assert out.shape == (1,)

    def test_random_list_size(self):
        u = np.random.uniform(size=[2, 3])
        assert u.shape == (2, 3)

    def test_softmax_length_masked(self):
        x = np.array(onp.zeros((2, 4), dtype="float32"))
        out = npx.softmax(x, axis=-1, length=np.array(
            onp.array([2, 4], dtype="int32")))
        got = out.asnumpy()
        onp.testing.assert_allclose(got[0], [0.5, 0.5, 0.0, 0.0], atol=1e-6)
        onp.testing.assert_allclose(got[1], [0.25] * 4, atol=1e-6)

    def test_leaky_relu_act_types(self):
        x = np.array(onp.array([-1.0, 1.0], dtype="float32"))
        onp.testing.assert_allclose(
            npx.leaky_relu(x, slope=0.1).asnumpy(), [-0.1, 1.0], rtol=1e-5)
        elu = npx.leaky_relu(x, act_type="elu", slope=1.0).asnumpy()
        onp.testing.assert_allclose(elu, [onp.expm1(-1.0), 1.0], rtol=1e-5)
        with pytest.raises(mx.MXNetError, match="act_type"):
            npx.leaky_relu(x, act_type="bogus")


class TestNpBreadth:
    """Round-4 np_* long tail: spot-sweep the delegated/host/alias surface
    against the NumPy oracle."""

    def _a(self, shape=(3, 4), seed=0):
        rs = onp.random.RandomState(seed)
        return rs.randn(*shape).astype("float32")

    def test_delegated_unary_sweep(self):
        x = self._a()
        for name in ["fabs", "fix", "positive", "signbit", "sinc",
                     "nan_to_num", "deg2rad", "rad2deg", "exp2", "real",
                     "conj", "fliplr", "flipud", "ravel", "ptp",
                     "cumprod", "around"]:
            got = getattr(np, name)(np.array(x))
            want = getattr(onp, name)(x)
            onp.testing.assert_allclose(got.asnumpy(), want, rtol=1e-5,
                                        atol=1e-6, err_msg=name)

    def test_delegated_binary_sweep(self):
        a, b = self._a(seed=1), self._a(seed=2)
        for name in ["fmax", "fmin", "logaddexp", "heaviside",
                     "copysign", "float_power"]:
            got = getattr(np, name)(np.array(a), np.array(b))
            want = getattr(onp, name)(a, b)
            onp.testing.assert_allclose(got.asnumpy(), want, rtol=1e-4,
                                        atol=1e-5, err_msg=name)

    def test_reductions_and_stats(self):
        x = self._a((5, 6), seed=3)
        x[0, 0] = onp.nan
        for name in ["nanmax", "nanmin", "nansum", "nanmean", "nanstd"]:
            got = getattr(np, name)(np.array(x))
            want = getattr(onp, name)(x)
            onp.testing.assert_allclose(float(got.asnumpy()), want,
                                        rtol=1e-5, err_msg=name)
        onp.testing.assert_allclose(
            np.percentile(np.array(self._a()), 40).asnumpy(),
            onp.percentile(self._a(), 40), rtol=1e-5)
        onp.testing.assert_allclose(
            np.average(np.array(self._a()), axis=0).asnumpy(),
            onp.average(self._a(), axis=0), rtol=1e-5)

    def test_shape_and_indexing(self):
        x = self._a((4, 4), seed=4)
        onp.testing.assert_allclose(np.tril(np.array(x)).asnumpy(),
                                    onp.tril(x))
        onp.testing.assert_allclose(np.rot90(np.array(x)).asnumpy(),
                                    onp.rot90(x))
        onp.testing.assert_allclose(np.trace(np.array(x)).asnumpy(),
                                    onp.trace(x), rtol=1e-6)
        onp.testing.assert_allclose(
            np.diff(np.array(x), axis=1).asnumpy(), onp.diff(x, axis=1),
            rtol=1e-6)
        r, c = np.tril_indices(4)
        wr, wc = onp.tril_indices(4)
        onp.testing.assert_array_equal(r.asnumpy(), wr)
        onp.testing.assert_array_equal(c.asnumpy(), wc)
        parts = np.hsplit(np.array(x), 2)
        assert len(parts) == 2 and parts[0].shape == (4, 2)

    def test_host_fallbacks_dynamic_shapes(self):
        x = onp.array([[0.0, 1.0], [2.0, 0.0]], "float32")
        nz = np.nonzero(np.array(x))
        wr = onp.nonzero(x)
        for g, w in zip(nz, wr):
            onp.testing.assert_array_equal(g.asnumpy(), w)
        onp.testing.assert_array_equal(
            np.union1d(np.array([1, 2]), np.array([2, 3])).asnumpy(),
            [1, 2, 3])
        onp.testing.assert_array_equal(
            np.intersect1d(np.array([1, 2, 3]),
                           np.array([2, 3, 4])).asnumpy(), [2, 3])

    def test_aliases_and_meta(self):
        x = np.array(self._a())
        onp.testing.assert_allclose(np.acos(np.clip(x, -1, 1)).asnumpy(),
                                    onp.arccos(onp.clip(self._a(), -1, 1)),
                                    rtol=1e-5)
        onp.testing.assert_allclose(np.concat([x, x]).asnumpy(),
                                    onp.concatenate([self._a()] * 2),
                                    rtol=1e-6)
        assert np.finfo(np.float32).eps == onp.finfo(onp.float32).eps
        assert np.result_type(np.float32, np.int32) == \
            onp.result_type(onp.float32, onp.int32)
        assert np.isscalar(3.0) and not np.isscalar([3.0])
        assert np.size(x) == 12 and np.size(x, 1) == 4

    def test_histogram_and_poly(self):
        x = self._a((50,), seed=5)
        gh, ge = np.histogram(np.array(x), bins=7)
        wh, we = onp.histogram(x, bins=7)
        onp.testing.assert_array_equal(gh.asnumpy(), wh)
        onp.testing.assert_allclose(ge.asnumpy(), we, rtol=1e-5)
        c = onp.array([1.0, -2.0, 1.0], "float32")
        onp.testing.assert_allclose(
            np.polyval(np.array(c), np.array([0.0, 1.0, 2.0])).asnumpy(),
            onp.polyval(c, onp.array([0.0, 1.0, 2.0], "float32")),
            rtol=1e-5)

    def test_delegated_ops_are_tape_aware(self):
        import mxnet_tpu as mx

        x = np.array(self._a())
        x.attach_grad()
        with mx.autograd.record():
            y = np.fliplr(x) * 2.0
            s = y.sum()
        s.backward()
        onp.testing.assert_allclose(x.grad.asnumpy(),
                                    onp.full((3, 4), 2.0), rtol=1e-6)


class TestMaskedSoftmax:
    def test_masked_softmax_matches_manual(self):
        import mxnet_tpu as mx

        rs = onp.random.RandomState(0)
        x = rs.randn(2, 5).astype("float32")
        m = onp.array([[1, 1, 0, 1, 0], [0, 0, 0, 0, 0]], bool)
        got = mx.nd.masked_softmax(mx.nd.array(x),
                                   mx.nd.array(m.astype("float32")))
        g = got.asnumpy()
        row = onp.exp(x[0][m[0]])
        row = row / row.sum()
        onp.testing.assert_allclose(g[0][m[0]], row, rtol=1e-5)
        assert (g[0][~m[0]] == 0).all()
        assert (g[1] == 0).all()  # fully-masked row -> zeros, not NaN

    def test_masked_log_softmax(self):
        import mxnet_tpu as mx

        rs = onp.random.RandomState(1)
        x = rs.randn(3, 4).astype("float32")
        m = onp.ones((3, 4), bool)
        m[1, 2:] = False
        got = mx.nd.masked_log_softmax(mx.nd.array(x),
                                       mx.nd.array(m.astype("float32")))
        ref = mx.nd.masked_softmax(mx.nd.array(x),
                                   mx.nd.array(m.astype("float32")))
        g, r = got.asnumpy(), ref.asnumpy()
        onp.testing.assert_allclose(onp.exp(g[m]), r[m], rtol=1e-5)
        assert onp.isneginf(g[~m]).all()


class TestNpxOps:
    """Round-4 npx op-backed surface (reference: mx.npx.* wrappers)."""

    def test_fully_connected_and_activation(self):
        rs = onp.random.RandomState(0)
        x = np.array(rs.randn(4, 8).astype("float32"))
        w = np.array(rs.randn(3, 8).astype("float32"))
        b = np.array(rs.randn(3).astype("float32"))
        out = npx.fully_connected(x, w, b, num_hidden=3)
        want = x.asnumpy() @ w.asnumpy().T + b.asnumpy()
        onp.testing.assert_allclose(out.asnumpy(), want, rtol=1e-5)
        r = npx.activation(np.array([[-1.0, 2.0]]), act_type="relu")
        onp.testing.assert_allclose(r.asnumpy(), [[0.0, 2.0]])

    def test_convolution_pooling(self):
        rs = onp.random.RandomState(1)
        x = np.array(rs.randn(1, 2, 6, 6).astype("float32"))
        w = np.array(rs.randn(3, 2, 3, 3).astype("float32"))
        out = npx.convolution(data=x, weight=w, kernel=(3, 3), num_filter=3)
        assert out.shape == (1, 3, 4, 4)
        p = npx.pooling(out, kernel=(2, 2), stride=(2, 2))
        assert p.shape == (1, 3, 2, 2)

    def test_layer_norm_and_embedding(self):
        rs = onp.random.RandomState(2)
        x = np.array(rs.randn(2, 5).astype("float32"))
        g = np.array(onp.ones(5, "float32"))
        b = np.array(onp.zeros(5, "float32"))
        ln = npx.layer_norm(x, g, b).asnumpy()
        xm = x.asnumpy() - x.asnumpy().mean(-1, keepdims=True)
        want = xm / onp.sqrt((xm ** 2).mean(-1, keepdims=True) + 1e-5)
        onp.testing.assert_allclose(ln, want, rtol=1e-4, atol=1e-5)
        wt = np.array(rs.randn(10, 4).astype("float32"))
        idx = np.array(onp.array([1, 3], "int32"))
        emb = npx.embedding(idx, wt)
        onp.testing.assert_allclose(emb.asnumpy(),
                                    wt.asnumpy()[[1, 3]], rtol=1e-6)

    def test_smooth_l1_and_dropout_eval(self):
        x = np.array(onp.array([-2.0, 0.25, 2.0], "float32"))
        s = npx.smooth_l1(x, scalar=1.0).asnumpy()
        onp.testing.assert_allclose(s, [1.5, 0.03125, 1.5], rtol=1e-5)
        d = npx.dropout(x, p=0.5)  # not training: identity
        onp.testing.assert_allclose(d.asnumpy(), x.asnumpy())


def test_delegated_sequence_args_stay_on_tape():
    """Review r4: NDArrays nested one level inside sequence args (select,
    column_stack...) must be traced operands, not host-coerced constants."""
    rs = onp.random.RandomState(0)
    a = np.array(rs.randn(4).astype("float32"))
    b = np.array(rs.randn(4).astype("float32"))
    a.attach_grad()
    with autograd.record():
        y = np.column_stack((a, b))
        s = (y * y).sum()
    s.backward()
    onp.testing.assert_allclose(a.grad.asnumpy(), 2 * a.asnumpy(),
                                rtol=1e-5)
    cond = np.array(onp.array([True, False, True, False]))
    out = np.select([cond], [a], 0.0)
    want = onp.where(onp.array([True, False, True, False]),
                     a.asnumpy(), 0.0)
    onp.testing.assert_allclose(out.asnumpy(), want, rtol=1e-6)


def test_np_block_nested_grad_flows():
    """np.block's canonical nested [[A, B], [C, D]] form keeps every
    NDArray on the tape (two-level sequence lifting in _np_delegate)."""
    import numpy as onp
    from mxnet_tpu import autograd
    a = mx.np.array([[1.0, 2.0]])
    b = mx.np.array([[3.0, 4.0]])
    c = mx.np.array([[5.0, 6.0]])
    d = mx.np.array([[7.0, 8.0]])
    for t in (a, b, c, d):
        t.attach_grad()
    with autograd.record():
        y = mx.np.block([[a, b], [c, d]])
        loss = (y * y).sum()
    loss.backward()
    for t in (a, b, c, d):
        onp.testing.assert_allclose(t.grad.asnumpy(), 2 * t.asnumpy())


def test_npx_rnn_mode_required():
    import pytest
    with pytest.raises(ValueError, match="mode"):
        mx.npx.rnn(mx.np.ones((2, 1, 4)), mx.np.ones((100,)),
                   mx.np.ones((1, 1, 8)), state_size=8)


class TestNpSurfaceAdditions:
    """Round-4 tail: array-utility mirrors (asarray/atleast/put family)."""

    def test_asarray_noop_and_atleast(self):
        import numpy as onp
        a = mx.np.array([1.0, 2.0, 3.0])
        assert mx.np.asarray(a) is a
        assert mx.np.asanyarray(a) is a
        assert mx.np.ascontiguousarray(a) is not None
        assert mx.np.atleast_2d(a).shape == (1, 3)
        assert mx.np.atleast_3d(a).shape == (1, 3, 1)
        assert mx.np.atleast_2d(mx.np.array(5.0)).shape == (1, 1)
        a2, b2 = mx.np.atleast_2d(a, mx.np.array(1.0))
        assert a2.shape == (1, 3) and b2.shape == (1, 1)

    def test_put_family_matches_numpy(self):
        import numpy as onp
        e = mx.np.array([[10.0, 30.0], [40.0, 20.0]])
        idx = mx.np.array([[1], [0]]).astype("int32")
        mx.np.put_along_axis(e, idx, mx.np.array([[99.0], [88.0]]), 1)
        h = onp.array([[10.0, 30.0], [40.0, 20.0]], onp.float32)
        onp.put_along_axis(h, onp.array([[1], [0]]),
                           onp.array([[99.0], [88.0]], onp.float32), 1)
        onp.testing.assert_allclose(e.asnumpy(), h)

        c = mx.np.zeros((5,))
        mx.np.put(c, [0, 2], [9.0, 7.0])
        onp.testing.assert_allclose(c.asnumpy(), [9, 0, 7, 0, 0])

        d = mx.np.array([1.0, -2.0, 3.0])
        mx.np.putmask(d, onp.array([False, True, False]), mx.np.array([0.0]))
        onp.testing.assert_allclose(d.asnumpy(), [1.0, 0.0, 3.0])

        f = mx.np.array([1.0, 2.0])
        mx.np.place(f, onp.array([True, False]), [7.0])
        onp.testing.assert_allclose(f.asnumpy(), [7.0, 2.0])

        g = mx.np.zeros((2, 3))
        mx.np.copyto(g, mx.np.array([1.0, 2.0, 3.0]))
        onp.testing.assert_allclose(g.asnumpy(),
                                    onp.tile([1.0, 2.0, 3.0], (2, 1)))

    def test_lexsort_ndindex_isdtype_dlpack(self):
        import numpy as onp
        k = mx.np.lexsort([mx.np.array([2.0, 1.0, 3.0]),
                           mx.np.array([0.0, 0.0, 0.0])])
        onp.testing.assert_allclose(
            k.asnumpy(), onp.lexsort([onp.array([2.0, 1.0, 3.0]),
                                      onp.zeros(3)]))
        assert list(mx.np.ndindex(2, 2)) == list(onp.ndindex(2, 2))
        assert mx.np.isdtype(onp.float32, "real floating")
        got = mx.np.from_dlpack(onp.ones((2, 2), onp.float32))
        onp.testing.assert_allclose(got.asnumpy(), onp.ones((2, 2)))

    def test_put_cycles_raises_and_asarray_promotes(self):
        import numpy as onp
        import pytest
        c = mx.np.zeros((5,))
        mx.np.put(c, [0, 1, 2, 3], [1.0, 2.0])  # NumPy cycles values
        onp.testing.assert_allclose(c.asnumpy(), [1, 2, 1, 2, 0])
        with pytest.raises(IndexError):
            mx.np.put(mx.np.zeros((5,)), [10], [9.0])
        with pytest.raises(ValueError):  # NumPy: cannot cycle empty values
            mx.np.put(mx.np.zeros((5,)), [0, 1], [])
        mx.np.put(mx.np.zeros((5,)), [], [])  # both empty: no-op, no raise
        out = mx.np.asarray(mx.nd.ones((2, 3)))  # legacy NDArray promotes
        assert isinstance(out, mx.np.ndarray)

    def test_put_along_axis_partial_axis_indices(self):
        import numpy as onp
        e = mx.np.array([[10.0, 30.0, 50.0], [40.0, 20.0, 60.0]])
        mx.np.put_along_axis(e, mx.np.array([[0, 1], [1, 0]]).astype("int32"),
                             mx.np.array([[1.0, 2.0], [3.0, 4.0]]), 1)
        h = onp.array([[10.0, 30.0, 50.0], [40.0, 20.0, 60.0]], onp.float32)
        onp.put_along_axis(h, onp.array([[0, 1], [1, 0]]),
                           onp.array([[1.0, 2.0], [3.0, 4.0]], onp.float32), 1)
        onp.testing.assert_allclose(e.asnumpy(), h)


class TestNpxOpBackedAdditions:
    """Round-4 npx tail: op-backed wrappers upstream gluon-numpy models
    call (masked softmax, deconv, norms, sequence ops, ctc, roi, slices)."""

    def test_masked_softmax(self):
        import numpy as onp
        x = mx.np.array([[1.0, 2.0, 3.0]])
        m = mx.np.array([[1, 1, 0]]).astype("bool")
        got = mx.npx.masked_softmax(x, m).asnumpy()
        e = onp.exp([1.0, 2.0])
        onp.testing.assert_allclose(got[0, :2], e / e.sum(), rtol=1e-5)
        assert got[0, 2] == 0.0
        lg = mx.npx.masked_log_softmax(x, m).asnumpy()
        onp.testing.assert_allclose(lg[0, :2], onp.log(e / e.sum()),
                                    rtol=1e-5)

    def test_slices(self):
        import numpy as onp
        a = mx.np.arange(10)
        onp.testing.assert_allclose(
            mx.npx.slice(a, (2,), (8,), (2,)).asnumpy(), [2, 4, 6])
        b = mx.np.arange(10).reshape(2, 5)
        assert mx.npx.slice_axis(b, 1, 1, 3).shape == (2, 2)

    def test_deconv_and_norms(self):
        import numpy as onp
        rs = onp.random.RandomState(0)
        d = mx.np.array(rs.randn(1, 2, 4, 4).astype("f"))
        w = mx.np.array(rs.randn(2, 3, 2, 2).astype("f"))
        assert mx.npx.deconvolution(d, w, kernel=(2, 2), stride=(2, 2),
                                    num_filter=3).shape == (1, 3, 8, 8)
        g, b = mx.np.ones((2,)), mx.np.zeros((2,))
        assert mx.npx.instance_norm(d, g, b).shape == (1, 2, 4, 4)
        assert mx.npx.group_norm(d, g, b, num_groups=2).shape == (1, 2, 4, 4)
        x = mx.np.array([[1.0, 2.0, 3.0]])
        n = mx.npx.l2_normalization(x).asnumpy()
        onp.testing.assert_allclose((n ** 2).sum(), 1.0, rtol=1e-5)

    def test_sequence_ops_and_scatter(self):
        import numpy as onp
        rs = onp.random.RandomState(0)
        s = mx.np.array(rs.randn(3, 2, 4).astype("f"))
        sl = mx.np.array([2.0, 3.0])
        last = mx.npx.sequence_last(s, sl)
        onp.testing.assert_allclose(last.asnumpy()[0], s.asnumpy()[1, 0],
                                    rtol=1e-6)
        rev = mx.npx.sequence_reverse(s, sl)
        onp.testing.assert_allclose(rev.asnumpy()[0, 0], s.asnumpy()[1, 0],
                                    rtol=1e-6)
        got = mx.npx.scatter_nd(mx.np.array([5.0]),
                                mx.np.array([[1]]).astype("int32"), (3,))
        onp.testing.assert_allclose(got.asnumpy(), [0, 5, 0])

    def test_ctc_and_roi(self):
        import numpy as onp
        rs = onp.random.RandomState(0)
        # CTC: (seq, batch, alphabet)
        data = mx.np.array(rs.rand(6, 1, 5).astype("f"))
        label = mx.np.array([[1.0, 2.0]])
        loss = mx.npx.ctc_loss(data, label)
        assert float(loss.asnumpy().ravel()[0]) > 0
        feat = mx.np.array(rs.rand(1, 2, 8, 8).astype("f"))
        rois = mx.np.array([[0.0, 0.0, 0.0, 4.0, 4.0]])
        out = mx.npx.roi_pooling(feat, rois, pooled_size=(2, 2),
                                 spatial_scale=1.0)
        assert out.shape == (1, 2, 2, 2)

    def test_npx_wrapper_review_regressions(self):
        """masked_softmax without mask = plain softmax; deconvolution
        honors a supplied bias; ctc_loss with only label_lengths binds
        positionally correct; additions appear in __all__."""
        import numpy as onp
        x = mx.np.array([[1.0, 2.0, 3.0]])
        onp.testing.assert_allclose(
            mx.npx.masked_softmax(x).asnumpy().sum(), 1.0, rtol=1e-5)
        d = mx.np.ones((1, 1, 2, 2))
        w = mx.np.ones((1, 1, 1, 1))
        b = mx.np.array([100.0])
        out = mx.npx.deconvolution(d, w, b, kernel=(1, 1), num_filter=1)
        assert float(out.asnumpy().ravel()[0]) == 101.0
        data = mx.np.array(onp.random.RandomState(0).rand(6, 1, 5)
                           .astype("f"))
        loss = mx.npx.ctc_loss(data, mx.np.array([[1.0, 2.0]]),
                               label_lengths=mx.np.array([2.0]))
        assert float(loss.asnumpy().ravel()[0]) > 0
        for name in ("masked_softmax", "ctc_loss", "deconvolution",
                     "slice_axis"):
            assert name in mx.npx.__all__


class TestIndexTricks:
    """numpy.lib.index_tricks mirrors (round 5: mgrid/ogrid/r_/c_)."""

    def test_mgrid_ogrid(self):
        import numpy as onp
        onp.testing.assert_allclose(mx.np.mgrid[0:3, 0:2].asnumpy(),
                                    onp.mgrid[0:3, 0:2])
        onp.testing.assert_allclose(mx.np.mgrid[1:2:5j].asnumpy(),
                                    onp.mgrid[1:2:5j])
        got = mx.np.ogrid[0:3, 0:2]
        want = onp.ogrid[0:3, 0:2]
        for a, b in zip(got, want):
            onp.testing.assert_allclose(a.asnumpy(), b)

    def test_r_and_c(self):
        import numpy as onp
        onp.testing.assert_allclose(
            mx.np.r_[0:4, mx.np.array([9.0, 8.0]), 7].asnumpy(),
            onp.r_[0:4, [9.0, 8.0], 7])
        onp.testing.assert_allclose(mx.np.r_[1:2:5j].asnumpy(),
                                    onp.r_[1:2:5j])
        onp.testing.assert_allclose(
            mx.np.c_[mx.np.array([1, 2, 3]), mx.np.array([4, 5, 6])]
            .asnumpy(), onp.c_[[1, 2, 3], [4, 5, 6]])
        import pytest
        with pytest.raises(NotImplementedError):
            mx.np.r_["2,0", mx.np.array([1.0])]
