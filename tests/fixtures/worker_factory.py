"""Model factories importable by serving worker subprocesses.

A :class:`~mxnet_tpu.serving.remote.RemoteReplica` ships a
``module:function`` spec (not a closure) across the exec boundary;
tests point workers here via ``python_paths=[tests/fixtures]``.
Weights are seeded deterministically so a worker's responses are
bit-identical to an in-process oracle built from the same factory.
"""
import numpy as np


def tiny_net(seed=0, in_units=8, units=4):
    """The test_serving_router make_net model, importable by spec."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn

    net = nn.Dense(units, in_units=in_units)
    net.initialize()
    rs = np.random.RandomState(seed)
    net.weight.set_data(mx.nd.array(
        rs.randn(units, in_units).astype(np.float32)))
    net.bias.set_data(mx.nd.array(rs.randn(units).astype(np.float32)))
    net.hybridize()
    return net


def tiny_llama(seed=7, vocab_size=64, num_layers=2, units=32,
               hidden_size=64, num_heads=4, num_kv_heads=2):
    """A 2-layer LLaMA small enough to decode on CPU in a test worker.

    ``mx.random.seed`` makes ``initialize()`` reproducible, so a worker
    process and an in-process oracle built from the same spec hold
    bit-identical weights — the decode bit-identity tests depend on it.
    """
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.nlp import LlamaModel

    mx.random.seed(seed)
    net = LlamaModel(vocab_size=vocab_size, num_layers=num_layers,
                     units=units, hidden_size=hidden_size,
                     num_heads=num_heads, num_kv_heads=num_kv_heads,
                     rope_theta=10000.0, eps=1e-6)
    net.initialize()
    net(mx.nd.zeros((1, 2), dtype="int32"))  # materialize deferred shapes
    net.hybridize()
    return net


def paced_block(dispatch_ms=20.0):
    """Eager block with a fixed dispatch latency — overload/backpressure
    tests need a controlled service rate, not raw speed."""
    import time

    import mxnet_tpu as mx

    class PacedBlock(mx.gluon.Block):
        def forward(self, x):
            time.sleep(dispatch_ms / 1e3)
            return x * 2

    return PacedBlock()
