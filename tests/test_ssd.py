"""SSD / multibox op tests (reference: tests for multibox_prior/
target/detection + example/ssd training behavior).

Oracles: hand-computed anchor geometry, encode→decode round-trip
(MultiBoxTarget's offsets fed through MultiBoxDetection must reproduce
the ground-truth box), and a trainable toy SSD that learns a fixed scene.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.gluon import Trainer
from mxnet_tpu.gluon.model_zoo import vision


class TestMultiBoxPrior:
    def test_geometry(self):
        x = mx.nd.ones((1, 1, 2, 2))
        an = mx.nd.contrib.MultiBoxPrior(x, sizes=(0.5,), ratios=(1.0,))
        a = an.asnumpy()[0]
        assert a.shape == (4, 4)
        # first cell center (0.25, 0.25), size 0.5 -> [0, 0, 0.5, 0.5]
        onp.testing.assert_allclose(a[0], [0.0, 0.0, 0.5, 0.5], atol=1e-6)
        # last cell center (0.75, 0.75)
        onp.testing.assert_allclose(a[3], [0.5, 0.5, 1.0, 1.0], atol=1e-6)

    def test_anchor_count_and_clip(self):
        x = mx.nd.ones((1, 1, 3, 5))
        an = mx.nd.contrib.MultiBoxPrior(
            x, sizes=(0.9, 0.4), ratios=(1.0, 2.0, 0.5), clip=True)
        # A = 2 + 3 - 1 = 4
        assert an.shape == (1, 3 * 5 * 4, 4)
        a = an.asnumpy()
        assert a.min() >= 0.0 and a.max() <= 1.0


class TestTargetDetectRoundTrip:
    def test_encode_decode_recovers_gt(self):
        """Offsets computed by MultiBoxTarget, decoded by
        MultiBoxDetection with a perfect classifier, must reproduce the
        ground-truth box."""
        x = mx.nd.ones((1, 1, 4, 4))
        an = mx.nd.contrib.MultiBoxPrior(x, sizes=(0.4,),
                                         ratios=(1.0, 2.0))
        n = an.shape[1]
        gt = onp.array([[[1, 0.22, 0.31, 0.58, 0.66]]], "float32")
        cls_pred = mx.nd.zeros((1, 3, n))
        loc_t, loc_m, cls_t = mx.nd.contrib.MultiBoxTarget(
            an, mx.nd.array(gt), cls_pred)
        ct = cls_t.asnumpy()[0]
        assert (ct == 2).sum() >= 1          # class 1 -> target 2
        # perfect softmax probs: matched anchors say class 1
        probs = onp.zeros((1, 3, n), "float32")
        probs[0, 0, :] = 1.0                 # background everywhere
        matched = ct > 0
        probs[0, 0, matched] = 0.0
        probs[0, 2, matched] = 1.0
        det = mx.nd.contrib.MultiBoxDetection(
            mx.nd.array(probs), loc_t, an, threshold=0.5,
            nms_threshold=0.5).asnumpy()[0]
        kept = det[det[:, 0] >= 0]
        assert len(kept) >= 1
        onp.testing.assert_allclose(kept[0, 2:6], gt[0, 0, 1:5],
                                    atol=1e-3)
        assert kept[0, 0] == 1.0             # class id back to 0-based

    def test_hard_negative_mining(self):
        x = mx.nd.ones((1, 1, 4, 4))
        an = mx.nd.contrib.MultiBoxPrior(x, sizes=(0.4,), ratios=(1.0,))
        n = an.shape[1]
        gt = onp.array([[[0, 0.2, 0.2, 0.6, 0.6]]], "float32")
        rs = onp.random.RandomState(0)
        cls_pred = mx.nd.array(rs.randn(1, 2, n).astype("float32"))
        _lt, _lm, ct = mx.nd.contrib.MultiBoxTarget(
            an, mx.nd.array(gt), cls_pred, negative_mining_ratio=3.0)
        c = ct.asnumpy()[0]
        n_pos = (c > 0).sum()
        n_neg = (c == 0).sum()
        n_ign = (c == -1).sum()
        assert n_pos >= 1 and n_ign > 0
        assert n_neg <= 3 * n_pos + 1        # mined ratio respected


class TestSSDModel:
    def test_shapes_and_zoo(self):
        net = vision.get_model("ssd_toy", num_classes=3)
        net.initialize()
        x = mx.nd.ones((2, 3, 64, 64))
        an, cp, bp = net(x)
        assert an.shape[0] == 1 and an.shape[2] == 4
        assert cp.shape == (2, an.shape[1], 4)
        assert bp.shape == (2, an.shape[1] * 4)
        det = net.detect(x)
        assert det.shape == (2, an.shape[1], 6)

    def test_training_learns_fixed_scene(self):
        onp.random.seed(3)
        mx.random.seed(3)
        net = vision.ssd_toy(num_classes=2)
        net.initialize()
        loss_fn = vision.SSDMultiBoxLoss()
        # one fixed image with one box of class 0
        rs = onp.random.RandomState(4)
        img = rs.rand(1, 3, 32, 32).astype("float32")
        img[:, :, 8:24, 8:24] += 2.0          # bright square = the object
        x = mx.nd.array(img)
        label = mx.nd.array(onp.array(
            [[[0, 0.25, 0.25, 0.75, 0.75]]], "float32"))
        trainer = Trainer(net.collect_params(), "adam",
                          {"learning_rate": 5e-3})
        first = last = None
        for i in range(40):
            with autograd.record():
                anchors, cls_preds, box_preds = net(x)
                loc_t, loc_m, cls_t = net.targets(anchors, label,
                                                  cls_preds)
                loss = loss_fn(cls_preds, box_preds, cls_t, loc_t, loc_m)
            loss.backward()
            trainer.step(1)
            v = float(loss.asnumpy())
            first = first if first is not None else v
            last = v
        assert last < first * 0.5, (first, last)
        det = net.detect(x, threshold=0.3).asnumpy()[0]
        kept = det[det[:, 0] >= 0]
        assert len(kept) >= 1
        # best detection overlaps the ground truth decently
        bx = kept[0, 2:6]
        ix = max(0, min(bx[2], 0.75) - max(bx[0], 0.25)) * \
            max(0, min(bx[3], 0.75) - max(bx[1], 0.25))
        union = (bx[2] - bx[0]) * (bx[3] - bx[1]) + 0.25 - ix
        assert ix / union > 0.3, kept[0]


def test_two_gts_sharing_best_anchor_both_match():
    """Regression: iterative bipartite matching — two gt boxes whose
    best anchor coincides must BOTH get a positive anchor."""
    x = mx.nd.ones((1, 1, 2, 2))
    an = mx.nd.contrib.MultiBoxPrior(x, sizes=(0.5,), ratios=(1.0,))
    # both gts' best anchor is cell (0,0); the loser must fall back to
    # its next-best positively-overlapping anchor
    gt = onp.array([[[0, 0.02, 0.02, 0.48, 0.48],
                     [1, 0.10, 0.10, 0.60, 0.60]]], "float32")
    cp = mx.nd.zeros((1, 3, an.shape[1]))
    _lt, _lm, ct = mx.nd.contrib.MultiBoxTarget(an, mx.nd.array(gt), cp)
    c = ct.asnumpy()[0]
    assert (c == 1).sum() >= 1 and (c == 2).sum() >= 1, c


def test_prior_reference_order():
    """Anchor order: sizes with ratio[0] first, then ratios[1:] with
    size[0] — the reference emission order."""
    x = mx.nd.ones((1, 1, 1, 1))
    an = mx.nd.contrib.MultiBoxPrior(
        x, sizes=(0.4, 0.2), ratios=(1.0, 4.0)).asnumpy()[0]
    w = an[:, 2] - an[:, 0]
    h = an[:, 3] - an[:, 1]
    onp.testing.assert_allclose(w, [0.4, 0.2, 0.8], atol=1e-6)
    onp.testing.assert_allclose(h, [0.4, 0.2, 0.2], atol=1e-6)


def test_ssd_exports(tmp_path):
    net = vision.ssd_toy(num_classes=2)
    net.initialize()
    x = mx.nd.ones((2, 3, 32, 32))
    net(x)
    net.hybridize()
    net(x)
    prefix = str(tmp_path / "ssd")
    net.export(prefix)                      # symbolic trace must work
    sym = mx.sym.load(prefix + "-symbol.json")
    assert "MultiBoxPrior" in sym.tojson()


def test_svm_output_hinge_grad():
    """SVMOutput backward: hinge gradient w.r.t. scores, not identity."""
    from mxnet_tpu import autograd

    x = mx.nd.array(onp.array([[2.0, 1.5, -1.0]], "float32"))
    x.attach_grad()
    lab = mx.nd.array(onp.array([0.0], "float32"))
    with autograd.record():
        out = mx.nd.SVMOutput(x, lab, margin=1.0, use_linear=True,
                              regularization_coefficient=1.0)
    out.backward()
    g = x.grad.asnumpy()[0]
    # class 1 violates (2.0 - 1.5 < 1): +1 there, -1 at the label;
    # class 2 satisfies (2.0 - (-1.0) >= 1): 0
    onp.testing.assert_allclose(g, [-1.0, 1.0, 0.0], atol=1e-6)
