"""Tests for mxnet_tpu.parallel — mesh, sharding rules, fused TrainStep.

Runs on the virtual 8-device CPU mesh (root conftest forces
XLA_FLAGS=--xla_force_host_platform_device_count=8), the fake-cluster
strategy from SURVEY.md §4.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import parallel as par
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn, loss as gloss
from jax.sharding import PartitionSpec as P


def _mlp(units=64):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(units, activation="relu"))
        net.add(nn.Dense(10))
    net.initialize()
    return net


class TestMesh:
    def test_default_all_dp(self):
        mesh = par.make_mesh()
        assert mesh.shape["dp"] == 8

    def test_infer_axis(self):
        mesh = par.make_mesh({"dp": -1, "tp": 2})
        assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2

    def test_bad_sizes(self):
        with pytest.raises(MXNetError):
            par.make_mesh({"dp": 3})
        with pytest.raises(MXNetError):
            par.make_mesh({"dp": -1, "tp": -1})

    def test_use_mesh(self):
        mesh = par.make_mesh({"dp": 8})
        assert par.current_mesh() is None
        with par.use_mesh(mesh):
            assert par.current_mesh() is mesh
        assert par.current_mesh() is None


class TestShardingRules:
    def test_first_match_wins_and_fallback(self):
        rules = par.ShardingRules([(r"_weight$", P("tp", None))])
        mesh = par.make_mesh({"dp": 2, "tp": 4})
        assert par.spec_for_param("dense0_weight", (128, 16), rules, mesh) == P("tp", None)
        # 10 % 4 != 0 -> replicate instead of invalid sharding
        assert par.spec_for_param("dense1_weight", (10, 16), rules, mesh) == P()
        assert par.spec_for_param("dense0_bias", (128,), rules, mesh) == P()

    def test_shard_parameters(self):
        net = _mlp(128)
        net(mx.nd.array(np.zeros((2, 16), dtype="float32")))  # settle shapes
        mesh = par.make_mesh({"dp": 2, "tp": 4})
        w = [p for p in net.collect_params().values()
             if p.shape == (128, 16)][0]
        rules = par.ShardingRules([(w.name + "$", P("tp", None))])
        specs = par.shard_parameters(net.collect_params(), mesh, rules)
        assert w.data().data.sharding.spec == P("tp", None)
        assert specs[w.name] == P("tp", None)


class TestTrainStep:
    def test_dp_converges(self):
        np.random.seed(0)
        mx.random.seed(0)
        net = _mlp()
        mesh = par.make_mesh({"dp": 8})
        step = par.TrainStep(net, gloss.SoftmaxCrossEntropyLoss(), "adam",
                             mesh=mesh, optimizer_params={"learning_rate": 1e-2})
        x = mx.nd.array(np.random.randn(32, 20).astype("float32"))
        y = mx.nd.array(np.random.randint(0, 10, (32,)).astype("float32"))
        losses = [float(step(x, y)[0].asnumpy()) for _ in range(8)]
        assert losses[-1] < losses[0]

    def test_dp_matches_single_device(self):
        """DP over 8 devices must be numerically the single-device step."""
        def run(mesh_axes):
            np.random.seed(42)
            mx.random.seed(42)
            net = _mlp()
            import jax
            n = int(np.prod(list(mesh_axes.values())))
            mesh = par.make_mesh(mesh_axes, devices=jax.devices()[:n])
            step = par.TrainStep(net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
                                 mesh=mesh,
                                 optimizer_params={"learning_rate": 0.5})
            x = mx.nd.array(np.random.RandomState(1).randn(16, 12).astype("float32"))
            y = mx.nd.array(np.random.RandomState(2).randint(0, 10, (16,)).astype("float32"))
            losses = [float(step(x, y)[0].asnumpy()) for _ in range(3)]
            return losses

        l_dp = run({"dp": 8})
        l_single = run({"dp": 1})
        np.testing.assert_allclose(l_dp, l_single, rtol=2e-5)

    def test_tp_converges_and_layout_stable(self):
        np.random.seed(0)
        net = _mlp(128)
        net(mx.nd.array(np.zeros((2, 20), dtype="float32")))  # settle shapes
        params = list(net.collect_params().values())
        w0 = [p for p in params if p.shape == (128, 20)][0]
        b0 = [p for p in params if p.shape == (128,)][0]
        w1 = [p for p in params if p.shape == (10, 128)][0]
        mesh = par.make_mesh({"dp": 2, "tp": 4})
        rules = par.ShardingRules([
            (w0.name + "$", P("tp", None)),
            (b0.name + "$", P("tp")),
            (w1.name + "$", P(None, "tp")),
        ])
        step = par.TrainStep(net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
                             mesh=mesh, rules=rules,
                             optimizer_params={"learning_rate": 0.1,
                                               "momentum": 0.9})
        x = mx.nd.array(np.random.randn(16, 20).astype("float32"))
        y = mx.nd.array(np.random.randint(0, 10, (16,)).astype("float32"))
        losses = [float(step(x, y)[0].asnumpy()) for _ in range(6)]
        assert losses[-1] < losses[0]
        assert w0.data().data.sharding.spec == P("tp", None)

    def test_batchnorm_aux_updates(self):
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(16, activation="relu"))
            net.add(nn.BatchNorm())
            net.add(nn.Dense(4))
        net.initialize()
        mesh = par.make_mesh({"dp": 8})
        step = par.TrainStep(net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
                             mesh=mesh, optimizer_params={"learning_rate": 0.1})
        x = mx.nd.array(np.random.randn(16, 8).astype("float32") * 3 + 1)
        y = mx.nd.array(np.random.randint(0, 4, (16,)).astype("float32"))
        step(x, y)  # settles deferred shapes and updates stats once
        bn = [p for p in net.collect_params().values()
              if p.name.endswith("running_mean")][0]
        before = bn.data().asnumpy().copy()
        step(x, y)
        after = bn.data().asnumpy()
        assert not np.allclose(before, after), "BN moving stats must update"

    def test_lr_schedule_stays_one_executable(self):
        from mxnet_tpu import lr_scheduler
        net = _mlp()
        mesh = par.make_mesh({"dp": 8})
        sched = lr_scheduler.FactorScheduler(step=2, factor=0.5, base_lr=0.1)
        step = par.TrainStep(net, gloss.SoftmaxCrossEntropyLoss(), "adam",
                             mesh=mesh,
                             optimizer_params={"learning_rate": 0.1,
                                               "lr_scheduler": sched})
        x = mx.nd.array(np.random.randn(8, 4).astype("float32"))
        y = mx.nd.array(np.random.randint(0, 10, (8,)).astype("float32"))
        for _ in range(5):
            step(x, y)
        # one shape key -> one compiled executable despite the schedule
        assert len(step._cache) == 1
