"""NLP model zoo tests: transformer/BERT/Llama forward shapes, causality,
weight tying, sharded training on the 8-device CPU mesh, hybridize parity."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import parallel as par
from mxnet_tpu.gluon import loss as gloss
from mxnet_tpu.gluon.model_zoo import nlp


def toks(b, l, vocab, seed=0):
    return mx.nd.array(
        np.random.RandomState(seed).randint(0, vocab, (b, l)).astype("float32"))


class _LMLoss:
    def __init__(self):
        self._l = gloss.SoftmaxCrossEntropyLoss()

    def __call__(self, out, labels):
        if isinstance(out, (tuple, list)):
            out = out[-1]  # mlm logits
        return self._l(out.reshape((-1, out.shape[-1])), labels.reshape((-1,)))


class TestForwardShapes:
    def test_bert_outputs(self):
        bert = nlp.BERTModel(vocab_size=100, max_length=32, num_layers=2,
                             units=32, hidden_size=64, num_heads=4)
        bert.initialize()
        seq, pooled, cls, mlm = bert(
            toks(2, 16, 100), toks(2, 16, 2, 1),
            mx.nd.array(np.ones((2, 16), dtype="float32")))
        assert seq.shape == (2, 16, 32)
        assert pooled.shape == (2, 32)
        assert cls.shape == (2, 2)
        assert mlm.shape == (2, 16, 100)

    def test_transformer_nmt(self):
        tr = nlp.Transformer(src_vocab=50, num_layers=2, units=32,
                             hidden_size=64, num_heads=4, max_length=32)
        tr.initialize()
        out = tr(toks(2, 10, 50), toks(2, 12, 50, 1))
        assert out.shape == (2, 12, 50)

    def test_llama_logits(self):
        ll = nlp.llama_tiny()
        ll.initialize()
        out = ll(toks(2, 16, 256))
        assert out.shape == (2, 16, 256)

    def test_get_model(self):
        m = nlp.get_model("llama_tiny")
        assert isinstance(m, nlp.LlamaModel)
        with pytest.raises(ValueError):
            nlp.get_model("nope")


class TestSemantics:
    def test_llama_causality(self):
        """Changing a future token must not change earlier logits."""
        ll = nlp.llama_tiny()
        ll.initialize()
        x1 = toks(1, 8, 256, 3)
        x2 = x1.copy()
        x2[0, -1] = (float(x2[0, -1].asnumpy()) + 1) % 256
        o1 = ll(x1).asnumpy()
        o2 = ll(x2).asnumpy()
        np.testing.assert_allclose(o1[0, :-1], o2[0, :-1], rtol=1e-4,
                                   atol=1e-5)
        assert not np.allclose(o1[0, -1], o2[0, -1])

    def test_bert_mask_blocks_padding(self):
        """Masked (padding) keys must not influence valid positions."""
        bert = nlp.BERTModel(vocab_size=50, max_length=16, num_layers=1,
                             units=16, hidden_size=32, num_heads=2,
                             dropout=0.0, use_pooler=False,
                             use_classifier=False, use_decoder=False)
        bert.initialize()
        x1 = toks(1, 8, 50, 5)
        x2 = x1.copy()
        x2[0, -2:] = 0  # change padding-region tokens
        mask = np.ones((1, 8), dtype="float32")
        mask[0, -2:] = 0
        m = mx.nd.array(mask)
        o1 = bert(x1, None, m).asnumpy()
        o2 = bert(x2, None, m).asnumpy()
        np.testing.assert_allclose(o1[0, :6], o2[0, :6], rtol=1e-4, atol=1e-5)

    def test_bert_tied_decoder(self):
        """MLM decoder weight IS the word-embedding weight."""
        bert = nlp.BERTModel(vocab_size=40, max_length=8, num_layers=1,
                             units=16, hidden_size=32, num_heads=2)
        bert.initialize()
        emb_w = bert.word_embed.params.get("weight")
        dec_w = bert.decoder.params.get("weight")
        assert emb_w is dec_w

    def test_rope_rotation_invariance(self):
        """RoPE preserves norms (pure rotation of pairs)."""
        import mxnet_tpu.ndarray as nd
        x = mx.nd.array(np.random.RandomState(0)
                        .randn(2, 8, 4, 16).astype("float32"))
        r = nd.rope(x, theta=10000.0)
        np.testing.assert_allclose(
            np.linalg.norm(x.asnumpy(), axis=-1),
            np.linalg.norm(r.asnumpy(), axis=-1), rtol=1e-5)

    def test_rope_rotate_half_convention(self):
        """Default rope matches the Llama/HF rotate-half formula:
        x*cos + rotate_half(x)*sin with half-split frequencies."""
        import mxnet_tpu.ndarray as nd
        rs = np.random.RandomState(1)
        b, l, h, d = 2, 6, 3, 8
        x = rs.randn(b, l, h, d).astype("float32")
        out = nd.rope(mx.nd.array(x), theta=10000.0).asnumpy()

        pos = np.arange(l, dtype=np.float64)
        inv_freq = 1.0 / (10000.0 ** (np.arange(0, d, 2) / d))
        ang = pos[:, None] * inv_freq[None, :]               # (L, d/2)
        cos = np.concatenate([np.cos(ang)] * 2, -1)[None, :, None, :]
        sin = np.concatenate([np.sin(ang)] * 2, -1)[None, :, None, :]
        rot = np.concatenate([-x[..., d // 2:], x[..., : d // 2]], -1)
        ref = x * cos + rot * sin
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_rope_interleaved_convention(self):
        """interleaved=True keeps the GPT-J even/odd pair rotation."""
        import mxnet_tpu.ndarray as nd
        rs = np.random.RandomState(2)
        x = rs.randn(1, 4, 2, 6).astype("float32")
        out = nd.rope(mx.nd.array(x), theta=100.0,
                      interleaved=True).asnumpy()
        d = 6
        pos = np.arange(4, dtype=np.float64)
        inv_freq = 1.0 / (100.0 ** (np.arange(0, d, 2) / d))
        ang = pos[:, None] * inv_freq[None, :]
        cos = np.cos(ang)[None, :, None, :]
        sin = np.sin(ang)[None, :, None, :]
        x1, x2 = x[..., 0::2], x[..., 1::2]
        ref = np.empty_like(x)
        ref[..., 0::2] = x1 * cos - x2 * sin
        ref[..., 1::2] = x2 * cos + x1 * sin
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_sdp_attention_matches_manual(self):
        import mxnet_tpu.ndarray as nd
        rs = np.random.RandomState(0)
        q = rs.randn(1, 2, 4, 8).astype("float32")
        k = rs.randn(1, 2, 4, 8).astype("float32")
        v = rs.randn(1, 2, 4, 8).astype("float32")
        out = nd.sdp_attention(mx.nd.array(q), mx.nd.array(k),
                               mx.nd.array(v)).asnumpy()
        scores = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(8)
        e = np.exp(scores - scores.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = np.einsum("bhqk,bhkd->bhqd", p, v)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


class TestShardedTraining:
    def test_llama_tp_sp_dp_trains(self):
        ll = nlp.llama_tiny()
        ll.initialize()
        mesh = par.make_mesh({"dp": 2, "sp": 2, "tp": 2})
        step = par.TrainStep(ll, _LMLoss(), "adamw", mesh=mesh,
                             rules=nlp.llama_sharding_rules(), seq_axis="sp",
                             optimizer_params={"learning_rate": 3e-3})
        x, y = toks(4, 16, 256, 1), toks(4, 16, 256, 2)
        losses = [float(step(x, y)[0].asnumpy()) for _ in range(6)]
        assert losses[-1] < losses[0]
        from jax.sharding import PartitionSpec as P
        w = [p for p in ll.collect_params().values()
             if p.name.endswith("gateup_weight")][0]
        assert w.data().data.sharding.spec == P("tp", None)

    def test_bert_tp_trains(self):
        bert = nlp.BERTModel(vocab_size=100, max_length=32, num_layers=2,
                             units=32, hidden_size=64, num_heads=4,
                             dropout=0.0, use_pooler=False,
                             use_classifier=False, use_decoder=True)
        bert.initialize()
        step = par.TrainStep(bert, _LMLoss(), "adamw",
                             mesh=par.make_mesh({"dp": 4, "tp": 2}),
                             rules=nlp.bert_sharding_rules(),
                             optimizer_params={"learning_rate": 1e-2})
        x, y = toks(4, 16, 100, 1), toks(4, 16, 100, 2)
        losses = [float(step(x, y)[0].asnumpy()) for _ in range(15)]
        assert losses[-1] < losses[0] * 0.8


class TestHybridize:
    def test_llama_hybridize_parity(self):
        ll = nlp.llama_tiny()
        ll.initialize()
        x = toks(2, 8, 256, 7)
        eager = ll(x).asnumpy()
        ll.hybridize()
        jitted = ll(x).asnumpy()
        np.testing.assert_allclose(eager, jitted, rtol=1e-4, atol=1e-5)


def test_remat_policy_grads_match():
    """remat policies (full save-nothing vs dots-saveable vs none) must be
    pure memory/FLOPs trades — identical losses and gradients."""
    import numpy as onp

    from mxnet_tpu import autograd, parallel as par
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo.nlp.llama import LlamaModel

    import jax

    results = {}
    for remat in (False, True, "dots"):
        import mxnet_tpu as mx

        mx.random.seed(7)  # initializer reproducibility contract (r5)
        net = LlamaModel(vocab_size=64, num_layers=2, units=32,
                         hidden_size=64, num_heads=4, num_kv_heads=2,
                         remat=remat, fused_ce=True)
        net.initialize()
        mesh = par.make_mesh({"dp": 1}, devices=jax.devices()[:1])
        step = par.TrainStep(net, lambda outs, *a: outs, "sgd", mesh=mesh,
                             loss_only=True,
                             optimizer_params={"learning_rate": 0.1})
        rs = onp.random.RandomState(3)
        toks = mx.nd.array(rs.randint(0, 64, (2, 16)).astype(onp.int32))
        labs = mx.nd.array(rs.randint(0, 64, (2, 16)).astype(onp.int32))
        loss, _ = step((toks, labs), ())
        params = {k: v.data().asnumpy() for k, v in
                  net._collect_params_with_prefix().items()}
        results[str(remat)] = (float(loss.asnumpy()), params)

    base_loss, base_params = results["False"]
    for key in ("True", "dots"):
        loss_v, params_v = results[key]
        assert loss_v == pytest.approx(base_loss, rel=1e-5), key
        for k in base_params:
            onp.testing.assert_allclose(params_v[k], base_params[k],
                                        rtol=1e-4, atol=1e-5,
                                        err_msg=f"{key}:{k}")
