"""Subgraph backend / custom pass tests (reference:
tests/python/mkl/test_subgraph.py — conv+BN fusion parity, backend
registration; SURVEY §2.1 subgraph partitioning row).

Oracle = the unfused graph: a backend pass must preserve inference
outputs exactly (up to float assoc) while changing the graph/params.
"""
import json

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import subgraph
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn


def _convnet():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, kernel_size=3, padding=1),
            nn.BatchNorm(),
            nn.Activation("relu"),
            nn.Conv2D(4, kernel_size=1, use_bias=False),
            nn.BatchNorm(),
            nn.Flatten(), nn.Dense(3))
    net.initialize()
    return net


class TestFuseConvBN:
    def test_symbol_path_matches_and_shrinks(self, tmp_path):
        onp.random.seed(0)
        net = _convnet()
        x = mx.nd.array(onp.random.RandomState(1).randn(2, 3, 8, 8)
                        .astype("float32"))
        net(x)          # settle + BN stats step (nontrivial mean/var)
        want = net(x).asnumpy()
        net.hybridize()
        net(x)
        prefix = str(tmp_path / "m")
        net.export(prefix)
        sym = mx.sym.load(prefix + "-symbol.json")
        saved = mx.nd.load(prefix + "-0000.params")
        arg = {k[4:]: v for k, v in saved.items() if k.startswith("arg:")}
        aux = {k[4:]: v for k, v in saved.items() if k.startswith("aux:")}

        fused = sym.optimize_for("TPU", arg, aux)
        ops = [n["op"] for n in json.loads(fused.tojson())["nodes"]]
        assert "BatchNorm" not in ops
        assert not aux                      # moving stats consumed
        assert not any("gamma" in k or "beta" in k for k in arg)

        from mxnet_tpu.symbol.executor import eval_symbol

        feed = dict(arg)
        feed["data"] = x
        got = eval_symbol(fused, feed).asnumpy()
        onp.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_gluon_optimize_for(self):
        onp.random.seed(2)
        net = _convnet()
        x = mx.nd.array(onp.random.RandomState(3).randn(2, 3, 8, 8)
                        .astype("float32"))
        net(x)
        want = net(x).asnumpy()
        got = net.optimize_for(x, backend="TPU").asnumpy()
        onp.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
        # swapped-in graph serves later calls too
        again = net(x).asnumpy()
        onp.testing.assert_allclose(again, want, rtol=1e-4, atol=1e-5)

    def test_shared_conv_output_not_fused(self):
        # conv output consumed by BN AND a residual add: must not fold
        d = mx.sym.var("data")
        w = mx.sym.var("conv_weight")
        c = mx.sym.Convolution(d, w, kernel=(1, 1), num_filter=2,
                               no_bias=True, name="conv")
        g_, b_, m_, v_ = (mx.sym.var(n) for n in ("g", "b", "m", "v"))
        bn = mx.sym.BatchNorm(c, g_, b_, m_, v_, name="bn")
        out = bn + c
        rs = onp.random.RandomState(4)
        arg = {"conv_weight": mx.nd.array(rs.randn(2, 2, 1, 1)
                                          .astype("float32")),
               "g": mx.nd.ones((2,)), "b": mx.nd.zeros((2,))}
        aux = {"m": mx.nd.zeros((2,)), "v": mx.nd.ones((2,))}
        fused = out.optimize_for("TPU", arg, aux)
        ops = [n["op"] for n in json.loads(fused.tojson())["nodes"]]
        assert "BatchNorm" in ops          # fusion correctly skipped


class TestPassRegistry:
    def test_custom_pass_and_backend(self):
        calls = []

        @subgraph.register_pass("test_noop_pass")
        def _noop(sym, arg, aux, **kw):
            calls.append(kw)
            return sym, arg, aux

        subgraph.register_backend("TEST_BE", ["test_noop_pass"])
        assert "TEST_BE" in subgraph.list_backends()
        s = mx.sym.var("x") + 1.0
        s.optimize_for("test_be", marker=42)   # case-insensitive
        assert calls and calls[0]["marker"] == 42

    def test_unknown_backend_and_pass(self):
        with pytest.raises(MXNetError, match="unknown backend"):
            (mx.sym.var("x") + 1.0).optimize_for("NOPE")
        with pytest.raises(MXNetError, match="unknown passes"):
            subgraph.register_backend("BAD", ["does_not_exist"])


def test_optimized_block_cleared_on_reload(tmp_path):
    """Regression: the optimize_for graph holds folded param COPIES;
    load_parameters / hybridize must reconnect the live params."""
    onp.random.seed(9)
    net = _convnet()
    x = mx.nd.array(onp.random.RandomState(10).randn(2, 3, 8, 8)
                    .astype("float32"))
    net(x)
    f = str(tmp_path / "w.params")
    net.save_parameters(f)
    net.optimize_for(x, backend="TPU")
    assert getattr(net, "_optimized_block", None) is not None
    net.load_parameters(f)
    assert getattr(net, "_optimized_block", None) is None
    net.optimize_for(x, backend="TPU")
    net.hybridize()
    assert getattr(net, "_optimized_block", None) is None


def test_fuse_eps_default_matches_op():
    """Regression: a BN node with no eps attr runs with the OP default
    (1e-3); the fold must use the same value."""
    d = mx.sym.var("data")
    w = mx.sym.var("w")
    c = mx.sym.Convolution(d, w, kernel=(1, 1), num_filter=2,
                           no_bias=True, name="c")
    g_, b_, m_, v_ = (mx.sym.var(n) for n in "gbmv")
    out = mx.sym.BatchNorm(c, g_, b_, m_, v_, fix_gamma=False, name="bn")
    rs = onp.random.RandomState(11)
    arg = {"w": mx.nd.array(rs.randn(2, 2, 1, 1).astype("float32")),
           "g": mx.nd.array(rs.rand(2).astype("float32") + 0.5),
           "b": mx.nd.zeros((2,))}
    aux = {"m": mx.nd.zeros((2,)),
           "v": mx.nd.array(onp.full(2, 1e-3, "float32"))}  # eps-sized var
    from mxnet_tpu.symbol.executor import eval_symbol

    x = mx.nd.array(rs.randn(2, 2, 4, 4).astype("float32"))
    feed = dict(arg); feed.update(aux); feed["data"] = x
    want = eval_symbol(out, feed).asnumpy()
    fused = out.optimize_for("TPU", arg, aux)
    feed2 = dict(arg); feed2["data"] = x
    got = eval_symbol(fused, feed2).asnumpy()
    onp.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
