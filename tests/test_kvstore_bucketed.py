"""Bucketed gradient fusion tests (ISSUE 5).

The kvstore's batched ``pushpull`` coalesces keys into dtype-segregated
flat buckets (``MXNET_KV_BUCKET_MB``) and reduces each with ONE
collective. Contracts under test:

* bit-identity — bucketed uncompressed exchange == per-key exchange,
  on the local and ``tpu_sync`` stores and through a data-parallel
  Trainer step;
* planning — mixed dtypes split into separate buckets, a single param
  larger than the cap gets its own bucket, dispatch honors the
  descending-priority order;
* compression semantics — per-bucket 2-bit error feedback converges to
  the true gradient sum, residual state survives ``Trainer.save_states``
  and ``CheckpointManager`` resume bit-exactly, unsupported dtypes raise
  ``MXNetError`` instead of silently casting;
* telemetry — the bucketed path records collective-dispatch/bucket-byte
  counters.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import kvstore as kv
from mxnet_tpu.base import MXNetError
from mxnet_tpu.kvstore.bucketing import plan_buckets

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SHAPES = [(4, 5), (3,), (2, 2, 2), (7,), (1, 9)]


def _grads(shapes=SHAPES, copies=2, seed=0, dtype=np.float32):
    rs = np.random.RandomState(seed)
    return [[rs.randn(*sh).astype(dtype) for _ in range(copies)]
            for sh in shapes]


def _exchange(store, grads_np, shapes=SHAPES, dtype="float32",
              spread_devices=True):
    """Init + one batched pushpull; returns pulled numpy per key/slot."""
    copies = len(grads_np[0])
    ctx = [mx.Context("cpu", c if spread_devices else 0)
           for c in range(copies)]
    vals = [[mx.nd.array(g, ctx=c, dtype=dtype)
             for c, g in zip(ctx, gl)] for gl in grads_np]
    outs = [[mx.nd.zeros(sh, ctx=c, dtype=dtype) for c in ctx]
            for sh in shapes]
    for i, sh in enumerate(shapes):
        store.init(i, mx.nd.zeros(sh, dtype=dtype))
    keys = list(range(len(shapes)))
    store.pushpull(keys, vals, out=outs,
                   priority=[-k for k in keys])
    return [[o.asnumpy() for o in ol] for ol in outs]


class TestBitIdentity:
    @pytest.mark.parametrize("store_type", ["device", "tpu_sync"])
    def test_bucketed_matches_perkey(self, store_type):
        """The tentpole gate: bucketed uncompressed pushpull is
        BIT-identical (array_equal, not allclose) to the per-key path."""
        grads = _grads()
        s_pk = kv.create(store_type)
        s_pk._bucket_bytes = 0          # per-key decomposition
        r_pk = _exchange(s_pk, grads)
        s_bk = kv.create(store_type)
        assert s_bk._bucket_bytes == 25 << 20   # MXNET_KV_BUCKET_MB def
        r_bk = _exchange(s_bk, grads)
        for a, b in zip(r_pk, r_bk):
            for x, y in zip(a, b):
                assert np.array_equal(x, y)

    def test_values_correct_tpu_sync(self):
        """Bucketed psum result equals the cross-device gradient sum."""
        grads = _grads()
        out = _exchange(kv.create("tpu_sync"), grads)
        for gl, ol in zip(grads, out):
            want = np.sum(gl, axis=0)
            for o in ol:
                np.testing.assert_allclose(o, want, rtol=1e-6)

    def test_scalar_pushpull_thin_wrapper(self):
        """The scalar form is a one-key batch over the same fused path."""
        store = kv.create("device")
        store.init("w", mx.nd.zeros((3,)))
        g = mx.nd.array(np.array([1.0, 2.0, 3.0], np.float32))
        store.pushpull("w", g)           # out defaults to value
        np.testing.assert_allclose(g.asnumpy(), [1, 2, 3])
        out = mx.nd.zeros((3,))
        store.pull("w", out)
        np.testing.assert_allclose(out.asnumpy(), [1, 2, 3])

    def test_store_consistent_after_bucketed_pushpull(self):
        """A later scalar pull sees the bucketed reduction's result."""
        grads = _grads(copies=2)
        store = kv.create("tpu_sync")
        _exchange(store, grads)
        out = mx.nd.zeros(SHAPES[2])
        store.pull(2, out)
        np.testing.assert_allclose(out.asnumpy(),
                                   np.sum(grads[2], axis=0), rtol=1e-6)

    def test_updater_falls_back_per_key(self):
        """Server-side optimizer: the batched form decomposes and the
        updater applies per key, exactly like scalar push/pull."""
        store = kv.create("local")
        store.set_optimizer(mx.optimizer.create("sgd", learning_rate=1.0,
                                                wd=0.0))
        store.init(0, mx.nd.zeros((3,)))
        store.init(1, mx.nd.zeros((2,)))
        g0 = mx.nd.ones((3,))
        g1 = mx.nd.full((2,), 2.0)
        o0, o1 = mx.nd.zeros((3,)), mx.nd.zeros((2,))
        store.pushpull([0, 1], [g0, g1], out=[o0, o1])
        np.testing.assert_allclose(o0.asnumpy(), -np.ones(3))
        np.testing.assert_allclose(o1.asnumpy(), -2 * np.ones(2))
        assert 0 in store._updater.states and 1 in store._updater.states

    def test_trainer_bucketed_step_bit_identical(self):
        """Data-parallel Trainer over tpu_sync: per-key vs bucketed
        training is bit-identical (losses and weights)."""
        from mxnet_tpu import autograd, gluon
        from mxnet_tpu.gluon import nn
        from mxnet_tpu.gluon.loss import L2Loss

        def run(bucket_mb):
            prev = os.environ.get("MXNET_KV_BUCKET_MB")
            os.environ["MXNET_KV_BUCKET_MB"] = str(bucket_mb)
            try:
                mx.random.seed(0)
                net = nn.Dense(4, in_units=8)
                net.initialize()
                rs = np.random.RandomState(5)
                net.weight.set_data(mx.nd.array(
                    rs.randn(4, 8).astype(np.float32)))
                net.bias.set_data(mx.nd.zeros(4))
                ctxs = [mx.Context("cpu", 0), mx.Context("cpu", 1)]
                net.collect_params().reset_ctx(ctxs)
                tr = gluon.Trainer(net.collect_params(), "sgd",
                                   {"learning_rate": 0.1},
                                   kvstore="tpu_sync")
                loss_fn = L2Loss()
                rs2 = np.random.RandomState(1)
                x = rs2.randn(8, 8).astype(np.float32)
                y = rs2.randn(8, 4).astype(np.float32)
                losses = []
                for _ in range(3):
                    with autograd.record():
                        ls = [loss_fn(
                            net(mx.nd.array(x[i * 4:(i + 1) * 4],
                                            ctx=c)),
                            mx.nd.array(y[i * 4:(i + 1) * 4], ctx=c))
                            for i, c in enumerate(ctxs)]
                    autograd.backward(ls)
                    tr.step(8)
                    losses.append(
                        [float(l.asnumpy().sum()) for l in ls])
                return losses, net.weight.data(ctxs[0]).asnumpy()
            finally:
                if prev is None:
                    os.environ.pop("MXNET_KV_BUCKET_MB", None)
                else:
                    os.environ["MXNET_KV_BUCKET_MB"] = prev

        losses_pk, w_pk = run(0)
        losses_bk, w_bk = run(25)
        assert losses_pk == losses_bk
        assert np.array_equal(w_pk, w_bk)


class TestBucketPlanning:
    def _entries(self, specs):
        """specs: (shape, dtype_str) in dispatch order."""
        out = []
        for i, (shape, dt) in enumerate(specs):
            n = int(np.prod(shape)) if shape else 1
            nbytes = n * np.dtype(dt).itemsize
            out.append((i, shape, dt, (dt, 1, ("d0",)), nbytes))
        return out

    def test_cap_splits_buckets(self):
        entries = self._entries([((256,), "float32")] * 5)  # 1 KB each
        buckets = plan_buckets(entries, 2 * 1024)
        assert [b.indices for b in buckets] == [[0, 1], [2, 3], [4]]
        assert all(b.nbytes <= 2 * 1024 for b in buckets)

    def test_mixed_dtypes_split(self):
        """fp32/fp16 members never share a flat buffer, even interleaved;
        each dtype keeps its own open bucket."""
        entries = self._entries([((8,), "float32"), ((8,), "float16"),
                                 ((8,), "float32"), ((8,), "float16")])
        buckets = plan_buckets(entries, 1 << 20)
        assert [b.indices for b in buckets] == [[0, 2], [1, 3]]
        assert [b.dtype for b in buckets] == ["float32", "float16"]

    def test_oversize_param_gets_own_bucket(self):
        """A single tensor above the cap is never split and never shares."""
        entries = self._entries([((16,), "float32"),      # 64 B
                                 ((1024,), "float32"),    # 4 KB > cap
                                 ((16,), "float32")])
        buckets = plan_buckets(entries, 256)
        assert [b.indices for b in buckets] == [[0], [1], [2]]

    def test_mixed_dtype_exchange_end_to_end(self):
        """Mixed-dtype batched pushpull reduces each dtype correctly."""
        store = kv.create("device")
        rs = np.random.RandomState(0)
        g32 = rs.randn(4).astype(np.float32)
        g16 = rs.randn(6).astype(np.float16)
        store.init(0, mx.nd.zeros((4,)))
        store.init(1, mx.nd.zeros((6,), dtype="float16"))
        v0 = mx.nd.array(g32)
        v1 = mx.nd.array(g16, dtype="float16")
        store.pushpull([0, 1], [v0, v1], out=[v0, v1])
        np.testing.assert_allclose(v0.asnumpy(), g32)
        np.testing.assert_allclose(v1.asnumpy(), g16)
        assert v1.asnumpy().dtype == np.float16

    def test_priority_order_honored(self):
        """Buckets are dispatched in descending-priority order (the
        trainer's reverse-layer hint), stable for ties."""
        store = kv.create("device")
        store._bucket_bytes = 1          # force one bucket per key
        for i in range(3):
            store.init(i, mx.nd.zeros((2,)))
        seen = []
        orig = store._bucket_exchange_reduce

        def spy(bucket, vals_by_pos):
            seen.extend(vals_by_pos[p][0] for p in bucket.indices)
            return orig(bucket, vals_by_pos)

        store._bucket_exchange_reduce = spy
        vals = [mx.nd.ones((2,)) for _ in range(3)]
        store.pushpull([0, 1, 2], vals, out=vals, priority=[-5, 0, -3])
        assert seen == [1, 2, 0]         # highest priority first
        seen.clear()
        store.pushpull([0, 1, 2], vals, out=vals, priority=0)
        assert seen == [0, 1, 2]         # ties keep the given order

    def test_fallback_keys_keep_priority_position(self):
        """A non-dense payload falls back to per-key exchange but is
        dispatched at ITS priority slot, not banished behind every
        bucket."""
        import jax.numpy as jnp

        from mxnet_tpu.ndarray import NDArray

        class FakeSparse(NDArray):
            stype = "row_sparse"     # shadows the dense default

        store = kv.create("device")
        store._bucket_bytes = 1      # one bucket per dense key
        for i in range(3):
            store.init(i, mx.nd.zeros((2,)))
        calls = []
        orig_reduce = store._bucket_exchange_reduce
        orig_push = store.push

        def spy_reduce(bucket, vals_by_pos):
            calls.extend(vals_by_pos[p][0] for p in bucket.indices)
            return orig_reduce(bucket, vals_by_pos)

        def spy_push(key, value, priority=0):
            calls.append(key)
            return orig_push(key, value, priority)

        store._bucket_exchange_reduce = spy_reduce
        store.push = spy_push
        vals = [mx.nd.ones((2,)),
                FakeSparse(data=jnp.ones((2,))),
                mx.nd.ones((2,))]
        outs = [mx.nd.zeros((2,)) for _ in range(3)]
        store.pushpull([0, 1, 2], vals, out=outs, priority=[0, -1, -2])
        assert calls == [0, 1, 2]

    def test_batched_arg_validation(self):
        store = kv.create("device")
        store.init(0, mx.nd.zeros((2,)))
        with pytest.raises(MXNetError, match="values"):
            store.pushpull([0], [], out=[mx.nd.zeros((2,))])
        with pytest.raises(MXNetError, match="priorities"):
            store.pushpull([0], [mx.nd.zeros((2,))], priority=[0, 1])


class TestBucketedCompression:
    def test_error_feedback_converges_on_bucketed_path(self):
        """Over repeated bucketed pushes the transmitted mean converges
        to the true gradient (residual carries the remainder)."""
        store = kv.create("device")
        store.set_gradient_compression({"type": "2bit", "threshold": 0.5})
        g_np = np.array([0.4, -0.3, 0.1, -0.2, 0.0], np.float32)
        store.init(0, mx.nd.zeros((5,)))
        store.init(1, mx.nd.zeros((3,)))
        total = np.zeros(5, np.float32)
        for _ in range(40):
            v0 = mx.nd.array(g_np)
            v1 = mx.nd.zeros((3,))
            o0, o1 = mx.nd.zeros((5,)), mx.nd.zeros((3,))
            store.pushpull([0, 1], [v0, v1], out=[o0, o1])
            got = o0.asnumpy()
            # every transmitted value sits on the {-t, 0, +t} grid
            assert set(np.round(got / 0.5).astype(int)) <= {-1, 0, 1}
            total += got
        np.testing.assert_allclose(total / 40.0, g_np, atol=0.5 / 40)

    def test_unsupported_dtype_bucket_raises(self):
        """An integer-dtype bucket raises instead of silently casting."""
        store = kv.create("device")
        store.set_gradient_compression({"type": "2bit", "threshold": 0.5})
        store.init(0, mx.nd.zeros((4,), dtype="int32"))
        g = mx.nd.array(np.arange(4, dtype=np.int32), dtype="int32")
        with pytest.raises(MXNetError, match="float gradients only"):
            store.pushpull([0], [g], out=[g])
        # the scalar push path enforces the same contract
        with pytest.raises(MXNetError, match="float gradients only"):
            store.push(0, g)

    def test_trainer_states_carry_residuals(self):
        """Trainer.save_states/load_states round-trips the compression
        residuals bit-exactly (the envelope format)."""
        from mxnet_tpu import autograd, gluon
        from mxnet_tpu.gluon import nn
        from mxnet_tpu.gluon.loss import L2Loss
        import tempfile

        def setup():
            mx.random.seed(0)
            net = nn.Dense(2, in_units=4)
            net.initialize()
            net.weight.set_data(mx.nd.array(np.ones((2, 4), np.float32)))
            net.bias.set_data(mx.nd.zeros(2))
            tr = gluon.Trainer(
                net.collect_params(), "sgd", {"learning_rate": 0.1},
                kvstore="tpu_sync",
                compression_params={"type": "2bit", "threshold": 0.3})
            return net, tr

        def step(net, tr, seed):
            rs = np.random.RandomState(seed)
            x = mx.nd.array(rs.randn(4, 4).astype(np.float32))
            y = mx.nd.array(rs.randn(4, 2).astype(np.float32))
            with autograd.record():
                loss = L2Loss()(net(x), y)
            loss.backward()
            tr.step(4)

        net, tr = setup()
        for s in range(3):
            step(net, tr, s)
        fname = os.path.join(tempfile.mkdtemp(), "trainer.states")
        tr.save_states(fname)
        res_before = {
            k: np.asarray(v) for k, v in
            tr._kvstore._compression._residual.items()}
        assert res_before, "compression produced no residual state"

        net2, tr2 = setup()
        # params must match for the updater states to be meaningful
        net2.weight.set_data(net.weight.data())
        net2.bias.set_data(net.bias.data())
        tr2.load_states(fname)
        res_after = tr2._kvstore._compression._residual
        assert set(res_after) == set(res_before)
        for k, v in res_before.items():
            assert np.array_equal(np.asarray(res_after[k]), v)

    def test_checkpoint_manager_resume_bit_exact(self):
        """The full CheckpointManager flow: a resumed compressed run's
        weights track the uninterrupted run bit-exactly (residual stream
        continues, not restarts)."""
        from mxnet_tpu import autograd, gluon
        from mxnet_tpu.checkpoint import CheckpointManager
        from mxnet_tpu.gluon import nn
        from mxnet_tpu.gluon.loss import L2Loss
        import tempfile

        def setup():
            mx.random.seed(0)
            net = nn.Dense(2, in_units=4)
            net.initialize()
            net.weight.set_data(mx.nd.array(np.ones((2, 4), np.float32)))
            net.bias.set_data(mx.nd.zeros(2))
            tr = gluon.Trainer(
                net.collect_params(), "sgd", {"learning_rate": 0.1},
                kvstore="tpu_sync",
                compression_params={"type": "2bit", "threshold": 0.3})
            return net, tr

        def step(net, tr, seed):
            rs = np.random.RandomState(seed)
            x = mx.nd.array(rs.randn(4, 4).astype(np.float32))
            y = mx.nd.array(rs.randn(4, 2).astype(np.float32))
            with autograd.record():
                loss = L2Loss()(net(x), y)
            loss.backward()
            tr.step(4)
            return net.weight.data().asnumpy()

        # uninterrupted run: 6 steps
        net, tr = setup()
        for s in range(6):
            w_cont = step(net, tr, s)

        # interrupted run: 3 steps, checkpoint, fresh process state,
        # resume, 3 more
        net2, tr2 = setup()
        for s in range(3):
            step(net2, tr2, s)
        mgr = CheckpointManager(tempfile.mkdtemp())
        mgr.save(3, params=net2, trainer=tr2)
        net3, tr3 = setup()
        mgr.restore(block=net3, trainer=tr3)
        for s in range(3, 6):
            w_res = step(net3, tr3, s)
        assert np.array_equal(w_cont, w_res)

    def test_threshold_mismatch_on_restore_raises(self):
        from mxnet_tpu.kvstore.gradient_compression import (
            GradientCompression)

        a = GradientCompression(threshold=0.5)
        a.compress("w", 0, mx.nd.array(np.ones(3, np.float32)))
        b = GradientCompression(threshold=0.25)
        with pytest.raises(MXNetError, match="threshold"):
            b.set_state(a.get_state())

    def test_legacy_states_clear_live_residuals(self):
        """Loading a residual-less (legacy) state file into a
        compressing trainer must CLEAR its live residuals — the restored
        stream has to match a fresh process loading the same file."""
        from mxnet_tpu import autograd, gluon
        from mxnet_tpu.gluon import nn
        from mxnet_tpu.gluon.loss import L2Loss
        import tempfile

        def make(compress):
            mx.random.seed(0)
            net = nn.Dense(2, in_units=4)
            net.initialize()
            net(mx.nd.array(np.ones((1, 4), np.float32)))
            kwargs = {"compression_params":
                      {"type": "2bit", "threshold": 0.3}} if compress \
                else {}
            return net, gluon.Trainer(
                net.collect_params(), "sgd", {"learning_rate": 0.1},
                kvstore="tpu_sync", **kwargs)

        # legacy-format file: a trainer without compression
        net_plain, tr_plain = make(False)
        x = mx.nd.array(np.ones((4, 4), np.float32))
        y = mx.nd.array(np.zeros((4, 2), np.float32))
        with autograd.record():
            loss = L2Loss()(net_plain(x), y)
        loss.backward()
        tr_plain.step(4)
        fname = os.path.join(tempfile.mkdtemp(), "trainer.states")
        tr_plain.save_states(fname)

        net_c, tr_c = make(True)
        with autograd.record():
            loss = L2Loss()(net_c(x), y)
        loss.backward()
        tr_c.step(4)
        assert tr_c._kvstore._compression._residual
        tr_c.load_states(fname)
        assert tr_c._kvstore._compression._residual == {}

    def test_load_states_without_compression_raises(self):
        """A residual-carrying state file loaded into a trainer with no
        compression configured is a loud error, not silent data loss."""
        from mxnet_tpu import autograd, gluon
        from mxnet_tpu.gluon import nn
        from mxnet_tpu.gluon.loss import L2Loss
        import tempfile

        mx.random.seed(0)
        net = nn.Dense(2, in_units=4)
        net.initialize()
        tr = gluon.Trainer(
            net.collect_params(), "sgd", {"learning_rate": 0.1},
            kvstore="tpu_sync",
            compression_params={"type": "2bit", "threshold": 0.3})
        x = mx.nd.array(np.ones((4, 4), np.float32))
        y = mx.nd.array(np.zeros((4, 2), np.float32))
        with autograd.record():
            loss = L2Loss()(net(x), y)
        loss.backward()
        tr.step(4)
        fname = os.path.join(tempfile.mkdtemp(), "trainer.states")
        tr.save_states(fname)

        net2 = nn.Dense(2, in_units=4)
        net2.initialize()
        net2(x)
        tr2 = gluon.Trainer(net2.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore="tpu_sync")
        with pytest.raises(MXNetError, match="compression"):
            tr2.load_states(fname)


class TestBucketTelemetry:
    def test_bucketed_counters_recorded(self):
        from mxnet_tpu import telemetry

        telemetry.enable()
        try:
            telemetry.reset()
            grads = _grads()
            _exchange(kv.create("tpu_sync"), grads)
            snap = telemetry.snapshot()["metrics"]
            coll = {s["labels"]["path"]: s["value"] for s in
                    snap["mxnet_kvstore_collective_dispatch_total"]
                    ["samples"]}
            assert coll.get("bucketed", 0) >= 1
            bb = snap["mxnet_kvstore_bucket_bytes"]["samples"][0]
            assert bb["count"] >= 1 and bb["sum"] > 0
            keys = snap["mxnet_kvstore_bucketed_keys_total"]["samples"]
            assert keys[0]["value"] == len(SHAPES)
            kv_ops = {s["labels"]["op"] for s in
                      snap["mxnet_kvstore_calls_total"]["samples"]}
            assert "pushpull" in kv_ops
        finally:
            telemetry.disable()
            telemetry.reset()

    def test_compression_counters_recorded(self):
        from mxnet_tpu import telemetry

        telemetry.enable()
        try:
            telemetry.reset()
            store = kv.create("device")
            store.set_gradient_compression(
                {"type": "2bit", "threshold": 0.5})
            store.init(0, mx.nd.zeros((8,)))
            g = mx.nd.array(np.ones(8, np.float32))
            store.pushpull([0], [g], out=[g])
            snap = telemetry.snapshot()["metrics"]
            ratio = snap["mxnet_kvstore_compression_ratio"]["samples"]
            assert ratio[0]["value"] == 16.0     # fp32 -> 2 bit
            els = snap["mxnet_kvstore_compressed_elements_total"]
            assert els["samples"][0]["value"] == 8
        finally:
            telemetry.disable()
            telemetry.reset()


def test_resnet50_param_shapes_scale():
    """The comms bench's ResNet-50-scale set really is ResNet-50 scale:
    161 tensors, ~25.5M parameters."""
    import importlib.util as ilu

    spec = ilu.spec_from_file_location(
        "comms_bench", os.path.join(REPO, "tools", "comms_bench.py"))
    cb = ilu.module_from_spec(spec)
    spec.loader.exec_module(cb)
    shapes = cb.resnet50_param_shapes()
    total = sum(int(np.prod(s)) for s in shapes)
    assert len(shapes) == 161
    assert 24e6 < total < 27e6


@pytest.mark.slow
def test_comms_bench_tool_contract(tmp_path):
    """tools/comms_bench.py emits the data_bench JSON contract (one
    flushed line per stage, contract keys first) and its loss gate
    passes on the tiny param set."""
    import json

    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("DMLC_", "XLA_FLAGS"))}
    env.update(JAX_PLATFORMS="cpu", PYTHONPATH="",
               COMMS_BENCH_SCALE="tiny", COMMS_BENCH_REPS="2")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "comms_bench.py")],
        env=env, capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    lines = [ln for ln in out.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 4               # one per completed stage
    first = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in first              # the shared driver contract
    last = json.loads(lines[-1])
    assert last["comms_bucketed_loss_bit_identical"] is True
    assert last["comms_perkey_collectives_per_step"] > \
        last["comms_bucketed_collectives_per_step"]
    # stage 4 (ISSUE 7): allreduce-under-backward overlap, bit-identical
    assert last["comms_overlap_loss_bit_identical"] is True
    assert last["comms_overlap_dispatch_pct"] > 0.0


class TestBackwardOverlap:
    """Backward-overlapped collectives (ISSUE 7): grad-ready hooks
    dispatch each bucket's pushpull INSIDE autograd.backward, results
    bit-identical to the at-step exchange."""

    def test_plan_pushpull_matches_bucket_plan(self):
        store = kv.create("local")
        store._bucket_bytes = 60  # tiny cap -> several buckets
        vals = _grads()
        nds = [[mx.nd.array(v) for v in vs] for vs in vals]
        for k, sh in enumerate(SHAPES):
            store.init(k, mx.nd.zeros(sh))
        keys = list(range(len(SHAPES)))
        groups = store.plan_pushpull(keys, nds, [-k for k in keys])
        # every key exactly once, in descending-priority dispatch order
        flat = [p for g in groups for p in g]
        assert sorted(flat) == keys
        assert flat == keys  # priority -k => ascending key order
        # each group fits the cap (or is a singleton oversize)
        for g in groups:
            nbytes = sum(4 * int(np.prod(SHAPES[p])) for p in g)
            assert len(g) == 1 or nbytes <= 60

    def test_plan_pushpull_perkey_when_disabled(self):
        store = kv.create("local")
        store._bucket_bytes = 0
        nds = [[mx.nd.array(v) for v in vs] for vs in _grads()]
        groups = store.plan_pushpull(list(range(len(SHAPES))), nds)
        assert groups == [[p] for p in range(len(SHAPES))]

    @staticmethod
    def _trainer_losses(bucket_mb, overlap, steps=4):
        from mxnet_tpu import autograd, gluon
        from mxnet_tpu.gluon import nn
        from mxnet_tpu.gluon.loss import L2Loss

        prev = os.environ.get("MXNET_KV_BUCKET_MB")
        os.environ["MXNET_KV_BUCKET_MB"] = str(bucket_mb)
        try:
            mx.random.seed(0)
            net = nn.HybridSequential()
            with net.name_scope():
                net.add(nn.Dense(32, in_units=16), nn.Dense(32),
                        nn.Dense(8))
            net.initialize()
            net(mx.nd.zeros((1, 16)))
            rs = np.random.RandomState(7)
            for p in net.collect_params().values():
                p.set_data(mx.nd.array(
                    rs.randn(*p.shape).astype(np.float32) * 0.1))
            ctxs = [mx.Context("cpu", 0), mx.Context("cpu", 1)]
            net.collect_params().reset_ctx(ctxs)
            tr = gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.05},
                               kvstore="tpu_sync",
                               overlap_comms=overlap)
            loss_fn = L2Loss()
            rs2 = np.random.RandomState(11)
            x = rs2.randn(8, 16).astype(np.float32)
            y = rs2.randn(8, 8).astype(np.float32)
            losses, stats = [], []
            for _ in range(steps):
                with autograd.record():
                    ls = [loss_fn(net(mx.nd.array(x[i * 4:(i + 1) * 4],
                                                  ctx=c)),
                                  mx.nd.array(y[i * 4:(i + 1) * 4],
                                              ctx=c))
                          for i, c in enumerate(ctxs)]
                autograd.backward(ls)
                tr.step(8)
                if tr.last_overlap_stats is not None:
                    stats.append(dict(tr.last_overlap_stats))
                losses.append(float(sum(l.asnumpy().sum()
                                        for l in ls)))
            weights = [p.data(ctxs[0]).asnumpy()
                       for p in net.collect_params().values()]
            return losses, weights, stats
        finally:
            if prev is None:
                os.environ.pop("MXNET_KV_BUCKET_MB", None)
            else:
                os.environ["MXNET_KV_BUCKET_MB"] = prev

    def test_overlapped_trainer_bit_identical_to_perkey(self):
        l_pk, w_pk, _ = self._trainer_losses(0, False)
        l_ov, w_ov, stats = self._trainer_losses(0.005, True)
        assert l_pk == l_ov
        for a, b in zip(w_pk, w_ov):
            np.testing.assert_array_equal(a, b)
        # steady state (hooks arm during step 1's kvstore init): every
        # bucket dispatched inside backward
        assert stats, "overlap stats not recorded"
        steady = stats[1:]
        assert steady and all(
            s["dispatched_in_backward"] == s["groups"] > 0
            for s in steady)

    def test_overlap_disabled_under_nonfinite_guard(self):
        from mxnet_tpu import gluon
        from mxnet_tpu.gluon import nn

        net = nn.Dense(4, in_units=4)
        net.initialize()
        ctxs = [mx.Context("cpu", 0), mx.Context("cpu", 1)]
        net.collect_params().reset_ctx(ctxs)
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1}, kvstore="tpu_sync",
                           overlap_comms=True, check_nonfinite=True)
        tr._init_kvstore()
        # the guard must see gradients BEFORE any reduce -> no overlap
        assert tr._overlap is None

    def test_watch_grad_ready_fires_inside_backward(self):
        from mxnet_tpu import autograd

        x = mx.nd.array(np.ones((2, 2), np.float32))
        x.attach_grad()
        seen = []

        class Owner:
            def cb(self, arr):
                # the grad buffer is already finalized when we fire
                seen.append(np.asarray(arr.grad.asnumpy()).copy())

        owner = Owner()
        autograd.watch_grad_ready([x], owner.cb)
        try:
            with autograd.record():
                y = (x * 3.0).sum()
            y.backward()
            assert len(seen) == 1
            np.testing.assert_allclose(seen[0], 3.0 * np.ones((2, 2)))
            # grad also visible after backward as usual
            np.testing.assert_allclose(x.grad.asnumpy(),
                                       3.0 * np.ones((2, 2)))
        finally:
            autograd.unwatch_grad_ready([x])

    def test_unwatch_and_dead_owner_are_safe(self):
        from mxnet_tpu import autograd

        x = mx.nd.array(np.ones((2,), np.float32))
        x.attach_grad()

        class Owner:
            hits = 0

            def cb(self, arr):
                Owner.hits += 1

        owner = Owner()
        autograd.watch_grad_ready([x], owner.cb)
        del owner  # weak callback: dead owner must not fire or leak
        with autograd.record():
            y = (x * 2.0).sum()
        y.backward()
        assert Owner.hits == 0
        np.testing.assert_allclose(x.grad.asnumpy(), 2.0 * np.ones(2))
        autograd.unwatch_grad_ready([x])

    def test_overlap_self_heals_after_abandoned_backward(self):
        """A backward not followed by step() (aborted iteration) must
        not leave stale dispatched-state that makes the NEXT step skip
        its exchange — the sweep-seq check resets it."""
        from mxnet_tpu import autograd, gluon
        from mxnet_tpu.gluon import nn
        from mxnet_tpu.gluon.loss import L2Loss

        prev = os.environ.get("MXNET_KV_BUCKET_MB")
        os.environ["MXNET_KV_BUCKET_MB"] = "0.005"
        try:
            def run(overlap):
                mx.random.seed(0)
                net = nn.HybridSequential()
                with net.name_scope():
                    net.add(nn.Dense(32, in_units=16), nn.Dense(8))
                net.initialize()
                net(mx.nd.zeros((1, 16)))
                rs = np.random.RandomState(7)
                for p in net.collect_params().values():
                    p.set_data(mx.nd.array(
                        rs.randn(*p.shape).astype(np.float32) * 0.1))
                ctxs = [mx.Context("cpu", 0), mx.Context("cpu", 1)]
                net.collect_params().reset_ctx(ctxs)
                tr = gluon.Trainer(net.collect_params(), "sgd",
                                   {"learning_rate": 0.05},
                                   kvstore="tpu_sync",
                                   overlap_comms=overlap)
                lf = L2Loss()
                rs2 = np.random.RandomState(11)
                x = rs2.randn(8, 16).astype(np.float32)
                y = rs2.randn(8, 8).astype(np.float32)

                def bwd():
                    with autograd.record():
                        ls = [lf(net(mx.nd.array(x[i * 4:(i + 1) * 4],
                                                 ctx=c)),
                                 mx.nd.array(y[i * 4:(i + 1) * 4],
                                             ctx=c))
                              for i, c in enumerate(ctxs)]
                    autograd.backward(ls)

                for step_i in range(3):
                    bwd()
                    if step_i == 1:
                        bwd()   # abandoned first backward: no step()
                    tr.step(8)
                return [p.data(ctxs[0]).asnumpy()
                        for p in net.collect_params().values()]

            w_pk = run(False)
            w_ov = run(True)
            for a, b in zip(w_pk, w_ov):
                np.testing.assert_array_equal(a, b)
        finally:
            if prev is None:
                os.environ.pop("MXNET_KV_BUCKET_MB", None)
            else:
                os.environ["MXNET_KV_BUCKET_MB"] = prev
