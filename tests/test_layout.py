"""Channels-last (NHWC) internal layout: ops, blocks, and zoo parity.

The TPU-preferred conv layout is channels-last; ``nn.conv_layout("NHWC")``
switches block construction defaults while weights stay OIHW so the same
checkpoint loads into either layout. These tests pin the numerical parity
NCHW <-> NHWC across conv/pool/BN and a small zoo model.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import nn


def _rand(shape, seed=0):
    return mx.nd.array(np.random.RandomState(seed).uniform(-1, 1, shape).astype(np.float32))


def test_convolution_op_nhwc_matches_nchw():
    x = _rand((2, 4, 9, 9))
    w = _rand((8, 4, 3, 3), seed=1)
    b = _rand((8,), seed=2)
    y_ref = nd.Convolution(x, w, b, kernel=(3, 3), num_filter=8, stride=(2, 2),
                           pad=(1, 1))
    y_nhwc = nd.Convolution(x.transpose((0, 2, 3, 1)), w, b, kernel=(3, 3),
                            num_filter=8, stride=(2, 2), pad=(1, 1),
                            layout="NHWC")
    np.testing.assert_allclose(y_nhwc.transpose((0, 3, 1, 2)).asnumpy(),
                               y_ref.asnumpy(), rtol=1e-5, atol=1e-5)


def test_grouped_convolution_nhwc():
    x = _rand((2, 4, 8, 8))
    w = _rand((8, 2, 3, 3), seed=1)
    y_ref = nd.Convolution(x, w, None, kernel=(3, 3), num_filter=8,
                           num_group=2, pad=(1, 1), no_bias=True)
    y_nhwc = nd.Convolution(x.transpose((0, 2, 3, 1)), w, None, kernel=(3, 3),
                            num_filter=8, num_group=2, pad=(1, 1),
                            no_bias=True, layout="NHWC")
    np.testing.assert_allclose(y_nhwc.transpose((0, 3, 1, 2)).asnumpy(),
                               y_ref.asnumpy(), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("pool_type", ["max", "avg"])
def test_pooling_op_nhwc(pool_type):
    x = _rand((2, 3, 9, 9))
    y_ref = nd.Pooling(x, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                       pool_type=pool_type)
    y_nhwc = nd.Pooling(x.transpose((0, 2, 3, 1)), kernel=(3, 3),
                        stride=(2, 2), pad=(1, 1), pool_type=pool_type,
                        layout="NHWC")
    np.testing.assert_allclose(y_nhwc.transpose((0, 3, 1, 2)).asnumpy(),
                               y_ref.asnumpy(), rtol=1e-5, atol=1e-5)


def test_global_pool_nhwc():
    x = _rand((2, 3, 5, 5))
    y_ref = nd.Pooling(x, global_pool=True, pool_type="avg", kernel=(1, 1))
    y_nhwc = nd.Pooling(x.transpose((0, 2, 3, 1)), global_pool=True,
                        pool_type="avg", kernel=(1, 1), layout="NHWC")
    np.testing.assert_allclose(y_nhwc.transpose((0, 3, 1, 2)).asnumpy(),
                               y_ref.asnumpy(), rtol=1e-6, atol=1e-6)


def test_deconvolution_nhwc_matches_nchw():
    x = _rand((2, 4, 5, 5))
    w = _rand((4, 6, 3, 3), seed=1)  # Deconvolution weight: (in, out, kh, kw)
    b = _rand((6,), seed=2)
    y_ref = nd.Deconvolution(x, w, b, kernel=(3, 3), num_filter=6,
                             stride=(2, 2), pad=(1, 1), no_bias=False)
    y_nhwc = nd.Deconvolution(x.transpose((0, 2, 3, 1)), w, b, kernel=(3, 3),
                              num_filter=6, stride=(2, 2), pad=(1, 1),
                              no_bias=False, layout="NHWC")
    np.testing.assert_allclose(y_nhwc.transpose((0, 3, 1, 2)).asnumpy(),
                               y_ref.asnumpy(), rtol=1e-5, atol=1e-5)


def test_conv_layout_context_defaults():
    with nn.conv_layout("NHWC"):
        conv = nn.Conv2D(4, 3, padding=1)
        pool = nn.MaxPool2D(2)
        bn = nn.BatchNorm()
    assert conv._layout == "NHWC"
    assert pool._kwargs["layout"] == "NHWC"
    assert bn._axis == -1
    # outside the context the defaults are unchanged
    assert nn.Conv2D(4, 3)._layout == "NCHW"
    assert nn.BatchNorm()._axis == 1
    # explicit channels-last outside any context still honored
    assert nn.Conv2D(4, 3, layout="NHWC")._layout == "NHWC"


def test_batchnorm_axis_last_matches_axis1():
    x = _rand((2, 6, 4, 4))
    bn1 = nn.BatchNorm(in_channels=6)
    bn2 = nn.BatchNorm(axis=-1, in_channels=6)
    bn1.initialize()
    bn2.initialize()
    with mx.autograd.record():
        y1 = bn1(x)
    with mx.autograd.record():
        y2 = bn2(x.transpose((0, 2, 3, 1)))
    np.testing.assert_allclose(y2.transpose((0, 3, 1, 2)).asnumpy(),
                               y1.asnumpy(), rtol=1e-5, atol=1e-5)


def test_resnet_nhwc_parity_and_train_step():
    from mxnet_tpu.gluon.model_zoo.vision import resnet18_v1

    net1 = resnet18_v1(classes=10, thumbnail=True)
    net2 = resnet18_v1(classes=10, thumbnail=True, layout="NHWC")
    net1.initialize()
    net2.initialize()
    x = _rand((2, 3, 32, 32))
    y1 = net1(x)
    y2 = net2(x)  # settles deferred shapes
    # insertion order is structural (same build order in both nets); the
    # name counters differ across nets so sorted names would misalign
    p1, p2 = net1.collect_params(), net2.collect_params()
    for k1, k2 in zip(list(p1), list(p2)):
        p2[k2].set_data(p1[k1].data())
    y2 = net2(x)
    assert y2.shape == y1.shape == (2, 10)
    np.testing.assert_allclose(y2.asnumpy(), y1.asnumpy(), rtol=2e-4,
                               atol=2e-4)
    # gradients flow through the NHWC path
    from mxnet_tpu.gluon import loss as gloss

    lossfn = gloss.SoftmaxCrossEntropyLoss()
    label = mx.nd.array(np.array([1, 2], dtype=np.float32))
    with mx.autograd.record():
        out = lossfn(net2(x), label)
    out.backward()
    g = net2.collect_params()[list(p2)[0]].grad()
    assert float(np.abs(g.asnumpy()).sum()) > 0


def test_conv_layout_keeps_explicit_channels_first():
    """Round-3 advisor finding: an EXPLICIT layout='NCHW' (or BatchNorm
    axis=1) inside conv_layout('NHWC') must be kept, not flipped."""
    with nn.conv_layout("NHWC"):
        default_conv = nn.Conv2D(4, 3)
        explicit_conv = nn.Conv2D(4, 3, layout="NCHW")
        default_bn = nn.BatchNorm()
        explicit_bn = nn.BatchNorm(axis=1)
    assert default_conv._layout == "NHWC"
    assert explicit_conv._layout == "NCHW"
    assert default_bn._axis == -1
    assert explicit_bn._axis == 1
    # outside any context the defaults are channels-first
    assert nn.Conv2D(4, 3)._layout == "NCHW"
    assert nn.BatchNorm()._axis == 1


def test_pooling_convention_same():
    """pooling_convention='same' implements TF SAME: out = ceil(in/stride),
    avg excludes the implicit pad cells only via count_include_pad."""
    x = _rand((1, 1, 5, 5))
    out = nd.Pooling(x, kernel=(3, 3), stride=(2, 2), pool_type="max",
                     pooling_convention="same")
    assert out.shape == (1, 1, 3, 3)
    # oracle: manual pad to SAME then valid pooling
    xa = x.asnumpy()[0, 0]
    padded = np.full((7, 7), -np.inf, "float32")
    padded[1:6, 1:6] = xa
    want = np.stack([[padded[r:r + 3, c:c + 3].max()
                      for c in (0, 2, 4)] for r in (0, 2, 4)])
    np.testing.assert_allclose(out.asnumpy()[0, 0], want, rtol=1e-6)
    with pytest.raises(Exception, match="same"):
        nd.Pooling(x, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                   pooling_convention="same")


def test_conv_dw_patches_matches_vjp(monkeypatch):
    """MXNET_TPU_CONV_DW=patches (the im2col dW experiment path) must
    produce the same gradients as XLA's conv backward."""
    from mxnet_tpu import autograd

    rs = np.random.RandomState(0)
    x_np = rs.randn(2, 9, 9, 5).astype("float32")
    w_np = rs.randn(6, 5, 3, 3).astype("float32") * 0.1
    grads = {}
    for mode in ("vjp", "patches"):
        monkeypatch.setenv("MXNET_TPU_CONV_DW", mode)
        x, w = mx.nd.array(x_np), mx.nd.array(w_np)
        x.attach_grad(); w.attach_grad()
        with mx.autograd.record():
            y = nd.Convolution(x, w, kernel=(3, 3), stride=(2, 2),
                               pad=(1, 1), num_filter=6, no_bias=True,
                               layout="NHWC")
            ((y * y).sum()).backward()
        grads[mode] = (x.grad.asnumpy(), w.grad.asnumpy())
    np.testing.assert_allclose(grads["patches"][0], grads["vjp"][0],
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(grads["patches"][1], grads["vjp"][1],
                               rtol=2e-3, atol=2e-3)


class TestHandDerivedVJPs:
    """Round-4 perf paths: hand-derived BN backward + 1x1-conv-as-dot.

    Both replace autodiff-derived backward graphs with closed-form VJPs
    (PERF.md round 4: the autodiff BN backward carried ~7 full-tensor
    reductions; 1x1 conv backward sat in XLA's conv algorithm selection).
    Gates: gradients must match the plain formulation to fp tolerance.
    """

    def _bn_ref(self, x, g, b, eps):
        import jax
        import jax.numpy as jnp
        mean = jnp.mean(x, axis=(0, 1, 2))
        var = jnp.var(x, axis=(0, 1, 2))
        inv = jax.lax.rsqrt(var + eps)
        return (x - mean) * inv * g + b

    def test_bn_train_grads_match_autodiff(self):
        import jax
        import jax.numpy as jnp
        from mxnet_tpu.ops import nn as opsnn
        rs = np.random.RandomState(3)
        x = jnp.asarray(rs.randn(4, 5, 6, 7).astype(np.float32))
        g = jnp.asarray(rs.rand(7).astype(np.float32) + 0.5)
        b = jnp.asarray(rs.randn(7).astype(np.float32))
        eps = 1e-3
        dy = jnp.asarray(rs.randn(4, 5, 6, 7).astype(np.float32))
        o1, vjp1 = jax.vjp(lambda *a: self._bn_ref(*a, eps), x, g, b)
        o2, vjp2 = jax.vjp(lambda *a: opsnn._bn_train(3, eps, *a)[0], x, g, b)
        np.testing.assert_allclose(o1, o2, atol=1e-5)
        for got, want in zip(vjp2(dy), vjp1(dy)):
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       atol=1e-4)

    def test_bn_train_stats_outputs(self):
        import jax.numpy as jnp
        from mxnet_tpu.ops import nn as opsnn
        rs = np.random.RandomState(4)
        x = jnp.asarray(rs.randn(3, 4, 5, 6).astype(np.float32))
        g = jnp.ones((6,), np.float32)
        b = jnp.zeros((6,), np.float32)
        _, mean, var = opsnn._bn_train(3, 1e-3, x, g, b)
        np.testing.assert_allclose(mean, np.mean(np.asarray(x), axis=(0, 1, 2)),
                                   atol=1e-5)
        np.testing.assert_allclose(var, np.var(np.asarray(x), axis=(0, 1, 2)),
                                   atol=1e-4)

    def test_bn_channel_axis_1(self):
        """NCHW (axis=1) goes through the same custom-vjp path."""
        import jax
        import jax.numpy as jnp
        from mxnet_tpu.ops import nn as opsnn
        rs = np.random.RandomState(5)
        x = jnp.asarray(rs.randn(2, 5, 4, 4).astype(np.float32))
        g = jnp.asarray(rs.rand(5).astype(np.float32) + 0.5)
        b = jnp.asarray(rs.randn(5).astype(np.float32))

        def ref(x, g, b):
            import jax as _jax
            mean = jnp.mean(x, axis=(0, 2, 3), keepdims=True)
            var = jnp.var(x, axis=(0, 2, 3), keepdims=True)
            inv = _jax.lax.rsqrt(var + 1e-3)
            return (x - mean) * inv * g.reshape(1, -1, 1, 1) \
                + b.reshape(1, -1, 1, 1)

        dy = jnp.asarray(rs.randn(2, 5, 4, 4).astype(np.float32))
        o1, vjp1 = jax.vjp(ref, x, g, b)
        o2, vjp2 = jax.vjp(lambda *a: opsnn._bn_train(1, 1e-3, *a)[0],
                           x, g, b)
        np.testing.assert_allclose(o1, o2, atol=1e-5)
        for got, want in zip(vjp2(dy), vjp1(dy)):
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       atol=1e-4)

    def test_conv1x1_dot_grads_match_conv(self):
        import jax
        import jax.numpy as jnp
        from mxnet_tpu.ops import nn as opsnn
        rs = np.random.RandomState(6)
        x = jnp.asarray(rs.randn(2, 5, 6, 8).astype(np.float32))
        w = jnp.asarray(rs.randn(12, 8, 1, 1).astype(np.float32))

        def conv_ref(x, w):
            dn = jax.lax.conv_dimension_numbers(
                x.shape, w.shape, ("NHWC", "OIHW", "NHWC"))
            return jax.lax.conv_general_dilated(
                x, w, (1, 1), [(0, 0), (0, 0)], dimension_numbers=dn)

        o1, vjp1 = jax.vjp(conv_ref, x, w)
        o2, vjp2 = jax.vjp(opsnn._conv1x1_dot, x, w)
        np.testing.assert_allclose(o1, o2, atol=1e-4)
        dy = jnp.asarray(rs.randn(*o1.shape).astype(np.float32))
        for got, want, tol in zip(vjp2(dy), vjp1(dy), (1e-4, 1e-3)):
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       atol=tol)

    def test_conv1x1_dot_used_by_convolution_op(self):
        """nd.Convolution on a stride-1 1x1 NHWC conv routes to the dot
        path and still matches the NCHW conv formulation."""
        x = _rand((2, 8, 6, 6))
        w = _rand((12, 8, 1, 1), seed=1)
        y_ref = nd.Convolution(x, w, None, kernel=(1, 1), num_filter=12,
                               no_bias=True)
        y_nhwc = nd.Convolution(x.transpose((0, 2, 3, 1)), w, None,
                                kernel=(1, 1), num_filter=12, no_bias=True,
                                layout="NHWC")
        np.testing.assert_allclose(y_nhwc.transpose((0, 3, 1, 2)).asnumpy(),
                                   y_ref.asnumpy(), rtol=1e-4, atol=1e-4)

    def test_conv_s2d_stem_matches_direct(self):
        """The ResNet-stem rewrite (stride-2 large-kernel conv as
        space-to-depth + stride-1 conv) is an exact re-indexing: fwd and
        both grads match the direct conv bitwise-close."""
        import jax
        import jax.numpy as jnp
        from mxnet_tpu.ops import nn as opsnn
        rs = np.random.RandomState(7)
        for k in (7, 5):
            pad = (k - 1) // 2
            x = jnp.asarray(rs.randn(2, 16, 16, 3).astype(np.float32))
            w = jnp.asarray(rs.randn(8, 3, k, k).astype(np.float32) * 0.1)
            dn = jax.lax.conv_dimension_numbers(
                x.shape, w.shape, ("NHWC", "OIHW", "NHWC"))

            def ref(x, w):
                return jax.lax.conv_general_dilated(
                    x, w, (2, 2), [(pad, pad)] * 2, dimension_numbers=dn)

            o1, vjp1 = jax.vjp(ref, x, w)
            o2, vjp2 = jax.vjp(
                lambda x, w: opsnn._conv_s2d(x, w, (k, k)), x, w)
            np.testing.assert_allclose(o1, o2, atol=1e-4)
            dy = jnp.asarray(rs.randn(*o1.shape).astype(np.float32))
            for got, want in zip(vjp2(dy), vjp1(dy)):
                np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                           atol=1e-3)

    def test_conv1x1_strided_dot_grads_match_conv(self):
        import jax
        import jax.numpy as jnp
        from mxnet_tpu.ops import nn as opsnn
        rs = np.random.RandomState(8)
        x = jnp.asarray(rs.randn(2, 8, 8, 6).astype(np.float32))
        w = jnp.asarray(rs.randn(10, 6, 1, 1).astype(np.float32))
        dn = jax.lax.conv_dimension_numbers(
            x.shape, w.shape, ("NHWC", "OIHW", "NHWC"))

        def ref(x, w):
            return jax.lax.conv_general_dilated(
                x, w, (2, 2), [(0, 0), (0, 0)], dimension_numbers=dn)

        o1, vjp1 = jax.vjp(ref, x, w)
        o2, vjp2 = jax.vjp(
            lambda x, w: opsnn._conv1x1_strided_dot(x, w, (2, 2)), x, w)
        np.testing.assert_allclose(o1, o2, atol=1e-5)
        dy = jnp.asarray(rs.randn(*o1.shape).astype(np.float32))
        for got, want in zip(vjp2(dy), vjp1(dy)):
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       atol=1e-4)

    def test_stem_conv_op_s2d_parity(self, monkeypatch):
        """nd.Convolution with the exact ResNet stem geometry (7x7/s2/p3,
        3 channels, NHWC) routes through the s2d rewrite and matches the
        NCHW direct formulation. The route itself is asserted (a spy on
        _conv_s2d) so a dispatch-guard regression cannot silently fall
        back to the direct conv with a green test."""
        from mxnet_tpu.ops import nn as opsnn
        calls = []
        real = opsnn._conv_s2d
        monkeypatch.setattr(
            opsnn, "_conv_s2d",
            lambda x, w, k: calls.append(k) or real(x, w, k))
        x = _rand((2, 3, 16, 16))
        w = _rand((8, 3, 7, 7), seed=1)
        y_ref = nd.Convolution(x, w, None, kernel=(7, 7), num_filter=8,
                               stride=(2, 2), pad=(3, 3), no_bias=True)
        y_nhwc = nd.Convolution(x.transpose((0, 2, 3, 1)), w, None,
                                kernel=(7, 7), num_filter=8, stride=(2, 2),
                                pad=(3, 3), no_bias=True, layout="NHWC")
        assert calls == [(7, 7)], "stem conv did not route through s2d"
        np.testing.assert_allclose(y_nhwc.transpose((0, 3, 1, 2)).asnumpy(),
                                   y_ref.asnumpy(), rtol=1e-4, atol=1e-4)
