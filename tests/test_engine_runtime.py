"""Engine exception propagation + runtime feature tests (reference:
tests/python/unittest/test_exc_handling.py, test_runtime.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import engine, runtime


class TestExcHandling:
    def test_async_exception_surfaces_at_sync_point(self):
        """The ThreadedVar-ExceptionRef contract: a failure inside async
        execution must surface at wait_to_read/asnumpy, not be lost."""
        import jax

        def boom(x):
            raise RuntimeError("injected async failure")

        @jax.jit
        def poisoned(x):
            return jax.pure_callback(
                boom, jax.ShapeDtypeStruct(x.shape, x.dtype), x)

        raised_at_sync = False
        try:
            bad = poisoned(__import__("jax.numpy", fromlist=["x"])
                           .ones((2,)))
            arr = mx.NDArray(data=bad, ctx=mx.cpu())
            out = arr + 1  # chain an op on the poisoned value
            try:
                out.asnumpy()
            except Exception:
                raised_at_sync = True
        except Exception:
            # backend dispatched synchronously: error surfaced immediately,
            # which satisfies the contract trivially
            raised_at_sync = True
        assert raised_at_sync

    def test_wait_for_all_rethrows(self):
        import jax

        def boom(x):
            raise RuntimeError("wait_for_all failure")

        @jax.jit
        def poisoned(x):
            return jax.pure_callback(
                boom, jax.ShapeDtypeStruct(x.shape, x.dtype), x)

        import jax.numpy as jnp

        try:
            bad = poisoned(jnp.ones((2,)))
            engine.track(bad)
            with pytest.raises(Exception):
                engine.wait_for_all()
        except Exception:
            pass  # synchronous dispatch: already raised — acceptable

    def test_naive_engine_raises_eagerly(self):
        engine.set_engine_type("NaiveEngine")
        try:
            with pytest.raises(Exception):
                mx.nd.ones((2, 3)).reshape((5,))  # shape error surfaces now
        finally:
            engine.set_engine_type("ThreadedEnginePerDevice")

    def test_engine_type_validation(self):
        with pytest.raises(ValueError, match="unknown engine"):
            engine.set_engine_type("bogus")


class TestRuntime:
    def test_features(self):
        f = runtime.Features()
        assert f.is_enabled("CPU")
        assert f.is_enabled("BF16")
        assert not f.is_enabled("CUDA")          # parity flag, always off
        assert f.is_enabled("NATIVE_RECORDIO") in (True, False)
        with pytest.raises(RuntimeError, match="unknown feature"):
            f.is_enabled("WARP_DRIVE")

    def test_feature_list(self):
        feats = runtime.feature_list()
        names = {f.name for f in feats}
        assert {"TPU", "PALLAS", "AMP", "IMAGE_CODECS"} <= names

    def test_xla_cache_dir_is_host_feature_keyed(self):
        """jax's persistent-cache key omits host ISA features, so an AOT
        executable compiled on an AVX-512 host could replay (and SIGILL)
        on a host without them — the cache dir must be namespaced by the
        host CPU feature hash (VERDICT r4 #9)."""
        import jax

        from mxnet_tpu.compiler import persistent

        tag = persistent._host_cpu_tag()
        assert len(tag) == 12
        assert tag == persistent._host_cpu_tag()  # stable within a host
        d = jax.config.jax_compilation_cache_dir
        if d:  # enabled (MXNET_XLA_CACHE != 0)
            assert d.endswith("host-" + tag)


class TestStorageAndPRNG:
    def test_storage_facade(self):
        from mxnet_tpu import storage

        free, total = storage.memory_info()
        stats = storage.pool_stats()
        assert set(stats) >= {"bytes_in_use", "peak_bytes_in_use",
                              "bytes_limit"}
        assert free >= 0 and total >= 0
        storage.empty_cache()            # must not raise

    def test_per_device_prng_streams(self):
        import mxnet_tpu as mx
        from mxnet_tpu import random_state

        # same seed -> reproducible stream on the default device
        mx.random.seed(7)
        a = mx.nd.random.uniform(shape=(4,)).asnumpy()
        mx.random.seed(7)
        b = mx.nd.random.uniform(shape=(4,)).asnumpy()
        onp_testing = __import__("numpy").testing
        onp_testing.assert_array_equal(a, b)
        # per-device seeding (reference: mx.random.seed(s, ctx)) reseeds
        # ONE device's stream without touching others
        mx.random.seed(7)
        _ = mx.nd.random.uniform(shape=(4,))     # advance cpu(0)
        mx.random.seed(7, ctx=mx.cpu(0))
        c = mx.nd.random.uniform(shape=(4,)).asnumpy()
        mx.random.seed(7)
        d = mx.nd.random.uniform(shape=(4,)).asnumpy()
        # ctx-seeded stream restarts from PRNGKey(seed); the 'all' path
        # derives per-device keys via fold_in — distinct streams by design
        assert not (c == d).all()
        # different devices draw different streams from one logical seed
        mx.random.seed(11)
        s0 = random_state._stream(random_state._global(), ("cpu", 0))
        s1 = random_state._stream(random_state._global(), ("cpu", 1))
        assert not (__import__("numpy").asarray(s0)
                    == __import__("numpy").asarray(s1)).all()
