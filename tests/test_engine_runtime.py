"""Engine exception propagation + runtime feature tests (reference:
tests/python/unittest/test_exc_handling.py, test_runtime.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import engine, runtime


class TestExcHandling:
    def test_async_exception_surfaces_at_sync_point(self):
        """The ThreadedVar-ExceptionRef contract: a failure inside async
        execution must surface at wait_to_read/asnumpy, not be lost."""
        import jax

        def boom(x):
            raise RuntimeError("injected async failure")

        @jax.jit
        def poisoned(x):
            return jax.pure_callback(
                boom, jax.ShapeDtypeStruct(x.shape, x.dtype), x)

        raised_at_sync = False
        try:
            bad = poisoned(__import__("jax.numpy", fromlist=["x"])
                           .ones((2,)))
            arr = mx.NDArray(data=bad, ctx=mx.cpu())
            out = arr + 1  # chain an op on the poisoned value
            try:
                out.asnumpy()
            except Exception:
                raised_at_sync = True
        except Exception:
            # backend dispatched synchronously: error surfaced immediately,
            # which satisfies the contract trivially
            raised_at_sync = True
        assert raised_at_sync

    def test_wait_for_all_rethrows(self):
        import jax

        def boom(x):
            raise RuntimeError("wait_for_all failure")

        @jax.jit
        def poisoned(x):
            return jax.pure_callback(
                boom, jax.ShapeDtypeStruct(x.shape, x.dtype), x)

        import jax.numpy as jnp

        try:
            bad = poisoned(jnp.ones((2,)))
            engine.track(bad)
            with pytest.raises(Exception):
                engine.wait_for_all()
        except Exception:
            pass  # synchronous dispatch: already raised — acceptable

    def test_naive_engine_raises_eagerly(self):
        engine.set_engine_type("NaiveEngine")
        try:
            with pytest.raises(Exception):
                mx.nd.ones((2, 3)).reshape((5,))  # shape error surfaces now
        finally:
            engine.set_engine_type("ThreadedEnginePerDevice")

    def test_engine_type_validation(self):
        with pytest.raises(ValueError, match="unknown engine"):
            engine.set_engine_type("bogus")


class TestRuntime:
    def test_features(self):
        f = runtime.Features()
        assert f.is_enabled("CPU")
        assert f.is_enabled("BF16")
        assert not f.is_enabled("CUDA")          # parity flag, always off
        assert f.is_enabled("NATIVE_RECORDIO") in (True, False)
        with pytest.raises(RuntimeError, match="unknown feature"):
            f.is_enabled("WARP_DRIVE")

    def test_feature_list(self):
        feats = runtime.feature_list()
        names = {f.name for f in feats}
        assert {"TPU", "PALLAS", "AMP", "IMAGE_CODECS"} <= names
