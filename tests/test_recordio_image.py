"""recordio + mx.image + ImageRecordIter tests (reference:
tests/python/unittest/test_recordio.py, test_image.py).

Includes the VERDICT #8 'done' criterion: training can be fed from a
generated recordio file end to end.
"""
import os
import subprocess
import sys

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import image as img_mod
from mxnet_tpu import recordio as rio
from mxnet_tpu.base import MXNetError


def _img(i, size=32):
    rs = onp.random.RandomState(i)
    return (rs.rand(size, size, 3) * 255).astype("uint8")


class TestRecordIO:
    def test_sequential_round_trip(self, tmp_path):
        path = str(tmp_path / "t.rec")
        w = rio.MXRecordIO(path, "w")
        payloads = [bytes([i]) * (i * 7 + 1) for i in range(20)]
        for p in payloads:
            w.write(p)
        w.close()
        r = rio.MXRecordIO(path, "r")
        got = []
        while True:
            rec = r.read()
            if rec is None:
                break
            got.append(rec)
        assert got == payloads

    def test_byte_layout_is_upstream_format(self, tmp_path):
        """First 8 bytes: magic 0xced7230a, then cflag<<29|len — the
        dmlc-core recordio framing upstream files use."""
        import struct

        path = str(tmp_path / "l.rec")
        w = rio.MXRecordIO(path, "w")
        w.write(b"abcde")
        w.close()
        raw = open(path, "rb").read()
        magic, lrec = struct.unpack("<II", raw[:8])
        assert magic == 0xced7230a
        assert lrec & ((1 << 29) - 1) == 5 and lrec >> 29 == 0
        assert len(raw) == 8 + 8  # payload padded 5 -> 8

    def test_python_and_native_interop(self, tmp_path):
        """Files written by the C++ writer parse with the pure-python
        reader and vice versa."""
        from mxnet_tpu._native import recordio_lib

        if recordio_lib() is None:
            pytest.skip("no native toolchain")
        path = str(tmp_path / "i.rec")
        w = rio.MXRecordIO(path, "w")     # native writer
        w.write(b"x" * 10)
        w.close()
        r = rio.MXRecordIO(path, "r")
        r._h = None                        # force python reader
        r._pyf = open(path, "rb")
        assert r._py_read() == b"x" * 10

    def test_indexed_random_access(self, tmp_path):
        idx, recp = str(tmp_path / "r.idx"), str(tmp_path / "r.rec")
        w = rio.MXIndexedRecordIO(idx, recp, "w")
        for i in range(10):
            w.write_idx(i, f"payload-{i}".encode())
        w.close()
        r = rio.MXIndexedRecordIO(idx, recp, "r")
        assert r.keys == list(range(10))
        assert r.read_idx(7) == b"payload-7"
        assert r.read_idx(2) == b"payload-2"

    def test_pack_img_unpack_img(self, tmp_path):
        arr = _img(0)
        rec = rio.pack_img(rio.IRHeader(0, 3.0, 1, 0), arr, img_fmt=".png")
        header, out = rio.unpack_img(rec)
        assert header.label == 3.0
        onp.testing.assert_array_equal(out, arr)  # png is lossless

    def test_multi_label_pack(self):
        rec = rio.pack(rio.IRHeader(0, [1.0, 2.0], 5, 0), b"d")
        h, payload = rio.unpack(rec)
        assert list(h.label) == [1.0, 2.0] and payload == b"d"


class TestImage:
    def test_imdecode_imresize(self):
        arr = _img(1, 40)
        rec = rio.pack_img(rio.IRHeader(0, 0.0, 0, 0), arr, img_fmt=".png")
        _, payload = rio.unpack(rec)
        img = img_mod.imdecode(payload)
        assert img.shape == (40, 40, 3)
        small = img_mod.imresize(img, 16, 24)
        assert small.shape == (24, 16, 3)

    def test_resize_short_and_crops(self):
        arr = _img(2, 48)
        wide = onp.concatenate([arr, arr], axis=1)  # 48 x 96
        r = img_mod.resize_short(wide, 32)
        assert r.shape[0] == 32 and r.shape[1] == 64
        c, box = img_mod.center_crop(r, (32, 32))
        assert c.shape == (32, 32, 3)
        rc, _ = img_mod.random_crop(r, (16, 16))
        assert rc.shape == (16, 16, 3)

    def test_augmenter_list(self):
        augs = img_mod.CreateAugmenter((3, 24, 24), resize=28,
                                       rand_crop=True, rand_mirror=True,
                                       mean=True, std=True)
        img = _img(3, 64)
        out = img
        for a in augs:
            out = a(out)
        arr = out.asnumpy()
        assert arr.shape == (24, 24, 3) and arr.dtype == onp.float32

    def test_color_jitter_types(self):
        img = _img(4)
        for aug in (img_mod.BrightnessJitterAug(0.3),
                    img_mod.ContrastJitterAug(0.3),
                    img_mod.SaturationJitterAug(0.3),
                    img_mod.RandomGrayAug(1.0),
                    img_mod.LightingAug(0.1, [1.0, 1.0, 1.0],
                                        onp.eye(3))):
            out = aug(img)
            assert out.shape == (32, 32, 3)


def _make_dataset(tmp_path, n=12, size=40):
    idx, recp = str(tmp_path / "d.idx"), str(tmp_path / "d.rec")
    w = rio.MXIndexedRecordIO(idx, recp, "w")
    for i in range(n):
        w.write_idx(i, rio.pack_img(
            rio.IRHeader(0, float(i % 3), i, 0), _img(i, size),
            img_fmt=".png"))
    w.close()
    return idx, recp


class TestImageRecordIter:
    def test_batches_and_labels(self, tmp_path):
        idx, recp = _make_dataset(tmp_path)
        it = mx.io.ImageRecordIter(path_imgrec=recp, path_imgidx=idx,
                                   data_shape=(3, 32, 32), batch_size=4)
        batches = list(it)
        assert len(batches) == 3
        b = batches[0]
        assert b.data[0].shape == (4, 3, 32, 32)
        assert b.label[0].shape == (4,)
        onp.testing.assert_allclose(b.label[0].asnumpy(),
                                    [0.0, 1.0, 2.0, 0.0])

    def test_shuffle_reorders(self, tmp_path):
        import random

        idx, recp = _make_dataset(tmp_path)
        it = mx.io.ImageRecordIter(path_imgrec=recp, path_imgidx=idx,
                                   data_shape=(3, 32, 32), batch_size=12,
                                   shuffle=True)
        random.seed(3)
        it.reset()
        labels = next(it).label[0].asnumpy().tolist()
        assert sorted(labels) == sorted([float(i % 3) for i in range(12)])
        assert labels != [float(i % 3) for i in range(12)]

    def test_module_fit_from_recordio(self, tmp_path):
        """VERDICT #8 done criterion: train from a generated record file."""
        from mxnet_tpu import symbol as sym
        from mxnet_tpu.module import Module

        idx, recp = _make_dataset(tmp_path, n=24, size=12)
        it = mx.io.ImageRecordIter(path_imgrec=recp, path_imgidx=idx,
                                   data_shape=(3, 8, 8), batch_size=8)
        data = sym.var("data")
        net = sym.Flatten(data, name="flat")
        net = sym.FullyConnected(net, name="fc", num_hidden=3)
        net = sym.SoftmaxOutput(net, name="softmax")
        mod = Module(net, data_names=("data",),
                     label_names=("softmax_label",))
        mod.fit(it, num_epoch=2, optimizer="sgd",
                optimizer_params=(("learning_rate", 0.01),))
        # loss decreased enough to show real training happened
        score = mod.score(it, "acc")
        assert score[0][1] >= 0.3


class TestIm2Rec:
    def test_im2rec_tool(self, tmp_path):
        from PIL import Image

        root = tmp_path / "imgs"
        for cls in ("cat", "dog"):
            (root / cls).mkdir(parents=True)
            for i in range(3):
                Image.fromarray(_img(i)).save(root / cls / f"{i}.png")
        prefix = str(tmp_path / "set")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        out = subprocess.run(
            [sys.executable, os.path.join(repo, "tools", "im2rec.py"),
             prefix, str(root)],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stdout + out.stderr
        it = mx.io.ImageRecordIter(path_imgrec=prefix + ".rec",
                                   path_imgidx=prefix + ".idx",
                                   data_shape=(3, 32, 32), batch_size=6)
        b = next(it)
        assert sorted(b.label[0].asnumpy().tolist()) == [0., 0., 0.,
                                                         1., 1., 1.]
