"""Module / checkpoint tests (reference: tests/python/unittest/test_module.py).

Covers the round-1 advisor findings: Module.load must actually restore the
checkpointed weights (high), and init_params must raise on params missing
from a provided arg_params dict when allow_missing=False (medium).
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.base import MXNetError
from mxnet_tpu.io import NDArrayIter
from mxnet_tpu.module import Module


def _mlp_symbol():
    data = sym.var("data")
    fc1 = sym.FullyConnected(data, name="fc1", num_hidden=16)
    act = sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = sym.FullyConnected(act, name="fc2", num_hidden=4)
    return sym.SoftmaxOutput(fc2, name="softmax")


def _toy_iter(n=64, batch=16, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, 8).astype(np.float32)
    w = rs.randn(8, 4).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.float32)
    return NDArrayIter(data=x, label=y, batch_size=batch)


class TestModuleFit:
    def test_fit_converges(self):
        from mxnet_tpu import initializer as init

        mod = Module(_mlp_symbol(), data_names=("data",),
                     label_names=("softmax_label",))
        train = _toy_iter()
        # SoftmaxOutput grads are per-sample sums (reference default
        # normalization='null'), so keep lr small
        mod.fit(train, num_epoch=20, optimizer="sgd",
                initializer=init.Xavier(),
                optimizer_params=(("learning_rate", 0.05),))
        score = mod.score(_toy_iter(), "acc")
        assert score[0][1] > 0.9, f"Module.fit failed to converge: {score}"


class TestModuleCheckpoint:
    def test_load_restores_weights(self, tmp_path):
        """Advisor high finding: load+bind+init_params must yield the saved
        weights, not freshly initialized ones."""
        prefix = str(tmp_path / "mlp")
        mod = Module(_mlp_symbol())
        train = _toy_iter()
        mod.fit(train, num_epoch=2, optimizer="sgd")
        mod.save_checkpoint(prefix, 1)
        saved_args, saved_aux = mod.get_params()

        mod2 = Module.load(prefix, 1)
        mod2.bind(data_shapes=train.provide_data,
                  label_shapes=train.provide_label)
        mod2.init_params()
        loaded_args, _ = mod2.get_params()
        for name, arr in saved_args.items():
            np.testing.assert_allclose(
                loaded_args[name].asnumpy(), arr.asnumpy(), rtol=1e-6,
                err_msg=f"param {name} not restored by Module.load")

        # outputs match too
        batch = next(iter(_toy_iter()))
        mod.forward(batch, is_train=False)
        mod2.forward(batch, is_train=False)
        np.testing.assert_allclose(mod2.get_outputs()[0].asnumpy(),
                                   mod.get_outputs()[0].asnumpy(),
                                   rtol=1e-5, atol=1e-6)

    def test_load_optimizer_states(self, tmp_path):
        prefix = str(tmp_path / "mlp")
        mod = Module(_mlp_symbol())
        train = _toy_iter()
        mod.fit(train, num_epoch=2, optimizer="sgd",
                optimizer_params=(("learning_rate", 0.1),
                                  ("momentum", 0.9)))
        mod.save_checkpoint(prefix, 1, save_optimizer_states=True)
        assert os.path.exists(f"{prefix}-0001.states")

        mod2 = Module.load(prefix, 1, load_optimizer_states=True)
        mod2.bind(data_shapes=train.provide_data,
                  label_shapes=train.provide_label)
        mod2.init_params()
        mod2.init_optimizer(optimizer="sgd",
                            optimizer_params=(("learning_rate", 0.1),
                                              ("momentum", 0.9)))
        s1 = mod._updater.states
        s2 = mod2._updater.states
        assert set(s1.keys()) == set(s2.keys())

    def test_init_params_missing_raises(self):
        """Advisor medium finding: a provided arg_params dict missing a
        param must raise unless allow_missing=True."""
        mod = Module(_mlp_symbol())
        train = _toy_iter()
        mod.bind(data_shapes=train.provide_data,
                 label_shapes=train.provide_label)
        partial = {"fc1_weight": mx.nd.zeros((16, 8))}
        with pytest.raises(MXNetError, match="missing"):
            mod.init_params(arg_params=partial, allow_missing=False)
        # allow_missing=True initializes the rest instead
        mod.init_params(arg_params=partial, allow_missing=True,
                        force_init=True)
        args, _ = mod.get_params()
        np.testing.assert_allclose(args["fc1_weight"].asnumpy(), 0.0)

    def test_load_bind_forward_no_init_params(self, tmp_path):
        """Round-2 review finding: load+bind+forward (no explicit
        init_params call) must run with the checkpointed weights —
        reference Module.load marks params initialized at load time."""
        prefix = str(tmp_path / "mlp")
        mod = Module(_mlp_symbol())
        train = _toy_iter()
        mod.fit(train, num_epoch=2, optimizer="sgd")
        mod.save_checkpoint(prefix, 1)

        mod2 = Module.load(prefix, 1)
        mod2.bind(data_shapes=train.provide_data,
                  label_shapes=train.provide_label, for_training=False)
        assert mod2.params_initialized
        batch = next(iter(_toy_iter()))
        mod.forward(batch, is_train=False)
        mod2.forward(batch, is_train=False)
        np.testing.assert_allclose(mod2.get_outputs()[0].asnumpy(),
                                   mod.get_outputs()[0].asnumpy(),
                                   rtol=1e-5, atol=1e-6)

    def test_load_partial_init_keeps_other_half(self, tmp_path):
        """Round-2 review finding: init_params with only one of
        arg_params/aux_params on a loaded module must keep the
        checkpointed other half, not reinitialize it."""
        prefix = str(tmp_path / "mlp")
        mod = Module(_mlp_symbol())
        train = _toy_iter()
        mod.fit(train, num_epoch=1, optimizer="sgd")
        mod.save_checkpoint(prefix, 0)
        saved_args, _ = mod.get_params()

        mod2 = Module.load(prefix, 0)
        mod2.bind(data_shapes=train.provide_data,
                  label_shapes=train.provide_label)
        mod2.init_params(aux_params={}, allow_missing=True, force_init=True)
        loaded_args, _ = mod2.get_params()
        for name, arr in saved_args.items():
            np.testing.assert_allclose(
                loaded_args[name].asnumpy(), arr.asnumpy(), rtol=1e-6,
                err_msg=f"preloaded param {name} discarded by partial init")

    def test_init_params_missing_aux_raises(self):
        """Round-2 review finding: strictness must cover aux states too."""
        data = sym.var("data")
        fc = sym.FullyConnected(data, name="fc1", num_hidden=4)
        bn = sym.BatchNorm(fc, name="bn")
        out = sym.SoftmaxOutput(bn, name="softmax")
        mod = Module(out)
        mod.bind(data_shapes=[("data", (8, 8))],
                 label_shapes=[("softmax_label", (8,))])
        mod.init_params(allow_missing=True)
        args, _ = mod.get_params()
        with pytest.raises(MXNetError, match="auxiliary"):
            mod.init_params(arg_params=args, aux_params={},
                            allow_missing=False, force_init=True)


class TestInstallMonitor:
    def test_fit_with_monitor_module(self, caplog):
        import logging

        from mxnet_tpu.monitor import Monitor

        mod = Module(_mlp_symbol(), data_names=("data",),
                     label_names=("softmax_label",))
        mon = Monitor(interval=2, pattern=".*fc1.*")
        with caplog.at_level(logging.INFO):
            mod.fit(_toy_iter(), num_epoch=1, monitor=mon)
        assert mod._exec in mon.exes
        assert any("fc1_weight" in r.getMessage() for r in caplog.records)

    def test_fit_with_monitor_bucketing(self):
        """Round-2 review finding: BaseModule.fit touched Module-only _exec;
        install_monitor must be polymorphic over BucketingModule too."""
        from mxnet_tpu.module import BucketingModule
        from mxnet_tpu.monitor import Monitor

        def sym_gen(key):
            return _mlp_symbol(), ("data",), ("softmax_label",)

        mod = BucketingModule(sym_gen, default_bucket_key=8)
        mon = Monitor(interval=1)
        mod.fit(_toy_iter(), num_epoch=1, monitor=mon)
        assert len(mon.exes) == 1

    def test_rebind_swaps_monitored_executor(self):
        from mxnet_tpu.monitor import Monitor

        mod = Module(_mlp_symbol(), data_names=("data",),
                     label_names=("softmax_label",))
        mon = Monitor(interval=1)
        mod.fit(_toy_iter(), num_epoch=1, monitor=mon)
        first = mod._exec
        mod.fit(_toy_iter(), num_epoch=1, monitor=mon, force_rebind=True,
                force_init=True)
        assert first not in mon.exes and mod._exec in mon.exes
        assert len(mon.exes) == 1


def test_metric_pcc_and_legacy_aliases():
    """PCC equals MCC for binary confusion; Torch/Caffe = Loss aliases."""
    m = mx.metric.PCC()
    lab = mx.nd.array([0, 1, 1, 0, 1, 1])
    pred = mx.nd.array([[0.9, 0.1], [0.2, 0.8], [0.3, 0.7],
                        [0.6, 0.4], [0.8, 0.2], [0.1, 0.9]])
    m.update([lab], [pred])
    tp, tn, fp, fn = 3, 2, 0, 1
    want = (tp * tn - fp * fn) / ((tp + fp) * (tp + fn)
                                  * (tn + fp) * (tn + fn)) ** 0.5
    assert abs(m.get()[1] - want) < 1e-6
    t = mx.metric.Torch()
    t.update(None, mx.nd.array([1.0, 2.0]))
    assert t.get()[1] == 1.5


def test_initializer_load():
    import numpy as onp
    d = mx.nd.ones((2, 3)) * 7
    init = mx.init.Load({"w": d}, default_init=mx.init.Zero())
    arr = mx.nd.zeros((2, 3))
    init("w", arr)
    onp.testing.assert_allclose(arr.asnumpy(), 7)
    arr2 = mx.nd.ones((4,))
    init("other", arr2)
    onp.testing.assert_allclose(arr2.asnumpy(), 0)
    with pytest.raises(ValueError):
        mx.init.Load({})("missing", mx.nd.ones((1,)))
