"""Small-training convergence tier (reference: tests/python/train/
test_conv.py, test_mlp.py — tiny nets must cross an accuracy threshold;
the tier that catches silent numeric bugs no unit test sees).

The conv net deliberately includes BatchNorm (the hand-derived custom-VJP
training path) and a 1x1 conv (the dot formulation) so end-to-end training
through the round-4 perf paths is gated on actually learning.
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn


def _separable(n=256, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.uniform(-1, 1, (n, 1, 8, 8)).astype(np.float32)
    y = (X.mean(axis=(1, 2, 3)) > 0).astype(np.float32)
    X[y == 1] += 0.45
    return X, y


def test_convnet_with_bn_converges():
    X, y = _separable()
    with nn.conv_layout("NHWC"):
        net = nn.HybridSequential()
        net.add(nn.Conv2D(8, 3, padding=1), nn.BatchNorm(),
                nn.Activation("relu"),
                nn.Conv2D(16, 1), nn.BatchNorm(), nn.Activation("relu"),
                nn.GlobalAvgPool2D(), nn.Dense(1))
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.05})
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    xb, yb = mx.nd.array(X), mx.nd.array(y)
    for _ in range(60):
        with autograd.record():
            out = net(xb)
            loss = loss_fn(out.reshape(-1), yb).mean()
        loss.backward()
        trainer.step(1)
    pred = (net(xb).reshape(-1).asnumpy() > 0).astype(np.float32)
    acc = float((pred == y).mean())
    assert acc > 0.95, f"convnet failed to converge: acc={acc}"
    # BN moving stats must have moved (aux write-back through the
    # custom-vjp path)
    rm = net[1].running_mean.data().asnumpy()
    assert float(np.abs(rm).max()) > 1e-5


def test_mlp_converges():
    rs = np.random.RandomState(1)
    X = rs.uniform(-1, 1, (256, 16)).astype(np.float32)
    w = rs.randn(16).astype(np.float32)
    y = (X @ w > 0).astype(np.float32)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(1))
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5, "momentum": 0.9})
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    xb, yb = mx.nd.array(X), mx.nd.array(y)
    for _ in range(80):
        with autograd.record():
            loss = loss_fn(net(xb).reshape(-1), yb).mean()
        loss.backward()
        trainer.step(1)
    pred = (net(xb).reshape(-1).asnumpy() > 0).astype(np.float32)
    acc = float((pred == y).mean())
    assert acc > 0.95, f"mlp failed to converge: acc={acc}"
