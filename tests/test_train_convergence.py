"""Small-training convergence tier (reference: tests/python/train/
test_conv.py, test_mlp.py — tiny nets must cross an accuracy threshold;
the tier that catches silent numeric bugs no unit test sees).

The conv net deliberately includes BatchNorm (the hand-derived custom-VJP
training path) and a 1x1 conv (the dot formulation) so end-to-end training
through the round-4 perf paths is gated on actually learning.
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn


def _separable(n=256, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.uniform(-1, 1, (n, 1, 8, 8)).astype(np.float32)
    y = (X.mean(axis=(1, 2, 3)) > 0).astype(np.float32)
    X[y == 1] += 0.45
    return X, y


def test_convnet_with_bn_converges():
    X, y = _separable()
    with nn.conv_layout("NHWC"):
        net = nn.HybridSequential()
        net.add(nn.Conv2D(8, 3, padding=1), nn.BatchNorm(),
                nn.Activation("relu"),
                nn.Conv2D(16, 1), nn.BatchNorm(), nn.Activation("relu"),
                nn.GlobalAvgPool2D(), nn.Dense(1))
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.05})
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    xb, yb = mx.nd.array(X), mx.nd.array(y)
    for _ in range(60):
        with autograd.record():
            out = net(xb)
            loss = loss_fn(out.reshape(-1), yb).mean()
        loss.backward()
        trainer.step(1)
    pred = (net(xb).reshape(-1).asnumpy() > 0).astype(np.float32)
    acc = float((pred == y).mean())
    assert acc > 0.95, f"convnet failed to converge: acc={acc}"
    # BN moving stats must have moved (aux write-back through the
    # custom-vjp path)
    rm = net[1].running_mean.data().asnumpy()
    assert float(np.abs(rm).max()) > 1e-5


def test_mlp_converges():
    rs = np.random.RandomState(1)
    X = rs.uniform(-1, 1, (256, 16)).astype(np.float32)
    w = rs.randn(16).astype(np.float32)
    y = (X @ w > 0).astype(np.float32)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(1))
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5, "momentum": 0.9})
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    xb, yb = mx.nd.array(X), mx.nd.array(y)
    for _ in range(80):
        with autograd.record():
            loss = loss_fn(net(xb).reshape(-1), yb).mean()
        loss.backward()
        trainer.step(1)
    pred = (net(xb).reshape(-1).asnumpy() > 0).astype(np.float32)
    acc = float((pred == y).mean())
    assert acc > 0.95, f"mlp failed to converge: acc={acc}"


def test_synthetic_dataset_splits_share_class_structure():
    """The zero-egress dataset surrogates must draw the SAME class
    prototypes for train and test — per-split prototypes made a model
    trained on the surrogate train split score at chance on its test
    split (the silent-generalization-failure bug fixed in round 4)."""
    from mxnet_tpu.gluon.data import vision

    for cls in (vision.MNIST, vision.CIFAR10):
        tr = cls(root="/nonexistent-forces-synthetic", train=True)
        te = cls(root="/nonexistent-forces-synthetic", train=False)
        assert tr.synthetic and te.synthetic

        def class_means(ds):
            import numpy as onp
            xs = ds._data[:512].astype(onp.float32)
            ys = onp.asarray(ds._label[:512])
            return onp.stack([xs[ys == c].mean(axis=0).ravel()
                              for c in range(10)])

        import numpy as onp
        a, b = class_means(tr), class_means(te)
        # same-class means across splits must correlate far better than
        # cross-class ones
        same = onp.mean([onp.corrcoef(a[c], b[c])[0, 1] for c in range(10)])
        cross = onp.mean([onp.corrcoef(a[c], b[(c + 1) % 10])[0, 1]
                          for c in range(10)])
        assert same > 0.5 and same > cross + 0.3, (same, cross)


def test_synthetic_mnist_train_generalizes_to_test():
    """End-to-end: a linear probe fit on the surrogate train split must
    transfer to the surrogate test split."""
    import numpy as onp
    from mxnet_tpu.gluon.data import vision

    tr = vision.MNIST(root="/nonexistent-forces-synthetic", train=True)
    te = vision.MNIST(root="/nonexistent-forces-synthetic", train=False)
    xtr = onp.asarray(tr._data[:2048], onp.float32).reshape(2048, -1) / 255.0
    ytr = onp.asarray(tr._label[:2048])
    xte = onp.asarray(te._data[:512], onp.float32).reshape(512, -1) / 255.0
    yte = onp.asarray(te._label[:512])
    # nearest-class-mean classifier
    means = onp.stack([xtr[ytr == c].mean(axis=0) for c in range(10)])
    pred = ((xte[:, None, :] - means[None]) ** 2).sum(-1).argmin(1)
    acc = float((pred == yte).mean())
    assert acc > 0.9, f"surrogate test split not learnable from train: {acc}"
