"""Gluon tests (reference: tests/python/unittest/test_gluon.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn


def test_dense_shapes_and_flatten():
    d = nn.Dense(7)
    d.initialize()
    out = d(mx.nd.ones((4, 3, 5)))
    assert out.shape == (4, 7)  # flatten=True
    d2 = nn.Dense(7, flatten=False)
    d2.initialize()
    assert d2(mx.nd.ones((4, 3, 5))).shape == (4, 3, 7)


def test_deferred_init_and_explicit():
    d = nn.Dense(3)
    d.initialize()
    with pytest.raises(Exception):
        d.weight.data()  # deferred until first forward
    d(mx.nd.ones((2, 9)))
    assert d.weight.shape == (3, 9)
    e = nn.Dense(3, in_units=9)
    e.initialize()
    assert e.weight.data().shape == (3, 9)


def test_conv_pool_stack():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(8, 3, padding=1), nn.MaxPool2D(), nn.Conv2D(4, 1))
    net.initialize()
    out = net(mx.nd.ones((2, 3, 8, 8)))
    assert out.shape == (2, 4, 4, 4)


def test_conv_groups_and_transpose():
    c = nn.Conv2D(8, 3, groups=2, in_channels=4)
    c.initialize()
    assert c(mx.nd.ones((1, 4, 5, 5))).shape == (1, 8, 3, 3)
    t = nn.Conv2DTranspose(3, 4, strides=2, in_channels=2)
    t.initialize()
    out = t(mx.nd.ones((1, 2, 4, 4)))
    assert out.shape == (1, 3, 10, 10)  # (4-1)*2 + 4


def test_parameter_sharing():
    d1 = nn.Dense(5, in_units=4)
    d2 = nn.Dense(5, in_units=4, params=d1.collect_params())
    d1.initialize()
    x = mx.nd.random.uniform(shape=(2, 4))
    assert np.allclose(d1(x).asnumpy(), d2(x).asnumpy())


def test_collect_params_select():
    net = nn.HybridSequential(prefix="model_")
    with net.name_scope():
        net.add(nn.Dense(3, in_units=2), nn.Dense(2, in_units=3))
    params = net.collect_params(".*weight")
    assert all(k.endswith("weight") for k in params.keys())
    assert len(params) == 2


def test_hybridize_parity_and_cache():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    x = mx.nd.random.normal(shape=(3, 8))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    assert np.allclose(eager, hybrid, rtol=1e-5, atol=1e-6)
    # different shape recompiles transparently
    y = mx.nd.random.normal(shape=(5, 8))
    assert net(y).shape == (5, 4)


def test_hybridize_dropout_fresh_masks():
    # one compiled executable must yield fresh randomness per call
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dropout(0.5))
    net.initialize()
    net.hybridize()
    x = mx.nd.ones((100,))
    with autograd.record():
        a = net(x).asnumpy()
        b = net(x).asnumpy()
    assert not np.allclose(a, b), "dropout mask must differ across calls"


def test_hybridize_batchnorm_aux_updates():
    bn = nn.BatchNorm()
    bn.initialize()
    bn.hybridize()
    x = mx.nd.random.normal(loc=5.0, shape=(16, 3))
    with autograd.record():
        bn(x)
    rm = bn.running_mean.data().asnumpy()
    assert not np.allclose(rm, 0), "traced aux-state update must write back"


def test_hybridize_grads_match_eager():
    def build():
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(8, activation="tanh"), nn.Dense(1))
        return net

    net = build()
    net.initialize(mx.init.Xavier())
    x = mx.nd.random.normal(shape=(4, 6))
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    eager_grads = {k: p.grad().asnumpy().copy()
                   for k, p in net.collect_params().items()}
    for p in net.collect_params().values():
        p.zero_grad()
    net.hybridize()
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    for k, p in net.collect_params().items():
        assert np.allclose(p.grad().asnumpy(), eager_grads[k], rtol=1e-4,
                           atol=1e-5), k


def test_trainer_step_converges():
    net = nn.Dense(1, in_units=2)
    net.initialize(mx.init.Normal(0.1))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    x = mx.nd.array(np.random.randn(64, 2).astype("float32"))
    w_true = np.array([[2.0], [-3.0]], dtype="float32")
    y = mx.nd.array(x.asnumpy() @ w_true)
    l2 = gluon.loss.L2Loss()
    for _ in range(200):
        with autograd.record():
            loss = l2(net(x), y)
        loss.backward()
        trainer.step(64)
    w = net.weight.data().asnumpy()
    assert np.allclose(w, w_true.T, atol=1e-2)


def test_loss_values_vs_numpy():
    pred = mx.nd.array([[1.0, 2.0, 3.0], [1.0, 1.0, 1.0]])
    label = mx.nd.array([2, 0])
    l = gluon.loss.SoftmaxCrossEntropyLoss()(pred, label).asnumpy()
    logp = pred.asnumpy() - np.log(np.exp(pred.asnumpy()).sum(-1, keepdims=True))
    expect = -np.array([logp[0, 2], logp[1, 0]])
    assert np.allclose(l, expect, rtol=1e-5)
    # L2
    p = mx.nd.array([1.0, 2.0])
    t = mx.nd.array([0.0, 0.0])
    assert np.allclose(gluon.loss.L2Loss()(p, t).asnumpy(), [0.5, 2.0])
    # BCE with logits is stable at extremes
    big = mx.nd.array([100.0, -100.0])
    lbl = mx.nd.array([1.0, 0.0])
    bce = gluon.loss.SigmoidBCELoss()(big, lbl).asnumpy()
    assert np.all(np.isfinite(bce)) and np.allclose(bce, 0, atol=1e-4)


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4, in_units=3), nn.BatchNorm(in_channels=4))
    net.initialize()
    x = mx.nd.random.normal(shape=(2, 3))
    ref = net(x).asnumpy()
    f = str(tmp_path / "net.params")
    net.save_parameters(f)
    net2 = nn.HybridSequential()
    with net2.name_scope():
        net2.add(nn.Dense(4, in_units=3), nn.BatchNorm(in_channels=4))
    net2.load_parameters(f)
    assert np.allclose(net2(x).asnumpy(), ref, atol=1e-6)
    with pytest.raises(Exception):
        bad = nn.Dense(9, in_units=3)
        bad.load_parameters(f)


def test_dataloader_batching_and_workers():
    ds = gluon.data.ArrayDataset(np.arange(20).astype("float32"),
                                 np.arange(20).astype("int32"))
    loader = gluon.data.DataLoader(ds, batch_size=6, last_batch="keep")
    batches = list(loader)
    assert len(batches) == 4
    assert batches[0][0].shape == (6,)
    assert batches[-1][0].shape == (2,)
    loader = gluon.data.DataLoader(ds, batch_size=6, last_batch="discard")
    assert len(list(loader)) == 3
    # multiprocess workers produce identical content for sequential sampling
    loader_mp = gluon.data.DataLoader(ds, batch_size=5, num_workers=2)
    got = np.concatenate([b[0].asnumpy() for b in loader_mp])
    assert np.allclose(np.sort(got), np.arange(20))


def test_transforms_pipeline():
    from mxnet_tpu.gluon.data.vision import transforms

    img = mx.nd.array(np.random.randint(0, 255, (28, 28, 3)), dtype="uint8")
    t = transforms.Compose([transforms.ToTensor(),
                            transforms.Normalize(0.5, 0.5)])
    out = t(img)
    assert out.shape == (3, 28, 28)
    assert out.dtype == np.float32
    r = transforms.Resize(14)(img)
    assert r.shape == (14, 14, 3)
    c = transforms.CenterCrop(20)(img)
    assert c.shape == (20, 20, 3)
    rc = transforms.RandomResizedCrop(16)(img)
    assert rc.shape == (16, 16, 3)


def test_rnn_cells_match_layer():
    # single-layer unidirectional LSTM: cell unroll == fused layer
    hidden = 5
    layer = gluon.rnn.LSTM(hidden, input_size=4)
    layer.initialize()
    cell = gluon.rnn.LSTMCell(hidden, input_size=4)
    cell.initialize()
    # copy layer weights into cell
    cell.i2h_weight.set_data(layer.l0_i2h_weight.data())
    cell.h2h_weight.set_data(layer.l0_h2h_weight.data())
    cell.i2h_bias.set_data(layer.l0_i2h_bias.data())
    cell.h2h_bias.set_data(layer.l0_h2h_bias.data())
    x = mx.nd.random.normal(shape=(7, 2, 4))  # TNC
    fused = layer(x).asnumpy()
    outs, _ = cell.unroll(7, x, layout="TNC", merge_outputs=True)
    assert np.allclose(outs.asnumpy(), fused, rtol=1e-4, atol=1e-5)


def test_gru_rnn_layers_run():
    for layer in (gluon.rnn.GRU(6, num_layers=2),
                  gluon.rnn.RNN(6, activation="tanh")):
        layer.initialize()
        out = layer(mx.nd.random.normal(shape=(4, 3, 5)))
        assert out.shape == (4, 3, 6)


def test_sequential_slicing():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4), nn.Dense(3), nn.Dense(2))
    assert len(net) == 3
    assert isinstance(net[1], nn.Dense)
    sub = net[:2]
    assert len(sub) == 2


def test_metrics():
    acc = mx.metric.Accuracy()
    acc.update([mx.nd.array([1, 0])], [mx.nd.array([[0.2, 0.8], [0.9, 0.1]])])
    assert acc.get()[1] == 1.0
    topk = mx.metric.TopKAccuracy(top_k=2)
    topk.update([mx.nd.array([2])], [mx.nd.array([[0.4, 0.3, 0.35]])])
    assert topk.get()[1] == 1.0
    mse = mx.metric.MSE()
    mse.update([mx.nd.array([1.0, 2.0])], [mx.nd.array([0.0, 0.0])])
    assert np.isclose(mse.get()[1], 2.5)
    comp = mx.metric.CompositeEvalMetric()
    comp.add(mx.metric.Accuracy())
    comp.add(mx.metric.MSE())
    names, values = comp.get()
    assert len(names) == 2


def test_block_hooks():
    net = nn.Dense(2, in_units=2)
    net.initialize()
    calls = []
    h1 = net.register_forward_pre_hook(lambda blk, inp: calls.append("pre"))
    h2 = net.register_forward_hook(lambda blk, inp, out: calls.append("post"))
    net(mx.nd.ones((1, 2)))
    assert calls == ["pre", "post"]
    h1.detach()
    h2.detach()
    net(mx.nd.ones((1, 2)))
    assert calls == ["pre", "post"]


def test_cast_dtype():
    net = nn.Dense(3, in_units=2)
    net.initialize()
    net.cast("bfloat16")
    out = net(mx.nd.ones((2, 2), dtype="bfloat16"))
    assert str(out.dtype) == "bfloat16"


def test_name_scope_not_leaked_by_reentrant_blocks():
    """Regression: Dense(activation=...) re-enters its own name_scope in
    __init__ (via _make_activation); the scope stack must unwind to None
    or every later top-level block inherits a bogus prefix."""
    from mxnet_tpu.gluon.block import _scope

    before = _scope.current
    net = nn.HybridSequential()
    net.add(nn.Dense(4, activation="relu"), nn.Dense(2))
    assert _scope.current is before
    d = nn.Dense(3)
    assert not d.prefix.startswith(net.prefix)


def test_dataloader_shm_process_workers(monkeypatch):
    """Round-4 (VERDICT r3 missing #7): fork workers ship batches as
    shared-memory descriptors, not pickled payloads; content identical to
    the in-process loader and no shm blocks leak."""
    import glob

    monkeypatch.setenv("MXNET_TPU_FORK_WORKERS", "1")
    pre_existing = set(glob.glob("/dev/shm/psm_*"))
    data = np.arange(60, dtype="float32").reshape(20, 3)
    labels = np.arange(20, dtype="int32")
    ds = gluon.data.ArrayDataset(data, labels)

    want = [(b[0].asnumpy(), b[1].asnumpy())
            for b in gluon.data.DataLoader(ds, batch_size=5)]

    def run():
        loader = gluon.data.DataLoader(ds, batch_size=5, num_workers=2)
        out = [(b[0].asnumpy(), b[1].asnumpy()) for b in loader]
        del loader
        return out

    got = run()
    assert len(got) == len(want)
    for (gd, gl), (wd, wl) in zip(got, want):
        np.testing.assert_allclose(gd, wd)
        np.testing.assert_array_equal(gl, wl)
    # parent unlinked every block THIS loader created (other processes'
    # psm_* segments may legitimately exist)
    leaked = set(glob.glob("/dev/shm/psm_*")) - pre_existing
    assert not leaked, leaked

    # early-stop cleanup: prefetched-but-unconsumed batches are unlinked
    # when the iterator is closed mid-stream
    loader = gluon.data.DataLoader(ds, batch_size=5, num_workers=2)
    it = iter(loader)
    next(it)
    it.close()
    del loader
    leaked = set(glob.glob("/dev/shm/psm_*")) - pre_existing
    assert not leaked, leaked

    # opt-out still works (pickled-numpy fallback)
    monkeypatch.setenv("MXNET_TPU_SHM", "0")
    got2 = run()
    for (gd, _), (wd, _) in zip(got2, want):
        np.testing.assert_allclose(gd, wd)


def test_dataloader_shm_structure_matches_inprocess(monkeypatch):
    """Review r4: batch STRUCTURE must be identical across transports,
    including 1-tuple samples."""
    monkeypatch.setenv("MXNET_TPU_FORK_WORKERS", "1")

    class OneTuple(gluon.data.Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            return (np.full((3,), float(i), "float32"),)

    ds = OneTuple()
    ref = list(gluon.data.DataLoader(ds, batch_size=4))
    got = list(gluon.data.DataLoader(ds, batch_size=4, num_workers=2))
    assert type(ref[0]) is type(got[0]) and len(ref[0]) == len(got[0]) == 1
    np.testing.assert_allclose(got[0][0].asnumpy(), ref[0][0].asnumpy())


def test_nn_exposes_block_bases_and_hybrid_sequential_cell():
    """Upstream surface: gluon.nn.Block/HybridBlock/SymbolBlock aliases and
    rnn.HybridSequentialRNNCell exist."""
    from mxnet_tpu.gluon import nn as gnn, rnn as grnn
    assert gnn.Block is mx.gluon.Block
    assert gnn.HybridBlock is mx.gluon.HybridBlock
    cell = grnn.HybridSequentialRNNCell()
    cell.add(grnn.LSTMCell(8, input_size=4))
    cell.initialize()
    x = mx.nd.ones((2, 4))
    out, _ = cell(x, cell.begin_state(batch_size=2, func=mx.nd.zeros))
    assert out.shape == (2, 8)
