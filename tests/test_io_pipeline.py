"""Async input pipeline tests (PR: device-feed input pipeline).

Covers the three pipeline stages end to end on the 8-device CPU mesh:

* ``image.ImageIter`` process decode workers — bit-identical to the
  serial path under fixed seeds, shm hygiene, close() protocol;
* ``io.PrefetchingIter`` — post-exhaustion StopIteration (regression:
  next() after the final None used to block forever on the dead
  worker's queue), worker-error surfacing, close/join;
* ``io.DeviceFeedIter`` — sharded staging matching
  ``TrainStep.input_shardings``, on-device transform, reset/exhaustion/
  close semantics, ``mxnet_data_wait_seconds`` emission, and the
  ``datafeed.put`` fault site surfacing as MXNetError instead of a hang.
"""
import glob
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import fault, image as mimg, io as mxio, recordio, telemetry
from mxnet_tpu.base import MXNetError

pytestmark = pytest.mark.io


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _write_rec(path, n=24, size=40, indexed=False):
    rs = np.random.RandomState(0)
    writer = recordio.MXIndexedRecordIO(str(path) + ".idx", str(path), "w") \
        if indexed else recordio.MXRecordIO(str(path), "w")
    for i in range(n):
        img = rs.randint(0, 256, (size, size, 3), np.uint8)
        rec = recordio.pack_img(recordio.IRHeader(0, float(i), i, 0), img,
                                quality=90)
        if indexed:
            writer.write_idx(i, rec)
        else:
            writer.write(rec)
    writer.close()
    return str(path)


def _aug():
    return [mimg.RandomCropAug((32, 32)), mimg.HorizontalFlipAug(0.5)]


class _SlowAug:
    """Module-level (fork-inheritable) augmenter that outruns a short
    worker_timeout."""

    def __call__(self, src):
        time.sleep(3.0)
        return src


def _image_iter(rec, mode, workers=2, seed=7, dtype="uint8", **kw):
    return mimg.ImageIter(batch_size=8, data_shape=(3, 32, 32),
                          path_imgrec=rec, aug_list=_aug(), seed=seed,
                          dtype=dtype, worker_mode=mode,
                          preprocess_threads=workers, **kw)


def _drain(it):
    out = []
    try:
        while True:
            b = it.next()
            out.append((b.data[0].asnumpy(), b.label[0].asnumpy()))
    except StopIteration:
        pass
    return out


def _pipeline_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith(("mxnet-prefetch", "mxnet-"))
            and t.is_alive()]


# ---------------------------------------------------------------------------
# ImageIter decode workers
# ---------------------------------------------------------------------------

class TestImageIterWorkers:
    def test_process_bit_identical_to_serial(self, tmp_path):
        """The acceptance contract: seeded augmenters make process-worker
        batches EQUAL the single-thread path's, across epochs."""
        rec = _write_rec(tmp_path / "a.rec")
        pre = set(glob.glob("/dev/shm/psm_*"))
        it_s = _image_iter(rec, "serial", 1)
        it_p = _image_iter(rec, "process", 2)
        for epoch in range(2):
            a, b = _drain(it_s), _drain(it_p)
            assert len(a) == len(b) == 3
            for (da, la), (db, lb) in zip(a, b):
                np.testing.assert_array_equal(da, db)
                np.testing.assert_array_equal(la, lb)
            it_s.reset()
            it_p.reset()
        it_s.close()
        it_p.close()
        # the parent unlinked every chunk block it consumed
        assert not set(glob.glob("/dev/shm/psm_*")) - pre

    def test_float32_default_augmenters_identical(self, tmp_path):
        """Same contract through the full float pipeline (cast +
        normalize + jitter augmenters from CreateAugmenter)."""
        rec = _write_rec(tmp_path / "b.rec", n=8)
        aug = lambda: mimg.CreateAugmenter(  # noqa: E731
            (3, 32, 32), rand_crop=True, rand_mirror=True, brightness=0.2,
            mean=np.array([1.0, 2.0, 3.0]), std=np.array([4.0, 5.0, 6.0]))
        outs = []
        for mode, w in (("serial", 1), ("process", 2)):
            it = mimg.ImageIter(batch_size=8, data_shape=(3, 32, 32),
                                path_imgrec=rec, aug_list=aug(), seed=3,
                                worker_mode=mode, preprocess_threads=w)
            outs.append(it.next().data[0].asnumpy())
            it.close()
        assert outs[0].dtype == np.float32
        np.testing.assert_array_equal(outs[0], outs[1])

    def test_seeded_shuffle_is_deterministic(self, tmp_path):
        rec = _write_rec(tmp_path / "c.rec", indexed=True)
        labels = []
        for _ in range(2):
            it = mimg.ImageIter(batch_size=8, data_shape=(3, 32, 32),
                                path_imgrec=rec, path_imgidx=rec + ".idx",
                                shuffle=True, aug_list=_aug(), seed=11,
                                worker_mode="serial")
            labels.append(np.concatenate(
                [lab for _, lab in _drain(it)]))
            it.close()
        np.testing.assert_array_equal(labels[0], labels[1])
        assert not np.array_equal(labels[0], np.sort(labels[0]))

    def test_close_idempotent_and_pool_gone(self, tmp_path):
        rec = _write_rec(tmp_path / "d.rec", n=8)
        it = _image_iter(rec, "process", 2)
        it.next()
        assert it._pool is not None
        it.close()
        assert it._pool is None
        it.close()  # idempotent

    def test_worker_failure_raises_mxnet_error(self, tmp_path):
        """A crashing decode worker surfaces as MXNetError, not a hang,
        and leaks no shm blocks."""
        path = str(tmp_path / "bad.rec")
        writer = recordio.MXRecordIO(path, "w")
        for i in range(8):
            writer.write(recordio.pack(recordio.IRHeader(0, 0.0, i, 0),
                                       b"not a jpeg"))
        writer.close()
        pre = set(glob.glob("/dev/shm/psm_*"))
        it = mimg.ImageIter(batch_size=8, data_shape=(3, 32, 32),
                            path_imgrec=path, aug_list=_aug(),
                            worker_mode="process", preprocess_threads=2)
        with pytest.raises(MXNetError, match="decode worker"):
            it.next()
        it.close()
        assert not set(glob.glob("/dev/shm/psm_*")) - pre

    def test_env_knob_selects_process_mode(self, tmp_path, monkeypatch):
        monkeypatch.setenv("MXNET_DATA_WORKERS", "2")
        rec = _write_rec(tmp_path / "e.rec", n=8)
        it = mimg.ImageIter(batch_size=8, data_shape=(3, 32, 32),
                            path_imgrec=rec, aug_list=_aug())
        assert it._worker_mode == "process" and it._n_workers == 2
        it.next()
        it.close()

    def test_bad_worker_mode_rejected(self, tmp_path):
        rec = _write_rec(tmp_path / "f.rec", n=8)
        with pytest.raises(MXNetError, match="worker_mode"):
            mimg.ImageIter(batch_size=8, data_shape=(3, 32, 32),
                           path_imgrec=rec, worker_mode="gpu")

    def test_uint8_with_host_normalization_rejected(self, tmp_path):
        """Review regression: normalized floats cast to uint8 WRAP into
        garbage — both the factory and the decode path must refuse."""
        rec = _write_rec(tmp_path / "n.rec", n=8)
        with pytest.raises(MXNetError, match="incompatible with dtype"):
            mxio.ImageRecordIter(path_imgrec=rec, data_shape=(3, 32, 32),
                                 batch_size=4, mean_r=123.0, dtype="uint8")
        it = mimg.ImageIter(
            batch_size=4, data_shape=(3, 32, 32), path_imgrec=rec,
            aug_list=[mimg.CenterCropAug((32, 32)),
                      mimg.ColorNormalizeAug([1, 2, 3], [4, 5, 6])],
            dtype="uint8", worker_mode="serial")
        with pytest.raises(MXNetError, match="dtype"):
            it.next()
        it.close()

    def test_factory_uint8_pipeline_stays_uint8(self, tmp_path):
        """ImageRecordIter(dtype='uint8') without normalization emits a
        cast-free uint8 batch (CreateAugmenter is dtype-aware)."""
        rec = _write_rec(tmp_path / "u8.rec", n=8, size=32)
        it = mxio.ImageRecordIter(path_imgrec=rec, data_shape=(3, 32, 32),
                                  batch_size=4, rand_mirror=True,
                                  dtype="uint8", worker_mode="serial")
        b = it.next()
        assert b.data[0].asnumpy().dtype == np.uint8
        it.close()

    def test_worker_timeout_blocks_swept_on_close(self, tmp_path):
        """A chunk that exceeds worker_timeout errors out cleanly and its
        orphaned shm block (descriptor never arrived) is swept by
        close() via the parent-assigned name prefix."""
        rec = _write_rec(tmp_path / "slow.rec", n=4)
        it = mimg.ImageIter(batch_size=4, data_shape=(3, 32, 32),
                            path_imgrec=rec, aug_list=[_SlowAug()],
                            worker_mode="process", preprocess_threads=2,
                            worker_timeout=0.5)
        with pytest.raises(MXNetError, match="decode worker"):
            it.next()
        time.sleep(0.3)  # let a worker reach its _alloc_shm
        it.close()
        assert not glob.glob(f"/dev/shm/{it._shm_prefix}*")


# ---------------------------------------------------------------------------
# PrefetchingIter lifecycle (regression: post-exhaustion deadlock)
# ---------------------------------------------------------------------------

class _CloseRecordingIter(mxio.NDArrayIter):
    closed = False

    def close(self):
        self.closed = True


class TestPrefetchingIter:
    def _iter(self, n=32, batch=8, cls=mxio.NDArrayIter):
        data = np.arange(n * 4, dtype="float32").reshape(n, 4)
        label = np.arange(n, dtype="float32")
        return cls(data, label, batch_size=batch)

    def test_post_exhaustion_raises_immediately(self):
        pf = mxio.PrefetchingIter(self._iter())
        assert len(_drain(pf)) == 4
        # regression: this next() used to block forever on the dead
        # worker's empty queue
        t0 = time.perf_counter()
        with pytest.raises(StopIteration):
            pf.next()
        with pytest.raises(StopIteration):
            next(pf)
        assert time.perf_counter() - t0 < 2.0
        pf.close()

    def test_reset_after_exhaustion_restarts(self):
        pf = mxio.PrefetchingIter(self._iter())
        _drain(pf)
        pf.reset()
        assert len(_drain(pf)) == 4
        pf.close()

    def test_close_joins_worker_and_inner(self):
        inner = self._iter(cls=_CloseRecordingIter)
        pf = mxio.PrefetchingIter(inner)
        pf.next()
        thread = pf._thread
        pf.close()
        assert thread is None or not thread.is_alive()
        assert pf._thread is None
        assert inner.closed
        pf.close()  # idempotent

    def test_no_worker_thread_leak_per_epoch(self):
        """Daemon prefetch threads must not accumulate across epochs."""
        pf = mxio.PrefetchingIter(self._iter())
        for _ in range(5):
            _drain(pf)
            pf.reset()
        alive = [t for t in threading.enumerate()
                 if t.name == "mxnet-prefetch" and t.is_alive()]
        assert len(alive) == 1  # exactly the current epoch's worker
        pf.close()
        time.sleep(0.1)
        alive = [t for t in threading.enumerate()
                 if t.name == "mxnet-prefetch" and t.is_alive()]
        assert not alive

    def test_inner_error_surfaces_not_hangs(self):
        class Boom(mxio.NDArrayIter):
            def next(self):
                raise ValueError("decode exploded")

        pf = mxio.PrefetchingIter(
            Boom(np.zeros((8, 2), "float32"), batch_size=4))
        with pytest.raises(MXNetError, match="worker thread died"):
            _drain(pf)
        pf.close()

    def test_next_after_close_raises_not_hangs(self):
        """Regression (review): next() on a closed iterator must error,
        not block forever on the joined worker's empty queue."""
        pf = mxio.PrefetchingIter(self._iter())
        pf.next()
        pf.close()
        t0 = time.perf_counter()
        with pytest.raises(MXNetError, match="closed"):
            pf.next()
        with pytest.raises(MXNetError, match="closed"):
            pf.reset()
        assert time.perf_counter() - t0 < 2.0

    def test_reset_midstream_yields_fresh_epoch(self):
        """Regression (review): an in-flight producer put must not leak
        a stale batch (or None sentinel) into the post-reset queue."""
        data = np.arange(64, dtype="float32").reshape(16, 4)
        it = mxio.NDArrayIter(data, np.arange(16, dtype="float32"),
                              batch_size=4)
        pf = mxio.PrefetchingIter(it, prefetch_depth=1)
        for _ in range(20):
            first = pf.next()  # consume one, queue refills behind it
            pf.reset()
            fresh = pf.next()
            # epoch always restarts at batch 0
            np.testing.assert_array_equal(fresh.data[0].asnumpy(),
                                          data[:4])
            del first
        pf.close()


# ---------------------------------------------------------------------------
# DeviceFeedIter
# ---------------------------------------------------------------------------

def _mlp_step(donate_inputs=False):
    from mxnet_tpu import parallel as par
    from mxnet_tpu.gluon import nn, loss as gloss

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8, activation="relu"), nn.Dense(4))
    net.initialize()
    mesh = par.make_mesh({"dp": 8})
    return par.TrainStep(net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
                         mesh=mesh, donate_inputs=donate_inputs)


def _nd_iter(n=64, batch=16, dim=6):
    data = np.random.rand(n, dim).astype("float32")
    label = np.random.randint(0, 4, (n,)).astype("float32")
    return mxio.NDArrayIter(data, label, batch_size=batch)


class TestDeviceFeedIter:
    def test_requires_exactly_one_placement_source(self):
        with pytest.raises(MXNetError, match="exactly one"):
            mxio.DeviceFeedIter(_nd_iter())
        step = _mlp_step()
        with pytest.raises(MXNetError, match="exactly one"):
            mxio.DeviceFeedIter(_nd_iter(), step=step, shardings=[None])

    def test_batches_staged_with_step_sharding(self):
        """Tentpole contract: fed batches carry the step's exact input
        sharding (dp-sharded dim 0 on the 8-device mesh) so the step's
        device_put is a no-op, and training runs end to end."""
        step = _mlp_step()
        feed = mxio.DeviceFeedIter(_nd_iter(), step=step, depth=2)
        shs = step.input_shardings(
            mx.nd.array(np.zeros((16, 6), "float32")),
            mx.nd.array(np.zeros((16,), "float32")))
        from jax.sharding import PartitionSpec as P

        assert shs[0].spec == P("dp", None) and shs[1].spec == P("dp")
        n = 0
        for b in feed:
            assert b.data[0].data.sharding == shs[0]
            assert b.label[0].data.sharding == shs[1]
            loss, _ = step(b.data[0], b.label[0])
            n += 1
        assert n == 4
        assert np.isfinite(loss.asnumpy()).all()
        feed.close()

    def test_donated_inputs_with_fresh_batches(self):
        """donate_inputs=True composes with the feed: every step gets a
        fresh staged buffer, so donation never reuses a dead one."""
        step = _mlp_step(donate_inputs=True)
        feed = mxio.DeviceFeedIter(_nd_iter(), step=step)
        losses = [float(step(b.data[0], b.label[0])[0].asnumpy())
                  for b in feed]
        assert len(losses) == 4 and all(np.isfinite(losses))
        feed.close()

    def test_plain_iterable_and_explicit_shardings(self):
        """DataLoader-shaped sources (lists of arrays) keep their form;
        explicit shardings accept anything device_put does."""
        import jax

        from mxnet_tpu import gluon

        ds = gluon.data.ArrayDataset(
            np.arange(32, dtype="float32").reshape(16, 2),
            np.arange(16, dtype="float32"))
        loader = gluon.data.DataLoader(ds, batch_size=4)
        dev = jax.devices()[0]
        feed = mxio.DeviceFeedIter(loader, shardings=[dev, dev])
        batches = list(feed)
        assert len(batches) == 4
        assert isinstance(batches[0], list) and len(batches[0]) == 2
        assert batches[0][0].data.devices() == {dev}
        feed.close()

    def test_device_transform_runs_on_device(self):
        """uint8 wire format + on-device normalize: values match the
        host-side float math."""
        import jax.numpy as jnp

        raw = np.random.randint(0, 256, (32, 3, 4, 4), np.uint8)
        labels = np.arange(32, dtype="float32")
        it = mxio.NDArrayIter(raw, labels, batch_size=8)
        step = _mlp_step()

        def tf(x, y):
            return (x.astype(jnp.float32) - 127.5) / 3.0, y

        feed = mxio.DeviceFeedIter(it, step=step, device_transform=tf)
        b = next(feed)
        got = b.data[0].asnumpy()
        np.testing.assert_allclose(
            got, (raw[:8].astype(np.float32) - 127.5) / 3.0, rtol=1e-6)
        feed.close()

    def test_transform_arity_mismatch_surfaces(self):
        step = _mlp_step()
        feed = mxio.DeviceFeedIter(_nd_iter(), step=step,
                                   device_transform=lambda x, y: x,
                                   name="badtf")
        with pytest.raises(MXNetError, match="badtf"):
            next(feed)
        feed.close()

    def test_reset_exhaustion_close_semantics(self):
        step = _mlp_step()
        feed = mxio.DeviceFeedIter(_nd_iter(), step=step, name="life")
        assert len(list(feed)) == 4
        t0 = time.perf_counter()
        with pytest.raises(StopIteration):
            next(feed)  # immediate, not a queue hang
        assert time.perf_counter() - t0 < 2.0
        feed.reset()
        assert len(list(feed)) == 4
        feed.close()
        assert feed._thread is None
        feed.close()  # idempotent
        with pytest.raises(MXNetError, match="closed"):
            next(feed)
        alive = [t for t in threading.enumerate()
                 if t.name == "mxnet-life" and t.is_alive()]
        assert not alive

    def test_close_chains_to_source(self, tmp_path):
        import jax

        rec = _write_rec(tmp_path / "g.rec", n=8)
        it = _image_iter(rec, "process", 2)
        dev = jax.devices()[0]
        feed = mxio.DeviceFeedIter(
            it, shardings=lambda vals: [dev] * len(vals))
        next(feed)
        feed.close()
        assert it._pool is None  # ImageIter.close ran

    def test_fault_injection_surfaces_as_error(self):
        """fault site datafeed.put: a producer crash is an MXNetError
        naming the stage — never a hang on the empty queue."""
        step = _mlp_step()
        with fault.inject("datafeed.put=once"):
            feed = mxio.DeviceFeedIter(_nd_iter(), step=step,
                                       name="chaos_stage")
            t0 = time.perf_counter()
            with pytest.raises(MXNetError) as ei:
                for _ in feed:
                    pass
            assert time.perf_counter() - t0 < 5.0
            msg = str(ei.value)
            assert "chaos_stage" in msg and "datafeed.put" in msg
            # the error is sticky: the consumer can't silently continue
            with pytest.raises(MXNetError):
                next(feed)
            feed.close()

    def test_data_wait_telemetry_emitted(self, tmp_path, monkeypatch):
        """mxnet_data_wait_seconds{stage} + queue depth + decode counter
        land in the registry and in prom_text()."""
        monkeypatch.setattr(telemetry._state, "enabled", True)
        rec = _write_rec(tmp_path / "h.rec", n=16)
        it = _image_iter(rec, "serial", 1, seed=None)
        step = _mlp_step()

        # ImageIter batches are (3,32,32) images; feed them through
        # explicit shardings (the MLP step's shapes don't matter here)
        import jax

        dev = jax.devices()[0]
        feed = mxio.DeviceFeedIter(it, shardings=lambda vals:
                                   [dev] * len(vals), name="telemetry_t")
        _drain(feed)
        feed.close()
        snap = telemetry.snapshot()["metrics"]
        waits = snap["mxnet_data_wait_seconds"]["samples"]
        assert any(s["labels"]["stage"] == "telemetry_t" and s["count"] > 0
                   for s in waits)
        assert "mxnet_data_queue_depth" in snap
        decoded = snap["mxnet_data_decoded_images_total"]["samples"]
        assert decoded and decoded[0]["value"] >= 16
        text = telemetry.prom_text()
        assert 'mxnet_data_wait_seconds_count{stage="telemetry_t"}' in text
        telemetry.reset()


# ---------------------------------------------------------------------------
# DataLoader pin_memory routing
# ---------------------------------------------------------------------------

class TestPinMemory:
    def test_pin_memory_stages_on_device(self):
        import jax

        from mxnet_tpu import gluon
        from mxnet_tpu.context import cpu_pinned

        ds = gluon.data.ArrayDataset(
            np.arange(24, dtype="float32").reshape(12, 2),
            np.arange(12, dtype="float32"))
        loader = gluon.data.DataLoader(ds, batch_size=4, pin_memory=True)
        batch = next(iter(loader))
        assert batch[0].context == cpu_pinned()
        assert batch[0].data.devices() == {jax.devices()[0]}
        np.testing.assert_allclose(batch[0].asnumpy(),
                                   np.arange(8, dtype="float32")
                                   .reshape(4, 2))

    def test_pin_memory_with_workers(self):
        from mxnet_tpu import gluon

        ds = gluon.data.ArrayDataset(
            np.arange(24, dtype="float32").reshape(12, 2),
            np.arange(12, dtype="float32"))
        want = [b[0].asnumpy()
                for b in gluon.data.DataLoader(ds, batch_size=4)]
        loader = gluon.data.DataLoader(ds, batch_size=4, pin_memory=True,
                                       num_workers=2)
        got = [b[0].asnumpy() for b in loader]
        for g, w in zip(got, want):
            np.testing.assert_allclose(g, w)
