"""Post-training int8 quantization tests (reference:
tests/python/quantization/test_quantization.py — calibration modes,
quantize_model, quantized op numerics).

Oracle = the fp32 net: int8 inference must track it closely on
in-distribution data; weights must actually be stored int8.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.contrib import quantization as qz
from mxnet_tpu.gluon import nn


def _rel_err(a, b):
    return onp.abs(a - b).max() / max(onp.abs(b).max(), 1e-9)


def _mlp():
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(8))
    net.initialize()
    return net


class TestWeightQuant:
    def test_roundtrip_error_small(self):
        w = onp.random.RandomState(0).randn(16, 32).astype("float32")
        wq, scale = qz.quantize_weight(w)
        assert wq.dtype == onp.int8 and scale.shape == (16,)
        back = wq.astype("float32") * scale[:, None]
        assert _rel_err(back, w) < 1e-2

    def test_kl_threshold_gaussian(self):
        rs = onp.random.RandomState(1)
        x = onp.abs(rs.randn(200_000)) * 0.5
        x[:10] = 8.0          # outliers the KL sweep should clip away
        hist, edges = onp.histogram(x, bins=2048)
        t = qz.optimal_threshold_kl(hist, edges[1:])
        assert 1.0 < t < 8.0   # tighter than max, looser than the bulk


class TestQuantizeNet:
    @pytest.mark.parametrize("calib_mode", ["naive", "entropy", "none"])
    def test_mlp_close_to_fp32(self, calib_mode):
        onp.random.seed(2)
        mx.random.seed(2)
        net = _mlp()
        rs = onp.random.RandomState(3)
        x = mx.nd.array(rs.randn(16, 20).astype("float32"))
        want = net(x).asnumpy()
        calib = None if calib_mode == "none" else x
        qz.quantize_net(net, calib_data=calib, calib_mode=calib_mode)
        got = net(x).asnumpy()
        assert _rel_err(got, want) < 0.05, _rel_err(got, want)
        qparams = [p for p in net.collect_params().values()
                   if str(p.dtype) == "int8"]
        assert len(qparams) == 2       # both Dense weights stored int8

    def test_convnet_and_exclude(self):
        onp.random.seed(4)
        mx.random.seed(4)
        net = nn.HybridSequential()
        net.add(nn.Conv2D(8, kernel_size=3, padding=1),
                nn.Activation("relu"), nn.Flatten(), nn.Dense(4))
        net.initialize()
        rs = onp.random.RandomState(5)
        x = mx.nd.array(rs.randn(4, 3, 8, 8).astype("float32"))
        want = net(x).asnumpy()
        dense_name = [c.name for c in net._children.values()
                      if isinstance(c, nn.Dense)][0]
        qz.quantize_net(net, calib_data=x, calib_mode="naive",
                        exclude_layers=[dense_name])
        got = net(x).asnumpy()
        assert _rel_err(got, want) < 0.05
        qparams = [p for p in net.collect_params().values()
                   if str(p.dtype) == "int8"]
        assert len(qparams) == 1       # conv quantized, dense excluded

    def test_hybridized_after_quantize(self):
        onp.random.seed(6)
        mx.random.seed(6)
        net = _mlp()
        x = mx.nd.array(onp.random.RandomState(7).randn(8, 10)
                        .astype("float32"))
        net.hybridize()
        net(x)
        qz.quantize_net(net, calib_data=x, calib_mode="naive")
        # calibration must have run eagerly (hooks bypass CachedOp):
        # every quantized layer carries a real calibrated range
        qlayers = [c for c in net._children.values()
                   if hasattr(c, "_range")]
        assert qlayers and all(c._range is not None for c in qlayers)
        eager = net(x).asnumpy()
        net.hybridize()
        jit = net(x).asnumpy()
        onp.testing.assert_allclose(jit, eager, rtol=1e-5, atol=1e-5)

    def test_errors(self):
        net = _mlp()
        with pytest.raises(MXNetError, match="calib_data"):
            qz.quantize_net(net, calib_mode="naive")
        with pytest.raises(MXNetError, match="calib_mode"):
            qz.quantize_net(_mlp(), calib_data=mx.nd.ones((2, 4)),
                            calib_mode="bogus")


class TestQuantizeModel:
    def test_symbol_path(self, tmp_path):
        onp.random.seed(8)
        mx.random.seed(8)
        net = _mlp()
        x = mx.nd.array(onp.random.RandomState(9).randn(8, 12)
                        .astype("float32"))
        want = net(x).asnumpy()
        net.hybridize()
        net(x)
        prefix = str(tmp_path / "mlp")
        net.export(prefix)
        sym = mx.sym.load(prefix + "-symbol.json")
        saved = mx.nd.load(prefix + "-0000.params")
        arg_params = {k.split(":", 1)[1]: v for k, v in saved.items()
                      if k.startswith("arg:")}
        aux_params = {k.split(":", 1)[1]: v for k, v in saved.items()
                      if k.startswith("aux:")}

        qsym, qarg, qaux = qz.quantize_model(
            sym, arg_params, aux_params, calib_mode="naive", calib_data=x)
        assert any(k.endswith("_quant") for k in qarg)
        assert not any(k.endswith("weight") and qarg[k].dtype == "float32"
                       for k in qarg if "_scale" not in k and
                       "_quant" not in k and "dense" in k and
                       k.endswith("weight"))
        from mxnet_tpu.symbol.executor import eval_symbol

        feed = dict(qarg)
        feed.update(qaux)
        feed["data"] = x
        got = eval_symbol(qsym, feed).asnumpy()
        assert _rel_err(got, want) < 0.05

        # the rewritten graph still serializes/loads
        qsym2 = mx.sym.load_json(qsym.tojson())
        got2 = eval_symbol(qsym2, feed).asnumpy()
        onp.testing.assert_allclose(got2, got, rtol=1e-6, atol=1e-6)


class TestInt8MXUPath:
    """Round 3 (VERDICT #7): on TPU the quantized ops run REAL s8xs8->s32
    GEMMs. The path itself is platform-independent XLA — forced on here
    via the execution-platform override — and must agree with the
    fake-quant f32 oracle at the shared tolerances."""

    def _force_tpu(self):
        from mxnet_tpu.base import execution_platform

        return execution_platform("tpu")

    def test_dense_matches_oracle_and_emits_s8_dot(self):
        import jax
        import jax.numpy as jnp

        from mxnet_tpu import nd

        rs = onp.random.RandomState(0)
        x = mx.nd.array(rs.randn(8, 32).astype(onp.float32))
        wq = mx.nd.array(rs.randint(-127, 128, (16, 32)).astype(onp.int8))
        ws = mx.nd.array((rs.rand(16).astype(onp.float32) + 0.5) / 100)
        b = mx.nd.array(rs.randn(16).astype(onp.float32))

        oracle = nd.contrib.quantized_dense(
            x, wq, ws, b, num_hidden=16, min_calib_range=-3.0,
            max_calib_range=3.0)
        with self._force_tpu():
            got = nd.contrib.quantized_dense(
                x, wq, ws, b, num_hidden=16, min_calib_range=-3.0,
                max_calib_range=3.0)
        onp.testing.assert_allclose(got.asnumpy(), oracle.asnumpy(),
                                    rtol=1e-5, atol=1e-5)
        # the s8 executable must actually be a DIFFERENT trace than the
        # oracle's: the per-op cache is platform-keyed (round-3 review
        # finding — an unkeyed cache served the oracle under the
        # override). Assert the cache keying DIRECTLY: same op+attrs,
        # different platform -> distinct executables. (Bit-inequality of
        # the outputs is not asserted — the grid-snapped arithmetic can
        # legitimately agree bit-for-bit; round-3 advisor finding.)
        from mxnet_tpu.ops import registry as _registry

        attr_items = tuple(sorted({
            "num_hidden": 16, "min_calib_range": -3.0,
            "max_calib_range": 3.0}.items()))
        f_cpu = _registry._cached_call("_contrib_quantized_dense",
                                       attr_items, 4, False, "cpu")
        f_tpu = _registry._cached_call("_contrib_quantized_dense",
                                       attr_items, 4, False, "tpu")
        assert f_cpu is not f_tpu, \
            "per-op executable cache is not platform-keyed"

        # the compiled path must contain an s8 x s8 -> s32 dot
        from mxnet_tpu.ops.contrib import quantized_dense as qd_fn

        with self._force_tpu():
            jaxpr = jax.make_jaxpr(
                lambda a, w, s, bb: qd_fn(a, w, s, bb, num_hidden=16,
                                          min_calib_range=-3.0,
                                          max_calib_range=3.0))(
                x.data, wq.data, ws.data, b.data)
        dots = [e for e in jaxpr.jaxpr.eqns
                if e.primitive.name == "dot_general"]
        assert dots, jaxpr
        assert all(str(iv.aval.dtype) == "int8" for e in dots
                   for iv in e.invars), jaxpr
        assert all(str(ov.aval.dtype) == "int32" for e in dots
                   for ov in e.outvars), jaxpr

    def test_conv_matches_oracle(self):
        from mxnet_tpu import nd

        rs = onp.random.RandomState(1)
        x = mx.nd.array(rs.randn(2, 4, 8, 8).astype(onp.float32))
        wq = mx.nd.array(rs.randint(-127, 128, (6, 4, 3, 3)).astype(onp.int8))
        ws = mx.nd.array((rs.rand(6).astype(onp.float32) + 0.5) / 100)

        oracle = nd.contrib.quantized_conv(
            x, wq, ws, kernel=(3, 3), num_filter=6, pad=(1, 1),
            no_bias=True, min_calib_range=-3.0, max_calib_range=3.0)
        with self._force_tpu():
            got = nd.contrib.quantized_conv(
                x, wq, ws, kernel=(3, 3), num_filter=6, pad=(1, 1),
                no_bias=True, min_calib_range=-3.0, max_calib_range=3.0)
        onp.testing.assert_allclose(got.asnumpy(), oracle.asnumpy(),
                                    rtol=1e-4, atol=1e-4)


class TestInt8EndToEnd:
    """Round-5 quantized-op tail (VERDICT r4 #5): pooling/concat/flatten
    consume and produce int8 CODES, and the conv->pool->concat->flatten->
    dense trunk carries no f32 tensor between layers."""

    def test_quantized_pooling_matches_oracle(self):
        import jax.numpy as jnp

        rs = onp.random.RandomState(0)
        x = rs.randn(2, 4, 8, 8).astype("float32")
        t = float(onp.abs(x).max())
        codes = onp.clip(onp.round(x * 127.0 / t), -127, 127).astype("int8")
        out, mn, mxr = mx.nd._contrib_quantized_pooling(
            mx.nd.array(codes, dtype="int8"), mx.nd.array([-t]),
            mx.nd.array([t]), kernel=(2, 2), stride=(2, 2), pool_type="max")
        assert out.dtype == onp.int8
        # max pooling on codes == quantize(max pooling on values)
        want = codes.reshape(2, 4, 4, 2, 4, 2).max(axis=(3, 5))
        onp.testing.assert_array_equal(out.asnumpy(), want)

        avg, _, _ = mx.nd._contrib_quantized_pooling(
            mx.nd.array(codes, dtype="int8"), mx.nd.array([-t]),
            mx.nd.array([t]), kernel=(2, 2), stride=(2, 2), pool_type="avg")
        want_avg = onp.round(
            codes.astype("float32").reshape(2, 4, 4, 2, 4, 2)
            .mean(axis=(3, 5)))
        onp.testing.assert_allclose(avg.asnumpy(), want_avg)

    def test_quantized_concat_requantizes_to_widest(self):
        a = onp.array([[100, -100]], dtype="int8")
        b = onp.array([[50, 25]], dtype="int8")
        # a spans +-1.0, b spans +-4.0 -> output grid is +-4.0
        out, mn, mxr = mx.nd._contrib_quantized_concat(
            mx.nd.array(a, dtype="int8"), mx.nd.array(b, dtype="int8"),
            mx.nd.array([-1.0]), mx.nd.array([1.0]),
            mx.nd.array([-4.0]), mx.nd.array([4.0]), dim=1, num_args=2)
        assert out.dtype == onp.int8
        got = out.asnumpy().astype("float32") * float(mxr.asnumpy()) / 127.0
        want = onp.concatenate(
            [a.astype("float32") * 1.0 / 127.0,
             b.astype("float32") * 4.0 / 127.0], axis=1)
        onp.testing.assert_allclose(got, want, atol=4.0 / 127.0)

    def test_int8_trunk_no_f32_between_layers(self):
        """conv(out int8) -> max pool -> concat -> flatten -> dense: the
        jaxpr's inter-layer tensors are all int8 (no dequantize)."""
        import jax
        import jax.numpy as jnp

        rs = onp.random.RandomState(1)
        x = rs.randn(2, 3, 16, 16).astype("float32")
        w = (rs.randn(8, 3, 3, 3) * 0.2).astype("float32")
        from mxnet_tpu.contrib.quantization import quantize_weight
        wq, ws = quantize_weight(w)
        t_in = float(onp.abs(x).max())
        t_out = 4.0

        from mxnet_tpu.ops.registry import get_op

        conv = get_op("_contrib_quantized_conv").fn
        pool = get_op("_contrib_quantized_pooling").fn
        cat = get_op("_contrib_quantized_concat").fn
        flat = get_op("_contrib_quantized_flatten").fn

        boundaries = []

        def trunk(xv, wqv, wsv):
            c, mn, mxr = conv(
                xv, wqv, wsv, None, kernel=(3, 3), num_filter=8,
                stride=(1, 1), pad=(1, 1), no_bias=True,
                min_calib_range=-t_in, max_calib_range=t_in,
                out_type="int8", out_min_calib=-t_out,
                out_max_calib=t_out)
            p, mn, mxr = pool(c, mn, mxr, kernel=(2, 2), stride=(2, 2),
                              pool_type="max")
            cc, mn, mxr = cat(p, p, mn, mxr, mn, mxr, dim=1, num_args=2)
            f, mn, mxr = flat(cc, mn, mxr)
            boundaries.extend([c.dtype, p.dtype, cc.dtype, f.dtype])
            return f, mn, mxr

        f, mn, mxr = jax.jit(trunk)(jnp.asarray(x), jnp.asarray(wq),
                                    jnp.asarray(ws))
        # every inter-layer tensor is int8 codes — the f32 scale math
        # lives only inside the producing op's (fused) epilogue
        assert all(d == jnp.int8 for d in boundaries), boundaries
        assert f.dtype == jnp.int8
        # f32 oracle parity: dequantized trunk output tracks the float
        # pipeline within two grid steps
        import jax.numpy as jnp2
        ref_conv = jax.lax.conv_general_dilated(
            jnp.asarray(x), jnp.asarray(w), (1, 1), [(1, 1), (1, 1)])
        ref_pool = jax.lax.reduce_window(
            ref_conv, -jnp.inf, jax.lax.max, (1, 1, 2, 2), (1, 1, 2, 2),
            "VALID")
        ref = jnp.concatenate([ref_pool, ref_pool], axis=1).reshape(2, -1)
        got = f.astype(jnp.float32) * mxr / 127.0
        onp.testing.assert_allclose(
            onp.asarray(got), onp.clip(onp.asarray(ref), -t_out, t_out),
            atol=3 * t_out / 127.0)

    def test_requantize_s32_to_s8(self):
        import jax.numpy as jnp

        acc = onp.array([2147483647, -2147483647, 1073741824, 0],
                        dtype="int32")
        out, mn, mxr = mx.nd._contrib_requantize(
            mx.nd.array(acc, dtype="int32"), mx.nd.array([-8.0]),
            mx.nd.array([8.0]))
        assert out.dtype == onp.int8
        onp.testing.assert_array_equal(out.asnumpy(), [127, -127, 64, 0])


class TestInt8Trunk:
    """quantize_net(int8_trunk=True): HybridSequential conv/relu/pool/
    flatten runs fuse into Int8Run blocks passing int8 CODES between
    layers (round 5, VERDICT r4 #5 user-level completion)."""

    def _net(self):
        from mxnet_tpu.gluon import nn

        mx.random.seed(4)
        net = nn.HybridSequential()
        net.add(nn.Conv2D(8, 3, padding=1), nn.Activation("relu"),
                nn.MaxPool2D(),
                nn.Conv2D(16, 3, padding=1), nn.Activation("relu"),
                nn.Flatten(), nn.Dense(10))
        net.initialize()
        return net

    def test_trunk_fuses_and_tracks_fp32(self):
        net = self._net()
        rs = onp.random.RandomState(0)
        x = mx.nd.array(rs.randn(4, 3, 16, 16).astype("float32"))
        want = net(x).asnumpy()
        qz.quantize_net(net, calib_data=x, calib_mode="naive",
                        int8_trunk=True)
        names = [type(c).__name__ for c in net._children.values()]
        assert names == ["Int8Run", "QuantizedDense"], names
        run = next(iter(net._children.values()))
        kinds = [k for k, _ in run._steps]
        # two convs chained through relu/pool, one dequant at the tail
        assert kinds.count("conv") == 2 and kinds[-1] == "dequant", kinds
        got = net(x).asnumpy()
        rel = abs(got - want).max() / (abs(want).max() + 1e-9)
        assert rel < 0.15, rel
        # hybridized path identical
        net.hybridize()
        onp.testing.assert_allclose(net(x).asnumpy(), got,
                                    rtol=1e-4, atol=1e-4)

    def test_trunk_requires_calibration(self):
        net = self._net()
        with pytest.raises(MXNetError, match="int8_trunk"):
            qz.quantize_net(net, calib_mode="none", int8_trunk=True)

    def test_codes_flow_between_layers(self):
        """The run's inner boundary really is int8: probe the fused ops
        eagerly with the same grids the fusion pass assigned."""
        net = self._net()
        rs = onp.random.RandomState(1)
        x = mx.nd.array(rs.randn(2, 3, 16, 16).astype("float32"))
        qz.quantize_net(net, calib_data=x, calib_mode="naive",
                        int8_trunk=True)
        run = next(iter(net._children.values()))
        conv1 = run._steps[0][1]
        assert conv1._out_grid is not None       # emits codes
        convs = [p for k, p in run._steps if k == "conv"]
        assert convs[1]._in_codes is not None    # consumes codes

    def test_trunk_tail_conv_emits_f32(self):
        """conv->relu->conv (no pool): the tail conv consumes codes but
        emits f32 — no tuple-unpack crash (round-5 review repro)."""
        mx.random.seed(9)
        net = nn.HybridSequential()
        net.add(nn.Conv2D(8, 3, padding=1), nn.Activation("relu"),
                nn.Conv2D(8, 3, padding=1))
        net.initialize()
        rs = onp.random.RandomState(0)
        x = mx.nd.array(rs.randn(2, 3, 8, 8).astype("float32"))
        want = net(x).asnumpy()
        qz.quantize_net(net, calib_data=x, calib_mode="naive",
                        int8_trunk=True)
        got = net(x).asnumpy()
        assert _rel_err(got, want) < 0.1
        run = next(iter(net._children.values()))
        assert [k for k, _ in run._steps][-1] == "conv_f32"

    def test_trunk_grid_uses_output_range(self):
        """Conv outputs far beyond the input range (20x weights): the
        requantize grid comes from the calibrated OUTPUT range, so the
        trunk tracks fp32 instead of clipping (round-5 review repro:
        0.67 rel err before the fix)."""
        mx.random.seed(11)
        net = self._net()
        rs = onp.random.RandomState(1)
        x = mx.nd.array(rs.randn(4, 3, 16, 16).astype("float32"))
        net(x)
        for p in net.collect_params().values():
            if p.name.endswith("weight") and "conv" in p.name:
                p.set_data(p.data() * 20)
        want = net(x).asnumpy()
        qz.quantize_net(net, calib_data=x, calib_mode="naive",
                        int8_trunk=True)
        got = net(x).asnumpy()
        assert _rel_err(got, want) < 0.1, _rel_err(got, want)


class TestQuantizedElemwiseAdd:
    def test_matches_dequantized_sum(self):
        rs = onp.random.RandomState(0)
        a = rs.randn(4, 8).astype("float32")
        b = (rs.randn(4, 8) * 3).astype("float32")
        ta, tb = float(onp.abs(a).max()), float(onp.abs(b).max())
        ca = onp.clip(onp.round(a * 127 / ta), -127, 127).astype("int8")
        cb = onp.clip(onp.round(b * 127 / tb), -127, 127).astype("int8")
        out, mn, mxr = mx.nd._contrib_quantized_elemwise_add(
            mx.nd.array(ca, dtype="int8"), mx.nd.array(cb, dtype="int8"),
            mx.nd.array([-ta]), mx.nd.array([ta]),
            mx.nd.array([-tb]), mx.nd.array([tb]))
        assert out.dtype == onp.int8
        t = float(mxr.asnumpy())
        got = out.asnumpy().astype("float32") * t / 127.0
        onp.testing.assert_allclose(got, a + b, atol=3 * t / 127.0)

    def test_calibrated_output_grid(self):
        ca = onp.array([[127, -127]], dtype="int8")
        cb = onp.array([[127, 127]], dtype="int8")
        out, mn, mxr = mx.nd._contrib_quantized_elemwise_add(
            mx.nd.array(ca, dtype="int8"), mx.nd.array(cb, dtype="int8"),
            mx.nd.array([-1.0]), mx.nd.array([1.0]),
            mx.nd.array([-1.0]), mx.nd.array([1.0]),
            min_calib_range=-2.0, max_calib_range=2.0)
        got = out.asnumpy().astype("float32") * 2.0 / 127.0
        onp.testing.assert_allclose(got, [[2.0, 0.0]], atol=2 / 127.0)
