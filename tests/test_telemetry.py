"""Runtime telemetry subsystem tests (mxnet_tpu/telemetry.py).

Covers: counter/gauge/histogram semantics, enable/disable toggling (env
var and API), thread safety under concurrent increments, the three
exporters (JSON, Prometheus text — validated by a minimal line-format
checker, chrome-trace counter events merged into profiler.dumps), the
instrumented layers (op dispatch, engine, kvstore, jit caches), the
TrainingTelemetry step hook, and that disabled-mode dispatch records
nothing and allocates nothing in telemetry.py.
"""
import json
import os
import re
import subprocess
import sys
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, engine, profiler, telemetry

pytestmark = pytest.mark.telemetry


@pytest.fixture
def tel():
    """Fresh, enabled telemetry for one test; always disabled + cleared
    after (the conftest leak guard fails tests that forget this)."""
    telemetry.reset()
    telemetry.enable()
    yield telemetry
    telemetry.disable()
    telemetry.reset()


def _metric(name):
    return json.loads(telemetry.dumps())["metrics"].get(name)


def _samples(name):
    fam = _metric(name)
    return fam["samples"] if fam else []


def _value(name, **labels):
    for s in _samples(name):
        if all(s["labels"].get(k) == v for k, v in labels.items()):
            return s.get("value", s.get("count"))
    return None


# ---------------------------------------------------------------------------
# primitive semantics
# ---------------------------------------------------------------------------

class TestPrimitives:
    def test_counter(self, tel):
        c = telemetry.counter("t_counter", "help text", ("k",))
        c.labels("a").inc()
        c.labels("a").inc(2.5)
        c.labels("b").inc()
        assert _value("t_counter", k="a") == 3.5
        assert _value("t_counter", k="b") == 1.0
        with pytest.raises(ValueError, match="only go up"):
            c.labels("a").inc(-1)

    def test_gauge(self, tel):
        g = telemetry.gauge("t_gauge")
        g.set(5)
        g.inc(2)
        g.dec(3)
        assert _value("t_gauge") == 4.0

    def test_histogram_buckets_cumulative(self, tel):
        h = telemetry.histogram("t_hist", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        s = _samples("t_hist")[0]
        assert s["count"] == 5
        assert s["sum"] == pytest.approx(56.05)
        # bucket counts are cumulative and end at +Inf == count
        assert s["buckets"]["0.1"] == 1
        assert s["buckets"]["1"] == 3
        assert s["buckets"]["10"] == 4
        assert s["buckets"]["+Inf"] == 5

    def test_reregistration_same_family(self, tel):
        a = telemetry.counter("t_same", labelnames=("x",))
        b = telemetry.counter("t_same", labelnames=("x",))
        assert a is b
        with pytest.raises(ValueError, match="already registered"):
            telemetry.gauge("t_same")
        with pytest.raises(ValueError, match="already registered"):
            telemetry.counter("t_same", labelnames=("y",))

    def test_label_arity_checked(self, tel):
        c = telemetry.counter("t_arity", labelnames=("a", "b"))
        with pytest.raises(ValueError, match="expected labels"):
            c.labels("only-one")

    def test_child_cap_degrades_to_overflow(self, tel, monkeypatch):
        monkeypatch.setattr(telemetry, "_MAX_CHILDREN", 3)
        c = telemetry.counter("t_cap", labelnames=("k",))
        for i in range(10):
            c.labels(f"v{i}").inc()
        fam = _metric("t_cap")
        # 3 real children + one overflow catch-all, never 10
        assert len(fam["samples"]) == 4
        assert _value("t_cap", k=telemetry._OVERFLOW_LABEL) == 7.0

    def test_thread_safety(self, tel):
        c = telemetry.counter("t_mt").labels()
        h = telemetry.histogram("t_mt_h", buckets=(0.5,)).labels()
        n_threads, per_thread = 8, 2000

        def worker():
            for _ in range(per_thread):
                c.inc()
                h.observe(0.25)

        threads = [threading.Thread(target=worker) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert _value("t_mt") == n_threads * per_thread
        s = _samples("t_mt_h")[0]
        assert s["count"] == n_threads * per_thread
        assert s["buckets"]["0.5"] == n_threads * per_thread


# ---------------------------------------------------------------------------
# enable/disable
# ---------------------------------------------------------------------------

class TestToggle:
    def test_api_toggle(self):
        assert not telemetry.enabled()
        telemetry.enable()
        try:
            assert telemetry.enabled()
        finally:
            telemetry.disable()
        assert not telemetry.enabled()

    def test_env_var_enables_at_import(self):
        env = dict(os.environ, MXNET_TELEMETRY="1")
        out = subprocess.run(
            [sys.executable, "-c",
             "from mxnet_tpu import telemetry; print(telemetry.enabled())"],
            capture_output=True, text=True, env=env, timeout=300)
        assert out.returncode == 0, out.stderr
        assert out.stdout.strip() == "True"

    def test_record_helpers_noop_when_disabled(self):
        telemetry.reset()
        assert not telemetry.enabled()
        telemetry.record_op_dispatch("x", 0.001)
        telemetry.record_cache("c", True)
        telemetry.record_kv("push", 10, 0.001)
        telemetry.record_engine_wait(0.001)
        telemetry.set_live_arrays(3)
        telemetry.record_live_evictions(2)
        telemetry.record_training_step(0.1, 8, 50.0)
        assert json.loads(telemetry.dumps())["metrics"] == {}


# ---------------------------------------------------------------------------
# instrumented layers
# ---------------------------------------------------------------------------

class TestDispatchInstrumentation:
    def test_disabled_dispatch_records_and_allocates_nothing(self):
        """Disabled mode: the instrumentation branch runs, but records
        nothing and allocates nothing inside telemetry.py."""
        import tracemalloc

        telemetry.reset()
        assert not telemetry.enabled()
        x = mx.nd.ones((4, 4))
        (x * 2).asnumpy()  # warm the executable cache outside the window
        tracemalloc.start()
        try:
            for _ in range(20):
                x = x * 2 + 1
            x.asnumpy()
            snap = tracemalloc.take_snapshot()
        finally:
            tracemalloc.stop()
        assert json.loads(telemetry.dumps())["metrics"] == {}
        tel_allocs = snap.filter_traces(
            [tracemalloc.Filter(True, telemetry.__file__)]).statistics("lineno")
        assert not tel_allocs, tel_allocs

    def test_eager_dispatch_counts_and_latency(self, tel):
        x = mx.nd.ones((4, 4))
        for _ in range(3):
            x = x + 1
        x.asnumpy()
        ops = {s["labels"]["op"]: s["value"]
               for s in _samples("mxnet_op_dispatch_total")}
        assert ops and sum(ops.values()) >= 3
        hist = _samples("mxnet_op_dispatch_seconds")
        assert sum(s["count"] for s in hist) >= 3

    def test_recording_path_counts_ops(self, tel):
        x = mx.nd.ones((2, 3))
        x.attach_grad()
        with autograd.record():
            y = (x * 2).sum()
        y.backward()
        ops = {s["labels"]["op"] for s in _samples("mxnet_op_dispatch_total")}
        assert ops, "recording-path dispatch not counted"

    def test_eager_op_cache_hit_miss(self, tel):
        x = mx.nd.ones((5, 5))
        (x * 3).asnumpy()
        (x * 3).asnumpy()  # same op+attrs+platform -> lru hit
        hits = _value("mxnet_jit_cache_total", cache="eager_op", result="hit")
        assert hits and hits >= 1


class TestEngineInstrumentation:
    def test_wait_for_all_and_live_gauge(self, tel):
        import jax.numpy as jnp

        engine.track(jnp.ones((8,)))
        engine.wait_for_all()
        assert _samples("mxnet_engine_wait_all_seconds")[0]["count"] >= 1
        assert _value("mxnet_engine_live_arrays") == 0.0

    def test_overflow_evicts_dead_first_and_counts_live_evictions(
            self, tel, monkeypatch):
        import jax.numpy as jnp

        monkeypatch.setattr(engine, "_MAX_LIVE", 4)
        monkeypatch.setattr(engine, "_live_arrays", [])
        live = [jnp.full((2,), i) for i in range(5)]
        for a in live:
            engine.track(a)
        # all 5 refs live: compaction finds no dead entries and must evict
        # live ones — counted, not silent
        assert _value("mxnet_engine_live_evictions_total") == 2.0
        # dead refs are preferred: drop our strong refs, track more — the
        # collected entries compact away without touching the live counter
        evictions_before = _value("mxnet_engine_live_evictions_total")
        del live
        import gc

        gc.collect()
        fresh = [jnp.full((2,), i) for i in range(3)]
        for a in fresh:
            engine.track(a)
        assert _value("mxnet_engine_live_evictions_total") == evictions_before


class TestKVStoreInstrumentation:
    def test_local_push_pull_bytes(self, tel):
        kv = mx.kv.create("local")
        v = mx.nd.ones((16, 4))  # float32: 256 bytes
        kv.init(7, v)
        kv.push(7, v)
        out = mx.nd.zeros((16, 4))
        kv.pull(7, out)
        assert _value("mxnet_kvstore_calls_total", op="push") == 1.0
        assert _value("mxnet_kvstore_calls_total", op="pull") == 1.0
        assert _value("mxnet_kvstore_bytes_total", op="push") == 256.0
        assert _value("mxnet_kvstore_bytes_total", op="pull") == 256.0
        lat = {s["labels"]["op"]: s["count"]
               for s in _samples("mxnet_kvstore_seconds")}
        assert lat.get("push") == 1 and lat.get("pull") == 1

    def test_tpu_sync_allreduce_counted(self, tel):
        import jax

        if len(jax.local_devices(backend="cpu")) < 2:
            pytest.skip("needs the 8-device virtual CPU mesh")
        kv = mx.kv.create("tpu_sync")
        a = mx.nd.ones((8,), ctx=mx.cpu(0))
        b = mx.nd.ones((8,), ctx=mx.cpu(1))
        kv.init("g", a)
        kv.push("g", [a, b])  # copies on distinct devices -> one psum
        assert _value("mxnet_kvstore_calls_total", op="allreduce") == 1.0
        # payload entering the psum: one f32 copy per mesh slot
        assert _value("mxnet_kvstore_bytes_total", op="allreduce") == 64.0


class TestJitCacheInstrumentation:
    def test_cached_op_hit_miss(self, tel):
        from mxnet_tpu.gluon import nn

        net = nn.Dense(3)
        net.initialize()
        net.hybridize()
        x = mx.nd.ones((2, 4))
        net(x).asnumpy()   # miss (build+compile)
        net(x).asnumpy()   # hit
        assert _value("mxnet_jit_cache_total",
                      cache="cached_op", result="miss") == 1.0
        assert _value("mxnet_jit_cache_total",
                      cache="cached_op", result="hit") == 1.0

    def test_executor_cache_hit_miss(self, tel):
        data = mx.sym.var("data")
        net = mx.sym.FullyConnected(data, name="fc", num_hidden=2)
        exe = net.simple_bind(ctx=mx.cpu(), data=(2, 3))
        exe.forward(data=mx.nd.ones((2, 3)))
        exe.forward(data=mx.nd.ones((2, 3)))
        assert _value("mxnet_jit_cache_total",
                      cache="executor", result="miss") == 1.0
        hits = _value("mxnet_jit_cache_total",
                      cache="executor", result="hit")
        assert hits and hits >= 1.0


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

# minimal Prometheus text-format (0.0.4) line checker — no dependency
_PROM_HELP = re.compile(r"^# HELP [a-zA-Z_:][a-zA-Z0-9_:]* .*$")
_PROM_TYPE = re.compile(
    r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) "
    r"(counter|gauge|histogram|summary|untyped)$")
_PROM_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"                      # metric name
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\""   # first label
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\")*\})?"  # more labels
    r" (-?[0-9]+(\.[0-9]+)?([eE][-+]?[0-9]+)?|\+Inf|-Inf|NaN)$")


def check_prom_text(text):
    """Validate exposition format; returns {family: type}."""
    types = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP"):
            assert _PROM_HELP.match(line), line
            continue
        if line.startswith("# TYPE"):
            m = _PROM_TYPE.match(line)
            assert m, line
            types[m.group(1)] = m.group(2)
            continue
        m = _PROM_SAMPLE.match(line)
        assert m, f"bad sample line: {line!r}"
        name = m.group(1)
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        fam = name if name in types else base
        assert fam in types, f"sample before TYPE: {line!r}"
        if types[fam] == "histogram" and name.endswith("_bucket"):
            assert 'le="' in line, f"histogram bucket missing le: {line!r}"
    return types


class TestExporters:
    def _populate(self):
        telemetry.counter("exp_total", "a counter", ("op",)).labels(
            'weird"\\name').inc(2)
        telemetry.gauge("exp_gauge", "a gauge").set(1.5)
        telemetry.histogram("exp_lat", "a histogram", ("op",),
                            buckets=(0.1, 1.0)).labels("x").observe(0.5)

    def test_json_dumps(self, tel):
        self._populate()
        snap = json.loads(telemetry.dumps())
        assert snap["enabled"] is True
        m = snap["metrics"]
        assert m["exp_total"]["type"] == "counter"
        assert m["exp_gauge"]["samples"][0]["value"] == 1.5
        h = m["exp_lat"]["samples"][0]
        assert h["count"] == 1 and h["buckets"]["+Inf"] == 1

    def test_prom_text_valid(self, tel):
        self._populate()
        types = check_prom_text(telemetry.prom_text())
        assert types["exp_total"] == "counter"
        assert types["exp_gauge"] == "gauge"
        assert types["exp_lat"] == "histogram"

    def test_prom_text_of_real_run_valid(self, tel):
        x = mx.nd.ones((4, 4))
        (x + x).asnumpy()
        kv = mx.kv.create("local")
        kv.init(0, x)
        kv.push(0, x)
        types = check_prom_text(telemetry.prom_text())
        assert types.get("mxnet_op_dispatch_total") == "counter"
        assert types.get("mxnet_op_dispatch_seconds") == "histogram"

    def test_chrome_counter_events(self, tel):
        self._populate()
        events = telemetry.chrome_counter_events(ts_us=123.0)
        assert events and all(e["ph"] == "C" for e in events)
        names = {e["name"] for e in events}
        assert {"exp_total", "exp_gauge", "exp_lat"} <= names
        lat = next(e for e in events if e["name"] == "exp_lat")
        assert lat["args"]["x_count"] == 1

    def test_chrome_trace_merged_into_profiler_dumps(self, tel):
        self._populate()
        with profiler.Task("merge-task"):
            pass
        profiler.Marker("merge-marker").mark()
        doc = json.loads(profiler.dumps(format="chrome_trace"))
        names = {e["name"] for e in doc["traceEvents"]}
        assert "Task::merge-task" in names       # profiler span
        assert "merge-marker" in names           # profiler marker
        assert "exp_total" in names              # telemetry counter
        profiler.dumps(reset=True)


# ---------------------------------------------------------------------------
# scrape parser: the /metrics channel must be lossless, or the
# cross-process control plane acts on corrupted signals
# ---------------------------------------------------------------------------

class TestScrapeParser:
    def test_prom_text_round_trips_through_the_parser(self, tel):
        """parse -> emit -> parse is the identity on a real payload —
        including label values holding every escaped character ('"',
        newline, backslash). A scrape channel that mangles one label
        would silently mis-attribute a replica's metrics."""
        telemetry.counter("rt_total", "labels with teeth", ("op",)) \
            .labels('quote " backslash \\ newline \n mix \\"\n').inc(3)
        telemetry.counter("rt_total", "labels with teeth", ("op",)) \
            .labels("plain").inc(1)
        telemetry.gauge("rt_gauge", "a gauge", ("k",)) \
            .labels("\\n is two chars, \n is one").set(-2.5)
        telemetry.histogram("rt_lat", "a histogram", ("op",),
                            buckets=(0.1, 1.0)).labels("x").observe(0.5)
        text = telemetry.prom_text()
        parsed = telemetry.parse_prom_text(text)
        emitted = telemetry.emit_prom_text(parsed)
        assert telemetry.parse_prom_text(emitted) == parsed
        # and the re-emitted text is still valid exposition format
        check_prom_text(emitted)
        # the hairy label survived BOTH trips byte-for-byte
        hairy = 'quote " backslash \\ newline \n mix \\"\n'
        ops = [s["labels"]["op"]
               for s in parsed["rt_total"]["samples"]]
        assert hairy in ops
        assert telemetry.prom_value(parsed, "rt_total",
                                    {"op": hairy}) == 3.0
        assert telemetry.prom_value(
            parsed, "rt_gauge",
            {"k": "\\n is two chars, \n is one"}) == -2.5

    def test_histogram_samples_attributed_to_family(self, tel):
        telemetry.histogram("rt_h", "h", buckets=(0.5,)).observe(0.2)
        parsed = telemetry.parse_prom_text(telemetry.prom_text())
        names = {s["name"] for s in parsed["rt_h"]["samples"]}
        assert {"rt_h_bucket", "rt_h_sum", "rt_h_count"} <= names
        assert "rt_h_bucket" not in parsed     # no orphan family
        assert parsed["rt_h"]["type"] == "histogram"

    def test_prom_value_sums_label_series(self, tel):
        c = telemetry.counter("rt_sum_total", "c", ("reason",))
        c.labels("a").inc(2)
        c.labels("b").inc(3)
        parsed = telemetry.parse_prom_text(telemetry.prom_text())
        assert telemetry.prom_value(parsed, "rt_sum_total") == 5.0
        assert telemetry.prom_value(parsed, "rt_sum_total",
                                    {"reason": "b"}) == 3.0
        assert telemetry.prom_value(parsed, "rt_sum_total",
                                    {"reason": "nope"},
                                    default=-1.0) == -1.0
        assert telemetry.prom_value(parsed, "rt_missing",
                                    default=7.0) == 7.0

    def test_malformed_lines_raise(self, tel):
        for bad in ('rt{op="unterminated 1',
                    'rt{op="v"',
                    "rt notafloat"):
            with pytest.raises(ValueError):
                telemetry.parse_prom_text(bad)

    def test_exporter_serves_metrics_and_healthz(self, tel):
        import urllib.error
        import urllib.request

        telemetry.counter("rt_exp_total", "c").inc(4)
        exp = telemetry.start_exporter(
            healthz_fn=lambda: {"ok": True, "who": "test"})
        try:
            parsed = telemetry.scrape(exp.url)
            assert telemetry.prom_value(parsed, "rt_exp_total") == 4.0
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{exp.port}/healthz",
                    timeout=5) as resp:
                hz = json.loads(resp.read())
            assert hz == {"ok": True, "who": "test"}
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{exp.port}/nope", timeout=5)
        finally:
            exp.stop()


# ---------------------------------------------------------------------------
# training-step observability
# ---------------------------------------------------------------------------

class TestTrainingTelemetry:
    def test_step_scope_records_mfu(self, tel):
        tt = telemetry.TrainingTelemetry(
            batch_size=8, flops_per_step=1e9, peak_flops=1e12)
        with tt.step():
            pass
        assert tt.steps == 1
        assert tt.last_step_seconds > 0
        assert tt.last_examples_per_sec == pytest.approx(
            8 / tt.last_step_seconds)
        # MFU = 100 * flops / (dt * peak)
        assert tt.last_mfu_pct == pytest.approx(
            100.0 * 1e9 / (tt.last_step_seconds * 1e12))
        assert _value("mxnet_training_steps_total") == 1.0
        assert _value("mxnet_training_examples_total") == 8.0
        assert _value("mxnet_training_mfu_pct") == pytest.approx(
            tt.last_mfu_pct)

    def test_flops_per_sample_and_unknown_peak(self, tel):
        tt = telemetry.TrainingTelemetry(batch_size=4, flops_per_sample=2e6)
        assert tt.flops_per_step == 8e6
        with tt.step():
            pass
        # CPU has no known peak -> MFU skipped, throughput still recorded
        if tt.last_mfu_pct is None:
            assert _metric("mxnet_training_mfu_pct") is None
        assert _value("mxnet_training_examples_per_sec") > 0

    def test_batch_end_adapter(self, tel):
        tt = telemetry.TrainingTelemetry(batch_size=2)
        tt.batch_end(None)   # arms the clock
        assert tt.steps == 0
        tt.batch_end(None)
        tt(None)             # __call__ alias
        assert tt.steps == 2
        assert _value("mxnet_training_steps_total") == 2.0

    def test_batch_end_epoch_rollover_rearms(self, tel):
        """nbatch == 0 (first batch of an epoch) re-arms the clock — the
        gap since the last batch of the previous epoch spans validation/
        checkpointing, not a training step."""
        class P:
            def __init__(self, nbatch):
                self.nbatch = nbatch

        tt = telemetry.TrainingTelemetry(batch_size=2)
        tt.batch_end(P(0))   # epoch 0 first batch: arm only
        tt.batch_end(P(1))   # one real step
        assert tt.steps == 1
        tt.batch_end(P(0))   # epoch 1 first batch: eval gap NOT observed
        assert tt.steps == 1
        tt.batch_end(P(1))
        assert tt.steps == 2


# ---------------------------------------------------------------------------
# acceptance: a short Gluon training run
# ---------------------------------------------------------------------------

class TestGluonRunAcceptance:
    def test_training_run_populates_all_surfaces(self, tel):
        from mxnet_tpu.gluon import Trainer, loss as gloss, nn

        net = nn.Dense(4)
        net.initialize()
        trainer = Trainer(net.collect_params(), "sgd",
                          {"learning_rate": 0.1}, kvstore="tpu_sync")
        lfn = gloss.SoftmaxCrossEntropyLoss()
        tt = telemetry.TrainingTelemetry(
            batch_size=8, flops_per_step=1e6, peak_flops=1e12)
        x = mx.nd.ones((8, 3))
        y = mx.nd.zeros((8,))
        for _ in range(2):
            with tt.step():
                with autograd.record():
                    loss = lfn(net(x), y)
                loss.backward()
                trainer.step(8)
        mx.nd.waitall()
        snap = json.loads(telemetry.dumps())["metrics"]
        # per-op dispatch counts
        assert sum(s["value"]
                   for s in snap["mxnet_op_dispatch_total"]["samples"]) > 0
        # kvstore byte counters — the trainer's gradient exchange now
        # goes through the fused bucketed pushpull
        kv_bytes = {s["labels"]["op"]: s["value"]
                    for s in snap["mxnet_kvstore_bytes_total"]["samples"]}
        assert kv_bytes.get("pushpull", 0) > 0
        # one bucketed collective dispatch per step, not one per param
        coll = {s["labels"]["path"]: s["value"] for s in
                snap["mxnet_kvstore_collective_dispatch_total"]["samples"]}
        assert coll.get("bucketed", 0) > 0
        # jit-cache hit/miss
        cache = {(s["labels"]["cache"], s["labels"]["result"])
                 for s in snap["mxnet_jit_cache_total"]["samples"]}
        assert any(c == "eager_op" for c, _ in cache)
        # per-step MFU
        assert snap["mxnet_training_mfu_pct"]["samples"][0]["value"] > 0
        assert snap["mxnet_training_steps_total"]["samples"][0]["value"] == 2
        # and the prom exporter stays valid on the full real payload
        check_prom_text(telemetry.prom_text())


# ---------------------------------------------------------------------------
# tool plumbing: the shared --telemetry-out contract
# ---------------------------------------------------------------------------

class TestTelemetryOutFlag:
    def test_strips_both_forms(self):
        argv, path = telemetry.pop_telemetry_out_flag(
            ["bert", "--telemetry-out", "/tmp/t.json", "40"])
        assert argv == ["bert", "40"] and path == "/tmp/t.json"
        argv, path = telemetry.pop_telemetry_out_flag(
            ["--telemetry-out=/x.json", "resnet"])
        assert argv == ["resnet"] and path == "/x.json"
        argv, path = telemetry.pop_telemetry_out_flag(["bert", "40"])
        assert argv == ["bert", "40"] and path is None

    def test_missing_path_is_an_error(self):
        with pytest.raises(SystemExit, match="requires a PATH"):
            telemetry.pop_telemetry_out_flag(["bert", "--telemetry-out"])
        with pytest.raises(SystemExit, match="requires a PATH"):
            telemetry.pop_telemetry_out_flag(["--telemetry-out="])
        with pytest.raises(SystemExit, match="requires a PATH"):
            # a following option is not a path
            telemetry.pop_telemetry_out_flag(
                ["--telemetry-out", "--some-flag"])

    def test_write_snapshot(self, tel, tmp_path):
        telemetry.counter("snap_total").inc(3)
        out = tmp_path / "snap.json"
        telemetry.write_snapshot(str(out))
        snap = json.loads(out.read_text())
        assert snap["metrics"]["snap_total"]["samples"][0]["value"] == 3.0

    def test_env_out_enables_and_writes_at_exit(self, tmp_path):
        """MXNET_TELEMETRY_OUT=PATH: subprocess records without any CLI
        plumbing and drops a snapshot at interpreter exit (the hook
        bench.py's BERT/Llama stages rely on)."""
        out = tmp_path / "child.json"
        env = dict(os.environ, MXNET_TELEMETRY_OUT=str(out))
        r = subprocess.run(
            [sys.executable, "-c",
             "from mxnet_tpu import telemetry\n"
             "assert telemetry.enabled()\n"
             "telemetry.counter('child_total').inc(2)"],
            capture_output=True, text=True, env=env, timeout=300)
        assert r.returncode == 0, r.stderr
        snap = json.loads(out.read_text())
        assert snap["metrics"]["child_total"]["samples"][0]["value"] == 2.0


# ---------------------------------------------------------------------------
# profiler satellite fixes
# ---------------------------------------------------------------------------

class TestProfilerSatellites:
    def test_markers_in_aggregate_table(self):
        profiler.dumps(reset=True)
        profiler.Marker("tele-marker").mark()
        profiler.Marker("tele-marker").mark(scope="global")
        table = profiler.dumps(reset=True)
        assert "Marker::tele-marker (process)" in table
        assert "Marker::tele-marker (global)" in table

    def test_counters_in_aggregate_table(self):
        profiler.Counter("tele-counter", 7).increment(5)
        table = profiler.dumps(reset=True)
        assert "tele-counter" in table
        assert "12.000" in table

    def test_reset_while_paused_rebases_open_window(self, tmp_path):
        """dumps(reset=True) during an open pause must not leave the
        original pause start behind — resume() would re-account the
        already-reported (and reset) portion."""
        import time as _time

        profiler.set_config(filename=str(tmp_path / "p.json"))
        profiler.dumps(reset=True)
        profiler.start()
        try:
            profiler.pause()
            _time.sleep(0.05)
            assert "excluded paused time" in profiler.dumps(reset=True)
            t_rebase = _time.perf_counter()
            profiler.resume()
        finally:
            profiler.stop()
        table = profiler.dumps(reset=True)
        m = re.search(r"excluded paused time: ([0-9.]+) ms", table)
        if m:  # only the post-rebase sliver may remain, never the 50 ms
            assert float(m.group(1)) <= (
                _time.perf_counter() - t_rebase) * 1e3 + 1.0

    def test_chrome_trace_includes_open_pause_window(self, tmp_path):
        profiler.set_config(filename=str(tmp_path / "p.json"))
        profiler.dumps(format="chrome_trace", reset=True)
        profiler.start()
        try:
            profiler.pause()
            import time as _time

            _time.sleep(0.02)
            doc = json.loads(profiler.dumps(format="chrome_trace"))
            assert doc["otherData"]["excluded_paused_ms"] >= 20.0
            profiler.resume()
        finally:
            profiler.stop()
        profiler.dumps(reset=True)

    def test_pause_resume_excluded_time_in_header(self, tmp_path):
        profiler.set_config(filename=str(tmp_path / "p.json"))
        profiler.dumps(reset=True)
        profiler.start()
        try:
            profiler.pause()
            profiler.resume()
        finally:
            profiler.stop()
        table = profiler.dumps(reset=True)
        assert "excluded paused time" in table
        # reset clears the pause accounting
        assert "excluded paused time" not in profiler.dumps()

    def test_chrome_trace_format_parses(self):
        with profiler.Event("ct-span"):
            pass
        doc = json.loads(profiler.dumps(format="chrome_trace", reset=True))
        spans = [e for e in doc["traceEvents"]
                 if e["name"] == "Event::ct-span"]
        assert spans and spans[0]["ph"] == "X"
        assert spans[0]["args"]["calls"] == 1
        with pytest.raises(ValueError, match="unknown dumps format"):
            profiler.dumps(format="bogus")
