"""model_store + .params byte-compat tests.

Reference strategy: upstream pins weight integrity in
``python/mxnet/gluon/model_zoo/model_store.py`` (sha1 table + cache) and
the ``.params`` wire format in ``src/ndarray/ndarray.cc::NDArray::Save``.
With no network and an empty reference mount, byte compatibility is pinned
by ``tests/fixtures/golden_v2.params`` — a fixture whose bytes were
hand-assembled with ``struct`` from the documented layout (NOT produced by
this framework's writer), which the loader must parse exactly and the
writer must reproduce byte-for-byte for the V2-dense subset.
"""
import hashlib
import os
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon.model_zoo import model_store, vision

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "golden_v2.params")

# what the hand-assembled fixture contains
_GOLDEN = {
    "arg:w": (np.arange(12, dtype=np.float32).reshape(3, 4) / 7.0),
    "arg:b": np.array([-1.5, 0.25, 3.0], dtype=np.float64),
    "aux:s": np.array(42, dtype=np.int32),
    "arg:h": (np.arange(6, dtype=np.float16) * 0.5).reshape(2, 3),
}


def test_golden_fixture_loads_exactly():
    loaded = mx.nd.load(FIXTURE)
    assert set(loaded) == set(_GOLDEN)
    for k, want in _GOLDEN.items():
        got = loaded[k].asnumpy()
        assert got.dtype == want.dtype, k
        assert got.shape == want.shape, k
        np.testing.assert_array_equal(got, want, err_msg=k)


def _v2_entry(a: np.ndarray) -> bytes:
    dt = {np.dtype("float32"): 0, np.dtype("float64"): 1,
          np.dtype("float16"): 2, np.dtype("uint8"): 3,
          np.dtype("int32"): 4}[a.dtype]
    b = struct.pack("<I", 0xF993FAC9) + struct.pack("<i", 0)
    b += struct.pack("<i", a.ndim)
    for d in a.shape:
        b += struct.pack("<i", d)
    b += struct.pack("<ii", 1, 0) + struct.pack("<i", dt)
    return b + a.tobytes(order="C")


def test_writer_bytes_match_hand_assembly(tmp_path):
    """mx.nd.save output must equal independently struct-packed bytes."""
    names = ["arg:w", "aux:s", "arg:h"]  # V2-dense subset of the golden set
    data = {k: mx.nd.array(_GOLDEN[k], dtype=_GOLDEN[k].dtype) for k in names}
    out = str(tmp_path / "w.params")
    mx.nd.save(out, data)

    want = struct.pack("<QQ", 0x112, 0) + struct.pack("<Q", len(names))
    for k in names:
        want += _v2_entry(_GOLDEN[k])
    want += struct.pack("<Q", len(names))
    for k in names:
        want += struct.pack("<Q", len(k.encode())) + k.encode()
    with open(out, "rb") as f:
        got = f.read()
    assert got == want


@pytest.fixture
def clean_registry():
    saved = dict(model_store._model_sha1)
    yield
    model_store._model_sha1.clear()
    model_store._model_sha1.update(saved)


def _publish(net, name, repo_root):
    """Save a net's params into a file:// repo laid out like upstream's."""
    models = repo_root / "gluon" / "models"
    models.mkdir(parents=True, exist_ok=True)
    net(mx.nd.zeros((1, 3, 32, 32)))  # settle deferred shapes
    tmp = models / "tmp.params"
    net.save_parameters(str(tmp))
    sha1 = hashlib.sha1(tmp.read_bytes()).hexdigest()
    model_store.register(name, sha1)
    tmp.rename(models / f"{name}-{sha1[:8]}.params")
    return sha1


def test_pretrained_from_file_repo(tmp_path, monkeypatch, clean_registry):
    src = vision.get_model("mobilenet0.25", classes=10)
    src.initialize()
    _publish(src, "mobilenet0.25", tmp_path / "repo")
    monkeypatch.setenv("MXNET_GLUON_REPO", f"file://{tmp_path / 'repo'}/")

    cache = tmp_path / "cache"
    net = vision.get_model("mobilenet0.25", classes=10, pretrained=True,
                           root=str(cache))
    # compare on the block-relative names save/load_parameters key by
    def _p(net_):
        return {k: v.data().asnumpy()
                for k, v in net_._collect_params_with_prefix().items()}

    want = _p(src)
    got = _p(net)
    assert set(want) == set(got)
    for k in want:
        np.testing.assert_array_equal(want[k], got[k], err_msg=k)

    # cache hit: repo can vanish, the verified cached file still serves
    import shutil
    shutil.rmtree(tmp_path / "repo")
    net2 = vision.get_model("mobilenet0.25", classes=10, pretrained=True,
                            root=str(cache))
    got2 = _p(net2)
    key = sorted(want)[0]
    np.testing.assert_array_equal(got2[key], want[key])


def test_corrupted_cache_refetches(tmp_path, monkeypatch, clean_registry):
    src = vision.get_model("squeezenet1.1", classes=10)
    src.initialize()
    sha1 = _publish(src, "squeezenet1.1", tmp_path / "repo")
    monkeypatch.setenv("MXNET_GLUON_REPO", f"file://{tmp_path / 'repo'}/")
    cache = tmp_path / "cache"
    path = model_store.get_model_file("squeezenet1.1", root=str(cache))
    # corrupt the cached copy; next resolve must detect + refetch
    with open(path, "r+b") as f:
        f.seek(64)
        f.write(b"\xff\xff\xff\xff")
    assert not model_store.check_sha1(path, sha1)
    path2 = model_store.get_model_file("squeezenet1.1", root=str(cache))
    assert path2 == path and model_store.check_sha1(path2, sha1)


def test_unregistered_name_raises(clean_registry):
    with pytest.raises(MXNetError, match="no sha1 registered"):
        model_store.get_model_file("resnet50_v1")


def test_sha1_mismatch_raises(tmp_path, monkeypatch, clean_registry):
    src = vision.get_model("squeezenet1.1", classes=10)
    src.initialize()
    _publish(src, "squeezenet1.1", tmp_path / "repo")
    # poison the registered hash (keep prefix so the repo file name matches)
    real = model_store._model_sha1["squeezenet1.1"]
    model_store.register("squeezenet1.1", real[:8] + "0" * 32)
    monkeypatch.setenv("MXNET_GLUON_REPO", f"file://{tmp_path / 'repo'}/")
    with pytest.raises(MXNetError, match="mismatched sha1"):
        model_store.get_model_file("squeezenet1.1", root=str(tmp_path / "c"))


def test_purge(tmp_path, monkeypatch, clean_registry):
    src = vision.get_model("squeezenet1.1", classes=10)
    src.initialize()
    _publish(src, "squeezenet1.1", tmp_path / "repo")
    monkeypatch.setenv("MXNET_GLUON_REPO", f"file://{tmp_path / 'repo'}/")
    cache = tmp_path / "cache"
    model_store.get_model_file("squeezenet1.1", root=str(cache))
    assert any(f.endswith(".params") for f in os.listdir(cache))
    model_store.purge(str(cache))
    assert not any(f.endswith(".params") for f in os.listdir(cache))
