"""Inference serving stack (mxnet_tpu/serving/): bucket grid, cached-
graph warmup/keying, Server continuous batching, SLO close, fault
retry, hot reload, telemetry.

Bitwise comparisons are always made at MATCHED batch buckets (the same
compiled executable): XLA:CPU may pick a different matmul kernel per
batch size (see serving/buckets.py), so cross-bucket comparisons are
an environment property, not a serving invariant — the invariant is
padding transparency within a bucket.
"""
import os
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import fault, serving, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.serving.buckets import BucketGrid

pytestmark = pytest.mark.serving


def make_net(in_units=8, units=4, seed=0):
    net = nn.Dense(units, in_units=in_units)
    net.initialize()
    rs = np.random.RandomState(seed)
    net.weight.set_data(mx.nd.array(
        rs.randn(units, in_units).astype(np.float32)))
    net.bias.set_data(mx.nd.array(rs.randn(units).astype(np.float32)))
    net.hybridize()
    return net


def direct(net, rows, cap):
    """Reference: the padded bucket-`cap` dispatch the server makes."""
    pad = np.zeros((cap,) + rows[0].shape, np.float32)
    for i, r in enumerate(rows):
        pad[i] = r
    return net(mx.nd.array(pad)).asnumpy()


class SleepBlock(mx.gluon.Block):
    """Eager block that sleeps per dispatch (queue-pressure tests)."""

    def __init__(self, seconds, **kw):
        super().__init__(**kw)
        self.seconds = seconds

    def forward(self, x):
        time.sleep(self.seconds)
        return x * 2


class BoomBlock(mx.gluon.Block):
    def forward(self, x):
        raise MXNetError("boom")


# ---------------------------------------------------------------------------
# BucketGrid
# ---------------------------------------------------------------------------

def test_batch_bucket_selection():
    g = BucketGrid(batch_buckets=(4, 1, 16))
    assert g.batch_buckets == (1, 4, 16)
    assert g.max_batch == 16
    assert g.batch_bucket(1) == 1
    assert g.batch_bucket(2) == 4
    assert g.batch_bucket(5) == 16
    assert g.batch_bucket(99) == 16   # callers cap n at max_batch


def test_shape_bucket_exact_mode():
    g = BucketGrid()
    assert g.bucket_shape((3, 5)) == (3, 5)


def test_shape_bucket_tightest_fit():
    g = BucketGrid(shape_buckets=[(16,), (8,), (32,)])
    assert g.bucket_shape((5,)) == (8,)
    assert g.bucket_shape((8,)) == (8,)
    assert g.bucket_shape((9,)) == (16,)
    with pytest.raises(MXNetError):
        g.bucket_shape((33,))          # too big for every bucket
    with pytest.raises(MXNetError):
        g.bucket_shape((4, 4))         # rank mismatch


def test_grid_validation():
    with pytest.raises(MXNetError):
        BucketGrid(batch_buckets=())
    with pytest.raises(MXNetError):
        BucketGrid(batch_buckets=(0, 2))
    with pytest.raises(MXNetError):
        BucketGrid(shape_buckets=[])
    with pytest.raises(MXNetError):
        BucketGrid(shape_buckets=[(0, 3)])


def test_pad_sample():
    out = BucketGrid.pad_sample(np.ones((2, 3), np.float32), (4, 3))
    assert out.shape == (4, 3)
    assert np.array_equal(out[:2], np.ones((2, 3), np.float32))
    assert not out[2:].any()
    same = np.ones((2, 3), np.float32)
    assert BucketGrid.pad_sample(same, (2, 3)) is same


def test_input_signatures():
    g = BucketGrid(batch_buckets=(1, 2), shape_buckets=[(8,), (16,)])
    assert sorted(g.input_signatures()) == [
        (1, 8), (1, 16), (2, 8), (2, 16)]
    # exact-shape mode has no inventory without explicit samples
    assert BucketGrid(batch_buckets=(2,)).input_signatures() == []
    assert BucketGrid(batch_buckets=(2,)).input_signatures([(3, 3)]) == \
        [(2, 3, 3)]


# ---------------------------------------------------------------------------
# _CachedGraph warmup + cache keying across padded batch sizes
# ---------------------------------------------------------------------------

def test_warmup_one_entry_per_bucket():
    net = make_net()
    n = net.warmup([(1, 8), (2, 8), (4, 8)])
    assert n == 3
    assert len(net._cached_graph._cache) == 3
    assert net.warmup([(1, 8), (2, 8), (4, 8)]) == 0   # already warm


def test_warmup_requires_hybridize():
    net = nn.Dense(4, in_units=8)
    net.initialize()
    with pytest.raises(MXNetError, match="hybridize"):
        net.warmup([(1, 8)])


def test_warmup_multi_input_spec():
    class TwoIn(mx.gluon.HybridBlock):
        def hybrid_forward(self, F, a, b):
            return a + b

    blk = TwoIn()
    blk.hybridize()
    assert blk.warmup([[(2, 4), (2, 4)]]) == 1
    out = blk(mx.nd.ones((2, 4)), mx.nd.ones((2, 4)))
    assert len(blk._cached_graph._cache) == 1   # the call was a hit
    assert np.array_equal(out.asnumpy(), np.full((2, 4), 2, np.float32))


def test_warmup_zero_retraces_on_repeat_shapes():
    net = make_net()
    net.warmup([(2, 8), (4, 8)])
    was = telemetry.enabled()
    telemetry.reset()
    telemetry.enable()
    try:
        x2 = mx.nd.array(np.ones((2, 8), np.float32))
        x4 = mx.nd.array(np.ones((4, 8), np.float32))
        for _ in range(3):
            net(x2)
            net(x4)
        assert len(net._cached_graph._cache) == 2    # zero new entries
        snap = telemetry.snapshot()["metrics"]["mxnet_jit_cache_total"]
        hits = {tuple(s["labels"].values()): s["value"]
                for s in snap["samples"]}
        assert hits.get(("cached_op", "hit"), 0) == 6
        assert ("cached_op", "miss") not in hits
    finally:
        telemetry.reset()
        if not was:
            telemetry.disable()


def test_warmup_outputs_eager_identical():
    net = make_net()
    net.warmup([(2, 8)])
    x = np.random.RandomState(3).randn(2, 8).astype(np.float32)
    compiled = net(mx.nd.array(x)).asnumpy()
    eager = net._eager_forward(mx.nd.array(x)).asnumpy()
    assert np.array_equal(compiled, eager)


def test_cache_keying_padded_batches_share_entries():
    """Distinct fill levels of one bucket are ONE cache entry; padding
    rows are bit-transparent within the bucket."""
    net = make_net()
    rs = np.random.RandomState(1)
    rows = [rs.randn(8).astype(np.float32) for _ in range(4)]
    full = direct(net, rows, 4)
    assert len(net._cached_graph._cache) == 1
    part = direct(net, rows[:2], 4)      # 2 real + 2 padded rows
    assert len(net._cached_graph._cache) == 1
    assert np.array_equal(part[:2], full[:2])


# ---------------------------------------------------------------------------
# Server: batching, SLO, ordering, errors
# ---------------------------------------------------------------------------

def test_server_basic_bit_identical():
    net = make_net()
    rs = np.random.RandomState(2)
    rows = [rs.randn(8).astype(np.float32) for _ in range(2)]
    ref = direct(net, rows, 2)
    with serving.Server(net, batch_buckets=(2,), shape_buckets=[(8,)],
                        slo_ms=200) as srv:
        futs = [srv.submit(r) for r in rows]
        outs = [f.result(timeout=10) for f in futs]
    assert np.array_equal(outs[0], ref[0])
    assert np.array_equal(outs[1], ref[1])


def test_server_pads_single_request():
    net = make_net()
    row = np.random.RandomState(4).randn(8).astype(np.float32)
    ref = direct(net, [row], 2)
    with serving.Server(net, batch_buckets=(2,), shape_buckets=[(8,)],
                        slo_ms=50) as srv:
        out = srv.submit(row).result(timeout=10)
        assert srv.stats()["batches"] == 1
    assert np.array_equal(out, ref[0])


def test_server_multi_output_model():
    class TwoOut(mx.gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return x * 2, (x + 1,)

    blk = TwoOut()
    blk.hybridize()
    row = np.arange(4, dtype=np.float32)
    with serving.Server(blk, batch_buckets=(2,), shape_buckets=[(4,)],
                        slo_ms=50) as srv:
        out = srv.submit(row).result(timeout=10)
    assert isinstance(out, tuple) and isinstance(out[1], tuple)
    assert np.array_equal(out[0], row * 2)
    assert np.array_equal(out[1][0], row + 1)


def test_server_shape_bucket_padding():
    net = make_net()
    short = np.ones(5, np.float32)
    padded = np.zeros(8, np.float32)
    padded[:5] = short
    ref = direct(net, [padded], 2)
    with serving.Server(net, batch_buckets=(2,), shape_buckets=[(8,)],
                        slo_ms=50) as srv:
        out = srv.submit(short).result(timeout=10)
    assert np.array_equal(out, ref[0])


def test_server_two_shape_buckets_separate_dispatches():
    class RowSum(mx.gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return F.sum(x, axis=1)

    blk = RowSum()
    blk.hybridize()
    a = np.ones(3, np.float32)
    b = np.ones(6, np.float32)
    with serving.Server(blk, batch_buckets=(2,),
                        shape_buckets=[(4,), (8,)], slo_ms=100) as srv:
        fa, fb = srv.submit(a), srv.submit(b)
        ra, rb = fa.result(timeout=10), fb.result(timeout=10)
        assert srv.stats()["batches"] == 2     # one per shape bucket
    pa = np.zeros(4, np.float32)
    pa[:3] = a
    pb = np.zeros(8, np.float32)
    pb[:6] = b
    assert np.array_equal(ra, direct(blk, [pa], 2)[0])
    assert np.array_equal(rb, direct(blk, [pb], 2)[0])


def test_server_rejects_unbucketable_shape():
    net = make_net()
    with serving.Server(net, batch_buckets=(2,), shape_buckets=[(8,)],
                        slo_ms=50) as srv:
        with pytest.raises(MXNetError, match="no shape bucket"):
            srv.submit(np.ones(9, np.float32))


def test_server_deadline_close_partial_batch():
    net = make_net()
    with serving.Server(net, batch_buckets=(8,), shape_buckets=[(8,)],
                        slo_ms=100, close_margin_ms=10) as srv:
        t0 = time.perf_counter()
        srv.submit(np.ones(8, np.float32)).result(timeout=10)
        dt = time.perf_counter() - t0
    # closed by deadline, not by fill: ~slo, far under the 10 s timeout
    assert dt < 2.0


def test_server_full_close_beats_slo():
    net = make_net()
    with serving.Server(net, batch_buckets=(4,), shape_buckets=[(8,)],
                        slo_ms=5000) as srv:
        rows = [np.ones(8, np.float32)] * 4
        t0 = time.perf_counter()
        futs = [srv.submit(r) for r in rows]
        for f in futs:
            f.result(timeout=10)
        dt = time.perf_counter() - t0
        assert srv.stats()["batches"] >= 1
    assert dt < 2.0     # a full bucket dispatches immediately, not at SLO


def test_tight_deadline_overrides_lazy_head():
    net = make_net()
    with serving.Server(net, batch_buckets=(8,), shape_buckets=[(8,)],
                        slo_ms=30000, close_margin_ms=5) as srv:
        lazy = srv.submit(np.ones(8, np.float32))     # 30 s deadline
        t0 = time.perf_counter()
        tight = srv.submit(np.ones(8, np.float32), deadline_ms=50)
        tight.result(timeout=10)
        dt = time.perf_counter() - t0
        assert lazy.done()      # same key: it rode the tight batch
    assert dt < 2.0             # closed on the TIGHTEST queued deadline


def test_non_batch_major_output_fails_batch_not_server():
    class ScalarOut(mx.gluon.Block):
        def forward(self, x):
            return mx.nd.array(np.float32(1.0))      # no batch axis

    srv = serving.Server(ScalarOut(), batch_buckets=(2,), slo_ms=20,
                         warmup=False).start()
    try:
        f = srv.submit(np.ones(4, np.float32))
        with pytest.raises(Exception):
            f.result(timeout=10)
        assert srv.is_running       # scheduler survived
        assert srv.stats()["errors"] == 1
    finally:
        srv.stop()


def test_server_drains_overflow_into_next_batch():
    net = make_net()
    with serving.Server(net, batch_buckets=(2, 4), shape_buckets=[(8,)],
                        slo_ms=100) as srv:
        futs = [srv.submit(np.ones(8, np.float32)) for _ in range(9)]
        for f in futs:
            f.result(timeout=10)
        assert srv.stats()["batches"] >= 3     # 9 requests, cap 4


def test_submit_requires_running_server():
    net = make_net()
    srv = serving.Server(net, batch_buckets=(2,), shape_buckets=[(8,)])
    with pytest.raises(MXNetError, match="not running"):
        srv.submit(np.ones(8, np.float32))
    srv.start()
    srv.stop()
    with pytest.raises(MXNetError, match="not running"):
        srv.submit(np.ones(8, np.float32))


def test_queue_full_rejects_synchronously():
    blk = SleepBlock(0.3)
    srv = serving.Server(blk, batch_buckets=(1,), slo_ms=20,
                         close_margin_ms=10, max_queue=2, warmup=False)
    srv.start()
    try:
        futs = [srv.submit(np.ones(4, np.float32))]
        time.sleep(0.1)   # first request now dispatched (sleeping)
        futs += [srv.submit(np.ones(4, np.float32)) for _ in range(2)]
        with pytest.raises(MXNetError, match="queue full"):
            srv.submit(np.ones(4, np.float32))
        for f in futs:
            f.result(timeout=10)
    finally:
        srv.stop()


def test_stop_drain_serves_pending():
    blk = SleepBlock(0.1)
    srv = serving.Server(blk, batch_buckets=(2,), slo_ms=5000,
                         warmup=False).start()
    futs = [srv.submit(np.full(4, i, np.float32)) for i in range(3)]
    srv.stop(drain=True)
    outs = [f.result(timeout=1) for f in futs]
    for i, o in enumerate(outs):
        assert np.array_equal(o, np.full(4, 2 * i, np.float32))


def test_stop_no_drain_fails_pending():
    blk = SleepBlock(0.3)
    srv = serving.Server(blk, batch_buckets=(1,), slo_ms=20,
                         close_margin_ms=10, warmup=False).start()
    first = srv.submit(np.ones(4, np.float32))
    time.sleep(0.1)       # first is mid-dispatch; the rest stay queued
    pending = [srv.submit(np.ones(4, np.float32)) for _ in range(2)]
    srv.stop(drain=False)
    first.result(timeout=10)      # in-flight dispatch still completes
    for f in pending:
        with pytest.raises(MXNetError, match="stopped"):
            f.result(timeout=1)


def test_cancelled_future_skipped_not_fatal():
    blk = SleepBlock(0.2)
    srv = serving.Server(blk, batch_buckets=(1,), slo_ms=20,
                         close_margin_ms=10, warmup=False).start()
    try:
        first = srv.submit(np.ones(4, np.float32))
        time.sleep(0.05)     # first now mid-dispatch
        doomed = srv.submit(np.ones(4, np.float32))
        keeper = srv.submit(np.full(4, 3, np.float32))
        assert doomed.cancel()
        first.result(timeout=10)
        out = keeper.result(timeout=10)   # scheduler survived the cancel
        assert np.array_equal(out, np.full(4, 6, np.float32))
        assert srv.is_running
    finally:
        srv.stop()


def test_dispatch_error_fails_futures_not_server():
    srv = serving.Server(BoomBlock(), batch_buckets=(2,), slo_ms=20,
                         warmup=False).start()
    try:
        f1 = srv.submit(np.ones(4, np.float32))
        with pytest.raises(MXNetError, match="boom"):
            f1.result(timeout=10)
        assert srv.is_running
        assert srv.stats()["errors"] == 1
    finally:
        srv.stop()


def test_transient_dispatch_fault_retried():
    net = make_net()
    with serving.Server(net, batch_buckets=(2,), shape_buckets=[(8,)],
                        slo_ms=50) as srv:
        row = np.ones(8, np.float32)
        ref = direct(net, [row], 2)
        with fault.inject("serving.dispatch=once"):
            out = srv.submit(row).result(timeout=10)
        assert np.array_equal(out, ref[0])
        assert srv.stats()["errors"] == 0


def test_exhausted_dispatch_fault_surfaces(monkeypatch):
    monkeypatch.setenv("MXNET_COMM_RETRY_ATTEMPTS", "2")
    monkeypatch.setenv("MXNET_COMM_RETRY_DELAY", "0.001")
    net = make_net()
    with serving.Server(net, batch_buckets=(2,), shape_buckets=[(8,)],
                        slo_ms=50) as srv:
        with fault.inject("serving.dispatch=every:1"):
            f = srv.submit(np.ones(8, np.float32))
            with pytest.raises(MXNetError, match="serving.dispatch"):
                f.result(timeout=10)
        assert srv.is_running
        assert srv.stats()["errors"] == 1


def test_double_start_raises_and_live_servers_tracks():
    net = make_net()
    srv = serving.Server(net, batch_buckets=(2,), shape_buckets=[(8,)])
    srv.start()
    try:
        assert srv in serving.live_servers()
        with pytest.raises(MXNetError, match="already running"):
            srv.start()
    finally:
        srv.stop()
    assert srv not in serving.live_servers()


def test_server_warms_grid_at_start():
    net = make_net()
    with serving.Server(net, batch_buckets=(2, 4),
                        shape_buckets=[(8,)], slo_ms=50):
        assert len(net._cached_graph._cache) == 2   # (2,8) and (4,8)


# ---------------------------------------------------------------------------
# poll_newest + hot reload
# ---------------------------------------------------------------------------

def test_poll_newest_semantics(tmp_path):
    mgr = mx.checkpoint.CheckpointManager(str(tmp_path), keep_last=5)
    assert mgr.poll_newest("t") is None          # nothing there yet
    net = make_net()
    mgr.save(1, params=net)
    assert mgr.poll_newest("t") == 1
    assert mgr.poll_newest("t") is None          # unchanged
    mgr.save(2, params=net)
    assert mgr.poll_newest("t") == 2
    mgr.save(2, params=net)                      # re-save same step
    assert mgr.poll_newest("t") == 2
    assert mgr.poll_newest("other") == 2         # per-tag state
    assert mgr.poll_newest("t") is None


def test_poll_newest_no_change_path_skips_validation(tmp_path,
                                                     monkeypatch):
    mgr = mx.checkpoint.CheckpointManager(str(tmp_path), keep_last=5)
    mgr.save(1, params=make_net())
    assert mgr.poll_newest("t") == 1
    calls = []
    orig = mx.checkpoint.CheckpointManager.is_valid
    monkeypatch.setattr(mx.checkpoint.CheckpointManager, "is_valid",
                        lambda self, step: calls.append(step)
                        or orig(self, step))
    assert mgr.poll_newest("t") is None
    assert calls == []        # one stat(), zero manifest re-hashes


def _factory_for(tmp_path, seed=0):
    def factory(path):
        net = make_net(seed=seed)
        net.load_parameters(os.path.join(path, "params.params"))
        net.hybridize()
        return net
    return factory


def test_manual_reload_swaps_and_warms(tmp_path):
    old = make_net(seed=0)
    new = make_net(seed=9)
    mgr = mx.checkpoint.CheckpointManager(str(tmp_path))
    mgr.save(7, params=new)
    row = np.ones(8, np.float32)
    ref_new = direct(new, [row], 2)
    with serving.Server(old, batch_buckets=(2,), shape_buckets=[(8,)],
                        slo_ms=50) as srv:
        srv.submit(row).result(timeout=10)
        step = srv.reload(mgr, _factory_for(tmp_path))
        assert step == 7 and srv.loaded_step == 7
        # the swapped-in block was warmed BEFORE the swap
        assert len(srv._model._cached_graph._cache) >= 1
        out = srv.submit(row).result(timeout=10)
    assert np.array_equal(out, ref_new[0])
    assert srv.stats()["reloads"] == 1


def test_reload_failure_keeps_old_model(tmp_path):
    old = make_net(seed=0)
    mgr = mx.checkpoint.CheckpointManager(str(tmp_path))
    mgr.save(1, params=old)
    row = np.ones(8, np.float32)
    ref = direct(old, [row], 2)

    def bad_factory(path):
        raise MXNetError("factory exploded")

    with serving.Server(old, batch_buckets=(2,), shape_buckets=[(8,)],
                        slo_ms=50) as srv:
        with pytest.raises(MXNetError, match="factory exploded"):
            srv.reload(mgr, bad_factory)
        out = srv.submit(row).result(timeout=10)
    assert np.array_equal(out, ref[0])
    assert srv.loaded_step is None


def test_failed_reload_retried_next_tick(tmp_path):
    mgr = mx.checkpoint.CheckpointManager(str(tmp_path), keep_last=2)
    old = make_net(seed=0)
    mgr.save(0, params=old)
    attempts = []
    real = _factory_for(tmp_path)

    def flaky_factory(path):
        attempts.append(path)
        if len(attempts) == 1:
            raise MXNetError("factory exploded once")
        return real(path)

    with serving.Server(old, batch_buckets=(2,), shape_buckets=[(8,)],
                        slo_ms=20) as srv:
        srv.enable_hot_reload(mgr, flaky_factory, interval_s=0.02)
        mgr.save(1, params=make_net(seed=9))
        deadline = time.time() + 10
        while srv.loaded_step != 1 and time.time() < deadline:
            time.sleep(0.02)
        # poll_reset re-offered the bundle after the failed attempt
        assert srv.loaded_step == 1
        assert len(attempts) >= 2


def test_hot_reload_watcher_serves_during_swap(tmp_path):
    mgr = mx.checkpoint.CheckpointManager(str(tmp_path), keep_last=2)
    old = make_net(seed=0)
    new = make_net(seed=9)
    mgr.save(0, params=old)
    row = np.ones(8, np.float32)
    ref_old = direct(old, [row], 2)
    ref_new = direct(new, [row], 2)
    with serving.Server(old, batch_buckets=(2,), shape_buckets=[(8,)],
                        slo_ms=20) as srv:
        srv.enable_hot_reload(mgr, _factory_for(tmp_path),
                              interval_s=0.02)
        outs = [srv.submit(row).result(timeout=10)]
        mgr.save(1, params=new)
        deadline = time.time() + 10
        while srv.loaded_step != 1 and time.time() < deadline:
            outs.append(srv.submit(row).result(timeout=10))
        assert srv.loaded_step == 1
        outs.append(srv.submit(row).result(timeout=10))
    for o in outs:      # every response is one model or the other
        assert np.array_equal(o, ref_old[0]) or \
            np.array_equal(o, ref_new[0])
    assert np.array_equal(outs[-1], ref_new[0])
    assert srv._watcher is None     # stop() tore the watcher down


# ---------------------------------------------------------------------------
# int8 serving + quantize_net hybridize propagation
# ---------------------------------------------------------------------------

def _mlp(seed=0):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=8),
                nn.Dense(4, in_units=16))
    net.initialize()
    rs = np.random.RandomState(seed)
    for p in net.collect_params().values():
        p.set_data(mx.nd.array(rs.randn(*p.shape).astype(np.float32)))
    return net


def test_quantize_net_keeps_hybridized():
    from mxnet_tpu.contrib.quantization import quantize_net

    net = _mlp()
    net.hybridize()
    calib = mx.nd.array(np.random.RandomState(1).randn(8, 8)
                        .astype(np.float32))
    quantize_net(net, calib_data=calib, calib_mode="naive")
    assert net._active
    assert all(getattr(c, "_active", True) for c in net._children.values())
    assert net.warmup([(2, 8)]) == 1     # warms without a manual re-hybridize


def test_server_serves_quantized_net():
    from mxnet_tpu.contrib.quantization import quantize_net

    net = _mlp()
    net.hybridize()
    calib = mx.nd.array(np.random.RandomState(1).randn(8, 8)
                        .astype(np.float32))
    quantize_net(net, calib_data=calib, calib_mode="naive")
    row = np.random.RandomState(2).randn(8).astype(np.float32)
    ref = direct(net, [row], 2)
    with serving.Server(net, batch_buckets=(2,), shape_buckets=[(8,)],
                        slo_ms=50) as srv:
        out = srv.submit(row).result(timeout=10)
    assert np.array_equal(out, ref[0])


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------

def test_serving_buckets_are_subms_fine():
    assert telemetry.SERVING_BUCKETS == \
        tuple(sorted(telemetry.SERVING_BUCKETS))
    assert sum(1 for b in telemetry.SERVING_BUCKETS if b < 1e-3) >= 5
    assert telemetry.SERVING_BUCKETS[0] <= 5e-5


def test_serving_metrics_exported():
    was = telemetry.enabled()
    telemetry.reset()
    telemetry.enable()
    try:
        net = make_net()
        with serving.Server(net, batch_buckets=(2,),
                            shape_buckets=[(8,)], slo_ms=20) as srv:
            futs = [srv.submit(np.ones(8, np.float32)) for _ in range(3)]
            for f in futs:
                f.result(timeout=10)
        text = telemetry.prom_text()
        assert 'mxnet_serving_requests_total{outcome="ok"} 3' in text
        assert "mxnet_serving_request_seconds_bucket" in text
        assert "mxnet_serving_time_in_queue_seconds_bucket" in text
        assert "mxnet_serving_batch_occupancy_bucket" in text
        assert "mxnet_serving_batches_total" in text
        assert "mxnet_serving_queue_depth" in text
        snap = telemetry.snapshot()["metrics"]
        occ = snap["mxnet_serving_batch_occupancy"]["samples"][0]
        assert occ["count"] >= 2
    finally:
        telemetry.reset()
        if not was:
            telemetry.disable()


def test_reload_metric(tmp_path):
    was = telemetry.enabled()
    telemetry.reset()
    telemetry.enable()
    try:
        old = make_net(seed=0)
        mgr = mx.checkpoint.CheckpointManager(str(tmp_path))
        mgr.save(3, params=make_net(seed=9))
        with serving.Server(old, batch_buckets=(2,),
                            shape_buckets=[(8,)], slo_ms=50) as srv:
            srv.reload(mgr, _factory_for(tmp_path))
        text = telemetry.prom_text()
        assert 'mxnet_serving_reloads_total{outcome="ok"} 1' in text
    finally:
        telemetry.reset()
        if not was:
            telemetry.disable()


# ---------------------------------------------------------------------------
# serving_bench contract smoke
# ---------------------------------------------------------------------------

def test_serving_bench_stage_contract():
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                    "tools"))
    try:
        import serving_bench as sb
    finally:
        sys.path.pop(0)
    net = sb.build_net()
    samples = sb.make_traffic(8)
    rps, p50, p99, outs = sb.eager_stage(net, samples)
    assert rps > 0 and p50 <= p99 and len(outs) == 8
    brps, bp50, bp99, bouts, occ = sb.batched_stage(
        net, samples, max_batch=4, slo_ms=50, feeders=2)
    assert brps > 0 and len(bouts) == 8 and 0 < occ <= 1.0
    assert all(o is not None for o in bouts)
    assert serving.live_servers() == []
