"""Ring attention tests — sequence/context parallelism over the mesh
(capability row: SURVEY §5.7 long context; Ring Attention construction).

Oracle = dense f32 attention on the full sequence; the ring must be
numerically exact (same online-softmax algebra), fwd and bwd, causal and
not, and must compose with the sharded TrainStep on a dp x sp mesh.
"""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import autograd, parallel as par
from mxnet_tpu.ops.attention import _sdpa_reference


def _qkv(B=2, H=3, L=32, D=16, seed=0):
    rs = onp.random.RandomState(seed)
    return tuple(jnp.asarray(rs.randn(B, H, L, D), jnp.float32)
                 for _ in range(3))


class TestRingExactness:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense_fwd_bwd(self, causal):
        q, k, v = _qkv()
        mesh = par.make_mesh({"sp": 8}, devices=jax.devices()[:8])
        out = par.ring_attention(q, k, v, mesh=mesh, causal=causal)
        want = _sdpa_reference(q, k, v, None, 1.0 / 4.0, causal)
        onp.testing.assert_allclose(onp.asarray(out), onp.asarray(want),
                                    rtol=2e-5, atol=2e-5)

        def loss_ring(a, b, c):
            return (par.ring_attention(a, b, c, mesh=mesh,
                                       causal=causal) ** 2).sum()

        def loss_ref(a, b, c):
            return (_sdpa_reference(a, b, c, None, 1.0 / 4.0,
                                    causal) ** 2).sum()

        gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        gw = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, nm in zip(gr, gw, "qkv"):
            onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                        rtol=2e-4, atol=2e-4,
                                        err_msg=f"d{nm}")

    def test_under_jit_with_sharded_inputs(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        q, k, v = _qkv(L=64)
        mesh = par.make_mesh({"sp": 4}, devices=jax.devices()[:4])
        sh = NamedSharding(mesh, P(None, None, "sp", None))
        qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
        f = jax.jit(lambda a, b, c: par.ring_attention(
            a, b, c, mesh=mesh, causal=True))
        out = f(qs, ks, vs)
        want = _sdpa_reference(q, k, v, None, 1.0 / 4.0, True)
        onp.testing.assert_allclose(onp.asarray(out), onp.asarray(want),
                                    rtol=2e-5, atol=2e-5)
        # output keeps the sequence sharding (no implicit gather)
        assert out.sharding.spec == P(None, None, "sp", None)

    def test_single_device_axis_falls_back(self):
        q, k, v = _qkv()
        mesh = par.make_mesh({"dp": 1}, devices=jax.devices()[:1])
        out = par.ring_attention(q, k, v, mesh=mesh, axis="sp")
        want = _sdpa_reference(q, k, v, None, 1.0 / 4.0, False)
        onp.testing.assert_allclose(onp.asarray(out), onp.asarray(want),
                                    rtol=1e-5, atol=1e-6)


class TestRingInModel:
    def test_mha_cell_ring_vs_dense(self):
        """The same MultiHeadAttention weights must produce identical
        outputs with and without ring_axis under a dp x sp TrainStep."""
        from mxnet_tpu.gluon import loss as gloss
        from mxnet_tpu.gluon.model_zoo.nlp.attention import \
            MultiHeadAttention

        def build(ring):
            onp.random.seed(0)
            mx.random.seed(0)
            cell = MultiHeadAttention(units=16, num_heads=4, causal=True,
                                      ring_axis="sp" if ring else None)
            cell.initialize()
            return cell

        rs = onp.random.RandomState(1)
        x = mx.nd.array(rs.randn(4, 16, 16).astype(onp.float32))
        y = mx.nd.array(rs.randn(4, 16, 16).astype(onp.float32))

        losses = {}
        for ring in (False, True):
            cell = build(ring)
            mesh = par.make_mesh({"dp": 2, "sp": 4},
                                 devices=jax.devices()[:8])
            step = par.TrainStep(cell, gloss.L2Loss(), "sgd", mesh=mesh,
                                 seq_axis="sp",
                                 optimizer_params={"learning_rate": 0.1})
            l, _ = step(x, y)
            losses[ring] = float(l.asnumpy())
        assert losses[True] == pytest.approx(losses[False], rel=1e-5), \
            losses


class TestShardingPreservation:
    def test_no_allgather_over_other_axes(self):
        """Round-2 review finding: only the ring axis may be manual —
        dp/tp shardings must survive and no all-gather may appear."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = par.make_mesh({"dp": 2, "sp": 4}, devices=jax.devices()[:8])
        q = jnp.ones((4, 2, 32, 8), jnp.float32)
        qs = jax.device_put(q, NamedSharding(mesh,
                                             P("dp", None, "sp", None)))
        f = jax.jit(lambda a, b, c: par.ring_attention(
            a, b, c, mesh=mesh, causal=True))
        hlo = f.lower(qs, qs, qs).compile().as_text()
        assert "all-gather" not in hlo
        out = f(qs, qs, qs)
        assert out.sharding.spec == P("dp", None, "sp", None)

    def test_ring_axis_without_mesh_takes_normal_dispatch(self):
        """ring_axis on the op must fall through to flash/reference
        dispatch when no mesh is active (not pin the dense path)."""
        import mxnet_tpu as mxx

        q = mxx.nd.array(onp.random.RandomState(0)
                         .randn(1, 2, 16, 8).astype("float32"))
        out = mxx.nd.contrib.sdp_attention(q, q, q, causal=True,
                                           ring_axis="sp")
        want = _sdpa_reference(q.data, q.data, q.data, None,
                               1.0 / onp.sqrt(8), True)
        onp.testing.assert_allclose(out.asnumpy(), onp.asarray(want),
                                    rtol=1e-5, atol=1e-6)
