"""Ring attention tests — sequence/context parallelism over the mesh
(capability row: SURVEY §5.7 long context; Ring Attention construction).

Oracle = dense f32 attention on the full sequence; the ring must be
numerically exact (same online-softmax algebra), fwd and bwd, causal and
not, and must compose with the sharded TrainStep on a dp x sp mesh.
"""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import autograd, parallel as par
from mxnet_tpu.ops.attention import _sdpa_reference


def _qkv(B=2, H=3, L=32, D=16, seed=0):
    rs = onp.random.RandomState(seed)
    return tuple(jnp.asarray(rs.randn(B, H, L, D), jnp.float32)
                 for _ in range(3))


class TestRingExactness:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense_fwd_bwd(self, causal):
        q, k, v = _qkv()
        mesh = par.make_mesh({"sp": 8}, devices=jax.devices()[:8])
        out = par.ring_attention(q, k, v, mesh=mesh, causal=causal)
        want = _sdpa_reference(q, k, v, None, 1.0 / 4.0, causal)
        onp.testing.assert_allclose(onp.asarray(out), onp.asarray(want),
                                    rtol=2e-5, atol=2e-5)

        def loss_ring(a, b, c):
            return (par.ring_attention(a, b, c, mesh=mesh,
                                       causal=causal) ** 2).sum()

        def loss_ref(a, b, c):
            return (_sdpa_reference(a, b, c, None, 1.0 / 4.0,
                                    causal) ** 2).sum()

        gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        gw = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, nm in zip(gr, gw, "qkv"):
            onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                        rtol=2e-4, atol=2e-4,
                                        err_msg=f"d{nm}")

    def test_under_jit_with_sharded_inputs(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        q, k, v = _qkv(L=64)
        mesh = par.make_mesh({"sp": 4}, devices=jax.devices()[:4])
        sh = NamedSharding(mesh, P(None, None, "sp", None))
        qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
        f = jax.jit(lambda a, b, c: par.ring_attention(
            a, b, c, mesh=mesh, causal=True))
        out = f(qs, ks, vs)
        want = _sdpa_reference(q, k, v, None, 1.0 / 4.0, True)
        onp.testing.assert_allclose(onp.asarray(out), onp.asarray(want),
                                    rtol=2e-5, atol=2e-5)
        # output keeps the sequence sharding (no implicit gather)
        assert out.sharding.spec == P(None, None, "sp", None)

    def test_single_device_axis_falls_back(self):
        q, k, v = _qkv()
        mesh = par.make_mesh({"dp": 1}, devices=jax.devices()[:1])
        out = par.ring_attention(q, k, v, mesh=mesh, axis="sp")
        want = _sdpa_reference(q, k, v, None, 1.0 / 4.0, False)
        onp.testing.assert_allclose(onp.asarray(out), onp.asarray(want),
                                    rtol=1e-5, atol=1e-6)


class TestRingInModel:
    def test_mha_cell_ring_vs_dense(self):
        """The same MultiHeadAttention weights must produce identical
        outputs with and without ring_axis under a dp x sp TrainStep."""
        from mxnet_tpu.gluon import loss as gloss
        from mxnet_tpu.gluon.model_zoo.nlp.attention import \
            MultiHeadAttention

        def build(ring):
            onp.random.seed(0)
            mx.random.seed(0)
            cell = MultiHeadAttention(units=16, num_heads=4, causal=True,
                                      ring_axis="sp" if ring else None)
            cell.initialize()
            return cell

        rs = onp.random.RandomState(1)
        x = mx.nd.array(rs.randn(4, 16, 16).astype(onp.float32))
        y = mx.nd.array(rs.randn(4, 16, 16).astype(onp.float32))

        losses = {}
        for ring in (False, True):
            cell = build(ring)
            mesh = par.make_mesh({"dp": 2, "sp": 4},
                                 devices=jax.devices()[:8])
            step = par.TrainStep(cell, gloss.L2Loss(), "sgd", mesh=mesh,
                                 seq_axis="sp",
                                 optimizer_params={"learning_rate": 0.1})
            l, _ = step(x, y)
            losses[ring] = float(l.asnumpy())
        assert losses[True] == pytest.approx(losses[False], rel=1e-5), \
            losses


class TestShardingPreservation:
    def test_no_allgather_over_other_axes(self):
        """Round-2 review finding: only the ring axis may be manual —
        dp/tp shardings must survive and no all-gather may appear."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = par.make_mesh({"dp": 2, "sp": 4}, devices=jax.devices()[:8])
        q = jnp.ones((4, 2, 32, 8), jnp.float32)
        qs = jax.device_put(q, NamedSharding(mesh,
                                             P("dp", None, "sp", None)))
        f = jax.jit(lambda a, b, c: par.ring_attention(
            a, b, c, mesh=mesh, causal=True))
        hlo = f.lower(qs, qs, qs).compile().as_text()
        assert "all-gather" not in hlo
        out = f(qs, qs, qs)
        assert out.sharding.spec == P("dp", None, "sp", None)

    def test_ring_axis_without_mesh_takes_normal_dispatch(self):
        """ring_axis on the op must fall through to flash/reference
        dispatch when no mesh is active (not pin the dense path)."""
        import mxnet_tpu as mxx

        q = mxx.nd.array(onp.random.RandomState(0)
                         .randn(1, 2, 16, 8).astype("float32"))
        out = mxx.nd.contrib.sdp_attention(q, q, q, causal=True,
                                           ring_axis="sp")
        want = _sdpa_reference(q.data, q.data, q.data, None,
                               1.0 / onp.sqrt(8), True)
        onp.testing.assert_allclose(out.asnumpy(), onp.asarray(want),
                                    rtol=1e-5, atol=1e-6)


class TestMemoryScaling:
    def test_no_full_L_residual_in_backward(self):
        """Round-3 upgrade (VERDICT #4): training through ring attention
        must keep O(L_local) residuals — the old implementation saved the
        rotating K/V scan carries, a stacked (n_ring, B, H, L_local, D)
        tensor = full L per device. Walk the gradient jaxpr (recursively,
        shard_map/scan bodies included) and assert no intermediate holds
        n_ring x the shard size."""
        mesh = par.make_mesh({"sp": 8}, devices=jax.devices()[:8])
        b, h, l, d = 1, 2, 256, 16
        n_ring = 8
        shard_elems = b * h * (l // n_ring) * d

        q = jnp.ones((b, h, l, d), jnp.float32)

        def loss(q, k, v):
            return par.ring_attention(q, k, v, mesh=mesh,
                                      causal=True).sum()

        jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, q, q)

        def as_jaxpr(val):
            # duck-typed: ClosedJaxpr has .jaxpr, Jaxpr has .eqns
            if hasattr(val, "jaxpr"):
                val = val.jaxpr
            return val if hasattr(val, "eqns") else None

        def subjaxprs(eqn):
            for val in eqn.params.values():
                items = val if isinstance(val, (tuple, list)) else (val,)
                for item in items:
                    sub = as_jaxpr(item)
                    if sub is not None:
                        yield sub

        def max_size(jx):
            worst = 0
            for eqn in jx.eqns:
                for sub in subjaxprs(eqn):
                    worst = max(worst, max_size(sub))
                for var in list(eqn.outvars) + list(eqn.invars):
                    aval = getattr(var, "aval", None)
                    if aval is None or not hasattr(aval, "size"):
                        continue
                    worst = max(worst, int(aval.size))
            return worst

        worst = max_size(jaxpr.jaxpr)
        # global arrays at the shard_map boundary are b*h*l*d = n *
        # shard; a stacked scan residual would be n * that again
        assert worst <= n_ring * shard_elems, \
            f"found {worst}-element intermediate (> {n_ring}x shard)"

    def test_8k_tokens_on_cpu_mesh(self):
        """Long-context smoke: 8192 tokens ring-sharded over 8 devices,
        forward AND backward, vs the dense oracle."""
        mesh = par.make_mesh({"sp": 8}, devices=jax.devices()[:8])
        rs = onp.random.RandomState(0)
        b, h, l, d = 1, 1, 8192, 64
        q = jnp.asarray(rs.randn(b, h, l, d), jnp.float32)
        k = jnp.asarray(rs.randn(b, h, l, d), jnp.float32)
        v = jnp.asarray(rs.randn(b, h, l, d), jnp.float32)

        def ring_loss(q, k, v):
            out = par.ring_attention(q, k, v, mesh=mesh, causal=True)
            return (out * out).sum()

        def dense_loss(q, k, v):
            out = _sdpa_reference(q, k, v, None, 1.0 / onp.sqrt(d), True)
            return (out * out).sum()

        g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
        for gr, gd in zip(g_ring, g_dense):
            onp.testing.assert_allclose(onp.asarray(gr), onp.asarray(gd),
                                        rtol=2e-3, atol=2e-3)


    def test_kernel_path_matches_einsum_path(self, monkeypatch):
        """The Pallas-kernel per-pair path (used on TPU) must compute the
        same ring as the einsum path — exercised here via interpret mode."""
        import functools
        import importlib

        # the parallel package re-exports the ring_attention FUNCTION
        # under the same name; get the module itself
        ra = importlib.import_module(
            "mxnet_tpu.parallel.ring_attention")

        mesh = par.make_mesh({"sp": 4}, devices=jax.devices()[:4])
        rs = onp.random.RandomState(1)
        b, h, l, d = 1, 2, 512, 32
        q = jnp.asarray(rs.randn(b, h, l, d), jnp.float32)
        k = jnp.asarray(rs.randn(b, h, l, d), jnp.float32)
        v = jnp.asarray(rs.randn(b, h, l, d), jnp.float32)

        def loss(q, k, v):
            out = par.ring_attention(q, k, v, mesh=mesh, causal=True)
            return (out * out).sum()

        g_einsum = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        orig_fwd, orig_bwd = ra._pair_fwd, ra._pair_bwd
        monkeypatch.setattr(ra, "_use_kernel", lambda *a: True)
        monkeypatch.setattr(
            ra, "_pair_fwd",
            functools.partial(orig_fwd, interpret=True))
        monkeypatch.setattr(
            ra, "_pair_bwd",
            functools.partial(orig_bwd, interpret=True))
        g_kernel = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        for ge, gk, nm in zip(g_einsum, g_kernel, "qkv"):
            onp.testing.assert_allclose(onp.asarray(gk), onp.asarray(ge),
                                        rtol=2e-4, atol=2e-4,
                                        err_msg=f"d{nm}")
