"""gluon.contrib.rnn tests (reference:
tests/python/unittest/test_gluon_contrib.py — conv RNN cells,
VariationalDropoutCell).

Oracles: shape algebra (state preserves spatial dims), a numpy ConvLSTM
step, mask-reuse semantics for variational dropout.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.gluon import rnn
from mxnet_tpu.gluon.contrib import rnn as crnn


class TestConvCells:
    @pytest.mark.parametrize("cls,n_states", [
        (crnn.Conv2DRNNCell, 1), (crnn.Conv2DLSTMCell, 2),
        (crnn.Conv2DGRUCell, 1)])
    def test_2d_shapes_and_unroll(self, cls, n_states):
        cell = cls(input_shape=(3, 8, 8), hidden_channels=5,
                   i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
        cell.initialize()
        x = mx.nd.array(onp.random.RandomState(0)
                        .randn(2, 3, 8, 8).astype("float32"))
        out, states = cell(x)
        assert out.shape == (2, 5, 8, 8)
        assert len(states) == n_states
        for s in states:
            assert s.shape == (2, 5, 8, 8)
        seq = mx.nd.array(onp.random.RandomState(1)
                          .randn(2, 4, 3, 8, 8).astype("float32"))
        cell.reset()
        outs, final = cell.unroll(4, seq, layout="NTC")
        assert len(outs) == 4 and outs[0].shape == (2, 5, 8, 8)

    @pytest.mark.parametrize("cls,dims", [
        (crnn.Conv1DLSTMCell, 1), (crnn.Conv3DLSTMCell, 3)])
    def test_1d_3d(self, cls, dims):
        spatial = (6,) * dims
        cell = cls(input_shape=(2,) + spatial, hidden_channels=4,
                   i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
        cell.initialize()
        x = mx.nd.array(onp.random.RandomState(2)
                        .randn(2, 2, *spatial).astype("float32"))
        out, states = cell(x)
        assert out.shape == (2, 4) + spatial

    def test_convlstm_matches_numpy(self):
        cell = crnn.Conv2DLSTMCell(input_shape=(1, 4, 4), hidden_channels=1,
                                   i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
        cell.initialize()
        rs = onp.random.RandomState(3)
        x = rs.randn(1, 1, 4, 4).astype("float32")
        h0 = rs.randn(1, 1, 4, 4).astype("float32")
        c0 = rs.randn(1, 1, 4, 4).astype("float32")
        out, (h1, c1) = cell(mx.nd.array(x),
                             [mx.nd.array(h0), mx.nd.array(c0)])

        def conv(inp, w, b):
            from scipy.signal import correlate  # noqa: F401
            pad = onp.pad(inp[0], ((0, 0), (1, 1), (1, 1)))
            out = onp.zeros((w.shape[0], 4, 4), "float32")
            for o in range(w.shape[0]):
                for ci in range(w.shape[1]):
                    for i in range(4):
                        for j in range(4):
                            out[o, i, j] += (pad[ci, i:i + 3, j:j + 3]
                                             * w[o, ci]).sum()
                out[o] += b[o]
            return out[None]

        wi = cell.i2h_weight.data().asnumpy()
        wh = cell.h2h_weight.data().asnumpy()
        bi = cell.i2h_bias.data().asnumpy()
        bh = cell.h2h_bias.data().asnumpy()
        gates = conv(x, wi, bi) + conv(h0, wh, bh)
        ig, fg, it, og = onp.split(gates, 4, axis=1)
        sig = lambda v: 1.0 / (1.0 + onp.exp(-v))
        c_want = sig(fg) * c0 + sig(ig) * onp.tanh(it)
        h_want = sig(og) * onp.tanh(c_want)
        onp.testing.assert_allclose(h1.asnumpy(), h_want,
                                    rtol=1e-4, atol=1e-5)
        onp.testing.assert_allclose(c1.asnumpy(), c_want,
                                    rtol=1e-4, atol=1e-5)

    def test_even_h2h_kernel_rejected(self):
        with pytest.raises(ValueError, match="odd"):
            crnn.Conv2DLSTMCell(input_shape=(1, 4, 4), hidden_channels=1,
                                i2h_kernel=3, h2h_kernel=2)


class TestVariationalDropout:
    def test_mask_reused_across_steps(self):
        base = rnn.RNNCell(6)
        cell = crnn.VariationalDropoutCell(base, drop_inputs=0.5)
        cell.initialize()
        rs = onp.random.RandomState(4)
        ones = mx.nd.array(onp.ones((2, 6), "float32"))
        with autograd.record():  # training mode
            autograd.set_training(True)
            cell.reset()
            _o1, s = cell(ones)
            m1 = cell._input_mask.asnumpy()
            _o2, s = cell(ones, s)
            m2 = cell._input_mask.asnumpy()
        onp.testing.assert_array_equal(m1, m2)   # SAME mask, both steps
        cell.reset()
        with autograd.record():
            autograd.set_training(True)
            cell(ones)
            m3 = cell._input_mask.asnumpy()
        assert not (m1 == m3).all()              # fresh mask after reset

    def test_inference_identity(self):
        base = rnn.LSTMCell(5)
        cell = crnn.VariationalDropoutCell(base, drop_inputs=0.9,
                                           drop_states=0.9,
                                           drop_outputs=0.9)
        cell.initialize()
        x = mx.nd.array(onp.random.RandomState(5).randn(3, 4)
                        .astype("float32"))
        out, _ = cell(x)
        base.reset()
        want, _ = base(x)
        onp.testing.assert_allclose(out.asnumpy(), want.asnumpy(),
                                    rtol=1e-6)

    def test_unroll_trains(self):
        base = rnn.GRUCell(4)
        cell = crnn.VariationalDropoutCell(base, drop_states=0.3)
        cell.initialize()
        seq = mx.nd.array(onp.random.RandomState(6).randn(2, 5, 3)
                          .astype("float32"))
        with autograd.record():
            outs, _ = cell.unroll(5, seq, layout="NTC", merge_outputs=True)
            loss = (outs ** 2).mean()
        loss.backward()
        g = base.i2h_weight.grad()
        assert onp.isfinite(g.asnumpy()).all()
