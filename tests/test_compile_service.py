"""Compilation service tests (mxnet_tpu/compiler/): canonical signature
keying, the signature manifest, AOT warm-start, the in-process executable
table, eviction observability, and the retrace-regression guard that pins
the "starts hot, stays hot" invariant."""
import json
import os
import tempfile
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import compiler, telemetry
from mxnet_tpu import parallel as par
from mxnet_tpu.compiler import keys, manifest as manifest_mod, service
from mxnet_tpu.gluon import loss as gloss
from mxnet_tpu.gluon import nn


def _counter(snap, name, **labels):
    fam = snap["metrics"].get(name)
    if not fam:
        return 0.0
    total = 0.0
    for s in fam["samples"]:
        if all(s["labels"].get(k) == v for k, v in labels.items()):
            total += s["value"]
    return total


def _cache_misses(snap, base=None):
    """Per-cache miss counts (positive only), optionally as the DELTA
    from a ``base`` snapshot — the registry is process-global, so a
    guard reading absolutes would blame misses other tests legitimately
    recorded in THEIR telemetry windows (order fragility)."""
    def read(s):
        fam = s["metrics"].get("mxnet_jit_cache_total", {"samples": []})
        return {sm["labels"]["cache"]: sm["value"]
                for sm in fam["samples"]
                if sm["labels"]["result"] == "miss"}

    now = read(snap)
    before = read(base) if base is not None else {}
    return {k: v - before.get(k, 0) for k, v in now.items()
            if v - before.get(k, 0) > 0}


def _make_net(width=16, seed=0):
    mx.random.seed(seed)
    net = nn.HybridSequential(prefix="svc_")
    with net.name_scope():
        net.add(nn.Dense(width, activation="relu"))
        net.add(nn.Dense(4))
    net.initialize()
    return net


def _make_step(width=16, seed=0):
    net = _make_net(width=width, seed=seed)
    return par.TrainStep(net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
                         optimizer_params={"learning_rate": 0.1})


def _batch(b=4):
    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.rand(b, 8).astype("float32"))
    y = mx.nd.array((np.arange(b) % 4).astype("float32"))
    return x, y


# ---------------------------------------------------------------------------
# canonical keying
# ---------------------------------------------------------------------------

class TestKeys:
    def test_same_signature_is_equal_and_hashable(self):
        k1 = compiler.signature("eager_op", "relu", attrs=(("a", 1),),
                                platform="cpu", extra=(2, False))
        k2 = compiler.signature("eager_op", "relu", attrs=(("a", 1),),
                                platform="cpu", extra=(2, False))
        assert k1 == k2 and hash(k1) == hash(k2)
        assert compiler.fingerprint(k1) == compiler.fingerprint(k2)

    def test_routing_knob_toggle_changes_key(self, monkeypatch):
        k1 = compiler.signature("eager_op", "relu", platform="cpu")
        monkeypatch.setenv("MXNET_PALLAS_FUSED", "1")
        k2 = compiler.signature("eager_op", "relu", platform="cpu")
        assert k1 != k2

    def test_every_site_component_distinguishes(self):
        base = dict(avals=((2, 2),), attrs=(("k", 1),), platform="cpu",
                    routing=(False,), extra=(True,))
        k = compiler.signature("cached_op", "g", **base)
        for field, mutated in [
                ("avals", ((4, 4),)), ("attrs", (("k", 2),)),
                ("platform", "tpu"), ("routing", (True,)),
                ("extra", (False,))]:
            other = dict(base, **{field: mutated})
            assert compiler.signature("cached_op", "g", **other) != k
        assert compiler.signature("train_step", "g", **base) != k
        assert compiler.signature("cached_op", "h", **base) != k

    def test_codec_round_trips_tuples_exactly(self):
        obj = ((1, 2), "a", [3.5, None], {"k": (True, "x")},
               ("s", ("r", 0, 1)))
        dec = keys.decode(keys.encode(obj))
        assert dec == obj
        assert isinstance(dec[0], tuple) and isinstance(dec[2], list)

    def test_graph_ident_matches_factory_twins_only(self):
        a, b = _make_net(seed=0), _make_net(seed=1)
        assert compiler.graph_ident(a) == compiler.graph_ident(b)

        class Custom(nn.HybridSequential):
            def hybrid_forward(self, F, x):
                return super().hybrid_forward(F, x) * 2

        c = Custom(prefix="svc_")
        with c.name_scope():
            c.add(nn.Dense(16, activation="relu"))
            c.add(nn.Dense(4))
        c.initialize()
        # same children, different forward BYTECODE -> different ident
        assert compiler.graph_ident(c) != compiler.graph_ident(a)

    def test_callable_ident_sees_bytecode(self):
        f1 = lambda x: x + 1            # noqa: E731
        f2 = lambda x: x + 1            # noqa: E731
        g = lambda x: x * 3             # noqa: E731
        assert keys.callable_ident(f1).split(":")[-1] \
            == keys.callable_ident(f2).split(":")[-1]
        assert keys.callable_ident(f1) != keys.callable_ident(g)


# ---------------------------------------------------------------------------
# site caches + executable table
# ---------------------------------------------------------------------------

class TestSiteCache:
    def test_lru_policy_and_eviction_telemetry(self):
        c = service.SiteCache("svc_test", maxsize=2)
        telemetry.enable()
        try:
            c.insert("a", 1)
            c.insert("b", 2)
            assert c.lookup("a") == 1          # touch: a is now MRU
            c.insert("c", 3)                   # evicts b
            assert "b" not in c and "a" in c and "c" in c
            snap = telemetry.snapshot()
            assert _counter(snap, "mxnet_jit_cache_evictions_total",
                            cache="svc_test") == 1
            assert _counter(snap, "mxnet_jit_cache_total",
                            cache="svc_test", result="hit") == 1
        finally:
            telemetry.disable()

    def test_lookup_insert_round_trip(self):
        c = service.SiteCache("svc_test2")
        assert c.lookup("k") is c.MISS
        c.insert("k", "v")
        assert c.lookup("k") == "v" and "k" in c and len(c) == 1


class TestExecutableTable:
    def test_single_flight_dedupes_concurrent_builds(self):
        t = service.ExecutableTable()
        builds = []

        def build():
            import time

            time.sleep(0.02)
            builds.append(1)
            return object()

        results = []
        threads = [threading.Thread(
            target=lambda: results.append(t.get_or_build("fp", build)))
            for _ in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert len(builds) == 1
        assert all(r is results[0] for r in results)
        assert t.stats()["dedup_hits"] == 7

    def test_failed_build_releases_the_slot(self):
        t = service.ExecutableTable()
        with pytest.raises(RuntimeError):
            t.get_or_build("fp", lambda: (_ for _ in ()).throw(
                RuntimeError("boom")))
        assert t.get_or_build("fp", lambda: "ok") == "ok"

    def test_guarded_exec_tracer_calls_use_fallback_per_call(self):
        import jax

        sds = jax.ShapeDtypeStruct((4,), np.float32)
        jitted = jax.jit(lambda v: v * 2)
        compiled = jitted.lower(sds).compile()
        g = service.GuardedExec(compiled, lambda: jitted)
        x = np.ones((4,), np.float32)
        assert np.array_equal(np.asarray(g(x)), [2.0] * 4)
        # inside someone else's trace (autograd's jax.vjp over a
        # hybridized block): a Compiled can't take tracers — the guard
        # must route through the traceable fallback for that call...
        out = jax.jit(lambda v: g(v))(x)
        assert np.array_equal(np.asarray(out), [2.0] * 4)
        # ...WITHOUT permanently abandoning the compiled executable
        assert not g._permanent
        assert np.array_equal(np.asarray(g(x)), [2.0] * 4)

    def test_recorded_training_through_sealed_graph(self):
        from mxnet_tpu import autograd

        net = _make_net()
        net.hybridize()
        x = mx.nd.array(np.ones((2, 8), np.float32))
        net(x)                       # inference entry: sealed, compiled
        with autograd.record():      # training entry: traceable jit
            out = net(x)
            out.sum().backward()
        grads = [p.grad() for p in net.collect_params().values()
                 if p.grad_req != "null"]
        assert all(np.isfinite(g.asnumpy()).all() for g in grads)

    def test_guarded_exec_falls_back_on_aval_mismatch(self):
        calls = []

        def bad(*args):
            raise TypeError("aval mismatch")

        g = service.GuardedExec(bad, lambda: lambda *a: calls.append(a)
                                or "fb")
        assert g(1, 2) == "fb"
        assert g(3) == "fb"              # stays on the fallback
        assert calls == [(1, 2), (3,)]


# ---------------------------------------------------------------------------
# manifest
# ---------------------------------------------------------------------------

class TestManifest:
    def test_round_trip_and_dedupe(self, tmp_path):
        m = compiler.Manifest(str(tmp_path / "sig.jsonl"))
        spec = {"op": "relu", "avals": ((3, 4), "float32")}
        assert m.record("eager_op", spec) is not None
        assert m.record("eager_op", spec) is None       # dedupe
        m.record("train_step", {"ident": "x", "data": (((2,), "f4"),)})
        loaded = compiler.Manifest(str(tmp_path / "sig.jsonl")).entries()
        assert [e["site"] for e in loaded] == ["eager_op", "train_step"]
        assert loaded[0]["spec"] == spec    # tuples restored exactly

    def test_corrupt_and_stale_lines_are_skipped(self, tmp_path):
        path = str(tmp_path / "sig.jsonl")
        m = compiler.Manifest(path)
        m.record("eager_op", {"op": "relu"})
        with open(path, "a") as f:
            f.write("{not json\n")
            f.write(json.dumps({"v": 99, "site": "eager_op",
                                "fp": "z", "spec": None}) + "\n")
            f.write(json.dumps({"v": 1, "site": "no_such_site",
                                "fp": "y", "spec": None}) + "\n")
            f.write(json.dumps({"v": 1, "site": "eager_op",
                                "spec": None}) + "\n")   # no fp
        m2 = compiler.Manifest(path)
        assert len(m2.entries()) == 1
        assert m2.n_skipped == 3 + 1

    def test_missing_file_is_empty_not_fatal(self, tmp_path):
        m = compiler.Manifest(str(tmp_path / "absent.jsonl"))
        assert m.entries() == []

    def test_env_recorder_gating(self, monkeypatch, tmp_path):
        monkeypatch.setattr(manifest_mod, "_env_checked", False)
        monkeypatch.setattr(manifest_mod._recorder, "manifest", None)
        monkeypatch.setenv("MXNET_COMPILE_MANIFEST", "0")
        assert compiler.recorder() is None
        monkeypatch.setattr(manifest_mod, "_env_checked", False)
        monkeypatch.setenv("MXNET_COMPILE_MANIFEST",
                           str(tmp_path / "m.jsonl"))
        rec = compiler.recorder()
        assert rec is not None and rec.path.endswith("m.jsonl")
        manifest_mod.disable_recording()


# ---------------------------------------------------------------------------
# warm start
# ---------------------------------------------------------------------------

class TestWarmStart:
    def test_cached_op_warm_means_zero_retrace_on_first_call(
            self, tmp_path):
        m = compiler.enable_recording(str(tmp_path / "m.jsonl"))
        try:
            x = mx.nd.array(np.ones((3, 8), np.float32))
            cold = _make_net()
            cold.hybridize()
            y_cold = cold(x).asnumpy()

            warm = _make_net()      # same factory, fresh process-proxy
            report = compiler.warm_start(m, blocks=[warm])
            assert report["failed"] == 0
            assert report["replayed"] + report["deduped"] >= 1

            telemetry.enable()
            try:
                y_warm = warm(x).asnumpy()
                snap = telemetry.snapshot()
                assert _counter(snap, "mxnet_jit_cache_total",
                                cache="cached_op", result="miss") == 0
                assert _counter(snap, "mxnet_jit_cache_total",
                                cache="cached_op", result="hit") >= 1
            finally:
                telemetry.disable()
            # warmed execution must be bit-identical to cold execution
            assert y_warm.tobytes() == y_cold.tobytes()
        finally:
            compiler.disable_recording()

    def test_train_step_warm_means_zero_retrace_and_bit_identity(
            self, tmp_path):
        m = compiler.enable_recording(str(tmp_path / "m.jsonl"))
        try:
            x, y = _batch()
            cold = _make_step()
            loss_cold, _ = cold(x, y)
            loss_cold = loss_cold.asnumpy()

            warm = _make_step()
            report = compiler.warm_start(m, train_steps=[warm])
            assert report["failed"] == 0

            telemetry.enable()
            try:
                base = telemetry.snapshot()
                loss_warm, _ = warm(x, y)
                loss_warm = loss_warm.asnumpy()
                snap = telemetry.snapshot()
                assert _counter(snap, "mxnet_jit_cache_total",
                                cache="train_step", result="miss") \
                    == _counter(base, "mxnet_jit_cache_total",
                                cache="train_step", result="miss")
                assert _counter(snap, "mxnet_jit_cache_total",
                                cache="train_step", result="hit") \
                    - _counter(base, "mxnet_jit_cache_total",
                               cache="train_step", result="hit") == 1
            finally:
                telemetry.disable()
            assert loss_warm.tobytes() == loss_cold.tobytes()
        finally:
            compiler.disable_recording()

    def test_fused_segment_warm_replay(self, tmp_path):
        from mxnet_tpu import engine
        from mxnet_tpu.ops import registry

        m = compiler.enable_recording(str(tmp_path / "m.jsonl"))
        try:
            def run_chain():
                with engine.bulk(8):
                    t = mx.nd.ones((4, 4))
                    for _ in range(5):
                        t = mx.nd.relu(t + 1)
                return t.asnumpy()

            ref = run_chain()
            registry.fused_segment_cache_clear()
            report = compiler.warm_start(m)
            assert report["failed"] == 0
            telemetry.enable()
            try:
                out = run_chain()
                snap = telemetry.snapshot()
                assert _counter(snap, "mxnet_jit_cache_total",
                                cache="fused_segment", result="miss") == 0
                assert _counter(snap, "mxnet_jit_cache_total",
                                cache="fused_segment", result="hit") >= 1
            finally:
                telemetry.disable()
            assert np.array_equal(out, ref)
        finally:
            compiler.disable_recording()

    def test_unmatched_providers_are_skipped_not_fatal(self, tmp_path):
        m = compiler.Manifest(str(tmp_path / "m.jsonl"))
        m.record("cached_op", {"graph": "nope", "args": (((1,), "f4"),),
                               "training": False})
        m.record("train_step", {"ident": "nope", "data": ()})
        m.record("executor", {"training": True})
        report = compiler.warm_start(m)
        assert report == {"replayed": 0, "deduped": 0, "skipped": 3,
                          "failed": 0, "entries": 3,
                          "seconds": report["seconds"]}

    def test_concurrent_warm_start_is_thread_safe(self, tmp_path):
        m = compiler.enable_recording(str(tmp_path / "m.jsonl"))
        try:
            x, y = _batch()
            cold = _make_step()
            cold(x, y)

            warm = _make_step()
            reports = []
            threads = [threading.Thread(
                target=lambda: reports.append(
                    compiler.warm_start(m, train_steps=[warm])))
                for _ in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert len(reports) == 4
            assert all(r["failed"] == 0 for r in reports)
            assert len(warm._cache) == 1    # one signature, once
            loss, _ = warm(x, y)            # still trains fine
            assert np.isfinite(loss.asnumpy()).all()
        finally:
            compiler.disable_recording()


class TestElasticWarmHook:
    def test_warm_start_hook_fires_after_bootstrap(self, tmp_path):
        from mxnet_tpu.parallel import elastic

        seen = []
        net = _make_net()
        runner = elastic.ElasticRunner(
            str(tmp_path), params=net, world_size=1, rank=0,
            heartbeat_interval=0.05,
            warm_start=lambda membership: seen.append(
                membership.world_size))
        try:
            runner.start()
            assert seen == [1]
            assert "elastic_warm_done" in compiler.events()
        finally:
            runner.stop()

    def test_warm_hook_failure_is_contained(self, tmp_path):
        from mxnet_tpu.parallel import elastic

        def boom(membership):
            raise RuntimeError("warm hook failed")

        net = _make_net()
        runner = elastic.ElasticRunner(
            str(tmp_path), params=net, world_size=1, rank=0,
            heartbeat_interval=0.05, warm_start=boom)
        try:
            runner.start()      # must not raise: warm is best-effort
            assert runner.membership.world_size == 1
        finally:
            runner.stop()


# ---------------------------------------------------------------------------
# cold-start events + persistent tier
# ---------------------------------------------------------------------------

class TestColdStartAccounting:
    def test_mark_event_records_first_occurrence_only(self):
        name = f"svc_test_event_{os.getpid()}"
        t1 = service.mark_event(name)
        assert t1 is not None and t1 >= 0
        assert service.mark_event(name) is None
        assert service.events()[name] == t1

    def test_first_train_step_event_is_marked(self):
        x, y = _batch()
        step = _make_step()
        step(x, y)
        assert "first_train_step" in compiler.events()


class TestPersistentTier:
    def test_gc_evicts_oldest_past_cap(self, tmp_path):
        d = str(tmp_path)
        stem = "jit_f-" + "0" * 63
        for i in range(4):
            with open(os.path.join(d, f"{stem}{i}-cache"), "wb") as f:
                f.write(b"x" * 100)
            with open(os.path.join(d, f"{stem}{i}-atime"), "wb") as f:
                f.write(b"")
            os.utime(os.path.join(d, f"{stem}{i}-atime"), (i, i))
        from mxnet_tpu.compiler import persistent

        removed = persistent.gc_cache(max_bytes=250, directory=d)
        assert removed == 2
        left = {f for f in os.listdir(d) if f.endswith("-cache")}
        # oldest-used entries went first
        assert left == {f"{stem}2-cache", f"{stem}3-cache"}

    def test_exported_blob_roundtrip_and_table_dedupe(self, tmp_path,
                                                      monkeypatch):
        import jax

        from mxnet_tpu.compiler import persistent

        monkeypatch.setattr(persistent, "_cache_dir",
                            str(tmp_path / "host-x"))
        os.makedirs(str(tmp_path / "host-x"), exist_ok=True)
        sds = jax.ShapeDtypeStruct((4,), np.float32)
        jitted = jax.jit(lambda v: v * 2 + 1)
        fp = f"svc_blob_test_{os.getpid()}"
        g1 = service.seal_executable(fp, jitted, (sds,),
                                     fallback=lambda: jitted)
        assert isinstance(g1, service.GuardedExec)
        blob = str(tmp_path / "exported" / (fp + ".shlo"))
        assert os.path.exists(blob)
        out = g1(np.ones((4,), np.float32))
        assert np.array_equal(np.asarray(out), [3.0] * 4)
        # second seal at the same signature: table hit, no rebuild
        before = service.exec_table.stats()["builds"]
        g2 = service.seal_executable(fp, jitted, (sds,),
                                     fallback=lambda: jitted)
        assert service.exec_table.stats()["builds"] == before
        assert g2.compiled is g1.compiled


# ---------------------------------------------------------------------------
# retrace-regression guard (the "starts hot, stays hot" CI gate)
# ---------------------------------------------------------------------------

@pytest.mark.retrace
class TestRetraceGuard:
    """Fails when a steady-state train or serve step records ANY jit
    cache miss after warmup — the invariant every cache-keying change
    must preserve (a key component computed differently per call, an
    unstable hash, a knob read at the wrong time all break it)."""

    def test_steady_state_train_records_zero_misses(self):
        x, y = _batch()
        step = _make_step()
        step(x, y)                       # warm: compile once
        telemetry.enable()
        try:
            base = telemetry.snapshot()
            for _ in range(3):
                loss, _ = step(x, y)
            loss.asnumpy()
            misses = _cache_misses(telemetry.snapshot(), base)
            assert not misses, (
                f"steady-state training re-traced after warmup: {misses}")
        finally:
            telemetry.disable()

    def test_steady_state_serving_records_zero_misses(self):
        from mxnet_tpu import serving

        net = _make_net()
        net.hybridize()
        srv = serving.Server(net, batch_buckets=(1, 2),
                             shape_buckets=[(8,)], slo_ms=100,
                             name="retrace_guard")
        with srv:
            srv.submit(np.zeros((8,), np.float32)).result(timeout=60)
            telemetry.enable()
            try:
                base = telemetry.snapshot()
                for _ in range(3):
                    srv.submit(
                        np.zeros((8,), np.float32)).result(timeout=60)
                misses = _cache_misses(telemetry.snapshot(), base)
                assert not misses, (
                    f"steady-state serving re-traced after warmup: "
                    f"{misses}")
            finally:
                telemetry.disable()
