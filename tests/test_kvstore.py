"""KVStore tests (reference: tests/python/unittest/test_kvstore.py).

The tpu_sync collective path (round-2: a real shard_map+psum all-reduce,
not a host-side sum) is exercised on the 8-device virtual CPU mesh, and a
2-process jax.distributed bootstrap test covers the DMLC_* env contract.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import kvstore as kv
from mxnet_tpu.base import MXNetError


class TestLocal:
    def test_init_push_pull(self):
        store = kv.create("local")
        store.init(3, mx.nd.ones((2, 3)))
        out = mx.nd.zeros((2, 3))
        store.pull(3, out)
        np.testing.assert_allclose(out.asnumpy(), np.ones((2, 3)))
        store.push(3, mx.nd.full((2, 3), 4.0))
        store.pull(3, out)
        np.testing.assert_allclose(out.asnumpy(), np.full((2, 3), 4.0))

    def test_uninitialized_key_raises(self):
        store = kv.create("local")
        with pytest.raises(MXNetError, match="not initialized"):
            store.push(0, mx.nd.ones((1,)))

    def test_aggregates_multiple_values(self):
        store = kv.create("device")
        store.init("w", mx.nd.zeros((4,)))
        store.push("w", [mx.nd.ones((4,)) * i for i in range(1, 4)])
        out = mx.nd.zeros((4,))
        store.pull("w", out)
        np.testing.assert_allclose(out.asnumpy(), np.full((4,), 6.0))

    def test_server_side_updater(self):
        store = kv.create("local")
        store.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.5))
        store.init(0, mx.nd.ones((3,)))
        store.push(0, mx.nd.ones((3,)))  # w <- w - 0.5 * g
        out = mx.nd.zeros((3,))
        store.pull(0, out)
        np.testing.assert_allclose(out.asnumpy(), np.full((3,), 0.5))

    def test_dist_async_rejected_without_flag(self):
        with pytest.raises(MXNetError, match="MXNET_KVSTORE_DIST_ASYNC_EMU"):
            kv.create("dist_async")

    def test_dist_async_emulation_local_semantics(self, monkeypatch):
        """Single-process slice of the ADR-002 shim: pushes apply the
        server-side optimizer immediately to the local replica, no
        optimizer is a loud error, staleness knob is honored."""
        monkeypatch.setenv("MXNET_KVSTORE_DIST_ASYNC_EMU", "1")
        monkeypatch.setenv("MXNET_KVSTORE_ASYNC_STALENESS", "3")
        store = kv.create("dist_async")
        assert isinstance(store, kv.KVStoreDistAsyncEmu)
        assert store.staleness == 3
        store.init(0, mx.nd.zeros((3,)))
        with pytest.raises(MXNetError, match="server-side optimizer"):
            store.push(0, mx.nd.ones((3,)))
        store.set_optimizer(mx.optimizer.create("sgd", learning_rate=1.0,
                                                wd=0.0))
        for i in range(4):  # crosses the staleness boundary (no-op at P=1)
            store.push(0, mx.nd.ones((3,)))
        out = mx.nd.zeros((3,))
        store.pull(0, out)
        np.testing.assert_allclose(out.asnumpy(), np.full((3,), -4.0))

    def test_dist_async_sync_replicas_bounded_names_key(self, monkeypatch):
        """Uneven per-key push counts must not wedge the replica-sync
        psum forever (ADVICE r5): the pre-collective rendezvous is
        bounded by MXNET_KV_BARRIER_TIMEOUT and the typed error names
        the key, the lockstep contract, and ADR-002."""
        import jax

        from mxnet_tpu.kvstore import kvstore as kvmod

        monkeypatch.setenv("MXNET_KVSTORE_DIST_ASYNC_EMU", "1")
        store = kv.create("dist_async")
        # fake a 2-process world where the peer never announces
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(jax, "process_index", lambda: 0)

        class Stub:
            def __init__(self):
                self.d = {}

            def key_value_set(self, k, v):
                self.d[k] = v

            def key_value_dir_get(self, p):
                return [(k, v) for k, v in self.d.items()
                        if k.startswith(p)]

        monkeypatch.setattr(kvmod, "_coord_client", lambda: Stub())
        monkeypatch.setenv("MXNET_KV_BARRIER_TIMEOUT", "0.15")
        with pytest.raises(kv.BarrierTimeoutError) as ei:
            store._sync_replicas("weight0")
        msg = str(ei.value)
        assert "'weight0'" in msg
        assert "LOCKSTEP" in msg and "ADR-002" in msg
        assert "missing ranks [1]" in msg


class TestTPUSync:
    def test_push_is_one_collective(self):
        """Per-device copies reduce via ONE compiled psum; pulls into the
        participating devices are local views of the replicated result."""
        import jax

        devs = jax.devices()[:4]
        store = kv.create("tpu_sync")
        store.init(0, mx.nd.zeros((8, 16)))
        rs = np.random.RandomState(0)
        grads_np = [rs.randn(8, 16).astype(np.float32) for _ in devs]
        grads = [mx.nd.array(g).as_in_context(mx.Context("cpu", i))
                 for i, g in enumerate(grads_np)]
        # each copy must actually live on its own device
        for g, d in zip(grads, devs):
            assert next(iter(g.data.devices())) == d
        store.push(0, grads)
        outs = [mx.nd.zeros((8, 16), ctx=mx.Context("cpu", i))
                for i in range(len(devs))]
        store.pull(0, outs)
        want = np.sum(grads_np, axis=0)
        for i, o in enumerate(outs):
            np.testing.assert_allclose(o.asnumpy(), want, rtol=1e-6,
                                       err_msg=f"device {i}")
            assert next(iter(o.data.devices())) == devs[i]

    def test_reducer_cache_reused(self):
        store = kv.create("tpu_sync")
        store.init(0, mx.nd.zeros((4,)))
        store.init(1, mx.nd.zeros((4,)))
        for key in (0, 1):
            store.push(key, [mx.nd.ones((4,)).as_in_context(
                mx.Context("cpu", i)) for i in range(2)])
        assert len(store._reducers) == 1  # same signature -> one executable

    def test_trainer_tpu_sync_matches_single_device(self):
        """VERDICT #4 'done' criterion: Trainer with kvstore='tpu_sync'
        over per-device grads matches the plain single-device update."""
        from mxnet_tpu import gluon
        from mxnet_tpu.gluon import nn

        def make_net(seed):
            net = nn.Dense(4, in_units=8)
            net.initialize(mx.init.Xavier(rnd_type="gaussian"), force_reinit=True)
            mx.random.seed(seed)
            w = np.random.RandomState(5).randn(4, 8).astype(np.float32)
            b = np.zeros(4, np.float32)
            net.weight.set_data(mx.nd.array(w))
            net.bias.set_data(mx.nd.array(b))
            return net

        rs = np.random.RandomState(1)
        x = rs.randn(8, 8).astype(np.float32)
        y = rs.randn(8, 4).astype(np.float32)

        # single device reference
        net1 = make_net(0)
        tr1 = gluon.Trainer(net1.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore="local")
        from mxnet_tpu import autograd
        from mxnet_tpu.gluon.loss import L2Loss

        loss_fn = L2Loss()
        with autograd.record():
            l = loss_fn(net1(mx.nd.array(x)), mx.nd.array(y))
        l.backward()
        tr1.step(8)

        # 2-device data parallel via tpu_sync
        net2 = make_net(0)
        ctxs = [mx.Context("cpu", 0), mx.Context("cpu", 1)]
        net2.collect_params().reset_ctx(ctxs)
        tr2 = gluon.Trainer(net2.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore="tpu_sync")
        with autograd.record():
            losses = [loss_fn(net2(mx.nd.array(x[i * 4:(i + 1) * 4],
                                               ctx=c)),
                              mx.nd.array(y[i * 4:(i + 1) * 4], ctx=c))
                      for i, c in enumerate(ctxs)]
        autograd.backward(losses)
        tr2.step(8)

        w1 = net1.weight.data().asnumpy()
        w2 = net2.weight.data(ctxs[0]).asnumpy()
        np.testing.assert_allclose(w2, w1, rtol=1e-5, atol=1e-6)


_DIST_WORKER = r"""
import os, sys
import numpy as np
os.environ.setdefault("JAX_PLATFORMS", "cpu")
DEVS = int(os.environ.get("TEST_DEVS_PER_PROC", "2"))
NPROC = int(os.environ.get("TEST_NUM_PROC", "2"))
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + f" --xla_force_host_platform_device_count={DEVS}")
import mxnet_tpu as mx
from mxnet_tpu import kvstore as kv
store = kv.create("dist_sync")
import jax
assert jax.process_count() == NPROC, jax.process_count()
assert store.num_workers == NPROC
assert store.rank == int(os.environ["DMLC_WORKER_ID"])
# real cross-host reduce: each of the NPROC*DEVS global devices
# contributes rank*DEVS+i+1; the psum must cross process boundaries
rank = store.rank
total = NPROC * DEVS
want = total * (total + 1) / 2.0
store.init(0, mx.nd.zeros((4, 8)))
grads = [mx.nd.full((4, 8), float(rank * DEVS + i + 1),
                    ctx=mx.Context("cpu", i)) for i in range(DEVS)]
store.push(0, grads)
outs = [mx.nd.zeros((4, 8), ctx=mx.Context("cpu", i)) for i in range(DEVS)]
store.pull(0, outs)
for o in outs:
    got = o.asnumpy()
    assert np.allclose(got, want), (rank, got[0, 0], want)
sys.stdout.write(f"DIST_OK {store.rank}\n"); sys.stdout.flush()
"""


_DIST_ASYNC_WORKER = r"""
import os, sys
import numpy as np
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1")
os.environ["MXNET_KVSTORE_DIST_ASYNC_EMU"] = "1"
os.environ["MXNET_KVSTORE_ASYNC_STALENESS"] = "2"
import mxnet_tpu as mx
from mxnet_tpu import kvstore as kv
store = kv.create("dist_async")
rank = store.rank
store.set_optimizer(mx.optimizer.create("sgd", learning_rate=1.0, wd=0.0))
store.init(0, mx.nd.zeros((2, 2)))
g = float(rank * 2 + 1)                      # rank0: 1, rank1: 3
# push 1: applied LOCALLY, no cross-process barrier -> replicas diverge
store.push(0, mx.nd.full((2, 2), g))
out = mx.nd.zeros((2, 2)); store.pull(0, out)
assert np.allclose(out.asnumpy(), -g), (rank, out.asnumpy()[0, 0])
# push 2 hits the staleness bound -> replicas averaged: mean(-2,-6) = -4
store.push(0, mx.nd.full((2, 2), g))
store.pull(0, out)
assert np.allclose(out.asnumpy(), -4.0), (rank, out.asnumpy()[0, 0])
# training continues locally on the synced value
store.push(0, mx.nd.full((2, 2), g))
store.pull(0, out)
assert np.allclose(out.asnumpy(), -4.0 - g), (rank, out.asnumpy()[0, 0])
sys.stdout.write(f"ASYNC_OK {rank}\n"); sys.stdout.flush()
"""


class TestDistSync:
    def _run_two_workers(self, tmp_path, source, ok_token):
        script = tmp_path / "worker.py"
        script.write_text(source)
        env_base = {k: v for k, v in os.environ.items()
                    if not k.startswith(("DMLC_", "XLA_FLAGS"))}
        import socket

        with socket.socket() as s:
            s.bind(("", 0))
            port = s.getsockname()[1]
        procs = []
        for rank in range(2):
            repo_root = os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))
            env = dict(env_base,
                       JAX_PLATFORMS="cpu",
                       PYTHONPATH=repo_root + os.pathsep
                       + env_base.get("PYTHONPATH", ""),
                       DMLC_PS_ROOT_URI="127.0.0.1",
                       DMLC_PS_ROOT_PORT=str(port),
                       DMLC_NUM_WORKER="2",
                       DMLC_WORKER_ID=str(rank))
            procs.append(subprocess.Popen(
                [sys.executable, str(script)], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        outs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=180)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
            outs.append(out)
        for rank, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0 and f"{ok_token} {rank}" in out, \
                f"rank {rank} failed:\n{out[-2000:]}"

    def test_dist_async_emulation_bounded_staleness(self, tmp_path):
        """ADR-002 shim across 2 processes: pushes apply locally with no
        barrier (replicas diverge), the staleness-th push averages the
        replicas, training continues on the synced value."""
        self._run_two_workers(tmp_path, _DIST_ASYNC_WORKER, "ASYNC_OK")

    def test_two_process_bootstrap(self, tmp_path):
        """create('dist_sync') bootstraps jax.distributed from the DMLC_*
        env contract (SURVEY.md §5.6.4) — 2 local processes."""
        script = tmp_path / "worker.py"
        script.write_text(_DIST_WORKER)
        env_base = {k: v for k, v in os.environ.items()
                    if not k.startswith(("DMLC_", "XLA_FLAGS"))}
        import socket

        with socket.socket() as s:
            s.bind(("", 0))
            port = s.getsockname()[1]
        procs = []
        for rank in range(2):
            repo_root = os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))
            env = dict(env_base,
                       JAX_PLATFORMS="cpu",
                       PYTHONPATH=repo_root + os.pathsep
                       + env_base.get("PYTHONPATH", ""),
                       DMLC_PS_ROOT_URI="127.0.0.1",
                       DMLC_PS_ROOT_PORT=str(port),
                       DMLC_NUM_WORKER="2",
                       DMLC_WORKER_ID=str(rank))
            procs.append(subprocess.Popen(
                [sys.executable, str(script)], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        outs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=180)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
            outs.append(out)
        for rank, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0 and f"DIST_OK {rank}" in out, \
                f"rank {rank} failed:\n{out[-2000:]}"


class TestLauncher:
    def test_local_launch_two_workers(self, tmp_path):
        """tools/launch.py local mode: exports the DMLC_* contract and the
        workers rendezvous through jax.distributed."""
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = tmp_path / "worker.py"
        script.write_text(_DIST_WORKER)
        env = {k: v for k, v in os.environ.items()
               if not k.startswith(("DMLC_", "XLA_FLAGS"))}
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        out = subprocess.run(
            [sys.executable, os.path.join(repo_root, "tools", "launch.py"),
             "-n", "2", sys.executable, str(script)],
            env=env, capture_output=True, text=True, timeout=180)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "DIST_OK 0" in out.stdout and "DIST_OK 1" in out.stdout, \
            out.stdout + out.stderr

    def test_pushed_value_is_snapshotted(self):
        """Round-2 review finding: mutating a pushed NDArray afterwards
        must not change the stored value."""
        store = kv.create("tpu_sync")
        store.init(1, mx.nd.zeros((3,)))
        g = mx.nd.ones((3,))
        store.push(1, g)
        g += 41
        out = mx.nd.zeros((3,))
        store.pull(1, out)
        np.testing.assert_allclose(out.asnumpy(), np.ones(3))

    def test_string_key_updater_state_stable(self):
        """String keys index updater state by the key itself (stable),
        not hash() (process-randomized)."""
        store = kv.create("local")
        store.set_optimizer(mx.optimizer.create("sgd", learning_rate=1.0,
                                                momentum=0.9))
        store.init("fc_weight", mx.nd.ones((2,)))
        store.push("fc_weight", mx.nd.ones((2,)))
        assert "fc_weight" in store._updater.states


class TestGradientCompression:
    def test_2bit_quantization_and_error_feedback(self):
        from mxnet_tpu.kvstore.gradient_compression import (
            GradientCompression, create_compression)

        comp = GradientCompression(threshold=0.5)
        g = mx.nd.array(np.array([0.9, -0.7, 0.1, -0.2, 0.0],
                                  dtype="float32"))
        q = comp.compress("w", 0, g)
        np.testing.assert_allclose(q.asnumpy(), [0.5, -0.5, 0.0, 0.0, 0.0])
        # error feedback: for gradients within +-t, repeated pushes
        # transmit the true mean in the limit (residual carries the
        # remainder; |g| > t saturates at t/round by construction)
        g2 = mx.nd.array(np.array([0.4, -0.3, 0.1, -0.2, 0.0],
                                  dtype="float32"))
        total = np.zeros(5, dtype="float32")
        for _ in range(40):
            total += comp.compress("w2", 0, g2).asnumpy()
        np.testing.assert_allclose(total / 40.0, g2.asnumpy(),
                                   atol=0.5 / 40)

        with pytest.raises(mx.base.MXNetError, match="type"):
            create_compression({"type": "1bit"})
        with pytest.raises(mx.base.MXNetError, match="threshold"):
            create_compression({"type": "2bit", "threshold": -1.0})

    def test_kvstore_push_compressed(self):
        kv = mx.kv.create("local")
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.25})
        v = mx.nd.zeros((4,))
        kv.init("x", v)
        kv.push("x", mx.nd.array(np.array([1.0, -1.0, 0.1, 0.0],
                                           dtype="float32")))
        out = mx.nd.zeros((4,))
        kv.pull("x", out)
        # every transmitted value is on the {-t, 0, +t} grid
        got = out.asnumpy()
        assert set(np.round(got / 0.25).astype(int)) <= {-1, 0, 1}, got

    def test_trainer_with_compression_converges(self):
        from mxnet_tpu.gluon import Trainer, nn
        from mxnet_tpu import autograd

        np.random.seed(21)
        net = nn.Dense(1)
        net.initialize()
        rs = np.random.RandomState(22)
        x = mx.nd.array(rs.randn(64, 4).astype("float32"))
        w_true = np.array([[1.0, -2.0, 0.5, 3.0]], dtype="float32")
        y = mx.nd.array(x.asnumpy() @ w_true.T)
        trainer = Trainer(net.collect_params(), "sgd",
                          {"learning_rate": 0.05}, kvstore="tpu_sync",
                          compression_params={"type": "2bit",
                                              "threshold": 2.0})
        losses = []
        for _ in range(200):
            with autograd.record():
                loss = ((net(x) - y) ** 2).mean()
            loss.backward()
            trainer.step(1)
            losses.append(float(loss.asnumpy()))
        assert losses[-1] < losses[0] * 0.1, (losses[0], losses[-1])


def test_bandwidth_tool():
    """tools/bandwidth.py (reference tools/bandwidth/measure.py): the
    compiled allreduce path must run and report sane numbers."""
    import importlib.util as ilu
    import os

    spec = ilu.spec_from_file_location(
        "bandwidth", os.path.join(os.path.dirname(__file__), "..",
                                  "tools", "bandwidth.py"))
    bw = ilu.module_from_spec(spec)
    spec.loader.exec_module(bw)
    rec = bw.measure(size_mb=4, iters=3)
    assert rec["devices"] >= 2 and rec["value"] > 0
    assert rec["bus_gb_s"] > rec["value"]  # 2(n-1)/n > 1 for n >= 2


class TestMultiHostHardening:
    """Round-3 (VERDICT #8): beyond 2 localhost processes."""

    def test_four_process_two_device_composition(self, tmp_path):
        """4 processes x 2 local devices: per-process device meshes
        compose with the cross-process (DCN) psum — 8 global devices."""
        script = tmp_path / "worker.py"
        script.write_text(_DIST_WORKER)
        env_base = {k: v for k, v in os.environ.items()
                    if not k.startswith(("DMLC_", "XLA_FLAGS"))}
        import socket

        with socket.socket() as s:
            s.bind(("", 0))
            port = s.getsockname()[1]
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        procs = []
        for rank in range(4):
            env = dict(env_base,
                       JAX_PLATFORMS="cpu",
                       PYTHONPATH=repo_root + os.pathsep
                       + env_base.get("PYTHONPATH", ""),
                       TEST_NUM_PROC="4", TEST_DEVS_PER_PROC="2",
                       DMLC_PS_ROOT_URI="127.0.0.1",
                       DMLC_PS_ROOT_PORT=str(port),
                       DMLC_NUM_WORKER="4",
                       DMLC_WORKER_ID=str(rank))
            procs.append(subprocess.Popen(
                [sys.executable, str(script)], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        outs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=240)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
            outs.append(out)
        for rank, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"rank {rank}:\n{out}"
            assert f"DIST_OK {rank}" in out, f"rank {rank}:\n{out}"

    def test_ssh_mode_dry_run(self, tmp_path):
        """launch.py -H hostfile fans out over ssh; a stub ssh on PATH
        executes the remote command locally, validating the full export
        + quoting + cd contract without a real cluster."""
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = tmp_path / "worker.py"
        script.write_text(_DIST_WORKER)
        hostfile = tmp_path / "hosts"
        # both "hosts" are loopback so the coordinator (hosts[0]) is
        # reachable; the ssh fanout/quoting contract is what's under test
        hostfile.write_text("127.0.0.1\n127.0.0.1\n")
        ssh_stub = tmp_path / "ssh"
        ssh_stub.write_text(
            "#!/bin/sh\n"
            "# drop ssh options (-o val pairs) and the host, run the rest\n"
            'while [ "$1" = "-o" ]; do shift 2; done\n'
            "host=$1; shift\n"
            'echo "SSH_STUB host=$host" 1>&2\n'
            'exec /bin/sh -c "$*"\n')
        ssh_stub.chmod(0o755)
        env = {k: v for k, v in os.environ.items()
               if not k.startswith(("DMLC_", "XLA_FLAGS"))}
        env["PATH"] = str(tmp_path) + os.pathsep + env.get("PATH", "")
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        out = subprocess.run(
            [sys.executable, os.path.join(repo_root, "tools", "launch.py"),
             "-n", "2", "-H", str(hostfile),
             "--env", "TEST_NUM_PROC=2", "--env", "TEST_DEVS_PER_PROC=2",
             "--env", "JAX_PLATFORMS=cpu",
             "--env", "PYTHONPATH=" + env["PYTHONPATH"],
             sys.executable, str(script)],
            env=env, capture_output=True, text=True, timeout=240)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "DIST_OK 0" in out.stdout and "DIST_OK 1" in out.stdout, \
            out.stdout + out.stderr
        assert out.stderr.count("SSH_STUB host=127.0.0.1") == 2
