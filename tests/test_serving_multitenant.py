"""Multi-tenant serving (ISSUE 18): the tenant registry behind one
replica fleet, SLO classes, weighted admission (token buckets +
weighted-fair decode slots), priority preemption at the decode-step
boundary, per-model rolling upgrade, and the wire's absent-field-=-
default forward-compat contract. ``tools/chaos_check.py`` gate 10 and
``tools/serving_bench.py`` stage 10 exercise the same machinery under
load; here each contract is pinned in isolation.
"""
import os
import socket
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import serving, tracing
from mxnet_tpu.base import MXNetError
from mxnet_tpu.serving import wire
from mxnet_tpu.serving.controller import rolling_upgrade
from mxnet_tpu.serving.kvcache import Preempted
from mxnet_tpu.serving.server import DEFAULT_MODEL, TenantThrottled

pytestmark = [pytest.mark.serving, pytest.mark.multitenant]

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
if FIXTURES not in sys.path:
    sys.path.insert(0, FIXTURES)

import worker_factory  # noqa: E402  (the fixtures dir is the point)

_NETS = {}


def get_llama(seed=7):
    """One tiny LLaMA per seed, shared across tests (the decode
    engine's compile cache is keyed by architecture)."""
    if seed not in _NETS:
        _NETS[seed] = worker_factory.tiny_llama(seed=seed)
    return _NETS[seed]


def oracle(net, prompt, n_new):
    """Full-recompute argmax decode — the bit-identity reference."""
    toks = list(prompt)
    for _ in range(n_new):
        logits = net(mx.nd.array(np.asarray(toks, np.int32)[None, :],
                                 dtype="int32")).asnumpy()
        toks.append(int(np.argmax(logits[0, -1])))
    return np.asarray(toks[len(prompt):], dtype=np.int32)


def make_decode_server(net=None, **kw):
    kw.setdefault("batch_buckets", (1, 2))
    kw.setdefault("shape_buckets", [(8,)])
    kw.setdefault("slo_ms", 60000.0)
    kw.setdefault("dtype", "int32")
    kw.setdefault("warmup", False)
    kw.setdefault("decode_pages", 96)
    kw.setdefault("page_size", 4)
    kw.setdefault("len_buckets", (8, 16))
    return serving.Server(net if net is not None else get_llama(), **kw)


def make_classify_server(net, **kw):
    kw.setdefault("batch_buckets", (1,))
    kw.setdefault("shape_buckets", [(8,)])
    kw.setdefault("slo_ms", 2000.0)
    kw.setdefault("warmup", False)
    return serving.Server(net, **kw)


def classify_oracle(net, x):
    return net(mx.nd.array(np.asarray(x, np.float32)[None, :])).asnumpy()[0]


PROMPT = np.array([3, 1, 4, 1, 5], dtype=np.int32)
X = np.linspace(-1.0, 1.0, 8).astype(np.float32)


def wait_until(pred, timeout=60.0, interval=0.01, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# tenant registry
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_register_models_and_stats(self):
        srv = make_classify_server(worker_factory.tiny_net(seed=0))
        srv.register_model("b", worker_factory.tiny_net(seed=1),
                           slo_class="premium", priority=5, weight=2.0)
        assert srv.models() == ["b", DEFAULT_MODEL]
        ms = srv.stats()["models"]
        assert ms["b"]["slo_class"] == "premium"
        assert ms["b"]["priority"] == 5 and ms["b"]["weight"] == 2.0
        assert ms[DEFAULT_MODEL]["slo_class"] == "standard"
        with pytest.raises(MXNetError):
            srv.register_model("b", worker_factory.tiny_net(seed=2))

    def test_unknown_model_refused_synchronously(self):
        with make_classify_server(worker_factory.tiny_net(seed=0)) as srv:
            with pytest.raises(MXNetError, match="unknown model"):
                srv.submit(X, model="ghost")

    def test_submit_routes_to_registered_tenant_bit_identical(self):
        net_a = worker_factory.tiny_net(seed=0)
        net_b = worker_factory.tiny_net(seed=1)
        ref_a = classify_oracle(net_a, X)
        ref_b = classify_oracle(net_b, X)
        assert not np.array_equal(ref_a, ref_b)
        with make_classify_server(net_a) as srv:
            srv.register_model("b", net_b)
            out_a = srv.submit(X).result(timeout=60)
            out_b = srv.submit(X, model="b").result(timeout=60)
        assert np.array_equal(out_a, ref_a)
        assert np.array_equal(out_b, ref_b)

    def test_router_unknown_model_refused_before_routing(self):
        srv = make_classify_server(worker_factory.tiny_net(seed=0))
        with serving.Router([srv], slo_ms=2000.0) as router:
            with pytest.raises(MXNetError, match="register_model"):
                router.submit(X, model="ghost")


# ---------------------------------------------------------------------------
# weighted admission: per-tenant token buckets
# ---------------------------------------------------------------------------

class TestThrottle:
    def test_token_bucket_sheds_typed_and_scoped_to_one_tenant(self):
        with make_classify_server(worker_factory.tiny_net(seed=0)) as srv:
            # a refill rate of ~0/s makes the burst the whole budget:
            # admission is deterministic, not a race with the clock
            srv.register_model("lim", worker_factory.tiny_net(seed=1),
                               rate_limit=1e-6, burst=2)
            futs = [srv.submit(X, model="lim") for _ in range(2)]
            with pytest.raises(TenantThrottled):
                srv.submit(X, model="lim")
            # the neighbor tenant is untouched by lim's empty bucket
            out = srv.submit(X).result(timeout=60)
            for f in futs:
                f.result(timeout=60)
            ms = srv.stats()["models"]
        assert ms["lim"]["shed"] == 1
        assert ms[DEFAULT_MODEL]["shed"] == 0
        assert out is not None

    def test_router_throttle_terminal_not_fleet_multiplied(self):
        reps = [make_classify_server(worker_factory.tiny_net(seed=0),
                                     name=f"thr{i}") for i in range(2)]
        with serving.Router(reps, slo_ms=2000.0) as router:
            router.register_model(
                "lim", lambda: worker_factory.tiny_net(seed=1),
                rate_limit=1e-6, burst=1)
            n_throttled = 0
            for _ in range(4):
                try:
                    router.submit(X, deadline_ms=2000,
                                  model="lim").result(timeout=60)
                except TenantThrottled:
                    n_throttled += 1
            # each replica's burst admits AT MOST one request (where
            # the least-loaded picks land is the router's business);
            # the rest MUST shed — and each shed counts exactly once
            # fleet-wide: a sibling retry would multiply lim's
            # configured rate by the replica count
            total_shed = sum(r.stats()["models"]["lim"]["shed"]
                             for r in reps)
        assert 2 <= n_throttled <= 3
        assert total_shed == n_throttled


# ---------------------------------------------------------------------------
# weighted-fair decode slots
# ---------------------------------------------------------------------------

class TestDecodeFairness:
    def test_token_share_tracks_weights(self):
        net_a, net_b = get_llama(7), get_llama(11)
        n_new, streams = 48, 4
        pages_per = -(-(PROMPT.size + n_new) // 4)
        srv = make_decode_server(
            net_a, batch_buckets=(4,),
            decode_pages=2 * streams * pages_per + 1,
            max_generate_tokens=PROMPT.size + n_new, weight=1.0)
        srv.start()
        try:
            srv.register_model("fast", net_b, weight=3.0)
            srv.submit_generate(PROMPT, 2).result(timeout=600)
            srv.submit_generate(PROMPT, 2,
                                model="fast").result(timeout=600)

            def tokens():
                ms = srv.stats()["models"]
                return (ms[DEFAULT_MODEL]["tokens"],
                        ms["fast"]["tokens"])

            handles = []
            for _ in range(streams):
                handles.append(srv.submit_generate(PROMPT, n_new))
                handles.append(srv.submit_generate(PROMPT, n_new,
                                                   model="fast"))
            base = tokens()
            wait_until(
                lambda: (srv.stats()["generates_active"] == 2 * streams
                         and sum(tokens()) - sum(base) >= 24),
                timeout=120, msg="both tenants decoding steadily")
            a1, b1 = tokens()
            wait_until(
                lambda: (tokens()[0] - a1) + (tokens()[1] - b1) >= 96,
                timeout=120, msg="measurement window tokens")
            a2, b2 = tokens()
            share_fast = (b2 - b1) / ((a2 - a1) + (b2 - b1))
            # weights 3:1 with 4 decode slots per round -> the smooth
            # WRR hands tenant "fast" exactly 3 of 4 slots each round
            assert abs(share_fast - 0.75) / 0.75 <= 0.10
            for h in handles:
                h.result(timeout=600)
        finally:
            srv.stop(drain=False)


# ---------------------------------------------------------------------------
# priority preemption at the decode-step boundary
# ---------------------------------------------------------------------------

class TestPreemption:
    def test_preemption_contract_end_to_end(self):
        net_lo, net_hi = get_llama(7), get_llama(11)
        low_new, hi_new = 40, 8
        orc_lo = oracle(net_lo, PROMPT, low_new)
        orc_hi = oracle(net_hi, PROMPT, hi_new)
        tracing.reset()
        tracing.enable()
        srv = make_decode_server(
            net_lo, decode_pages=40, len_buckets=(8, 16, 32, 64),
            max_generate_tokens=PROMPT.size + low_new, priority=0)
        srv.start()
        try:
            srv.register_model("premium", net_hi, slo_class="premium",
                               priority=10)
            srv.submit_generate(PROMPT, 2).result(timeout=600)
            srv.submit_generate(PROMPT, 2,
                                model="premium").result(timeout=600)
            # 3 low-priority squatters reserve 3 x 12 of 39 usable
            # pages; the premium arrival needs 4 -> must preempt
            lows = [srv.submit_generate(PROMPT, low_new)
                    for _ in range(3)]
            wait_until(lambda: srv.stats()["generates_active"] >= 3,
                       msg="squatters admitted")
            his = [srv.submit_generate(PROMPT, hi_new, model="premium")
                   for _ in range(2)]
            for h in his:
                assert np.array_equal(h.result(timeout=600), orc_hi)
            n_preempted = 0
            for h in lows:
                try:
                    got = h.result(timeout=600)
                except Preempted:
                    n_preempted += 1
                    got = h.tokens()
                    # sealed clean prefix: every token emitted before
                    # the eviction matches the oracle, and the stream
                    # never yields another token after the typed end
                    assert h.next_token(len(got), timeout=1) is None
                assert np.array_equal(
                    np.asarray(got, np.int32), orc_lo[:len(got)])
            assert n_preempted >= 1
            events = tracing.events("preempted")
            assert events, "flight recorder lost the preemption"
            for e in events:
                assert e["victim_model"] == DEFAULT_MODEL
                assert e["beneficiary_model"] == "premium"
                assert e["victim"] is not None
                assert e["beneficiary"] is not None
            st = srv.stats()
            assert st["preemptions"] == n_preempted
            assert st["models"][DEFAULT_MODEL]["preempted"] == \
                n_preempted
        finally:
            srv.stop(drain=False)
            tracing.reset()

    def test_lower_priority_arrival_never_evicts(self):
        net_hi, net_lo = get_llama(7), get_llama(11)
        tracing.reset()
        tracing.enable()
        # default tenant IS the high-priority one here: its streams
        # hold the pool while a low-priority arrival waits its turn
        srv = make_decode_server(
            net_hi, decode_pages=40, len_buckets=(8, 16, 32, 64),
            max_generate_tokens=PROMPT.size + 40, priority=10)
        srv.start()
        try:
            srv.register_model("low", net_lo, priority=0)
            srv.submit_generate(PROMPT, 2).result(timeout=600)
            srv.submit_generate(PROMPT, 2,
                                model="low").result(timeout=600)
            highs = [srv.submit_generate(PROMPT, 40) for _ in range(3)]
            wait_until(lambda: srv.stats()["generates_active"] >= 3,
                       msg="high-priority streams admitted")
            lo = srv.submit_generate(PROMPT, 8, model="low")
            # the low arrival must WAIT (head-of-line on its own
            # tenant queue), not evict anyone, and complete correctly
            # once the actives release their pages
            for h in highs:
                h.result(timeout=600)
            got = lo.result(timeout=600)
            assert np.array_equal(got, oracle(net_lo, PROMPT, 8))
            assert tracing.events("preempted") == []
            assert srv.stats()["preemptions"] == 0
        finally:
            srv.stop(drain=False)
            tracing.reset()


# ---------------------------------------------------------------------------
# automatic defrag trigger
# ---------------------------------------------------------------------------

class TestAutoDefrag:
    def test_defrag_fires_under_fragmentation_and_streams_stay_clean(self):
        net = get_llama(7)
        orc_long = oracle(net, PROMPT, 60)
        srv = make_decode_server(
            net, decode_pages=40, len_buckets=(8, 16, 32, 64),
            max_generate_tokens=PROMPT.size + 60,
            defrag_threshold=0.1)
        srv.start()
        try:
            srv.submit_generate(PROMPT, 2).result(timeout=600)
            # two short streams allocate LOW pages and finish early;
            # the long stream's pages sit above the holes they leave —
            # free-below-high-water crosses the 10% threshold and the
            # between-steps trigger must pack the pool while the long
            # stream keeps decoding
            shorts = [srv.submit_generate(PROMPT, 8) for _ in range(2)]
            wait_until(lambda: srv.stats()["generates_active"] >= 2,
                       msg="short streams admitted")
            long = srv.submit_generate(PROMPT, 60)
            for h in shorts:
                h.result(timeout=600)
            got = long.result(timeout=600)
            st = srv.stats()
        finally:
            srv.stop(drain=False)
        assert st["defrags"] >= 1
        assert np.array_equal(got, orc_long)


# ---------------------------------------------------------------------------
# per-model rolling upgrade
# ---------------------------------------------------------------------------

class TestPerModelUpgrade:
    def test_upgrading_tenant_b_leaves_default_untouched(self):
        net_a = worker_factory.tiny_net(seed=0)
        ref_a = classify_oracle(net_a, X)
        ref_b2 = classify_oracle(worker_factory.tiny_net(seed=2), X)
        reps = [make_classify_server(worker_factory.tiny_net(seed=0),
                                     name=f"up{i}") for i in range(2)]
        with serving.Router(reps, slo_ms=2000.0) as router:
            router.register_model(
                "b", lambda: worker_factory.tiny_net(seed=1))
            v0 = reps[0].model_versions()
            out = rolling_upgrade(
                router, lambda server: worker_factory.tiny_net(seed=2),
                bake_s=0.05, model="b")
            assert out["model"] == "b"
            assert sorted(out["upgraded"]) == ["up0", "up1"]
            for r in reps:
                v1 = r.model_versions()
                assert v1["b"] == v0["b"] + 1
                assert v1[DEFAULT_MODEL] == v0[DEFAULT_MODEL]
            out_a = router.submit(X, deadline_ms=2000).result(timeout=60)
            out_b = router.submit(X, deadline_ms=2000,
                                  model="b").result(timeout=60)
        assert np.array_equal(out_a, ref_a)
        assert np.array_equal(out_b, ref_b2)

    def test_upgrade_refuses_partially_registered_tenant(self):
        reps = [make_classify_server(worker_factory.tiny_net(seed=0),
                                     name=f"part{i}") for i in range(2)]
        # tenant "b" registered on ONE replica behind the router's
        # back: upgrading it fleet-wide would swap a model half the
        # fleet does not serve
        reps[0].register_model("b", worker_factory.tiny_net(seed=1))
        with serving.Router(reps, slo_ms=2000.0) as router:
            with pytest.raises(MXNetError, match="whole fleet"):
                rolling_upgrade(
                    router,
                    lambda server: worker_factory.tiny_net(seed=2),
                    bake_s=0.05, model="b")


# ---------------------------------------------------------------------------
# wire forward-compat: absent field = default tenant
# ---------------------------------------------------------------------------

class TestWireForwardCompat:
    def _roundtrip(self, frame):
        a, b = socket.socketpair()
        try:
            wire.send_frame(a, frame)
            return wire.recv_frame(b)
        finally:
            a.close()
            b.close()

    def test_new_fields_survive_and_old_reader_ignores_them(self):
        frame = {"kind": "submit", "id": 3,
                 "payload": np.arange(8, dtype=np.float32),
                 "model": "premium", "priority": 7,
                 "a_field_from_the_future": True}
        back = self._roundtrip(frame)
        # a new peer reads the tenant fields...
        assert back["model"] == "premium" and back["priority"] == 7
        # ...an old peer never looks: unknown fields ride through the
        # codec untouched, so the frame still parses and serves
        assert back["kind"] == "submit" and back["id"] == 3
        assert back["a_field_from_the_future"] is True
        assert np.array_equal(back["payload"], frame["payload"])

    def test_absent_fields_mean_default_tenant(self):
        # a frame from a peer that predates multi-tenancy: no model,
        # no priority — the .get() read every handler uses yields the
        # default-tenant sentinel, never a KeyError
        back = self._roundtrip({"kind": "submit", "id": 1,
                                "payload": np.zeros(8, np.float32)})
        assert back.get("model") is None
        assert back.get("priority") is None

    def test_error_registry_roundtrips_tenant_errors(self):
        for exc, etype in ((Preempted("evicted at step 3"),
                            "preempted"),
                           (TenantThrottled("lim over rate"),
                            "throttled")):
            name, msg = wire.encode_error(exc)
            assert name == etype
            again = wire.decode_error(name, msg)
            assert isinstance(again, type(exc))
            assert str(exc) in str(again)


# ---------------------------------------------------------------------------
# tenant context across the socket edge
# ---------------------------------------------------------------------------

class TestIngressTenants:
    def test_model_field_crosses_the_socket_and_absent_is_default(self):
        net_a = worker_factory.tiny_net(seed=0)
        net_b = worker_factory.tiny_net(seed=1)
        ref_a = classify_oracle(net_a, X)
        ref_b = classify_oracle(net_b, X)
        srv = make_classify_server(net_a, name="ing_mt")
        with serving.Router([srv], slo_ms=2000.0) as router:
            router.register_model(
                "b", lambda: worker_factory.tiny_net(seed=1))
            with serving.Ingress(router, window=16) as ing, \
                    serving.IngressClient("127.0.0.1", ing.port) as cli:
                out_b = cli.submit(X, deadline_ms=2000,
                                   model="b").result(timeout=60)
                # no model field on the wire -> default tenant
                out_a = cli.submit(X,
                                   deadline_ms=2000).result(timeout=60)
                with pytest.raises(MXNetError):
                    cli.submit(X, deadline_ms=2000,
                               model="ghost").result(timeout=60)
        assert np.array_equal(out_a, ref_a)
        assert np.array_equal(out_b, ref_b)


# ---------------------------------------------------------------------------
# tools/latency_report.py: per-tenant rollup + preemption pairing
# ---------------------------------------------------------------------------

class TestLatencyReportTenants:
    def _report_mod(self):
        sys.path.insert(0, os.path.join(
            os.path.dirname(__file__), os.pardir, "tools"))
        try:
            import latency_report
        finally:
            sys.path.pop(0)
        return latency_report

    @staticmethod
    def _trace(model, slo, dur_us, status="ok"):
        spans = [{"name": "request", "ts": 0, "dur": dur_us,
                  "tags": {"model": model, "slo_class": slo}}] \
            if model else [{"name": "request", "ts": 0, "dur": dur_us}]
        return {"trace_id": f"{model}-{dur_us}", "status": status,
                "spans": spans}

    def test_tables_split_by_tenant_and_preemptions_pair_up(self):
        lr = self._report_mod()
        traces = (
            [self._trace("premium", "premium", 1000)] * 4
            + [self._trace(None, None, 9000)] * 4)
        events = [
            {"event": "preempted", "victim_model": "default",
             "beneficiary_model": "premium", "victim_tokens": 12},
            {"event": "preempted", "victim_model": "default",
             "beneficiary_model": "premium", "victim_tokens": 20},
            {"event": "preempted", "victim_model": "default",
             "beneficiary_model": "premium", "victim_tokens": 30},
            {"event": "shed", "reason": "throttled", "model": "premium"},
        ]
        rows = {r["model"]: r for r in lr.tenant_rollup(traces, events)}
        assert set(rows) == {"default", "premium"}
        # whose p99: the untagged traces ARE the default tenant, and
        # the split keeps each tenant's percentiles apart
        assert rows["default"]["request_p99_ms"] == 9.0
        assert rows["premium"]["request_p99_ms"] == 1.0
        assert rows["premium"]["sheds"] == {"throttled": 1}
        pre = lr.preemption_rollup(events)
        assert pre["events"] == 3
        pair = pre["pairs"]["premium preempted default"]
        assert pair["count"] == 3
        assert pair["victim_clean_prefix_p50_tokens"] == 20.0
        rep = lr.report(traces, events)
        assert "tenants" in rep and "preemptions" in rep
