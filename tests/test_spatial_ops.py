"""Spatial/box op tests (reference: test_operator.py::test_bilinear_sampler,
test_spatial_transformer, tests for contrib box_nms/box_iou).

Oracles: identity-transform passthrough, hand-computed IoU, reference
greedy NMS in numpy.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx


class TestScalarOps:
    def test_hard_sigmoid(self):
        x = mx.nd.array(onp.array([-10.0, 0.0, 10.0, 1.0], "float32"))
        got = mx.nd.hard_sigmoid(x).asnumpy()
        onp.testing.assert_allclose(got, [0.0, 0.5, 1.0, 0.7], rtol=1e-6)

    def test_unravel_index(self):
        got = mx.nd.unravel_index(mx.nd.array([5, 11], dtype="int32"),
                                  shape=(3, 4)).asnumpy()
        onp.testing.assert_array_equal(got, [[1, 2], [1, 3]])

    def test_multi_all_finite(self):
        a = mx.nd.ones((3,))
        b = mx.nd.array(onp.array([1.0, onp.inf], "float32"))
        assert mx.nd.multi_all_finite(a, a, num_arrays=2).asnumpy()[0] == 1
        assert mx.nd.multi_all_finite(a, b, num_arrays=2).asnumpy()[0] == 0


class TestBoxOps:
    def test_box_iou(self):
        a = mx.nd.array(onp.array([[0, 0, 2, 2]], "float32"))
        b = mx.nd.array(onp.array([[1, 1, 3, 3], [0, 0, 2, 2],
                                   [5, 5, 6, 6]], "float32"))
        got = mx.nd.contrib.box_iou(a, b).asnumpy()
        onp.testing.assert_allclose(got, [[1 / 7, 1.0, 0.0]], rtol=1e-5)

    def test_box_iou_center_format(self):
        a = mx.nd.array(onp.array([[1, 1, 2, 2]], "float32"))  # ctr 1,1 2x2
        b = mx.nd.array(onp.array([[0, 0, 2, 2]], "float32"))  # corners
        got_center = mx.nd.contrib.box_iou(a, a, format="center").asnumpy()
        onp.testing.assert_allclose(got_center, [[1.0]], rtol=1e-6)

    def test_box_nms_suppresses(self):
        # rows: [cls, score, x1, y1, x2, y2]
        rows = onp.array([
            [0, 0.9, 0.0, 0.0, 1.0, 1.0],
            [0, 0.8, 0.05, 0.05, 1.0, 1.0],   # overlaps #0 -> suppressed
            [0, 0.7, 2.0, 2.0, 3.0, 3.0],     # far away -> kept
            [1, 0.6, 0.0, 0.0, 1.0, 1.0],     # other class -> kept
            [0, 0.0, 0.0, 0.0, 1.0, 1.0],     # below valid_thresh
        ], "float32")
        got = mx.nd.contrib.box_nms(
            mx.nd.array(rows), overlap_thresh=0.5, valid_thresh=0.01,
            coord_start=2, score_index=1, id_index=0).asnumpy()
        scores = got[:, 1]
        kept = scores[scores >= 0]
        onp.testing.assert_allclose(sorted(kept, reverse=True),
                                    [0.9, 0.7, 0.6], rtol=1e-6)

    def test_box_nms_force_suppress_and_batch(self):
        rows = onp.array([
            [0, 0.9, 0.0, 0.0, 1.0, 1.0],
            [1, 0.8, 0.0, 0.0, 1.0, 1.0],
        ], "float32")
        batch = onp.stack([rows, rows])
        got = mx.nd.contrib.box_nms(
            mx.nd.array(batch), overlap_thresh=0.5, valid_thresh=0.01,
            coord_start=2, score_index=1, id_index=0,
            force_suppress=True).asnumpy()
        assert got.shape == batch.shape
        for b in range(2):
            kept = got[b][got[b][:, 1] >= 0]
            assert len(kept) == 1 and kept[0, 1] == pytest.approx(0.9)


class TestSamplers:
    def test_bilinear_sampler_identity(self):
        rs = onp.random.RandomState(0)
        data = rs.rand(2, 3, 5, 7).astype("float32")
        ys, xs = onp.meshgrid(onp.linspace(-1, 1, 5),
                              onp.linspace(-1, 1, 7), indexing="ij")
        grid = onp.stack([xs, ys])[None].repeat(2, axis=0).astype("float32")
        got = mx.nd.BilinearSampler(mx.nd.array(data),
                                    mx.nd.array(grid)).asnumpy()
        onp.testing.assert_allclose(got, data, rtol=1e-5, atol=1e-5)

    def test_bilinear_sampler_outside_zero(self):
        data = mx.nd.ones((1, 1, 4, 4))
        grid = mx.nd.array(onp.full((1, 2, 1, 1), -5.0, "float32"))
        got = mx.nd.BilinearSampler(data, grid).asnumpy()
        onp.testing.assert_allclose(got, onp.zeros((1, 1, 1, 1)))

    def test_spatial_transformer_identity(self):
        rs = onp.random.RandomState(1)
        data = rs.rand(2, 3, 6, 6).astype("float32")
        theta = onp.tile(onp.array([1, 0, 0, 0, 1, 0], "float32"), (2, 1))
        got = mx.nd.SpatialTransformer(
            mx.nd.array(data), mx.nd.array(theta),
            target_shape=(6, 6)).asnumpy()
        onp.testing.assert_allclose(got, data, rtol=1e-5, atol=1e-5)

    def test_spatial_transformer_translate(self):
        # shift right by one pixel-step in normalized coords
        data = onp.zeros((1, 1, 1, 5), "float32")
        data[0, 0, 0] = onp.arange(5)
        theta = onp.array([[1, 0, 0.5, 0, 1, 0]], "float32")
        got = mx.nd.SpatialTransformer(
            mx.nd.array(data), mx.nd.array(theta),
            target_shape=(1, 5)).asnumpy()
        # x' = x + 0.5 in [-1,1] coords = +1 source pixel at 5 wide
        onp.testing.assert_allclose(got[0, 0, 0, :3],
                                    [1.0, 2.0, 3.0], rtol=1e-5)


def test_box_nms_out_format_center():
    """Regression: out_format='center' must actually convert kept rows
    while suppressed rows stay all -1."""
    rows = onp.array([
        [0, 0.9, 0.0, 0.0, 1.0, 1.0],
        [0, 0.8, 0.0, 0.0, 1.0, 1.0],     # suppressed duplicate
    ], "float32")
    got = mx.nd.contrib.box_nms(
        mx.nd.array(rows), overlap_thresh=0.5, valid_thresh=0.01,
        coord_start=2, score_index=1, id_index=0,
        out_format="center").asnumpy()
    onp.testing.assert_allclose(got[0, 2:6], [0.5, 0.5, 1.0, 1.0],
                                rtol=1e-6)
    assert (got[1] == -1).all()


class TestLongTailOps:
    def test_moments(self):
        x = mx.nd.array(onp.arange(6.0).reshape(2, 3))
        m, v = mx.nd.moments(x, axes=(1,))
        onp.testing.assert_allclose(m.asnumpy(), [1.0, 4.0])
        onp.testing.assert_allclose(v.asnumpy(), [2 / 3, 2 / 3], rtol=1e-6)
        m2, v2 = mx.nd.moments(x, axes=(0, 1), keepdims=True)
        assert v2.shape == (1, 1)

    def test_ravel_unravel_roundtrip(self):
        flat = mx.nd.array([5, 11, 0], dtype="int32")
        multi = mx.nd.unravel_index(flat, shape=(3, 4))
        back = mx.nd.ravel_multi_index(multi, shape=(3, 4))
        onp.testing.assert_array_equal(back.asnumpy(), [5, 11, 0])

    def test_index_array(self):
        out = mx.nd.index_array(mx.nd.ones((2, 3))).asnumpy()
        assert out.shape == (2, 3, 2)
        onp.testing.assert_array_equal(out[1, 2], [1, 2])

    def test_logicals(self):
        a = mx.nd.array([1.0, 0.0, 2.0])
        b = mx.nd.array([1.0, 1.0, 0.0])
        onp.testing.assert_array_equal(
            mx.nd.logical_and(a, b).asnumpy(), [1, 0, 0])
        onp.testing.assert_array_equal(
            mx.nd.logical_or(a, b).asnumpy(), [1, 1, 1])
        onp.testing.assert_array_equal(
            mx.nd.logical_xor(a, b).asnumpy(), [0, 1, 1])

    def test_softmax_activation_modes(self):
        x = mx.nd.array(onp.random.RandomState(0).randn(2, 3, 4)
                        .astype("float32"))
        inst = mx.nd.SoftmaxActivation(x).asnumpy()
        onp.testing.assert_allclose(inst.reshape(2, -1).sum(1), [1.0, 1.0],
                                    rtol=1e-5)
        chan = mx.nd.SoftmaxActivation(x, mode="channel").asnumpy()
        onp.testing.assert_allclose(chan.sum(1), onp.ones((2, 4)),
                                    rtol=1e-5)

    def test_digamma_all_finite(self):
        g = mx.nd.digamma(mx.nd.array([1.0, 2.0])).asnumpy()
        onp.testing.assert_allclose(g, [-0.5772157, 0.4227843], rtol=1e-4)
        assert mx.nd.all_finite(mx.nd.ones((2,))).asnumpy()[0] == 1
