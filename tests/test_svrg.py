"""SVRG optimization tests (reference:
tests/python/unittest/test_contrib_svrg_module.py /
test_contrib_svrg_optimizer.py).

Oracles: mu == mean of batch gradients at the snapshot; the corrected
direction reduces to plain SGD at the snapshot point; end-to-end fit
converges on least squares.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.contrib.svrg_optimization import SVRGModule


def _linreg_symbol():
    data = mx.sym.var("data")
    label = mx.sym.var("lin_label")
    fc = mx.sym.FullyConnected(data, mx.sym.var("fc_weight"),
                               mx.sym.var("fc_bias"), num_hidden=1,
                               name="fc")
    return mx.sym.LinearRegressionOutput(fc, label, name="lin")


def _data(n=64, batch=16, seed=0):
    rs = onp.random.RandomState(seed)
    x = rs.randn(n, 4).astype("float32")
    w = onp.array([[1.5, -2.0, 0.5, 3.0]], "float32")
    y = x @ w.T + 0.01 * rs.randn(n, 1).astype("float32")
    return mx.io.NDArrayIter(x, y, batch_size=batch,
                             label_name="lin_label")


class TestSVRGModule:
    def test_full_grads_is_mean_of_batch_grads(self):
        it = _data()
        mod = SVRGModule(_linreg_symbol(), data_names=("data",),
                         label_names=("lin_label",), update_freq=1)
        mod.bind(data_shapes=it.provide_data,
                 label_shapes=it.provide_label)
        mod.init_params()
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params=(("learning_rate", 0.0),))
        mod.take_snapshot()
        mod.update_full_grads(it)
        # hand-accumulate batch grads at the same (unchanged) weights
        it.reset()
        totals, nb = None, 0
        for batch in it:
            mod.forward_backward(batch)
            g = mod._exec.grad_dict["fc_weight"].asnumpy()
            totals = g.copy() if totals is None else totals + g
            nb += 1
        onp.testing.assert_allclose(
            mod._full_grads["fc_weight"].asnumpy(), totals / nb,
            rtol=1e-5, atol=1e-6)

    def test_correction_vanishes_at_snapshot(self):
        """At w == w~, g_i(w) - g_i(w~) + mu == mu: the applied update
        equals the full-gradient step for every batch."""
        it = _data()
        mod = SVRGModule(_linreg_symbol(), data_names=("data",),
                         label_names=("lin_label",), update_freq=1)
        mod.bind(data_shapes=it.provide_data,
                 label_shapes=it.provide_label)
        mod.init_params()
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params=(("learning_rate", 0.1),))
        mod.take_snapshot()
        mod.update_full_grads(it)
        w0 = mod._exec.arg_dict["fc_weight"].asnumpy().copy()
        it.reset()
        batch = next(iter(it))
        mod.svrg_forward_backward(batch)
        mod.update()
        w1 = mod._exec.arg_dict["fc_weight"].asnumpy()
        want = w0 - 0.1 * mod._full_grads["fc_weight"].asnumpy()
        onp.testing.assert_allclose(w1, want, rtol=1e-4, atol=1e-5)

    def test_fit_converges(self):
        it = _data(n=128, batch=16, seed=3)
        mod = SVRGModule(_linreg_symbol(), data_names=("data",),
                         label_names=("lin_label",), update_freq=2)
        mod.fit(it, eval_metric="mse", num_epoch=12, optimizer="sgd",
                optimizer_params=(("learning_rate", 0.05),))
        w = mod._exec.arg_dict["fc_weight"].asnumpy()
        onp.testing.assert_allclose(
            w, [[1.5, -2.0, 0.5, 3.0]], rtol=0.1, atol=0.05)

    def test_bad_update_freq(self):
        with pytest.raises(MXNetError, match="update_freq"):
            SVRGModule(_linreg_symbol(), update_freq=0)


def test_snapshot_grads_leave_live_weights_intact():
    """Regression: computing snapshot-point gradients must not clobber
    the live weights (save/restore must copy, not alias)."""
    it = _data()
    mod = SVRGModule(_linreg_symbol(), data_names=("data",),
                     label_names=("lin_label",), update_freq=1)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.1),))
    mod.take_snapshot()
    mod.update_full_grads(it)
    # move the live weights away from the snapshot
    live = mod._exec.arg_dict["fc_weight"]
    moved = live.asnumpy() + 1.0
    live._set_data(mx.nd.array(moved).data)
    it.reset()
    mod._compute_snapshot_batch_grads(next(iter(it)))
    onp.testing.assert_allclose(
        mod._exec.arg_dict["fc_weight"].asnumpy(), moved)
