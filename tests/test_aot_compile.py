"""AOT compilation of the sharded train step on abstract parameters.

The Llama-3-8B stretch recipe (BASELINE.json config[4]) is validated by
compiling — not executing — the full sharded TrainStep for meshes/host
sizes that can't hold the weights. These tests pin that machinery at tiny
size: abstract_init produces zero-cost placeholders, aot_compile runs the
normal settle/state/build/lower path, and the instance refuses to train.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import parallel as par
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import loss as gloss
from mxnet_tpu.gluon.parameter import abstract_init
from mxnet_tpu.gluon.model_zoo.nlp.llama import (
    LlamaModel, llama_sharding_rules)


def _build_abstract_net():
    with abstract_init():
        net = LlamaModel(vocab_size=256, num_layers=2, units=64,
                         hidden_size=128, num_heads=4, num_kv_heads=2,
                         remat=True)
        net.initialize()
    return net


def _aot(net, axes):
    import jax
    import jax.numpy as jnp

    mesh = par.make_mesh(axes)
    step = par.TrainStep(
        net, lambda outs, l: gloss.SoftmaxCrossEntropyLoss()(
            (outs[0] if isinstance(outs, (list, tuple)) else outs)
            .reshape(-1, 256), l.reshape(-1)),
        "adamw", mesh=mesh, rules=llama_sharding_rules(),
        loss_only=True,
        optimizer_params={"learning_rate": 1e-4, "multi_precision": True})
    tok = jax.ShapeDtypeStruct((4, 128), jnp.int32)
    lbl = jax.ShapeDtypeStruct((4, 128), jnp.float32)
    return step, step.aot_compile(tok, lbl)


def test_abstract_init_never_materializes():
    net = _build_abstract_net()
    # nothing concrete was allocated: every param is still deferred, and
    # the captured flag keeps it abstract even outside the context
    for p in net.collect_params().values():
        assert p._data is None
        assert p._deferred_init is not None and p._deferred_init[-1] is True


def test_aot_compile_outside_context_stays_abstract():
    import jax

    net = _build_abstract_net()
    step, compiled = _aot(net, {"dp": 2, "tp": 2, "sp": 2})
    assert compiled is not None
    # placeholders resolved inside the settle trace — no concrete arrays
    for p in net.collect_params().values():
        assert isinstance(p.data().data, jax.core.Tracer)


def test_aot_instance_refuses_to_train():
    net = _build_abstract_net()
    step, _ = _aot(net, {"dp": 2, "tp": 2, "sp": 2})
    tok = mx.nd.array(np.zeros((4, 128), dtype=np.int32))
    lbl = mx.nd.array(np.zeros((4, 128), dtype=np.float32))
    with pytest.raises(MXNetError, match="aot_compile"):
        step(tok, lbl)


def test_aot_state_layout_matches_live_training():
    """The AOT state metadata must match what a live TrainStep builds —
    the memory analysis is worthless if the layouts diverge."""
    import jax

    net = _build_abstract_net()
    step, _ = _aot(net, {"dp": 2, "tp": 2, "sp": 2})

    live_net = LlamaModel(vocab_size=256, num_layers=2, units=64,
                          hidden_size=128, num_heads=4, num_kv_heads=2)
    live_net.initialize()
    mesh = par.make_mesh({"dp": 2, "tp": 2, "sp": 2})
    live = par.TrainStep(
        live_net, lambda outs, l: gloss.SoftmaxCrossEntropyLoss()(
            (outs[0] if isinstance(outs, (list, tuple)) else outs)
            .reshape(-1, 256), l.reshape(-1)),
        "adamw", mesh=mesh, rules=llama_sharding_rules(),
        loss_only=True,
        optimizer_params={"learning_rate": 1e-4, "multi_precision": True})
    tok = mx.nd.array(np.zeros((4, 128), dtype=np.int32))
    lbl = mx.nd.array(np.zeros((4, 128), dtype=np.float32))
    live(tok, lbl)

    assert len(step._state_meta) == len(live._state_meta)
    for (_, p1, s1), (_, p2, s2) in zip(step._state_meta, live._state_meta):
        assert p1 == p2
        assert [tuple(s) for s in s1] == [tuple(s) for s in s2]
