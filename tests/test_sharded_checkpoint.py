"""Sharded (per-process) checkpoint round-trips (SURVEY §5.4 stretch,
VERDICT r4 #6): save from a sharded TrainStep without host-0 gather,
restore into a FRESH step, continue training bit-identically."""
import json
import os
import subprocess
import sys

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import parallel as par
from mxnet_tpu.gluon import loss as gloss, nn
from mxnet_tpu.gluon.model_zoo import nlp

import jax


def _build(mesh, seed):
    mx.random.seed(seed)
    net = nlp.LlamaModel(vocab_size=64, num_layers=2, units=32,
                         hidden_size=64, num_heads=4, num_kv_heads=2)
    net.initialize()

    class LMLoss:
        def __init__(self):
            self._l = gloss.SoftmaxCrossEntropyLoss()

        def __call__(self, out, labels):
            return self._l(out.reshape((-1, out.shape[-1])),
                           labels.reshape((-1,)))

    step = par.TrainStep(net, LMLoss(), "adam",
                         mesh=mesh, rules=nlp.llama_sharding_rules(),
                         optimizer_params={"learning_rate": 1e-3})
    return net, step


def _batch(rs):
    x = mx.nd.array(rs.randint(0, 64, (4, 8)).astype(onp.float32))
    y = mx.nd.array(rs.randint(0, 64, (4, 8)).astype(onp.float32))
    return x, y


class TestShardedCheckpoint:
    def test_roundtrip_bit_identical_continuation(self, tmp_path):
        mesh = par.make_mesh({"dp": 2, "tp": 4})
        rs = onp.random.RandomState(0)
        x, y = _batch(rs)
        net, step = _build(mesh, seed=3)
        for _ in range(2):
            loss, _ = step(x, y)
        step.save_sharded(str(tmp_path))

        # continue the ORIGINAL for one step — the reference trajectory
        ref_loss, _ = step(x, y)
        ref = float(ref_loss.asnumpy())

        # fresh net with a DIFFERENT init; restore; continue
        net2, step2 = _build(mesh, seed=99)
        step2.restore_sharded(str(tmp_path), example_data=(x,))
        got_loss, _ = step2(x, y)
        got = float(got_loss.asnumpy())
        assert got == ref, (got, ref)

    def test_restore_restores_sharding_layout(self, tmp_path):
        from jax.sharding import PartitionSpec as P

        mesh = par.make_mesh({"dp": 2, "tp": 4})
        rs = onp.random.RandomState(1)
        x, y = _batch(rs)
        net, step = _build(mesh, seed=3)
        step(x, y)
        step.save_sharded(str(tmp_path))
        net2, step2 = _build(mesh, seed=4)
        step2.restore_sharded(str(tmp_path), example_data=(x,))
        w = [p for p in net2.collect_params().values()
             if p.name.endswith("gateup_weight")][0]
        assert w.data().data.sharding.spec == P("tp", None)
        # restored values equal saved ones
        w1 = [p for p in net.collect_params().values()
              if p.name.endswith("gateup_weight")][0]
        onp.testing.assert_array_equal(w.data().asnumpy(),
                                       w1.data().asnumpy())

    def test_shard_files_are_deduplicated_slices(self, tmp_path):
        mesh = par.make_mesh({"dp": 2, "tp": 4})
        rs = onp.random.RandomState(2)
        x, y = _batch(rs)
        _, step = _build(mesh, seed=3)
        step(x, y)
        step.save_sharded(str(tmp_path))
        with open(tmp_path / "index-00000.json") as f:
            keys = list(json.load(f)["entries"])
        # a tp-sharded (tp=4) gateup weight contributes 4 distinct slices
        gu = [k for k in keys if k.startswith("layer0.mlp.gate_up.weight@")]
        assert len(gu) == 4, gu
        # a replicated norm weight contributes exactly ONE slice
        norms = [k for k in keys if k.startswith("layer0.attn_norm.weight@")]
        assert len(norms) == 1, norms

    def test_save_cleans_stale_checkpoint_files(self, tmp_path):
        """Saving into a directory holding an OLDER checkpoint (here:
        planted shard/index files from a fake 8-process topology) must
        remove it wholesale — restore would otherwise resolve slices
        from the stale files — while leaving foreign files alone
        (ADVICE r5: user-pointed shared dirs)."""
        mesh = par.make_mesh({"dp": 2, "tp": 4})
        rs = onp.random.RandomState(5)
        x, y = _batch(rs)
        net, step = _build(mesh, seed=3)
        step(x, y)
        (tmp_path / "shard-00007-of-00008.params").write_bytes(b"stale")
        (tmp_path / "index-00007.json").write_text(json.dumps(
            {"file": "shard-00007-of-00008.params", "entries": {}}))
        (tmp_path / "meta.json").write_text("{}")
        (tmp_path / "notes.txt").write_text("foreign file, keep me")
        step.save_sharded(str(tmp_path))
        names = set(os.listdir(tmp_path))
        assert "shard-00007-of-00008.params" not in names
        assert "index-00007.json" not in names
        assert "notes.txt" in names
        # and the fresh checkpoint round-trips
        ref_loss, _ = step(x, y)
        net2, step2 = _build(mesh, seed=99)
        step2.restore_sharded(str(tmp_path), example_data=(x,))
        got_loss, _ = step2(x, y)
        assert float(got_loss.asnumpy()) == float(ref_loss.asnumpy())

    def test_restore_validates_index_set_against_meta(self, tmp_path):
        """A stale index file that survived (e.g. a checkpoint written
        by a custom tool) must be refused, not silently consulted; a
        missing one means a truncated checkpoint."""
        mesh = par.make_mesh({"dp": 2, "tp": 4})
        rs = onp.random.RandomState(6)
        x, y = _batch(rs)
        _, step = _build(mesh, seed=3)
        step(x, y)
        step.save_sharded(str(tmp_path))
        # plant a stale EXTRA index (as if an older multi-proc save)
        (tmp_path / "index-00003.json").write_text(json.dumps(
            {"file": "shard-00003-of-00004.params", "entries": {}}))
        _, step2 = _build(mesh, seed=99)
        with pytest.raises(Exception, match="stale index files"):
            step2.restore_sharded(str(tmp_path), example_data=(x,))
        os.unlink(tmp_path / "index-00003.json")
        # remove the REAL index: truncated checkpoint
        os.unlink(tmp_path / "index-00000.json")
        with pytest.raises(Exception, match="missing index files"):
            step2.restore_sharded(str(tmp_path), example_data=(x,))

    def test_mismatched_model_raises(self, tmp_path):
        mesh = par.make_mesh({"dp": 2, "tp": 4})
        rs = onp.random.RandomState(3)
        x, y = _batch(rs)
        _, step = _build(mesh, seed=3)
        step(x, y)
        step.save_sharded(str(tmp_path))

        mx.random.seed(0)
        other = nn.Dense(4, in_units=8)
        other.initialize()
        step2 = par.TrainStep(other, gloss.L2Loss(), "adam",
                              mesh=par.make_mesh({"dp": 1},
                                                 devices=jax.devices()[:1]),
                              optimizer_params={"learning_rate": 1e-3})
        step2(mx.nd.ones((2, 8)), mx.nd.ones((2, 4)))
        with pytest.raises(Exception, match="mismatch"):
            step2.restore_sharded(str(tmp_path))


_MESH32_SCRIPT = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=32")
sys.path.insert(0, os.environ["REPO_ROOT"])
import numpy as onp
import mxnet_tpu as mx
from mxnet_tpu import parallel as par
from mxnet_tpu.gluon import loss as gloss
from mxnet_tpu.gluon.model_zoo import nlp

def build(seed):
    mx.random.seed(seed)
    net = nlp.LlamaModel(vocab_size=64, num_layers=2, units=32,
                         hidden_size=64, num_heads=4, num_kv_heads=2)
    net.initialize()
    class LMLoss:
        def __init__(self):
            self._l = gloss.SoftmaxCrossEntropyLoss()
        def __call__(self, out, labels):
            return self._l(out.reshape((-1, out.shape[-1])),
                           labels.reshape((-1,)))
    mesh = par.make_mesh({"dp": 4, "tp": 8})
    step = par.TrainStep(net, LMLoss(), "adam", mesh=mesh,
                         rules=nlp.llama_sharding_rules(),
                         optimizer_params={"learning_rate": 1e-3})
    return step

rs = onp.random.RandomState(0)
x = mx.nd.array(rs.randint(0, 64, (8, 8)).astype(onp.float32))
y = mx.nd.array(rs.randint(0, 64, (8, 8)).astype(onp.float32))
d = sys.argv[1]
step = build(3)
step(x, y); step(x, y)
step.save_sharded(d)
ref = float(step(x, y)[0].asnumpy())
step2 = build(77)
step2.restore_sharded(d, example_data=(x,))
got = float(step2(x, y)[0].asnumpy())
assert got == ref, (got, ref)
print("MESH32_OK", flush=True)
"""


def test_roundtrip_on_32_device_mesh(tmp_path):
    """The v5e-32 target topology (SURVEY §5.4): save/restore/continue on
    a dp=4 x tp=8 virtual mesh, in a subprocess so the 32-device XLA
    flag doesn't disturb this session's 8-device mesh."""
    script = tmp_path / "mesh32.py"
    script.write_text(_MESH32_SCRIPT)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("XLA_FLAGS")}
    env["REPO_ROOT"] = repo_root
    out = subprocess.run(
        [sys.executable, str(script), str(tmp_path / "ckpt")],
        env=env, capture_output=True, text=True, timeout=600)
    assert out.returncode == 0 and "MESH32_OK" in out.stdout, \
        out.stdout[-2000:] + out.stderr[-2000:]
